"""TPC-H queries 2, 7, 8, 9, 11, 15, 16, 18, 20, 21, 22 vs pandas
oracles — completing 22/22 coverage (Q1/3/4/5/6/10/12/13/14/17/19 live
in test_tpch_more.py / bench). Exercises partsupp, nested IN chains,
HAVING-over-subquery, CTE self-reference with scalar subquery, mixed
EXISTS / NOT EXISTS with non-equality correlation (residual semi/anti
joins), count(distinct), and substring-based grouping."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.utils import tpch

SF = 0.02


@pytest.fixture(scope="module")
def env(devices8):
    d = greengage_tpu.connect(numsegments=4)
    tpch.load(d, SF)
    d.sql("analyze")
    dfs = tpch.to_pandas(tpch.generate(SF))
    return d, dfs


def _day(s):
    return (np.datetime64(s) - np.datetime64("1970-01-01")).astype(int)


def test_q2_min_cost_supplier(env):
    d, f = env
    r = d.sql("""select s_acctbal, s_name, n_name, p_partkey, p_mfgr
      from part, supplier, partsupp, nation, region
      where p_partkey = ps_partkey and s_suppkey = ps_suppkey and p_size = 15
        and s_nationkey = n_nationkey and n_regionkey = r_regionkey
        and r_name = 'EUROPE'
        and ps_supplycost = (
          select min(ps_supplycost) from partsupp, supplier, nation, region
          where p_partkey = ps_partkey and s_suppkey = ps_suppkey
            and s_nationkey = n_nationkey and n_regionkey = r_regionkey
            and r_name = 'EUROPE')
      order by s_acctbal desc, n_name, s_name, p_partkey limit 10""")
    eu = f["nation"].merge(f["region"], left_on="n_regionkey",
                           right_on="r_regionkey")
    eu = eu[eu.r_name == "EUROPE"]
    sup = f["supplier"].merge(eu, left_on="s_nationkey",
                              right_on="n_nationkey")
    ps = f["partsupp"].merge(sup, left_on="ps_suppkey", right_on="s_suppkey")
    mc = ps.groupby("ps_partkey")["ps_supplycost"].min().rename("minc")
    j = ps.merge(mc, left_on="ps_partkey", right_index=True)
    j = j[j.ps_supplycost == j.minc].merge(
        f["part"], left_on="ps_partkey", right_on="p_partkey")
    j = j[j.p_size == 15]
    want = j.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                         ascending=[False, True, True, True]).head(10)
    got = r.rows()
    assert len(got) == min(10, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert row[0] == pytest.approx(w.s_acctbal)
        assert row[1] == w.s_name and row[3] == w.p_partkey


def test_q7_volume_shipping(env):
    d, f = env
    r = d.sql("""select supp_nation, cust_nation, l_year, sum(volume) as revenue
      from (select n1.n_name as supp_nation, n2.n_name as cust_nation,
                   extract(year from l_shipdate) as l_year,
                   l_extendedprice * (1 - l_discount) as volume
            from supplier, lineitem, orders, customer, nation n1, nation n2
            where s_suppkey = l_suppkey and o_orderkey = l_orderkey
              and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
              and c_nationkey = n2.n_nationkey
              and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
                or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
              and l_shipdate between date '1995-01-01' and date '1996-12-31'
           ) as shipping
      group by supp_nation, cust_nation, l_year
      order by supp_nation, cust_nation, l_year""")
    li = f["lineitem"]
    li = li[(li.l_shipdate >= _day("1995-01-01"))
            & (li.l_shipdate <= _day("1996-12-31"))]
    j = (li.merge(f["orders"], left_on="l_orderkey", right_on="o_orderkey")
           .merge(f["customer"], left_on="o_custkey", right_on="c_custkey")
           .merge(f["supplier"], left_on="l_suppkey", right_on="s_suppkey")
           .merge(f["nation"].add_prefix("s1_"), left_on="s_nationkey",
                  right_on="s1_n_nationkey")
           .merge(f["nation"].add_prefix("c2_"), left_on="c_nationkey",
                  right_on="c2_n_nationkey"))
    j = j[((j.s1_n_name == "FRANCE") & (j.c2_n_name == "GERMANY"))
          | ((j.s1_n_name == "GERMANY") & (j.c2_n_name == "FRANCE"))]
    j["l_year"] = (pd.to_datetime(j.l_shipdate, unit="D")).dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    want = (j.groupby(["s1_n_name", "c2_n_name", "l_year"])["volume"].sum()
             .reset_index().sort_values(["s1_n_name", "c2_n_name", "l_year"]))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.s1_n_name, w.c2_n_name, w.l_year)
        assert float(row[3]) == pytest.approx(w.volume, rel=1e-9)


def test_q9_product_type_profit(env):
    d, f = env
    r = d.sql("""select nation, o_year, sum(amount) as sum_profit
      from (select n_name as nation, extract(year from o_orderdate) as o_year,
                   l_extendedprice * (1 - l_discount)
                     - ps_supplycost * l_quantity as amount
            from part, supplier, lineitem, partsupp, orders, nation
            where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
              and ps_partkey = l_partkey and p_partkey = l_partkey
              and o_orderkey = l_orderkey and s_nationkey = n_nationkey
              and p_name like '%name 1%') as profit
      group by nation, o_year order by nation, o_year desc""")
    part = f["part"][f["part"].p_name.str.contains("name 1")]
    j = (f["lineitem"].merge(part, left_on="l_partkey", right_on="p_partkey")
         .merge(f["partsupp"], left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
         .merge(f["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(f["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(f["nation"], left_on="s_nationkey", right_on="n_nationkey"))
    j["o_year"] = pd.to_datetime(j.o_orderdate, unit="D").dt.year
    j["amount"] = (j.l_extendedprice * (1 - j.l_discount)
                   - j.ps_supplycost * j.l_quantity)
    want = (j.groupby(["n_name", "o_year"])["amount"].sum().reset_index()
             .sort_values(["n_name", "o_year"], ascending=[True, False]))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1]) == (w.n_name, w.o_year)
        assert float(row[2]) == pytest.approx(w.amount, rel=1e-9)


def test_q11_important_stock(env):
    d, f = env
    r = d.sql("""select ps_partkey, sum(ps_supplycost * ps_availqty) as value
      from partsupp, supplier, nation
      where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
        and n_name = 'GERMANY'
      group by ps_partkey
      having sum(ps_supplycost * ps_availqty) > (
        select sum(ps_supplycost * ps_availqty) * 0.0001
        from partsupp, supplier, nation
        where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
          and n_name = 'GERMANY')
      order by value desc, ps_partkey limit 20""")
    de = f["supplier"].merge(f["nation"], left_on="s_nationkey",
                             right_on="n_nationkey")
    de = de[de.n_name == "GERMANY"]
    ps = f["partsupp"].merge(de, left_on="ps_suppkey", right_on="s_suppkey")
    ps["value"] = ps.ps_supplycost * ps.ps_availqty
    g = ps.groupby("ps_partkey")["value"].sum()
    thresh = ps["value"].sum() * 0.0001
    want = (g[g > thresh].reset_index()
             .sort_values(["value", "ps_partkey"], ascending=[False, True])
             .head(20))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert row[0] == w.ps_partkey
        assert float(row[1]) == pytest.approx(w.value, rel=1e-9)


def test_q15_top_supplier_cte(env):
    d, f = env
    r = d.sql("""with revenue as (
        select l_suppkey as supplier_no,
               sum(l_extendedprice * (1 - l_discount)) as total_revenue
        from lineitem
        where l_shipdate >= date '1996-01-01'
          and l_shipdate < date '1996-04-01'
        group by l_suppkey)
      select s_suppkey, s_name, total_revenue from supplier, revenue
      where s_suppkey = supplier_no
        and total_revenue = (select max(total_revenue) from revenue)
      order by s_suppkey""")
    li = f["lineitem"]
    li = li[(li.l_shipdate >= _day("1996-01-01"))
            & (li.l_shipdate < _day("1996-04-01"))]
    li = li.assign(rev=li.l_extendedprice * (1 - li.l_discount))
    g = li.groupby("l_suppkey")["rev"].sum()
    top = g[g == g.max()]
    got = r.rows()
    assert len(got) == len(top)
    for row, (sk, rev) in zip(got, sorted(top.items())):
        assert row[0] == sk
        assert float(row[2]) == pytest.approx(rev, rel=1e-9)


def test_q16_supplier_count_distinct(env):
    d, f = env
    r = d.sql("""select p_brand, p_size, count(distinct ps_suppkey) as cnt
      from partsupp, part
      where p_partkey = ps_partkey and p_brand <> 'Brand#45'
        and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
      group by p_brand, p_size
      order by cnt desc, p_brand, p_size limit 15""")
    j = f["partsupp"].merge(f["part"], left_on="ps_partkey",
                            right_on="p_partkey")
    j = j[(j.p_brand != "Brand#45")
          & j.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    want = (j.groupby(["p_brand", "p_size"])["ps_suppkey"].nunique()
             .reset_index(name="cnt")
             .sort_values(["cnt", "p_brand", "p_size"],
                          ascending=[False, True, True]).head(15))
    got = r.rows()
    assert len(got) == min(15, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.p_brand, w.p_size, w.cnt)


def test_q18_large_volume_customer(env):
    d, f = env
    r = d.sql("""select c_name, c_custkey, o_orderkey, o_totalprice,
             sum(l_quantity)
      from customer, orders, lineitem
      where o_orderkey in (select l_orderkey from lineitem
                           group by l_orderkey having sum(l_quantity) > 150)
        and c_custkey = o_custkey and o_orderkey = l_orderkey
      group by c_name, c_custkey, o_orderkey, o_totalprice
      order by o_totalprice desc, o_orderkey limit 10""")
    li = f["lineitem"]
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 150]
    j = (li[li.l_orderkey.isin(big.index)]
         .merge(f["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(f["customer"], left_on="o_custkey", right_on="c_custkey"))
    want = (j.groupby(["c_name", "c_custkey", "o_orderkey", "o_totalprice"])
             ["l_quantity"].sum().reset_index()
             .sort_values(["o_totalprice", "o_orderkey"],
                          ascending=[False, True]).head(10))
    got = r.rows()
    assert len(got) == min(10, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[1], row[2]) == (w.c_custkey, w.o_orderkey)
        assert float(row[4]) == pytest.approx(w.l_quantity, rel=1e-9)


def test_q20_potential_part_promotion(env):
    d, f = env
    r = d.sql("""select s_name, s_address from supplier, nation
      where s_suppkey in (
        select ps_suppkey from partsupp
        where ps_partkey in (select p_partkey from part
                             where p_name like 'part name 1%')
          and ps_availqty > (select 0.5 * sum(l_quantity) from lineitem
              where l_partkey = ps_partkey and l_suppkey = ps_suppkey))
        and s_nationkey = n_nationkey and n_name = 'CANADA'
      order by s_name""")
    parts = f["part"][f["part"].p_name.str.startswith("part name 1")]
    ps = f["partsupp"][f["partsupp"].ps_partkey.isin(parts.p_partkey)]
    liq = (f["lineitem"].groupby(["l_partkey", "l_suppkey"])
           ["l_quantity"].sum())
    ps = ps.merge(liq.reset_index(name="q"), how="left",
                  left_on=["ps_partkey", "ps_suppkey"],
                  right_on=["l_partkey", "l_suppkey"])
    # NULL comparison: suppliers with no lineitem sales never qualify
    ps = ps[ps.q.notna() & (ps.ps_availqty > 0.5 * ps.q)]
    sup = f["supplier"].merge(f["nation"], left_on="s_nationkey",
                              right_on="n_nationkey")
    sup = sup[sup.n_name == "CANADA"]
    want = (sup[sup.s_suppkey.isin(ps.ps_suppkey)]
            .sort_values("s_name"))
    got = r.rows()
    assert [x[0] for x in got] == list(want.s_name)


def test_q21_suppliers_who_kept_orders_waiting(env):
    d, f = env
    r = d.sql("""select s_name, count(*) as numwait
      from supplier, lineitem l1, orders, nation
      where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
        and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
        and exists (select 1 from lineitem l2
                    where l2.l_orderkey = l1.l_orderkey
                      and l2.l_suppkey <> l1.l_suppkey)
        and not exists (select 1 from lineitem l3
                        where l3.l_orderkey = l1.l_orderkey
                          and l3.l_suppkey <> l1.l_suppkey
                          and l3.l_receiptdate > l3.l_commitdate)
        and s_nationkey = n_nationkey
      group by s_name order by numwait desc, s_name limit 10""")
    li = f["lineitem"]
    late = li[li.l_receiptdate > li.l_commitdate]
    # per l1 row: another supplier on the order exists / none is late
    all_per = li.groupby("l_orderkey")["l_suppkey"].agg(set)
    late_per = late.groupby("l_orderkey")["l_suppkey"].agg(set)
    j = (late.merge(f["orders"], left_on="l_orderkey", right_on="o_orderkey"))
    j = j[j.o_orderstatus == "F"]

    def qualifies(row):
        order = row.l_orderkey
        others = all_per.get(order, set()) - {row.l_suppkey}
        if not others:
            return False
        late_others = late_per.get(order, set()) - {row.l_suppkey}
        return len(late_others) == 0

    j = j[j.apply(qualifies, axis=1)]
    j = (j.merge(f["supplier"], left_on="l_suppkey", right_on="s_suppkey")
          .merge(f["nation"], left_on="s_nationkey", right_on="n_nationkey"))
    want = (j.groupby("s_name").size().reset_index(name="numwait")
             .sort_values(["numwait", "s_name"], ascending=[False, True])
             .head(10))
    got = r.rows()
    assert len(got) == min(10, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1]) == (w.s_name, w.numwait)


def test_q22_global_sales_opportunity(env):
    d, f = env
    # phone vocab here is synthetic ('phone N'); country code = a numeric
    # prefix of the payload, so group on substring(8 for 1)
    r = d.sql("""select cntrycode, count(*) as numcust,
                        sum(c_acctbal) as totacctbal
      from (select substring(c_phone from 7 for 1) as cntrycode, c_acctbal
            from customer
            where substring(c_phone from 7 for 1) in ('1','2','3')
              and c_acctbal > (select avg(c_acctbal) from customer
                               where c_acctbal > 0.00)
              and not exists (select 1 from orders
                              where o_custkey = c_custkey)) as custsale
      group by cntrycode order by cntrycode""")
    c = f["customer"].copy()
    c["code"] = c.c_phone.str[6:7]
    avg = c[c.c_acctbal > 0].c_acctbal.mean()
    cand = c[c.code.isin(["1", "2", "3"]) & (c.c_acctbal > avg)]
    cand = cand[~cand.c_custkey.isin(f["orders"].o_custkey)]
    want = (cand.groupby("code")
            .agg(numcust=("c_custkey", "size"), tot=("c_acctbal", "sum"))
            .reset_index().sort_values("code"))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1]) == (w.code, w.numcust)
        assert float(row[2]) == pytest.approx(w.tot, rel=1e-9)


def test_q8_market_share(env):
    d, f = env
    r = d.sql("""select o_year,
             sum(case when nation = 'BRAZIL' then volume else 0 end)
               / sum(volume) as mkt_share
      from (select extract(year from o_orderdate) as o_year,
                   l_extendedprice * (1 - l_discount) as volume,
                   n2.n_name as nation
            from part, supplier, lineitem, orders, customer,
                 nation n1, nation n2, region
            where p_partkey = l_partkey and s_suppkey = l_suppkey
              and l_orderkey = o_orderkey and o_custkey = c_custkey
              and c_nationkey = n1.n_nationkey
              and n1.n_regionkey = r_regionkey and r_name = 'AMERICA'
              and s_nationkey = n2.n_nationkey
              and o_orderdate between date '1995-01-01'
                                  and date '1996-12-31') as all_nations
      group by o_year order by o_year""")
    am = f["nation"].merge(f["region"], left_on="n_regionkey",
                           right_on="r_regionkey")
    am = am[am.r_name == "AMERICA"]
    j = (f["lineitem"]
         .merge(f["part"], left_on="l_partkey", right_on="p_partkey")
         .merge(f["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(f["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(f["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(f["nation"].add_prefix("s2_"), left_on="s_nationkey",
                right_on="s2_n_nationkey"))
    j = j[j.c_nationkey.isin(am.n_nationkey)]
    j = j[(j.o_orderdate >= _day("1995-01-01"))
          & (j.o_orderdate <= _day("1996-12-31"))]
    j["o_year"] = pd.to_datetime(j.o_orderdate, unit="D").dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["bra"] = np.where(j.s2_n_name == "BRAZIL", j.volume, 0.0)
    want = (j.groupby("o_year").agg(bra=("bra", "sum"), v=("volume", "sum"))
             .reset_index().sort_values("o_year"))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert row[0] == w.o_year
        assert float(row[1]) == pytest.approx(w.bra / w.v, abs=1e-6)
