"""Window functions over grouped aggregates (WindowAgg-over-Agg stack,
nodeWindowAgg.c above nodeAgg.c) — the TPC-DS staple
`rank() over (order by sum(v) desc)` via the two-level rewrite."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(3)
    n = 300
    g = rng.integers(0, 6, n).astype(np.int32)
    h = rng.integers(0, 2, n).astype(np.int32)
    v = rng.integers(0, 100, n).astype(np.int32)
    d.sql("create table t (g int, h int, v int, k int) distributed by (k)")
    d.load_table("t", {"g": g, "h": h, "v": v,
                       "k": np.arange(n, dtype=np.int32)})
    d.df = pd.DataFrame({"g": g, "h": h, "v": v})
    yield d
    d.close()


def test_rank_over_sum(db):
    r = db.sql("select g, sum(v) s, rank() over (order by sum(v) desc) rnk "
               "from t group by g order by rnk, g")
    agg = db.df.groupby("g", as_index=False).v.sum()
    agg["rnk"] = agg.v.rank(ascending=False, method="min").astype(int)
    want = sorted(agg[["g", "v", "rnk"]].values.tolist(),
                  key=lambda x: (x[2], x[0]))
    assert [list(map(int, row)) for row in r.rows()] == want


def test_percent_of_total(db):
    r = db.sql("select g, sum(v) s, sum(v) * 100.0 / sum(sum(v)) over () p "
               "from t group by g order by g")
    tot = db.df.v.sum()
    for g, s, p in r.rows():
        np.testing.assert_allclose(p, s * 100.0 / tot, rtol=1e-4)


def test_partitioned_window_over_agg(db):
    """TPC-DS Q36/Q70 shape: rank within a partition of the grouped
    result."""
    r = db.sql("select g, h, sum(v) s, "
               "rank() over (partition by h order by sum(v) desc) rnk "
               "from t group by g, h order by h, rnk, g")
    agg = db.df.groupby(["g", "h"], as_index=False).v.sum()
    agg["rnk"] = agg.groupby("h").v.rank(
        ascending=False, method="min").astype(int)
    want = sorted(agg[["g", "h", "v", "rnk"]].values.tolist(),
                  key=lambda x: (x[1], x[3], x[0]))
    assert [list(map(int, row)) for row in r.rows()] == want


def test_window_over_count_star_with_having(db):
    r = db.sql("select g, count(*) c, "
               "row_number() over (order by count(*) desc, g) rn "
               "from t group by g having count(*) > 10 order by rn")
    agg = db.df.groupby("g", as_index=False).size()
    agg = agg[agg["size"] > 10].sort_values(["size", "g"],
                                            ascending=[False, True])
    got = [list(map(int, row)) for row in r.rows()]
    assert [row[:2] for row in got] == agg[["g", "size"]].values.tolist()
    assert [row[2] for row in got] == list(range(1, len(got) + 1))


def test_window_over_stat_agg(db):
    """Composition: stddev (itself an expansion) inside the window order."""
    r = db.sql("select g, rank() over (order by stddev(v) desc) rnk "
               "from t group by g")
    sd = db.df.groupby("g").v.std()
    want_order = sd.rank(ascending=False, method="min").astype(int)
    for g, rnk in r.rows():
        assert rnk == want_order[g]


def test_rank_within_rollup_levels(db):
    """TPC-DS Q36 composition: windows over grouped aggregates ALSO
    composes with ROLLUP — rank within each grouping level."""
    r = db.sql("select g, h, sum(v) rev, grouping(g, h) lvl, "
               "rank() over (partition by grouping(g, h) "
               "order by sum(v) desc) rnk "
               "from t group by rollup(g, h) order by lvl, rnk")
    rows = r.rows()
    leaf = db.df.groupby(["g", "h"]).v.sum().sort_values(ascending=False)
    byg = db.df.groupby("g").v.sum().sort_values(ascending=False)
    lvl0 = [x for x in rows if x[3] == 0]
    lvl1 = [x for x in rows if x[3] == 1]
    lvl3 = [x for x in rows if x[3] == 3]
    assert (lvl0[0][0], lvl0[0][1]) == leaf.index[0]
    assert lvl0[0][2] == leaf.iloc[0] and lvl0[0][4] == 1
    assert lvl1[0][0] == byg.index[0] and lvl1[0][2] == byg.iloc[0]
    assert lvl3 == [(None, None, int(db.df.v.sum()), 3, 1)]
    assert len(rows) == len(leaf) + len(byg) + 1
