"""Sort-based high-cardinality grouped aggregation (the execHHashagg.c
spill-regime analog — VERDICT r1 item #1).

Group keys without a finite dictionary/bool domain take the sort +
segmented-reduction path; the estimated output capacity undershoots here
(est_groups is sqrt-based), so these also exercise the exact-count overflow
retry."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.002)
    return d


@pytest.fixture(scope="module")
def oracle():
    return tpch.to_pandas(tpch.generate(0.002))


def test_group_by_orderkey(db, oracle):
    """~3000 distinct int keys: far past the dense-domain path."""
    r = db.sql("select l_orderkey, count(*), sum(l_quantity), "
               "min(l_discount), max(l_extendedprice) "
               "from lineitem group by l_orderkey order by l_orderkey")
    li = oracle["lineitem"]
    want = li.groupby("l_orderkey").agg(
        n=("l_quantity", "size"), q=("l_quantity", "sum"),
        d=("l_discount", "min"), p=("l_extendedprice", "max")).reset_index()
    want = want.sort_values("l_orderkey")
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.array_equal(got.iloc[:, 0].values, want.l_orderkey.values)
    assert np.array_equal(got.iloc[:, 1].values, want.n.values)
    assert np.allclose(got.iloc[:, 2].astype(float), want.q.values)
    assert np.allclose(got.iloc[:, 3].astype(float), want.d.values)
    assert np.allclose(got.iloc[:, 4].astype(float), want.p.values)


def test_group_by_two_phase_high_cardinality(db, oracle):
    """Group key != distribution key: partial -> redistribute -> final."""
    r = db.sql("select l_suppkey, count(*), avg(l_quantity) from lineitem "
               "group by l_suppkey order by l_suppkey")
    li = oracle["lineitem"]
    want = li.groupby("l_suppkey").agg(
        n=("l_quantity", "size"), a=("l_quantity", "mean")).reset_index()
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.array_equal(got.iloc[:, 0].values, want.l_suppkey.values)
    assert np.array_equal(got.iloc[:, 1].values, want.n.values)
    assert np.allclose(got.iloc[:, 2].astype(float), want.a.values)


def test_group_by_mixed_text_and_int(db, oracle):
    """TEXT dict key x high-cardinality int key: product of domains pushes
    past the dense limit -> sort path with a text code operand."""
    r = db.sql("select l_returnflag, l_suppkey, sum(l_extendedprice) "
               "from lineitem group by l_returnflag, l_suppkey "
               "order by l_returnflag, l_suppkey")
    li = oracle["lineitem"]
    want = li.groupby(["l_returnflag", "l_suppkey"])["l_extendedprice"].sum() \
        .reset_index().sort_values(["l_returnflag", "l_suppkey"])
    got = r.to_pandas()
    assert len(got) == len(want)
    assert list(got.iloc[:, 0].values) == list(want.l_returnflag.values)
    assert np.array_equal(got.iloc[:, 1].values, want.l_suppkey.values)
    assert np.allclose(got.iloc[:, 2].astype(float), want.l_extendedprice.values)


def test_group_by_nullable_key(db):
    db.sql("create table nulg (k int, g int, v int) distributed by (k)")
    db.sql("insert into nulg values (1, 10, 1), (2, 10, 2), (3, null, 3), "
           "(4, null, 4), (5, 20, 5)")
    r = db.sql("select g, count(*), sum(v) from nulg group by g order by g")
    rows = r.rows()
    # NULL group aggregates together (SQL GROUP BY semantics)
    assert (10, 2, 3) in rows and (20, 1, 5) in rows
    assert any(row[0] is None and row[1] == 2 and row[2] == 7 for row in rows)


def test_group_by_float_key(db):
    db.sql("create table fltg (k int, g float, v int) distributed by (k)")
    db.sql("insert into fltg values (1, 1.5, 1), (2, 1.5, 2), (3, -0.0, 3), "
           "(4, 0.0, 4), (5, 2.5, 5)")
    r = db.sql("select g, sum(v) from fltg group by g order by g")
    rows = r.rows()
    assert len(rows) == 3          # -0.0 and 0.0 are one group
    assert rows[0] == (0.0, 7)
    assert rows[1] == (1.5, 3)
    assert rows[2] == (2.5, 5)


def test_group_by_having_high_cardinality(db, oracle):
    r = db.sql("select l_orderkey, count(*) as n from lineitem "
               "group by l_orderkey having count(*) >= 6 order by l_orderkey")
    li = oracle["lineitem"]
    want = li.groupby("l_orderkey").size()
    want = want[want >= 6]
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.array_equal(got.iloc[:, 0].values, want.index.values)
    assert np.array_equal(got.iloc[:, 1].values, want.values)


def test_float_sum_group_local_accuracy(db):
    """float64 group sums must not lose precision to the whole-batch
    magnitude (r2 review finding: prefix-sum span differences subtract two
    near-equal totals; the float path scatters group-locally instead)."""
    import numpy as np

    db.sql("create table fsum (k int, g int, v float) distributed by (k)")
    n = 20_000
    rng = np.random.default_rng(3)
    g = rng.integers(0, 5000, n)
    v = np.full(n, 1e9)          # batch total 2e13
    v[g == 7] = 1e-3             # one tiny-magnitude group
    db.load_table("fsum", {"k": np.arange(n), "g": g, "v": v})
    r = db.sql("select g, sum(v) from fsum where g = 7 group by g")
    want = 1e-3 * int((g == 7).sum())
    got = r.rows()[0][1]
    assert abs(got - want) / want < 1e-9, (got, want)
