"""Calibrated cost model (planner/cost.py) — plan goldens that flip on
stats, the CCostModelGPDB / CEngine-alternatives analog (VERDICT r2 #4).

The round-2 model costed motions in raw bytes, which systematically
over-broadcast mid-size relations (a broadcast build is sort-built
FULL-SIZE on every chip at ~40 ns/row/operand — ~250x its ICI transfer
cost per row) and hard-coded two-phase aggregation even when the group
key's NDV ~ row count makes the partial pass pure overhead. These tests
pin the flips the measured v5e constants produce.
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner import cost as C
from greengage_tpu.planner.logical import describe
from greengage_tpu.sql.parser import parse


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(3)
    nf = 200_000
    # fact: distributed by k; join columns fk_small/fk_mid are NOT the
    # distribution key, so a join on them always needs motion
    d.sql("create table fact (k int, u int, fk_small int, fk_mid int, v int) "
          "distributed by (k)")
    d.load_table("fact", {
        "k": np.arange(nf),
        "u": rng.permutation(nf).astype(np.int64),   # high-NDV, NOT the dist key
        "fk_small": rng.integers(0, 40, nf),
        "fk_mid": rng.integers(0, 4000, nf),
        "v": rng.integers(0, 1000, nf),
    })
    # dim tables distributed by a non-join column (m), so the dim side is
    # never pre-hashed on the join key either: the planner must choose
    # between broadcasting the dim and redistributing both sides
    d.sql("create table dim_small (pk int, m int, w int) distributed by (m)")
    d.load_table("dim_small", {
        "pk": np.arange(40), "m": np.arange(40), "w": np.arange(40)})
    d.sql("create table dim_mid (pk int, m int, w int) distributed by (m)")
    d.load_table("dim_mid", {
        "pk": np.arange(4000), "m": np.arange(4000), "w": np.arange(4000)})
    d.sql("analyze")
    return d


def _plan(db, sql: str) -> str:
    planned, _, _ = db._plan(parse(sql)[0])
    return describe(planned)


def _motion_above(plan_text: str, scan_substr: str) -> str:
    """The Motion line (if any) directly above the matching Scan line —
    i.e. the motion that feeds this scan into its join."""
    lines = plan_text.splitlines()
    for i, ln in enumerate(lines):
        if scan_substr in ln:
            for j in range(i - 1, -1, -1):
                if "Motion" in lines[j] or "Join" in lines[j]:
                    return lines[j]
    return ""


# ---------------------------------------------------------------------------
# broadcast vs redistribute: flips on the build side's size
# ---------------------------------------------------------------------------

def test_tiny_dim_is_broadcast(db):
    got = _plan(db, "select sum(f.v) from fact f, dim_small d "
                    "where f.fk_small = d.pk")
    assert "Motion Broadcast" in _motion_above(got, "Scan dim_small"), got


def test_mid_dim_is_redistributed_not_broadcast(db):
    # 4000-row build: raw-bytes costing says broadcast (4000*8 < 200k/7);
    # the calibrated model charges the full-size replicated sort build on
    # every chip and redistributes both sides instead
    got = _plan(db, "select sum(f.v) from fact f, dim_mid d "
                    "where f.fk_mid = d.pk")
    assert "Motion Redistribute" in _motion_above(got, "Scan dim_mid"), got
    assert got.count("Motion Redistribute") >= 2, got


def test_broadcast_flip_tracks_stats(db):
    # the same SQL shape flips purely on the build side's row count —
    # the "plan goldens that flip on stats" requirement
    small = _plan(db, "select sum(f.v) from fact f, dim_small d "
                      "where f.fk_small = d.pk")
    mid = _plan(db, "select sum(f.v) from fact f, dim_mid d "
                    "where f.fk_mid = d.pk")
    assert "Motion Broadcast" in _motion_above(small, "Scan dim_small")
    assert "Motion Redistribute" in _motion_above(mid, "Scan dim_mid")


def test_both_shapes_execute_correctly(db):
    want_small = db.sql("select sum(v) from fact").rows()[0][0]
    got = db.sql("select sum(f.v) from fact f, dim_small d "
                 "where f.fk_small = d.pk").rows()[0][0]
    assert got == want_small  # every fk_small in [0,40) matches exactly once
    got_mid = db.sql("select sum(f.v) from fact f, dim_mid d "
                     "where f.fk_mid = d.pk").rows()[0][0]
    assert got_mid == want_small


# ---------------------------------------------------------------------------
# aggregate placement: one-phase vs two-phase flips on group-key NDV
# ---------------------------------------------------------------------------

def test_low_ndv_group_uses_two_phase(db):
    # 40 groups: partial aggregation collapses 200k rows to <=320 states,
    # so the two-phase plan moves ~nothing
    got = _plan(db, "select fk_small, sum(v) from fact group by fk_small")
    assert "Aggregate partial" in got and "Aggregate final" in got, got


def test_high_ndv_group_skips_partial_phase(db):
    # group by a ~unique key (k): partial reduces nothing — the calibrated
    # choice ships raw rows and aggregates once after the motion
    got = _plan(db, "select u, sum(v) from fact group by u")
    assert "Aggregate partial" not in got, got
    assert "Aggregate single" in got, got
    assert "Motion Redistribute" in got, got


def test_agg_placement_results_identical(db):
    one = dict(db.sql("select u, sum(v) from fact group by u").rows())
    assert len(one) == 200_000
    two = dict(db.sql("select fk_small, sum(v) from fact group by fk_small")
               .rows())
    got = db.sql("select sum(v) from fact").rows()[0][0]
    assert sum(two.values()) == got
    assert sum(one.values()) == got


# ---------------------------------------------------------------------------
# cost-model unit sanity: the measured asymmetries the flips rely on
# ---------------------------------------------------------------------------

def test_replicated_build_dwarfs_its_ici_cost():
    rows, width, nseg = 4000, 16, 8
    ici = C.motion_cost("broadcast", rows, width, nseg)
    build_extra = (C.join_build_cost(rows, 1, nseg, replicated=True)
                   - C.join_build_cost(rows, 1, nseg))
    assert build_extra > 10 * ici


def test_gather_charges_host_relay_floor():
    # even a 1-row gather pays the ~65ms relay call (NOTES.md measurement)
    assert C.motion_cost("gather", 1, 8, 8) >= C.NS_HOST_CALL


# ---------------------------------------------------------------------------
# stale stats: packed keys must self-heal via the pack-violation retry
# ---------------------------------------------------------------------------

def test_stale_bounds_group_by_still_exact(db):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(5)
    d.sql("create table st (k int, g int, v int) distributed by (k)")
    n = 4000
    d.load_table("st", {"k": np.arange(n),
                        "g": rng.integers(0, 30000, n).astype(np.int64),
                        "v": np.ones(n, np.int64)})
    d.sql("analyze st")
    # grow the key domain far past the analyzed max WITHOUT re-analyzing
    d.sql("insert into st values (999991, 900000, 1), (999992, 900001, 1)")
    rows = d.sql("select g, sum(v) from st group by g").rows()
    got = {g: s for g, s in rows}
    assert got[900000] == 1 and got[900001] == 1
    assert sum(got.values()) == n + 2


def test_stale_bounds_join_still_exact(db):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table bl (pk int, m int) distributed by (m)")
    d.sql("create table pr (k int, fk int) distributed by (k)")
    d.load_table("bl", {"pk": np.arange(100), "m": np.arange(100)})
    d.load_table("pr", {"k": np.arange(500),
                        "fk": (np.arange(500) % 120).astype(np.int64)})
    d.sql("analyze")
    # stale build bounds: new build key outside the analyzed [0, 99]
    d.sql("insert into bl values (5000, 5000)")
    d.sql("insert into pr values (501, 5000)")
    n = d.sql("select count(*) from pr, bl where pr.fk = bl.pk").rows()[0][0]
    # fks 0..99 each appear ceil-ish times within 0..119 cycle + the 5000 row
    want = int(np.isin((np.arange(500) % 120), np.arange(100)).sum()) + 1
    assert n == want
