"""General device LIKE over raw TEXT (VERDICT r4 #7): %-patterns of
literal parts lower to byte-matrix matching over the staged wide window
(E.RawLike over @rw word lanes) — zero host per-row work at steady state;
rows longer than the window gate the whole predicate to the host path."""

import re

import numpy as np
import pytest

import greengage_tpu

STRS = [
    "special packages for requests", "no match here", "ends with requests",
    "special", "requestsspecial", "a special request",
    "x" * 100 + "special", "", "requests special deposits",
    "unusual accounts. special requests sleep",
]


def _mkdb(tmp=None, extra=None):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table rt (k int, c text) distributed by (k)")
    col = d.catalog.get("rt").column("c")
    object.__setattr__(col, "encoding", "raw")
    strs = STRS + (extra or [])
    d.load_table("rt", {"k": np.arange(len(strs), dtype=np.int32),
                        "c": np.array(strs, dtype=object)})
    return d, strs


def _oracle(strs, pat):
    rx = re.compile(
        "^" + ".*".join(re.escape(p) for p in pat.split("%")) + "$", re.S)
    return [i for i, s in enumerate(strs) if rx.match(s)]


@pytest.fixture(scope="module")
def db(devices8):
    d, strs = _mkdb()
    d.strs = strs
    yield d
    d.close()


PATTERNS = ["%special%requests%", "%requests", "%special%", "%es%wi%th%",
            "%special%deposits", "%sp%ec%ial", "%x%", "%%", "a%request"]


def test_device_like_matches_regex_oracle(db):
    for pat in PATTERNS:
        got = [x[0] for x in db.sql(
            f"select k from rt where c like '{pat}' order by k").rows()]
        assert got == _oracle(db.strs, pat), pat


def test_not_like_q13_shape(db):
    """TPC-H Q13's o_comment NOT LIKE '%special%requests%' filter."""
    got = [x[0] for x in db.sql(
        "select k from rt where c not like '%special%requests%' "
        "order by k").rows()]
    want = [i for i in range(len(db.strs))
            if i not in _oracle(db.strs, "%special%requests%")]
    assert got == want


def test_device_path_used_no_host_predicate(db):
    """The plan must stage @rw word lanes, not an @hp host predicate —
    that is the 'zero host per-row work' claim made checkable."""
    from greengage_tpu.planner.logical import Scan
    from greengage_tpu.sql.parser import parse

    planned, _, _ = db._plan(parse(
        "select k from rt where c like '%special%requests%'")[0])
    cols = []
    stack = [planned]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan):
            cols.extend(c.name for c in p.cols)
        stack.extend(p.children)
    assert any(c.startswith("@rw:") for c in cols), cols
    assert not any(c.startswith("@hp:") for c in cols), cols


def test_long_rows_gate_to_host_path(devices8):
    """A committed row longer than the wide window makes device matching
    undecidable: the binder must route the WHOLE predicate to the host
    path — and the answer stays right (the long row matches in its
    tail)."""
    long_row = "y" * 200 + "needle at the far end"
    d, strs = _mkdb(extra=[long_row])
    try:
        from greengage_tpu.planner.logical import Scan
        from greengage_tpu.sql.parser import parse

        planned, _, _ = d._plan(parse(
            "select k from rt where c like '%needle%'")[0])
        cols = []
        stack = [planned]
        while stack:
            p = stack.pop()
            if isinstance(p, Scan):
                cols.extend(c.name for c in p.cols)
            stack.extend(p.children)
        assert any(c.startswith("@hp:") for c in cols)
        got = [x[0] for x in d.sql(
            "select k from rt where c like '%needle%'").rows()]
        assert got == [len(strs) - 1]
    finally:
        d.close()


def test_device_like_composes_with_other_predicates(db):
    got = [x[0] for x in db.sql(
        "select k from rt where c like '%special%' and k < 5 "
        "order by k").rows()]
    assert got == [i for i in _oracle(db.strs, "%special%") if i < 5]
