"""WITH RECURSIVE — nodeRecursiveunion.c / WorkTableScan role
(gram.y:12190): session-level fixpoint iteration; every term runs as an
ordinary distributed statement over a materialized worktable."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import QueryError
from greengage_tpu.sql.parser import SqlError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table edges (src int, dst int, w int) distributed by (src)")
    d.sql("insert into edges values (1,2,4),(2,3,1),(3,4,7),(2,5,2),(6,7,9)")
    d.sql("create table emp (id int, boss int, name text) "
          "distributed by (id)")
    d.sql("insert into emp values (1, null, 'ceo'), (2, 1, 'vp1'), "
          "(3, 1, 'vp2'), (4, 2, 'mgr'), (5, 4, 'eng'), (6, 4, 'eng2')")
    yield d
    d.close()


def test_series_generation(db):
    r = db.sql("with recursive s(n) as (select 1 union all "
               "select n+1 from s where n < 100) "
               "select count(*), sum(n), min(n), max(n) from s")
    assert r.rows() == [(100, 5050, 1, 100)]


def test_graph_reachability_union_dedupes(db):
    r = db.sql("with recursive reach(node) as (select 1 union "
               "select dst from edges, reach where edges.src = reach.node) "
               "select node from reach order by node")
    assert [x[0] for x in r.rows()] == [1, 2, 3, 4, 5]


def test_hierarchy_with_depth_and_join(db):
    """Org-chart walk carrying depth; final query joins the CTE result."""
    r = db.sql(
        "with recursive org(id, depth) as ("
        "  select id, 0 from emp where boss is null"
        "  union all"
        "  select emp.id, org.depth + 1 from emp, org where emp.boss = org.id"
        ") select emp.name, org.depth from org, emp "
        "where org.id = emp.id order by org.depth, emp.name")
    rows = [tuple(x) for x in r.rows()]
    assert rows[0] == ("ceo", 0)
    assert ("vp1", 1) in rows and ("vp2", 1) in rows
    assert ("eng", 3) in rows and ("eng2", 3) in rows


def test_cycle_terminates_with_union(db):
    db.sql("create table cyc (a int, b int) distributed by (a)")
    db.sql("insert into cyc values (1,2),(2,3),(3,1)")
    r = db.sql("with recursive t(n) as (select 1 union "
               "select b from cyc, t where cyc.a = t.n) "
               "select count(*) from t")
    assert r.rows() == [(3,)]


def test_runaway_union_all_bounded(db):
    with pytest.raises(QueryError, match="iterations"):
        db.sql("with recursive t(n) as (select 1 union all "
               "select n from t) select count(*) from t")


def test_self_ref_without_recursive_is_plain_table_ref(db):
    # PG semantics: without RECURSIVE the inner reference resolves to a
    # real table of that name — absent here, so the statement fails with
    # a resolution error (NOT silent recursion)
    with pytest.raises(Exception, match="t"):
        db.sql("with t(n) as (select 1 union all select n+1 from t) "
               "select * from t")


def test_no_base_term_rejected(db):
    with pytest.raises(SqlError, match="non-recursive"):
        db.sql("with recursive t(n) as (select n from t union all "
               "select n from t) select * from t")


def test_mixed_with_plain_cte(db):
    """A plain CTE alongside a recursive one; the plain one inlines, the
    recursive one materializes, and they compose in the final query."""
    r = db.sql(
        "with recursive "
        "roots(node) as (select src from edges where src = 1), "
        "reach(node) as (select node from roots union "
        "  select dst from edges, reach where edges.src = reach.node) "
        "select count(*) from reach")
    assert r.rows() == [(5,)]
