"""Remote TCP connections with authentication — the libpq auth.c /
pg_hba.conf role: unix-socket peers stay trusted, TCP peers prove a
gg_hba.json password via challenge-response (never sent on the wire)."""

import json
import socket

import pytest

import greengage_tpu
from greengage_tpu.runtime import auth
from greengage_tpu.runtime.server import SqlClient, SqlServer


@pytest.fixture()
def served(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=2)
    d.sql("create table t (a int) distributed by (a)")
    d.sql("insert into t values (1), (2), (3)")
    auth.add_user(d.path, "alice", "s3cret")
    srv = SqlServer(d, str(tmp_path / "s.sock"), host="127.0.0.1", port=0)
    srv.start()
    yield d, srv, str(tmp_path / "s.sock")
    srv.stop()
    d.close()


def test_tcp_auth_roundtrip(served):
    d, srv, _ = served
    c = SqlClient(host="127.0.0.1", port=srv.port,
                  user="alice", password="s3cret")
    r = c.sql("select count(*), sum(a) from t")
    assert r["rows"] == [[3, 6]]
    c.sql("insert into t values (10)")
    assert c.sql("select count(*) from t")["rows"] == [[4]]
    c.close()


def test_wrong_password_rejected(served):
    _, srv, _ = served
    with pytest.raises(PermissionError, match="authentication failed"):
        SqlClient(host="127.0.0.1", port=srv.port,
                  user="alice", password="nope")


def test_unknown_user_rejected_without_leaking(served):
    _, srv, _ = served
    # the challenge for an unknown user must look like any other (no
    # user-existence oracle); the proof still fails
    s = socket.create_connection(("127.0.0.1", srv.port))
    f = s.makefile("rwb")
    f.write((json.dumps({"user": "mallory"}) + "\n").encode())
    f.flush()
    ch = json.loads(f.readline())
    assert set(ch) == {"auth", "salt", "nonce"}
    f.write((json.dumps({"proof": "0" * 64}) + "\n").encode())
    f.flush()
    assert json.loads(f.readline())["ok"] is False
    s.close()


def test_password_never_on_wire(served):
    """The handshake carries user/salt/nonce/proof only."""
    _, srv, _ = served
    s = socket.create_connection(("127.0.0.1", srv.port))
    f = s.makefile("rwb")
    f.write((json.dumps({"user": "alice"}) + "\n").encode())
    f.flush()
    ch = json.loads(f.readline())
    proof = auth.prove(ch["salt"], ch["nonce"], "s3cret")
    assert "s3cret" not in proof
    f.write((json.dumps({"proof": proof}) + "\n").encode())
    f.flush()
    assert json.loads(f.readline())["ok"] is True
    s.close()


def test_unix_socket_stays_trusted(served):
    _, _, sock = served
    c = SqlClient(sock)
    assert c.sql("select 1 + 1")["rows"] == [[2]]
    c.close()


def test_useradd_cli(devices8, tmp_path):
    from greengage_tpu.mgmt import cli

    path = str(tmp_path / "c2")
    greengage_tpu.connect(path, numsegments=2).close()
    rc = cli.main(["useradd", "-d", path, "-u", "bob", "-P", "pw"])
    assert rc == 0
    users = auth.load_users(path)
    assert "bob" in users and users["bob"]["hash"] != "pw"
    import os
    assert (os.stat(auth._hba_path(path)).st_mode & 0o777) == 0o600


def test_unknown_user_salt_is_stable(served):
    """No user-existence oracle via salt stability: unknown users get the
    SAME deterministic mock salt across connections."""
    _, srv, _ = served
    salts = []
    for _ in range(2):
        s = socket.create_connection(("127.0.0.1", srv.port))
        f = s.makefile("rwb")
        f.write((json.dumps({"user": "ghost"}) + "\n").encode())
        f.flush()
        salts.append(json.loads(f.readline())["salt"])
        s.close()
    assert salts[0] == salts[1]


def test_dropped_handshake_no_traceback(served):
    _, srv, _ = served
    s = socket.create_connection(("127.0.0.1", srv.port))
    s.close()          # drop before the hello; server must not traceback
    c = SqlClient(host="127.0.0.1", port=srv.port,
                  user="alice", password="s3cret")
    assert c.sql("select 1")["rows"] == [[1]]
    c.close()
