"""Mid-flight memory enforcement — the vmem tracker / red-zone handler /
runaway cleaner roles (vmem_tracker.c, redzone_handler.c,
runaway_cleaner.c). Cross-statement: per-statement admission cannot see
the cluster-wide in-flight total; the tracker flags the heaviest
statement at red zone and it dies at its next cancellation point (tier /
spill-pass boundary), while lighter concurrent statements complete."""

import threading
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.runaway import TRACKER, RunawayCancelled


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    n = 1_200_000
    rng = np.random.default_rng(4)
    d.sql("create table heavy (k int, g int, v int) distributed by (k)")
    d.load_table("heavy", {"k": np.arange(n),
                           "g": (np.arange(n) % 23).astype(np.int32),
                           "v": rng.integers(0, 100, n)})
    d.sql("create table light (a int, v int) distributed by (a)")
    d.load_table("light", {"a": np.arange(100_000, dtype=np.int32),
                           "v": rng.integers(0, 9, 100_000).astype(np.int32)})
    d.sql("analyze")
    yield d
    d.close()


def test_tracker_red_zone_picks_heaviest():
    done = threading.Event()
    picked = {}

    def heavy():
        TRACKER.enter()
        try:
            TRACKER.reprice(100 << 20, 64 << 20, 0.9)
            done.wait(5)
            try:
                TRACKER.check()
                picked["heavy"] = False
            except RunawayCancelled:
                picked["heavy"] = True
        finally:
            TRACKER.release()

    t = threading.Thread(target=heavy)
    t.start()
    time.sleep(0.2)
    TRACKER.enter()
    try:
        # 100MB + 10MB > 0.9 * 64MB: the 100MB statement is the runaway
        TRACKER.reprice(10 << 20, 64 << 20, 0.9)
        TRACKER.check()          # the light statement survives
    finally:
        TRACKER.release()
        done.set()
        t.join()
    assert picked["heavy"] is True


def test_runaway_spill_query_canceled_while_small_completes(db):
    """A spilling statement (many passes = many cancellation points) is
    flagged when concurrent admissions cross the red zone; it dies with
    the cleaner's message while the small statements finish."""
    db.sql("set vmem_protect_limit_mb = 1")     # heavy query must spill
    db.sql("set vmem_global_limit_mb = 1")
    db.sql("set runaway_red_zone = 0.6")        # red zone: 0.6 MB total
    err: dict = {}

    def heavy():
        try:
            db.sql("select g, count(*), sum(v) from heavy group by g")
            err["heavy"] = None
        except Exception as e:
            err["heavy"] = str(e)

    t = threading.Thread(target=heavy)
    try:
        t.start()
        time.sleep(0.5)          # let it enter the spill pass loop
        for _ in range(200):     # small statements keep being admitted
            r = db.sql("select sum(v) from light")
            assert len(r.rows()) == 1
            if not t.is_alive():
                break
            time.sleep(0.05)
        t.join(timeout=120)
        assert not t.is_alive()
        assert err["heavy"] is not None, "heavy statement should be canceled"
        assert "runaway cleaner" in err["heavy"], err["heavy"]
    finally:
        db.sql("set vmem_global_limit_mb = 0")
        db.sql("set runaway_red_zone = 0.9")
        db.sql("set vmem_protect_limit_mb = 12288")


def test_no_global_limit_means_no_enforcement(db):
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        r = db.sql("select g, count(*) from heavy group by g")
        assert r.stats.get("spill_passes", 0) >= 2
        assert len(r.rows()) == 23
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
