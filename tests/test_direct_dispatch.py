"""Direct dispatch: distribution-key point queries stage ONE segment —
VERDICT r1 missing item #8 (cdbtargeteddispatch.c analog)."""

import numpy as np
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table pts (id bigint, v int) distributed by (id)")
    d.sql("insert into pts values " + ",".join(f"({i},{i * 3})" for i in range(200)))
    d.sql("create table tkey (name text, v int) distributed by (name)")
    d.sql("insert into tkey values ('alpha', 1), ('beta', 2), ('gamma', 3)")
    return d


def test_point_query_stages_one_segment(db):
    r = db.sql("select v from pts where id = 42")
    assert r.rows() == [(126,)]
    assert "pts" in r.stats["direct_dispatch"]
    # the pinned segment is the row's true placement
    schema = db.catalog.get("pts")
    seg = db.store.segment_for_values(schema, {"id": 42})
    assert r.stats["direct_dispatch"]["pts"] == seg


def test_point_query_results_match_full_scan(db):
    for key in (0, 7, 199):
        r = db.sql(f"select v from pts where id = {key}")
        assert r.rows() == [(key * 3,)]


def test_direct_dispatch_text_key(db):
    r = db.sql("select v from tkey where name = 'beta'")
    assert r.rows() == [(2,)]
    assert "tkey" in r.stats["direct_dispatch"]


def test_absent_text_key_is_empty_not_error(db):
    r = db.sql("select v from tkey where name = 'nope'")
    assert r.rows() == []


def test_no_direct_on_partial_key_or_range(db):
    r = db.sql("select count(*) from pts where id > 100")
    assert r.rows() == [(99,)]
    assert "pts" not in r.stats.get("direct_dispatch", {})


def test_explain_shows_direct(db):
    txt = db.sql("explain select v from pts where id = 42")
    s = txt if isinstance(txt, str) else "\n".join(
        str(row[0]) for row in txt.rows())
    assert "direct dispatch" in s


def test_direct_with_extra_conjuncts(db):
    r = db.sql("select v from pts where id = 10 and v > 0")
    assert r.rows() == [(30,)]
    assert "pts" in r.stats["direct_dispatch"]
