"""Observability subsystem (docs/OBSERVABILITY.md): statement tracing
spans, per-operator EXPLAIN ANALYZE, the Prometheus metrics exposition,
and the slow-statement log — the gpperfmon-analog PR's acceptance tests.
"""

import json
import os
import re
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.logger import (counters, histograms,
                                          prometheus_text, read_entries)
from greengage_tpu.runtime.trace import (TRACES, Trace, TraceRegistry,
                                         to_chrome)


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table obs (k int, g int, v int) distributed by (k)")
    n = 5000
    d.load_table("obs", {"k": np.arange(n), "g": np.arange(n) % 7,
                         "v": np.arange(n) % 11})
    d.sql("create table dimt (g int, tag int) distributed by (g)")
    d.sql("insert into dimt values " + ",".join(
        f"({i},{i * 10})" for i in range(7)))
    # spill corpus (mirrors test_spill.py's shape at a smaller scale)
    d.sql("create table sdim (pk int, grp int) distributed by (pk)")
    d.sql("insert into sdim values " + ",".join(
        f"({i},{i % 11})" for i in range(1, 301)))
    d.sql("create table sbig (k int, fk int, v int) distributed by (k)")
    nb = 200_000
    rng = np.random.default_rng(8)
    d.load_table("sbig", {"k": np.arange(nb),
                          "fk": rng.integers(1, 301, nb),
                          "v": rng.integers(0, 100, nb)})
    d.sql("analyze")
    return d


# ---------------------------------------------------------------------------
# span tree
# ---------------------------------------------------------------------------

def test_span_tree_local_statement(db):
    db.sql("select g, count(*) from obs group by g order by g")
    tr = TRACES.last()
    assert tr is not None
    spans = tr.export()
    names = [s["name"] for s in spans]
    for want in ("statement", "parse", "stage", "stage:obs", "dispatch",
                 "fetch", "finalize"):
        assert want in names, names
    by_id = {s["id"]: s for s in spans}
    root = next(s for s in spans if s["name"] == "statement")
    assert root["parent"] is None
    # every other span parents (transitively) under the statement root
    for s in spans:
        if s["id"] == root["id"]:
            continue
        p = s
        hops = 0
        while p["parent"] is not None and hops < 50:
            p = by_id[p["parent"]]
            hops += 1
        assert p["id"] == root["id"], s
    # the per-table staging unit is a child of the stage phase
    st = next(s for s in spans if s["name"] == "stage")
    stt = next(s for s in spans if s["name"] == "stage:obs")
    assert stt["parent"] == st["id"]
    assert stt["args"].get("kind") in ("read", "hit", "dup")
    # durations recorded, non-negative
    assert all(s["dur"] is not None and s["dur"] >= 0 for s in spans)


def test_trace_id_is_statement_id_and_ring_lookup(db):
    db.sql("select count(*) from obs")
    tr = TRACES.last()
    assert tr.trace_id > 0
    assert TRACES.get(tr.trace_id) is tr


def test_chrome_export_shape(db):
    db.sql("select count(*) from obs where v > 3")
    ch = to_chrome(TRACES.last())
    evs = ch["traceEvents"]
    assert isinstance(evs, list) and evs
    xs = [e for e in evs if e.get("ph") == "X"]
    assert xs, evs
    for e in xs:
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float))
        assert "span_id" in e["args"] and "parent" in e["args"]
    # metadata names the threads; the whole thing round-trips JSON
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in evs)
    json.loads(json.dumps(ch))
    assert ch["otherData"]["sql"].startswith("select count(*)")


def test_trace_disabled_records_nothing(db):
    db.sql("set trace_enabled = off")   # the SET itself is still traced
    try:
        last_id = TRACES.last().trace_id
        db.sql("select count(*) from obs")
        # no new ring entry: the statement ran untraced
        assert TRACES.last().trace_id == last_id
    finally:
        db.sql("set trace_enabled = on")


def test_active_span_registry_surface():
    reg = TraceRegistry()
    tr, outer = reg.enter(4242, "select 1", enabled=True)
    assert outer
    sid = tr.begin("stage", cat="stage")
    name, ms = reg.active_span(4242)
    assert name == "stage" and ms >= 0
    tr.end(sid)
    reg.exit(tr)
    assert reg.active_span(4242) is None
    assert reg.get(4242) is tr   # retired to the ring


def test_trace_ring_bounded():
    reg = TraceRegistry(ring_size=3)
    for i in range(10, 16):
        tr, _ = reg.enter(i, f"q{i}")
        reg.exit(tr)
    assert reg.get(10) is None and reg.get(12) is None
    assert reg.get(15) is not None
    assert reg.last().trace_id == 15


# ---------------------------------------------------------------------------
# per-operator EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_per_node_rows_vs_oracle(db):
    r = db.sql("explain analyze select o.g, count(*), sum(o.v) "
               "from obs o join dimt d on o.g = d.g "
               "group by o.g order by o.g")
    text = r.plan_text
    # the scan of the fact table reports exactly its row count
    scan_line = next(ln for ln in text.splitlines() if "Scan obs" in ln)
    assert "actual rows=5000" in scan_line, scan_line
    # per-node device attribution on every instrumented node
    assert "device ~" in scan_line and "host-attributed" in scan_line
    # a Motion node reports moved bytes
    motion_lines = [ln for ln in text.splitlines()
                    if "Motion" in ln and "actual rows=" in ln]
    assert any("motion ~" in ln and re.search(r"motion ~\d+ B", ln)
               for ln in motion_lines), text
    # the legacy statement-level lines survive (tests + docs rely on them)
    assert "Host data path: staging" in text
    assert "Execution time:" in text


def test_explain_analyze_spill_per_node(db):
    q = ("select grp, count(*), sum(v) from sbig join sdim "
         "on sbig.fk = sdim.pk group by grp order by grp")
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 2")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
        ea = db.sql("explain analyze " + q)
        text = ea.plan_text
        assert "Spill passes:" in text, text
        # per-plan-node rows survive spilling: the fact scan's count sums
        # across passes back to the full table cardinality
        scan_line = next(ln for ln in text.splitlines()
                         if "Scan sbig" in ln)
        assert "actual rows=200000" in scan_line, scan_line
        assert "device ~" in scan_line
        # spill passes leave spans in the trace
        names = [s["name"] for s in TRACES.last().export()]
        assert "spill-pass" in names and "spill-merge" in names, names
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_explain_analyze_sort_spill_per_node(db):
    q = "select k, v from sbig where v >= 50 order by v desc, k limit 20"
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        ea = db.sql("explain analyze " + q)
        text = ea.plan_text
        assert "Spill passes:" in text, text
        # sorted-run passes share node objects with the original plan, so
        # the scan's count sums across passes to the full cardinality
        scan_line = next(ln for ln in text.splitlines()
                         if "Scan sbig" in ln)
        assert "actual rows=200000" in scan_line, scan_line
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


# ---------------------------------------------------------------------------
# metrics exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{le="([^"\\]+)"\})? '
    r'(-?[0-9.]+(?:[eE][+-]?[0-9]+)?|\+Inf|NaN)$')
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|histogram|summary|untyped)$")


def _parse_exposition(text):
    """prometheus_client-style text parser: every line is a sample, a
    # TYPE comment, or blank; TYPE precedes its family's samples;
    histograms are cumulative with le="+Inf" == _count."""
    types, samples = {}, {}
    seen_families = set()
    for ln in text.splitlines():
        if not ln.strip():
            continue
        m = _TYPE_RE.match(ln)
        if m:
            assert m.group(1) not in types, f"duplicate TYPE: {ln}"
            types[m.group(1)] = m.group(2)
            continue
        assert not ln.startswith("#"), f"unknown comment: {ln}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        name, _, le, val = m.groups()
        fam = re.sub(r"_(bucket|sum|count)$", "", name) \
            if name.endswith(("_bucket", "_sum", "_count")) else name
        assert fam in types or name in types, \
            f"sample before TYPE: {ln}"
        seen_families.add(fam)
        samples.setdefault(name, []).append(
            (le, float(val.replace("+Inf", "inf"))))
    return types, samples


def test_metrics_exposition_parses(db):
    db.sql("select count(*) from obs")
    text = prometheus_text()
    types, samples = _parse_exposition(text)
    # counter vs gauge typing (satellite: gauge names must not be
    # mislabeled as counters)
    assert types.get("ggtpu_mh_topology_version") == "gauge"
    assert types.get("ggtpu_plan_cache_hit", "counter") == "counter"
    # the statement-latency histogram is present and well-formed
    assert types.get("ggtpu_statement_ms") == "histogram"
    buckets = samples["ggtpu_statement_ms_bucket"]
    vals = [v for _le, v in buckets]
    assert vals == sorted(vals), "buckets must be cumulative"
    inf = [v for le, v in buckets if le == "+Inf"]
    count = samples["ggtpu_statement_ms_count"][0][1]
    assert inf and inf[0] == count
    assert count >= 1
    assert samples["ggtpu_statement_ms_sum"][0][1] >= 0
    # host-data-path phase histograms ride along
    for fam in ("ggtpu_stage_ms", "ggtpu_dispatch_ms", "ggtpu_fetch_ms",
                "ggtpu_queue_wait_ms"):
        assert types.get(fam) == "histogram", sorted(types)


def test_gauge_tagging_on_counters():
    counters.set("mh_topology_version", 7)
    counters.inc("some_test_counter_obs")
    assert "mh_topology_version" in counters.gauges()
    assert counters.kind("mh_topology_version") == "gauge"
    assert counters.kind("some_test_counter_obs") == "counter"


def test_server_metrics_and_trace_ops(db, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    srv = SqlServer(db, str(tmp_path / "obs.sock"))
    srv.start()
    try:
        c = SqlClient(str(tmp_path / "obs.sock"))
        c.sql("select count(*) from obs")
        m = c.op({"op": "metrics"})
        assert m["ok"] and "# TYPE ggtpu_statement_ms histogram" in m["text"]
        _parse_exposition(m["text"])
        t = c.op({"op": "trace"})
        assert t["ok"], t
        evs = t["trace"]["traceEvents"]
        assert any(e.get("name") == "statement" for e in evs)
        ps = c.op({"op": "ps"})
        assert ps["ok"]
        bad = c.op({"op": "trace", "id": 99999999})
        assert not bad["ok"]
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# slow-statement log
# ---------------------------------------------------------------------------

def test_slow_statement_log_fires_at_threshold(db):
    def slow_entries():
        return [e for e in read_entries(db.path)
                if e["kind"] == "slow_statement"]

    db.sql("set log_min_duration_ms = 0")   # every statement qualifies
    try:
        db.sql("select count(*) from obs")
    finally:
        db.sql("set log_min_duration_ms = -1")
    entries = slow_entries()
    assert entries, "slow log did not fire at threshold 0"
    msg = entries[-1]["message"]
    assert "trace=" in msg and "plan=" in msg, msg
    assert float(entries[-1]["duration_ms"]) >= 0
    # the trace JSON export lands beside the CSV logs
    tid = re.search(r"trace=(\d+)", msg).group(1)
    tpath = os.path.join(db.path, "log", f"trace-{tid}.json")
    assert os.path.exists(tpath), tpath
    with open(tpath) as f:
        assert json.load(f)["traceEvents"]
    # and never fires for statements under the threshold
    n0 = len(slow_entries())
    db.sql("set log_min_duration_ms = 100000000")
    try:
        db.sql("select count(*) from obs")
    finally:
        db.sql("set log_min_duration_ms = -1")
    assert len(slow_entries()) == n0
    assert counters.get("slow_statements") >= 1


# ---------------------------------------------------------------------------
# overhead bound (acceptance: <= 5% on the warm plan-cache microbench)
# ---------------------------------------------------------------------------

def test_trace_overhead_bounded_on_warm_statement(db):
    q = "select count(*), sum(v) from obs where v > 3"
    db.sql(q)   # compile + cache
    runs, t0 = 5, time.perf_counter()
    for _ in range(runs):
        db.sql(q)
    warm_ms = (time.perf_counter() - t0) * 1e3 / runs
    nspans = len(TRACES.last().export())
    assert nspans <= 32, nspans   # warm path records a bounded span set
    # measured per-span record cost x spans per statement must stay under
    # 5% of the warm statement (timer-verified, not assumed)
    tr = Trace(0, "overhead-probe")
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        sid = tr.begin("probe", cat="exec", n=1)
        tr.end(sid)
    per_span_ms = (time.perf_counter() - t0) * 1e3 / reps
    overhead_ms = per_span_ms * nspans
    assert overhead_ms <= 0.05 * warm_ms, (
        f"trace overhead {overhead_ms:.4f} ms vs warm {warm_ms:.2f} ms "
        f"({nspans} spans @ {per_span_ms * 1e3:.2f} us)")


# ---------------------------------------------------------------------------
# multihost: worker spans land in the coordinator's trace
# ---------------------------------------------------------------------------

OBS_COORD_SCRIPT = r"""
import json, os, sys
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
db.sql("create table f (k bigint, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(
    f"({i}, {i % 7})" for i in range(2000)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
from greengage_tpu.runtime.trace import TRACES, to_chrome
out = {"rows": [int(x) for x in r.rows()[0]],
       "trace": to_chrome(TRACES.last())}
mh.channel.close()
print("RESULT:" + json.dumps(out), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_multihost_worker_spans_parent_under_dispatch(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": repo, "PYTHONPATH": repo,
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", OBS_COORD_SCRIPT, str(port), str(cport), path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=420)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"multihost timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])
    assert out["rows"] == [2000, sum(i % 7 for i in range(2000))]

    evs = out["trace"]["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    tid_names = {e["tid"]: e["args"]["name"] for e in evs
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
    # the coordinator recorded the multihost dispatch span
    disp = [e for e in xs if e["name"] == "dispatch"
            and e["cat"] == "multihost"]
    assert disp, [e["name"] for e in xs]
    disp_id = disp[0]["args"]["span_id"]
    # worker-side spans were grafted, tagged with the worker's tid...
    wevs = [e for e in xs
            if str(tid_names.get(e["tid"], "")).startswith("worker-")]
    assert wevs, f"no worker spans in {[e['name'] for e in xs]}"
    wnames = {e["name"] for e in wevs}
    assert "dispatch" in wnames or "stage" in wnames, wnames
    # ...and parent (transitively) under the coordinator's dispatch span
    by_id = {e["args"]["span_id"]: e for e in xs}
    for e in wevs:
        p, hops = e, 0
        while p["args"]["parent"] is not None and hops < 50:
            if p["args"]["parent"] == disp_id:
                break
            p = by_id[p["args"]["parent"]]
            hops += 1
        assert p["args"]["parent"] == disp_id or \
            p["args"]["span_id"] == disp_id, e
