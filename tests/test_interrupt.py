"""Statement lifecycle guardrails (runtime/interrupt.py): cooperative
cancellation at every wait state, statement timeouts, the unified
counter family, and the server's cancel protocol + client_gone handling.
The CHECK_FOR_INTERRUPTS / statement_timeout / pg_cancel_backend analog
(tcop/postgres.c ProcessInterrupts)."""

import json
import socket
import threading
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.interrupt import (REGISTRY, StatementCancelled,
                                             StatementContext)
from greengage_tpu.runtime.logger import counters


# ---------------------------------------------------------------------------
# pure-host primitives (no devices)
# ---------------------------------------------------------------------------

def test_context_check_raises_typed_cause():
    ctx = StatementContext(1, "select 1")
    ctx.check()                      # unflagged: no-op
    ctx.cancel("user")
    with pytest.raises(StatementCancelled) as ei:
        ctx.check()
    assert ei.value.cause == "user"
    assert "user request" in str(ei.value)
    ctx.cancel("timeout")            # first cause wins
    assert ctx.cause == "user"


def test_context_timeout_trips_flag():
    ctx = StatementContext(2, "select 1", timeout_s=0.05)
    assert ctx.remaining() <= 0.05
    time.sleep(0.08)
    assert ctx.cancelled
    with pytest.raises(StatementCancelled) as ei:
        ctx.check()
    assert ei.value.cause == "timeout"
    assert "statement timeout" in str(ei.value)


def test_context_listener_fires_on_cancel_and_immediately_when_late():
    ctx = StatementContext(3, "x")
    hits = []
    ctx.add_listener(lambda: hits.append("a"))
    ctx.cancel("user")
    assert hits == ["a"]
    ctx.add_listener(lambda: hits.append("b"))   # late: fires at once
    assert hits == ["a", "b"]


def test_registry_nesting_and_cancel_by_id():
    ctx, outer = REGISTRY.enter("select 1")
    try:
        assert outer
        inner, inner_outer = REGISTRY.enter("nested")
        assert inner is ctx and not inner_outer   # shared outermost ctx
        REGISTRY.exit(inner)
        assert REGISTRY.current() is ctx
        rows = REGISTRY.snapshot()
        assert any(r["id"] == ctx.statement_id for r in rows)
        assert REGISTRY.cancel(ctx.statement_id, "user")
        assert ctx.cancelled
        assert not REGISTRY.cancel(999999)        # unknown id: False
    finally:
        REGISTRY.exit(ctx)
    assert REGISTRY.current() is None


def test_registry_cancel_all_flags_everything():
    ctx, _ = REGISTRY.enter("select 1")
    try:
        assert REGISTRY.cancel_all("shutdown") >= 1
        assert ctx.cause == "shutdown"
    finally:
        REGISTRY.exit(ctx)


# ---------------------------------------------------------------------------
# engine-level cancellation at each wait state
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    n = 50_000
    d.sql("create table li (k int, g int, v int) distributed by (k)")
    d.load_table("li", {"k": np.arange(n), "g": (np.arange(n) % 11),
                        "v": (np.arange(n) % 7)})
    d.sql("analyze")
    yield d
    d.close()


def _cancel_sql(marker: str, cause: str = "user", timeout_s: float = 5.0):
    """Wait until a statement whose text carries ``marker`` shows in the
    registry, then cancel it; -> its id (None if never seen)."""
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        for row in REGISTRY.snapshot():
            if marker in row["sql"]:
                REGISTRY.cancel(row["id"], cause)
                return row["id"]
        time.sleep(0.01)
    return None


def test_statement_timeout_cancels_in_staging(db):
    """statement_timeout_s arms at statement start and the statement dies
    at a staging-unit cancellation point (scan_threads=1: units run
    serially on the statement thread, so the per-unit sleep fault makes
    the deadline trip deterministic)."""
    db.sql("set scan_threads = 1")
    db.sql("set statement_timeout_s = 0.3")
    faults.inject("cancel_in_staging", "sleep", sleep_s=0.2, occurrences=-1)
    base = counters.get("statements_cancelled_timeout")
    try:
        with pytest.raises(StatementCancelled) as ei:
            db.sql("select count(*) from li where v = 3 -- timeout-victim")
        assert ei.value.cause == "timeout"
        assert counters.get("statements_cancelled_timeout") == base + 1
    finally:
        faults.reset("cancel_in_staging")
        db.sql("set statement_timeout_s = 0")
        db.sql("set scan_threads = 0")
    # the registry is clean and the session still serves
    assert REGISTRY.current() is None
    assert db.sql("select count(*) from li").rows()[0][0] == 50_000


def test_user_cancel_lands_mid_staging(db):
    """`gg cancel` semantics: a statement parked in cold staging reads is
    cancelled mid-flight (between read units), within a bounded time."""
    db.sql("set scan_threads = 1")
    faults.inject("cancel_in_staging", "sleep", sleep_s=0.25, occurrences=-1)
    err = {}

    def victim():
        try:
            db.sql("select sum(v) from li -- staging-victim")
            err["e"] = None
        except Exception as e:
            err["e"] = e

    base = counters.get("statements_cancelled_user")
    t = threading.Thread(target=victim)
    t0 = time.monotonic()
    t.start()
    try:
        assert _cancel_sql("staging-victim") is not None
        t.join(timeout=10)
        assert not t.is_alive()
        assert isinstance(err["e"], StatementCancelled), err["e"]
        assert err["e"].cause == "user"
        # one boundary interval: a couple of 0.25s units, never a hang
        assert time.monotonic() - t0 < 5.0
        assert counters.get("statements_cancelled_user") == base + 1
    finally:
        faults.reset("cancel_in_staging")
        db.sql("set scan_threads = 0")


def test_cancel_statement_parked_in_resource_queue(db):
    """A queued statement observes cancellation IMMEDIATELY (listener
    wakeup, not the next timeout slice), re-notifies so the racing
    release is never lost, and counts in queue_cancelled_total."""
    db.sql("set resource_queue_active = 1")
    # the slot holder sleeps at the pre-dispatch fault, keeping the queue
    # full while the victim parks in admit()
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=1.5,
                  occurrences=1)
    res = {}

    def holder():
        try:
            res["holder"] = db.sql("select count(*) from li -- holder")
        except Exception as e:       # pragma: no cover
            res["holder"] = e

    def victim():
        try:
            db.sql("select sum(v) from li -- queue-victim")
            res["victim"] = None
        except Exception as e:
            res["victim"] = e

    qbase = counters.get("queue_cancelled_total")
    th = threading.Thread(target=holder)
    th.start()
    time.sleep(0.3)                  # holder admitted, now sleeping
    tv = threading.Thread(target=victim)
    t0 = time.monotonic()
    tv.start()
    try:
        assert _cancel_sql("queue-victim") is not None
        tv.join(timeout=10)
        assert not tv.is_alive(), "cancelled waiter never left the queue"
        waited = time.monotonic() - t0
        assert isinstance(res["victim"], StatementCancelled), res["victim"]
        assert res["victim"].cause == "user"
        assert waited < 1.4, f"queue exit took {waited:.2f}s (not immediate)"
        assert counters.get("queue_cancelled_total") == qbase + 1
        th.join(timeout=30)
        assert hasattr(res["holder"], "rows"), res["holder"]
        # the re-notify preserved the slot: a later statement admits fine
        assert db.sql("select count(*) from li").rows()[0][0] == 50_000
        assert db.resqueue.stats()["active"] == 0
    finally:
        faults.reset("cancel_before_dispatch")
        db.sql("set resource_queue_active = 0")


def test_cancel_between_spill_passes(db):
    """A spilling statement (pass-partitioned execution) is cancelled at
    a spill-pass boundary — the runaway cleaner's documented cancellation
    point, now shared by user cancels."""
    db.sql("set vmem_protect_limit_mb = 1")     # force the spill regime
    # slow each pass down at its pre-dispatch point so the cancel lands
    # while passes remain
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=0.3,
                  occurrences=-1)
    err = {}

    def victim():
        try:
            db.sql("select g, count(*), sum(v) from li group by g"
                   " -- spill-victim")
            err["e"] = None
        except Exception as e:
            err["e"] = e

    t = threading.Thread(target=victim)
    t.start()
    try:
        assert _cancel_sql("spill-victim") is not None
        t.join(timeout=60)
        assert not t.is_alive()
        assert isinstance(err["e"], StatementCancelled), err["e"]
        assert err["e"].cause == "user"
    finally:
        faults.reset("cancel_before_dispatch")
        db.sql("set vmem_protect_limit_mb = 12288")
    assert db.sql("select count(*) from li").rows()[0][0] == 50_000


def test_statement_timeout_zero_disables(db):
    db.sql("set statement_timeout_s = 0")
    assert db.sql("select count(*) from li").rows()[0][0] == 50_000


# ---------------------------------------------------------------------------
# server protocol: cancel frame + client_gone on disconnect
# ---------------------------------------------------------------------------

def test_server_cancel_frame_and_typed_error(db, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=2.0,
                  occurrences=1)
    try:
        err = {}

        def client_victim():
            c = SqlClient(sock)
            try:
                c.sql("select sum(v) from li -- wire-victim")
                err["e"] = None
            except Exception as e:
                err["e"] = e
            finally:
                c.close()

        t = threading.Thread(target=client_victim)
        t.start()
        # a SECOND connection finds and cancels it (the executing one is
        # blocked in its statement, like pg_cancel_backend from psql)
        c2 = SqlClient(sock)
        end = time.monotonic() + 5
        sid = None
        while time.monotonic() < end and sid is None:
            for row in c2.op({"op": "ps"}).get("rows", []):
                if "wire-victim" in row["sql"]:
                    sid = row["id"]
            time.sleep(0.02)
        assert sid is not None, "ps never showed the in-flight statement"
        assert c2.op({"op": "cancel", "id": sid}) == {"ok": True}
        assert c2.op({"op": "cancel", "id": 999999})["ok"] is False
        assert c2.op({"op": "bogus"})["ok"] is False
        c2.close()
        t.join(timeout=15)
        assert not t.is_alive()
        assert err["e"] is not None
        assert "cancel" in str(err["e"]).lower()
    finally:
        faults.reset("cancel_before_dispatch")
        srv.stop()


def test_client_disconnect_cancels_in_flight_statement(db, tmp_path):
    """The per-statement watcher observes the client's EOF while the
    handler thread is blocked in db.sql() and flags the statement
    client_gone — it dies at its next cancellation point instead of
    running to completion for nobody."""
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=3.0,
                  occurrences=1)
    base = counters.get("statements_cancelled_client_gone")
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(sock)
        s.sendall((json.dumps(
            {"sql": "select sum(v) from li -- gone-victim"}) + "\n")
            .encode())
        time.sleep(0.5)           # statement parked at the fault sleep
        s.close()                 # client vanishes mid-statement
        end = time.monotonic() + 15
        while counters.get("statements_cancelled_client_gone") == base \
                and time.monotonic() < end:
            time.sleep(0.05)
        assert counters.get("statements_cancelled_client_gone") == base + 1
        # the statement left the registry and the server still serves
        end = time.monotonic() + 5
        while any("gone-victim" in r["sql"] for r in REGISTRY.snapshot()) \
                and time.monotonic() < end:
            time.sleep(0.05)
        assert not any("gone-victim" in r["sql"]
                       for r in REGISTRY.snapshot())
        c = SqlClient(sock)
        assert c.sql("select count(*) from li")["rows"][0][0] == 50_000
        c.close()
    finally:
        faults.reset("cancel_before_dispatch")
        srv.stop()


def test_server_survives_client_disconnect_mid_exchange(db, tmp_path):
    """A client that sends a statement and vanishes must not let the
    broken pipe escape into socketserver: the handler ends cleanly and
    the server keeps serving other clients."""
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        for _ in range(3):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(sock)
            s.sendall((json.dumps(
                {"sql": "select count(*) from li"}) + "\n").encode())
            s.close()                       # gone before reading the rows
        time.sleep(0.3)                     # let the handlers run into it
        c = SqlClient(sock)                 # the server still serves
        assert c.sql("select count(*) from li")["rows"][0][0] == 50_000
        c.close()
    finally:
        srv.stop()
