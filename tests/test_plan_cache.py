"""Parameterized plan + executable cache (ISSUE 5): recompile-count
regression tests. XLA compiles are counted by monkeypatching the
jax.jit wrap in exec/compile.py — one jit() call per compiled program —
so the assertions are deterministic (never wall clocks).

The contract under test (docs/PERF.md "Plan cache"):
  (a) two SELECTs differing only in hoistable literals compile ONCE and
      both return value-correct results;
  (b) a DML that stays inside every capacity bucket does not invalidate
      the cached executable;
  (c) unsafe literals (partition-prune keys, LIMIT counts) correctly
      miss the cache — planning-relevant values never generalize.
"""

import numpy as np
import pytest

import greengage_tpu
import greengage_tpu.exec.compile as C
from greengage_tpu.runtime.logger import counters


@pytest.fixture()
def jits(monkeypatch):
    """Counts compiled programs: exec/compile.py wraps every traced
    query program in exactly one jax.jit call."""
    calls = {"n": 0}
    real = C.jax.jit

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(C.jax, "jit", counting)
    return calls


@pytest.fixture()
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    # pin the cache micro-contract in isolation: the self-tuning loop
    # (planner/feedback.py) deliberately re-plans a shape ONCE after a
    # calibration promotion, which would perturb the exact hit counts
    # asserted here; tests/test_feedback.py owns that interplay
    d.set("cost_feedback", False)
    d.sql("create table t (k int, a int, v double precision) "
          "distributed by (k)")
    d.load_table("t", {"k": np.arange(3000, dtype=np.int32),
                       "a": np.arange(3000, dtype=np.int32),
                       "v": np.arange(3000) * 0.5})
    return d


def test_repeated_shape_compiles_once(db, jits):
    """(a) Different hoistable literals: one plan, one executable, and
    value-correct results for every binding."""
    r1 = db.sql("select count(*) from t where a > 100")
    n1 = jits["n"]
    assert n1 >= 1 and r1.rows()[0][0] == 2899
    c0 = counters.snapshot()
    r2 = db.sql("select count(*) from t where a > 2000")
    r3 = db.sql("select count(*) from t where a > 100")
    assert jits["n"] == n1, "literal-only change must not recompile"
    assert r2.rows()[0][0] == 999
    assert r3.rows()[0][0] == 2899
    d = counters.since(c0)
    assert d.get("plan_cache_hit", 0) == 2
    assert d.get("program_cache_hit", 0) == 2
    assert not d.get("program_cache_miss")
    assert r2.stats["compiled"] is False
    assert r2.stats["plan_cache"] == {"hit": True, "params": 1,
                                      "fallback": False}


def test_float_and_arith_literals_hoist(db, jits):
    r1 = db.sql("select k, v * 2.5 from t where v < 10.0 and a >= 3")
    n1 = jits["n"]
    r2 = db.sql("select k, v * 7.5 from t where v < 4.0 and a >= 1")
    assert jits["n"] == n1
    assert len(r1) == 17 and len(r2) == 7
    vals = sorted(x[1] for x in r2.rows())
    assert vals[0] == pytest.approx(0.5 * 7.5)   # row a=1: v=0.5 -> 3.75


def test_dml_within_bucket_keeps_executable(db, jits):
    """(b) An INSERT that stays inside the pow2 capacity bucket re-binds
    the plan (manifest version moved) but REUSES the compiled program."""
    r1 = db.sql("select count(*), sum(v) from t where a > 10")
    n1 = jits["n"]
    assert r1.stats["compiled"] is True
    # 3000 rows / 4 segs ~ 750/seg -> bucket 1024; a handful more stays in
    db.sql("insert into t values (90001, 90001, 1.0)")
    r2 = db.sql("select count(*), sum(v) from t where a > 10")
    assert jits["n"] == n1, "within-bucket DML must not recompile"
    assert r2.stats["compiled"] is False
    assert r2.rows()[0][0] == r1.rows()[0][0] + 1   # sees the new row


def test_unsafe_literals_miss(devices8, jits):
    """(c) Partition-prune keys and LIMIT counts stay pinned: a changed
    value is a different cache entry (and a fresh compile)."""
    db = greengage_tpu.connect(numsegments=4)
    db.sql("create table pt (d int, m int) distributed by (m) "
           "partition by range (d) "
           "(partition p1 start (0) end (100), "
           " partition p2 start (100) end (200))")
    db.load_table("pt", {"d": np.arange(200, dtype=np.int32),
                         "m": np.arange(200, dtype=np.int32)})
    r1 = db.sql("select count(*) from pt where d < 50")
    n1 = jits["n"]
    r2 = db.sql("select count(*) from pt where d < 150")
    assert jits["n"] > n1, "partition-key literal must not generalize"
    assert r1.rows()[0][0] == 50 and r2.rows()[0][0] == 150
    # static pruning stayed value-exact: one child staged vs two
    assert r1.stats["partitions"]["pt"] == 1
    assert r2.stats["partitions"]["pt"] == 2
    # LIMIT is part of the shape
    db.sql("select m from pt limit 5")
    n2 = jits["n"]
    r = db.sql("select m from pt limit 7")
    assert jits["n"] > n2 and len(r) == 7


def test_distkey_equality_pinned_direct_dispatch(db, jits):
    """Equality on the hash-distribution key keeps direct dispatch (a
    value-generic plan would have to stage every segment)."""
    r1 = db.sql("select v from t where k = 17")
    r2 = db.sql("select v from t where k = 23")
    assert r1.stats["direct_dispatch"].get("t") is not None
    assert r2.stats["direct_dispatch"].get("t") is not None
    assert r1.rows()[0][0] == 8.5 and r2.rows()[0][0] == 11.5


def test_signature_covers_unpinned_capacity_merge(devices8, jits):
    """Conflicting direct pins (two point-scans naming different segments)
    disable direct dispatch, and compile() raises the staged capacity to
    cover EVERY segment; shape_signature must digest that same post-merge
    capacity, so DML growing a NON-pinned segment past its pow2 bucket
    recompiles instead of reusing a too-small executable."""
    db = greengage_tpu.connect(numsegments=4)
    db.sql("create table u (k int, v int) distributed by (k)")
    schema = db.catalog.get("u")

    def seg_of(kv):
        return db.store.segment_for_values(schema, {"k": kv})

    k0 = 0
    k1 = next(k for k in range(1, 64) if seg_of(k) != seg_of(k0))
    other = next(s for s in range(4) if s not in (seg_of(k0), seg_of(k1)))
    kb = next(k for k in range(64, 4096) if seg_of(k) == other)
    # the bulk segment sits exactly AT a pow2 bucket boundary (128)
    ks = np.array([k0] * 4 + [k1] * 4 + [kb] * 128, dtype=np.int32)
    db.load_table("u", {"k": ks, "v": np.ones(len(ks), dtype=np.int32)})

    q = (f"select count(*) c from u where k = {k0} "
         f"union all select count(*) c from u where k = {k1}")
    r1 = db.sql(q)
    assert r1.rows() == [(4,), (4,)]
    n1 = jits["n"]
    db.sql(q)
    assert jits["n"] == n1, "repeated conflicting-pin shape must reuse"
    # grow the NON-pinned bulk segment 128 -> 129: crosses the bucket the
    # pinned segments never see, so the cached executable is too small
    db.sql(f"insert into u values ({kb}, 1)")
    n2 = jits["n"]
    r3 = db.sql(q)
    assert r3.rows() == [(4,), (4,)]
    assert jits["n"] > n2, \
        "bucket cross on a non-pinned segment must recompile"


def test_zone_prune_resolves_param_values(devices8):
    """A hoisted literal still drives zone-map pruning — resolved at
    staging time — and pruning follows the CURRENT value, not the value
    that populated the cache."""
    db = greengage_tpu.connect(numsegments=2)
    db.set("cost_feedback", False)   # see the db fixture note
    db.sql("create table zt (k int, a int) distributed by (k)")
    # loaded in 'a' order: each segment's ~3 blocks (65536 rows each) get
    # tight zone ranges, so a selective value prunes
    n = 400_000
    db.load_table("zt", {"k": np.arange(n, dtype=np.int32),
                         "a": np.arange(n, dtype=np.int32)})
    r1 = db.sql("select count(*) from zt where a >= 399000")
    r2 = db.sql("select count(*) from zt where a >= 500")   # cache hit
    assert r1.rows()[0][0] == 1000
    assert r2.rows()[0][0] == n - 500, "stale prune value would drop rows"
    assert r2.stats["plan_cache"]["hit"] is True
    zp1 = r1.stats["zone_prune"]["zt"]
    zp2 = r2.stats["zone_prune"]["zt"]
    # the selective value pruned strictly more blocks than the broad one
    assert zp1[1] > 2 and zp1[0] < zp2[0], (zp1, zp2)


def test_plan_cache_lru_and_hint_lifetime(db):
    """Satellites: real LRU (not FIFO) in both caches, bounded by the
    plan_cache_size GUC; cap-hint/fused bookkeeping dies with the last
    program of its statement."""
    db.sql("set plan_cache_size = 2")
    db.sql("select count(*) from t where a > 1")          # shape A
    db.sql("select sum(v) from t where a > 2")            # shape B
    db.sql("select count(*) from t where a > 3")          # touch A (LRU)
    db.sql("select max(a) from t where v < 9.0")          # shape C evicts B
    assert len(db.executor._plan_cache) <= 2
    c0 = counters.snapshot()
    db.sql("select count(*) from t where a > 4")          # A again
    assert counters.since(c0).get("program_cache_hit", 0) == 1, \
        "LRU must have kept the recently-touched shape A"
    # bookkeeping for statements no longer cached is dropped
    live = {k[0] for k in db.executor._plan_cache}
    assert set(db.executor._cap_hints) <= live
    db.sql("set plan_cache_size = 256")


def test_plan_cache_params_off(db, jits):
    """The GUC restores classic value-pinned behavior."""
    db.sql("set plan_cache_params = off")
    db.sql("select count(*) from t where a > 7")
    n1 = jits["n"]
    db.sql("select count(*) from t where a > 8")
    assert jits["n"] > n1
    db.sql("set plan_cache_params = on")


def test_explain_analyze_reports_plan_cache(db):
    db.sql("select count(*) from t where a > 42")
    r = db.sql("explain analyze select count(*) from t where a > 43")
    line = [ln for ln in r.plan_text.split("\n") if "Plan cache" in ln]
    assert line and "hit" in line[0] and "params hoisted" in line[0]
