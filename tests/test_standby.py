"""Master standby — gpinitstandby/gpactivatestandby analog (VERDICT r3
missing #6): the coordinator's catalog+manifest+dictionaries are no
longer a single point of failure. Continuous post-commit sync ships the
metadata; activation promotes the copy against the surviving data trees."""

import os
import shutil

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.mgmt import cli
from greengage_tpu.runtime import standby


@pytest.fixture()
def cluster(devices8, tmp_path):
    path = str(tmp_path / "primary")
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (k int, name text, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(100),
                       "name": greengage_tpu.types.Coded(
                           ["a", "b"], (np.arange(100) % 2).astype(np.int32)),
                       "v": np.arange(100)})
    return d, path, str(tmp_path / "standby")


def test_init_sync_and_lag_tracking(cluster):
    d, path, sb = cluster
    rc = cli.main(["initstandby", "-d", path, "-s", sb])
    assert rc == 0
    v0 = standby.status(sb)["synced_version"]
    # every committed write ships automatically from the post-commit hook
    d.sql("insert into t values (1000, 'a', 1000)")
    d.sql("insert into t values (1001, 'b', 1001)")
    st = standby.status(sb)
    assert st["synced_version"] >= v0 + 2
    assert st["synced_version"] == \
        d.store.manifest.snapshot()["version"]


def test_activation_after_primary_loss(cluster):
    d, path, sb = cluster
    cli.main(["initstandby", "-d", path, "-s", sb])
    d.sql("insert into t values (555, 'a', 555)")
    d.sql("delete from t where k < 10")          # visimap bitmap too
    d.close()
    # simulate losing the coordinator metadata but not the data trees
    # (disk holding catalog/manifest dies; shared/mirrored storage lives)
    survived_data = path + "_surviving_data"
    shutil.move(os.path.join(path, "data"), survived_data)
    shutil.rmtree(path)
    rc = cli.main(["activatestandby", "-s", sb, "--data", survived_data])
    assert rc == 0
    d2 = greengage_tpu.connect(path=sb, numsegments=4)
    assert d2.sql("select count(*) from t").rows()[0][0] == 91
    assert d2.sql("select v from t where k = 555").rows() == [(555,)]
    # TEXT dictionaries came across in the sync
    assert d2.sql("select count(*) from t where name = 'a'"
                  ).rows()[0][0] == 46
    # the promoted coordinator serves writes
    d2.sql("insert into t values (777, 'b', 777)")
    assert d2.sql("select count(*) from t").rows()[0][0] == 92


def test_failed_sync_never_fails_the_write(cluster):
    d, path, sb = cluster
    cli.main(["initstandby", "-d", path, "-s", sb])
    shutil.rmtree(sb)                      # standby host dies
    d.sql("insert into t values (42, 'a', 42)")   # must still succeed
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    # and the dead standby was NOT silently resurrected as an empty dir
    # that claims to be synced (the sync must have genuinely failed)
    assert not os.path.exists(os.path.join(sb, "manifest.json"))


def test_activated_standby_fenced_from_old_primary(cluster):
    """Split-brain fence: a partitioned old primary must never overwrite
    a PROMOTED standby's committed state."""
    d, path, sb = cluster
    cli.main(["initstandby", "-d", path, "-s", sb])
    standby.activate(sb, os.path.join(path, "data"))
    with pytest.raises(RuntimeError, match="ACTIVATED|split-brain"):
        standby.sync(path, sb)
    # the old primary keeps serving its own writes (sync failure logged)
    d.sql("insert into t values (42, 'a', 42)")
    assert d.sql("select count(*) from t").rows()[0][0] == 101


def test_activation_is_idempotent_and_stops_self_sync(cluster):
    d, path, sb = cluster
    cli.main(["initstandby", "-d", path, "-s", sb])
    d.close()
    standby.activate(sb, os.path.join(path, "data"))
    st = standby.activate(sb)              # second call: no-op
    assert st["role"] == "activated"
    assert standby.registered_standby(sb) is None
