"""Wider TPC-H coverage: Q10, Q12, Q14, Q19 (multi-key groups, CASE sums,
OR-of-AND predicates, text IN-lists) vs pandas oracle."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.002)
    return d


@pytest.fixture(scope="module")
def oracle():
    return tpch.to_pandas(tpch.generate(0.002))


def _days(s):
    return (np.datetime64(s) - np.datetime64("1970-01-01")).astype(int)


def test_q10_returned_item_reporting(db, oracle):
    r = db.sql("""
      select c_custkey, c_name,
             sum(l_extendedprice * (1 - l_discount)) as revenue,
             c_acctbal, n_name
      from customer, orders, lineitem, nation
      where c_custkey = o_custkey and l_orderkey = o_orderkey
        and o_orderdate >= date '1993-10-01'
        and o_orderdate < date '1993-10-01' + interval '3' month
        and l_returnflag = 'R' and c_nationkey = n_nationkey
      group by c_custkey, c_name, c_acctbal, n_name
      order by revenue desc limit 20
    """)
    c, o, li, n = (oracle[t] for t in ("customer", "orders", "lineitem", "nation"))
    j = (o[(o.o_orderdate >= _days("1993-10-01")) & (o.o_orderdate < _days("1994-01-01"))]
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(li[li.l_returnflag == "R"], left_on="o_orderkey", right_on="l_orderkey")
         .merge(n, left_on="c_nationkey", right_on="n_nationkey"))
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = (j.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"], as_index=False)
            .agg(revenue=("revenue", "sum"))
            .sort_values("revenue", ascending=False).head(20))
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.allclose(got.revenue, want.revenue, rtol=1e-12)
    assert list(got.c_custkey) == list(want.c_custkey)


def test_q12_shipmode_priority(db, oracle):
    r = db.sql("""
      select l_shipmode,
             sum(case when o_orderpriority = '1-URGENT'
                       or o_orderpriority = '2-HIGH' then 1 else 0 end) as high_line_count,
             sum(case when o_orderpriority <> '1-URGENT'
                       and o_orderpriority <> '2-HIGH' then 1 else 0 end) as low_line_count
      from orders, lineitem
      where o_orderkey = l_orderkey
        and l_shipmode in ('MAIL', 'SHIP')
        and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
        and l_receiptdate >= date '1994-01-01'
        and l_receiptdate < date '1994-01-01' + interval '1' year
      group by l_shipmode order by l_shipmode
    """)
    o, li = oracle["orders"], oracle["lineitem"]
    f = li[li.l_shipmode.isin(["MAIL", "SHIP"])
           & (li.l_commitdate < li.l_receiptdate) & (li.l_shipdate < li.l_commitdate)
           & (li.l_receiptdate >= _days("1994-01-01"))
           & (li.l_receiptdate < _days("1995-01-01"))]
    j = f.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    j["high"] = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    want = j.groupby("l_shipmode").agg(high=("high", "sum"),
                                       low=("high", lambda s: (1 - s).sum()))
    got = r.to_pandas()
    assert list(got.l_shipmode) == list(want.index)
    assert list(got.high_line_count) == list(want.high)
    assert list(got.low_line_count) == list(want.low)


def test_q14_promo_effect(db, oracle):
    r = db.sql("""
      select 100.00 * sum(case when p_type like 'type 1%'
                          then l_extendedprice * (1 - l_discount) else 0 end)
             / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
      from lineitem, part
      where l_partkey = p_partkey
        and l_shipdate >= date '1995-09-01'
        and l_shipdate < date '1995-09-01' + interval '1' month
    """)
    li, p = oracle["lineitem"], oracle["part"]
    f = li[(li.l_shipdate >= _days("1995-09-01")) & (li.l_shipdate < _days("1995-10-01"))]
    j = f.merge(p, left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev[j.p_type.str.startswith("type 1")].sum()
    want = 100.0 * promo / rev.sum()
    got = r.rows()[0][0]
    # decimal division result scale is 6 fractional digits (types.arith_result)
    assert got == pytest.approx(want, abs=5e-7)


def test_q19_discounted_revenue(db, oracle):
    r = db.sql("""
      select sum(l_extendedprice * (1 - l_discount)) as revenue
      from lineitem, part
      where p_partkey = l_partkey
        and ((p_brand = 'Brand#11' and l_quantity between 1 and 11
              and p_size between 1 and 5)
          or (p_brand = 'Brand#22' and l_quantity between 10 and 20
              and p_size between 1 and 10)
          or (p_brand = 'Brand#33' and l_quantity between 20 and 30
              and p_size between 1 and 15))
        and l_shipmode in ('AIR', 'REG AIR')
    """)
    li, p = oracle["lineitem"], oracle["part"]
    j = li[li.l_shipmode.isin(["AIR", "REG AIR"])].merge(
        p, left_on="l_partkey", right_on="p_partkey")
    m = (((j.p_brand == "Brand#11") & j.l_quantity.between(1, 11) & j.p_size.between(1, 5))
         | ((j.p_brand == "Brand#22") & j.l_quantity.between(10, 20) & j.p_size.between(1, 10))
         | ((j.p_brand == "Brand#33") & j.l_quantity.between(20, 30) & j.p_size.between(1, 15)))
    want = (j[m].l_extendedprice * (1 - j[m].l_discount)).sum()
    got = r.rows()[0][0]
    if want == 0:
        assert got is None or got == 0
    else:
        assert got == pytest.approx(want, rel=1e-12)


def test_q13_customer_distribution(db, oracle):
    """LEFT OUTER JOIN with a NOT LIKE residual over a duplicate-key build
    side + two-level grouping (was a hard NotImplementedError in r1)."""
    r = db.sql("""
      select c_count, count(*) as custdist from (
        select c_custkey, count(o_orderkey) as c_count
        from customer left join orders
          on c_custkey = o_custkey and o_comment not like '%comment 1%'
        group by c_custkey
      ) c_orders
      group by c_count
      order by custdist desc, c_count desc
    """)
    c, o = oracle["customer"], oracle["orders"]
    of = o[~o.o_comment.str.contains("comment 1", regex=False)]
    j = c.merge(of, left_on="c_custkey", right_on="o_custkey", how="left")
    inner = j.groupby("c_custkey")["o_orderkey"].count().reset_index(name="c_count")
    want = inner.groupby("c_count").size().reset_index(name="custdist") \
        .sort_values(["custdist", "c_count"], ascending=[False, False])
    got = r.to_pandas()
    assert len(got) == len(want), (len(got), len(want))
    assert np.array_equal(got.iloc[:, 0].values, want.c_count.values)
    assert np.array_equal(got.iloc[:, 1].values, want.custdist.values)
