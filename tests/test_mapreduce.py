"""gpmapreduce analog (gpcontrib/gpmapreduce): YAML MAP/REDUCE jobs —
python mappers on the host, builtin reducers as distributed GROUP BY."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.mgmt.mapreduce import MapReduceError, run_job

WORDCOUNT = """
VERSION: 1.0.0.1
DEFINE:
  - INPUT:
      NAME: book
      FILE:
        - localhost:{path}
  - MAP:
      NAME: wordsplit_python
      FUNCTION: |
        for word in value.split():
          yield [word, 1]
      LANGUAGE: python
      PARAMETERS: value text
      RETURNS:
        - key text
        - value integer
EXECUTE:
  - RUN:
      SOURCE: book
      MAP: wordsplit_python
      REDUCE: SUM
"""


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    yield d
    d.close()


def test_wordcount_from_file(db, tmp_path):
    p = tmp_path / "book.txt"
    p.write_text("the quick brown fox\nthe lazy dog\nthe end\n")
    printed = []
    rows = run_job(db, WORDCOUNT.format(path=p), out=printed.append)
    got = dict(rows)
    assert got["the"] == 3
    assert got["quick"] == 1 and got["dog"] == 1
    assert len(printed) == len(rows)


def test_table_source_reduce_to_target(db):
    db.sql("create table mr_src (k text, v int) distributed by (v)")
    from greengage_tpu.types import Coded

    codes = np.array([0, 1, 0, 2, 1, 0], dtype=np.int32)
    db.load_table("mr_src", {
        "k": Coded(["a", "b", "c"], codes),
        "v": np.arange(6, dtype=np.int32)})
    job = """
DEFINE:
  - INPUT:
      NAME: src
      TABLE: mr_src
EXECUTE:
  - RUN:
      SOURCE: src
      REDUCE: SUM
      TARGET: mr_out
"""
    run_job(db, job, out=lambda *_: None)
    got = dict(db.sql("select k, v from mr_out order by k").rows())
    assert got == {"a": 0 + 2 + 5, "b": 1 + 4, "c": 3}


def test_identity_and_errors(db):
    with pytest.raises(MapReduceError, match="python only"):
        run_job(db, """
DEFINE:
  - INPUT:
      NAME: x
      TABLE: mr_src
  - MAP:
      NAME: m
      LANGUAGE: perl
      FUNCTION: "return [];"
EXECUTE:
  - RUN: {SOURCE: x, MAP: m}
""")
    with pytest.raises(MapReduceError, match="TRANSITION"):
        run_job(db, """
DEFINE:
  - INPUT: {NAME: x, TABLE: mr_src}
  - REDUCE: {NAME: r, TRANSITION: t}
EXECUTE:
  - RUN: {SOURCE: x}
""")


def test_cli_mapreduce(db, tmp_path, capsys):
    from greengage_tpu.mgmt import cli

    book = tmp_path / "b.txt"
    book.write_text("x y x\n")
    job = tmp_path / "job.yml"
    job.write_text(WORDCOUNT.format(path=book))
    rc = cli.main(["mapreduce", "-d", db.path, "-f", str(job)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "x\t2" in out and "y\t1" in out
