"""Window function tests (nodeWindowAgg analog) vs pandas."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table w (g text, k int, v int) distributed by (k)")
    d.sql("insert into w values "
          "('a', 1, 10), ('a', 2, 20), ('a', 3, 20), ('a', 4, 5), "
          "('b', 5, 7), ('b', 6, 7), ('b', 7, 1), "
          "('c', 8, null), ('c', 9, 3)")
    return d


def test_row_number_and_rank(db):
    r = db.sql("select g, v, row_number() over (partition by g order by v) rn, "
               "rank() over (partition by g order by v) rk, "
               "dense_rank() over (partition by g order by v) dr "
               "from w order by g, v nulls last")
    rows = [tuple(x) for x in r.rows()]
    # group a: v=5,10,20,20 -> rn 1..4, rank 1,2,3,3, dense 1,2,3,3
    assert rows[0] == ("a", 5, 1, 1, 1)
    assert rows[1] == ("a", 10, 2, 2, 2)
    assert rows[2][1:] == (20, 3, 3, 3)
    assert rows[3][1:] == (20, 4, 3, 3)
    # group b: v=1,7,7
    assert rows[4] == ("b", 1, 1, 1, 1)
    assert rows[5][1:] == (7, 2, 2, 2)
    assert rows[6][1:] == (7, 3, 2, 2)
    # group c: v=3, null (nulls last in window order)
    assert rows[7] == ("c", 3, 1, 1, 1)
    assert rows[8][0] == "c" and rows[8][1] is None and rows[8][2] == 2


def test_partition_aggregate_no_order(db):
    r = db.sql("select g, v, sum(v) over (partition by g) s, "
               "count(v) over (partition by g) c, "
               "max(v) over (partition by g) m "
               "from w order by g, k")
    df = pd.DataFrame({
        "g": list("aaaabbbcc"),
        "k": range(1, 10),
        "v": [10, 20, 20, 5, 7, 7, 1, None, 3],
    })
    want_s = df.groupby("g").v.transform("sum")
    want_c = df.groupby("g").v.transform("count")
    want_m = df.groupby("g").v.transform("max")
    got = r.to_pandas()
    assert list(got.s) == [int(x) for x in want_s]
    assert list(got.c) == [int(x) for x in want_c]
    assert list(got.m) == [int(x) for x in want_m]


def test_running_sum_with_peers(db):
    r = db.sql("select g, v, sum(v) over (partition by g order by v) rs "
               "from w where g = 'b' order by v")
    # b: v=1 -> 1 ; v=7,7 are peers -> both see 15
    assert [tuple(x) for x in r.rows()] == [("b", 1, 1), ("b", 7, 15), ("b", 7, 15)]


def test_global_window_no_partition(db):
    r = db.sql("select k, row_number() over (order by k desc) rn from w "
               "order by k")
    rows = [tuple(x) for x in r.rows()]
    assert rows[0] == (1, 9) and rows[-1] == (9, 1)


def test_window_count_star(db):
    r = db.sql("select g, count(*) over (partition by g) c from w "
               "order by g, k")
    got = [x[1] for x in r.rows()]
    assert got == [4, 4, 4, 4, 3, 3, 3, 2, 2]
