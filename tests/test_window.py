"""Window function tests (nodeWindowAgg analog) vs pandas."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table w (g text, k int, v int) distributed by (k)")
    d.sql("insert into w values "
          "('a', 1, 10), ('a', 2, 20), ('a', 3, 20), ('a', 4, 5), "
          "('b', 5, 7), ('b', 6, 7), ('b', 7, 1), "
          "('c', 8, null), ('c', 9, 3)")
    return d


def test_row_number_and_rank(db):
    r = db.sql("select g, v, row_number() over (partition by g order by v) rn, "
               "rank() over (partition by g order by v) rk, "
               "dense_rank() over (partition by g order by v) dr "
               "from w order by g, v nulls last")
    rows = [tuple(x) for x in r.rows()]
    # group a: v=5,10,20,20 -> rn 1..4, rank 1,2,3,3, dense 1,2,3,3
    assert rows[0] == ("a", 5, 1, 1, 1)
    assert rows[1] == ("a", 10, 2, 2, 2)
    assert rows[2][1:] == (20, 3, 3, 3)
    assert rows[3][1:] == (20, 4, 3, 3)
    # group b: v=1,7,7
    assert rows[4] == ("b", 1, 1, 1, 1)
    assert rows[5][1:] == (7, 2, 2, 2)
    assert rows[6][1:] == (7, 3, 2, 2)
    # group c: v=3, null (nulls last in window order)
    assert rows[7] == ("c", 3, 1, 1, 1)
    assert rows[8][0] == "c" and rows[8][1] is None and rows[8][2] == 2


def test_partition_aggregate_no_order(db):
    r = db.sql("select g, v, sum(v) over (partition by g) s, "
               "count(v) over (partition by g) c, "
               "max(v) over (partition by g) m "
               "from w order by g, k")
    df = pd.DataFrame({
        "g": list("aaaabbbcc"),
        "k": range(1, 10),
        "v": [10, 20, 20, 5, 7, 7, 1, None, 3],
    })
    want_s = df.groupby("g").v.transform("sum")
    want_c = df.groupby("g").v.transform("count")
    want_m = df.groupby("g").v.transform("max")
    got = r.to_pandas()
    assert list(got.s) == [int(x) for x in want_s]
    assert list(got.c) == [int(x) for x in want_c]
    assert list(got.m) == [int(x) for x in want_m]


def test_running_sum_with_peers(db):
    r = db.sql("select g, v, sum(v) over (partition by g order by v) rs "
               "from w where g = 'b' order by v")
    # b: v=1 -> 1 ; v=7,7 are peers -> both see 15
    assert [tuple(x) for x in r.rows()] == [("b", 1, 1), ("b", 7, 15), ("b", 7, 15)]


def test_global_window_no_partition(db):
    r = db.sql("select k, row_number() over (order by k desc) rn from w "
               "order by k")
    rows = [tuple(x) for x in r.rows()]
    assert rows[0] == (1, 9) and rows[-1] == (9, 1)


def test_window_count_star(db):
    r = db.sql("select g, count(*) over (partition by g) c from w "
               "order by g, k")
    got = [x[1] for x in r.rows()]
    assert got == [4, 4, 4, 4, 3, 3, 3, 2, 2]


# ---------------------------------------------------------------------------
# r2 additions: lag/lead/first_value/last_value/ntile, ROWS frames, mixed
# DISTINCT+plain aggregates, per-node EXPLAIN ANALYZE (VERDICT item #10)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def wdb(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table serie (g int, t int, v int) distributed by (g)")
    rows = []
    rng = np.random.default_rng(9)
    for g in range(3):
        for t in range(10):
            rows.append(f"({g}, {t}, {int(rng.integers(0, 100))})")
    d.sql("insert into serie values " + ",".join(rows))
    return d


def _oracle_df(wdb):
    import pandas as pd

    snap = wdb.store.manifest.snapshot()
    parts = []
    for seg in range(4):
        cols, _, n = wdb.store.read_segment("serie", seg, None, snap)
        if n:
            parts.append(pd.DataFrame({k: v for k, v in cols.items()}))
    return pd.concat(parts).sort_values(["g", "t"]).reset_index(drop=True)


def test_lag_lead(wdb):
    r = wdb.sql("select g, t, v, lag(v) over (partition by g order by t), "
                "lead(v, 2) over (partition by g order by t) "
                "from serie order by g, t")
    df = _oracle_df(wdb)
    want_lag = df.groupby("g")["v"].shift(1)
    want_lead = df.groupby("g")["v"].shift(-2)
    got = r.to_pandas()
    for i in range(len(df)):
        wl = want_lag.iloc[i]
        assert (got.iloc[i, 3] is None) == bool(np.isnan(wl)) \
            and (np.isnan(wl) or got.iloc[i, 3] == wl)
        wld = want_lead.iloc[i]
        assert (got.iloc[i, 4] is None) == bool(np.isnan(wld)) \
            and (np.isnan(wld) or got.iloc[i, 4] == wld)


def test_first_last_value(wdb):
    r = wdb.sql("select g, t, first_value(v) over (partition by g order by t), "
                "last_value(v) over (partition by g order by t "
                "rows between unbounded preceding and unbounded following) "
                "from serie order by g, t")
    df = _oracle_df(wdb)
    firsts = df.groupby("g")["v"].transform("first")
    lasts = df.groupby("g")["v"].transform("last")
    got = r.to_pandas()
    assert np.array_equal(got.iloc[:, 2].values.astype(int), firsts.values)
    assert np.array_equal(got.iloc[:, 3].values.astype(int), lasts.values)


def test_ntile(wdb):
    r = wdb.sql("select g, t, ntile(3) over (partition by g order by t) "
                "from serie order by g, t")
    got = r.to_pandas()
    # 10 rows in 3 buckets: sizes 4,3,3
    for g in range(3):
        buckets = got[got.iloc[:, 0] == g].iloc[:, 2].values
        assert list(buckets) == [1, 1, 1, 1, 2, 2, 2, 3, 3, 3]


def test_rows_frame_moving_sum(wdb):
    r = wdb.sql("select g, t, sum(v) over (partition by g order by t "
                "rows between 2 preceding and current row) "
                "from serie order by g, t")
    df = _oracle_df(wdb)
    want = df.groupby("g")["v"].rolling(3, min_periods=1).sum() \
        .reset_index(drop=True)
    got = r.to_pandas()
    assert np.allclose(got.iloc[:, 2].values.astype(float), want.values)


def test_rows_frame_with_following(wdb):
    r = wdb.sql("select g, t, count(*) over (partition by g order by t "
                "rows between 1 preceding and 1 following) "
                "from serie order by g, t")
    got = r.to_pandas()
    for g in range(3):
        c = got[got.iloc[:, 0] == g].iloc[:, 2].values
        assert list(c) == [2, 3, 3, 3, 3, 3, 3, 3, 3, 2]


def test_mixed_distinct_and_plain_aggregates(wdb):
    r = wdb.sql("select g, count(distinct v), count(*), sum(v) from serie "
                "group by g order by g")
    df = _oracle_df(wdb)
    want = df.groupby("g").agg(d=("v", "nunique"), n=("v", "size"),
                               s=("v", "sum")).reset_index()
    got = r.to_pandas()
    assert np.array_equal(got.iloc[:, 1].values, want.d.values)
    assert np.array_equal(got.iloc[:, 2].values, want.n.values)
    assert np.array_equal(got.iloc[:, 3].values, want.s.values)


def test_mixed_distinct_plain_scalar(wdb):
    r = wdb.sql("select count(distinct v), count(*), max(v) from serie")
    df = _oracle_df(wdb)
    assert r.rows()[0] == (df.v.nunique(), len(df), df.v.max())


def test_explain_analyze_per_node_rows(wdb):
    r = wdb.sql("explain analyze select g, count(*) from serie "
                "where v >= 0 group by g")
    text = r.plan_text
    assert "actual rows=" in text
    # the scan line carries the full row count
    scan_line = [ln for ln in text.split("\n") if "Scan serie" in ln][0]
    assert "actual rows=30" in scan_line


def test_mixed_distinct_null_group_key(wdb):
    """NULL group keys must survive the mixed-distinct rejoin (r2 review
    finding: plain join equality drops NULLs)."""
    wdb.sql("create table ng (k int, g int, v int) distributed by (k)")
    wdb.sql("insert into ng values (1,1,10),(2,1,20),(3,null,5),(4,null,5),(5,null,7)")
    r = wdb.sql("select g, count(distinct v), count(*), sum(v) from ng "
                "group by g order by g")
    rows = r.rows()
    assert (1, 2, 2, 30) in rows
    assert any(row[0] is None and row[1:] == (2, 3, 17) for row in rows)


def test_minmax_whole_partition_frame(wdb):
    wdb.sql("create table mmf (k int, g int, v int) distributed by (k)")
    wdb.sql("insert into mmf values (1,0,5),(2,0,3),(3,0,9)")
    r = wdb.sql("select v, min(v) over (partition by g order by v desc "
                "rows between unbounded preceding and unbounded following), "
                "max(v) over (partition by g order by v desc "
                "rows between unbounded preceding and current row) "
                "from mmf order by v desc")
    rows = r.rows()
    assert [row[1] for row in rows] == [3, 3, 3]   # whole-partition min
    assert [row[2] for row in rows] == [9, 9, 9]   # running max from 9


def test_frame_words_remain_identifiers(wdb):
    wdb.sql("create table fwords (id int, range int, current int) "
            "distributed by (id)")
    wdb.sql("insert into fwords values (1, 10, 20)")
    r = wdb.sql("select range, current from fwords")
    assert r.rows() == [(10, 20)]


def test_lag_with_default(wdb):
    wdb.sql("create table lg3 (k int, g int, v int) distributed by (k)")
    wdb.sql("insert into lg3 values (1,0,10),(2,0,20),(3,0,30)")
    r = wdb.sql("select v, lag(v, 1, -1) over (order by v) from lg3 order by v")
    assert [tuple(x) for x in r.rows()] == [(10, -1), (20, 10), (30, 20)]


# ---------------------------------------------------------------------------
# distributed GLOBAL windows (VERDICT r3 weak #9): no single-chip funnel
# ---------------------------------------------------------------------------

def test_global_unordered_window_stays_distributed(wdb):
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    q = ("select t, sum(v) over () as tot, count(*) over () as n, "
         "avg(v) over () as a, min(v) over () as lo, max(v) over () as hi "
         "from serie")
    planned, _, _ = wdb._plan(parse(q)[0])
    txt = describe(planned)
    assert "SingleQE" not in txt, txt          # NO one-chip funnel
    r = wdb.sql(q + " order by t limit 3")
    import numpy as np
    rows = wdb.sql("select v from serie").rows()
    vs = [x[0] for x in rows]
    want_tot, want_n = sum(vs), len(vs)
    for t, tot, n, a, lo, hi in r.rows():
        assert tot == want_tot and n == want_n
        assert a == pytest.approx(want_tot / want_n)
        assert lo == min(vs) and hi == max(vs)


def test_global_row_number_distributed_and_dense(wdb):
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    q = "select t, row_number() over () as rn from serie"
    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)
    r = wdb.sql(q)
    rns = sorted(x[1] for x in r.rows())
    assert rns == list(range(1, len(rns) + 1))   # a dense 1..N numbering


def test_global_ordered_window_still_exact(wdb):
    # ordered global windows keep the (correct) single-segment path
    r = wdb.sql("select g, t, row_number() over (order by g, t) as rn "
                "from serie order by g, t")
    rows = r.rows()
    assert [x[2] for x in rows] == list(range(1, len(rows) + 1))


def test_global_ordered_row_number_distributed(wdb):
    """row_number()/rank() over (order by k) on an int key with no NULLs
    computes IN PLACE (all-gathered sorted key runs), no one-chip funnel."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    q = ("select g, t, v, row_number() over (order by v) as rn, "
         "rank() over (order by v) as rk from serie")
    planned, _, _ = wdb._plan(parse(q)[0])
    txt = describe(planned)
    assert "SingleQE" not in txt, txt
    r = wdb.sql(q)
    rows = sorted(r.rows(), key=lambda x: x[3])
    # row_number is a dense 1..N permutation consistent with v-order
    assert [x[3] for x in rows] == list(range(1, len(rows) + 1))
    vs = [x[2] for x in rows]
    assert vs == sorted(vs)
    # rank: 1 + count of strictly smaller values (ties share rank)
    import collections
    cnt = collections.Counter(x[2] for x in rows)
    smaller = {}
    acc = 0
    for val in sorted(cnt):
        smaller[val] = acc
        acc += cnt[val]
    for _, _, v, rn, rk in rows:
        assert rk == smaller[v] + 1


def test_global_ordered_row_number_desc(wdb):
    q = "select v, row_number() over (order by v desc) as rn from serie"
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)
    rows = sorted(wdb.sql(q).rows(), key=lambda x: x[1])
    assert [x[1] for x in rows] == list(range(1, len(rows) + 1))
    vs = [x[0] for x in rows]
    assert vs == sorted(vs, reverse=True)


def test_global_ordered_rank_matches_funnel(wdb):
    # the distributed result must equal the single-segment path's result
    # (force the funnel via a float order key... use an expression key,
    # which stays on the funnel path)
    dist = sorted(wdb.sql(
        "select t, rank() over (order by v) as rk from serie").rows())
    funneled = sorted(wdb.sql(
        "select t, rank() over (order by v + 0) as rk from serie").rows())
    assert dist == funneled


def test_left_join_null_extended_key_distributed(wdb):
    """NULL keys manufactured by a left join used to force the funnel
    (review r4); the generalized in-place ranking now counts NULL rows as
    a runtime class — distributed, with PG placement (last for ASC)."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    wdb.sql("create table dim5 (pk int, w int) distributed by (pk)")
    wdb.sql("insert into dim5 values (0, 100), (1, 101)")
    q = ("select serie.t, dim5.w, rank() over (order by dim5.w) as rk "
         "from serie left join dim5 on serie.g = dim5.pk")
    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)   # no funnel
    rows = wdb.sql(q).rows()
    nn = [r for r in rows if r[1] is not None]
    nulls = [r for r in rows if r[1] is None]
    assert nulls, "fixture must produce null-extended rows"
    # non-null ranks: ties share; nulls rank after ALL non-nulls (ASC)
    assert max(r[2] for r in nn) < min(r[2] for r in nulls)


def test_global_ordered_multikey_distributed(wdb):
    """Multi-key ordered global ranking packs keys via exact zone-map
    bounds — distributed (no funnel), results equal pandas lexsort."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    q = ("select g, t, v, row_number() over (order by v, t desc) rn, "
         "rank() over (order by v, t desc) rk, "
         "dense_rank() over (order by v, t desc) dr from serie")
    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)
    df = _oracle_df(wdb)
    want = df.sort_values(["v", "t"], ascending=[True, False])
    rows = wdb.sql(q).rows()
    assert sorted(r[3] for r in rows) == list(range(1, len(rows) + 1))
    # where (v, t) is unique, row_number is fully determined: pin it
    key_counts = df.groupby(["v", "t"]).size()
    want_rn = {(r.v, r.t): i + 1 for i, (_, r) in enumerate(want.iterrows())}
    for g, t, v, rn, rk, dr in rows:
        if key_counts[(v, t)] == 1:
            assert rn == want_rn[(v, t)]
    # rank/dense_rank against pandas
    key = want[["v", "t"]].apply(tuple, axis=1)
    uniq = sorted(set(key), key=lambda x: (x[0], -x[1]))
    dense_of = {k: i + 1 for i, k in enumerate(uniq)}
    import collections
    cnt = collections.Counter(key)
    rank_of, acc = {}, 0
    for k in uniq:
        rank_of[k] = acc + 1
        acc += cnt[k]
    for g, t, v, rn, rk, dr in rows:
        assert rk == rank_of[(v, t)]
        assert dr == dense_of[(v, t)]


def test_global_ordered_dense_rank_single_key(wdb):
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    q = "select v, dense_rank() over (order by v) dr from serie"
    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)
    rows = wdb.sql(q).rows()
    uniq = sorted({r[0] for r in rows})
    dense_of = {v: i + 1 for i, v in enumerate(uniq)}
    for v, dr in rows:
        assert dr == dense_of[v]


def test_global_ordered_nullable_key_classes(wdb):
    """Stored NULL keys (not just null-extended) rank as one tied class,
    placed per NULLS FIRST/LAST, all in place."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    _ensure_nk(wdb)
    for q, first in (
            ("select k, rank() over (order by v) rk from nk", False),
            ("select k, rank() over (order by v desc) rk from nk", True),
            ("select k, rank() over (order by v nulls first) rk from nk",
             True)):
        planned, _, _ = wdb._plan(parse(q)[0])
        assert "SingleQE" not in describe(planned), q
        rows = wdb.sql(q).rows()
        nulls = [rk for k, rk in rows if k in (2, 4)]
        vals = [rk for k, rk in rows if k not in (2, 4)]
        assert nulls[0] == nulls[1]
        if first:
            assert nulls[0] == 1 and min(vals) == 3
        else:
            assert min(vals) == 1 and nulls[0] == 4


def _ensure_nk(wdb):
    if "nk" not in wdb.catalog.tables:
        wdb.sql("create table nk (k int, v int) distributed by (k)")
        wdb.sql("insert into nk values (1, 10), (2, null), (3, 7), "
                "(4, null), (5, 42)")


def test_global_ordered_dense_rank_with_nulls(wdb):
    _ensure_nk(wdb)
    rows = wdb.sql("select k, dense_rank() over (order by v) dr "
                   "from nk").rows()
    by_k = dict(rows)
    # values 7,10,42 -> dense 1,2,3; nulls last as one extra class
    assert by_k[3] == 1 and by_k[1] == 2 and by_k[5] == 3
    assert by_k[2] == by_k[4] == 4


def test_global_ordered_text_keys_distributed(wdb):
    """Dict-TEXT ORDER BY keys re-code into rank space at bind, so global
    rankings over text distribute (packed bounded ints) — and order
    LEXICOGRAPHICALLY, not by first-seen dictionary codes."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    wdb.sql("create table wt (s text, v int, k int) distributed by (k)")
    wdb.sql("insert into wt values ('zebra', 5, 0), ('apple', 3, 1), "
            "('mango', 9, 2), ('apple', 7, 3), ('zebra', 1, 4)")
    q = "select s, row_number() over (order by s) rn from wt"
    planned, _, _ = wdb._plan(parse(q)[0])
    assert "SingleQE" not in describe(planned)
    rows = sorted(wdb.sql(q).rows(), key=lambda x: x[1])
    assert [r[0] for r in rows] == ["apple", "apple", "mango",
                                    "zebra", "zebra"]
    # mixed TEXT + int multi-key packs too
    q2 = "select s, v, rank() over (order by s, v desc) rk from wt"
    planned2, _, _ = wdb._plan(parse(q2)[0])
    assert "SingleQE" not in describe(planned2)
    rows2 = sorted(wdb.sql(q2).rows(), key=lambda x: x[2])
    assert [(r[0], r[1]) for r in rows2] == [
        ("apple", 7), ("apple", 3), ("mango", 9), ("zebra", 5), ("zebra", 1)]
