"""TPC-DS star-join subset vs pandas oracles — the BASELINE.json
"TPC-DS star-join subset (Broadcast Motion + semi-join bitmap filter)"
config at test scale: store_sales fact with date_dim/item/store
dimensions. Q3 (brand revenue for a manufacturer by year), Q42
(category rollup for one month), Q52-analog (brand extended price), and
a semi-join bitmap-filter shape (fact rows restricted by a filtered
dimension subquery)."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.types import Coded

N_SS = 150_000
N_DATE, N_ITEM, N_STORE = 2000, 1200, 30


@pytest.fixture(scope="module")
def env(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(77)
    date_dim = {
        "d_date_sk": np.arange(N_DATE, dtype=np.int64),
        "d_year": (1998 + np.arange(N_DATE) // 365).astype(np.int32),
        "d_moy": (1 + (np.arange(N_DATE) // 30) % 12).astype(np.int32),
    }
    item = {
        "i_item_sk": np.arange(N_ITEM, dtype=np.int64),
        "i_brand_id": rng.integers(1, 60, N_ITEM).astype(np.int32),
        "i_category": Coded([f"Cat{i}" for i in range(10)],
                            rng.integers(0, 10, N_ITEM).astype(np.int32)),
        "i_manufact_id": rng.integers(1, 100, N_ITEM).astype(np.int32),
        "i_manager_id": rng.integers(1, 40, N_ITEM).astype(np.int32),
    }
    store = {
        "s_store_sk": np.arange(N_STORE, dtype=np.int64),
        "s_state": Coded(["CA", "NY", "TX", "WA"],
                         rng.integers(0, 4, N_STORE).astype(np.int32)),
    }
    ss = {
        "ss_sold_date_sk": rng.integers(0, N_DATE, N_SS),
        "ss_item_sk": rng.integers(0, N_ITEM, N_SS),
        "ss_store_sk": rng.integers(0, N_STORE, N_SS),
        "ss_quantity": rng.integers(1, 100, N_SS).astype(np.int32),
        "ss_ext_sales_price": rng.integers(100, 100_000, N_SS).astype(np.int64),
    }
    d.sql("create table date_dim (d_date_sk bigint, d_year int, d_moy int) "
          "distributed replicated")
    d.sql("create table item (i_item_sk bigint, i_brand_id int, "
          "i_category text, i_manufact_id int, i_manager_id int) "
          "distributed by (i_item_sk)")
    d.sql("create table store (s_store_sk bigint, s_state text) "
          "distributed replicated")
    d.sql("create table store_sales (ss_sold_date_sk bigint, "
          "ss_item_sk bigint, ss_store_sk bigint, ss_quantity int, "
          "ss_ext_sales_price bigint) distributed by (ss_item_sk)")
    for t, cols in (("date_dim", date_dim), ("item", item),
                    ("store", store), ("store_sales", ss)):
        d.load_table(t, cols)
    d.sql("analyze")
    dfs = {
        "date_dim": pd.DataFrame(date_dim),
        "item": pd.DataFrame({k: (v.decode() if isinstance(v, Coded) else v)
                              for k, v in item.items()}),
        "store": pd.DataFrame({k: (v.decode() if isinstance(v, Coded) else v)
                               for k, v in store.items()}),
        "store_sales": pd.DataFrame(ss),
    }
    return d, dfs


def test_ds_q3_brand_revenue(env):
    d, f = env
    r = d.sql("""select d_year, i_brand_id, sum(ss_ext_sales_price) as rev
      from store_sales, date_dim, item
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and i_manufact_id = 28 and d_moy = 11
      group by d_year, i_brand_id
      order by d_year, rev desc, i_brand_id limit 25""")
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manufact_id == 28) & (j.d_moy == 11)]
    want = (j.groupby(["d_year", "i_brand_id"])["ss_ext_sales_price"].sum()
             .reset_index(name="rev")
             .sort_values(["d_year", "rev", "i_brand_id"],
                          ascending=[True, False, True]).head(25))
    got = r.rows()
    assert len(got) == min(25, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.d_year, w.i_brand_id, w.rev)


def test_ds_q42_category_rollup(env):
    d, f = env
    r = d.sql("""select d_year, i_category, sum(ss_ext_sales_price) as rev
      from store_sales, date_dim, item
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 1 and d_moy = 11 and d_year = 1999
      group by d_year, i_category order by rev desc, i_category""")
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 1999)]
    want = (j.groupby(["d_year", "i_category"])["ss_ext_sales_price"].sum()
             .reset_index(name="rev")
             .sort_values(["rev", "i_category"], ascending=[False, True]))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[1], row[2]) == (w.i_category, w.rev)


def test_ds_semi_bitmap_filter(env):
    d, f = env
    # the star-join "bitmap filter" shape: fact rows restricted by a
    # filtered dimension through IN (semi join), aggregated by store state
    r = d.sql("""select s_state, count(*) as cnt, sum(ss_quantity) as q
      from store_sales, store
      where ss_store_sk = s_store_sk
        and ss_item_sk in (select i_item_sk from item where i_brand_id < 5)
        and ss_sold_date_sk in (select d_date_sk from date_dim
                                where d_year = 2000)
      group by s_state order by s_state""")
    items = set(f["item"][f["item"].i_brand_id < 5].i_item_sk)
    dates = set(f["date_dim"][f["date_dim"].d_year == 2000].d_date_sk)
    j = f["store_sales"]
    j = j[j.ss_item_sk.isin(items) & j.ss_sold_date_sk.isin(dates)]
    j = j.merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
    want = (j.groupby("s_state")
            .agg(cnt=("ss_quantity", "size"), q=("ss_quantity", "sum"))
            .reset_index().sort_values("s_state"))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.s_state, w.cnt, w.q)


def test_ds_q52_brand_by_month(env):
    d, f = env
    r = d.sql("""select d_year, i_brand_id, sum(ss_ext_sales_price) as p
      from date_dim, store_sales, item
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 1 and d_moy = 12 and d_year = 1998
      group by d_year, i_brand_id order by d_year, p desc, i_brand_id
      limit 10""")
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 12) & (j.d_year == 1998)]
    want = (j.groupby(["d_year", "i_brand_id"])["ss_ext_sales_price"].sum()
             .reset_index(name="p")
             .sort_values(["d_year", "p", "i_brand_id"],
                          ascending=[True, False, True]).head(10))
    got = r.rows()
    assert len(got) == min(10, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.d_year, w.i_brand_id, w.p)


def test_ds_q27_rollup_with_grouping(env):
    """TPC-DS Q27 shape: fact joined to dims, GROUP BY ROLLUP over two
    attributes with avg + grouping(), vs a pandas oracle."""
    d, f = env
    r = d.sql("""select i_category, s_state, grouping(i_category, s_state) g,
        avg(ss_quantity) aq, count(*) c
      from store_sales, item, store
      where ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
        and i_manager_id < 10
      group by rollup(i_category, s_state)
      order by g, i_category, s_state""")
    j = (f["store_sales"]
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[j.i_manager_id < 10]
    got = r.rows()
    # leaf level
    leaf = (j.groupby(["i_category", "s_state"])
             .ss_quantity.agg(["mean", "size"]))
    for cat, st, g, aq, c in got:
        if g == 0:
            np.testing.assert_allclose(aq, leaf.loc[(cat, st), "mean"],
                                       rtol=1e-12)
            assert c == leaf.loc[(cat, st), "size"]
        elif g == 1:
            assert st is None
            np.testing.assert_allclose(
                aq, j[j.i_category == cat].ss_quantity.mean(), rtol=1e-12)
        else:
            assert cat is None and st is None
            np.testing.assert_allclose(aq, j.ss_quantity.mean(), rtol=1e-12)
    n_leaf = j.groupby(["i_category", "s_state"]).ngroups
    assert len(got) == n_leaf + j.i_category.nunique() + 1


def test_ds_q22_style_percentile_by_category(env):
    """TPC-DS-style order statistics per category: median + p90 of fact
    quantities through the ordered-set path at join scale."""
    d, f = env
    r = d.sql("""select i_category,
        percentile_cont(0.5) within group (order by ss_quantity) med,
        percentile_cont(0.9) within group (order by ss_quantity) p90
      from store_sales, item
      where ss_item_sk = i_item_sk and i_brand_id < 20
      group by i_category order by i_category""")
    j = (f["store_sales"]
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[j.i_brand_id < 20]
    for cat, med, p90 in r.rows():
        vals = j[j.i_category == cat].ss_quantity
        np.testing.assert_allclose(med, np.percentile(vals, 50), rtol=1e-12)
        np.testing.assert_allclose(p90, np.percentile(vals, 90), rtol=1e-12)


def test_ds_q70_style_grouped_rank(env):
    """TPC-DS Q70 shape: rank states by total revenue (window over the
    grouped aggregate), top-k by rank."""
    d, f = env
    r = d.sql("""select s_state, sum(ss_ext_sales_price) rev,
        rank() over (order by sum(ss_ext_sales_price) desc) rnk
      from store_sales, store
      where ss_store_sk = s_store_sk
      group by s_state order by rnk""")
    j = f["store_sales"].merge(f["store"], left_on="ss_store_sk",
                               right_on="s_store_sk")
    agg = j.groupby("s_state", as_index=False).ss_ext_sales_price.sum()
    agg["rnk"] = agg.ss_ext_sales_price.rank(
        ascending=False, method="min").astype(int)
    want = agg.sort_values("rnk")
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert row[0] == w.s_state and row[1] == w.ss_ext_sales_price \
            and row[2] == w.rnk


def test_ds_q86_style_share_within_parent(env):
    """TPC-DS Q86 flavor: each category's share of the overall total via
    sum(sum()) over ()."""
    d, f = env
    r = d.sql("""select i_category, sum(ss_ext_sales_price) rev,
        sum(ss_ext_sales_price) * 100.0
          / sum(sum(ss_ext_sales_price)) over () share
      from store_sales, item
      where ss_item_sk = i_item_sk
      group by i_category order by i_category""")
    j = f["store_sales"].merge(f["item"], left_on="ss_item_sk",
                               right_on="i_item_sk")
    tot = j.ss_ext_sales_price.sum()
    for cat, rev, share in r.rows():
        want = j[j.i_category == cat].ss_ext_sales_price.sum()
        assert rev == want
        np.testing.assert_allclose(share, want * 100.0 / tot, rtol=1e-4)
