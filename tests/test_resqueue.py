"""Resource queues — SURVEY §2.4 (resscheduler.c ResLockPortal analog):
concurrency-bounded admission with FIFO queueing, timeouts, and a
per-query memory ceiling that routes big queries to the spill path."""

import threading
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.resqueue import QueueTimeout


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(1_000_000), "v": np.arange(1_000_000) % 7})
    return d


def test_concurrency_gate_queues_then_runs(db):
    db.sql("set resource_queue_active = 1")
    order = []
    lock = threading.Lock()

    def q(name):
        r = db.sql("select count(*) from t")
        with lock:
            order.append((name, r.rows()[0][0]))

    ts = [threading.Thread(target=q, args=(f"c{i}",)) for i in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(order) == 4 and all(n == 1_000_000 for _, n in order)
    st = db.resqueue.stats()
    assert st["admitted"] >= 4 and st["active"] == 0 and st["waiting"] == 0


def test_queue_timeout(db):
    db.sql("set resource_queue_active = 1")
    db.sql("set resource_queue_timeout_s = 0.2")
    slot = db.resqueue.admit()        # occupy the only slot
    try:
        with pytest.raises(QueueTimeout, match="resource queue slot"):
            db.sql("select count(*) from t")
    finally:
        slot.release()
    db.sql("set resource_queue_timeout_s = 30")
    assert db.sql("select count(*) from t").rows()[0][0] == 1_000_000


def test_queue_memory_cap_spills(db):
    db.sql("create table d2 (pk int, g int) distributed by (pk)")
    db.sql("insert into d2 values " + ",".join(f"({i},{i%5})" for i in range(1, 200)))
    db.sql("analyze")
    q = "select g, count(*) from t join d2 on t.v + 1 = d2.pk group by g order by g"
    want = db.sql(q).rows()
    db.sql("set resource_queue_memory_mb = 2")
    try:
        r = db.sql(q)
        assert r.rows() == want
        assert r.stats.get("spill_passes", 0) >= 2
    finally:
        db.sql("set resource_queue_memory_mb = 0")
