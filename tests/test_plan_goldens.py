"""Plan-shape goldens — the ORCA minidump analog (SURVEY §4): assert the
PLANNED tree's structure for canonical TPC-H queries so planner regressions
surface as readable diffs. Binder uid suffixes are normalized away."""

import re

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner.logical import describe
from greengage_tpu.sql.parser import parse


@pytest.fixture(scope="module")
def db(devices8):
    from greengage_tpu.utils import tpch

    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.01)
    d.sql("analyze")
    return d


def _norm(text: str) -> str:
    text = re.sub(r" rows=\d+", "", text)          # estimates drift with stats
    # expression detail on Filter/Project nodes is informative for humans
    # but too brittle for goldens — keep node name + locus only
    text = re.sub(r"^(\s*(?:Filter|Project))[^\n]*?((?:  \[[^\]]*\])?)$",
                  r"\1\2", text, flags=re.M)
    text = re.sub(r"#\d+", "#N", text)
    text = re.sub(r" \(direct dispatch: seg \d+\)", " (direct)", text)
    return text


def _plan(db, sql: str) -> str:
    planned, _, _ = db._plan(parse(sql)[0])
    return _norm(describe(planned))


def test_q1_plan_shape(db):
    got = _plan(db, """
      select l_returnflag, l_linestatus, sum(l_quantity), count(*)
      from lineitem where l_shipdate <= date '1998-09-02'
      group by l_returnflag, l_linestatus
      order by l_returnflag, l_linestatus""")
    assert got == """\
Motion Gather  [Entry]
  Sort  [Hashed(l_returnflag#N, l_linestatus#N) x8]
    Project  [Hashed(l_returnflag#N, l_linestatus#N) x8]
      Aggregate final keys=(l_returnflag, l_linestatus)  [Hashed(g#N, g#N) x8]
        Motion Redistribute by (g#N, g#N)  [Hashed(g#N, g#N) x8]
          Aggregate partial keys=(l_returnflag, l_linestatus)  [Strewn x8]
            Project  [Strewn x8]
              Filter  [Strewn x8]
                Scan lineitem  [Strewn x8]"""


def test_point_query_plan_direct_dispatch(db):
    got = _plan(db, "select o_totalprice from orders where o_orderkey = 100")
    assert "Scan orders (direct)" in got
    assert "Motion Gather" in got


def test_q3_plan_shape_joins_then_group(db):
    got = _plan(db, """
      select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
             o_orderdate, o_shippriority
      from customer, orders, lineitem
      where c_mktsegment = 'BUILDING'
        and c_custkey = o_custkey and l_orderkey = o_orderkey
        and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
      group by l_orderkey, o_orderdate, o_shippriority
      order by revenue desc, o_orderdate limit 10""")
    # structural invariants rather than the full text: single-phase group
    # (colocated on l_orderkey), both joins inner, customer reached via
    # a redistribute of the orders side
    assert got.count("Join inner") == 2
    assert "Aggregate single keys=(l_orderkey, o_orderdate, o_shippriority)" in got
    assert "Limit 10" in got
    assert got.index("Sort") < got.index("Aggregate")


def test_dim_joins_use_plain_unique_builds(db):
    got = _plan(db, """
      select n_name, count(*) from supplier, nation
      where s_nationkey = n_nationkey group by n_name""")
    assert "Join inner" in got
    # replicated dimension: no motion needed below the join for nation
    assert "Scan nation  [SegmentGeneral x8]" in got


def test_dp_join_order_star(db, devices8):
    """3+ relations with stats: the DP orders small filtered dims first;
    results and SELECT * column order must be independent of it."""
    import greengage_tpu

    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table fact (id int, did int, v int) distributed by (id)")
    d.sql("insert into fact values " + ",".join(
        f"({i},{i % 50},{i % 9})" for i in range(5000)))
    d.sql("create table dim1 (did int, grp int) distributed by (did)")
    d.sql("insert into dim1 values " + ",".join(
        f"({i},{i % 5})" for i in range(50)))
    d.sql("create table dim2 (grp int, name text) distributed by (grp)")
    d.sql("insert into dim2 values " + ",".join(
        f"({i},'n{i}')" for i in range(5)))
    q = ("select name, count(*), sum(v) from fact, dim1, dim2 "
         "where fact.did = dim1.did and dim1.grp = dim2.grp "
         "group by name order by name")
    star = "select * from fact, dim1, dim2 " \
           "where fact.did = dim1.did and dim1.grp = dim2.grp and fact.id = 1"
    before = d.sql(q).rows()
    cols_before = list(d.sql(star).columns)
    d.sql("analyze")
    after = d.sql(q).rows()
    assert after == before
    # SELECT * keeps FROM-clause column order even when the DP reorders
    assert list(d.sql(star).columns) == cols_before \
        == ["id", "did", "v", "did", "grp", "grp", "name"]
    # and the DP actually fired (order chosen from stats)
    from greengage_tpu.sql.binder import Binder
    from greengage_tpu.sql.parser import parse

    b = Binder(d.catalog, d.store)
    stmt = parse(q)[0]
    items = [b._bind_table_ref(t) for t in stmt.from_]
    import greengage_tpu.sql.binder as BB

    conds = BB._split_and(stmt.where)
    order = b._dp_join_order(items, conds)
    assert order is not None and len(order) == 3


def test_dp_bails_on_cross_product(db, devices8):
    import greengage_tpu

    d = greengage_tpu.connect(numsegments=4)
    for t in ("xa", "xb", "xc"):
        d.sql(f"create table {t} (k int, v int) distributed by (k)")
        d.sql(f"insert into {t} values (1, 1), (2, 2)")
    d.sql("analyze")
    from greengage_tpu.sql.binder import Binder
    import greengage_tpu.sql.binder as BB
    from greengage_tpu.sql.parser import parse

    stmt = parse("select * from xa, xb, xc where xa.k = xb.k")[0]
    b = Binder(d.catalog, d.store)
    items = [b._bind_table_ref(t) for t in stmt.from_]
    assert b._dp_join_order(items, BB._split_and(stmt.where)) is None
