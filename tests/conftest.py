"""Test fixture: an 8-device virtual CPU mesh — the demo-cluster analog.

The reference tests multi-node behavior on a single host via
``make create-demo-cluster`` (gpAux/gpdemo/demo_cluster.sh); we do the same
with XLA's host-platform device-count override so every sharding/collective
path runs under pytest without TPU hardware.
"""

import os

# The environment's sitecustomize may have imported jax already (TPU plugin
# registration), so env vars alone are too late — force via jax.config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
