"""Test fixture: an 8-device virtual CPU mesh — the demo-cluster analog.

The reference tests multi-node behavior on a single host via
``make create-demo-cluster`` (gpAux/gpdemo/demo_cluster.sh); we do the same
with XLA's host-platform device-count override so every sharding/collective
path runs under pytest without TPU hardware.
"""

import os

# The environment's sitecustomize may have imported jax already (TPU plugin
# registration), so env vars alone are too late — force via jax.config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import faulthandler  # noqa: E402

import pytest  # noqa: E402

# Hang forensics: tier-1 runs under `timeout -k 10 870`, which kills a hung
# suite SILENTLY. Dump every thread's stack shortly before that deadline so
# a future channel/collective hang leaves a traceback in the log instead of
# nothing (docs/ROBUSTNESS.md). repeat=False: one dump, no log spam.
_WATCHDOG_S = float(os.environ.get("GGTPU_TEST_WATCHDOG_S", "840"))
if _WATCHDOG_S > 0:
    faulthandler.dump_traceback_later(_WATCHDOG_S, repeat=False, exit=False)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; the slow tier holds the long fuzz loops
    config.addinivalue_line(
        "markers", "slow: long fuzz/stress variants excluded from tier-1")
    # debug-mode lock-order assertions (docs/ANALYSIS.md): every
    # lockdebug.named() site created after this point records real
    # acquisition orders and fails the suite on an inversion — the
    # dynamic half of the `gg check` lock-order analyzer
    from greengage_tpu.runtime import lockdebug

    lockdebug.enable(True)
    # cross-role access witness (docs/ANALYSIS.md "Race analysis"): every
    # lockdebug.shared() structure created after this point records
    # (thread role, held-lock set) per access and fails the suite on the
    # first unprotected cross-role pair — the dynamic half of the
    # `gg check races` analyzer
    lockdebug.enable_races(True)


def pytest_sessionfinish(session, exitstatus):
    # a finished run must not leave the timer armed (it would fire inside
    # whatever process reuses this interpreter, e.g. pytest plugins' atexit)
    faulthandler.cancel_dump_traceback_later()
    # failure forensics (docs/OBSERVABILITY.md): counters live in THIS
    # process, so a post-mortem shell can't read them — dump the snapshot
    # and the newest statement trace here, where CI uploads them as
    # workflow artifacts alongside the cluster CSV logs
    if exitstatus not in (0, 5):   # 5 = no tests collected
        import json

        try:
            from greengage_tpu.runtime.logger import counters, histograms

            with open("/tmp/gg_tier1_counters.json", "w") as f:
                json.dump({"counters": counters.snapshot(),
                           "gauges": sorted(counters.gauges()),
                           "histograms": histograms.snapshot()},
                          f, indent=1, sort_keys=True)
        except Exception:
            pass
        try:
            from greengage_tpu.runtime.trace import TRACES, to_chrome

            tr = TRACES.last()
            if tr is not None:
                with open("/tmp/gg_tier1_trace.json", "w") as f:
                    json.dump(to_chrome(tr), f, indent=1)
        except Exception:
            pass


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
