"""Streaming ingest plane (runtime/ingest.py StreamIngestor, docs/
ROBUSTNESS.md "Write-intent commit & streaming ingest"): long-lived COPY
streams with bounded host buffers, micro-batch commits on size/time
watermarks through the write-intent path, idempotent client resume from
the acked batch sequence, and admission through the overload armor. The
kill-9 half of the contract lives in test_crash_recovery.py."""

import threading
import time

import pytest

import greengage_tpu
from greengage_tpu.runtime import overload
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.resqueue import AdmissionShed


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table hot (k int, v double) distributed by (k)")
    yield d
    d.close()


def _count(db):
    return int(db.sql("select count(*) from hot").rows()[0][0])


def test_size_watermark_commits_microbatch(db):
    db.sql("set ingest_batch_rows = 4")
    out = db.ingest.stream_begin("hot", "s1")
    assert out == {"stream": "s1", "table": "hot", "resume_seq": 0}
    a1 = db.ingest.stream_rows("s1", {"k": [1, 2], "v": [0.1, 0.2]}, 1)
    assert a1["acked_seq"] == 1 and a1["committed_seq"] == 0
    assert _count(db) == 0               # buffered, below the watermark
    a2 = db.ingest.stream_rows("s1", {"k": [3, 4], "v": [0.3, 0.4]}, 2)
    assert a2["committed_seq"] == 2      # watermark tripped: ONE commit
    assert a2["buffered_rows"] == 0
    assert _count(db) == 4
    db.ingest.stream_end("s1")
    assert _count(db) == 4


def test_time_watermark_commits_via_flusher(db):
    """Below the size watermark, the gg-ingest-flush deadline thread
    commits the buffer once ingest_batch_ms elapses."""
    db.sql("set ingest_batch_ms = 50")
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        row = db.ingest.stream_status()[0]
        if row["committed_seq"] == 1:
            break
        time.sleep(0.02)
    else:
        raise AssertionError(
            f"time watermark never flushed: {db.ingest.stream_status()}")
    assert _count(db) == 1
    db.ingest.stream_end("s1")


def test_final_flush_on_stream_end(db):
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1, 2], "v": [1.0, None]}, 1)
    fin = db.ingest.stream_end("s1")
    assert fin["committed_seq"] == 1 and fin["error"] is None
    assert _count(db) == 2
    # the null rode the batch as an invalid row, not a fabricated value
    assert db.sql("select count(*) from hot where v is null") \
        .rows()[0][0] == 1


def test_resume_replays_dedup_below_watermark(db):
    """The idempotent-resume protocol: after reopen, resume_seq is the
    durable watermark; replayed batches at/below it are dropped, batches
    above it land exactly once."""
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1, 2], "v": [1.0, 2.0]}, 1)
    db.ingest.stream_end("s1")
    base = counters.snapshot()
    out = db.ingest.stream_begin("hot", "s1")     # the client re-begins
    assert out["resume_seq"] == 1
    dup = db.ingest.stream_rows("s1", {"k": [1, 2], "v": [1.0, 2.0]}, 1)
    assert dup["duplicate"] is True
    assert counters.since(base).get("ingest_resume_dedup_total") == 1
    db.ingest.stream_rows("s1", {"k": [3], "v": [3.0]}, 2)
    db.ingest.stream_end("s1")
    assert _count(db) == 3               # nothing twice, nothing lost


def test_flush_failure_fails_session_for_rebegin(db):
    """A failed micro-batch marks the SESSION failed (its drained batches
    are exactly what resume re-sends); the stream id stays resumable."""
    db.sql("set ingest_batch_rows = 1")
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
    with pytest.raises(ValueError, match="missing column"):
        db.ingest.stream_rows("s1", {"k": [2]}, 2)       # no "v"
    with pytest.raises(RuntimeError, match="re-begin"):
        db.ingest.stream_rows("s1", {"k": [3], "v": [3.0]}, 3)
    out = db.ingest.stream_begin("hot", "s1")
    assert out["resume_seq"] == 1        # batch 1 committed, batch 2 not
    db.ingest.stream_rows("s1", {"k": [2], "v": [2.0]}, 2)
    db.ingest.stream_end("s1")
    assert _count(db) == 2


def test_dict_growth_flush_carries_durable_watermark(db):
    """A streamed micro-batch whose TEXT values grow the dictionary is
    forced onto the per-table CAS path (cross-process code safety) — the
    full-state line it stages must still carry the stream's resume
    watermark, or committed_seq advances in memory while resume_seq
    stays stale and a crash replays already-durable batches."""
    db.sql("create table tagged (k int, tag text) distributed by (k)")
    db.sql("set ingest_batch_rows = 2")
    db.ingest.stream_begin("tagged", "s1")
    db.ingest.stream_rows("s1", {"k": [1, 2], "tag": ["a", "b"]}, 1)
    snap = db.store.manifest.snapshot()
    assert int(snap["tables"]["tagged"]
               .get("streams", {}).get("s1", 0)) == 1
    out = db.ingest.stream_begin("tagged", "s1")     # crash-style re-begin
    assert out["resume_seq"] == 1
    dup = db.ingest.stream_rows("s1", {"k": [1, 2], "tag": ["a", "b"]}, 1)
    assert dup["duplicate"] is True
    db.ingest.stream_rows("s1", {"k": [3], "tag": ["c"]}, 2)
    db.ingest.stream_end("s1")                       # final flush grows too
    snap = db.store.manifest.snapshot()
    assert int(snap["tables"]["tagged"]["streams"]["s1"]) == 2
    assert int(db.sql("select count(*) from tagged").rows()[0][0]) == 3


def test_live_rebegin_serializes_behind_inflight_flush(db):
    """Reconnect with the same stream id while the deadline flusher is
    mid-commit: stream_begin must quiesce the old session FIRST (it
    serializes behind the in-flight flush on the session lock), so the
    resume watermark it reads can never be below what is durable."""
    db.sql("set ingest_batch_ms = 40")
    db.ingest.stream_begin("hot", "s1")
    faults.inject("ingest_flush", "suspend", occurrences=1)
    try:
        db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if any(f["name"] == "ingest_flush" and f["hits"] > 0
                   for f in faults.status()):
                break
            time.sleep(0.01)
        else:
            raise AssertionError("flusher never reached ingest_flush")
        out: dict = {}
        t = threading.Thread(
            target=lambda: out.update(db.ingest.stream_begin("hot", "s1")))
        t.start()
        t.join(timeout=0.3)
        # blocked behind the suspended flush — NOT reading a stale snapshot
        assert t.is_alive()
    finally:
        faults.reset("ingest_flush")
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert out["resume_seq"] == 1        # the racing commit is visible
    dup = db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
    assert dup["duplicate"] is True      # resend dedups, no double-apply
    db.ingest.stream_end("s1")
    assert _count(db) == 1


def test_brownout_sheds_stream_admission_typed(db):
    ctl = overload.CONTROLLER
    faults.inject("brownout_force", "skip", occurrences=-1)
    try:
        assert ctl.evaluate(db.settings, force=True) is True
        base = counters.snapshot()
        with pytest.raises(AdmissionShed):
            db.ingest.stream_begin("hot", "s1")
        assert counters.since(base).get("ingest_shed_total") == 1
    finally:
        faults.reset("brownout_force")
        db.sql("set brownout_exit_s = 0")
        ctl.evaluate(db.settings, force=True)
    # pressure gone: admission recovers
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_end("s1")


def test_buffer_cap_sheds_oversized_batch(db):
    """ingest_buffer_rows bounds host memory: a batch that cannot fit
    even after an inline flush sheds typed-retryable, never buffers."""
    db.sql("set ingest_buffer_rows = 4")
    db.sql("set ingest_batch_rows = 100")        # size watermark idle
    db.ingest.stream_begin("hot", "s1")
    base = counters.snapshot()
    with pytest.raises(AdmissionShed, match="ingest_buffer_rows"):
        db.ingest.stream_rows(
            "s1", {"k": list(range(6)), "v": [0.0] * 6}, 1)
    assert counters.since(base).get("ingest_shed_total") == 1
    # a fitting batch buffers; the next one flushes inline to make room
    a1 = db.ingest.stream_rows(
        "s1", {"k": [1, 2, 3], "v": [0.0] * 3}, 2)
    assert a1["buffered_rows"] == 3 and a1["committed_seq"] == 0
    a2 = db.ingest.stream_rows(
        "s1", {"k": [4, 5, 6], "v": [0.0] * 3}, 3)
    assert a2["committed_seq"] == 2      # room was made by committing
    db.ingest.stream_end("s1")
    assert _count(db) == 6


def test_stop_drains_open_streams_bounded(db):
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
    db.ingest.stream_begin("hot", "s2")
    db.ingest.stream_rows("s2", {"k": [2], "v": [2.0]}, 1)
    assert counters.get("ingest_active_streams") == 2
    t0 = time.monotonic()
    db.ingest.stop()
    assert time.monotonic() - t0 < 15.0          # bounded join
    assert _count(db) == 2               # flush-or-abort chose flush
    assert counters.get("ingest_active_streams") == 0
    assert counters.get("ingest_buffered_rows") == 0
    with pytest.raises(RuntimeError, match="shut down"):
        db.ingest.stream_begin("hot", "s3")


def test_idle_stream_is_reaped_with_final_flush(db):
    db.sql("set ingest_stream_idle_s = 0.2")
    db.sql("set ingest_batch_ms = 60000")        # only idle can flush
    db.ingest.stream_begin("hot", "s1")
    db.ingest.stream_rows("s1", {"k": [1], "v": [1.0]}, 1)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if counters.get("ingest_active_streams") == 0:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("idle stream never reaped")
    assert _count(db) == 1               # reap flushed, not dropped
    with pytest.raises(ValueError, match="unknown stream"):
        db.ingest.stream_rows("s1", {"k": [2], "v": [2.0]}, 2)


def test_server_wire_ops_and_ps(db, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        c = SqlClient(sock)
        out = c.op({"op": "stream_begin", "table": "hot", "stream": "w1"})
        assert out["ok"] and out["resume_seq"] == 0
        ack = c.op({"op": "stream_rows", "stream": "w1",
                    "columns": {"k": [1, 2], "v": [1.0, 2.0]}, "seq": 1})
        assert ack["ok"] and ack["acked_seq"] == 1
        # a malformed frame without seq must be REJECTED, not silently
        # acked as a seq-0 duplicate (which would drop its rows)
        bad = c.op({"op": "stream_rows", "stream": "w1",
                    "columns": {"k": [9], "v": [9.0]}})
        assert bad["ok"] is False and "seq" in bad["error"]
        bad = c.op({"op": "stream_rows", "stream": "w1",
                    "columns": {"k": [9], "v": [9.0]}, "seq": "2"})
        assert bad["ok"] is False and "seq" in bad["error"]
        ps = c.op({"op": "ps"})
        assert [s["stream"] for s in ps["ingest"]] == ["w1"]
        st = c.op({"op": "status"})
        assert [s["stream"] for s in st["ingest"]] == ["w1"]
        assert "ingest_rows_total" in st["cluster"]["counters"] or \
            st["cluster"]["counters"].get("ingest_batches_total", 0) >= 0
        fin = c.op({"op": "stream_end", "stream": "w1"})
        assert fin["ok"] and fin["committed_seq"] == 1
        c.close()
        assert _count(db) == 2
    finally:
        srv.stop()
    # server stop left no abandoned buffers
    assert counters.get("ingest_buffered_rows") == 0


def test_streams_ride_storm_without_retries(db):
    """Streams and SQL appenders hit ONE table together: still zero claim
    retries, and the total is exact (the acceptance's mixed workload)."""
    db.sql("set ingest_batch_rows = 8")
    base = counters.snapshot()
    errs = []

    def sql_appender(w):
        try:
            for i in range(6):
                db.sql(f"insert into hot values ({w * 100 + i}, {w}.0)")
        except Exception as e:   # pragma: no cover
            errs.append(e)

    def streamer(sid):
        try:
            db.ingest.stream_begin("hot", sid)
            for seq in range(1, 7):
                db.ingest.stream_rows(
                    sid, {"k": [hash(sid) % 1000 + seq + 10000],
                          "v": [float(seq)]}, seq)
            db.ingest.stream_end(sid)
        except Exception as e:   # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=sql_appender, args=(w,))
          for w in range(4)]
    ts += [threading.Thread(target=streamer, args=(f"st{j}",))
           for j in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    d = counters.since(base)
    assert d.get("manifest_cas_retry_total", 0) == 0
    assert _count(db) == 4 * 6 + 4 * 6


@pytest.mark.slow
def test_sustained_stream_storm_steady_state(db):
    """Sustained mixed pressure holds steady state: the buffer gauge
    returns to zero between waves and every row is accounted for."""
    db.sql("set ingest_batch_rows = 32")
    total = 0
    for wave in range(5):
        sid = f"wave{wave}"
        db.ingest.stream_begin("hot", sid)
        for seq in range(1, 21):
            db.ingest.stream_rows(
                sid, {"k": [wave * 10000 + seq * 10 + j
                            for j in range(8)],
                      "v": [0.0] * 8}, seq)
        db.ingest.stream_end(sid)
        total += 20 * 8
        assert counters.get("ingest_buffered_rows") == 0
        assert _count(db) == total
    assert counters.get("ingest_active_streams") == 0
