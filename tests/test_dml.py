"""DELETE/UPDATE regression tests (append-only rewrite semantics)."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError
from greengage_tpu.storage import native


@pytest.fixture()
def db(tmp_path, devices8):
    d = greengage_tpu.connect(path=str(tmp_path / "dml"), numsegments=4)
    d.sql("create table t (k bigint, v int, s text, amt decimal(8,2)) distributed by (k)")
    d.sql("insert into t values (1, 10, 'a', 1.50), (2, 20, 'b', 2.50), "
          "(3, 30, 'a', 3.50), (4, null, 'c', 4.50), (5, 50, 'b', 5.50)")
    return d


def test_delete_with_predicate(db):
    assert db.sql("delete from t where v > 25") == "DELETE 2"
    r = db.sql("select k from t order by k")
    # v NULL row survives (predicate NULL -> not deleted)
    assert [x[0] for x in r.rows()] == [1, 2, 4]


def test_delete_all_and_empty_table(db):
    assert db.sql("delete from t") == "DELETE 5"
    assert db.sql("select count(*) from t").rows()[0][0] == 0
    db.sql("insert into t values (9, 9, 'z', 9.00)")
    assert db.sql("select count(*) from t").rows()[0][0] == 1


def test_update_values_and_nulls(db):
    assert db.sql("update t set v = v + 1 where k <= 2") == "UPDATE 2"
    r = db.sql("select k, v from t order by k")
    assert [tuple(x) for x in r.rows()] == [
        (1, 11), (2, 21), (3, 30), (4, None), (5, 50)]
    # set to NULL
    db.sql("update t set v = null where k = 1")
    assert db.sql("select v from t where k = 1").rows()[0][0] is None


def test_update_decimal_and_text(db):
    db.sql("update t set amt = amt * 2 where s = 'a'")
    r = db.sql("select k, amt from t where s = 'a' order by k")
    assert [tuple(x) for x in r.rows()] == [(1, 3.0), (3, 7.0)]
    db.sql("update t set s = 'zzz' where k = 2")
    assert db.sql("select s from t where k = 2").rows()[0][0] == "zzz"
    # text copied from same column family (identity) is fine
    db.sql("update t set s = s where k = 3")
    assert db.sql("select s from t where k = 3").rows()[0][0] == "a"


def test_update_distribution_key_moves_rows(db):
    # change k: the row must land on its new hash segment
    db.sql("update t set k = 1000 where k = 5")
    found = []
    for seg in range(4):
        cols, _, n = db.store.read_segment("t", seg)
        if n and 1000 in cols["k"]:
            found.append(seg)
    expect_seg = int(native.hash_i64(np.array([1000], dtype=np.int64))[0] % 4)
    assert found == [expect_seg]
    assert db.sql("select v from t where k = 1000").rows()[0][0] == 50


def test_dml_in_tx_supported(db):
    """r2: DML inside transactions stages a replacement published at
    COMMIT (was rejected in r1); same-table rewrite after a tx write is
    the one rejected interleaving."""
    before = db.sql("select count(*) from t").rows()[0][0]
    db.sql("begin")
    db.sql("delete from t where k = 1")
    assert db.sql("select count(*) from t").rows()[0][0] == before
    db.sql("rollback")
    assert db.sql("select count(*) from t").rows()[0][0] == before


def test_update_unknown_column(db):
    with pytest.raises(SqlError, match="does not exist"):
        db.sql("update t set nope = 1")
