"""Closed measurement loop — feedback-driven cost calibration and
measured admission (gpdb's missing EXPLAIN-vs-reality reconciliation,
done TPU-style: the executor's always-on row counters and the AOT
memory analysis feed planner/feedback.py, which re-prices the NEXT
execution of the same plan shape).

Pins the PR-20 acceptance bar: a query whose row estimate is 3x wrong
gets the corrected plan AND the corrected admission verdict on its
second execution; calibration survives a process restart and a standby
promotion; a skipped apply stays pending until `gg checkperf --apply`.
"""

import os

import pytest

import greengage_tpu
from greengage_tpu.runtime import memaccount, standby
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _explain(d, q):
    return "\n".join(r[0] for r in d.sql("explain " + q).rows())


def _line(text, tag):
    for ln in text.splitlines():
        if tag in ln:
            return ln.strip()
    return ""


def _mk_filter_db(tmp_path, name="c"):
    """500 rows, b = i % 7: `where b >= 0` passes ALL rows but the
    default selectivity prices it at ~1/3 — a 3x underestimate."""
    path = str(tmp_path / name)
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (a int, b int) distributed by (a)")
    d.sql("insert into t values " +
          ",".join(f"({i},{i % 7})" for i in range(500)))
    return d, path


FQ = "select count(*) from t where b >= 0"


# ---------------------------------------------------------------------------
# tentpole: wrong estimate -> corrected plan on the SECOND execution
# ---------------------------------------------------------------------------

def test_3x_wrong_filter_estimate_replans_second_execution(devices8,
                                                           tmp_path):
    d, _ = _mk_filter_db(tmp_path)
    cold = _line(_explain(d, FQ), "Filter")
    assert "rows=165" in cold          # plan golden: the 3x-wrong estimate
    base = counters.snapshot()
    r1 = d.sql(FQ)
    assert r1.rows()[0][0] == 500      # actual is 3x the estimate
    # reconcile promoted the correction right after run 1...
    assert counters.since(base).get("feedback_applied_total", 0) >= 1
    assert d.feedback.gen >= 1
    # ...so the SECOND execution plans with ground truth (plan golden)
    warm = _line(_explain(d, FQ), "Filter")
    assert "rows=500" in warm
    r2 = d.sql(FQ)
    assert r2.rows()[0][0] == 500


def test_calibration_settles_without_oscillation(devices8, tmp_path):
    """After the one promotion the EWMA observes residuals of an
    ALREADY-corrected plan — hysteresis must never re-fire (the
    implied-total-scale observation, not the raw residual)."""
    d, _ = _mk_filter_db(tmp_path)
    for _ in range(5):
        assert d.sql(FQ).rows()[0][0] == 500
    assert d.feedback.gen == 1
    assert d.feedback.report()["pending"] == 0


def test_cost_feedback_guc_disables_the_loop(devices8, tmp_path):
    d, _ = _mk_filter_db(tmp_path)
    d.set("cost_feedback", False)
    d.sql(FQ)
    d.sql(FQ)
    assert d.feedback.gen == 0
    assert "rows=165" in _line(_explain(d, FQ), "Filter")


# ---------------------------------------------------------------------------
# tentpole: corrected ADMISSION verdict on the second execution
# ---------------------------------------------------------------------------

AQ = "select a, count(*) from t group by a"


def _mk_group_db(tmp_path, name="g"):
    """500 distinct group keys vs the un-analyzed ~4*sqrt(n)=89 default
    group estimate — a >5x cardinality underestimate at the root."""
    path = str(tmp_path / name)
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (a int, b int) distributed by (a)")
    d.sql("insert into t values " +
          ",".join(f"({i},{i})" for i in range(500)))
    return d, path


def test_admission_error_collapses_on_second_execution(devices8, tmp_path):
    d, _ = _mk_group_db(tmp_path)
    assert "rows=89" in _line(_explain(d, AQ), "Aggregate")
    r1 = d.sql(AQ)
    assert len(r1.rows()) == 500
    r2 = d.sql(AQ)
    # run 2 was priced against run 1's MEASURED executable footprint:
    # the est-vs-actual admission error gauge collapses toward zero
    assert abs(counters.get("mem_est_error_pct")) <= 5
    assert r2.stats["mem"]["est_bytes"] > 0
    # and the re-planned shape carries the corrected group count
    assert "rows=499" in _line(_explain(d, AQ), "Aggregate")


def test_measured_admission_prices_cold_program_after_restart(
        devices8, tmp_path, monkeypatch):
    """The feedback store persists the measured per-segment footprint
    beside the catalog: a RESTARTED process with a stone-cold program
    cache admits by measurement, not estimate (the admission gate only
    trusts measurement when a device allocator is live — simulated
    here, since CPU JAX reports no memory stats)."""
    d, path = _mk_group_db(tmp_path)
    d.sql(AQ)
    d.sql(AQ)
    d.close()
    monkeypatch.setattr(memaccount, "device_memory_stats",
                        lambda: {"bytes_in_use": 0,
                                 "peak_bytes_in_use": 0})
    d2 = greengage_tpu.connect(path=path, numsegments=4)
    base = counters.snapshot()
    r = d2.sql(AQ)
    assert r.stats["mem"]["admitted_by"] == "measured"
    assert r.stats["mem"]["admitted_bytes"] != r.stats["mem"]["est_bytes"]
    delta = counters.since(base, prefix="admission_")
    assert delta.get("admission_measured_feedback_total", 0) >= 1


def test_estimate_only_admission_without_device_stats(devices8, tmp_path):
    """CPU backend exposes no allocator stats: admission must stay
    estimate-driven (the spill/overload suites depend on this)."""
    d, _ = _mk_group_db(tmp_path)
    r = d.sql(AQ)
    assert r.stats["mem"]["admitted_by"] == "estimate"


# ---------------------------------------------------------------------------
# durability: restart round-trip and standby promotion
# ---------------------------------------------------------------------------

def test_calibration_survives_process_restart(devices8, tmp_path):
    d, path = _mk_filter_db(tmp_path)
    d.sql(FQ)
    assert d.feedback.gen == 1
    d.close()
    d2 = greengage_tpu.connect(path=path, numsegments=4)
    assert d2.feedback.gen == 1
    assert "rows=500" in _line(_explain(d2, FQ), "Filter")
    assert d2.sql(FQ).rows()[0][0] == 500
    assert os.path.exists(os.path.join(path, "feedback.json"))


def test_calibration_survives_standby_promotion(devices8, tmp_path):
    d, path = _mk_filter_db(tmp_path)
    d.sql(FQ)                          # promotes + persists feedback.json
    assert d.feedback.gen == 1
    sb = str(tmp_path / "sb")
    standby.init_standby(path, sb)     # meta sync ships feedback.json
    assert os.path.exists(os.path.join(sb, "feedback.json"))
    st = standby.promote(sb, reason="operator")
    assert st["role"] == "activated"
    try:
        d.close()
    except RuntimeError:
        pass                           # fenced close-time flush
    d2 = greengage_tpu.connect(path=sb, numsegments=4)
    assert d2.feedback.gen == 1
    assert "rows=500" in _line(_explain(d2, FQ), "Filter")
    assert d2.sql(FQ).rows()[0][0] == 500


# ---------------------------------------------------------------------------
# operator surface: held-back corrections and the report
# ---------------------------------------------------------------------------

def test_feedback_apply_fault_holds_correction_pending(devices8, tmp_path):
    d, _ = _mk_filter_db(tmp_path)
    faults.inject("feedback_apply", "skip", occurrences=-1)
    d.sql(FQ)
    assert d.feedback.gen == 0         # promotion skipped...
    rep = d.feedback.report()
    assert rep["pending"] >= 1         # ...but the candidate is parked
    assert "rows=165" in _line(_explain(d, FQ), "Filter")
    faults.reset("feedback_apply")
    assert d.feedback.apply_pending() >= 1   # gg checkperf --apply path
    assert d.feedback.gen == 1
    assert "rows=500" in _line(_explain(d, FQ), "Filter")


def test_checkperf_report_carries_est_vs_actual(devices8, tmp_path):
    d, _ = _mk_filter_db(tmp_path)
    d.sql(FQ)
    d.sql(FQ)
    rep = d.feedback.report()
    assert rep["gen"] >= 1
    assert rep["shapes"], "report must list observed plan shapes"
    row = rep["shapes"][0]
    for k in ("sql", "runs", "rows_est", "rows_actual", "rows_err_pct",
              "est_bytes", "measured_bytes"):
        assert k in row
    assert row["runs"] >= 2
    assert rep["scales"], "promoted scale must be visible in the report"


def test_reset_drops_calibration_state(devices8, tmp_path):
    d, _ = _mk_filter_db(tmp_path)
    d.sql(FQ)
    assert d.feedback.gen == 1
    g = d.feedback.gen
    d.feedback.reset()
    assert d.feedback.gen > g          # gen bump invalidates cached plans
    assert d.feedback.report()["shapes"] == []
    assert "rows=165" in _line(_explain(d, FQ), "Filter")
