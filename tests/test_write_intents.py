"""Write-intent concurrent same-table DML (docs/ROBUSTNESS.md
"Write-intent commit & streaming ingest"): N appenders on ONE hot table
stage disjoint segment deltas under per-writer intent records and resolve
at commit into one fsynced merge line — ZERO claim retries, counter-
asserted — while readers keep seeing consistent snapshots and concurrent
DELETE/UPDATE arbitrate row visibility through the intent-sequence
fence."""

import os
import threading

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage.manifest import IntentConflict, Manifest

APPENDERS = 8
ROWS_EACH = 8


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table hot (k int, v double) distributed by (k)")
    yield d
    d.close()


def _storm(db, nthreads=APPENDERS, rows=ROWS_EACH, base=0):
    errs = []

    def appender(w):
        try:
            for i in range(rows):
                db.sql(f"insert into hot values ({base + w * 1000 + i}, "
                       f"{w}.5)")
        except Exception as e:   # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(w,))
          for w in range(nthreads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    return errs


def test_eight_appenders_one_table_zero_retries(db):
    """The tentpole acceptance: 8 concurrent same-table appenders, every
    commit through the intent path, manifest_cas_retry_total UNCHANGED."""
    base = counters.snapshot()
    errs = _storm(db)
    assert not errs, errs
    d = counters.since(base)
    assert d.get("manifest_cas_retry_total", 0) == 0
    # (manifest_cas_conflict_total may tick: a background fold whose
    # root CAS raced the merge lines retries composing — that is the
    # fold's conflict, not an appender claim retry)
    assert d.get("manifest_intent_conflict_total", 0) == 0
    assert d.get("manifest_intent_commits", 0) == APPENDERS * ROWS_EACH
    assert db.sql("select count(*) from hot").rows()[0][0] \
        == APPENDERS * ROWS_EACH
    # every appender's rows landed exactly once (no replayed merge line)
    assert db.sql("select count(distinct k) from hot").rows()[0][0] \
        == APPENDERS * ROWS_EACH


def test_readers_see_consistent_snapshots_during_storm(db):
    """A reader polling during the storm observes only committed states:
    monotone row counts, never a torn/partial merge."""
    stop = threading.Event()
    seen, errs = [], []

    def reader():
        try:
            while not stop.is_set():
                seen.append(int(
                    db.sql("select count(*) from hot").rows()[0][0]))
        except Exception as e:   # pragma: no cover
            errs.append(e)

    rt = threading.Thread(target=reader)
    rt.start()
    werrs = _storm(db)
    stop.set()
    rt.join()
    assert not errs and not werrs, (errs, werrs)
    assert seen == sorted(seen)          # snapshots never move backwards
    assert seen[-1] <= APPENDERS * ROWS_EACH


def test_delete_arbitrates_against_concurrent_appends(db):
    """DELETE racing the append storm: the intent-sequence fence makes the
    delmask writer retry against the fresh snapshot, so it can never
    silently drop rows an appender merged underneath it — the survivors
    are exactly (all rows) - (rows matching the predicate)."""
    db.sql("insert into hot values " +
           ",".join(f"({i}, 0.0)" for i in range(20)))
    errs = []

    def deleter():
        try:
            db.sql("delete from hot where k < 20")
        except Exception as e:   # pragma: no cover
            errs.append(e)

    dt = threading.Thread(target=deleter)
    dt.start()
    werrs = _storm(db, base=100000)      # appended keys all >= 100000
    dt.join()
    assert not errs and not werrs, (errs, werrs)
    # the delete killed its 20 rows; every concurrently appended row LIVES
    assert db.sql("select count(*) from hot").rows()[0][0] \
        == APPENDERS * ROWS_EACH
    assert db.sql("select count(*) from hot where k < 20").rows()[0][0] == 0


def test_stale_delmask_base_gets_typed_conflict(db):
    """The fence itself, hand-driven: a delmask tx begun BEFORE an intent
    merge must observe IntentConflict at prepare (the manifest-level
    primitive set_delmask's retry loop is built on)."""
    db.sql("insert into hot values (1, 1.0), (2, 2.0)")
    m = db.store.manifest
    tx = m.begin()                        # snapshot BEFORE the append
    db.sql("insert into hot values (3, 3.0)")     # intent merge lands
    tx["tables"]["hot"] = dict(tx["tables"]["hot"])
    base = counters.snapshot()
    with pytest.raises(IntentConflict):
        m.prepare_delta(tx, ["hot"])
    assert counters.since(base).get("manifest_intent_conflict_total") == 1


def test_in_doubt_intent_rolls_back_and_sweeps(db, tmp_path):
    """An intent staged but never resolved (the kill-9 shape, here built
    by hand) is invisible to every reader, blocks nothing, and recover()
    sweeps it like a stale delta claim — counter-verified."""
    db.sql("insert into hot values (1, 1.0)")
    m = db.store.manifest
    handle = m.stage_intent("hot", [(0, ["seg0/ghost.ggb"], 5)])
    idir = os.path.join(str(tmp_path / "c"), "intents")
    assert any(f.endswith(".intent") for f in os.listdir(idir))
    # in-doubt ≠ visible: the staged records are NOT part of any snapshot
    assert db.sql("select count(*) from hot").rows()[0][0] == 1
    # ... and concurrent appenders are not blocked by it (zero retries)
    base = counters.snapshot()
    db.sql("insert into hot values (2, 2.0)")
    assert counters.since(base).get("manifest_cas_retry_total", 0) == 0
    # recovery sweeps the orphan with the no-grace discipline
    assert m.recover() == []             # idempotent-recovery contract
    d = counters.since(base)
    assert d.get("manifest_intent_swept_total", 0) >= 1
    assert not any(f.endswith(".intent") for f in os.listdir(idir))
    # the parked writer now gets the clean typed conflict, not a commit
    with pytest.raises(IntentConflict):
        m.commit_intent(handle)


def test_fold_preserves_intent_merges(db):
    """The checkpoint fold composes merge lines into the root: nothing is
    lost, versions stay equal, and iseq fencing stays correct across the
    fold boundary."""
    errs = _storm(db, nthreads=4, rows=4)
    assert not errs
    v_before = db.store.manifest.version()
    assert db.store.manifest.fold(min_deltas=0) or True
    m2 = Manifest(db.path)               # fresh object: no memo, no cache
    assert m2.version() == db.store.manifest.version() >= v_before
    assert db.sql("select count(*) from hot").rows()[0][0] == 16
    db.sql("delete from hot where v > 100")      # fence sane post-fold
    assert db.sql("select count(*) from hot").rows()[0][0] == 16


@pytest.mark.slow
def test_sustained_storm_stays_healthy(db):
    """Sustained same-table pressure: several storm waves back-to-back
    keep committing retry-free and the manifest stays foldable."""
    base = counters.snapshot()
    for wave in range(6):
        errs = _storm(db, base=wave * 1_000_000)
        assert not errs
        db.store.maybe_fold_manifest()
    d = counters.since(base)
    assert d.get("manifest_cas_retry_total", 0) == 0
    assert d.get("manifest_intent_commits", 0) == 6 * APPENDERS * ROWS_EACH
    assert db.sql("select count(*) from hot").rows()[0][0] \
        == 6 * APPENDERS * ROWS_EACH
