"""Multi-host cluster: 2 processes x 4 virtual CPU devices = 8-segment mesh
spanning processes — VERDICT r1 item #6 (jax.distributed data plane +
statement-channel control plane; ic-proxy/libpq dispatch analog).

pytest's own process already owns a JAX backend, so both the coordinator
and the worker run as SUBPROCESSES sharing a cluster directory; the test
asserts the coordinator's results.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

COORD_SCRIPT = r"""
import json, os, sys
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, g int, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(
    f"({i}, {i % 13}, {i % 7})" for i in range(4000)))
db.sql("create table d (g int, name text) distributed by (g)")
db.sql("insert into d values " + ",".join(f"({i}, 'g{i}')" for i in range(13)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
out["scalar"] = [int(x) for x in r.rows()[0]]
# two-phase grouped agg: group key != distribution key => redistribute
r = db.sql("select g, count(*), sum(v) from f group by g order by g")
out["grouped"] = [[int(x) for x in row] for row in r.rows()]
out["grouped_segments"] = r.stats["segments"]
# cross-process join + broadcast of the dimension
r = db.sql("select d.name, count(*) from f join d on f.g = d.g "
           "group by d.name order by d.name limit 3")
out["join"] = [[row[0], int(row[1])] for row in r.rows()]
# DML with an internal mesh scan, then read back
db.sql("update f set v = 99 where k < 10")
r = db.sql("select count(*) from f where v = 99")
out["updated"] = int(r.rows()[0][0])
db.sql("delete from f where g = 12")
r = db.sql("select count(*) from f")
out["after_delete"] = int(r.rows()[0][0])
# parallel retrieve cursor: DECLARE broadcasts (workers join the
# collectives), RETRIEVE drains endpoints coordinator-side
db.sql("declare pc parallel retrieve cursor for select k from f where v = 99")
out["cursor_rows"] = sum(
    len(db.sql(f"retrieve all from endpoint {k} of pc").rows())
    for k in range(db.numsegments))
db.sql("close pc")
# spill under multihost: a big load (shared storage; host-side, no
# lockstep needed), then a grouped agg past a tight vmem limit — the SET
# broadcasts so both processes take the same pass-partitioned branch
import numpy as np
db.sql("create table f2 (k bigint, g int, v int) distributed by (k)")
n2 = 600_000
db.load_table("f2", {"k": np.arange(n2), "g": (np.arange(n2) % 13),
                     "v": (np.arange(n2) % 7)})
db.sql("analyze f2")
db.sql("set vmem_protect_limit_mb = 1")
r = db.sql("select g, count(*), sum(v) from f2 group by g order by g")
out["spilled"] = [[int(x) for x in row] for row in r.rows()]
out["spill_passes"] = int(r.stats.get("spill_passes", 0))
db.sql("set vmem_protect_limit_mb = 12288")
# round-5 analytic surface under lockstep: ROLLUP branches + the
# stat-agg moment expansion + percentile windows are deterministic
# rewrites, so both processes compile identical SPMD programs
r = db.sql("select g, count(*) c, grouping(g) lvl from f "
           "group by rollup(g) order by lvl, g")
out["rollup_total"] = [int(x) for x in r.rows()[-1][1:2]]
out["rollup_rows"] = len(r.rows())
r = db.sql("select stddev(v) from f")
out["stddev"] = round(float(r.rows()[0][0]), 9)
r = db.sql("select percentile_cont(0.5) within group (order by v) from f")
out["median"] = float(r.rows()[0][0])
# gpssh analog: run a command on every host over the control plane
ex = db.cluster_exec("echo host-$GGTPU_X; true")
out["exec_hosts"] = [e["ok"] for e in ex]
out["exec_n"] = len(ex)
ex2 = db.cluster_exec("exit 3")
out["exec_fail"] = [e["ok"] for e in ex2]
mh.channel.close()
print("RESULT:" + json.dumps(out), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_cluster(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_SCRIPT, str(port), str(cport), path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=480)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"multihost timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])

    # oracle (rows 0..3999, g = i%13, v = i%7)
    rows = [(i, i % 13, i % 7) for i in range(4000)]
    assert out["scalar"] == [4000, sum(v for _, _, v in rows)]
    assert out["grouped_segments"] == 8
    want_grouped = {}
    for _, g, v in rows:
        c, s = want_grouped.get(g, (0, 0))
        want_grouped[g] = (c + 1, s + v)
    assert out["grouped"] == [[g, *want_grouped[g]] for g in sorted(want_grouped)]
    want_join = sorted((f"g{g}", want_grouped[g][0]) for g in want_grouped)[:3]
    assert out["join"] == [[n, c] for n, c in want_join]
    assert out["updated"] == 10 - sum(1 for i in range(10) if i % 7 == 99)
    n_g12 = sum(1 for i in range(4000) if i % 13 == 12)
    assert out["after_delete"] == 4000 - n_g12
    assert out["cursor_rows"] == 10   # the rows updated to v=99 (k<10)
    want_spill = {}
    for i in range(600_000):
        c, s = want_spill.get(i % 13, (0, 0))
        want_spill[i % 13] = (c + 1, s + i % 7)
    assert out["spilled"] == [[g, *want_spill[g]] for g in sorted(want_spill)]
    assert out["spill_passes"] >= 2, out["spill_passes"]
    assert out["exec_n"] == 2
    # the round-5 analytic rewrites under lockstep: compare against the
    # same data computed locally
    import numpy as np

    ks = np.arange(4000)
    alive = (ks % 13) != 12
    v = np.where(ks < 10, 99, ks % 7)[alive]
    assert out["rollup_total"] == [int(alive.sum())]
    assert out["rollup_rows"] == 12 + 1
    assert abs(out["stddev"] - float(np.std(v, ddof=1))) < 1e-6
    assert out["median"] == float(np.percentile(v, 50))
    assert out["exec_hosts"] == [True, True]
    assert out["exec_fail"] == [False, False]


# ---------------------------------------------------------------------------
# worker death: detection on the readiness round + degraded local service
# ---------------------------------------------------------------------------

COORD_DEATH_SCRIPT = r"""
import json, os, sys, time
port, cport, path, mark = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(f"({i}, {i % 7})" for i in range(2000)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
out["pre"] = [int(x) for x in r.rows()[0]]
# this test pins the LEGACY degraded fallback (N-1 re-formation has its
# own tests): without the pin the coordinator would re-form and serve
db.sql("set mh_reform_enabled = off")
open(mark + ".phase1", "w").close()
while not os.path.exists(mark + ".killed"):
    time.sleep(0.05)
# the worker is gone: the readiness round must detect it BEFORE any
# collective, and the statement must still COMPLETE via the degraded
# single-process re-formation over the shared directory
r = db.sql("select count(*), sum(v) from f")
out["post"] = [int(x) for x in r.rows()[0]]
out["degraded"] = bool(db._mh_degraded)
r = db.sql("select count(*) from f where k < 10")
out["post2"] = int(r.rows()[0][0])
out["status_after"] = db.sql("delete from f where k < 100")
r = db.sql("select count(*) from f")
out["post3"] = int(r.rows()[0][0])
print("RESULT:" + json.dumps(out), flush=True)
# the degraded runtime's grpc teardown may error at interpreter exit
# (the dead peer can never complete its streams); results are already
# flushed, so exit without running teardown hooks
os._exit(0)
"""


def test_worker_death_detected_and_degraded_service(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    mark = str(tmp_path / "mark")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_DEATH_SCRIPT, str(port), str(cport),
         path, mark],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    import signal
    import time as _t
    try:
        deadline = _t.monotonic() + 300
        while not os.path.exists(mark + ".phase1"):
            assert _t.monotonic() < deadline, "coordinator never reached phase1"
            assert coord.poll() is None, coord.stdout.read()
            _t.sleep(0.05)
        os.kill(worker.pid, signal.SIGKILL)
        worker.wait(timeout=30)
        open(mark + ".killed", "w").close()
        cout, _ = coord.communicate(timeout=480)
    except subprocess.TimeoutExpired:
        coord.kill()
        raise AssertionError(
            f"coordinator hung after worker death:\n{coord.stdout.read()}")
    assert coord.returncode == 0, cout
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, cout
    out = json.loads(res[0][len("RESULT:"):])
    want_sum = sum(i % 7 for i in range(2000))
    assert out["pre"] == [2000, want_sum]
    assert out["post"] == [2000, want_sum]     # completed AFTER the death
    assert out["degraded"] is True
    assert out["post2"] == 10
    assert out["status_after"] == "DELETE 100"  # degraded DML works too
    assert out["post3"] == 1900


def test_plan_hash_deterministic_across_sessions(devices8, tmp_path):
    import numpy as np

    import greengage_tpu
    path = str(tmp_path / "c")
    d1 = greengage_tpu.connect(path=path, numsegments=4)
    d1.sql("create table t (k int, g int, v int) distributed by (k)")
    d1.load_table("t", {"k": np.arange(1000), "g": np.arange(1000) % 7,
                        "v": np.arange(1000)})
    d1.sql("analyze")
    q = "select g, sum(v) from t group by g order by g"
    h1 = d1.plan_hash(q)
    d2 = greengage_tpu.connect(path=path, numsegments=4)
    h2 = d2.plan_hash(q)
    assert h1 is not None and h1 == h2
    assert d1.plan_hash("select 1") is None          # no FROM: host-side


# ---------------------------------------------------------------------------
# worker SIGKILL + cross-host mirrors: the gang RE-FORMS over the survivors
# (N-1 mesh — never the single-process degraded path) and serves every
# content from PROMOTED mirror trees on surviving roots; DML included
# (ftsprobe.c:968 / the tentpole acceptance matrix)
# ---------------------------------------------------------------------------

COORD_MIRROR_DEATH_SCRIPT = r"""
import glob, json, os, sys, time
port, cport, path, mark = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
from greengage_tpu.runtime.logger import counters
db = greengage_tpu.connect(path, multihost=mh)
out = {}
r = db.sql("select count(*), sum(v) from f")
out["pre"] = [int(x) for x in r.rows()[0]]
reform0 = counters.get("mh_reform_total")
topo0 = counters.get("mh_topology_version")
open(mark + ".phase1", "w").close()
while not os.path.exists(mark + ".killed"):
    time.sleep(0.05)
# the dead worker's host took its data disk: contents 4..7 lose their
# primary trees; the re-formed topology must promote their mirrors
for content in (4, 5, 6, 7):
    for f in glob.glob(os.path.join(path, "data", "*", f"seg{content}", "*")):
        os.remove(f)
r = db.sql("select count(*), sum(v) from f")
out["post"] = [int(x) for x in r.rows()[0]]
out["degraded"] = bool(db._mh_degraded)
out["deg_stats"] = bool(getattr(r, "stats", {}).get("degraded"))
out["segments"] = r.stats.get("segments")
out["state"] = db.mh_state()["state"]
out["reform_delta"] = counters.get("mh_reform_total") - reform0
out["topo_bumped"] = counters.get("mh_topology_version") > topo0
out["promoted"] = sorted(
    c for c in range(8)
    if db.catalog.segments.acting_primary(c).preferred_role.value == "m")
# DML on the re-formed N-1 gang: manifest commits are coordinator-local,
# so writes flow without the dead worker
db.sql("delete from f where k < 100")
out["post_dml"] = int(db.sql("select count(*) from f").rows()[0][0])
print("RESULT:" + json.dumps(out), flush=True)
os._exit(0)
"""


def test_worker_death_promotes_cross_host_mirrors(tmp_path):
    import greengage_tpu
    from greengage_tpu.mgmt import cli

    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    mark = str(tmp_path / "mark")
    # build the mirrored cluster with spread mirror roots up front
    # (width 8 = the 2-process x 4-device global mesh)
    d = greengage_tpu.connect(path, numsegments=8, mirrors=True)
    d.sql("create table f (k bigint, v int) distributed by (k)")
    d.sql("insert into f values " + ",".join(
        f"({i}, {i % 7})" for i in range(2000)))
    d.sql("analyze")
    d.close()
    cli.main(["mirrorroots", "-d", path, "--roots",
              f"{tmp_path / 'hostA'},{tmp_path / 'hostB'}"])

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_MIRROR_DEATH_SCRIPT, str(port),
         str(cport), path, mark],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    import signal
    import time as _t
    try:
        deadline = _t.monotonic() + 300
        while not os.path.exists(mark + ".phase1"):
            assert _t.monotonic() < deadline, "coordinator never reached phase1"
            assert coord.poll() is None, coord.stdout.read()
            _t.sleep(0.05)
        os.kill(worker.pid, signal.SIGKILL)
        worker.wait(timeout=30)
        open(mark + ".killed", "w").close()
        cout, _ = coord.communicate(timeout=480)
    except subprocess.TimeoutExpired:
        coord.kill()
        raise AssertionError(
            f"coordinator hung after worker death:\n{coord.stdout.read()}")
    assert coord.returncode == 0, cout
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, cout
    out = json.loads(res[0][len("RESULT:"):])
    want = [2000, sum(i % 7 for i in range(2000))]
    assert out["pre"] == want
    # the gang RE-FORMED over the survivors: never the single-process path
    assert out["degraded"] is False
    assert out["deg_stats"] is False
    assert out["state"] == "n-1"
    assert out["segments"] == 8           # full local mesh, not a subprocess
    assert out["reform_delta"] >= 1       # mh_reform_total counted it
    assert out["topo_bumped"] is True     # mh_topology_version advanced
    assert out["promoted"] == [4, 5, 6, 7]  # mirrors promoted for lost trees
    assert out["post"] == want            # served from mirror data
    assert out["post_dml"] == 1900        # DML commits on the N-1 gang


# ---------------------------------------------------------------------------
# deadline/heartbeat/rejoin layer (docs/ROBUSTNESS.md): channel-level tests
# run the REAL protocol objects in-process (pure TCP, no devices), so every
# phase is deterministic and fast — the isolation2 fts_errors.sql analog.
# ---------------------------------------------------------------------------

import threading
import time


def _channel_pair(n_workers=1, connect_deadline=10.0):
    """A real CoordinatorChannel + WorkerChannel(s) over loopback."""
    from greengage_tpu.parallel.multihost import (CoordinatorChannel,
                                                  WorkerChannel)

    port = _free_port()
    box = {}

    def serve():
        box["ch"] = CoordinatorChannel(port, n_workers,
                                       connect_deadline=connect_deadline)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    workers = [WorkerChannel("127.0.0.1", port, process_id=i + 1,
                             connect_deadline=connect_deadline)
               for i in range(n_workers)]
    t.join(10)
    assert "ch" in box, "coordinator accept never completed"
    return box["ch"], workers


def test_accept_deadline_names_missing_workers():
    """A worker that never launches must fail startup with a joined-count,
    not hang accept() forever."""
    from greengage_tpu.parallel.multihost import CoordinatorChannel, WorkerDied

    t0 = time.monotonic()
    with pytest.raises(WorkerDied, match=r"0 of 2 workers joined"):
        CoordinatorChannel(_free_port(), 2, connect_deadline=0.4)
    assert time.monotonic() - t0 < 5.0


def test_silent_worker_classified_dead_within_deadline():
    """A connected-but-silent (hung) worker must classify as WorkerDied
    within the configured deadline on every ack phase."""
    from greengage_tpu.parallel.multihost import WorkerDied

    ch, (w,) = _channel_pair()
    try:
        t0 = time.monotonic()
        with pytest.raises(WorkerDied, match="timed out"):
            with ch.exchange():
                ch.send({"op": "sql", "sql": "select 1"})
                ch.collect_acks(deadline=0.4, phase="readiness")
        assert time.monotonic() - t0 < 5.0
    finally:
        ch.close()
        w.close()


def test_failed_send_releases_lock_and_close_does_not_deadlock():
    """Regression for the cross-method lock discipline: a send that fails
    (here via the dispatch_send fault point) must leave the per-exchange
    lock free so close() completes instead of deadlocking."""
    from greengage_tpu.parallel.multihost import WorkerDied
    from greengage_tpu.runtime.faultinject import faults

    ch, (w,) = _channel_pair()
    try:
        faults.inject("dispatch_send", "error", occurrences=1)
        with pytest.raises(WorkerDied, match="dispatch_send"):
            with ch.exchange():
                ch.send({"op": "ping"})
                ch.collect_acks(deadline=1.0)
    finally:
        faults.reset("dispatch_send")
    done = threading.Event()

    def closer():
        ch.close()
        done.set()

    threading.Thread(target=closer, daemon=True).start()
    assert done.wait(5.0), \
        "close() deadlocked on a lock left held by a failed send"
    w.close()


def test_worker_recv_distinguishes_stop_from_coordinator_death():
    """EOF without a stop frame is a CRASHED coordinator (CoordinatorLost,
    logged + rejoin attempt), never a silent clean exit."""
    from greengage_tpu.parallel.multihost import CoordinatorLost

    ch, (w,) = _channel_pair()
    with ch.exchange():
        ch.send({"op": "stop"})
    assert w.recv()["op"] == "stop"       # clean shutdown: a normal frame
    ch.close()
    w.close()

    ch2, (w2,) = _channel_pair()
    for p in ch2._workers:                # abrupt death: no stop frame
        p.close()
    with pytest.raises(CoordinatorLost, match="without a stop frame"):
        w2.recv()
    ch2.close()
    w2.close()


def test_heartbeat_detects_partition_and_marks_channel_dead():
    """Idle-time ping/pong: once a worker stops answering, hb_failure is
    recorded within ~one interval and every later send raises WorkerDied
    (the next statement degrades instead of dispatching)."""
    from greengage_tpu.config import Settings
    from greengage_tpu.parallel.multihost import WorkerDied

    ch, (w,) = _channel_pair()
    s = Settings()
    s.mh_heartbeat_interval = 0.1
    ch.settings = s
    answered = threading.Event()

    def pong_twice():
        for _ in range(2):
            if w.recv().get("op") == "ping":
                w.ack(True)
        answered.set()
        # then fall silent (partition analog) — keep the socket open

    t = threading.Thread(target=pong_twice, daemon=True)
    t.start()
    ch.start_heartbeat()
    assert answered.wait(5.0)
    end = time.monotonic() + 5.0
    while ch.hb_failure is None and time.monotonic() < end:
        time.sleep(0.02)
    assert ch.hb_failure is not None, \
        "silent worker never failed the heartbeat liveness check"
    with pytest.raises(WorkerDied, match="marked dead"):
        with ch.exchange():
            ch.send({"op": "sql", "sql": "select 1"})
    ch.close()
    w.close()


def test_quiesce_keeps_listener_and_gang_rejoins():
    """After quiesce (degrade) the listener stays open: a worker that
    reconnects + hellos is adopted and the channel serves exchanges
    again — the control-plane half of gang recovery."""
    from greengage_tpu.parallel.multihost import CoordinatorLost

    ch, (w,) = _channel_pair()
    ch.quiesce()
    with pytest.raises(CoordinatorLost):
        w.recv()                           # our connection was torn down
    assert w.reconnect(), "reconnect to the kept listener failed"
    end = time.monotonic() + 5.0
    while not ch.rejoin_ready() and time.monotonic() < end:
        time.sleep(0.02)
    assert ch.rejoin_ready(), "hello frame never completed the gang"
    ch.adopt_rejoined()

    def pong_once():
        if w.recv().get("op") == "ping":
            w.ack(True, topology_version=7)

    t = threading.Thread(target=pong_once, daemon=True)
    t.start()
    acks = ch.broadcast({"op": "ping"}, deadline=5.0)
    assert acks == [{"ok": True, "error": None, "topology_version": 7}]
    ch.close()
    w.close()


# ---------------------------------------------------------------------------
# session-level: a REAL Database dispatching through the protocol against a
# scripted worker thread (all 8 mesh devices are local to the coordinator,
# so results are complete without a second process). Covers hang/death at
# each phase — readiness, go, completion — with bounded-time degradation
# and rejoin, no sleeps longer than the configured deadlines.
# ---------------------------------------------------------------------------

def _scripted_gang(tmp_path, settings_json, n_workers=1):
    """Database(multihost=coordinator) + WorkerChannel(s) the test scripts.
    Setup statements are host-only (DDL / VALUES insert / analyze), so no
    worker needs to serve during them."""
    import json as _json

    import greengage_tpu
    from greengage_tpu.parallel.multihost import MultihostRuntime

    path = str(tmp_path / "cluster")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "settings.json"), "w") as f:
        f.write(_json.dumps(settings_json))
    ch, workers = _channel_pair(n_workers=n_workers)
    db = greengage_tpu.connect(path, numsegments=8,
                               multihost=MultihostRuntime(0, n_workers + 1,
                                                          ch))
    db.sql("create table t (k bigint, v int) distributed by (k)")
    db.sql("insert into t values " + ",".join(
        f"({i}, {i % 7})" for i in range(300)))
    db.sql("analyze")
    if n_workers == 1:
        return db, ch, workers[0]
    return db, ch, workers


def _serve_mesh(w, n=100):
    """Scripted worker: answer sync/ping/sql frames like worker_loop does
    (no device work — the coordinator owns every segment here)."""
    from greengage_tpu.parallel.multihost import CoordinatorLost

    try:
        for _ in range(n):
            msg = w.recv(idle_timeout=30.0)
            op = msg.get("op")
            if op == "stop":
                return
            if op == "sync":
                w.ack(True, topology_version=msg.get("topology_version"))
            elif op == "ping":
                w.ack(True)
            elif op == "sql":
                w.ack(True)                       # readiness
                if w.recv(idle_timeout=30.0).get("op") == "go":
                    w.ack(True)                   # completion
    except (CoordinatorLost, OSError):
        return


def _recover(db, timeout=10.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if db.mh_try_recover():
            return True
        time.sleep(0.05)
    return False


def test_session_hang_at_readiness_degrades_and_rejoins(devices8, tmp_path):
    """Worker goes silent on the readiness round: detection within
    mh_ready_deadline, the statement completes degraded, the worker
    rejoins, and the session returns to mesh dispatch."""
    # mh_retry_window_s = 0 and mh_reform_enabled = 0: this test asserts
    # the LEGACY degraded fallback, so neither the transparent read-only
    # redispatch (test_dispatch_retry_*) nor N-1 re-formation
    # (test_session_worker_death_reforms_n1_*) may win the race against
    # the instantly-reconnecting scripted worker
    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_ready_deadline": 0.5,
                                          "mh_retry_window_s": 0,
                                          "mh_reform_enabled": 0})

    def script():
        from greengage_tpu.parallel.multihost import CoordinatorLost

        try:
            while True:
                if w.recv(idle_timeout=30.0).get("op") == "sql":
                    break                 # swallow it: hung worker
        except (CoordinatorLost, OSError):
            pass
        try:
            while True:
                w.recv(idle_timeout=30.0)  # wait for the quiesce teardown
        except (CoordinatorLost, OSError):
            pass
        if w.reconnect():
            _serve_mesh(w)

    t = threading.Thread(target=script, daemon=True)
    t.start()
    res = {}
    qt = threading.Thread(
        target=lambda: res.update(r=db.sql("select count(*), sum(v) from t")),
        daemon=True)
    t0 = time.monotonic()
    qt.start()
    while db._mh_degraded is None and time.monotonic() - t0 < 5.0:
        time.sleep(0.02)
    detect_s = time.monotonic() - t0
    assert db._mh_degraded, "hung worker never detected"
    assert detect_s < 5.0                 # 0.5s deadline + slack, no hang
    qt.join(240)                          # degraded subprocess completes it
    assert not qt.is_alive(), "degraded statement never completed"
    r = res["r"]
    assert [int(x) for x in r.rows()[0]] == [300, sum(i % 7 for i in range(300))]
    assert r.stats.get("degraded") is True
    assert _recover(db), "gang never recovered after worker rejoin"
    assert db._mh_degraded is None
    r = db.sql("select count(*), sum(v) from t")   # two-phase mesh again
    assert [int(x) for x in r.rows()[0]] == [300, sum(i % 7 for i in range(300))]
    assert r.stats.get("segments") == 8            # mesh, not degraded
    ch.close()
    t.join(10)


def test_session_death_at_go_phase_degrades_and_rejoins(devices8, tmp_path):
    """The go frame fails (dispatch_send fault, start_after=1 so the sql
    broadcast before it succeeds): nobody entered a collective, the
    statement completes degraded, and the gang re-forms."""
    from greengage_tpu.runtime.faultinject import faults

    # retry window + reform 0: assert the degraded fallback (see above)
    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_retry_window_s": 0,
                                          "mh_reform_enabled": 0})

    def script():
        from greengage_tpu.parallel.multihost import CoordinatorLost

        try:
            msg = w.recv(idle_timeout=30.0)
            assert msg.get("op") == "sql"
            w.ack(True)                   # readiness answered fine
            while True:
                w.recv(idle_timeout=30.0)  # go never arrives; EOF next
        except (CoordinatorLost, OSError):
            pass
        if w.reconnect():
            _serve_mesh(w)

    t = threading.Thread(target=script, daemon=True)
    t.start()
    faults.inject("dispatch_send", "error", occurrences=1, start_after=1)
    try:
        r = db.sql("select count(*) from t")
    finally:
        faults.reset("dispatch_send")
    assert int(r.rows()[0][0]) == 300
    assert r.stats.get("degraded") is True
    assert db._mh_degraded
    assert _recover(db), "gang never recovered after worker rejoin"
    r = db.sql("select count(*) from t")
    assert int(r.rows()[0][0]) == 300
    assert r.stats.get("segments") == 8
    ch.close()
    t.join(10)


def test_session_hang_at_completion_keeps_result_and_rejoins(devices8, tmp_path):
    """Worker answers readiness + go but never acks completion: the
    coordinator's own result stands (it already executed), the session
    degrades within mh_ack_deadline, then recovers on rejoin."""
    # reform off: this test asserts the LEGACY degraded fallback (the N-1
    # re-formation path has its own tests below)
    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_ack_deadline": 0.5,
                                          "mh_reform_enabled": 0})

    def script():
        from greengage_tpu.parallel.multihost import CoordinatorLost

        try:
            msg = w.recv(idle_timeout=30.0)
            assert msg.get("op") == "sql"
            w.ack(True)                   # readiness
            w.recv(idle_timeout=30.0)     # go — then never ack completion
            while True:
                w.recv(idle_timeout=30.0)  # hang until EOF from quiesce
        except (CoordinatorLost, OSError):
            pass
        if w.reconnect():
            _serve_mesh(w)

    t = threading.Thread(target=script, daemon=True)
    t.start()
    r = db.sql("select count(*), sum(v) from t")
    assert [int(x) for x in r.rows()[0]] == [300, sum(i % 7 for i in range(300))]
    assert r.stats.get("segments") == 8   # computed on the mesh, not degraded
    assert db._mh_degraded, "completion-ack hang did not degrade the gang"
    assert _recover(db), "gang never recovered after worker rejoin"
    assert db._mh_degraded is None
    r = db.sql("select count(*) from t")
    assert int(r.rows()[0][0]) == 300
    ch.close()
    t.join(10)


# ---------------------------------------------------------------------------
# full 2-process cluster: fault-injected worker HANG (not death) during the
# readiness round — bounded-time degradation, then the woken worker rejoins
# over the kept listener and the session resumes two-phase mesh dispatch
# through the real worker_loop. Control-plane-only gang (distributed=False):
# this jax's CPU backend has no cross-process collectives, so each process
# runs the lockstep program on its own full local mesh.
# ---------------------------------------------------------------------------

COORD_HANG_REJOIN_SCRIPT = r"""
import json, os, sys, time
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(f"({i}, {i % 7})" for i in range(2000)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
out["pre"] = [int(x) for x in r.rows()[0]]
# this test pins the LEGACY degrade-then-rejoin path (the N-1 re-formation
# path is asserted by the reform tests): without the pin the coordinator
# would re-form over the survivors and never degrade
db.sql("set mh_reform_enabled = off")
# bound the readiness round tightly, then arm a one-shot 4s hang on the
# worker's ack path (gp_inject_fault dispatched over the control channel)
db.sql("set mh_ready_deadline = 1")
db.cluster_inject_fault("worker_ack", type="sleep", sleep_s=4, occurrences=1)
t0 = time.monotonic()
r = db.sql("select count(*), sum(v) from f")
out["stmt_s"] = time.monotonic() - t0
out["post"] = [int(x) for x in r.rows()[0]]
out["degraded_during"] = bool(db._mh_degraded)
out["deg_stats"] = bool(getattr(r, "stats", {}).get("degraded"))
# the worker wakes at ~4s, finds its connection gone, and redials the
# kept listener; recovery replays the settings/topology sync
rec = False
end = time.monotonic() + 90
while time.monotonic() < end:
    if db.mh_try_recover():
        rec = True
        break
    time.sleep(0.1)
out["recovered"] = rec
if rec:
    r = db.sql("select count(*), sum(v) from f")
    out["post_rejoin"] = [int(x) for x in r.rows()[0]]
    out["segments"] = r.stats.get("segments")
    out["degraded_after"] = bool(db._mh_degraded)
    db.sql("delete from f where k < 50")
    r = db.sql("select count(*) from f")
    out["post_dml"] = int(r.rows()[0][0])
    # idle-time partition: a one-shot 3s hang on the worker's ping reply
    # (heartbeat fault point) must mark the channel dead BETWEEN
    # statements, degrade the next (host-only) statement, and the gang
    # must recover a SECOND time once the worker wakes and redials
    db.cluster_inject_fault("heartbeat", type="sleep", sleep_s=3,
                            occurrences=1)
    end = time.monotonic() + 20
    while db.multihost.channel.hb_failure is None and time.monotonic() < end:
        time.sleep(0.1)
    out["hb_failure"] = bool(db.multihost.channel.hb_failure)
    db.sql("create table hb_marker (k int)")   # host-only: degrades locally
    out["hb_degraded"] = bool(db._mh_degraded)
    rec2 = False
    end = time.monotonic() + 90
    while time.monotonic() < end:
        if db.mh_try_recover():
            rec2 = True
            break
        time.sleep(0.1)
    out["recovered_again"] = rec2
    if rec2:
        r = db.sql("select count(*) from f")
        out["post_rejoin2"] = int(r.rows()[0][0])
mh.channel.close()   # clean stop frame: the worker exits instead of redialing
print("RESULT:" + json.dumps(out), flush=True)
os._exit(0)
"""


def test_cluster_worker_hang_bounded_degrade_then_rejoin(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_HANG_REJOIN_SCRIPT, str(port),
         str(cport), path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=480)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"hang/rejoin timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])
    want = [2000, sum(i % 7 for i in range(2000))]
    assert out["pre"] == want
    assert out["post"] == want            # completed DURING the hang, degraded
    assert out["degraded_during"] is True
    assert out["deg_stats"] is True
    assert out["stmt_s"] < 120            # bounded: no unbounded readline
    assert out["recovered"] is True, f"worker never rejoined:\n{wout}"
    assert out["post_rejoin"] == want     # two-phase mesh dispatch again
    assert out["segments"] == 8
    assert out["degraded_after"] is False
    assert out["post_dml"] == 1950        # post-rejoin DML dispatches too
    # idle-time partition caught by heartbeats, then a SECOND recovery
    assert out["hb_failure"] is True, "heartbeat never flagged the hang"
    assert out["hb_degraded"] is True
    assert out["recovered_again"] is True, f"second rejoin failed:\n{wout}"
    assert out["post_rejoin2"] == 1950
    # the worker LOGGED the loss and the rejoin instead of exiting silently
    assert "connection lost" in wout and "reconnected" in wout, wout


# ---------------------------------------------------------------------------
# dispatch-failure retry matrix (docs/ROBUSTNESS.md statement lifecycle):
# read-only statements redispatch transparently once the gang re-forms;
# writes surface the error without re-execution (exactly-once)
# ---------------------------------------------------------------------------

def _die_then_rejoin(w):
    """Scripted worker: die on the first sql frame (close mid-dispatch),
    then redial the kept listener and serve mesh exchanges normally."""
    from greengage_tpu.parallel.multihost import CoordinatorLost

    try:
        msg = w.recv(idle_timeout=30.0)
        assert msg.get("op") == "sql"
    except (CoordinatorLost, OSError):
        pass
    w.close()
    end = time.monotonic() + 15
    while time.monotonic() < end:
        if w.reconnect():
            break
        time.sleep(0.05)
    else:
        return
    _serve_mesh(w)


def test_dispatch_retry_readonly_redispatches_after_rejoin(devices8, tmp_path):
    """A read-only statement that loses its worker mid-dispatch succeeds
    TRANSPARENTLY on the re-formed mesh — statements_retried == 1, no
    degraded subprocess, no client-visible error."""
    from greengage_tpu.runtime.logger import counters

    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_retry_window_s": 15})
    t = threading.Thread(target=_die_then_rejoin, args=(w,), daemon=True)
    t.start()
    base = counters.get("statements_retried")
    r = db.sql("select count(*), sum(v) from t")
    assert [int(x) for x in r.rows()[0]] == \
        [300, sum(i % 7 for i in range(300))]
    assert r.stats.get("segments") == 8       # mesh result, not degraded
    assert not r.stats.get("degraded")
    assert counters.get("statements_retried") == base + 1
    assert db._mh_degraded is None            # gang recovered in-line
    ch.close()
    t.join(10)


def test_dispatch_failure_write_not_retried(devices8, tmp_path):
    """The same mid-dispatch worker death on a WRITE surfaces the error
    without re-execution: nothing committed (row count unchanged by
    assertion), statements_retried untouched — exactly-once stays the
    DTM's decision, never the dispatcher's."""
    from greengage_tpu.runtime.logger import counters

    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_retry_window_s": 15})
    t = threading.Thread(target=_die_then_rejoin, args=(w,), daemon=True)
    t.start()
    base = counters.get("statements_retried")
    with pytest.raises(Exception, match="auto-retried"):
        db.sql("delete from t where k < 10")
    assert counters.get("statements_retried") == base
    assert _recover(db), "gang never recovered after worker rejoin"
    r = db.sql("select count(*) from t")      # exactly-once: no row lost
    assert int(r.rows()[0][0]) == 300
    ch.close()
    t.join(10)


# ---------------------------------------------------------------------------
# N-1 mesh re-formation (the tentpole; docs/ROBUSTNESS.md "Topology
# re-formation"): a worker SIGKILL re-forms the gang over the SURVIVORS —
# subsequent statements (DML included) dispatch on the shrunken topology,
# never the single-process degraded path — and a rejoin restores full
# strength. Scripted 3-process gang: coordinator + 2 worker channels.
# ---------------------------------------------------------------------------

class _ReformWorker:
    """Scripted gang member for the re-formation tests: serves sync/ping/
    sql frames, survives quiesce teardowns by redialing the kept listener
    (the survivor half of re-formation), and can be killed — an abrupt
    socket close with no stop frame, the SIGKILL analog — then later
    allowed back in (the rejoin half). Reads BLOCK like the real
    worker_loop; every control transition arrives as a socket error
    (short recv timeouts poison the channel's buffered reader)."""

    def __init__(self, w):
        self.w = w
        self.die = threading.Event()
        self.dead = threading.Event()   # the close actually landed
        self.rejoin = threading.Event()
        self.halt = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def kill(self):
        """SIGKILL analog: shut the socket down under the serving thread —
        EOF with no stop frame. (shutdown, not close: closing the makefile
        from another thread deadlocks against an in-flight readline.) The
        thread parks until allow_rejoin()."""
        self.die.set()
        self._shutdown()

    def allow_rejoin(self):
        self.rejoin.set()

    def close(self):
        self.halt.set()
        self.rejoin.set()
        self._shutdown()
        self.thread.join(10)
        self.w.close()

    def _shutdown(self):
        try:
            self.w._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _redial(self):
        end = time.monotonic() + 15
        while time.monotonic() < end and not self.halt.is_set():
            if self.w.reconnect():
                return True
            time.sleep(0.05)
        return False

    def _run(self):
        from greengage_tpu.parallel.multihost import CoordinatorLost

        w = self.w
        while not self.halt.is_set():
            try:
                msg = w.recv()
                op = msg.get("op")
                if op == "stop":
                    return
                if op == "sync":
                    w.ack(True, topology_version=msg.get("topology_version"))
                elif op == "ping":
                    w.ack(True)
                elif op == "sql":
                    w.ack(True)                     # readiness
                    if w.recv().get("op") == "go":
                        w.ack(True)                 # completion
            except (CoordinatorLost, OSError):
                if self.halt.is_set():
                    return
                if self.die.is_set():               # killed: hold the EOF
                    self.dead.set()
                    self.rejoin.wait(60)
                    if self.halt.is_set():
                        return
                    self.die.clear()
                    self.rejoin.clear()
                    self.dead.clear()
                if not self._redial():              # quiesce/rejoin redial
                    return


def test_worker_sigkill_reforms_n1_then_rejoin_restores_full(devices8,
                                                             tmp_path):
    """The acceptance matrix: SIGKILL a worker mid-session -> the next
    statement (and DML) runs on the re-formed N-1 gang, counted in
    mh_reform_total with a bumped mh_topology_version; the worker's
    rejoin restores the full topology."""
    from greengage_tpu.runtime.logger import counters

    db, ch, (w1, w2) = _scripted_gang(
        tmp_path, {"mh_heartbeat_interval": 0, "mh_ready_deadline": 2,
                   "mh_reform_deadline_s": 5}, n_workers=2)
    g1, g2 = _ReformWorker(w1), _ReformWorker(w2)
    try:
        want = [300, sum(i % 7 for i in range(300))]
        r = db.sql("select count(*), sum(v) from t")
        assert [int(x) for x in r.rows()[0]] == want
        assert db.mh_state()["state"] == "full"
        base_reform = counters.get("mh_reform_total")
        topo0 = counters.get("mh_topology_version")

        g1.kill()                    # worker 1 dies: abrupt close, no stop
        assert g1.dead.wait(5), "scripted worker never closed its socket"
        r = db.sql("select count(*), sum(v) from t")
        assert [int(x) for x in r.rows()[0]] == want
        assert not r.stats.get("degraded"), \
            "worker death fell to the single-process path instead of N-1"
        assert r.stats.get("segments") == 8
        assert db._mh_degraded is None
        st = db.mh_state()
        assert st["state"] == "n-1"
        assert st["active_workers"] == 1 and st["expected_workers"] == 2
        assert counters.get("mh_reform_total") == base_reform + 1
        assert counters.get("mh_topology_version") > topo0
        assert counters.get("mh_topology_version") == \
            db.catalog.segments.version

        # DML on the re-formed gang: manifest commits are coordinator-local
        db.sql("delete from t where k < 5")
        r = db.sql("select count(*) from t")
        assert int(r.rows()[0][0]) == 295
        assert db.mh_state()["state"] == "n-1"

        topo_n1 = counters.get("mh_topology_version")
        g1.allow_rejoin()            # the lost worker returns
        end = time.monotonic() + 10
        while db.mh_state()["state"] != "full" and time.monotonic() < end:
            db.mh_try_recover()
            time.sleep(0.05)
        assert db.mh_state()["state"] == "full", \
            "rejoin never restored the full topology"
        assert counters.get("mh_topology_version") > topo_n1
        r = db.sql("select count(*), sum(v) from t")
        assert int(r.rows()[0][0]) == 295
        assert r.stats.get("segments") == 8
    finally:
        g1.close()
        g2.close()
        ch.close()


@pytest.mark.parametrize("fault", ["mesh_reform",
                                   "mirror_promote_during_reform"])
def test_reform_fault_falls_back_to_degraded(devices8, tmp_path, fault):
    """A re-formation that fails at either fault point (the reform step
    itself, or mirror promotion inside it) must take the legacy degraded
    path — bounded, never a hang or a half-formed gang — and the normal
    full-gang rejoin must still recover it."""
    from greengage_tpu.runtime.faultinject import faults
    from greengage_tpu.runtime.logger import counters

    db, ch, w = _scripted_gang(tmp_path, {"mh_heartbeat_interval": 0,
                                          "mh_retry_window_s": 0})
    t = threading.Thread(target=_die_then_rejoin, args=(w,), daemon=True)
    t.start()
    base = counters.get("mh_reform_total")
    faults.inject(fault, "error", occurrences=1)
    try:
        r = db.sql("select count(*) from t")
    finally:
        faults.reset(fault)
    assert int(r.rows()[0][0]) == 300
    assert r.stats.get("degraded") is True
    assert db._mh_degraded
    assert counters.get("mh_reform_total") == base
    assert _recover(db), "gang never recovered after worker rejoin"
    r = db.sql("select count(*) from t")
    assert int(r.rows()[0][0]) == 300
    assert r.stats.get("segments") == 8
    ch.close()
    t.join(10)

# ---------------------------------------------------------------------------
# chaos tier (slow; the tier1.yml non-blocking chaos step): repeated
# kill -> N-1 reform -> rejoin -> full cycles, with the reform fault
# points armed on later cycles so the degraded fallback and the recovery
# from it are exercised in the SAME session as successful re-formations
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_reform_rejoin_chaos_cycles(devices8, tmp_path):
    """Three kill/rejoin cycles against one session: every cycle must land
    in n-1 (never the single-process path), serve reads AND writes there,
    and restore full strength on rejoin — with monotonically advancing
    mh_reform_total / mh_topology_version. Cycle 2 arms a one-shot
    mesh_reform fault, so that cycle degrades instead, recovers via the
    full-gang rejoin, and the NEXT cycle still re-forms cleanly."""
    from greengage_tpu.runtime.faultinject import faults
    from greengage_tpu.runtime.logger import counters

    db, ch, (w1, w2) = _scripted_gang(
        tmp_path, {"mh_heartbeat_interval": 0, "mh_ready_deadline": 2,
                   "mh_reform_deadline_s": 5}, n_workers=2)
    g1, g2 = _ReformWorker(w1), _ReformWorker(w2)
    rows = 300
    try:
        for cycle, faulted in enumerate((False, True, False)):
            victim = (g1, g2)[cycle % 2]
            reform0 = counters.get("mh_reform_total")
            topo0 = counters.get("mh_topology_version")
            if faulted:
                faults.inject("mesh_reform", "error", occurrences=1)
            try:
                victim.kill()
                assert victim.dead.wait(5), \
                    f"cycle {cycle}: worker never closed its socket"
                r = db.sql("select count(*) from t")
            finally:
                if faulted:
                    faults.reset("mesh_reform")
            assert int(r.rows()[0][0]) == rows
            if faulted:
                assert r.stats.get("degraded") is True
                assert counters.get("mh_reform_total") == reform0
            else:
                assert not r.stats.get("degraded"), \
                    f"cycle {cycle} fell to the single-process path"
                assert db.mh_state()["state"] == "n-1"
                assert counters.get("mh_reform_total") == reform0 + 1
                assert counters.get("mh_topology_version") > topo0
                # writes flow on the shrunken gang every cycle
                db.sql(f"delete from t where k = {cycle}")
                rows -= 1
                assert int(db.sql("select count(*) from t")
                           .rows()[0][0]) == rows
            victim.allow_rejoin()
            end = time.monotonic() + 10
            while db.mh_state()["state"] != "full" \
                    and time.monotonic() < end:
                db.mh_try_recover()
                time.sleep(0.05)
            assert db.mh_state()["state"] == "full", \
                f"cycle {cycle}: rejoin never restored the full topology"
            r = db.sql("select count(*) from t")
            assert int(r.rows()[0][0]) == rows
            assert r.stats.get("segments") == 8
    finally:
        g1.close()
        g2.close()
        ch.close()


# ---------------------------------------------------------------------------
# multihost serving parity (ISSUE 18): a 2-process gang batch-serves
# concurrent same-shape statements through ONE broadcast window per
# dispatch — members_total > dispatch_total proves the amortization
# happened on the gang, not just on a single host
# ---------------------------------------------------------------------------

COORD_BATCH_SCRIPT = r"""
import json, os, sys, threading
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table t (k int, a int, v int) distributed by (k)")
db.sql("insert into t values " + ",".join(
    f"({i},{i},{i % 7})" for i in range(3000)))
db.sql("analyze")
def q(i):
    return f"select count(*), sum(v) from t where a > {i}"
# serial oracle BEFORE batching turns on (classic lockstep dispatch)
oracle = {i: [[int(x) for x in row] for row in db.sql(q(i)).rows()]
          for i in range(8)}
db.sql("set batch_serving_enabled = on")
db.sql("set batch_window_ms = 150")
db.sql(q(100))   # warm: plan cache + the width-1 bucket via the gang path
# hold the first dispatch on the "device" so a real multi-member window
# accumulates behind it (both processes sleep in their concurrent dispatch)
faults.inject("batch_dispatch", "sleep", sleep_s=0.4, occurrences=1)
c0 = counters.snapshot()
results, errors = {}, {}
def member(i):
    try:
        results[i] = [[int(x) for x in row] for row in db.sql(q(i)).rows()]
    except Exception as e:
        errors[i] = repr(e)
ts = [threading.Thread(target=member, args=(i,)) for i in range(8)]
for t in ts:
    t.start()
for t in ts:
    t.join(timeout=120)
d = counters.since(c0)
out["alive"] = sum(1 for t in ts if t.is_alive())
out["errors"] = errors
out["mismatch"] = [i for i in range(8) if results.get(i) != oracle[i]]
out["members"] = d.get("batch_members_total", 0)
out["dispatch"] = d.get("batch_dispatch_total", 0)
out["fallback"] = d.get("batch_fallback_total", 0)
# post-canary lockstep sanity: the gang still serves classic statements
r = db.sql("select count(*) from t")
out["post"] = int(r.rows()[0][0])
out["post_segments"] = r.stats.get("segments")
mh.channel.close()
print("RESULT:" + json.dumps(out), flush=True)
"""


def test_two_process_gang_batch_serving_canary(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_BATCH_SCRIPT, str(port), str(cport),
         path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=480)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"batch canary timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])
    assert out["alive"] == 0, out
    assert out["errors"] == {}, out
    assert out["mismatch"] == [], out
    # the canary property: the gang amortized members across dispatches
    assert out["members"] > out["dispatch"], out
    assert out["members"] >= 8, out
    assert out["fallback"] == 0, out
    # and classic lockstep service survived the batched windows
    assert out["post"] == 3000, out
    assert out["post_segments"] == 8, out


# ---------------------------------------------------------------------------
# cluster-wide runaway enforcement: aggregated HBM watermarks, one verdict
# ---------------------------------------------------------------------------

COORD_RUNAWAY_SCRIPT = r"""
import json, os, sys
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport, distributed=False)
import greengage_tpu
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.runaway import RunawayCancelled
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, g int, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(
    f"({i}, {i % 13}, {i % 7})" for i in range(2000)))
db.sql("analyze")
r = db.sql("select g, count(*) from f group by g order by g")
out["healthy_groups"] = len(r.rows())
# arm a synthetic 1 TB HBM watermark on every WORKER's completion ack
# (the coordinator's own peak stays honest), then set the global ceiling
db.cluster_inject_fault("mh_hbm_watermark", type="skip", occurrences=-1)
db.sql("set vmem_global_limit_mb = 64")
try:
    db.sql("select g, count(*), sum(v) from f group by g order by g")
    out["cancelled"] = False
except RunawayCancelled as e:
    out["cancelled"] = True
    out["reason"] = str(e)
except Exception as e:                          # noqa: BLE001
    out["cancelled"] = "wrong-type:" + type(e).__name__ + ":" + str(e)
out["coord_runaway_ctr"] = counters.get("statements_cancelled_runaway")
# disarm: the verdict killed the STATEMENT, not the gang
db.cluster_inject_fault("mh_hbm_watermark", type="skip", reset=True)
db.sql("set vmem_global_limit_mb = 0")
r = db.sql("select count(*) from f")
out["after"] = int(r.rows()[0][0])
mh.channel.close()
print("RESULT:" + json.dumps(out), flush=True)
"""


def test_cluster_runaway_aggregated_watermark_cancels_gangwide(tmp_path):
    """PR-20 acceptance: a multihost runaway is detected from AGGREGATED
    worker HBM watermarks (no worker is individually over), the
    cancellation broadcasts to the whole gang, and the client sees a
    typed RunawayCancelled — then the next statement serves normally."""
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1", "--no-distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_RUNAWAY_SCRIPT, str(port), str(cport),
         path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=480)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"runaway gang timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])
    assert out["healthy_groups"] == 13
    assert out["cancelled"] is True, out
    assert "red zone" in out["reason"]
    assert out["coord_runaway_ctr"] >= 1
    assert out["after"] == 2000           # the gang outlived the verdict
