"""Multi-host cluster: 2 processes x 4 virtual CPU devices = 8-segment mesh
spanning processes — VERDICT r1 item #6 (jax.distributed data plane +
statement-channel control plane; ic-proxy/libpq dispatch analog).

pytest's own process already owns a JAX backend, so both the coordinator
and the worker run as SUBPROCESSES sharing a cluster directory; the test
asserts the coordinator's results.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

COORD_SCRIPT = r"""
import json, os, sys
port, cport, path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, g int, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(
    f"({i}, {i % 13}, {i % 7})" for i in range(4000)))
db.sql("create table d (g int, name text) distributed by (g)")
db.sql("insert into d values " + ",".join(f"({i}, 'g{i}')" for i in range(13)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
out["scalar"] = [int(x) for x in r.rows()[0]]
# two-phase grouped agg: group key != distribution key => redistribute
r = db.sql("select g, count(*), sum(v) from f group by g order by g")
out["grouped"] = [[int(x) for x in row] for row in r.rows()]
out["grouped_segments"] = r.stats["segments"]
# cross-process join + broadcast of the dimension
r = db.sql("select d.name, count(*) from f join d on f.g = d.g "
           "group by d.name order by d.name limit 3")
out["join"] = [[row[0], int(row[1])] for row in r.rows()]
# DML with an internal mesh scan, then read back
db.sql("update f set v = 99 where k < 10")
r = db.sql("select count(*) from f where v = 99")
out["updated"] = int(r.rows()[0][0])
db.sql("delete from f where g = 12")
r = db.sql("select count(*) from f")
out["after_delete"] = int(r.rows()[0][0])
# parallel retrieve cursor: DECLARE broadcasts (workers join the
# collectives), RETRIEVE drains endpoints coordinator-side
db.sql("declare pc parallel retrieve cursor for select k from f where v = 99")
out["cursor_rows"] = sum(
    len(db.sql(f"retrieve all from endpoint {k} of pc").rows())
    for k in range(db.numsegments))
db.sql("close pc")
# spill under multihost: a big load (shared storage; host-side, no
# lockstep needed), then a grouped agg past a tight vmem limit — the SET
# broadcasts so both processes take the same pass-partitioned branch
import numpy as np
db.sql("create table f2 (k bigint, g int, v int) distributed by (k)")
n2 = 600_000
db.load_table("f2", {"k": np.arange(n2), "g": (np.arange(n2) % 13),
                     "v": (np.arange(n2) % 7)})
db.sql("analyze f2")
db.sql("set vmem_protect_limit_mb = 1")
r = db.sql("select g, count(*), sum(v) from f2 group by g order by g")
out["spilled"] = [[int(x) for x in row] for row in r.rows()]
out["spill_passes"] = int(r.stats.get("spill_passes", 0))
db.sql("set vmem_protect_limit_mb = 12288")
# round-5 analytic surface under lockstep: ROLLUP branches + the
# stat-agg moment expansion + percentile windows are deterministic
# rewrites, so both processes compile identical SPMD programs
r = db.sql("select g, count(*) c, grouping(g) lvl from f "
           "group by rollup(g) order by lvl, g")
out["rollup_total"] = [int(x) for x in r.rows()[-1][1:2]]
out["rollup_rows"] = len(r.rows())
r = db.sql("select stddev(v) from f")
out["stddev"] = round(float(r.rows()[0][0]), 9)
r = db.sql("select percentile_cont(0.5) within group (order by v) from f")
out["median"] = float(r.rows()[0][0])
# gpssh analog: run a command on every host over the control plane
ex = db.cluster_exec("echo host-$GGTPU_X; true")
out["exec_hosts"] = [e["ok"] for e in ex]
out["exec_n"] = len(ex)
ex2 = db.cluster_exec("exit 3")
out["exec_fail"] = [e["ok"] for e in ex2]
mh.channel.close()
print("RESULT:" + json.dumps(out), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_cluster(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_SCRIPT, str(port), str(cport), path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        cout, _ = coord.communicate(timeout=480)
        wout, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        coord.kill()
        worker.kill()
        cout = coord.stdout.read() if coord.stdout else ""
        wout = worker.stdout.read() if worker.stdout else ""
        raise AssertionError(
            f"multihost timeout\ncoordinator:\n{cout}\nworker:\n{wout}")
    assert coord.returncode == 0, f"coordinator:\n{cout}\nworker:\n{wout}"
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, f"coordinator:\n{cout}\nworker:\n{wout}"
    out = json.loads(res[0][len("RESULT:"):])

    # oracle (rows 0..3999, g = i%13, v = i%7)
    rows = [(i, i % 13, i % 7) for i in range(4000)]
    assert out["scalar"] == [4000, sum(v for _, _, v in rows)]
    assert out["grouped_segments"] == 8
    want_grouped = {}
    for _, g, v in rows:
        c, s = want_grouped.get(g, (0, 0))
        want_grouped[g] = (c + 1, s + v)
    assert out["grouped"] == [[g, *want_grouped[g]] for g in sorted(want_grouped)]
    want_join = sorted((f"g{g}", want_grouped[g][0]) for g in want_grouped)[:3]
    assert out["join"] == [[n, c] for n, c in want_join]
    assert out["updated"] == 10 - sum(1 for i in range(10) if i % 7 == 99)
    n_g12 = sum(1 for i in range(4000) if i % 13 == 12)
    assert out["after_delete"] == 4000 - n_g12
    assert out["cursor_rows"] == 10   # the rows updated to v=99 (k<10)
    want_spill = {}
    for i in range(600_000):
        c, s = want_spill.get(i % 13, (0, 0))
        want_spill[i % 13] = (c + 1, s + i % 7)
    assert out["spilled"] == [[g, *want_spill[g]] for g in sorted(want_spill)]
    assert out["spill_passes"] >= 2, out["spill_passes"]
    assert out["exec_n"] == 2
    # the round-5 analytic rewrites under lockstep: compare against the
    # same data computed locally
    import numpy as np

    ks = np.arange(4000)
    alive = (ks % 13) != 12
    v = np.where(ks < 10, 99, ks % 7)[alive]
    assert out["rollup_total"] == [int(alive.sum())]
    assert out["rollup_rows"] == 12 + 1
    assert abs(out["stddev"] - float(np.std(v, ddof=1))) < 1e-6
    assert out["median"] == float(np.percentile(v, 50))
    assert out["exec_hosts"] == [True, True]
    assert out["exec_fail"] == [False, False]


# ---------------------------------------------------------------------------
# worker death: detection on the readiness round + degraded local service
# ---------------------------------------------------------------------------

COORD_DEATH_SCRIPT = r"""
import json, os, sys, time
port, cport, path, mark = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
db.sql("create table f (k bigint, v int) distributed by (k)")
db.sql("insert into f values " + ",".join(f"({i}, {i % 7})" for i in range(2000)))
db.sql("analyze")
r = db.sql("select count(*), sum(v) from f")
out["pre"] = [int(x) for x in r.rows()[0]]
open(mark + ".phase1", "w").close()
while not os.path.exists(mark + ".killed"):
    time.sleep(0.05)
# the worker is gone: the readiness round must detect it BEFORE any
# collective, and the statement must still COMPLETE via the degraded
# single-process re-formation over the shared directory
r = db.sql("select count(*), sum(v) from f")
out["post"] = [int(x) for x in r.rows()[0]]
out["degraded"] = bool(db._mh_degraded)
r = db.sql("select count(*) from f where k < 10")
out["post2"] = int(r.rows()[0][0])
out["status_after"] = db.sql("delete from f where k < 100")
r = db.sql("select count(*) from f")
out["post3"] = int(r.rows()[0][0])
print("RESULT:" + json.dumps(out), flush=True)
# the degraded runtime's grpc teardown may error at interpreter exit
# (the dead peer can never complete its streams); results are already
# flushed, so exit without running teardown hooks
os._exit(0)
"""


def test_worker_death_detected_and_degraded_service(tmp_path):
    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    mark = str(tmp_path / "mark")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_DEATH_SCRIPT, str(port), str(cport),
         path, mark],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    import signal
    import time as _t
    try:
        deadline = _t.monotonic() + 300
        while not os.path.exists(mark + ".phase1"):
            assert _t.monotonic() < deadline, "coordinator never reached phase1"
            assert coord.poll() is None, coord.stdout.read()
            _t.sleep(0.05)
        os.kill(worker.pid, signal.SIGKILL)
        worker.wait(timeout=30)
        open(mark + ".killed", "w").close()
        cout, _ = coord.communicate(timeout=480)
    except subprocess.TimeoutExpired:
        coord.kill()
        raise AssertionError(
            f"coordinator hung after worker death:\n{coord.stdout.read()}")
    assert coord.returncode == 0, cout
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, cout
    out = json.loads(res[0][len("RESULT:"):])
    want_sum = sum(i % 7 for i in range(2000))
    assert out["pre"] == [2000, want_sum]
    assert out["post"] == [2000, want_sum]     # completed AFTER the death
    assert out["degraded"] is True
    assert out["post2"] == 10
    assert out["status_after"] == "DELETE 100"  # degraded DML works too
    assert out["post3"] == 1900


def test_plan_hash_deterministic_across_sessions(devices8, tmp_path):
    import numpy as np

    import greengage_tpu
    path = str(tmp_path / "c")
    d1 = greengage_tpu.connect(path=path, numsegments=4)
    d1.sql("create table t (k int, g int, v int) distributed by (k)")
    d1.load_table("t", {"k": np.arange(1000), "g": np.arange(1000) % 7,
                        "v": np.arange(1000)})
    d1.sql("analyze")
    q = "select g, sum(v) from t group by g order by g"
    h1 = d1.plan_hash(q)
    d2 = greengage_tpu.connect(path=path, numsegments=4)
    h2 = d2.plan_hash(q)
    assert h1 is not None and h1 == h2
    assert d1.plan_hash("select 1") is None          # no FROM: host-side


# ---------------------------------------------------------------------------
# worker death + cross-host mirrors: the re-formed topology serves from
# PROMOTED mirror trees on surviving roots (ftsprobe.c:968 / VERDICT r4 #8)
# ---------------------------------------------------------------------------

COORD_MIRROR_DEATH_SCRIPT = r"""
import glob, json, os, sys, time
port, cport, path, mark = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.environ["GGTPU_REPO"])
from greengage_tpu.parallel.multihost import init_multihost
mh = init_multihost(f"127.0.0.1:{port}", 2, 0, cport)
import greengage_tpu
db = greengage_tpu.connect(path, multihost=mh)
out = {}
r = db.sql("select count(*), sum(v) from f")
out["pre"] = [int(x) for x in r.rows()[0]]
open(mark + ".phase1", "w").close()
while not os.path.exists(mark + ".killed"):
    time.sleep(0.05)
# the dead worker's host took its data disk: contents 4..7 lose their
# primary trees; the re-formed topology must promote their mirrors
for content in (4, 5, 6, 7):
    for f in glob.glob(os.path.join(path, "data", "*", f"seg{content}", "*")):
        os.remove(f)
r = db.sql("select count(*), sum(v) from f")
out["post"] = [int(x) for x in r.rows()[0]]
out["degraded"] = bool(db._mh_degraded)
out["promoted"] = sorted(
    c for c in range(8)
    if db.catalog.segments.acting_primary(c).preferred_role.value == "m")
print("RESULT:" + json.dumps(out), flush=True)
os._exit(0)
"""


def test_worker_death_promotes_cross_host_mirrors(tmp_path):
    import greengage_tpu
    from greengage_tpu.mgmt import cli

    port, cport = _free_port(), _free_port()
    path = str(tmp_path / "cluster")
    mark = str(tmp_path / "mark")
    # build the mirrored cluster with spread mirror roots up front
    # (width 8 = the 2-process x 4-device global mesh)
    d = greengage_tpu.connect(path, numsegments=8, mirrors=True)
    d.sql("create table f (k bigint, v int) distributed by (k)")
    d.sql("insert into f values " + ",".join(
        f"({i}, {i % 7})" for i in range(2000)))
    d.sql("analyze")
    d.close()
    cli.main(["mirrorroots", "-d", path, "--roots",
              f"{tmp_path / 'hostA'},{tmp_path / 'hostB'}"])

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "GGTPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "GGTPU_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    worker = subprocess.Popen(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "worker",
         "-d", path, "--coordinator", f"127.0.0.1:{port}",
         "--control-port", str(cport), "--num-processes", "2",
         "--process-id", "1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    coord = subprocess.Popen(
        [sys.executable, "-c", COORD_MIRROR_DEATH_SCRIPT, str(port),
         str(cport), path, mark],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    import signal
    import time as _t
    try:
        deadline = _t.monotonic() + 300
        while not os.path.exists(mark + ".phase1"):
            assert _t.monotonic() < deadline, "coordinator never reached phase1"
            assert coord.poll() is None, coord.stdout.read()
            _t.sleep(0.05)
        os.kill(worker.pid, signal.SIGKILL)
        worker.wait(timeout=30)
        open(mark + ".killed", "w").close()
        cout, _ = coord.communicate(timeout=480)
    except subprocess.TimeoutExpired:
        coord.kill()
        raise AssertionError(
            f"coordinator hung after worker death:\n{coord.stdout.read()}")
    assert coord.returncode == 0, cout
    res = [ln for ln in cout.splitlines() if ln.startswith("RESULT:")]
    assert res, cout
    out = json.loads(res[0][len("RESULT:"):])
    want = [2000, sum(i % 7 for i in range(2000))]
    assert out["pre"] == want
    assert out["degraded"] is True
    assert out["promoted"] == [4, 5, 6, 7]  # mirrors promoted for lost trees
    assert out["post"] == want            # served from mirror data
