"""GROUP BY ROLLUP / CUBE / GROUPING SETS + grouping().

Reference parity: the grouping-extension grammar
(/root/reference/src/backend/parser/gram.y:12457 group_clause) and its
Append-of-Agg execution. Here each grouping set is an independent
distributed aggregate UNION ALLed (sql/binder._bind_grouping_sets);
absent keys project typed NULLs, grouping() folds per branch."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.types import Coded


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(5)
    n = 300
    d.sql("create table gs (cat text, brand text, region int, qty int, "
          "price double precision, k bigint) distributed by (k)")
    d.load_table("gs", {
        "cat": Coded(["books", "food", "toys"],
                     rng.integers(0, 3, n).astype(np.int32)),
        "brand": Coded([f"b{i}" for i in range(5)],
                       rng.integers(0, 5, n).astype(np.int32)),
        "region": rng.integers(0, 4, n).astype(np.int32),
        "qty": rng.integers(1, 50, n).astype(np.int32),
        "price": rng.uniform(1, 100, n),
        "k": np.arange(n, dtype=np.int64)})
    d.sql("analyze")
    # oracle frame rebuilt directly from the same RNG draws
    rng = np.random.default_rng(5)
    d.df = pd.DataFrame({
        "cat": np.array(["books", "food", "toys"])[rng.integers(0, 3, n)],
        "brand": np.array([f"b{i}" for i in range(5)])[rng.integers(0, 5, n)],
        "region": rng.integers(0, 4, n),
        "qty": rng.integers(1, 50, n),
        "price": rng.uniform(1, 100, n)})
    yield d
    d.close()


def _rollup_oracle(df, keys, val="qty"):
    """pandas oracle: concatenated group-bys for each rollup prefix."""
    frames = []
    for i in range(len(keys), -1, -1):
        ks = keys[:i]
        if ks:
            g = df.groupby(ks, as_index=False)[val].sum()
        else:
            g = pd.DataFrame({val: [df[val].sum()]})
        for missing in keys[i:]:
            g[missing] = None
        frames.append(g[keys + [val]])
    return pd.concat(frames, ignore_index=True)


def test_rollup_totals(db):
    r = db.sql("select cat, brand, sum(qty) q from gs "
               "group by rollup(cat, brand) order by cat, brand")
    want = _rollup_oracle(db.df, ["cat", "brand"])
    got = r.rows()
    assert len(got) == len(want)
    # leaf rows + per-cat subtotals + grand total all present and correct
    m = {(a, b): q for a, b, q in got}
    for _, w in want.iterrows():
        key = (w["cat"], w["brand"])
        assert m[key] == w["qty"], key


def test_cube_counts(db):
    r = db.sql("select cat, region, count(*) c from gs group by cube(cat, region)")
    got = r.rows()
    ncat, nreg = db.df.cat.nunique(), db.df.region.nunique()
    assert len(got) == ncat * nreg + ncat + nreg + 1
    total = next(c for a, b, c in got if a is None and b is None)
    assert total == len(db.df)


def test_grouping_sets_explicit(db):
    r = db.sql("select cat, region, sum(qty) q from gs "
               "group by grouping sets ((cat), (region), ())")
    got = r.rows()
    assert len(got) == db.df.cat.nunique() + db.df.region.nunique() + 1
    by_cat = db.df.groupby("cat").qty.sum()
    for a, b, q in got:
        if a is not None:
            assert b is None and q == by_cat[a]


def test_grouping_function_bitmask(db):
    r = db.sql("select grouping(cat, brand) g, count(*) c from gs "
               "group by rollup(cat, brand) order by g")
    masks = sorted({row[0] for row in r.rows()})
    assert masks == [0, 1, 3]      # leaf, brand-rolled, both-rolled


def test_mixed_plain_and_rollup(db):
    r = db.sql("select region, cat, sum(qty) q from gs "
               "group by region, rollup(cat)")
    got = r.rows()
    nreg = db.df.region.nunique()
    assert len(got) == nreg * db.df.cat.nunique() + nreg
    by_reg = db.df.groupby("region").qty.sum()
    for reg, cat, q in got:
        assert reg is not None          # region is always grouped
        if cat is None:
            assert q == by_reg[reg]


def test_having_on_grouping(db):
    r = db.sql("select cat, sum(qty) q from gs group by rollup(cat) "
               "having grouping(cat) = 1")
    got = r.rows()
    assert len(got) == 1 and got[0][0] is None
    assert got[0][1] == db.df.qty.sum()


def test_rollup_no_aggregates(db):
    """SELECT key only (no aggregate calls): the () branch still yields
    exactly one all-NULL row (keyless Aggregate anchored internally)."""
    r = db.sql("select cat from gs group by rollup(cat)")
    got = [row[0] for row in r.rows()]
    assert sorted(x for x in got if x is not None) == ["books", "food", "toys"]
    assert got.count(None) == 1


def test_rollup_with_stat_aggs(db):
    """Composition: the stat-agg expansion rides inside each grouping-set
    branch."""
    r = db.sql("select cat, stddev(price) s from gs group by rollup(cat) "
               "order by cat nulls last")
    want = db.df.groupby("cat").price.std()
    got = r.rows()
    for cat, s in got:
        ref = want[cat] if cat is not None else db.df.price.std()
        np.testing.assert_allclose(s, ref, rtol=1e-9)


def test_order_by_agg_expr_over_rollup(db):
    """ORDER BY sum(qty) / grouping() on a grouping-sets query (lifted as
    hidden helper columns across the union)."""
    r = db.sql("select cat, sum(qty) from gs group by rollup(cat) "
               "order by grouping(cat), sum(qty) desc")
    got = r.rows()
    assert len(got[0]) == 2                       # helpers stay hidden
    assert got[-1][0] is None                     # grand total last
    leaf = [q for c, q in got if c is not None]
    assert leaf == sorted(leaf, reverse=True)


def test_grouping_in_order_by_plain_group(db):
    """grouping() in ORDER BY of a PLAIN grouped select folds to 0 (PG)."""
    r = db.sql("select cat from gs group by cat order by grouping(cat), cat")
    assert [row[0] for row in r.rows()] == ["books", "food", "toys"]


def test_ds_q22_shape(db):
    """TPC-DS Q22 shape: joined fact + rollup over two dim attributes with
    avg, ordered; checked against a pandas oracle."""
    r = db.sql("select cat, brand, avg(qty) aq from gs "
               "where region < 3 group by rollup(cat, brand) "
               "order by aq desc, cat, brand limit 10")
    f = db.df[db.df.region < 3]
    frames = []
    for ks in (["cat", "brand"], ["cat"], []):
        if ks:
            g = f.groupby(ks, as_index=False).qty.mean()
        else:
            g = pd.DataFrame({"qty": [f.qty.mean()]})
        for missing in ("cat", "brand"):
            if missing not in ks:
                g[missing] = None
        frames.append(g[["cat", "brand", "qty"]])
    want = pd.concat(frames, ignore_index=True).sort_values(
        ["qty", "cat", "brand"], ascending=[False, True, True],
        na_position="first").head(10)
    got = r.rows()
    assert len(got) == 10
    for row, (_, w) in zip(got, want.iterrows()):
        np.testing.assert_allclose(row[2], w["qty"], rtol=1e-12)
