"""Statistics aggregate family (stddev/variance/covar/corr/regr_*).

Reference parity: pg_aggregate.h:246 float8 stat aggregates; semantics
checked against pandas/numpy oracles including PG's pair restriction for
two-argument forms (only rows with BOTH sides non-null contribute) and
var_samp(single row) -> NULL."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8, tmp_path_factory):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(7)
    n = 400
    g = rng.integers(0, 3, n).astype(np.int32)
    x = rng.normal(50, 12, n)
    y = 3.5 * x + rng.normal(0, 5, n)
    xnull = rng.random(n) < 0.15          # x NULL pattern
    ynull = rng.random(n) < 0.10          # y NULL pattern (overlaps)
    d.sql("create table st (g int, x double precision, y double precision, "
          "k bigint) distributed by (k)")
    d.load_table("st", {
        "g": g, "x": x, "y": y, "k": np.arange(n, dtype=np.int64)})
    d.sql("update st set x = null where k in (%s)" %
          ",".join(str(i) for i in np.flatnonzero(xnull)))
    d.sql("update st set y = null where k in (%s)" %
          ",".join(str(i) for i in np.flatnonzero(ynull)))
    d.df = pd.DataFrame({
        "g": g,
        "x": np.where(xnull, np.nan, x),
        "y": np.where(ynull, np.nan, y)})
    yield d
    d.close()


def _vals(r, name):
    for cid in r._order:
        if cid.startswith(name + "#") or cid == name:
            return np.asarray(r.cols[cid])
    raise KeyError(name)


def test_one_arg_family(db):
    r = db.sql("select g, stddev(x) sd, stddev_samp(x) sds, stddev_pop(x) sdp,"
               " variance(x) v, var_samp(x) vs, var_pop(x) vp"
               " from st group by g order by g")
    gg = db.df.groupby("g").x
    np.testing.assert_allclose(_vals(r, "sd"), gg.std().values, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "sds"), gg.std().values, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "sdp"), gg.std(ddof=0).values, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "v"), gg.var().values, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "vs"), gg.var().values, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "vp"), gg.var(ddof=0).values, rtol=1e-9)


def test_two_arg_pair_semantics(db):
    """covar/corr/regr must use only rows where BOTH x and y are non-null —
    the discriminating case vs naive per-column sums."""
    r = db.sql("select covar_pop(y, x) cp, covar_samp(y, x) cs, corr(y, x) c,"
               " regr_count(y, x) n, regr_slope(y, x) m,"
               " regr_intercept(y, x) b, regr_r2(y, x) r2,"
               " regr_avgx(y, x) ax, regr_avgy(y, x) ay from st")
    p = db.df.dropna(subset=["x", "y"])
    n = len(p)
    sx, sy = p.x.sum(), p.y.sum()
    sxx = (p.x * p.x).sum() - sx * sx / n
    syy = (p.y * p.y).sum() - sy * sy / n
    sxy = (p.x * p.y).sum() - sx * sy / n
    assert int(_vals(r, "n")[0]) == n
    np.testing.assert_allclose(_vals(r, "cp")[0], sxy / n, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "cs")[0], sxy / (n - 1), rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "c")[0], sxy / np.sqrt(sxx * syy),
                               rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "m")[0], sxy / sxx, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "b")[0],
                               sy / n - (sxy / sxx) * (sx / n), rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "r2")[0], sxy * sxy / (sxx * syy),
                               rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "ax")[0], sx / n, rtol=1e-9)
    np.testing.assert_allclose(_vals(r, "ay")[0], sy / n, rtol=1e-9)


def test_var_samp_single_row_null(db):
    """n=1 -> division by zero -> NULL (PG: var_samp of one row is NULL)."""
    r = db.sql("select var_samp(x) v, stddev(x) s from st where k = 1")
    for name in ("v", "s"):
        cid = next(c for c in r._order if c.startswith(name + "#"))
        valid = r.valids[cid]
        assert valid is not None and not bool(np.asarray(valid)[0])


def test_stat_aggs_in_having_and_order(db):
    r = db.sql("select g from st group by g having stddev(x) > 0"
               " order by variance(x) desc")
    gg = db.df.groupby("g").x.var().sort_values(ascending=False)
    assert list(_vals(r, "g")) == list(gg.index)


def test_stddev_distinct_rejected(db):
    with pytest.raises(ValueError):
        db.sql("select stddev(distinct x) from st")


def test_cast_dedup_no_collision(db):
    """sum(cast(x as bigint)) must NOT merge with the expansion's
    sum(cast(x as double precision)) — _ast_key keys on the cast target
    (regression: structural dedup ignored type_name)."""
    r = db.sql("select sum(cast(x as bigint)) s, variance(x) v from st"
               " where k < 50")
    p = db.df.iloc[:50].x.dropna()
    np.testing.assert_allclose(_vals(r, "v")[0], p.var(), rtol=1e-9)
    assert _vals(r, "s")[0] == np.floor(p).astype(np.int64).sum()


def test_order_by_agg_expression(db):
    """ORDER BY over an aggregate expression not in the output list."""
    r = db.sql("select g from st group by g order by sum(x)/count(x) desc")
    m = db.df.groupby("g").x.mean().sort_values(ascending=False)
    assert list(_vals(r, "g")) == list(m.index)
