"""Self-healing storage: corruption detection, mirror repair, quarantine,
FTS handoff, and the scrub pass — the storage-side twin of gang recovery
(AO block checksums + gprecoverseg recovery, cdbappendonlystorageformat.c).

These tests damage REAL committed block files (bit flips on disk and the
storage_corrupt_block fault point) and require either the exact original
rows back (repair) or a typed CorruptionError + quarantine + failover —
never silently wrong data."""

import glob
import json
import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.catalog.segments import SegmentRole, SegmentStatus
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage.blockfile import verify_column_file
from greengage_tpu.storage.corruption import CorruptionError
from greengage_tpu.storage.scrub import Scrubber
from greengage_tpu.storage.table_store import mirror_root

ROWS = [(i, i * 10) for i in range(64)]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "cluster"), numsegments=8,
                              mirrors=True)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.sql("insert into t values " + ",".join(f"({i},{v})" for i, v in ROWS))
    return d


def _victim(db, table="t"):
    """-> (content, rel) of the first committed data file."""
    snap = db.store.manifest.snapshot()
    for seg, rels in sorted(snap["tables"][table]["segfiles"].items(),
                            key=lambda kv: int(kv[0])):
        for rel in rels:
            if rel.endswith(".ggb"):
                return int(seg), rel
    raise AssertionError("no committed files")


def _flip_byte(path, offset=40):
    """Flip one payload byte of the first frame (header is 32 bytes)."""
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def _quarantined(db):
    qdir = os.path.join(db.path, ".quarantine")
    return sorted(os.listdir(qdir)) if os.path.isdir(qdir) else []


# ---------------------------------------------------------------------------
# read-path self-heal
# ---------------------------------------------------------------------------

def test_corrupt_primary_heals_from_mirror_transparently(db):
    content, rel = _victim(db)
    path = os.path.join(db.path, "data", "t", rel)
    _flip_byte(path)
    with pytest.raises(CorruptionError):
        verify_column_file(path)   # the damage is real
    before = counters.get("storage_repair")
    rows = sorted(db.sql("select k, v from t").rows())
    assert rows == ROWS                       # statement succeeds
    assert counters.get("storage_repair") == before + 1
    verify_column_file(path)                  # repaired file verifies clean
    mpath = os.path.join(mirror_root(db.path, content), "t", rel)
    with open(path, "rb") as a, open(mpath, "rb") as b:
        assert a.read() == b.read()           # byte-identical to the mirror
    assert db.store.storage_ok(content)       # no failover needed
    assert _quarantined(db) == []


def test_fault_injected_corruption_mid_query_repairs(db):
    """storage_corrupt_block flips a frame byte AT READ TIME (no disk
    damage); the read path must still verify, repair, and retry."""
    content, _rel = _victim(db)
    before = counters.get("storage_repair")
    faults.inject("storage_corrupt_block", "skip", segment=content,
                  occurrences=1)
    rows = sorted(db.sql("select k, v from t").rows())
    assert rows == ROWS
    assert counters.get("storage_repair") == before + 1


def test_occurrence_targeting_hits_a_later_read(db):
    """start_after arms the fault past the first N frame reads — the
    reference's start_occurrence — so mid-statement corruption (not just
    the first touched block) is exercised."""
    content, _rel = _victim(db)
    faults.inject("storage_corrupt_block", "skip", segment=content,
                  occurrences=1, start_after=1)
    assert sorted(db.sql("select k, v from t").rows()) == ROWS


def test_autorepair_off_quarantines_immediately(db):
    db.sql("set storage_autorepair = off")
    content, rel = _victim(db)
    _flip_byte(os.path.join(db.path, "data", "t", rel))
    with pytest.raises(CorruptionError):
        db.sql("select k, v from t")
    assert len(_quarantined(db)) == 2   # file + sidecar
    assert not db.store.storage_ok(content)


# ---------------------------------------------------------------------------
# no healthy copy -> quarantine + FTS failover
# ---------------------------------------------------------------------------

def test_repair_failure_quarantines_and_fts_promotes(db):
    content, rel = _victim(db)
    path = os.path.join(db.path, "data", "t", rel)
    _flip_byte(path)
    faults.inject("repair_copy", "error", segment=content, occurrences=1)
    before_q = counters.get("storage_quarantine")
    with pytest.raises(CorruptionError) as ei:
        db.sql("select k, v from t")
    assert ei.value.cause == "crc_mismatch"
    assert ei.value.content == content and ei.value.relpath == rel
    assert counters.get("storage_quarantine") == before_q + 1
    # quarantine: renamed file + JSON sidecar recording the cause
    q = _quarantined(db)
    assert any(f.endswith(".json") for f in q) and len(q) == 2
    with open(os.path.join(db.path, ".quarantine",
                           next(f for f in q if f.endswith(".json")))) as f:
        sidecar = json.load(f)
    assert sidecar["cause"] == "crc_mismatch"
    assert sidecar["table"] == "t" and sidecar["relpath"] == rel
    # storage_ok fails -> the FTS probe promotes the in-sync mirror
    assert not db.store.storage_ok(content)
    res = db.fts.probe_once()
    assert res[content] is False
    acting = db.catalog.segments.acting_primary(content)
    assert acting is not None and acting.preferred_role is SegmentRole.MIRROR
    assert sorted(db.sql("select k, v from t").rows()) == ROWS


def test_both_copies_corrupt_content_goes_down(db):
    content, rel = _victim(db)
    _flip_byte(os.path.join(db.path, "data", "t", rel))
    _flip_byte(os.path.join(mirror_root(db.path, content), "t", rel))
    with pytest.raises(CorruptionError):
        db.sql("select k, v from t")
    # BOTH copies quarantined (nothing may ever trust the mirror's rot)
    assert len(_quarantined(db)) == 4
    # first probe promotes the (marker-synced) mirror; its quarantined
    # tree then fails storage_ok, and the second probe takes it down too
    db.fts.probe_once()
    db.fts.probe_once()
    cfg = db.catalog.segments
    assert all(e.status is SegmentStatus.DOWN
               for e in cfg.entries if e.content == content)
    with pytest.raises(CorruptionError):
        db.sql("select k, v from t")


def test_commits_survive_unrelated_quarantine(db):
    """Post-commit replication must SKIP quarantined sources (one
    content's corruption cannot fail unrelated statements after their
    commit) — but must not stamp the incomplete tree as synced."""
    content, rel = _victim(db)
    _flip_byte(os.path.join(db.path, "data", "t", rel))
    _flip_byte(os.path.join(mirror_root(db.path, content), "t", rel))
    with pytest.raises(CorruptionError):
        db.sql("select k, v from t")   # both copies quarantined
    db.sql("create table u (a int) distributed by (a)")
    db.sql("insert into u values (1), (2), (3)")   # must not raise
    assert db.sql("select count(*) from u").rows() == [(3,)]
    # t's standby could not reach the new version: barred from promotion
    assert db.catalog.segments.entry(
        content, SegmentRole.MIRROR).mode_synced is False


def test_stale_standby_never_used_for_repair(db):
    db.sql("set mirror_sync = off")
    db.sql("insert into t values (500, 5)")   # mirrors now behind
    content, rel = _victim(db)
    _flip_byte(os.path.join(db.path, "data", "t", rel))
    with pytest.raises(CorruptionError):
        db.sql("select k from t")
    assert len(_quarantined(db)) == 2   # quarantined, not healed from stale


# ---------------------------------------------------------------------------
# scrub
# ---------------------------------------------------------------------------

def test_scrub_repairs_and_reports(db):
    snap = db.store.manifest.snapshot()
    total = sum(len(rels) for rels in
                snap["tables"]["t"]["segfiles"].values())
    # corrupt two files on different contents
    victims = []
    for seg, rels in sorted(snap["tables"]["t"]["segfiles"].items(),
                            key=lambda kv: int(kv[0])):
        if rels:
            victims.append((int(seg), rels[0]))
        if len(victims) == 2:
            break
    for _c, rel in victims:
        _flip_byte(os.path.join(db.path, "data", "t", rel))
    rep = Scrubber(db.store).scrub()
    assert rep["files_scanned"] == total
    assert rep["files_repaired"] == 2
    assert rep["files_verified"] == total - 2
    assert rep["files_quarantined"] == 0
    assert rep["bytes_scanned"] > 0
    assert {p["status"] for p in rep["problems"]} == {"repaired"}
    # second pass: everything clean
    rep2 = Scrubber(db.store).scrub()
    assert rep2["files_verified"] == total and rep2["files_repaired"] == 0
    assert sorted(db.sql("select k, v from t").rows()) == ROWS


def test_scrub_restores_quarantined_file(db):
    """A quarantined file (repair_copy fault made the read-path heal fail)
    is restored by the next scrub — the gprecoverseg role."""
    content, rel = _victim(db)
    path = os.path.join(db.path, "data", "t", rel)
    _flip_byte(path)
    faults.inject("repair_copy", "error", segment=content, occurrences=1)
    with pytest.raises(CorruptionError):
        db.sql("select k, v from t")
    assert not db.store.storage_ok(content)
    rep = Scrubber(db.store).scrub()
    assert rep["files_repaired"] == 1
    assert db.store.storage_ok(content)
    verify_column_file(path)
    assert sorted(db.sql("select k, v from t").rows()) == ROWS


def test_scrub_mirrors_refreshes_standby_rot(db):
    content, rel = _victim(db)
    mpath = os.path.join(mirror_root(db.path, content), "t", rel)
    _flip_byte(mpath)
    rep = Scrubber(db.store).scrub(mirrors=True)
    assert rep["standby_repaired"] == 1
    assert rep["files_repaired"] == 0   # acting tree was healthy
    verify_column_file(mpath)


def test_scrub_quarantines_without_mirror(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "nomirror"), numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.sql("insert into t values " + ",".join(f"({i},{v})" for i, v in ROWS))
    snap = d.store.manifest.snapshot()
    content, rel = next((int(s), rels[0]) for s, rels in
                        snap["tables"]["t"]["segfiles"].items() if rels)
    _flip_byte(os.path.join(d.path, "data", "t", rel))
    rep = Scrubber(d.store).scrub()
    assert rep["files_quarantined"] == 1 and rep["files_repaired"] == 0
    assert not d.store.storage_ok(content)
    assert len(_quarantined(d)) == 2


def test_scrub_table_filter_expands_partitions(db):
    db.sql("create table pt (k int, v int) distributed by (k) "
           "partition by range (v) (partition lo start (0) end (500), "
           "partition hi start (500) end (1000))")
    db.sql("insert into pt values " + ",".join(
        f"({i},{i * 10})" for i in range(64)))
    rep = Scrubber(db.store).scrub(tables=["pt"])
    assert rep["files_scanned"] > 0    # logical name found the children
    with pytest.raises(ValueError, match="unknown table"):
        Scrubber(db.store).scrub(tables=["nope"])


def test_scrub_skip_fault_records_coverage_hole(db):
    content, _rel = _victim(db)
    faults.inject("scrub_file", "skip", segment=content, occurrences=1)
    rep = Scrubber(db.store).scrub()
    assert any(p["status"] == "skipped" for p in rep["problems"])


def test_corruption_discovered_mid_scrub_via_fault(db):
    """storage_corrupt_block during the scrub's own verification reads:
    the scrubber sees a checksum failure, but the disk file is healthy, so
    the repair path re-verifies and the report records a repair."""
    content, _rel = _victim(db)
    faults.inject("storage_corrupt_block", "skip", segment=content,
                  occurrences=1)
    rep = Scrubber(db.store).scrub()
    assert rep["files_repaired"] == 1
    assert rep["files_quarantined"] == 0


# ---------------------------------------------------------------------------
# raw TEXT columns heal too (offsets/bytes blobs ride the same path)
# ---------------------------------------------------------------------------

def test_raw_text_blob_corruption_heals(db):
    from greengage_tpu.catalog.schema import Column

    db.sql("create table rt (k int, s text) distributed by (k)")
    schema = db.catalog.get("rt")
    col = schema.column("s")   # force raw (auto needs >=4096 rows)
    schema.columns[[c.name for c in schema.columns].index("s")] = \
        Column("s", col.type, col.nullable, "raw")
    db.catalog._save()
    vals = [f"payload-{i}-{'x' * (i % 13)}" for i in range(64)]
    db.sql("insert into rt values " + ",".join(
        f"({i},'{s}')" for i, s in enumerate(vals)))
    snap = db.store.manifest.snapshot()
    content, rel = next(
        (int(s), next(r for r in rels if r.endswith(".rawbytes.ggb")))
        for s, rels in snap["tables"]["rt"]["segfiles"].items()
        if any(r.endswith(".rawbytes.ggb") for r in rels))
    _flip_byte(os.path.join(db.path, "data", "rt", rel))
    before = counters.get("storage_repair")
    got = sorted(r[1] for r in db.sql("select k, s from rt").rows())
    assert got == sorted(vals)
    assert counters.get("storage_repair") == before + 1


def test_delmask_corruption_heals(db):
    db.sql("delete from t where k < 8")
    want = sorted((i, v) for i, v in ROWS if i >= 8)
    snap = db.store.manifest.snapshot()
    dm = snap["tables"]["t"].get("delmask", {})
    assert dm, "expected a deletion bitmap"
    seg, rel = next(iter(sorted(dm.items(), key=lambda kv: int(kv[0]))))
    _flip_byte(os.path.join(db.path, "data", "t", rel))
    before = counters.get("storage_repair")
    assert sorted(db.sql("select k, v from t").rows()) == want
    assert counters.get("storage_repair") == before + 1
