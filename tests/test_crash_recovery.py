"""Process-level crash recovery: kill -9 mid-2PC — VERDICT r3 #9, the
crash_recovery_dtm.sql analog
(/root/reference/src/test/isolation2/sql/crash_recovery_dtm.sql:1).

A real subprocess is SIGKILLed while parked on a fault point inside
Transaction.commit; the parent then asserts the distributed outcome is
EXACTLY one of commit/abort (never half), that the in-doubt per-table
delta claims block concurrent same-table writers until recovery, and that
recovery releases them. A second family kills the process mid-FOLD (the
delta-manifest checkpoint) and asserts no committed row is ever lost."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.storage.manifest import Manifest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
from greengage_tpu.runtime.faultinject import faults
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
# connect ran recover() (which may fold/compact, moving the root version):
# signal the parent that every predicate baseline is safe to sample NOW
open(sys.argv[1] + ".ready", "w").close()
faults.inject(sys.argv[3], "sleep", sleep_s=120)
db.sql("begin")
db.sql("insert into t values (100000, 7)")
db.sql("delete from u where k < 5")
print("COMMITTING", flush=True)
db.sql("commit")
print("COMMITTED", flush=True)
"""


def _setup(path):
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(100), "v": np.arange(100)})
    d.sql("create table u (k int, v int) distributed by (k)")
    d.load_table("u", {"k": np.arange(50), "v": np.arange(50)})
    d.close()
    return d


def _run_child_until(path, fault, wait_for, child=CHILD,
                     extra_env=None):
    """Spawn the committing child, wait for ``wait_for`` (a filesystem
    predicate), then SIGKILL it — the genuine kill -9 the thread-level
    concurrency tests could not deliver."""
    env = dict(os.environ)
    env["GGTPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-c", child, path, REPO, fault],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + 120
    try:
        # phase 1: the child's connect-time recover() may fold/compact
        # (both move the root version) — hold every predicate until the
        # child signals that startup is behind it, or the baselines race
        while time.monotonic() < deadline:
            if os.path.exists(path + ".ready"):
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"child exited early:\n{proc.stdout.read()}")
            time.sleep(0.05)
        else:
            raise AssertionError("child never finished connecting")
        while time.monotonic() < deadline:
            if wait_for():
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"child exited early:\n{proc.stdout.read()}")
            time.sleep(0.05)
        else:
            raise AssertionError("child never reached the fault point")
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL


def _committed_delta_keys(path):
    """(table, seq) pairs referenced by committed commit-log lines."""
    m = Manifest(path)
    root = m._root()
    lines, _end = m._log_lines(int(root.get("log_pos", 0)))
    out = set()
    for line in lines:
        for t, s in (line.get("t") or {}).items():
            out.add((t, int(s)))
    return out


def _staged_uncommitted_deltas(path):
    """Delta claims staged by an in-flight 2PC: files under deltas/ whose
    (table, seq) no committed log line references — the in-doubt state a
    kill -9 between prepare_delta and commit_delta leaves behind."""
    ddir = os.path.join(path, "deltas")
    if not os.path.isdir(ddir):
        return []
    committed = _committed_delta_keys(path)
    out = []
    for fn in os.listdir(ddir):
        if not fn.endswith(".delta"):
            continue
        stem, seq_s = fn[:-len(".delta")].rsplit(".", 1)
        if (stem, int(seq_s)) not in committed:
            out.append(fn)
    return out


def _staged_above_head(path):
    """Prepared-but-uncommitted ROOT stages (fold / structural commits)."""
    m = Manifest(path)
    head = m.snapshot().get("version", 0)
    return [fn for fn in os.listdir(path)
            if fn.startswith("manifest.") and fn.endswith(".prepared")
            and int(fn.split(".")[1]) > head]


def test_kill9_between_prepare_and_commit_rolls_back(tmp_path):
    path = str(tmp_path / "c")
    _setup(path)
    # wait for BOTH tables' claims: the predicate firing on the first
    # file would let the SIGKILL land mid-prepare_delta (t staged, u not
    # yet) instead of at the parked fault point
    _run_child_until(
        path, "dtx_after_prepare",
        lambda: {fn.split(".")[0]
                 for fn in _staged_uncommitted_deltas(path)} >= {"t", "u"})
    # in-doubt: the per-table delta claims exist without a commit record...
    staged = _staged_uncommitted_deltas(path)
    assert {fn.split(".")[0] for fn in staged} == {"t", "u"}
    m = Manifest(path)
    head_before = m.snapshot().get("version", 0)
    # ... and a concurrent writer to the SAME table cannot steal the
    # claimed sequence (the per-table CAS; cross-table writers — here a
    # fresh table name — are NOT blocked by the in-doubt claims)
    with pytest.raises(RuntimeError, match="write-write conflict"):
        tx = m.begin()
        tx["tables"]["t"] = dict(tx["tables"]["t"])
        m.prepare_delta(tx, ["t"])
    # recovery (runs inside connect) resolves the in-doubt tx: ABORT
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert not _staged_uncommitted_deltas(path)      # claims released
    assert d.store.manifest.snapshot()["version"] >= head_before
    # outcome is exactly-abort: NEITHER half of the transaction applied
    assert d.sql("select count(*) from t").rows()[0][0] == 100
    assert d.sql("select count(*) from u").rows()[0][0] == 50
    # and the released claims admit new writers
    d.sql("insert into t values (555, 555)")
    assert d.sql("select count(*) from t").rows()[0][0] == 101


def test_kill9_after_commit_preserves_commit(tmp_path):
    path = str(tmp_path / "c")
    _setup(path)
    # the commit evidence is the durable commit-LOG line (the delta path's
    # commit record): the _setup loads commit via intent MERGE lines (no
    # delta claim), so the 2PC's line (t.1 — the first delta claim the
    # cluster ever makes for t) appearing is baseline-free ground truth —
    # a lazy baseline would race a fast child that commits before the
    # parent's first poll
    _run_child_until(path, "dtx_after_commit",
                     lambda: ("t", 1) in _committed_delta_keys(path))
    # the commit-log line was durable before the kill: recovery must KEEP
    # the commit (and fold it into the root)
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert d.sql("select count(*) from t").rows()[0][0] == 101   # insert in
    assert d.sql("select count(*) from u").rows()[0][0] == 45    # delete in
    assert d.sql("select v from t where k = 100000").rows() == [(7,)]
    # the killed process never ran its deferred GC: orphan sweep is the
    # backstop and must not touch live files
    d.store.sweep_orphans(grace_s=0)
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert d.sql("select count(*) from u").rows()[0][0] == 45


def test_kill9_with_concurrent_writer_exactly_one_outcome(tmp_path):
    """The crash_recovery_dtm shape: writer A dies mid-2PC while writer B
    (another process, i.e. this one) keeps writing. B must never see half
    of A, and B's own commits must survive A's recovery."""
    path = str(tmp_path / "c")
    _setup(path)
    _run_child_until(path, "dtx_after_prepare",
                     lambda: bool(_staged_uncommitted_deltas(path)))
    d = greengage_tpu.connect(path=path, numsegments=4)   # recovers A
    d.sql("insert into u values (777, 1)")                # writer B
    assert d.sql("select count(*) from t").rows()[0][0] == 100   # A aborted
    assert d.sql("select count(*) from u").rows()[0][0] == 51
    # a second recovery pass is idempotent
    assert d.store.manifest.recover() == []


# ---------------------------------------------------------------------------
# kill -9 during a delta FOLD (the checkpoint): the root replace is atomic
# and replayed deltas are sequence-guarded, so committed rows survive a
# crash in either fold window (staged-not-committed / committed-not-GC'd)
# ---------------------------------------------------------------------------

FOLD_CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
from greengage_tpu.runtime.faultinject import faults
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
open(sys.argv[1] + ".ready", "w").close()         # startup recovery done
db.sql("set manifest_delta_fold_threshold = 1")   # fold on every commit
# start_after targets the fold window: 0 = parked after the fold root is
# STAGED (before the atomic replace), 1 = parked after the replace
# (before the folded delta files are GC'd)
faults.inject("delta_fold", "sleep", sleep_s=120,
              start_after=int(os.environ.get("GGTPU_FOLD_WINDOW", "0")))
db.sql("insert into t values (100000, 7)")
print("FOLDED", flush=True)
"""


@pytest.mark.parametrize("window", [0, 1])
def test_kill9_mid_fold_loses_no_committed_rows(tmp_path, window):
    path = str(tmp_path / f"c{window}")
    _setup(path)

    if window == 0:
        # parked between staging the fold root and the atomic replace:
        # the staged claim is visible above the committed head
        def parked():
            return bool(_staged_above_head(path))
    else:
        # parked after the replace: the new root folded the INSERT's
        # merge line, so its recorded INTENT sequence for t reached 2
        # (iseq 1 = the _setup load's merge, folded at the child's
        # startup compaction; iseq 2 = the insert — autocommit appends
        # commit via write intents, not delta claims). Baseline-free on
        # purpose — a lazy baseline races a fast child, which can fold
        # before the parent's first poll.
        def parked():
            seqs = Manifest(path)._root().get("intent_seqs", {})
            return int(seqs.get("t", 0)) >= 2

    _run_child_until(path, "delta_fold", parked, child=FOLD_CHILD,
                     extra_env={"GGTPU_FOLD_WINDOW": str(window)})
    # the INSERT's commit line was durable before the fold began: whatever
    # the fold got to, recovery must surface the committed row
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert d.sql("select v from t where k = 100000").rows() == [(7,)]
    assert not _staged_above_head(path)          # fold claim resolved
    assert not _staged_uncommitted_deltas(path)
    # recovery compacted: the store keeps serving writes
    d.sql("insert into t values (100001, 8)")
    assert d.sql("select count(*) from t").rows()[0][0] == 102
    assert d.store.manifest.recover() == []


# ---------------------------------------------------------------------------
# kill -9 on the WRITE-INTENT path (docs/ROBUSTNESS.md "Write-intent
# commit & streaming ingest"): the intent_resolve fault point fires TWICE
# per commit, so start_after pins either crash window — before the merge
# line (in-doubt intent, rolled back like a stale delta claim) and after
# it is durable but before the marker unlink (the commit SURVIVES)
# ---------------------------------------------------------------------------

INTENT_CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
from greengage_tpu.runtime.faultinject import faults
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
open(sys.argv[1] + ".ready", "w").close()         # startup recovery done
# window 0 = parked after the intent is staged, merge line NOT appended;
# window 1 = parked after the merge line is durable, marker NOT unlinked
faults.inject(sys.argv[3], "sleep", sleep_s=120,
              start_after=int(os.environ.get("GGTPU_INTENT_WINDOW", "0")))
db.sql("insert into t values (100000, 7)")
print("RESOLVED", flush=True)
"""


def _intent_files(path):
    idir = os.path.join(path, "intents")
    if not os.path.isdir(idir):
        return []
    return [fn for fn in os.listdir(idir) if fn.endswith(".intent")]


def _merge_lines_for(path, table):
    """Committed "w" merge lines for ``table`` past the root's log_pos."""
    m = Manifest(path)
    root = m._root()
    lines, _end = m._log_lines(int(root.get("log_pos", 0)))
    return [line["w"][table] for line in lines
            if table in (line.get("w") or {})]


def _merged_rows_for(path, table):
    return sum(int(n) for recs in _merge_lines_for(path, table)
               for _seg, _rels, n in recs)


@pytest.mark.parametrize("window", [0, 1])
def test_kill9_mid_intent_resolve_both_windows(tmp_path, window):
    path = str(tmp_path / f"c{window}")
    _setup(path)

    if window == 0:
        # parked between stage and resolve: the durable intent exists,
        # no merge line does — the in-doubt state recovery must roll back
        def parked():
            return bool(_intent_files(path))
    else:
        # parked after the fsynced merge line (the commit point), before
        # the marker unlink: the 1-row merge for t is ground truth (the
        # child's startup compaction folded the _setup load's 100 rows)
        def parked():
            return _merged_rows_for(path, "t") >= 1

    _run_child_until(path, "intent_resolve", parked, child=INTENT_CHILD,
                     extra_env={"GGTPU_INTENT_WINDOW": str(window)})
    assert _intent_files(path)           # both windows leave the marker
    if window == 0:
        assert _merged_rows_for(path, "t") == 0
    from greengage_tpu.runtime.logger import counters
    base = counters.snapshot()
    d = greengage_tpu.connect(path=path, numsegments=4)   # runs recover()
    # recovery swept the marker with the no-grace discipline either way:
    # window 0 rolls the writer back, window 1 clears committed garbage
    assert not _intent_files(path)
    assert counters.since(base).get("manifest_intent_swept_total", 0) >= 1
    expect = 100 if window == 0 else 101
    assert d.sql("select count(*) from t").rows()[0][0] == expect
    if window == 1:
        assert d.sql("select v from t where k = 100000").rows() == [(7,)]
    # the dead writer's segfiles: orphans (window 0) are reclaimed, live
    # files (window 1) are untouchable — either way counts are stable
    d.store.sweep_orphans(grace_s=0)
    assert d.sql("select count(*) from t").rows()[0][0] == expect
    # the manifest stays foldable past the crash
    d.sql("set manifest_delta_fold_threshold = 1")
    d.sql("insert into t values (100001, 8)")
    assert d.sql("select count(*) from t").rows()[0][0] == expect + 1
    assert d.store.manifest.recover() == []


# ---------------------------------------------------------------------------
# kill -9 mid-STREAM (the ingest_flush fault point parks a micro-batch
# after the client ack, before its intent commit): nothing past the last
# committed watermark survives, resume replays exactly the tail
# ---------------------------------------------------------------------------

STREAM_CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
from greengage_tpu.runtime.faultinject import faults
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
open(sys.argv[1] + ".ready", "w").close()
db.sql("set ingest_batch_rows = 1")      # every batch commits inline
db.ingest.stream_begin("t", "s1")
db.ingest.stream_rows("s1", {"k": [200000], "v": [1]}, 1)   # committed
faults.inject(sys.argv[3], "sleep", sleep_s=120)
open(sys.argv[1] + ".batch2", "w").close()
# batch 2 is ACKED into the buffer, then parks before its intent commit
db.ingest.stream_rows("s1", {"k": [200001], "v": [2]}, 2)
print("NEVER", flush=True)
"""


def _stream_mark(path, table, sid):
    return int(Manifest(path).snapshot()["tables"]
               .get(table, {}).get("streams", {}).get(sid, 0))


def test_kill9_mid_stream_resumes_from_watermark(tmp_path):
    path = str(tmp_path / "c")
    _setup(path)
    _run_child_until(
        path, "ingest_flush",
        lambda: os.path.exists(path + ".batch2")
        and _stream_mark(path, "t", "s1") >= 1,
        child=STREAM_CHILD)
    # batch 1's watermark rode its merge line; batch 2 died in the buffer
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert d.sql("select v from t where k = 200000").rows() == [(1,)]
    assert d.sql("select count(*) from t where k = 200001").rows() \
        == [(0,)]
    # the client re-begins with the SAME stream id: the durable watermark
    # names exactly what to re-send — and a replay of batch 1 dedups
    out = d.ingest.stream_begin("t", "s1")
    assert out["resume_seq"] == 1
    dup = d.ingest.stream_rows("s1", {"k": [200000], "v": [1]}, 1)
    assert dup["duplicate"] is True
    d.ingest.stream_rows("s1", {"k": [200001], "v": [2]}, 2)
    d.ingest.stream_end("s1")
    assert d.sql("select count(*) from t").rows()[0][0] == 102
    assert d.sql("select count(*) from t where k = 200001").rows() \
        == [(1,)]
    assert d.store.manifest.recover() == []
