"""Process-level crash recovery: kill -9 mid-2PC — VERDICT r3 #9, the
crash_recovery_dtm.sql analog
(/root/reference/src/test/isolation2/sql/crash_recovery_dtm.sql:1).

A real subprocess is SIGKILLed while parked on a fault point inside
Transaction.commit; the parent then asserts the distributed outcome is
EXACTLY one of commit/abort (never half), that the in-doubt claim blocks
concurrent writers until recovery, and that recovery releases it."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.storage.manifest import Manifest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
from greengage_tpu.runtime.faultinject import faults
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
faults.inject(sys.argv[3], "sleep", sleep_s=120)
db.sql("begin")
db.sql("insert into t values (100000, 7)")
db.sql("delete from u where k < 5")
print("COMMITTING", flush=True)
db.sql("commit")
print("COMMITTED", flush=True)
"""


def _setup(path):
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(100), "v": np.arange(100)})
    d.sql("create table u (k int, v int) distributed by (k)")
    d.load_table("u", {"k": np.arange(50), "v": np.arange(50)})
    d.close()


def _run_child_until(path, fault, wait_for):
    """Spawn the committing child, wait for ``wait_for`` (a filesystem
    predicate), then SIGKILL it — the genuine kill -9 the thread-level
    concurrency tests could not deliver."""
    env = dict(os.environ)
    env["GGTPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD, path, REPO, fault],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + 120
    try:
        while time.monotonic() < deadline:
            if wait_for():
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"child exited early:\n{proc.stdout.read()}")
            time.sleep(0.05)
        else:
            raise AssertionError("child never reached the fault point")
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL


def _staged_above_head(path):
    m = Manifest(path)
    head = m.snapshot().get("version", 0)
    return [fn for fn in os.listdir(path)
            if fn.startswith("manifest.") and fn.endswith(".prepared")
            and int(fn.split(".")[1]) > head]


def test_kill9_between_prepare_and_commit_rolls_back(tmp_path):
    path = str(tmp_path / "c")
    _setup(path)
    _run_child_until(path, "dtx_after_prepare",
                     lambda: bool(_staged_above_head(path)))
    # in-doubt: the prepared claim exists above the committed head ...
    assert _staged_above_head(path)
    m = Manifest(path)
    head_before = m.snapshot().get("version", 0)
    # ... and a concurrent writer cannot steal the claimed version
    with pytest.raises(RuntimeError, match="write-write conflict"):
        tx = m.begin()
        m.prepare(tx)
    # recovery (runs inside connect) resolves the in-doubt tx: ABORT
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert not _staged_above_head(path)          # claim released
    assert d.store.manifest.snapshot()["version"] == head_before
    # outcome is exactly-abort: NEITHER half of the transaction applied
    assert d.sql("select count(*) from t").rows()[0][0] == 100
    assert d.sql("select count(*) from u").rows()[0][0] == 50
    # and the released claim admits new writers
    d.sql("insert into t values (555, 555)")
    assert d.sql("select count(*) from t").rows()[0][0] == 101


def test_kill9_after_commit_preserves_commit(tmp_path):
    path = str(tmp_path / "c")
    _setup(path)
    m = Manifest(path)
    v0 = m.snapshot().get("version", 0)
    _run_child_until(path, "dtx_after_commit",
                     lambda: m.snapshot().get("version", 0) > v0)
    # the swap happened before the kill: recovery must KEEP the commit
    d = greengage_tpu.connect(path=path, numsegments=4)
    assert d.sql("select count(*) from t").rows()[0][0] == 101   # insert in
    assert d.sql("select count(*) from u").rows()[0][0] == 45    # delete in
    assert d.sql("select v from t where k = 100000").rows() == [(7,)]
    # the killed process never ran its deferred GC: orphan sweep is the
    # backstop and must not touch live files
    d.store.sweep_orphans(grace_s=0)
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert d.sql("select count(*) from u").rows()[0][0] == 45


def test_kill9_with_concurrent_writer_exactly_one_outcome(tmp_path):
    """The crash_recovery_dtm shape: writer A dies mid-2PC while writer B
    (another process, i.e. this one) keeps writing. B must never see half
    of A, and B's own commits must survive A's recovery."""
    path = str(tmp_path / "c")
    _setup(path)
    _run_child_until(path, "dtx_after_prepare",
                     lambda: bool(_staged_above_head(path)))
    d = greengage_tpu.connect(path=path, numsegments=4)   # recovers A
    d.sql("insert into u values (777, 1)")                # writer B
    assert d.sql("select count(*) from t").rows()[0][0] == 100   # A aborted
    assert d.sql("select count(*) from u").rows()[0][0] == 51
    # a second recovery pass is idempotent
    assert d.store.manifest.recover() == []
