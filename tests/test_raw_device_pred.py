"""Device-side raw TEXT predicates — VERDICT r3 #7.

Raw columns stage a packed 32-byte prefix (int64 lanes, big-endian) plus
exact length; equality, wildcard-free LIKE, LIKE-'prefix%', and IN lower
to integer compares ON DEVICE (one mesh pass), with the O(heap) host
path kept only for general patterns, chains, and >32-byte literals.
Reference role: vectorized texteq/text_like fast paths (varlena.c)."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner.logical import Scan
from greengage_tpu.sql.parser import parse


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table m (k int, s text, v int) distributed by (k)")
    n = 9000
    rng = np.random.default_rng(17)
    strs = np.array(
        [f"msg-{i:05d} payload {rng.integers(10 ** 9)}" for i in range(n)],
        dtype=object)
    strs[7] = "special exact match"
    strs[8] = "special exact match but longer than the thirty-two byte cap"
    strs[11] = "spe"
    strs[4242] = "ünïcode-прefix テスト"
    d.load_table("m", {"k": np.arange(n), "s": strs,
                       "v": np.arange(n) % 7})
    assert d.catalog.get("m").column("s").encoding == "raw"
    valid = np.ones(500, bool)
    valid[::5] = False
    d.sql("create table mn (k int, s text) distributed by (k)")
    d.load_table("mn", {"k": np.arange(500),
                        "s": np.array([f"x{i}" for i in range(500)],
                                      dtype=object)},
                 valids={"s": valid})
    return d


def _scan_cols(db, sql):
    planned, _, _ = db._plan(parse(sql)[0])
    names = []
    stack = [planned]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan):
            names.extend(c.name for c in p.cols)
        stack.extend(p.children)
    return names


def test_equality_runs_on_device(db):
    q = "select k from m where s = 'special exact match'"
    cols = _scan_cols(db, q)
    assert any(c.startswith("@rp:") for c in cols), cols
    assert any(c.startswith("@rl:") for c in cols), cols
    assert not any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows() == [(7,)]
    assert db.sql("select count(*) from m where s <> 'special exact match'"
                  ).rows()[0][0] == 8999


def test_long_literal_falls_back_to_host(db):
    q = ("select k from m where s = "
         "'special exact match but longer than the thirty-two byte cap'")
    cols = _scan_cols(db, q)
    assert any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows() == [(8,)]


def test_prefix_like_on_device(db):
    q = "select k from m where s like 'special exact%' order by k"
    cols = _scan_cols(db, q)
    assert any(c.startswith("@rp:") for c in cols), cols
    assert not any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows() == [(7,), (8,)]
    # 'spe%' catches the 3-byte row too (length >= prefix via @rl)
    assert db.sql("select count(*) from m where s like 'spe%'"
                  ).rows()[0][0] == 3


def test_wildcard_free_like_is_equality(db):
    q = "select k from m where s like 'spe'"
    cols = _scan_cols(db, q)
    assert not any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows() == [(11,)]


def test_general_pattern_now_on_device(db):
    # '%contains%' moved on-device via the wide byte window (r5); only
    # _-wildcards and escapes still take the host path
    q = "select count(*) from m where s like '%payload%'"
    cols = _scan_cols(db, q)
    assert any(c.startswith("@rw:") for c in cols), cols
    assert not any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows()[0][0] == 8996


def test_underscore_pattern_still_host(db):
    q = "select count(*) from m where s like '%payl_ad%'"
    cols = _scan_cols(db, q)
    assert any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows()[0][0] == 8996


def test_in_list_on_device(db):
    q = "select k from m where s in ('spe', 'special exact match') order by k"
    cols = _scan_cols(db, q)
    assert not any(c.startswith("@hp:") for c in cols), cols
    assert db.sql(q).rows() == [(7,), (11,)]


def test_unicode_equality_and_prefix(db):
    assert db.sql("select k from m where s = 'ünïcode-прefix テスト'"
                  ).rows() == [(4242,)]
    assert db.sql("select k from m where s like 'ünïcode-пр%'"
                  ).rows() == [(4242,)]


def test_nulls_never_match(db):
    n_valid = 500 - len(range(0, 500, 5))
    assert db.sql("select count(*) from mn where s like 'x%'"
                  ).rows()[0][0] == n_valid
    assert db.sql("select count(*) from mn where s = 'x5'"
                  ).rows()[0][0] == 0       # row 5 is NULL
    assert db.sql("select count(*) from mn where s = 'x6'"
                  ).rows()[0][0] == 1


def test_device_pred_respects_delete_bitmap(db):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table dm (k int, s text) distributed by (k)")
    strs = np.array([f"row-{i:06d}-{'pad' * (i % 5)}" for i in range(5000)],
                    dtype=object)
    d.load_table("dm", {"k": np.arange(5000), "s": strs})
    assert d.catalog.get("dm").column("s").encoding == "raw"
    assert d.sql("select count(*) from dm where s like 'row-0000%'"
                 ).rows()[0][0] == 100
    d.sql("delete from dm where k < 50")
    assert d.sql("select count(*) from dm where s like 'row-0000%'"
                 ).rows()[0][0] == 50
    assert d.sql("select k from dm where s = 'row-000050-'"
                 ).rows() == [(50,)]
