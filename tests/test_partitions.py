"""Partitioned tables + pruning (reference parity: cdbpartition.c range/
list partitioning, nodePartitionSelector.c pruning roles). Each partition
is its own child storage table; pruning is a plan-time staging decision
that also shrinks the compiled program's scan capacity."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("""
        create table sales (id int, day int, amount bigint, region text)
        distributed by (id)
        partition by range (day) (
            partition q1 start (0) end (90),
            partition q2 start (90) end (180),
            partition q3 start (180) end (270),
            default partition tail
        )""")
    d.sql("insert into sales values " + ",".join(
        f"({i}, {i % 400}, {i * 3}, 'r{i % 3}')" for i in range(400)))
    return d


def test_rows_land_in_partitions(db):
    # child storage tables hold disjoint day ranges
    counts = {p.name: sum(db.store.segment_rowcounts(f"sales#{p.name}"))
              for p in db.catalog.get("sales").partitions}
    assert counts["q1"] == 90 and counts["q2"] == 90 and counts["q3"] == 90
    assert counts["tail"] == 130          # days 270..399
    assert sum(counts.values()) == 400


def test_select_spans_partitions(db):
    r = db.sql("select count(*), sum(amount) from sales")
    assert r.rows() == [(400, sum(i * 3 for i in range(400)))]


def test_static_pruning_matches_oracle_and_prunes(db):
    r = db.sql("select count(*) from sales where day < 90")
    assert r.rows() == [(90,)]
    # EXPLAIN shows the pruned partition set (default partition never
    # statically pruned)
    txt = db.sql("explain select count(*) from sales where day < 90")
    assert "partitions: 2/4" in str(txt)
    r = db.sql("select count(*) from sales where day >= 90 and day < 180")
    assert r.rows() == [(90,)]
    txt = db.sql(
        "explain select count(*) from sales where day >= 90 and day < 180")
    assert "partitions: 2/4" in str(txt)
    # point query
    r = db.sql("select amount from sales where day = 5 order by amount")
    assert [a for (a,) in r.rows()] == [15]


def test_group_by_across_partitions(db):
    r = db.sql("select region, count(*) from sales group by region "
               "order by region")
    assert r.rows() == [("r0", 134), ("r1", 133), ("r2", 133)]


def test_join_partitioned_fact(db):
    db.sql("create table dim (region text, label int) "
           "distributed replicated")
    db.sql("insert into dim values ('r0', 10), ('r1', 11), ('r2', 12)")
    r = db.sql("select label, count(*) from sales join dim "
               "on sales.region = dim.region group by label order by label")
    assert r.rows() == [(10, 134), (11, 133), (12, 133)]


def test_dml_routes_and_moves_rows(db):
    db.sql("delete from sales where day >= 270")
    assert db.sql("select count(*) from sales").rows() == [(270,)]
    assert sum(db.store.segment_rowcounts("sales#tail")) == 0
    # UPDATE that moves a row across partitions (day 10 -> 100)
    db.sql("update sales set day = 100 where id = 10")
    assert sum(db.store.segment_rowcounts("sales#q1")) == 89
    assert sum(db.store.segment_rowcounts("sales#q2")) == 91
    r = db.sql("select day from sales where id = 10")
    assert r.rows() == [(100,)]


def test_no_partition_accepts_errors_without_default(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c2"), numsegments=2)
    d.sql("create table t (k int, v int) distributed by (k) "
          "partition by range (v) (partition a start (0) end (10))")
    with pytest.raises(SqlError, match="no partition"):
        d.sql("insert into t values (1, 99)")
    d.sql("insert into t values (1, 5)")
    assert d.sql("select count(*) from t").rows() == [(1,)]


def test_list_partitions(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c3"), numsegments=2)
    d.sql("""create table ev (k int, typ int) distributed by (k)
             partition by list (typ) (
               partition small values (1, 2),
               partition big values (3),
               default partition other)""")
    d.sql("insert into ev values (1,1),(2,2),(3,3),(4,7)")
    assert sum(d.store.segment_rowcounts("ev#small")) == 2
    assert sum(d.store.segment_rowcounts("ev#big")) == 1
    assert sum(d.store.segment_rowcounts("ev#other")) == 1
    assert d.sql("select count(*) from ev where typ = 3").rows() == [(1,)]
    txt = d.sql("explain select count(*) from ev where typ = 3")
    assert "partitions: 2/3" in str(txt)   # big + default


def test_every_expansion(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c4"), numsegments=2)
    d.sql("create table m (k int, d int) distributed by (k) partition by "
          "range (d) (partition p start (0) end (30) every (10))")
    names = [p.name for p in d.catalog.get("m").partitions]
    assert names == ["p_1", "p_2", "p_3"]
    d.sql("insert into m values (1, 5), (2, 15), (3, 25)")
    assert sum(d.store.segment_rowcounts("m#p_2")) == 1


def test_add_drop_partition(db):
    db.sql("alter table sales drop partition tail")
    assert db.sql("select count(*) from sales").rows() == [(270,)]
    db.sql("alter table sales add partition q4 start (270) end (360)")
    db.sql("insert into sales values (9000, 300, 1, 'r0')")
    assert sum(db.store.segment_rowcounts("sales#q4")) == 1
    # dropped storage is gone from the manifest
    snap = db.store.manifest.snapshot()
    assert "sales#tail" not in snap["tables"]
    with pytest.raises(SqlError, match="no partition"):
        db.sql("insert into sales values (9001, 900, 1, 'r0')")


def test_overlap_and_duplicate_validation(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c5"), numsegments=2)
    with pytest.raises(SqlError, match="overlapping"):
        d.sql("create table x (k int, v int) distributed by (k) "
              "partition by range (v) (partition a start (0) end (10), "
              "partition b start (5) end (20))")
    with pytest.raises(SqlError, match="multiple list"):
        d.sql("create table y (k int, v int) distributed by (k) "
              "partition by list (v) (partition a values (1), "
              "partition b values (1, 2))")


def test_analyze_and_stats_span_partitions(db):
    db.sql("analyze sales")
    st = db.catalog.get("sales").stats
    assert st.rows == 400
    assert st.columns["day"].min == 0 and st.columns["day"].max == 399


def test_transactional_multi_partition_insert(db):
    db.sql("begin")
    db.sql("insert into sales values (9100, 10, 1, 'r0'), "
           "(9101, 100, 1, 'r1'), (9102, 500, 1, 'r2')")
    db.sql("rollback")
    assert db.sql("select count(*) from sales").rows() == [(400,)]
    db.sql("begin")
    db.sql("insert into sales values (9100, 10, 1, 'r0'), "
           "(9101, 100, 1, 'r1')")
    db.sql("commit")
    assert db.sql("select count(*) from sales").rows() == [(402,)]


def test_drop_table_drops_children(db):
    db.sql("drop table sales")
    snap = db.store.manifest.snapshot()
    assert not any(t.startswith("sales#") for t in snap["tables"])
    with pytest.raises(ValueError, match="does not exist"):
        db.sql("select * from sales")


def test_expand_partitioned(db):
    before = db.sql("select sum(amount) from sales").rows()
    db.expand(8)
    assert db.sql("select sum(amount) from sales").rows() == before
    counts = db.store.segment_rowcounts("sales#q1")
    assert len(counts) == 8 and sum(counts) == 90


def test_every_with_dates(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c6"), numsegments=2)
    d.sql("create table dt (k int, dd date) distributed by (k) partition by "
          "range (dd) (partition m start (date '2024-01-01') "
          "end (date '2024-03-01') every (31))")
    assert len(d.catalog.get("dt").partitions) == 2   # 60 days / 31
    d.sql("insert into dt values (1, date '2024-02-15')")
    assert sum(d.store.segment_rowcounts("dt#m_2")) == 1


def test_partition_def_shape_validation(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c7"), numsegments=2)
    d.sql("create table lt (k int, v int) distributed by (k) partition by "
          "list (v) (partition a values (1))")
    with pytest.raises(SqlError, match="VALUES"):
        d.sql("alter table lt add partition b")   # range-shaped def on LIST
    d.sql("create table rt (k int, v int) distributed by (k) partition by "
          "range (v) (partition a start (0) end (10))")
    with pytest.raises(SqlError, match="LIST syntax"):
        d.sql("alter table rt add partition b values (5)")
    with pytest.raises(SqlError, match="NULL"):
        d.sql("create table nt (k int, v int) distributed by (k) partition "
              "by list (v) (partition a values (null))")


def test_failed_routed_insert_stages_nothing_in_tx(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c8"), numsegments=2)
    d.sql("create table st (k int, v int not null) distributed by (k) "
          "partition by range (v) (partition a start (0) end (10), "
          "partition b start (10) end (20))")
    d.sql("begin")
    with pytest.raises(SqlError, match="not-null"):
        # valid row routes to a; NULL row would route later — nothing may
        # stage before the whole batch validates
        d.sql("insert into st values (1, 5), (2, null)")
    d.sql("commit")
    assert d.sql("select count(*) from st").rows() == [(0,)]


def test_two_unbounded_starts_rejected(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c9"), numsegments=2)
    with pytest.raises(SqlError, match="overlapping"):
        d.sql("create table ub (k int, v int) distributed by (k) partition "
              "by range (v) (partition a end (10), partition b end (20))")


def test_checkcat_clean(db, tmp_path, capsys):
    from greengage_tpu.mgmt import cli

    rc = cli.main(["checkcat", "-d", str(tmp_path / "c")])
    out = capsys.readouterr().out
    assert rc == 0 and "consistent" in out
