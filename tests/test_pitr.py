"""Continuous archiving + PITR (storage/archive.py) — the WAL-archive /
recovery-target analog (xlogarchive.c, recovery_target_time)."""

import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.storage.archive import Archive


@pytest.fixture()
def clu(tmp_path, devices8):
    d = greengage_tpu.connect(path=str(tmp_path / "c"), numsegments=4)
    d.sql("set archive_mode to on")
    d.sql(f"set archive_dir to '{tmp_path / 'arch'}'")
    return d, str(tmp_path / "arch"), tmp_path


def test_every_commit_archives(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int, b text) distributed by (a)")
    db.sql("insert into t values (1, 'one')")        # v1
    db.sql("insert into t values (2, 'two')")        # v2
    db.sql("delete from t where a = 1")              # v3
    vs = [v for v, _ in Archive(arch).versions()]
    # v0 = the CREATE TABLE (catalog-only DDL archive), then one per write
    assert vs == [0, 1, 2, 3]


def test_pitr_restores_each_version(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int, b text) distributed by (a)")
    db.sql("insert into t values (1, 'one')")
    db.sql("insert into t values (2, 'two')")
    db.sql("update t set b = 'TWO' where a = 2")
    db.sql("delete from t where a = 1")
    a = Archive(arch)
    want = {1: [(1, "one")],
            2: [(1, "one"), (2, "two")],
            3: [(1, "one"), (2, "TWO")],
            4: [(2, "TWO")]}
    for v, rows in want.items():
        tgt = str(tmp / f"restored{v}")
        assert a.restore(tgt, version=v) == v
        r = greengage_tpu.connect(path=tgt)
        assert r.sql("select a, b from t order by a").rows() == rows


def test_pitr_after_old_files_gced(clu):
    # the point of archiving: DML GC'd the v1 files from the cluster, but
    # the archive still serves v1
    db, arch, tmp = clu
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (1), (2), (3)")
    db.sql("delete from t")                           # republish, GC old
    db.store.gc_now() if hasattr(db.store, "gc_now") else None
    a = Archive(arch)
    tgt = str(tmp / "old")
    a.restore(tgt, version=1)
    r = greengage_tpu.connect(path=tgt)
    assert r.sql("select count(*) from t").rows() == [(3,)]


def test_pitr_time_target(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (1)")
    a = Archive(arch)
    vs = a.versions()
    ts1 = vs[-1][1]
    db.sql("insert into t values (2)")
    # target = the first commit's timestamp -> restores v1 (<= semantics)
    tgt = str(tmp / "by_time")
    v = a.restore(tgt, time=ts1)
    r = greengage_tpu.connect(path=tgt)
    assert v == 1 and r.sql("select count(*) from t").rows() == [(1,)]
    with pytest.raises(ValueError, match="no archived version"):
        a.resolve_target(time="1999-01-01T00:00:00")


def test_restore_refuses_existing_cluster(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (1)")
    with pytest.raises(ValueError, match="already a cluster"):
        Archive(arch).restore(db.path)


def test_transaction_archives_once_at_commit(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int) distributed by (a)")       # v0 (DDL only)
    db.sql("insert into t values (0)")                        # v1
    before = len(Archive(arch).versions())
    db.sql("begin")
    db.sql("insert into t values (1)")
    db.sql("insert into t values (2)")
    assert len(Archive(arch).versions()) == before   # invisible until commit
    db.sql("commit")
    vs = Archive(arch).versions()
    assert len(vs) == before + 1
    tgt = str(tmp / "txr")
    Archive(arch).restore(tgt)
    r = greengage_tpu.connect(path=tgt)
    assert r.sql("select count(*) from t").rows() == [(3,)]


def test_ddl_after_archive_refreshes_catalog(clu):
    # DDL moves the catalog without a manifest commit: the archived
    # catalog for the current version must refresh, or a restored
    # cluster would lose the new table's schema
    db, arch, tmp = clu
    db.sql("create table t1 (a int) distributed by (a)")
    db.sql("insert into t1 values (1)")               # v1 archived
    db.sql("create table t2 (b int) distributed by (b)")   # DDL only
    tgt = str(tmp / "ddl")
    Archive(arch).restore(tgt)
    r = greengage_tpu.connect(path=tgt)
    assert r.sql("select count(*) from t2").rows() == [(0,)]
    assert r.sql("select a from t1").rows() == [(1,)]


def test_drop_table_recoverable_by_time(clu):
    # the accidental-DROP scenario PITR exists for: catalog revisions are
    # timestamped, never overwritten
    db, arch, tmp = clu
    db.sql("create table precious (a int) distributed by (a)")
    db.sql("insert into precious values (41), (42)")
    ts_before_drop = Archive(arch).versions()[-1][1]
    db.sql("drop table precious")
    tgt = str(tmp / "undrop")
    v = Archive(arch).restore(tgt, time=ts_before_drop)
    r = greengage_tpu.connect(path=tgt)
    assert sorted(r.sql("select a from precious").rows()) == [(41,), (42,)]
    # plain restore (latest): the post-drop state wins
    tgt2 = str(tmp / "postdrop")
    Archive(arch).restore(tgt2)
    r2 = greengage_tpu.connect(path=tgt2)
    assert "precious" not in r2.catalog.tables


def test_pg_style_time_target(clu):
    db, arch, tmp = clu
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (1)")
    # 'YYYY-MM-DD HH:MM:SS' form far in the future resolves to the latest
    v = Archive(arch).resolve_target(time="2199-01-01 00:00:00")
    assert v == Archive(arch).versions()[-1][0]


def test_partitioned_dict_text_archives(clu):
    db, arch, tmp = clu
    db.sql("create table pt (a int, tag text) distributed by (a) "
           "partition by list (a) (partition p0 values (0), "
           "partition p1 values (1))")
    db.sql("insert into pt values (0, 'zero'), (1, 'one')")
    tgt = str(tmp / "part")
    Archive(arch).restore(tgt)
    r = greengage_tpu.connect(path=tgt)
    assert sorted(r.sql("select a, tag from pt").rows()) == \
        [(0, "zero"), (1, "one")]


def test_cli_archive_and_restore(tmp_path, devices8, capsys):
    from greengage_tpu.mgmt import cli

    clu = str(tmp_path / "c2")
    assert cli.main(["init", "-d", clu, "-n", "4"]) == 0
    db = greengage_tpu.connect(path=clu)
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (7)")
    arch = str(tmp_path / "a2")
    assert cli.main(["archive", "-d", clu, "-a", arch]) == 0
    out = capsys.readouterr().out
    assert "archived version" in out
    tgt = str(tmp_path / "r2")
    assert cli.main(["restore-pitr", "-d", tgt, "-a", arch]) == 0
    r = greengage_tpu.connect(path=tgt)
    assert r.sql("select a from t").rows() == [(7,)]
