"""Overload armor (docs/ROBUSTNESS.md "Overload protection"): the
bounded front end (connection cap / auth deadline / frame cap), typed
admission load shedding, the memory-pressure brownout state machine,
watcher reuse + transient-error classification, and graceful drain.

Fast tier: every rejection SHAPE pinned deterministically (fault points
and tiny tables — no storms). Slow tier: the 64-client storm against
max_connections=8 / admission_queue_limit=4 with bounded threads and
full post-storm recovery.
"""

import errno
import json
import socket
import threading
import time

import pytest

import greengage_tpu
from greengage_tpu.runtime import overload
from greengage_tpu.runtime import server as server_mod
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.resqueue import AdmissionShed
from greengage_tpu.runtime.server import SqlClient, SqlServer, _watch_tick


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=2)
    d.sql("create table t (a int, v int) distributed by (a)")
    d.sql("insert into t values (1, 10), (2, 20), (3, 30)")
    yield d
    faults.reset()
    overload.CONTROLLER.reset()
    d.close()


@pytest.fixture()
def served(db, tmp_path):
    sock = str(tmp_path / "s.sock")
    srv = SqlServer(db, sock, host="127.0.0.1", port=0)
    srv.start()
    yield db, srv, sock
    faults.reset()
    overload.CONTROLLER.reset()
    srv.stop()


def _raw_unix(sock_path):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(sock_path)
    return s, s.makefile("rwb")


# ---------------------------------------------------------------------
# layer 1: the bounded front end
# ---------------------------------------------------------------------

def test_connection_cap_typed_rejection(served):
    d, srv, sock = served
    d.sql("set max_connections = 1")
    c0 = counters.get("connections_shed_total")
    c1 = SqlClient(sock)                      # holds the only slot
    assert c1.sql("select count(*) from t")["rows"] == [[3]]
    s, f = _raw_unix(sock)                    # over cap: typed fast-fail
    resp = json.loads(f.readline())
    assert resp["ok"] is False
    assert resp["code"] == "too_many_connections"
    assert resp["sqlstate"] == "53300"
    assert resp["retryable"] is True
    assert f.readline() == b""                # and the socket closes
    s.close()
    assert counters.get("connections_shed_total") == c0 + 1
    c1.close()
    # released slot admits again
    time.sleep(0.1)
    c2 = SqlClient(sock)
    assert c2.sql("select 1")["rows"] == [[1]]
    c2.close()


def test_overload_accept_fault_forces_shed(served):
    _, _, sock = served
    faults.inject("overload_accept", "skip", occurrences=1)
    s, f = _raw_unix(sock)
    resp = json.loads(f.readline())
    assert resp["code"] == "too_many_connections"
    s.close()
    # the next connect (fault spent) admits normally
    c = SqlClient(sock)
    assert c.sql("select 1")["rows"] == [[1]]
    c.close()


def test_frame_too_large_typed_close(served):
    d, _, sock = served
    d.sql("set max_frame_bytes = 4096")
    s, f = _raw_unix(sock)
    f.write(b'{"sql": "' + b"x" * 8192 + b'"}\n')
    f.flush()
    resp = json.loads(f.readline())
    assert resp["ok"] is False and resp["code"] == "frame_too_large"
    # cannot resync: the server closes (EOF, or a reset when our unread
    # tail was still in its buffer — both mean "connection over")
    try:
        rest = f.readline()
    except OSError:
        rest = b""
    assert rest == b""
    s.close()
    assert counters.get("frames_rejected_total") >= 1


def test_auth_deadline_closes_silent_peer(served):
    d, srv, _ = served
    d.sql("set client_auth_deadline_s = 0.3")
    t0 = time.monotonic()
    s = socket.create_connection(("127.0.0.1", srv.port))
    f = s.makefile("rwb")
    # send NOTHING: the handshake read must time out server-side
    assert f.readline() == b""                # EOF, not a hang
    assert time.monotonic() - t0 < 3.0
    s.close()


def test_idle_timeout_typed_close(served):
    d, _, sock = served
    d.sql("set client_idle_timeout_s = 0.3")
    s, f = _raw_unix(sock)
    f.write(b'{"sql": "select 1"}\n')
    f.flush()
    assert json.loads(f.readline())["ok"] is True
    t0 = time.monotonic()
    resp = json.loads(f.readline())           # idle: server speaks first
    assert resp["code"] == "idle_timeout"
    assert f.readline() == b""
    assert time.monotonic() - t0 < 3.0
    s.close()


# ---------------------------------------------------------------------
# watcher: one thread per connection; transient errors never cancel
# ---------------------------------------------------------------------

def test_watcher_reused_across_pipelined_statements(served):
    _, _, sock = served
    c = SqlClient(sock)
    c.sql("select 1")
    watchers = [t for t in threading.enumerate()
                if t.name == "gg-client-watch"]
    assert len(watchers) == 1
    first = watchers[0]
    for _ in range(30):
        c.sql("select 1")
    watchers = [t for t in threading.enumerate()
                if t.name == "gg-client-watch"]
    assert watchers == [first]                # same thread, not 30 new ones
    c.close()
    time.sleep(0.3)
    assert not first.is_alive()               # shut down with its connection


def test_watch_tick_classifies_oserrors():
    class _Boom:
        def __init__(self, err):
            self._err = err

        def fileno(self):
            raise self._err

        def recv(self, *a):
            raise self._err

    # transient poll failures (ENOMEM, EINTR-ish) must NOT read as EOF
    assert _watch_tick(_Boom(OSError(errno.ENOMEM, "boom"))) == "transient"
    # errnos proving the peer/fd is gone DO read as EOF
    assert _watch_tick(_Boom(OSError(errno.EBADF, "gone"))) == "eof"
    assert _watch_tick(_Boom(OSError(errno.ECONNRESET, "rst"))) == "eof"
    # a closed-socket ValueError (fileno == -1 after close) is EOF too
    sp_a, sp_b = socket.socketpair()
    sp_a.close()
    assert _watch_tick(sp_a) == "eof"
    sp_b.close()


def test_transient_select_failure_does_not_cancel(served, monkeypatch):
    """Regression (satellite): the old _watch_client treated ANY OSError
    from select as a client EOF and cancelled a live client's statement.
    With select failing transiently for the whole statement, the
    statement must complete."""
    _, _, sock = served

    class _FlakySelect:
        @staticmethod
        def select(*a, **kw):
            raise OSError(errno.ENOMEM, "spurious poll failure")

    monkeypatch.setattr(server_mod, "select", _FlakySelect)
    # slow the statement so the watcher polls (and fails) several times
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=0.5,
                  occurrences=1)
    c = SqlClient(sock)
    resp = c.op({"sql": "select count(*) from t"})
    assert resp["ok"] is True and resp["rows"] == [[3]]
    assert "cancelled" not in resp
    c.close()


def test_watcher_still_cancels_real_disconnect(served):
    """The transient-classification fix must not break the real thing:
    a client that vanishes mid-statement still flags client_gone."""
    d, _, sock = served
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=0.6,
                  occurrences=1)
    s, f = _raw_unix(sock)
    f.write(b'{"sql": "select count(*) from t"}\n')
    f.flush()
    time.sleep(0.2)
    f.close()                                 # vanish mid-statement (the
    s.close()                                 # makefile dup holds the fd)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if counters.get("statements_cancelled_client_gone") >= 1:
            break
        time.sleep(0.05)
    assert counters.get("statements_cancelled_client_gone") >= 1


# ---------------------------------------------------------------------
# layer 2: admission load shedding
# ---------------------------------------------------------------------

def test_admission_queue_shed_typed_error(db):
    db.sql("set resource_queue_active = 1")
    db.sql("set admission_queue_limit = 1")
    c0 = counters.get("admission_shed_total")
    # holder occupies the single slot, parked at the pre-dispatch fault
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=1.2,
                  occurrences=1)
    errs = []

    def run(i):
        try:
            db.sql("select count(*) from t")
        except Exception as e:
            errs.append((i, e))

    t1 = threading.Thread(target=run, args=(1,))   # holder (admitted)
    t1.start()
    time.sleep(0.3)
    t2 = threading.Thread(target=run, args=(2,))   # waiter (depth 1)
    t2.start()
    time.sleep(0.3)
    with pytest.raises(AdmissionShed) as ei:       # depth at cap: shed
        db.sql("select count(*) from t")
    assert ei.value.retryable is True
    assert ei.value.sqlstate == "53300"
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert not errs, errs                          # holder+waiter succeed
    assert counters.get("admission_shed_total") == c0 + 1
    db.sql("set resource_queue_active = 0")
    db.sql("set admission_queue_limit = 0")


def test_server_maps_shed_to_retryable_frame(served):
    d, _, sock = served
    d.sql("set resource_queue_active = 1")
    d.sql("set admission_queue_limit = 1")
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=1.2,
                  occurrences=1)
    holder = SqlClient(sock)
    waiter = SqlClient(sock)
    shed = SqlClient(sock)
    results = {}

    def go(name, cli):
        results[name] = cli.op({"sql": "select count(*) from t"})

    ts = [threading.Thread(target=go, args=(n, c))
          for n, c in (("holder", holder),)]
    ts[0].start()
    time.sleep(0.3)
    ts.append(threading.Thread(target=go, args=("waiter", waiter)))
    ts[1].start()
    time.sleep(0.3)
    go("shed", shed)                          # depth at cap: typed frame
    for t in ts:
        t.join(timeout=30)
    assert results["holder"]["ok"] and results["waiter"]["ok"]
    assert results["shed"]["ok"] is False
    assert results["shed"]["code"] == "admission_shed"
    assert results["shed"]["sqlstate"] == "53300"
    assert results["shed"]["retryable"] is True
    for c in (holder, waiter, shed):
        c.close()
    d.sql("set resource_queue_active = 0")
    d.sql("set admission_queue_limit = 0")


def test_resgroup_path_sheds_too(db):
    db.sql("set resource_group_global_active = 1")
    db.sql("set admission_queue_limit = 1")
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=1.0,
                  occurrences=1)
    errs = []

    def run():
        try:
            db.sql("select count(*) from t")
        except Exception as e:
            errs.append(e)

    t1 = threading.Thread(target=run)
    t1.start()
    time.sleep(0.3)
    t2 = threading.Thread(target=run)
    t2.start()
    time.sleep(0.3)
    with pytest.raises(AdmissionShed):
        db.sql("select count(*) from t")
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert not errs, errs
    db.sql("set resource_group_global_active = 0")
    db.sql("set admission_queue_limit = 0")


# ---------------------------------------------------------------------
# layer 3: the brownout state machine
# ---------------------------------------------------------------------

def test_brownout_enter_effects_and_hysteresis(db):
    ctl = overload.CONTROLLER
    base_limit = db.store.blockcache.limit_bytes()
    e0 = counters.get("brownout_entered_total")
    x0 = counters.get("brownout_exited_total")
    faults.inject("brownout_force", "skip", occurrences=-1)
    assert ctl.evaluate(db.settings, force=True) is True
    # gauge + counters
    assert counters.get("brownout") == 1
    assert counters.get("brownout_entered_total") == e0 + 1
    # block-cache budget shrunk by brownout_cache_factor (0.5 default)
    assert db.store.blockcache.limit_bytes() <= base_limit // 2
    # batch serving disabled while browned out
    db.sql("set batch_serving_enabled = on")
    assert db._batch_eligible({"@params@": [1]}, {}) is False
    # admission ceiling scaled (spill-tier preference)
    assert ctl.scaled_vmem(1 << 30) == (1 << 30) // 2
    # statements still execute (degraded, not dead)
    assert db.sql("select count(*) from t").rows() == [(3,)]
    # HYSTERESIS: pressure cleared but the dwell has not elapsed — the
    # state must hold
    faults.reset("brownout_force")
    db.sql("set brownout_exit_s = 30")
    assert ctl.evaluate(db.settings, force=True) is True
    assert counters.get("brownout") == 1
    # dwell satisfied (exit_s = 0): clean exit restores everything
    db.sql("set brownout_exit_s = 0")
    assert ctl.evaluate(db.settings, force=True) is False
    assert counters.get("brownout") == 0
    assert counters.get("brownout_exited_total") == x0 + 1
    assert db.store.blockcache.limit_bytes() == base_limit
    assert ctl.scaled_vmem(1 << 30) == 1 << 30
    assert db._batch_eligible({"@params@": [1]}, {}) is True
    db.sql("set batch_serving_enabled = off")


def test_brownout_oom_streak_trigger(db):
    ctl = overload.CONTROLLER
    db.sql("set brownout_oom_events = 2")
    db.sql("set brownout_window_s = 30")
    assert ctl.evaluate(db.settings, force=True) is False
    counters.inc("oom_events", 2)             # two classified OOMs
    assert ctl.evaluate(db.settings, force=True) is True
    snap = ctl.snapshot()
    assert snap["brownout"] and "OOM" in snap["reason"]
    db.sql("set brownout_exit_s = 0")
    db.sql("set brownout_oom_events = 1000")  # clear the signal
    assert ctl.evaluate(db.settings, force=True) is False


def test_brownout_disabled_guc_wins(db):
    db.sql("set brownout_enabled = off")
    faults.inject("brownout_force", "skip", occurrences=-1)
    assert overload.CONTROLLER.evaluate(db.settings, force=True) is False
    db.sql("set brownout_enabled = on")


def test_brownout_visible_in_status_and_ps(served, capsys):
    d, srv, sock = served
    d.sql("set brownout_exit_s = 0")
    faults.inject("brownout_force", "skip", occurrences=-1)
    c = SqlClient(sock)
    st = c.op({"op": "status"})               # status evaluates fresh
    assert st["overload"]["brownout"] is True
    assert st["overload"]["batch_serving_disabled"] is True
    assert st["cluster"]["counters"].get("brownout") == 1
    ps = c.op({"op": "ps"})
    assert ps["overload"]["brownout"] is True
    c.close()
    # `gg ps` prints the brownout banner
    from greengage_tpu.mgmt import cli

    assert cli.main(["ps", "-s", sock]) == 0
    out = capsys.readouterr().out
    assert "BROWNOUT" in out
    faults.reset("brownout_force")


# ---------------------------------------------------------------------
# batch-pipeline member cap
# ---------------------------------------------------------------------

def test_batch_queue_limit_sheds_to_serial(db):
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_queue_limit = 1")
    db.sql("select count(*) from t where a > 1")   # create the pipeline
    bs = db._batch_server
    assert bs is not None
    c0 = counters.get("batch_members_shed_total")
    # hold the dispatcher so a window would accumulate, then exceed the
    # member cap: submit must return None (classic path) not enqueue
    faults.inject("batch_dispatch", "sleep", sleep_s=0.3, occurrences=1)
    res = {}

    def q(i):
        res[i] = db.sql(f"select count(*) from t where a > {i}").rows()

    ts = [threading.Thread(target=q, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert len(res) == 3                       # every statement answered
    assert counters.get("batch_members_shed_total") >= c0
    db.sql("set batch_serving_enabled = off")
    db.sql("set batch_queue_limit = 512")


# ---------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------

def test_graceful_drain_cancels_and_joins(db, tmp_path):
    sock = str(tmp_path / "d.sock")
    srv = SqlServer(db, sock)
    srv.start()
    c = SqlClient(sock)
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=1.0,
                  occurrences=1)
    out = {}

    def go():
        out["resp"] = c.op({"sql": "select count(*) from t"})

    t = threading.Thread(target=go)
    t.start()
    time.sleep(0.3)                            # statement in flight
    t0 = time.monotonic()
    srv.stop()
    drained = time.monotonic() - t0
    assert drained < float(db.settings.server_drain_s) + 2.0
    t.join(timeout=5)
    # the in-flight statement surfaced the typed shutdown cause
    assert out["resp"]["ok"] is False
    assert out["resp"].get("cancelled") == "shutdown"
    assert counters.get("statements_cancelled_shutdown") >= 1
    # no stray serving threads survive the drain
    time.sleep(0.3)
    stray = [th.name for th in threading.enumerate()
             if th.name in ("gg-server", "gg-server-tcp",
                            "gg-client-watch")]
    assert not stray, stray
    assert counters.get("server_active_connections") == 0
    c.close()


def test_drain_rejects_new_connects_typed(db, tmp_path):
    sock = str(tmp_path / "d2.sock")
    srv = SqlServer(db, sock)
    srv.start()
    srv.stop()
    # post-stop: the listener is gone entirely
    with pytest.raises(OSError):
        SqlClient(sock)


# ---------------------------------------------------------------------
# the storm (slow tier)
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_overload_storm_bounded_and_recovers(served):
    d, srv, sock = served
    d.sql("set max_connections = 8")
    d.sql("set resource_queue_active = 2")
    d.sql("set admission_queue_limit = 4")
    d.sql("set resource_queue_timeout_s = 60")
    q = "select count(*), sum(v) from t"
    oracle = [list(r) for r in d.sql(q).rows()]   # wire rows are lists
    warm = _best_of(d, q)
    base_threads = threading.active_count()
    outcomes = []
    mu = threading.Lock()

    def client(i):
        try:
            c = SqlClient(sock)
        except OSError as e:
            with mu:
                outcomes.append(("connect_error", repr(e)))
            return
        try:
            resp = c.op({"sql": q})
            if resp.get("ok"):
                kind = "ok" if resp["rows"] == oracle else "wrong"
            else:
                kind = (resp.get("code")
                        or ("timeout" if "timed out" in resp["error"]
                            else "error"))
            with mu:
                outcomes.append((kind, resp.get("error")))
        finally:
            c.close()

    ts = [threading.Thread(target=client, args=(i,)) for i in range(64)]
    for t in ts:
        t.start()
        # thread count stays bounded DURING the storm: 8 admitted
        # handlers + 8 watchers + the listeners + the 64 test clients
        assert threading.active_count() < base_threads + 64 + 8 * 2 + 8
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts), "hung storm client"
    kinds = {}
    for k, _ in outcomes:
        kinds[k] = kinds.get(k, 0) + 1
    # every request ended in a result or a TYPED outcome
    assert len(outcomes) == 64, kinds
    assert kinds.get("wrong", 0) == 0, kinds
    assert kinds.get("error", 0) == 0, kinds
    assert kinds.get("connect_error", 0) == 0, kinds
    allowed = {"ok", "too_many_connections", "admission_shed", "timeout"}
    assert set(kinds) <= allowed, kinds
    assert kinds.get("ok", 0) >= 1
    assert kinds.get("too_many_connections", 0) >= 1
    # post-storm: population drains, service recovers
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and counters.get("server_active_connections") > 0:
        time.sleep(0.05)
    assert counters.get("server_active_connections") == 0
    assert threading.active_count() <= base_threads + 4
    post = _best_of(d, q)
    # acceptance target is 5%; the in-test bound is looser because
    # wall-clock ratios on shared CI jitter — a real regression (a leaked
    # queue slot, a stuck brownout) shows up as multiples, not percents
    assert post <= warm * 1.25 + 0.005, (post, warm)
    assert [list(r) for r in d.sql(q).rows()] == oracle


def _best_of(d, q, runs=10):
    d.sql(q)
    best = 1e9
    for _ in range(runs):
        t0 = time.perf_counter()
        d.sql(q)
        best = min(best, time.perf_counter() - t0)
    return best
