"""Host-offload spill — VERDICT r1 item #1's second half: queries whose
working set exceeds the vmem limit complete via pass-partitioned execution
(the workfile-manager role, workfile_mgr.c:544) instead of being
rejected."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import QueryError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table dim (pk int, grp int) distributed by (pk)")
    d.sql("insert into dim values " + ",".join(
        f"({i},{i % 11})" for i in range(1, 501)))
    d.sql("create table big (k int, fk int, v int) distributed by (k)")
    n = 400_000
    rng = np.random.default_rng(6)
    d.load_table("big", {"k": np.arange(n),
                         "fk": rng.integers(1, 501, n),
                         "v": rng.integers(0, 100, n)})
    d.sql("analyze")
    return d


Q = ("select grp, count(*), sum(v) from big join dim on big.fk = dim.pk "
     "group by grp order by grp")
QS = "select count(*), sum(v) from big join dim on big.fk = dim.pk"


def test_spill_matches_in_memory(db):
    want = db.sql(Q).rows()
    db.sql("set vmem_protect_limit_mb = 4")   # force multiple passes
    try:
        r = db.sql(Q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_spill_scalar_aggregate(db):
    want = db.sql(QS).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(QS)
        assert r.rows() == want
        assert r.stats.get("spill_passes", 0) >= 2
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_unspillable_shape_still_rejected(db):
    # plain full-table select (no aggregate cut): honest rejection
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        with pytest.raises(QueryError, match="not spillable|above vmem"):
            db.sql("select k, v from big where v >= 0 order by k")
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_distinct_agg_unspillable(db):
    """A nested dedupe Aggregate is not row-linear: chunked passes would
    double-count distinct values, so the plan must refuse to spill (r2
    review finding — previously returned silently wrong counts)."""
    q = ("select count(distinct v) from big join dim on big.fk = dim.pk")
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        with pytest.raises(QueryError, match="not spillable"):
            db.sql(q)
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
    assert db.sql(q).rows() == want
