"""Host-offload spill — VERDICT r1 item #1's second half: queries whose
working set exceeds the vmem limit complete via pass-partitioned execution
(the workfile-manager role, workfile_mgr.c:544) instead of being
rejected."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import QueryError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table dim (pk int, grp int) distributed by (pk)")
    d.sql("insert into dim values " + ",".join(
        f"({i},{i % 11})" for i in range(1, 501)))
    d.sql("create table big (k int, fk int, v int) distributed by (k)")
    n = 400_000
    rng = np.random.default_rng(6)
    d.load_table("big", {"k": np.arange(n),
                         "fk": rng.integers(1, 501, n),
                         "v": rng.integers(0, 100, n)})
    d.sql("analyze")
    return d


Q = ("select grp, count(*), sum(v) from big join dim on big.fk = dim.pk "
     "group by grp order by grp")
QS = "select count(*), sum(v) from big join dim on big.fk = dim.pk"


def test_spill_matches_in_memory(db):
    want = db.sql(Q).rows()
    db.sql("set vmem_protect_limit_mb = 4")   # force multiple passes
    try:
        r = db.sql(Q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_spill_scalar_aggregate(db):
    want = db.sql(QS).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(QS)
        assert r.rows() == want
        assert r.stats.get("spill_passes", 0) >= 2
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_unspillable_shape_still_rejected(db):
    # explicit-frame GLOBAL window: funneled to SingleQE, no reduction
    # point, no partition keys to bucket, no sort at the gather — honest
    # rejection (partitioned windows now spill; tests/test_window_spill.py)
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        with pytest.raises(QueryError, match="not spillable|above vmem"):
            db.sql("select k, sum(v) over (order by v, k rows between "
                   "1 preceding and current row) from big")
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_window_partition_spill_replaces_rejection(db):
    """The shape the pre-window-spill engine rejected (ISSUE 12): a
    per-partition window over the whole table completes via PARTITION BY
    hash-bucket passes, exactly (full matrix in test_window_spill.py)."""
    q = "select k, sum(v) over (partition by fk) s from big"
    want = sorted(db.sql(q).rows())
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert sorted(r.rows()) == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_sort_spill_matches_in_memory(db):
    """External-merge sort spill (tuplesort.c role): a full ORDER BY over
    a table above the admission limit completes via per-pass device sorts
    + host merge, matching the in-memory result exactly."""
    q = "select k, v from big where v >= 50 order by v desc, k"
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.stats.get("spill_kind") == "sort"
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_sort_spill_with_limit_offset(db):
    q = "select k, v from big order by v, k limit 7 offset 3"
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "sort", r.stats
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_distinct_agg_spills_exact(db):
    """The DISTINCT dedupe level is its own reduction point (r3 VERDICT
    #6): passes capture per-chunk deduped keys, the merge re-dedupes the
    union — dedupe is idempotent under union, so counts are exact (the
    r2 double-counting hazard is structurally gone)."""
    q = ("select count(distinct v) from big join dim on big.fk = dim.pk")
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
    assert db.sql(q).rows() == want


def test_distinct_colocated_dedupe_spills_exact(devices8):
    """DISTINCT on the distribution key: the dedupe is a COLOCATED
    single-phase aggregate with no motion of its own, yet the same key
    value recurs across pass chunks — the merge must insert its own
    redistribute before re-deduping or the count silently inflates."""
    d = greengage_tpu.connect(numsegments=4)
    n = 400_000
    d.sql("create table cg (g int, v int) distributed by (g)")
    d.load_table("cg", {"g": (np.arange(n) % 2000).astype(np.int64),
                        "v": np.arange(n)})
    d.sql("analyze")
    q = "select count(distinct g) from cg"
    want = d.sql(q).rows()
    assert want == [(2000,)]
    d.sql("set vmem_protect_limit_mb = 1")
    try:
        r = d.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        d.sql("set vmem_protect_limit_mb = 12288")


def test_distinct_unique_key_recursive_merge(db):
    """DISTINCT over a ~unique key reduces nothing per pass, so the merge
    working set is the full domain: the recursive merge level partitions
    the captured keys BY KEY HASH into disjoint buckets and sums the
    additive partial states across buckets (execHHashagg.c batch
    recursion analog) — exact, where r4 rejected honestly."""
    q = "select count(distinct k) from big"
    assert db.sql(q).rows() == [(400_000,)]
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        r = db.sql(q)
        assert r.rows() == [(400_000,)]
        assert r.stats.get("spill_merge_buckets", 0) >= 2, r.stats
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_grouped_distinct_spills_exact(db):
    q = ("select grp, count(distinct big.v) from big join dim "
         "on big.fk = dim.pk group by grp order by grp")
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_grace_join_build_side_partitioned(devices8):
    """Both join sides exceed the limit: the grace-join regime partitions
    probe AND build ranges and walks the chunk grid — inner-join output
    is a disjoint union over build partitions, so partial sums merge
    exactly (nodeHashjoin.c batching analog)."""
    d = greengage_tpu.connect(numsegments=4)
    n = 300_000
    rng = np.random.default_rng(9)
    d.sql("create table probe (k int, fk int, v int) distributed by (k)")
    d.load_table("probe", {"k": np.arange(n),
                           "fk": rng.permutation(n),
                           "v": rng.integers(0, 100, n)})
    d.sql("create table build (pk int, m int, w int) distributed by (m)")
    d.load_table("build", {"pk": np.arange(n), "m": rng.permutation(n),
                           "w": rng.integers(0, 50, n)})
    d.sql("analyze")
    q = ("select count(*), sum(probe.v + build.w) from probe "
         "join build on probe.fk = build.pk")
    want = d.sql(q).rows()
    assert want[0][0] == n
    d.sql("set vmem_protect_limit_mb = 6")
    try:
        r = d.sql(q)
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        d.sql("set vmem_protect_limit_mb = 12288")


def test_semi_join_build_not_partitioned_but_probe_is(db):
    # the partitioned table must never sit under a semi join's build side
    # (per-pass EXISTS would double-count); the probe side still spills
    q = ("select count(*) from big where big.fk in "
         "(select pk from dim where pk <= 200)")
    want = db.sql(q).rows()
    db.sql("set vmem_protect_limit_mb = 4")
    try:
        r = db.sql(q)
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
