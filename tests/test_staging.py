"""Pipelined host data path: parallel staging reads, the byte-accounted
LRU block cache, manifest-version invalidation, and the deterministic
perf-regression guard (docs/PERF.md).

The guard asserts COUNTER VALUES (files read, bytes decoded, cache hits),
never wall clocks, so it is stable on shared CPU runners."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage.blockcache import CacheRegistry
from greengage_tpu.storage.corruption import CorruptionError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "cluster"), numsegments=8)
    d.sql("create table t (k int, v bigint, w bigint) distributed by (k)")
    d.sql("insert into t values "
          + ",".join(f"({i},{i * 10},{i * 100})" for i in range(256)))
    return d


def _data_files(db, table, cols):
    """Manifest-referenced data files read by a scan of ``cols``."""
    snap = db.store.manifest.snapshot()
    n = 0
    for files in snap["tables"][table]["segfiles"].values():
        for rel in files:
            fn = os.path.basename(rel)
            if fn.endswith(".ggb") and not fn.endswith(".valid.ggb") \
                    and fn.split(".")[0] in cols:
                n += 1
    return n


# ---------------------------------------------------------------------------
# the blockcache registry itself
# ---------------------------------------------------------------------------

def test_lru_evicts_recency_not_insertion_order():
    reg = CacheRegistry(limit_mb=1)   # 1 MB budget
    c = reg.cache("x")
    a = np.zeros(300_000, np.uint8)   # ~0.3 MB each
    c.put("k0", a.copy())
    c.put("k1", a.copy())
    c.put("k2", a.copy())
    assert c.get("k0") is not None    # touch the OLDEST -> now MRU
    c.put("k3", a.copy())             # over budget: must evict k1, not k0
    assert "k0" in c
    assert "k1" not in c


def test_byte_budget_spans_caches_and_counts_evictions():
    reg = CacheRegistry(limit_mb=1)
    a = reg.cache("a")
    b = reg.cache("b")
    big = np.zeros(600_000, np.uint8)
    before = counters.get("scan_cache_evict")
    a.put("ka", big.copy())
    b.put("kb", big.copy())           # pushes the registry over 1 MB
    assert "ka" not in a              # global LRU: a's entry went first
    assert "kb" in b
    assert reg.total_bytes <= reg.limit_bytes()
    assert counters.get("scan_cache_evict") > before


def test_version_invalidation_spares_untagged_entries():
    reg = CacheRegistry(limit_mb=64)
    c = reg.cache("x")
    c.put("immutable", 1)                  # no version: committed file
    c.put("v1", 2, version=1)
    c.put("v2", 3, version=2)
    assert reg.invalidate_versions(2) == 1
    assert "immutable" in c and "v2" in c and "v1" not in c


# ---------------------------------------------------------------------------
# deterministic perf-regression guard (counter values, never wall clocks)
# ---------------------------------------------------------------------------

def test_cold_scan_reads_each_file_once_and_repeat_reads_nothing(db):
    expect = _data_files(db, "t", {"v"})
    assert expect > 0
    base = counters.snapshot()
    r = db.sql("select sum(v) from t")
    assert r.rows()[0][0] == sum(i * 10 for i in range(256))
    io = counters.since(base, "scan_")
    assert io.get("scan_files_read") == expect
    assert io.get("scan_bytes_decoded", 0) >= expect  # every file decoded

    # repeat statement: served from the staged-input cache, ZERO file I/O
    base = counters.snapshot()
    db.sql("select sum(v) from t")
    io = counters.since(base, "scan_")
    assert io.get("scan_files_read", 0) == 0
    assert io.get("scan_bytes_decoded", 0) == 0

    # drop only the staged inputs: the scan re-assembles entirely from the
    # BLOCK cache — still zero file reads, and real cache hits
    db.executor._stage_cache.clear()
    base = counters.snapshot()
    r = db.sql("select sum(v) from t")
    assert r.rows()[0][0] == sum(i * 10 for i in range(256))
    io = counters.since(base, "scan_")
    assert io.get("scan_files_read", 0) == 0
    assert io.get("scan_cache_hit", 0) > 0


def test_per_statement_scan_io_stats_and_explain(db):
    db.executor._stage_cache.clear()
    db.store.blockcache.clear()
    r = db.sql("select sum(v), sum(w) from t")
    s = r.stats
    assert s["scan_io"]["scan_files_read"] == _data_files(db, "t", {"v", "w"})
    assert s["stage_ms"] >= 0 and s["compute_ms"] >= 0 and s["fetch_ms"] >= 0
    db.executor._stage_cache.clear()
    db.store.blockcache.clear()
    plan = db.sql("explain analyze select sum(v) from t").plan_text
    assert "Host data path: staging" in plan
    assert "Scan I/O:" in plan and "files read" in plan


def test_scan_threads_guc_serial_matches_parallel(db):
    want = sorted((i, i * 10) for i in range(256))
    for n in (1, 2, 0):
        db.sql(f"set scan_threads = {n}")
        db.executor._stage_cache.clear()
        db.store.blockcache.clear()
        assert sorted(db.sql("select k, v from t").rows()) == want
    assert str(db.settings.show("scan_threads")) == "0"


# ---------------------------------------------------------------------------
# invalidation: manifest bump (DML), index build
# ---------------------------------------------------------------------------

def test_dml_bumps_version_and_scan_sees_new_rows(db):
    assert db.sql("select count(*) from t").rows()[0][0] == 256
    db.sql("insert into t values (9999, 5, 7)")
    r = db.sql("select count(*), sum(v) from t")
    assert r.rows()[0][0] == 257
    assert r.rows()[0][1] == sum(i * 10 for i in range(256)) + 5
    db.sql("delete from t where k = 9999")
    assert db.sql("select count(*) from t").rows()[0][0] == 256


def test_index_build_drops_staged_inputs_so_scans_prune(db, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "idx"), numsegments=8)
    d.sql("create table u (k int, v bigint) distributed by (k)")
    for lo in range(0, 4096, 1024):   # several blocks per segment file
        d.sql("insert into u values "
              + ",".join(f"({i},{i})" for i in range(lo, lo + 1024)))
    assert d.sql("select sum(v) from u where k = 77").rows()[0][0] == 77
    d.sql("create index u_k on u (k)")
    assert len(d.executor._stage_cache) == 0    # staged inputs dropped
    assert d.sql("select sum(v) from u where k = 77").rows()[0][0] == 77


# ---------------------------------------------------------------------------
# concurrency: parallel readers vs corruption (repair exactly once)
# ---------------------------------------------------------------------------

def _first_data_rel(db, table="t"):
    snap = db.store.manifest.snapshot()
    for seg, rels in sorted(snap["tables"][table]["segfiles"].items(),
                            key=lambda kv: int(kv[0])):
        for rel in rels:
            if rel.endswith(".ggb"):
                return rel
    raise AssertionError("no files")


def _flip_byte(path, offset=40):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


@pytest.fixture()
def mdb(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "mirrored"), numsegments=8,
                              mirrors=True)
    d.sql("create table t (k int, v bigint) distributed by (k)")
    d.sql("insert into t values "
          + ",".join(f"({i},{i * 10})" for i in range(128)))
    return d


def test_parallel_readers_repair_a_corrupt_file_exactly_once(mdb):
    rel = _first_data_rel(mdb)
    path = os.path.join(mdb.path, "data", "t", rel)
    _flip_byte(path)
    mdb.store.blockcache.clear()
    before = counters.get("storage_repair")
    results, errors = [], []

    def read():
        try:
            results.append(mdb.store.read_file("t", rel))
        except Exception as e:   # pragma: no cover - failure detail
            errors.append(e)

    threads = [threading.Thread(target=read) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(results) == 6
    for a in results[1:]:
        assert np.array_equal(a, results[0])
    # exactly ONE repair despite six racing readers
    assert counters.get("storage_repair") == before + 1
    assert not os.path.isdir(os.path.join(mdb.path, ".quarantine"))


def test_parallel_readers_quarantine_exactly_once_without_mirror(
        devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "bare"), numsegments=8)
    d.sql("create table t (k int, v bigint) distributed by (k)")
    d.sql("insert into t values "
          + ",".join(f"({i},{i * 10})" for i in range(128)))
    rel = _first_data_rel(d)
    _flip_byte(os.path.join(d.path, "data", "t", rel))
    d.store.blockcache.clear()
    before = counters.get("storage_quarantine")
    errors = []

    def read():
        try:
            d.store.read_file("t", rel)
        except (CorruptionError, IOError) as e:
            errors.append(e)

    threads = [threading.Thread(target=read) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(errors) == 6                     # nobody got bad data
    assert counters.get("storage_quarantine") == before + 1


def test_fault_injected_corruption_under_parallel_staging(mdb):
    """storage_corrupt_block fires once mid-statement while the staging
    pool reads concurrently: the hit thread repairs, every other thread
    proceeds, the statement returns exact rows."""
    mdb.sql("set scan_threads = 4")
    mdb.executor._stage_cache.clear()
    mdb.store.blockcache.clear()
    before = counters.get("storage_repair")
    faults.inject("storage_corrupt_block", "skip", occurrences=1)
    rows = sorted(mdb.sql("select k, v from t").rows())
    assert rows == sorted((i, i * 10) for i in range(128))
    assert counters.get("storage_repair") == before + 1


# ---------------------------------------------------------------------------
# cache-budget behavior under the GUC
# ---------------------------------------------------------------------------

def test_scan_cache_limit_mb_bounds_resident_bytes(db):
    db.sql("set scan_cache_limit_mb = 1")
    db.executor._stage_cache.clear()
    db.store.blockcache.clear()
    db.sql("select sum(v), sum(w), sum(k) from t")
    assert db.store.blockcache.total_bytes <= 1 << 20
    db.sql("set scan_cache_limit_mb = 1024")


# ---------------------------------------------------------------------------
# microbench smoke: one-line JSON, CPU-only
# ---------------------------------------------------------------------------

def test_staging_microbench_emits_headline(tmp_path):
    env = dict(os.environ)
    env.update({
        "GGTPU_MB_ROWS": "20000", "GGTPU_MB_COLS": "3",
        "GGTPU_MB_SEGS": "4", "GGTPU_MB_RUNS": "1",
        "GGTPU_BENCH_PLATFORM": "cpu",
    })
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--microbench", "staging"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stderr[-3000:]
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["metric"] == "staging_cold_mb_per_sec"
    assert line["value"] > 0
    assert line["unit"] == "MB/s"
    assert line["files_read"] > 0
    assert line["warm_files_read"] == 0   # repeat served from block cache
