"""runtime/retry.py: the shared Deadline/backoff/RetryPolicy primitives
that bound every control-channel and probe loop (cdbgang/ftsprobe retry
parity). Pure-host tests — no devices, no sleeps beyond fractions of a
second."""

import socket
import time

import pytest

from greengage_tpu.runtime.retry import (Deadline, RetryPolicy,
                                         TRANSIENT_ERRORS, backoff_delays)


def test_deadline_budget_and_clamp():
    d = Deadline(0.2)
    assert not d.expired
    r = d.remaining()
    assert 0.0 < r <= 0.2
    assert d.clamp(10.0) <= 0.2          # step timeouts never exceed budget
    assert d.clamp(0.001) <= 0.001
    time.sleep(0.25)
    assert d.expired
    assert d.remaining() == 0.0
    assert d.remaining(minimum=0.05) == 0.05
    with pytest.raises(TimeoutError, match="worker ack"):
        d.require("worker ack")


def test_deadline_unbounded():
    d = Deadline(None)
    assert not d.expired
    assert d.remaining() is None
    assert d.clamp(7.5) == 7.5
    d.require("anything")                 # never raises


def test_backoff_growth_and_jitter_bounds():
    delays = backoff_delays(base=0.1, factor=2.0, cap=0.8, jitter=0.5)
    seq = [next(delays) for _ in range(6)]
    # nominal ladder 0.1, 0.2, 0.4, 0.8, 0.8, 0.8 with +-50% jitter
    for got, nominal in zip(seq, [0.1, 0.2, 0.4, 0.8, 0.8, 0.8]):
        assert 0.5 * nominal <= got <= 1.5 * nominal


def test_backoff_stops_at_deadline():
    dl = Deadline(0.05)
    delays = backoff_delays(base=0.02, jitter=0.0, deadline=dl)
    total, n = 0.0, 0
    for delay in delays:
        assert delay <= 0.06              # clamped to the remaining budget
        time.sleep(delay)
        total += delay
        n += 1
        assert n < 50, "generator must terminate once the budget is spent"
    assert dl.expired


def test_retry_policy_retries_transient_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionRefusedError("not up yet")
        return "ok"

    pol = RetryPolicy(attempts=5, base_s=0.01, jitter=0.0)
    assert pol.call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_policy_exhausts_attempts():
    pol = RetryPolicy(attempts=3, base_s=0.001, jitter=0.0)
    calls = []

    def always_down():
        calls.append(1)
        raise TimeoutError("silent peer")

    with pytest.raises(TimeoutError):
        pol.call(always_down)
    assert len(calls) == 3


def test_retry_policy_nonretryable_propagates_immediately():
    pol = RetryPolicy(attempts=10, base_s=0.001)
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("protocol garbage is not transient")

    with pytest.raises(ValueError):
        pol.call(broken)
    assert len(calls) == 1


def test_retry_policy_deadline_bound():
    pol = RetryPolicy(deadline_s=0.1, base_s=0.02, jitter=0.0)
    t0 = time.monotonic()
    with pytest.raises(ConnectionResetError):
        pol.call(lambda: (_ for _ in ()).throw(ConnectionResetError("x")))
    assert time.monotonic() - t0 < 1.0    # bounded, not unbounded retry


def test_retry_policy_on_retry_observer():
    seen = []
    pol = RetryPolicy(attempts=3, base_s=0.001, jitter=0.0)

    def fn():
        if len(seen) < 1:
            raise ConnectionError("first")
        return 42

    assert pol.call(fn, on_retry=lambda a, e, d: seen.append((a, str(e)))) == 42
    assert seen == [(1, "first")]


def test_transient_classification_covers_socket_errors():
    # the classes the control channel actually raises on a dead/hung peer
    for exc in (ConnectionResetError("r"), ConnectionRefusedError("c"),
                BrokenPipeError("p"), socket.timeout("t"), TimeoutError("t"),
                socket.gaierror("g")):
        assert isinstance(exc, TRANSIENT_ERRORS), type(exc)
    assert not isinstance(ValueError("v"), TRANSIENT_ERRORS)
