"""Window-partition spill (ISSUE 12): a window whose working set exceeds
the admission limit completes via PARTITION BY hash-bucket passes —
capture the window's input in chunked passes, run the window per disjoint
bucket (whole partitions per bucket = exact), merge Sort/Limit on the
host. Plus the PR-10 OOM demotion giving windows a second life."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import QueryError
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table w (k int, g int, v int) distributed by (k)")
    n = 200_000
    rng = np.random.default_rng(11)
    d.df = pd.DataFrame({"k": np.arange(n),
                         "g": rng.integers(0, 400, n),
                         "v": rng.integers(0, 1000, n)})
    d.load_table("w", {c: d.df[c].values for c in ("k", "g", "v")})
    d.sql("analyze")
    yield d
    d.close()


def _with_limit(db, mb):
    db.sql(f"set vmem_protect_limit_mb = {mb}")


def test_window_spill_matches_in_memory(db):
    q = ("select k, g, v, sum(v) over (partition by g order by v, k) rs, "
         "row_number() over (partition by g order by v, k) rn from w")
    want = sorted(db.sql(q).rows())
    _with_limit(db, 4)
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.stats.get("spill_window_buckets", 0) >= 2, r.stats
        assert sorted(r.rows()) == want
    finally:
        _with_limit(db, 12288)


def test_window_spill_ntile_lag_oracle(db):
    """ntile/lag inside a spilled partitioned window stay exact vs the
    pandas oracle (partitions are whole per bucket)."""
    q = ("select k, ntile(3) over (partition by g order by v, k) nt, "
         "lag(v) over (partition by g order by v, k) lg from w")
    _with_limit(db, 4)
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
    finally:
        _with_limit(db, 12288)
    got = {k: (nt, lg) for k, nt, lg in r.rows()}
    df = db.df.sort_values(["g", "v", "k"])
    grp = df.groupby("g")
    sizes = grp["v"].transform("size")
    pos = grp.cumcount()
    q_, r_ = sizes // 3, sizes % 3
    big = r_ * (q_ + 1)
    nt = np.where(pos < big, pos // np.maximum(q_ + 1, 1),
                  r_ + (pos - big) // np.maximum(q_, 1)) + 1
    lg = grp["v"].shift(1)
    for k, want_nt, want_lg in zip(df.k, nt, lg):
        gnt, glg = got[k]
        assert gnt == want_nt, (k, gnt, want_nt)
        assert glg == (None if pd.isna(want_lg) else want_lg), k


def test_window_spill_sort_limit_on_host(db):
    q = ("select k, g, rank() over (partition by g order by v desc) rk "
         "from w order by g, rk, k limit 23 offset 5")
    want = db.sql(q).rows()
    _with_limit(db, 4)
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
        assert r.rows() == want
    finally:
        _with_limit(db, 12288)


def test_window_spill_with_filter_above(db):
    """Row-wise wrappers above the window run inside every bucket."""
    q = ("select k, s from (select k, sum(v) over (partition by g) s "
         "from w) t where s > 100000")
    want = sorted(db.sql(q).rows())
    _with_limit(db, 4)
    try:
        r = db.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
        assert sorted(r.rows()) == want
    finally:
        _with_limit(db, 12288)


def test_window_spill_explain_analyze_rows(db):
    """EXPLAIN ANALYZE of a spilling window keeps per-node actual rows
    (capture passes + bucket programs sum onto the original nodes) and
    shows the pass count — gg trace parity with the DISTINCT spill."""
    _with_limit(db, 4)
    try:
        r = db.sql("explain analyze select k, sum(v) over "
                   "(partition by g) s from w")
        text = r.plan_text
        assert "Spill passes:" in text, text
        scan_line = [ln for ln in text.split("\n") if "Scan w" in ln][0]
        assert "actual rows=200000" in scan_line, scan_line
        win_line = [ln for ln in text.split("\n") if "Window" in ln][0]
        assert "actual rows=200000" in win_line, win_line
    finally:
        _with_limit(db, 12288)


def test_window_spill_disabled_rejects(db):
    db.sql("set window_spill_enabled = off")
    _with_limit(db, 4)
    try:
        with pytest.raises(QueryError, match="not spillable|above vmem"):
            db.sql("select k, sum(v) over (partition by g) s from w")
    finally:
        db.sql("set window_spill_enabled = on")
        _with_limit(db, 12288)


def test_window_oom_demotes_to_spill(db):
    """PR-10's oom_spill_retry path: a faked RESOURCE_EXHAUSTED on a
    window statement demotes ONCE to the window spill and completes."""
    q = "select g, count(*) over (partition by g) c from w where k < 5000"
    want = sorted(db.sql(q).rows())
    c0 = counters.snapshot()
    faults.inject("device_oom", "skip", occurrences=1)
    try:
        r = db.sql(q)
    finally:
        faults.reset()
    assert r.stats.get("oom_demoted") is True, r.stats
    assert r.stats.get("spill_kind") == "window", r.stats
    assert sorted(r.rows()) == want
    d = counters.since(c0)
    assert d.get("oom_spill_retries", 0) == 1
    assert d.get("window_spill_runs", 0) == 1


def test_window_spill_trace_has_passes(db):
    """The spill passes land in the statement trace like any other
    (per-pass spans with the spill category)."""
    from greengage_tpu.runtime.trace import TRACES

    _with_limit(db, 4)
    try:
        db.sql("select k, max(v) over (partition by g) m from w")
        spans = [s for s in TRACES.last().export()
                 if s["name"] == "spill-pass"]
        assert len(spans) >= 2, spans
        phases = {(s.get("args") or {}).get("phase") for s in spans}
        assert {"capture", "window"} <= phases, spans
    finally:
        _with_limit(db, 12288)


@pytest.mark.slow
def test_window_spill_4x_admission_limit(devices8):
    """Acceptance: a window over a table ~4x the admission limit
    completes with results matching the pandas oracle."""
    d = greengage_tpu.connect(numsegments=4)
    n = 600_000
    rng = np.random.default_rng(13)
    df = pd.DataFrame({"k": np.arange(n),
                       "g": rng.integers(0, 1000, n),
                       "v": rng.integers(0, 10_000, n)})
    d.sql("create table big4 (k int, g int, v int) distributed by (k)")
    d.load_table("big4", {c: df[c].values for c in ("k", "g", "v")})
    d.sql("analyze")
    q = ("select k, sum(v) over (partition by g order by v, k) rs, "
         "rank() over (partition by g order by v, k) rk from big4")
    # measure the un-spilled estimate, then set the limit to ~1/4 of it
    planned = d.sql("explain " + q)
    from greengage_tpu.exec.executor import effective_limit_bytes  # noqa: F401
    from greengage_tpu.exec.compile import Compiler
    from greengage_tpu.sql.parser import parse

    p, consts, _ = d._plan(parse(q)[0])
    comp = Compiler(d.catalog, d.store, d.mesh, d.numsegments, consts,
                    d.settings).compile(p)
    limit_mb = max(int(comp.est_bytes / (1 << 20) / 4), 1)
    d.sql(f"set vmem_protect_limit_mb = {limit_mb}")
    try:
        r = d.sql(q)
        assert r.stats.get("spill_kind") == "window", r.stats
        assert r.stats.get("spill_passes", 0) >= 2
    finally:
        d.sql("set vmem_protect_limit_mb = 12288")
    got = {k: (rs, rk) for k, rs, rk in r.rows()}
    sdf = df.sort_values(["g", "v", "k"])
    grp = sdf.groupby("g")
    rs = grp["v"].cumsum()
    rk = grp.cumcount() + 1        # (v, k) unique within g
    for k, want_rs, want_rk in zip(sdf.k, rs, rk):
        assert got[k] == (want_rs, want_rk), k
    d.close()
