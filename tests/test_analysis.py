"""gg check: plan-invariant validator + codebase analysis suite.

Five layers:
  * plancheck over the REAL TPC-H / TPC-DS plan corpus (every corpus
    statement validates clean; deliberately mutated plans — a dropped
    Motion, a wrong distribution key, an interior Gather — are rejected
    with typed PlanInvariantErrors),
  * the per-statement plan_validate GUC hook,
  * the static analyzers against known-bad fixture snippets (a lock
    cycle, an unpolled wait loop, a tracer-sync violation) plus the
    runtime lock-order hook,
  * the ISSUE-14 thread-topology suite: cross-role race fixtures,
    shipped-tree mutations (a de-locked BlockCache / program LRU, an
    unregistered thread spawn, a dropped plan-cache GUC) that must each
    produce a typed finding, and the runtime access witness,
  * the merge gate itself: `gg check` over the shipped tree is clean.
"""

import dataclasses
import json

import pytest

import greengage_tpu
from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.plancheck import (PlanInvariantError,
                                              validate_capacities,
                                              validate_plan)
from greengage_tpu.analysis.plancorpus import (TPCDS_QUERIES, TPCH_QUERIES,
                                               load_tpcds_mini,
                                               validate_corpus)
from greengage_tpu.planner.locus import Locus, LocusKind
from greengage_tpu.planner.logical import (Aggregate, Join, Motion,
                                           MotionKind)
from greengage_tpu.sql.parser import parse
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.005)
    d.sql("analyze")
    return d


@pytest.fixture(scope="module")
def dsdb(devices8):
    d = greengage_tpu.connect(numsegments=8)
    load_tpcds_mini(d, n_fact=5_000)
    return d


def _find(plan, pred):
    stack = [plan]
    while stack:
        p = stack.pop()
        if pred(p):
            return p
        stack.extend(p.children)
    return None


# ---------------------------------------------------------------------
# plan corpus: every TPC-H / TPC-DS shape validates clean (I1-I7)
# ---------------------------------------------------------------------

def test_tpch_corpus_validates(db):
    failures = validate_corpus(db, TPCH_QUERIES)
    assert failures == [], failures


def test_tpcds_corpus_validates(dsdb):
    failures = validate_corpus(dsdb, TPCDS_QUERIES)
    assert failures == [], failures


# ---------------------------------------------------------------------
# mutated plans are rejected with typed errors naming the node path
# ---------------------------------------------------------------------

def test_dropped_motion_rejected(db):
    """Splice the state Redistribute out from under Q1's final
    aggregate: partial states stay Strewn, the final merge would
    double-count across segments — plancheck must refuse (I5)."""
    planned, _, _ = db._plan(parse(TPCH_QUERIES["q1_pricing_summary"])[0])
    final = _find(planned, lambda p: isinstance(p, Aggregate)
                  and p.phase == "final")
    moved = final.child
    assert isinstance(moved, Motion) \
        and moved.kind is MotionKind.REDISTRIBUTE
    final.child = moved.child          # the dropped Motion
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I5"
    assert "Aggregate(final)" in ei.value.path


def test_wrong_dist_key_rejected(db):
    """Re-label a moved join side as hashed on the WRONG key: the join's
    locality claim no longer holds (I4)."""
    planned, _, _ = db._plan(parse(TPCH_QUERIES["q3_shipping_priority"])[0])

    def both_hashed(p):
        return (isinstance(p, Join) and p.left.locus is not None
                and p.right.locus is not None
                and p.left.locus.kind is LocusKind.HASHED
                and p.right.locus.kind is LocusKind.HASHED)

    join = _find(planned, both_hashed)
    assert join is not None, "expected a co-located hashed join in Q3"
    other = [c.id for c in join.right.out_cols()
             if c.id not in join.right.locus.keys]
    join.right.locus = Locus.hashed((other[0],),
                                    join.right.locus.numsegments)
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I4"


def test_interior_gather_rejected(db):
    planned, _, _ = db._plan(parse(TPCH_QUERIES["q1_pricing_summary"])[0])
    final = _find(planned, lambda p: isinstance(p, Aggregate)
                  and p.phase == "final")
    funnel = Motion(MotionKind.GATHER, final.child)
    funnel.locus = Locus.entry()
    funnel.est_rows = final.child.est_rows
    final.child = funnel
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I3"


def test_bad_prune_predicate_rejected(db):
    planned, _, _ = db._plan(
        parse("select count(*) from orders where o_orderkey > 7")[0])
    scan = _find(planned, lambda p: getattr(p, "prune_preds", ()))
    assert scan is not None
    scan.prune_preds = (("no_such_column", ">", 7),)
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I6"


def test_capacity_bucketing_enforced(db):
    """I7 negative: a compiler whose scan bucketing is broken (returns a
    non-pow2 capacity) must be refused."""
    from greengage_tpu.exec.compile import Compiler

    planned, consts, _ = db._plan(
        parse("select count(*) from lineitem")[0])
    comp = Compiler(db.catalog, db.store, db.mesh, db.numsegments,
                    consts, db.settings)
    validate_capacities(comp, planned)   # the honest compiler passes
    comp2 = Compiler(db.catalog, db.store, db.mesh, db.numsegments,
                     consts, db.settings)
    comp2._bucket_cap = lambda table, cap: max(cap, 1) * 3   # de-bucketed
    with pytest.raises(PlanInvariantError) as ei:
        validate_capacities(comp2, planned)
    assert ei.value.invariant == "I7"


def test_window_ordered_global_spec_enforced(db):
    """I5 negative (ISSUE 12): an ordered-global window stripped of its
    gkey_spec — or carrying an over-budget packed spec — is refused."""
    from greengage_tpu.planner.logical import Window

    q = ("select o_orderkey, ntile(4) over (order by o_orderkey) nt "
         "from orders")
    planned, _, _ = db._plan(parse(q)[0])
    win = _find(planned, lambda p: isinstance(p, Window))
    assert win is not None and win.global_mode == "ordered"
    validate_plan(planned, db.catalog)
    spec = win.gkey_spec
    win.gkey_spec = None
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I5"
    # over-budget packed fields: the uint64 claim is false
    win.gkey_spec = {"mode": "packed",
                     "fields": [dict(f, bits=40) for f in spec["fields"]]
                     + [dict(spec["fields"][0], bits=40)]}
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I5"


def test_window_global_above_funnel_rejected(db):
    """I3 negative: a global-mode window sitting above a SingleQE funnel
    claims gather-freedom it does not have."""
    from greengage_tpu import expr as E
    from greengage_tpu import types as T
    from greengage_tpu.planner.locus import Locus as L
    from greengage_tpu.planner.logical import Window

    q = ("select o_orderkey, ntile(4) over (order by o_orderkey) nt "
         "from orders")
    planned, _, _ = db._plan(parse(q)[0])
    win = _find(planned, lambda p: isinstance(p, Window))
    funnel = Motion(MotionKind.REDISTRIBUTE, win.child,
                    hash_exprs=[E.Literal(0, T.INT64)])
    funnel.locus = L(LocusKind.SINGLE_QE, (), db.numsegments)
    funnel.est_rows = win.child.est_rows
    win.child = funnel
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I3"


def test_window_range_mode_needs_range_motion(db):
    """I5 negative: a range-mode window whose child lost its range
    Redistribute no longer owns whole key ranges."""
    from greengage_tpu.planner.logical import Window

    q = ("select o_orderkey, sum(o_totalprice) over "
         "(order by o_totalprice, o_orderkey) rs from orders")
    planned, _, _ = db._plan(parse(q)[0])
    win = _find(planned, lambda p: isinstance(p, Window))
    assert win is not None and win.global_mode == "range", win
    validate_plan(planned, db.catalog)
    moved = win.child
    assert isinstance(moved, Motion) and moved.range_spec is not None
    win.child = moved.child          # splice the range motion out
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I5"
    # a range Redistribute claiming a HASHED landing is an I2 violation
    win.child = moved
    moved.locus = Locus.hashed((moved.hash_exprs[0].name,),
                               db.numsegments)
    with pytest.raises(PlanInvariantError) as ei:
        validate_plan(planned, db.catalog)
    assert ei.value.invariant == "I2"


# ---------------------------------------------------------------------
# the plan_validate GUC hook
# ---------------------------------------------------------------------

def test_plan_validate_guc_hook(db, monkeypatch):
    import greengage_tpu.exec.session as S

    calls = []
    orig = S.validate_plan
    monkeypatch.setattr(
        S, "validate_plan",
        lambda p, cat=None: (calls.append(1), orig(p, cat))[1])
    db.sql("select count(*) + 17 from region")   # unique: forces a plan
    assert calls, "plan_validate on: _plan must run the validator"
    calls.clear()
    db.sql("set plan_validate = off")
    try:
        db.sql("select count(*) + 18 from region")
        assert not calls, "plan_validate off: validator must not run"
    finally:
        db.sql("set plan_validate = on")


# ---------------------------------------------------------------------
# static analyzers against known-bad fixtures
# ---------------------------------------------------------------------

def _sources(tmp_path, files: dict):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return astutil.SourceSet(roots=[str(tmp_path)])


def test_lock_cycle_detected(tmp_path):
    from greengage_tpu.analysis import lint_locks

    src = _sources(tmp_path, {"lockmod.py": (
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n"
        "def g():\n"
        "    with b:\n"
        "        with a:\n"
        "            pass\n")})
    rep = lint_locks.run(src)
    assert len(rep.findings) == 1
    assert "lock-order cycle" in rep.findings[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    from greengage_tpu.analysis import lint_locks

    src = _sources(tmp_path, {"lockmod.py": (
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n"
        "def g():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n")})
    assert lint_locks.run(src).findings == []


def test_lock_cycle_through_call_detected(tmp_path):
    """One interprocedural hop: f holds A and calls helper() which takes
    B; g nests them the other way round."""
    from greengage_tpu.analysis import lint_locks

    src = _sources(tmp_path, {"lockmod.py": (
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def helper_take_b():\n"
        "    with b:\n"
        "        pass\n"
        "def f():\n"
        "    with a:\n"
        "        helper_take_b()\n"
        "def g():\n"
        "    with b:\n"
        "        with a:\n"
        "            pass\n")})
    rep = lint_locks.run(src)
    assert len(rep.findings) == 1


def test_unpolled_wait_loop_detected(tmp_path):
    from greengage_tpu.analysis import lint_interrupts

    bad = ("import time\n"
           "def waiter(ready):\n"
           "    while not ready():\n"
           "        time.sleep(0.1)\n")
    good = ("import time\n"
            "from greengage_tpu.runtime.interrupt import check_interrupts\n"
            "def waiter(ready):\n"
            "    while not ready():\n"
            "        check_interrupts()\n"
            "        time.sleep(0.1)\n")
    rep = lint_interrupts.run(_sources(tmp_path / "bad", {"w.py": bad}))
    assert [f.key for f in rep.findings] == ["waiter:sleep-loop"]
    rep = lint_interrupts.run(_sources(tmp_path / "good", {"w.py": good}))
    assert rep.findings == []


def test_unpolled_condition_wait_detected(tmp_path):
    from greengage_tpu.analysis import lint_interrupts

    src = _sources(tmp_path, {"w.py": (
        "def admit(cond, full):\n"
        "    with cond:\n"
        "        while full():\n"
        "            cond.wait()\n")})
    rep = lint_interrupts.run(src)
    assert [f.key for f in rep.findings] == ["admit:condition-wait"]


def test_tracer_sync_violation_detected(tmp_path):
    from greengage_tpu.analysis import lint_tracer

    src = _sources(tmp_path, {"ops/kern.py": (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def bad(vals):\n"
        "    ident = jnp.array(0, vals.dtype)\n"     # the PR-5 bug class
        "    return ident.item()\n"
        "def good(vals):\n"
        "    ident = np.array(0, vals.dtype)\n"      # host-concrete: the fix
        "    return ident.item()\n"
        "def also_bad(vals):\n"
        "    s = jnp.sum(vals)\n"
        "    return float(s)\n")})
    rep = lint_tracer.run(src)
    keys = sorted(f.key for f in rep.findings)
    assert len(keys) == 2
    assert any("bad" in k and ".item()" in k for k in keys)
    assert any("also_bad" in k and "float()" in k for k in keys)


def test_tracer_lint_covers_scalar_library():
    """ISSUE 13: the device scalar library (ops/scalar.py) is inside the
    tracer lint's jit-traced scope — its byte-window/date kernels run
    under trace, so a host sync there is the PR-5 bug class. Guard the
    scope (the /ops/ glob must keep matching it) and its cleanliness."""
    from greengage_tpu.analysis import astutil, lint_tracer

    sources = astutil.SourceSet()
    rels = {s.rel.replace("\\", "/") for s in sources}
    assert any(r.endswith("ops/scalar.py") for r in rels), \
        sorted(r for r in rels if "/ops/" in r)
    rep = lint_tracer.run(sources)
    scalar_findings = [f for f in rep.findings
                       if f.path.endswith("ops/scalar.py")]
    assert scalar_findings == [], scalar_findings


def test_lockdebug_runtime_inversion():
    import threading

    from greengage_tpu.runtime import lockdebug

    prior = lockdebug.enabled()   # conftest enables suite-wide: restore,
    lockdebug.enable(True)        # never hard-disable for later tests
    try:
        a = lockdebug.named(threading.Lock(), "A")
        b = lockdebug.named(threading.Lock(), "B")
        with a:
            with b:
                pass
        with pytest.raises(lockdebug.LockOrderError):
            with b:
                with a:
                    pass
    finally:
        lockdebug.enable(prior)
        lockdebug.reset()   # drop this test's A->B edge from the table


# ---------------------------------------------------------------------
# the merge gate: the shipped tree is clean, and the CLI surfaces it
# ---------------------------------------------------------------------

def test_gg_check_shipped_tree_clean():
    from greengage_tpu.analysis.runner import run_checks

    rep = run_checks()
    assert rep.findings == [], rep.to_text()


def test_gg_check_cli_json():
    import io
    from contextlib import redirect_stdout

    from greengage_tpu.mgmt import cli

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["check", "--json"])
    assert rc == 0
    payload = json.loads(buf.getvalue())
    assert payload["clean"] is True and payload["findings"] == []


def test_baseline_suppression(tmp_path):
    from greengage_tpu.analysis.report import Report, load_baseline

    rep = Report()
    rep.add("locks", "x.py", 3, "cycle:a>b", "boom")
    bl = tmp_path / "baseline.txt"
    bl.write_text("# comment\nlocks x.py::cycle:a>b\n")
    out = rep.suppressed(load_baseline(str(bl)))
    assert out.findings == []
    out2 = rep.suppressed(load_baseline(str(tmp_path / "missing.txt")))
    assert len(out2.findings) == 1


# ---------------------------------------------------------------------
# ISSUE 14: thread-topology race analysis (threads + races checks) and
# the runtime access witness — all pure-AST / host-only
# ---------------------------------------------------------------------

def _two_roles(entries_a, entries_b):
    from greengage_tpu.analysis.threadmodel import Role

    return {
        "alpha": Role("alpha", "fixture role A", (), tuple(entries_a)),
        "beta": Role("beta", "fixture role B", (), tuple(entries_b)),
    }


_RACY = (
    "import threading\n"
    "lock = threading.Lock()\n"
    "state = {}\n"
    "def writer_loop():\n"
    "    state['x'] = 1\n"
    "def reader_loop():\n"
    "    return state.get('x')\n")

_LOCKED = (
    "import threading\n"
    "lock = threading.Lock()\n"
    "state = {}\n"
    "def writer_loop():\n"
    "    with lock:\n"
    "        state['x'] = 1\n"
    "def reader_loop():\n"
    "    with lock:\n"
    "        return state.get('x')\n")


def test_cross_role_bare_write_detected(tmp_path):
    from greengage_tpu.analysis import lint_races

    src = _sources(tmp_path, {"racemod.py": _RACY})
    roles = _two_roles([("racemod.py", "", "writer_loop")],
                       [("racemod.py", "", "reader_loop")])
    rep = lint_races.run(src, roles=roles)
    assert len(rep.findings) == 1, rep.to_text()
    f = rep.findings[0]
    assert f.check == "races" and "racemod.state" in f.key
    # the typed finding carries BOTH access paths and names both roles
    assert "alpha" in f.message and "beta" in f.message
    assert f.message.count("racemod.py:") == 2


def test_cross_role_locked_and_single_role_clean(tmp_path):
    from greengage_tpu.analysis import lint_races

    src = _sources(tmp_path / "locked", {"racemod.py": _LOCKED})
    roles = _two_roles([("racemod.py", "", "writer_loop")],
                       [("racemod.py", "", "reader_loop")])
    assert lint_races.run(src, roles=roles).findings == []
    # same bare write, but only ONE role ever touches it: clean (the
    # analyzer is cross-role by design; intra-role races are the lock
    # lint's and the session's domain)
    src2 = _sources(tmp_path / "single", {"racemod.py": _RACY})
    roles2 = _two_roles([("racemod.py", "", "writer_loop"),
                         ("racemod.py", "", "reader_loop")], [])
    assert lint_races.run(src2, roles=roles2).findings == []


def _mutated(sources, rel_suffix, old, new):
    import ast as _ast

    src = sources.get(rel_suffix)
    text = src.text.replace(old, new)
    assert text != src.text, f"mutation anchor drifted in {rel_suffix}"
    src.text = text
    src.tree = _ast.parse(text)
    src.lines = text.splitlines()
    return sources


def test_mutation_unlocked_blockcache_read_flagged():
    """Strip the registry lock from BlockCache.get: the races check must
    name the structure and two real roles (staging pool vs statement /
    serving pipeline all reach the block cache)."""
    from greengage_tpu.analysis import lint_races

    src = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
    _mutated(src, "storage/blockcache.py",
             "        with reg._lock:\n            ent = self._d.get(key)",
             "        if True:\n            ent = self._d.get(key)")
    rep = lint_races.run(src)
    hit = [f for f in rep.findings if "BlockCache._d" in f.key]
    assert hit, rep.to_text()
    assert "written by role" in hit[0].message \
        and "no common lock" in hit[0].message


def test_mutation_unlocked_program_lru_flagged():
    """Strip _cache_mu from the program-LRU insert: the races check must
    flag _plan_cache between the serving stager and statement threads."""
    from greengage_tpu.analysis import lint_races

    src = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
    _mutated(src, "exec/executor.py",
             "        with self._cache_mu:\n"
             "            self._plan_cache[ck] = comp",
             "        if True:\n"
             "            self._plan_cache[ck] = comp")
    rep = lint_races.run(src)
    hit = [f for f in rep.findings if "Executor._plan_cache" in f.key]
    assert hit, rep.to_text()


def test_thread_hygiene_both_ways():
    from greengage_tpu.analysis import threadmodel

    # shipped tree: every spawn site modelled, every model row live
    src = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
    rep = threadmodel.run(src)
    assert rep.findings == [], rep.to_text()
    assert rep.notes["thread_spawn_sites"] >= 12
    # an unregistered spawn site is a finding
    src2 = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
    _mutated(src2, "runtime/fts.py",
             "    def stop(self) -> None:",
             "    def rogue(self):\n"
             "        threading.Thread(target=self.probe_once).start()\n\n"
             "    def stop(self) -> None:")
    rep2 = threadmodel.run(src2)
    assert any("unregistered-spawn" in f.key for f in rep2.findings), \
        rep2.to_text()


def test_plan_cache_guc_lint_mutation():
    """ISSUE 14 satellite: dropping a binding-read GUC from the SET
    handler's _select_cache.clear() tuple is a finding; so is a tuple
    entry the binding path no longer reads."""
    from greengage_tpu.analysis import lint_registry

    src = astutil.SourceSet()
    _mutated(src, "exec/session.py",
             'if stmt.name in ("optimizer", "plan_cache_params",',
             'if stmt.name in ("plan_cache_params",')
    rep = lint_registry.run(src)
    assert any(f.key == "plan-cache-guc-unclears:optimizer"
               for f in rep.findings), rep.to_text()
    src2 = astutil.SourceSet()
    _mutated(src2, "exec/session.py",
             'if stmt.name in ("optimizer", "plan_cache_params",',
             'if stmt.name in ("optimizer", "motion_retry_tiers", '
             '"plan_cache_params",')
    rep2 = lint_registry.run(src2)
    assert any(f.key == "plan-cache-guc-stale:motion_retry_tiers"
               for f in rep2.findings), rep2.to_text()


def test_queue_get_timeout_and_thread_join_detected(tmp_path):
    """ISSUE 14 satellite: the PR-11 ready-queue wait (`.get(timeout=)`
    on any receiver) and the PR-12 prefetcher drain (`.join(timeout=)`
    on a thread) are blocking waits; polling variants are clean."""
    from greengage_tpu.analysis import lint_interrupts

    bad = ("def pump(dq):\n"
           "    while True:\n"
           "        item = dq.get(timeout=0.25)\n"
           "def drain(worker_thread):\n"
           "    worker_thread.join(timeout=60.0)\n")
    good = ("def pump(dq, ctx):\n"
            "    while True:\n"
            "        ctx.check()\n"
            "        item = dq.get(timeout=0.25)\n"
            "def drain(worker_thread, ctx):\n"
            "    if not ctx.cancelled:\n"
            "        worker_thread.join(timeout=60.0)\n")
    rep = lint_interrupts.run(_sources(tmp_path / "bad", {"w.py": bad}))
    assert sorted(f.key for f in rep.findings) == \
        ["drain:thread-join", "pump:queue-get"], rep.to_text()
    rep2 = lint_interrupts.run(_sources(tmp_path / "good", {"w.py": good}))
    assert rep2.findings == []


def test_race_witness_runtime():
    """The dynamic half: an injected bare cross-role access under the
    armed witness raises RaceWitnessError naming both roles; the same
    access under a common named lock is clean."""
    import threading

    from greengage_tpu.runtime import lockdebug

    prior = lockdebug.races_enabled()
    lockdebug.enable_races(True)
    try:
        c = lockdebug.shared({}, "test.witness")
        mu = lockdebug.named(threading.Lock(), "test.witness_mu")
        c["x"] = 1               # statement role (MainThread), bare
        got = []

        def bare():
            try:
                c["x"] = 2       # fts role by thread name, bare: races
            except lockdebug.RaceWitnessError as e:
                got.append(e)
        t = threading.Thread(target=bare, name="fts-prober")
        t.start()
        t.join()
        assert got and "fts" in str(got[0]) and "statement" in str(got[0])

        c2 = lockdebug.shared({}, "test.witness_locked")
        with mu:
            c2["x"] = 1
        ok = []

        def locked():
            with mu:
                c2["x"] = 2
            ok.append(True)
        t2 = threading.Thread(target=locked, name="fts-prober")
        t2.start()
        t2.join()
        assert ok, "common named lock must satisfy the witness"
    finally:
        lockdebug.enable_races(prior)


def test_gg_check_list_catalog():
    """`gg check --list` prints every registered check (threads/races
    included) with per-check finding counts; clean tree exits 0."""
    import io
    from contextlib import redirect_stdout

    from greengage_tpu.mgmt import cli

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["check", "--list", "--json"])
    assert rc == 0
    payload = json.loads(buf.getvalue())
    names = {r["check"] for r in payload["checks"]}
    assert {"threads", "races", "locks", "interrupts", "registry",
            "tracer", "imports"} <= names
    assert all(r["findings"] == 0 for r in payload["checks"]), payload
