"""Device operator tests on the virtual CPU mesh, checked against
numpy/pandas oracles (the pg_regress analog at the operator level)."""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from greengage_tpu import expr as E
from greengage_tpu import types as T
from greengage_tpu.ops import agg as agg_ops
from greengage_tpu.ops import hashing as dev_hash
from greengage_tpu.ops import join as join_ops
from greengage_tpu.ops import sort as sort_ops
from greengage_tpu.ops.batch import Batch
from greengage_tpu.ops.expr_eval import Evaluator
from greengage_tpu.storage import native as host_hash


# ---------------------------------------------------------------------------
# hashing: device must match host spec bit-for-bit
# ---------------------------------------------------------------------------

def test_device_hash_matches_host():
    vals = np.array([0, 1, -1, 2**40, -(2**40), 987654321, 2**63 - 1], dtype=np.int64)
    host = host_hash.hash_i64(vals)
    dev = np.asarray(dev_hash.hash_i64(jnp.asarray(vals)))
    assert np.array_equal(host, dev)
    hc = host_hash.hash_combine(host, host[::-1].copy())
    dc = np.asarray(dev_hash.hash_combine(jnp.asarray(host), jnp.asarray(host[::-1].copy())))
    assert np.array_equal(hc, dc)


def test_device_placement_matches_storage():
    vals = np.random.default_rng(0).integers(-(2**60), 2**60, 5000).astype(np.int64)
    host_seg = host_hash.hash_i64(vals) % np.uint32(8)
    dev_seg = np.asarray(dev_hash.segment_of(dev_hash.hash_i64(jnp.asarray(vals)), 8))
    assert np.array_equal(host_seg.astype(np.int32), dev_seg)


# ---------------------------------------------------------------------------
# expression evaluation
# ---------------------------------------------------------------------------

def _batch(**cols):
    arrs = {}
    valids = {}
    for k, v in cols.items():
        if isinstance(v, tuple):
            arrs[k] = jnp.asarray(v[0])
            valids[k] = jnp.asarray(v[1])
        else:
            arrs[k] = jnp.asarray(v)
    return Batch(arrs, valids)


def test_expr_arith_and_decimal():
    # price decimal(2), disc decimal(2): price * (1 - disc) — the Q1 kernel
    price = np.array([10050, 200], dtype=np.int64)     # 100.50, 2.00
    disc = np.array([10, 50], dtype=np.int64)          # 0.10, 0.50
    b = _batch(p=price, d=disc)
    dec2 = T.decimal(2)
    e = E.BinOp("*", E.ColRef("p", dec2),
                E.BinOp("-", E.Literal(100, dec2), E.ColRef("d", dec2), dec2),
                T.arith_result("*", dec2, dec2))
    v, valid = Evaluator(b).value(e)
    assert e.type.scale == 4
    # 100.50*0.90 = 90.45 -> 904500 at scale 4 ; 2.00*0.50=1.00 -> 10000
    assert list(np.asarray(v)) == [904500, 10000]
    assert valid is None


def test_expr_int_division_truncates():
    b = _batch(x=np.array([7, -7, 7], dtype=np.int32), y=np.array([2, 2, 0], dtype=np.int32))
    e = E.BinOp("/", E.ColRef("x", T.INT32), E.ColRef("y", T.INT32),
                T.arith_result("/", T.INT32, T.INT32))
    v, valid = Evaluator(b).value(e)
    assert list(np.asarray(v)[:2]) == [3, -3]
    assert not bool(np.asarray(valid)[2])  # div by zero -> NULL


def test_expr_3vl():
    x = (np.array([1, 0, 0], dtype=np.int32), np.array([True, True, False]))
    b = _batch(x=x)
    gt = E.Cmp(">", E.ColRef("x", T.INT32), E.Literal(0, T.INT32))
    # x > 0 AND false -> false even for NULL x? (false AND null = false)
    e = E.BoolOp("and", (gt, E.Literal(False, T.BOOL)))
    v, valid = Evaluator(b).value(e)
    res = np.asarray(v)
    assert not res.any()
    assert valid is None or np.asarray(valid).all()
    # NULL OR true = true
    e2 = E.BoolOp("or", (gt, E.Literal(True, T.BOOL)))
    v2, valid2 = Evaluator(b).value(e2)
    assert np.asarray(v2).all()
    assert valid2 is None or np.asarray(valid2).all()
    # IS NULL
    v3, _ = Evaluator(b).value(E.IsNull(E.ColRef("x", T.INT32)))
    assert list(np.asarray(v3)) == [False, False, True]


def test_expr_case_and_inlist():
    b = _batch(x=np.array([1, 2, 3], dtype=np.int32))
    e = E.Case(
        whens=((E.Cmp("=", E.ColRef("x", T.INT32), E.Literal(1, T.INT32)),
                E.Literal(10, T.INT32)),),
        else_=E.Literal(0, T.INT32), type=T.INT32)
    v, _ = Evaluator(b).value(e)
    assert list(np.asarray(v)) == [10, 0, 0]
    v2, _ = Evaluator(b).value(E.InList(E.ColRef("x", T.INT32), (1, 3)))
    assert list(np.asarray(v2)) == [True, False, True]


# ---------------------------------------------------------------------------
# hash aggregation vs pandas
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,groups", [(1000, 7), (5000, 230)])
def test_groupby_matches_pandas(n, groups):
    rng = np.random.default_rng(3)
    k1 = rng.integers(0, groups, n).astype(np.int64)
    k2 = rng.integers(0, 3, n).astype(np.int32)
    v = rng.integers(-100, 100, n).astype(np.int64)
    sel = rng.random(n) < 0.8

    keys = [agg_ops.KeySpec(jnp.asarray(k1), None, T.INT64),
            agg_ops.KeySpec(jnp.asarray(k2), None, T.INT32)]
    perm, boundary, sel_sorted, _ = agg_ops.group_sort(keys, jnp.asarray(sel))
    out_cap = n
    vals, valids, srcpos, total = agg_ops.sorted_group_aggregate(
        boundary, sel_sorted,
        [agg_ops.AggSpec("cnt", "count_star", None, None),
         agg_ops.AggSpec("s", "sum", jnp.asarray(v)[perm], None),
         agg_ops.AggSpec("mn", "min", jnp.asarray(v)[perm], None),
         agg_ops.AggSpec("av", "avg", jnp.asarray(v)[perm], None)],
        out_cap)
    G = int(total)
    rep = np.asarray(perm)[np.asarray(srcpos)[:G]]
    got = pd.DataFrame({
        "k1": k1[rep],
        "k2": k2[rep],
        "cnt": np.asarray(vals["cnt"])[:G],
        "s": np.asarray(vals["s"])[:G],
        "mn": np.asarray(vals["mn"])[:G],
        "av": np.asarray(vals["av"])[:G],
    }).sort_values(["k1", "k2"]).reset_index(drop=True)

    df = pd.DataFrame({"k1": k1[sel], "k2": k2[sel], "v": v[sel]})
    want = df.groupby(["k1", "k2"], as_index=False).agg(
        cnt=("v", "size"), s=("v", "sum"), mn=("v", "min"), av=("v", "mean")
    ).sort_values(["k1", "k2"]).reset_index(drop=True)

    assert len(got) == len(want)
    assert np.array_equal(got["k1"], want["k1"])
    assert np.array_equal(got["cnt"], want["cnt"])
    assert np.array_equal(got["s"], want["s"])
    assert np.array_equal(got["mn"], want["mn"])
    assert np.allclose(got["av"], want["av"])


def test_groupby_null_keys_merge():
    k = np.array([1, 1, 2, 0, 0], dtype=np.int64)
    kv = np.array([True, True, True, False, False])
    sel = np.ones(5, dtype=bool)
    perm, boundary, sel_sorted, _ = agg_ops.group_sort(
        [agg_ops.KeySpec(jnp.asarray(k), jnp.asarray(kv), T.INT64)],
        jnp.asarray(sel))
    assert int(np.asarray(boundary).sum()) == 3  # groups: 1, 2, NULL
    vals, _, srcpos, total = agg_ops.sorted_group_aggregate(
        boundary, sel_sorted,
        [agg_ops.AggSpec("c", "count_star", None, None)], 5)
    cnts = sorted(np.asarray(vals["c"])[:int(total)].tolist())
    assert cnts == [1, 2, 2]


def test_groupby_dead_rows_excluded():
    # dead rows must neither form groups nor leak into neighbors' aggregates
    k = np.array([5, 5, 7, 7, 9], dtype=np.int64)
    sel = np.array([True, False, True, True, False])
    perm, boundary, sel_sorted, _ = agg_ops.group_sort(
        [agg_ops.KeySpec(jnp.asarray(k), None, T.INT64)], jnp.asarray(sel))
    assert int(np.asarray(boundary).sum()) == 2  # groups 5 and 7 only
    v = jnp.asarray(np.array([1, 100, 2, 3, 100], dtype=np.int64))[perm]
    vals, _, srcpos, total = agg_ops.sorted_group_aggregate(
        boundary, sel_sorted, [agg_ops.AggSpec("s", "sum", v, None)], 5)
    got = sorted(np.asarray(vals["s"])[:int(total)].tolist())
    assert got == [1, 5]


# ---------------------------------------------------------------------------
# hash join vs pandas
# ---------------------------------------------------------------------------

def test_hash_join_pk_fk():
    rng = np.random.default_rng(5)
    nb, np_ = 300, 2000
    bkey = rng.permutation(1000)[:nb].astype(np.int64)   # unique build keys
    bval = rng.integers(0, 50, nb).astype(np.int64)
    pkey = rng.integers(0, 1000, np_).astype(np.int64)
    psel = rng.random(np_) < 0.9

    table = join_ops.build(
        [agg_ops.KeySpec(jnp.asarray(bkey), None, T.INT64)],
        jnp.ones(nb, dtype=bool), 1024, 8)
    assert not bool(table.overflow) and not bool(table.dup)
    matched, brow, walk_ov = join_ops.probe(
        table, [agg_ops.KeySpec(jnp.asarray(pkey), None, T.INT64)],
        jnp.asarray(psel), 8)
    assert not bool(walk_ov)

    bcols, bvalids = join_ops.gather_build_columns(
        {"bval": jnp.asarray(bval)}, {}, brow, matched)

    df = pd.merge(
        pd.DataFrame({"pkey": pkey[psel]}),
        pd.DataFrame({"bkey": bkey, "bval": bval}),
        left_on="pkey", right_on="bkey", how="inner")
    m = np.asarray(matched)
    assert m.sum() == len(df)
    got = np.sort(np.asarray(bcols["bval"])[m])
    assert np.array_equal(got, np.sort(df["bval"].to_numpy()))


def test_hash_join_duplicate_build_detected():
    bkey = np.array([1, 2, 2, 3], dtype=np.int64)
    table = join_ops.build(
        [agg_ops.KeySpec(jnp.asarray(bkey), None, T.INT64)],
        jnp.ones(4, dtype=bool), 16, 4)
    assert bool(table.dup)


def test_hash_join_null_keys_never_match():
    bkey = np.array([1, 2], dtype=np.int64)
    table = join_ops.build([agg_ops.KeySpec(jnp.asarray(bkey), None, T.INT64)],
                           jnp.ones(2, dtype=bool), 8, 4)
    pkey = np.array([1, 0], dtype=np.int64)
    pvalid = np.array([True, False])
    matched, _, _ = join_ops.probe(
        table, [agg_ops.KeySpec(jnp.asarray(pkey), jnp.asarray(pvalid), T.INT64)],
        jnp.ones(2, dtype=bool), 4)
    assert list(np.asarray(matched)) == [True, False]


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def test_sort_multi_key_desc_nulls():
    a = np.array([3, 1, 2, 1, 9], dtype=np.int64)
    av = np.array([True, True, True, True, False])
    bcol = np.array([1.5, -2.0, 0.0, 7.0, 0.0])
    sel = np.array([True, True, True, True, True])
    keys = [
        sort_ops.SortKey(jnp.asarray(a), jnp.asarray(av), T.INT64, desc=False),
        sort_ops.SortKey(jnp.asarray(bcol), None, T.FLOAT64, desc=True),
    ]
    perm, sel_sorted, _ = sort_ops.sort_batch(keys, jnp.asarray(sel), 5)
    order = np.asarray(perm)
    # asc on a (nulls last), desc on b: (1,7.0),(1,-2.0),(2,0.0),(3,1.5),(null)
    assert list(a[order][:4]) == [1, 1, 2, 3]
    assert list(bcol[order][:2]) == [7.0, -2.0]
    assert not av[order][4]


def test_sort_dead_rows_pushed_back_and_limit():
    x = np.array([5, 4, 3, 2, 1], dtype=np.int64)
    sel = np.array([True, False, True, False, True])
    keys = [sort_ops.SortKey(jnp.asarray(x), None, T.INT64)]
    perm, sel_sorted, _ = sort_ops.sort_batch(keys, jnp.asarray(sel), 5)
    assert list(np.asarray(sel_sorted)) == [True, True, True, False, False]
    assert list(x[np.asarray(perm)][:3]) == [1, 3, 5]
    cols, valids, s = sort_ops.limit({"x": jnp.asarray(x)[np.asarray(perm)]}, {}, sel_sorted, 2)
    assert list(np.asarray(cols["x"])) == [1, 3]


# ---------------------------------------------------------------------------
# packed group sort (stats-bounded keys in one uint64 operand)
# ---------------------------------------------------------------------------

def test_packed_group_sort_matches_unpacked():
    import pandas as pd

    rng = np.random.default_rng(9)
    n = 5000
    k1 = rng.integers(-37, 4000, n).astype(np.int64)
    k2 = rng.integers(0, 12, n).astype(np.int32)
    kv2 = rng.random(n) < 0.9          # k2 nullable
    v = rng.integers(-100, 100, n).astype(np.int64)
    sel = rng.random(n) < 0.8
    keys = [agg_ops.KeySpec(jnp.asarray(k1), None, T.INT64),
            agg_ops.KeySpec(jnp.asarray(k2), jnp.asarray(kv2), T.INT32)]
    bounds = [(-37, 3999), (0, 11)]
    assert agg_ops.pack_bits(bounds) is not None

    perm, boundary, sel_sorted, viol = agg_ops.group_sort(
        keys, jnp.asarray(sel), bounds)
    assert viol is not None and not bool(viol)
    vals, _, srcpos, total = agg_ops.sorted_group_aggregate(
        boundary, sel_sorted,
        [agg_ops.AggSpec("c", "count_star", None, None),
         agg_ops.AggSpec("s", "sum", jnp.asarray(v)[perm], None)], n)
    G = int(total)
    rep = np.asarray(perm)[np.asarray(srcpos)[:G]]
    got = pd.DataFrame({
        "k1": k1[rep], "k2": np.where(kv2[rep], k2[rep], -999),
        "c": np.asarray(vals["c"])[:G], "s": np.asarray(vals["s"])[:G],
    }).sort_values(["k1", "k2"]).reset_index(drop=True)
    df = pd.DataFrame({"k1": k1[sel], "k2": np.where(kv2, k2, -999)[sel],
                       "v": v[sel]})
    want = df.groupby(["k1", "k2"], as_index=False).agg(
        c=("v", "size"), s=("v", "sum")).sort_values(
        ["k1", "k2"]).reset_index(drop=True)
    assert len(got) == len(want)
    assert np.array_equal(got["k1"], want["k1"])
    assert np.array_equal(got["k2"], want["k2"])
    assert np.array_equal(got["c"], want["c"])
    assert np.array_equal(got["s"], want["s"])


def test_packed_group_sort_flags_bounds_violation():
    k = np.array([5, 100, 7], dtype=np.int64)   # 100 outside (0, 63)
    keys = [agg_ops.KeySpec(jnp.asarray(k), None, T.INT64)]
    _, _, _, viol = agg_ops.group_sort(
        keys, jnp.asarray(np.ones(3, bool)), [(0, 63)])
    assert bool(viol)
    # dead rows outside bounds do NOT trip the flag
    _, _, _, viol2 = agg_ops.group_sort(
        keys, jnp.asarray(np.array([True, False, True])), [(0, 63)])
    assert not bool(viol2)


def test_pack_bits_budget():
    assert agg_ops.pack_bits([(0, 2**40), (0, 2**30)]) is None  # > 63 bits
    assert agg_ops.pack_bits([(0, 2**40), (0, 2**20)]) is not None
    assert agg_ops.pack_bits([(0, 0)]) == 1
    assert agg_ops.pack_bits([None]) is None
    assert agg_ops.pack_bits([]) is None


def test_packed_join_matches_unpacked():
    rng = np.random.default_rng(21)
    nb, np_ = 500, 3000
    bkey = rng.permutation(5000)[:nb].astype(np.int64) - 250  # unique, offset
    pkey = rng.integers(-400, 5200, np_).astype(np.int64)
    bounds = [(int(bkey.min()), int(bkey.max()))]
    bs = [agg_ops.KeySpec(jnp.asarray(bkey), None, T.INT64)]
    ps = [agg_ops.KeySpec(jnp.asarray(pkey), None, T.INT64)]
    sel_b = jnp.ones(nb, bool)
    sel_p = jnp.ones(np_, bool)
    for kb in (None, bounds):
        table = join_ops.build(bs, sel_b, 2048, 64, kb)
        if kb is not None:
            assert table.bounds is not None and not bool(table.pack_viol)
        matched, brow, ov = join_ops.probe(table, ps, sel_p, 64)
        assert not bool(ov)
        want = np.isin(pkey, bkey)
        assert np.array_equal(np.asarray(matched), want)
        hit = np.asarray(matched)
        assert np.array_equal(bkey[np.asarray(brow)[hit]], pkey[hit])


def test_packed_join_build_violation_flag():
    bkey = np.array([1, 2, 99], dtype=np.int64)   # 99 outside stale (0, 10)
    bs = [agg_ops.KeySpec(jnp.asarray(bkey), None, T.INT64)]
    table = join_ops.build(bs, jnp.ones(3, bool), 64, 16, [(0, 10)])
    assert bool(table.pack_viol)


def test_packed_order_sort_matches_unpacked():
    from greengage_tpu.ops import sort as sort_ops

    rng = np.random.default_rng(33)
    n = 4000
    a = rng.integers(-50, 1000, n).astype(np.int64)
    b = rng.integers(0, 90, n).astype(np.int32)
    bv = rng.random(n) < 0.85
    sel = rng.random(n) < 0.9
    for desc_a, desc_b, nf in ((False, False, None), (True, False, None),
                               (False, True, True), (True, True, False)):
        keys = [sort_ops.SortKey(jnp.asarray(a), None, T.INT64, desc=desc_a),
                sort_ops.SortKey(jnp.asarray(b), jnp.asarray(bv), T.INT32,
                                 desc=desc_b, nulls_first=nf)]
        bounds = [(-50, 999), (0, 89)]
        p1, s1, viol = sort_ops.sort_batch(keys, jnp.asarray(sel), n, bounds)
        assert viol is not None and not bool(viol)
        p2, s2, v2 = sort_ops.sort_batch(keys, jnp.asarray(sel), n)
        assert v2 is None
        # same live set, identical key order (perm may differ only where
        # rows tie on every key INCLUDING null state -> compare key tuples)
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
        k1a, k1b = a[np.asarray(p1)], b[np.asarray(p1)]
        k2a, k2b = a[np.asarray(p2)], b[np.asarray(p2)]
        v1b, v2b = bv[np.asarray(p1)], bv[np.asarray(p2)]
        live = np.asarray(s1)
        assert np.array_equal(k1a[live], k2a[live])
        assert np.array_equal(v1b[live], v2b[live])
        assert np.array_equal(k1b[live & v1b], k2b[live & v2b])
