"""Regression tests for the round-1 advisor findings (ADVICE.md).

Each test pins a verified bug: scalar aggregate over a SINGLE_QE child,
cross-table TEXT equi-joins, LIMIT 0, the dictionary hash sentinel row, and
DECIMAL division rounding.
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.storage.dictionary import Dictionary
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.002)
    return d


# ---------------------------------------------------------------------------
# high: scalar aggregate over a SINGLE_QE child (top-N-then-aggregate)
# ---------------------------------------------------------------------------

def test_scalar_agg_over_subquery_limit(db):
    r = db.sql("select count(*) from "
               "(select l_orderkey from lineitem order by l_orderkey limit 2) q")
    assert r.rows() == [(2,)]


def test_scalar_agg_over_subquery_limit_sum(db):
    sub = db.sql("select l_orderkey from lineitem order by l_orderkey, l_linenumber limit 3")
    want = sum(row[0] for row in sub.rows())
    r = db.sql("select sum(l_orderkey), count(*), min(l_orderkey) from "
               "(select l_orderkey, l_linenumber from lineitem "
               "order by l_orderkey, l_linenumber limit 3) q")
    assert r.rows() == [(want, 3, sub.rows()[0][0])]


# ---------------------------------------------------------------------------
# high: cross-table TEXT equi-join (translated codes need the left dict LUT)
# ---------------------------------------------------------------------------

def test_cross_table_text_join(db):
    db.sql("create table txj_a (k text, v int) distributed by (k);"
           "create table txj_b (k text, w int) distributed by (k)")
    db.sql("insert into txj_a values ('apple', 1), ('pear', 2), ('plum', 3)")
    # 'kiwi' is absent from txj_a's dictionary -> translated code -1
    db.sql("insert into txj_b values ('pear', 10), ('apple', 20), ('kiwi', 30)")
    r = db.sql("select a.k, a.v, b.w from txj_a a join txj_b b on a.k = b.k "
               "order by a.k")
    assert r.rows() == [("apple", 1, 20), ("pear", 2, 10)]
    # and with the text key flowing through a redistribute motion (group by)
    r = db.sql("select a.k, count(*) from txj_a a join txj_b b on a.k = b.k "
               "group by a.k order by a.k")
    assert r.rows() == [("apple", 1), ("pear", 1)]


# ---------------------------------------------------------------------------
# medium: LIMIT 0
# ---------------------------------------------------------------------------

def test_limit_zero_toplevel(db):
    r = db.sql("select l_orderkey from lineitem limit 0")
    assert len(r) == 0
    assert r.rows() == []


def test_limit_zero_derived(db):
    r = db.sql("select count(*) from (select l_orderkey from lineitem limit 0) q")
    assert r.rows() == [(0,)]


def test_buried_limit_offset(db):
    """A LIMIT/OFFSET inside a derived table must drop the offset prefix on
    device (no host trim applies there) — r2 code-review finding."""
    r = db.sql("select o_orderkey from "
               "(select o_orderkey from orders order by o_orderkey "
               " limit 5 offset 3) q order by o_orderkey")
    assert [row[0] for row in r.rows()] == [4, 5, 6, 7, 8]
    r = db.sql("select count(*) from "
               "(select o_orderkey from orders order by o_orderkey "
               " limit 5 offset 3) q")
    assert r.rows() == [(5,)]
    # offset with no limit
    r = db.sql("select count(*) from "
               "(select o_orderkey from orders order by o_orderkey offset 10) q")
    total = db.sql("select count(*) from orders").rows()[0][0]
    assert r.rows() == [(total - 10,)]


# ---------------------------------------------------------------------------
# low: dictionary hash LUT sentinel row for code -1
# ---------------------------------------------------------------------------

def test_dictionary_hash_sentinel():
    d = Dictionary(["a", "b", "c"])
    h = d.hashes()
    assert len(h) == len(d) + 1
    # code -1 must hit the sentinel (0), not wrap to the last real entry
    assert h[-1] == 0
    codes = np.array([0, 2, -1], dtype=np.int32)
    picked = h[codes]
    assert picked[2] == 0 and picked[1] == h[2]


# ---------------------------------------------------------------------------
# low: DECIMAL division rounds half away from zero (PG numeric semantics)
# ---------------------------------------------------------------------------

def test_decimal_division_rounding(db):
    db.sql("create table decdiv (k int, q decimal(12,2)) distributed by (k)")
    db.sql("insert into decdiv values (1, 1.00), (2, 5.00), (3, -1.00)")
    # result scale is max(sa, 6); these quotients land EXACTLY on .5 at the
    # 6th fractional digit in float64 (verified): 1.00/2000000*1e6 == 0.5,
    # 5.00/2000000*1e6 == 2.5. Half-away-from-zero rounds them up;
    # half-to-even (the old jnp.round) would give 0 and 2.
    r = db.sql("select k, q / 2000000 from decdiv order by k")
    got = [row[1] for row in r.rows()]
    assert abs(got[0] - 1e-6) < 1e-12, got
    assert abs(got[1] - 3e-6) < 1e-12, got
    assert abs(got[2] - (-1e-6)) < 1e-12, got


def test_decimal_division_by_zero_is_null(db):
    r = db.sql("select k, q / 0 from decdiv order by k")
    assert all(row[1] is None for row in r.rows())
