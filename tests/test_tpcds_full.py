"""Full TPC-DS queries vs pandas oracles (ISSUE 13 / ROADMAP item 4).

26 queries from the official TPC-DS set (Q3, Q7, Q12, Q13, Q15, Q19,
Q20, Q21, Q26, Q27, Q32, Q37, Q42, Q43, Q48, Q52, Q55, Q61, Q62, Q65,
Q68, Q73, Q82, Q89, Q96, Q98) over the dsdgen-lite star schema
(utils/tpcds.py: three sales channels + inventory over 12 shared
dimensions), each verified row-for-row against a pandas oracle. Values
are tuned to the generated data's ranges; two structural adaptations are
applied where the engine's binder requires them and semantics are
unchanged: (a) join equalities that the official text repeats inside
every OR branch (Q13/Q48) are hoisted to top-level conjuncts, (b) a few
ORDER BYs gain trailing tiebreaker columns so LIMIT boundaries are
deterministic against the oracle.

The scalar work these queries carry (d_year/d_moy date math, substr
grouping, CASE buckets, coalesce-class NULL handling, decimal division)
runs inside the fused device programs — test_scalar_funcs.py asserts
that fusion directly; here the *answers* are the contract."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.utils import tpcds

SCALE = 1.0


def _day(s):
    return int((np.datetime64(s) - np.datetime64("1970-01-01")).astype(int))


@pytest.fixture(scope="module")
def env(devices8):
    d = greengage_tpu.connect(numsegments=4)
    tpcds.load(d, SCALE)
    d.sql("analyze")
    dfs = tpcds.to_pandas(tpcds.generate(SCALE))
    return d, dfs


def _rows(r):
    out = []
    for row in r.rows():
        out.append(tuple(None if v is None
                         else (v.item() if hasattr(v, "item") else v)
                         for v in row))
    return out


def _check(got, want_df, approx_cols=(), rel=1e-9):
    """Row-for-row comparison of engine rows vs an oracle frame (already
    sorted/limited). approx_cols = positional indexes compared with
    pytest.approx (float aggregates)."""
    assert len(got) == len(want_df), (len(got), len(want_df))
    for row, (_, w) in zip(got, want_df.iterrows()):
        wvals = list(w)
        assert len(row) == len(wvals)
        for i, (g, e) in enumerate(zip(row, wvals)):
            if e is None or (isinstance(e, float) and np.isnan(e)):
                assert g is None, (i, row, wvals)
            elif i in approx_cols:
                assert g == pytest.approx(e, rel=rel, abs=1e-6), (i, row, wvals)
            else:
                assert g == e, (i, row, wvals)


def _nlast(df, by, ascending=None):
    return df.sort_values(by, ascending=ascending if ascending is not None
                          else [True] * len(by),
                          na_position="last", kind="mergesort")


# ----------------------------------------------------------------------
# reporting-class star joins
# ----------------------------------------------------------------------

def test_q3_brand_by_year(env):
    d, f = env
    got = _rows(d.sql("""
      select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
             sum(ss_ext_sales_price) sum_agg
      from date_dim dt, store_sales, item
      where dt.d_date_sk = store_sales.ss_sold_date_sk
        and store_sales.ss_item_sk = item.i_item_sk
        and item.i_manufact_id = 28 and dt.d_moy = 12
      group by dt.d_year, item.i_brand_id, item.i_brand
      order by dt.d_year, sum_agg desc, brand_id limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manufact_id == 28) & (j.d_moy == 12)]
    w = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
          .ss_ext_sales_price.sum())
    w = _nlast(w, ["d_year", "ss_ext_sales_price", "i_brand_id"],
               [True, False, True]).head(100)
    _check(got, w, approx_cols=(3,))


def test_q42_category_by_year(env):
    d, f = env
    got = _rows(d.sql("""
      select dt.d_year, item.i_category_id, item.i_category,
             sum(ss_ext_sales_price)
      from date_dim dt, store_sales, item
      where dt.d_date_sk = store_sales.ss_sold_date_sk
        and store_sales.ss_item_sk = item.i_item_sk
        and item.i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
      group by dt.d_year, item.i_category_id, item.i_category
      order by sum(ss_ext_sales_price) desc, dt.d_year, item.i_category_id,
               item.i_category limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    w = (j.groupby(["d_year", "i_category_id", "i_category"], as_index=False)
          .ss_ext_sales_price.sum())
    w = _nlast(w, ["ss_ext_sales_price", "d_year", "i_category_id",
                   "i_category"], [False, True, True, True]).head(100)
    w = w[["d_year", "i_category_id", "i_category", "ss_ext_sales_price"]]
    _check(got, w, approx_cols=(3,))


def test_q52_brand_by_year(env):
    d, f = env
    got = _rows(d.sql("""
      select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
             sum(ss_ext_sales_price) ext_price
      from date_dim dt, store_sales, item
      where dt.d_date_sk = store_sales.ss_sold_date_sk
        and store_sales.ss_item_sk = item.i_item_sk
        and item.i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
      group by dt.d_year, item.i_brand, item.i_brand_id
      order by dt.d_year, ext_price desc, brand_id limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    w = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
          .ss_ext_sales_price.sum())
    w = _nlast(w, ["d_year", "ss_ext_sales_price", "i_brand_id"],
               [True, False, True]).head(100)
    w = w[["d_year", "i_brand_id", "i_brand", "ss_ext_sales_price"]]
    _check(got, w, approx_cols=(3,))


def test_q55_brand_revenue(env):
    d, f = env
    got = _rows(d.sql("""
      select i_brand_id brand_id, i_brand brand,
             sum(ss_ext_sales_price) ext_price
      from date_dim, store_sales, item
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 28 and d_moy = 11 and d_year = 1999
      group by i_brand, i_brand_id
      order by ext_price desc, brand_id limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manager_id == 28) & (j.d_moy == 11) & (j.d_year == 1999)]
    w = (j.groupby(["i_brand_id", "i_brand"], as_index=False)
          .ss_ext_sales_price.sum())
    w = _nlast(w, ["ss_ext_sales_price", "i_brand_id"],
               [False, True]).head(100)
    _check(got, w, approx_cols=(2,))


# ----------------------------------------------------------------------
# demographics-filtered averages
# ----------------------------------------------------------------------

def _q7_oracle(f):
    j = (f["store_sales"]
         .merge(f["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(f["promotion"], left_on="ss_promo_sk", right_on="p_promo_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    w = (j.groupby("i_item_id", as_index=False)
          .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
               agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean")))
    return _nlast(w, ["i_item_id"]).head(100)


def test_q7_promo_demographics(env):
    d, f = env
    got = _rows(d.sql("""
      select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
             avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
      from store_sales, customer_demographics, date_dim, item, promotion
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
        and cd_gender = 'M' and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and (p_channel_email = 'N' or p_channel_event = 'N')
        and d_year = 2000
      group by i_item_id order by i_item_id limit 100"""))
    _check(got, _q7_oracle(f), approx_cols=(1, 2, 3, 4))


def test_q26_catalog_demographics(env):
    d, f = env
    got = _rows(d.sql("""
      select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
             avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
      from catalog_sales, customer_demographics, date_dim, item, promotion
      where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
        and cd_gender = 'F' and cd_marital_status = 'W'
        and cd_education_status = 'Primary'
        and (p_channel_email = 'N' or p_channel_event = 'N')
        and d_year = 2000
      group by i_item_id order by i_item_id limit 100"""))
    j = (f["catalog_sales"]
         .merge(f["customer_demographics"], left_on="cs_bill_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(f["date_dim"], left_on="cs_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="cs_item_sk", right_on="i_item_sk")
         .merge(f["promotion"], left_on="cs_promo_sk", right_on="p_promo_sk"))
    j = j[(j.cd_gender == "F") & (j.cd_marital_status == "W")
          & (j.cd_education_status == "Primary")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    w = (j.groupby("i_item_id", as_index=False)
          .agg(agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
               agg3=("cs_coupon_amt", "mean"), agg4=("cs_sales_price", "mean")))
    w = _nlast(w, ["i_item_id"]).head(100)
    _check(got, w, approx_cols=(1, 2, 3, 4))


def test_q27_rollup_demographics(env):
    d, f = env
    got = _rows(d.sql("""
      select i_item_id, s_state, grouping(s_state) g_state,
             avg(ss_quantity) agg1, avg(ss_list_price) agg2,
             avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
      from store_sales, customer_demographics, date_dim, store, item
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
        and cd_gender = 'M' and cd_marital_status = 'S'
        and cd_education_status = 'College' and d_year = 2002
        and s_state in ('CA', 'TX', 'NY', 'OH')
      group by rollup (i_item_id, s_state)
      order by i_item_id, s_state limit 100"""))
    j = (f["store_sales"]
         .merge(f["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College") & (j.d_year == 2002)
          & j.s_state.isin(["CA", "TX", "NY", "OH"])]
    levels = []
    leaf = (j.groupby(["i_item_id", "s_state"], as_index=False)
             .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                  agg3=("ss_coupon_amt", "mean"),
                  agg4=("ss_sales_price", "mean")))
    leaf.insert(2, "g_state", 0)
    levels.append(leaf)
    mid = (j.groupby("i_item_id", as_index=False)
            .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                 agg3=("ss_coupon_amt", "mean"),
                 agg4=("ss_sales_price", "mean")))
    mid.insert(1, "s_state", None)
    mid.insert(2, "g_state", 1)
    levels.append(mid)
    if len(j):
        top = pd.DataFrame([{
            "i_item_id": None, "s_state": None, "g_state": 1,
            "agg1": j.ss_quantity.mean(), "agg2": j.ss_list_price.mean(),
            "agg3": j.ss_coupon_amt.mean(), "agg4": j.ss_sales_price.mean()}])
        levels.append(top)
    w = _nlast(pd.concat(levels, ignore_index=True),
               ["i_item_id", "s_state"]).head(100)
    _check(got, w, approx_cols=(3, 4, 5, 6))


# ----------------------------------------------------------------------
# channel revenue-share windows (Q12 / Q20 / Q98)
# ----------------------------------------------------------------------

_Q12_SQL = """
  select i_item_id, i_item_desc, i_category, i_class, i_current_price,
         sum({v}_ext_sales_price) as itemrevenue,
         sum({v}_ext_sales_price) * 100 /
           sum(sum({v}_ext_sales_price)) over (partition by i_class)
           as revenueratio
  from {t}, item, date_dim
  where {v}_item_sk = i_item_sk
    and i_category in ('Sports', 'Books', 'Home')
    and {v}_sold_date_sk = d_date_sk
    and d_date between cast('{d0}' as date) and (cast('{d0}' as date) + 30 days)
  group by i_item_id, i_item_desc, i_category, i_class, i_current_price
  order by i_category, i_class, i_item_id, i_item_desc, revenueratio"""


def _share_oracle(f, tab, v, d0):
    j = (f[tab]
         .merge(f["item"], left_on=f"{v}_item_sk", right_on="i_item_sk")
         .merge(f["date_dim"], left_on=f"{v}_sold_date_sk",
                right_on="d_date_sk"))
    j = j[j.i_category.isin(["Sports", "Books", "Home"])
          & (j.d_date >= _day(d0)) & (j.d_date <= _day(d0) + 30)]
    w = (j.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                    "i_current_price"], as_index=False)
          [f"{v}_ext_sales_price"].sum()
          .rename(columns={f"{v}_ext_sales_price": "itemrevenue"}))
    w["revenueratio"] = (w.itemrevenue * 100
                         / w.groupby("i_class").itemrevenue.transform("sum"))
    w = _nlast(w, ["i_category", "i_class", "i_item_id", "i_item_desc",
                   "revenueratio"])
    return w[["i_item_id", "i_item_desc", "i_category", "i_class",
              "i_current_price", "itemrevenue", "revenueratio"]]


def test_q12_web_revenue_share(env):
    d, f = env
    got = _rows(d.sql(_Q12_SQL.format(t="web_sales", v="ws", d0="1999-02-22")))
    _check(got, _share_oracle(f, "web_sales", "ws", "1999-02-22"),
           approx_cols=(5, 6), rel=1e-5)


def test_q20_catalog_revenue_share(env):
    d, f = env
    got = _rows(d.sql(_Q12_SQL.format(t="catalog_sales", v="cs",
                                      d0="2000-03-10")))
    _check(got, _share_oracle(f, "catalog_sales", "cs", "2000-03-10"),
           approx_cols=(5, 6), rel=1e-5)


def test_q98_store_revenue_share(env):
    d, f = env
    got = _rows(d.sql(_Q12_SQL.format(t="store_sales", v="ss",
                                      d0="2001-01-12")))
    _check(got, _share_oracle(f, "store_sales", "ss", "2001-01-12"),
           approx_cols=(5, 6), rel=1e-5)


# ----------------------------------------------------------------------
# OR-heavy single-row aggregates (Q13 / Q48)
# ----------------------------------------------------------------------

def test_q13_triple_or_averages(env):
    d, f = env
    got = _rows(d.sql("""
      select avg(ss_quantity), avg(ss_ext_sales_price),
             avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
      from store_sales, store, customer_demographics,
           household_demographics, customer_address, date_dim
      where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
        and d_year = 2001
        and ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ((cd_marital_status = 'M'
              and cd_education_status = 'Advanced Degree'
              and ss_sales_price between 10.00 and 50.00
              and hd_dep_count = 3)
          or (cd_marital_status = 'S' and cd_education_status = 'College'
              and ss_sales_price between 5.00 and 30.00
              and hd_dep_count = 1)
          or (cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
              and ss_sales_price between 15.00 and 60.00
              and hd_dep_count = 1))
        and ((ca_state in ('TX', 'OH', 'CA')
              and ss_net_profit between 100 and 200)
          or (ca_state in ('IL', 'NY', 'GA')
              and ss_net_profit between 150 and 300)
          or (ca_state in ('WA', 'TN') and ss_net_profit between 50 and 250))
      """))
    j = (f["store_sales"]
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(f["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(f["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(f["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
         .merge(f["date_dim"], left_on="ss_sold_date_sk",
                right_on="d_date_sk"))
    j = j[(j.d_year == 2001) & (j.ca_country == "United States")]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "Advanced Degree")
             & j.ss_sales_price.between(10.0, 50.0) & (j.hd_dep_count == 3))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(5.0, 30.0) & (j.hd_dep_count == 1))
            | ((j.cd_marital_status == "W")
               & (j.cd_education_status == "2 yr Degree")
               & j.ss_sales_price.between(15.0, 60.0)
               & (j.hd_dep_count == 1)))
    addr = ((j.ca_state.isin(["TX", "OH", "CA"])
             & j.ss_net_profit.between(100, 200))
            | (j.ca_state.isin(["IL", "NY", "GA"])
               & j.ss_net_profit.between(150, 300))
            | (j.ca_state.isin(["WA", "TN"])
               & j.ss_net_profit.between(50, 250)))
    j = j[demo & addr]
    assert len(got) == 1
    if len(j) == 0:
        assert got[0] == (None, None, None, None)
    else:
        want = (j.ss_quantity.mean(), j.ss_ext_sales_price.mean(),
                j.ss_ext_wholesale_cost.mean(), j.ss_ext_wholesale_cost.sum())
        for g, e in zip(got[0], want):
            assert g == pytest.approx(e, rel=1e-9)


def test_q48_quantity_sum_or_blocks(env):
    d, f = env
    got = _rows(d.sql("""
      select sum(ss_quantity)
      from store_sales, store, customer_demographics,
           customer_address, date_dim
      where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
        and d_year = 2000
        and cd_demo_sk = ss_cdemo_sk and ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ((cd_marital_status = 'M' and cd_education_status = '4 yr Degree'
              and ss_sales_price between 10.00 and 50.00)
          or (cd_marital_status = 'D' and cd_education_status = '2 yr Degree'
              and ss_sales_price between 5.00 and 35.00)
          or (cd_marital_status = 'S' and cd_education_status = 'College'
              and ss_sales_price between 15.00 and 60.00))
        and ((ca_state in ('CA', 'OH', 'TX')
              and ss_net_profit between 0 and 2000)
          or (ca_state in ('IL', 'NY', 'GA')
              and ss_net_profit between 150 and 3000)
          or (ca_state in ('WA', 'TN') and ss_net_profit between 50 and 2500))
      """))
    j = (f["store_sales"]
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(f["customer_demographics"], left_on="ss_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(f["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk")
         .merge(f["date_dim"], left_on="ss_sold_date_sk",
                right_on="d_date_sk"))
    j = j[(j.d_year == 2000) & (j.ca_country == "United States")]
    demo = (((j.cd_marital_status == "M")
             & (j.cd_education_status == "4 yr Degree")
             & j.ss_sales_price.between(10.0, 50.0))
            | ((j.cd_marital_status == "D")
               & (j.cd_education_status == "2 yr Degree")
               & j.ss_sales_price.between(5.0, 35.0))
            | ((j.cd_marital_status == "S")
               & (j.cd_education_status == "College")
               & j.ss_sales_price.between(15.0, 60.0)))
    addr = ((j.ca_state.isin(["CA", "OH", "TX"])
             & j.ss_net_profit.between(0, 2000))
            | (j.ca_state.isin(["IL", "NY", "GA"])
               & j.ss_net_profit.between(150, 3000))
            | (j.ca_state.isin(["WA", "TN"])
               & j.ss_net_profit.between(50, 2500)))
    j = j[demo & addr]
    want = None if len(j) == 0 else int(j.ss_quantity.sum())
    assert got == [(want,)]


# ----------------------------------------------------------------------
# zip/substr shapes (Q15 / Q19 / Q62)
# ----------------------------------------------------------------------

def test_q15_catalog_by_zip(env):
    d, f = env
    zips = "'81', '82', '83', '84', '8100', '8101', '8102', '8103', '8104'"
    got = _rows(d.sql(f"""
      select ca_zip, sum(cs_sales_price)
      from catalog_sales, customer, customer_address, date_dim
      where cs_bill_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk
        and (substr(ca_zip, 1, 5) in ({zips})
             or ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 500)
        and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
      group by ca_zip order by ca_zip limit 100"""))
    zlist = [z.strip().strip("'") for z in zips.split(",")]
    j = (f["catalog_sales"]
         .merge(f["customer"], left_on="cs_bill_customer_sk",
                right_on="c_customer_sk")
         .merge(f["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
         .merge(f["date_dim"], left_on="cs_sold_date_sk",
                right_on="d_date_sk"))
    j = j[(j.d_qoy == 2) & (j.d_year == 2001)
          & (j.ca_zip.str[:5].isin(zlist)
             | j.ca_state.isin(["CA", "WA", "GA"])
             | (j.cs_sales_price > 500))]
    w = _nlast(j.groupby("ca_zip", as_index=False).cs_sales_price.sum(),
               ["ca_zip"]).head(100)
    _check(got, w, approx_cols=(1,))


def test_q19_brand_cross_zip(env):
    d, f = env
    got = _rows(d.sql("""
      select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
             sum(ss_ext_sales_price) ext_price
      from date_dim, store_sales, item, customer, customer_address, store
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 8 and d_moy = 11 and d_year = 1998
        and ss_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk
        and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
        and ss_store_sk = s_store_sk
      group by i_brand, i_brand_id, i_manufact_id, i_manufact
      order by ext_price desc, brand, i_brand_id, i_manufact_id, i_manufact
      limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(f["customer"], left_on="ss_customer_sk",
                right_on="c_customer_sk")
         .merge(f["customer_address"], left_on="c_current_addr_sk",
                right_on="ca_address_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[(j.i_manager_id == 8) & (j.d_moy == 11) & (j.d_year == 1998)
          & (j.ca_zip.str[:5] != j.s_zip.str[:5])]
    w = (j.groupby(["i_brand_id", "i_brand", "i_manufact_id", "i_manufact"],
                   as_index=False).ss_ext_sales_price.sum())
    w = _nlast(w, ["ss_ext_sales_price", "i_brand", "i_brand_id",
                   "i_manufact_id", "i_manufact"],
               [False, True, True, True, True]).head(100)
    w = w[["i_brand_id", "i_brand", "i_manufact_id", "i_manufact",
           "ss_ext_sales_price"]]
    _check(got, w, approx_cols=(4,))


def test_q62_ship_latency_buckets(env):
    d, f = env
    got = _rows(d.sql("""
      select substr(w_warehouse_name, 1, 20), sm_type, web_name,
        sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
                 then 1 else 0 end) as d30,
        sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
                  and (ws_ship_date_sk - ws_sold_date_sk <= 60)
                 then 1 else 0 end) as d60,
        sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
                  and (ws_ship_date_sk - ws_sold_date_sk <= 90)
                 then 1 else 0 end) as d90,
        sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
                  and (ws_ship_date_sk - ws_sold_date_sk <= 120)
                 then 1 else 0 end) as d120,
        sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120)
                 then 1 else 0 end) as dmore
      from web_sales, warehouse, ship_mode, web_site, date_dim
      where d_month_seq between 1200 and 1211
        and ws_ship_date_sk = d_date_sk
        and ws_warehouse_sk = w_warehouse_sk
        and ws_ship_mode_sk = sm_ship_mode_sk
        and ws_web_site_sk = web_site_sk
      group by substr(w_warehouse_name, 1, 20), sm_type, web_name
      order by 1, 2, 3 limit 100"""))
    j = (f["web_sales"]
         .merge(f["warehouse"], left_on="ws_warehouse_sk",
                right_on="w_warehouse_sk")
         .merge(f["ship_mode"], left_on="ws_ship_mode_sk",
                right_on="sm_ship_mode_sk")
         .merge(f["web_site"], left_on="ws_web_site_sk",
                right_on="web_site_sk")
         .merge(f["date_dim"], left_on="ws_ship_date_sk",
                right_on="d_date_sk"))
    j = j[(j.d_month_seq >= 1200) & (j.d_month_seq <= 1211)].copy()
    j["wname"] = j.w_warehouse_name.str[:20]
    lat = j.ws_ship_date_sk - j.ws_sold_date_sk
    j["d30"] = (lat <= 30).astype(int)
    j["d60"] = ((lat > 30) & (lat <= 60)).astype(int)
    j["d90"] = ((lat > 60) & (lat <= 90)).astype(int)
    j["d120"] = ((lat > 90) & (lat <= 120)).astype(int)
    j["dmore"] = (lat > 120).astype(int)
    w = (j.groupby(["wname", "sm_type", "web_name"], as_index=False)
          [["d30", "d60", "d90", "d120", "dmore"]].sum())
    w = _nlast(w, ["wname", "sm_type", "web_name"]).head(100)
    _check(got, w)


# ----------------------------------------------------------------------
# inventory shapes (Q21 / Q37 / Q82)
# ----------------------------------------------------------------------

def test_q21_inventory_before_after(env):
    d, f = env
    got = _rows(d.sql("""
      select w_warehouse_name, i_item_id,
        sum(case when d_date < cast('2000-03-11' as date)
                 then inv_quantity_on_hand else 0 end) as inv_before,
        sum(case when d_date >= cast('2000-03-11' as date)
                 then inv_quantity_on_hand else 0 end) as inv_after
      from inventory, warehouse, item, date_dim
      where i_item_sk = inv_item_sk and inv_warehouse_sk = w_warehouse_sk
        and inv_date_sk = d_date_sk
        and i_current_price between 10.00 and 14.90
        and d_date between (cast('2000-03-11' as date) - 30 days)
                       and (cast('2000-03-11' as date) + 30 days)
      group by w_warehouse_name, i_item_id
      having (case when sum(case when d_date < cast('2000-03-11' as date)
                               then inv_quantity_on_hand else 0 end) > 0
              then sum(case when d_date >= cast('2000-03-11' as date)
                            then inv_quantity_on_hand else 0 end) * 1.0
                 / sum(case when d_date < cast('2000-03-11' as date)
                            then inv_quantity_on_hand else 0 end)
              else null end) between 2.0 / 3.0 and 3.0 / 2.0
      order by w_warehouse_name, i_item_id limit 100"""))
    cut = _day("2000-03-11")
    j = (f["inventory"]
         .merge(f["warehouse"], left_on="inv_warehouse_sk",
                right_on="w_warehouse_sk")
         .merge(f["item"], left_on="inv_item_sk", right_on="i_item_sk")
         .merge(f["date_dim"], left_on="inv_date_sk", right_on="d_date_sk"))
    j = j[j.i_current_price.between(10.0, 14.9)
          & (j.d_date >= cut - 30) & (j.d_date <= cut + 30)].copy()
    j["before"] = np.where(j.d_date < cut, j.inv_quantity_on_hand, 0)
    j["after"] = np.where(j.d_date >= cut, j.inv_quantity_on_hand, 0)
    w = (j.groupby(["w_warehouse_name", "i_item_id"], as_index=False)
          [["before", "after"]].sum())
    ratio = np.where(w.before > 0, w.after / np.where(w.before > 0,
                                                      w.before, 1), np.nan)
    w = w[(ratio >= 2.0 / 3.0) & (ratio <= 3.0 / 2.0)]
    w = _nlast(w, ["w_warehouse_name", "i_item_id"]).head(100)
    _check(got, w)


def _q37_oracle(f, fact, key, price_lo, price_hi, d0, manufs):
    j = (f["item"]
         .merge(f["inventory"], left_on="i_item_sk", right_on="inv_item_sk")
         .merge(f["date_dim"], left_on="inv_date_sk", right_on="d_date_sk"))
    j = j[j.i_current_price.between(price_lo, price_hi)
          & (j.d_date >= _day(d0)) & (j.d_date <= _day(d0) + 60)
          & j.i_manufact_id.isin(manufs)
          & j.inv_quantity_on_hand.between(100, 500)]
    sold = set(f[fact][key])
    j = j[j.i_item_sk.isin(sold)]
    w = (j.groupby(["i_item_id", "i_item_desc", "i_current_price"])
          .size().reset_index()[["i_item_id", "i_item_desc",
                                 "i_current_price"]])
    return _nlast(w, ["i_item_id"]).head(100)


def test_q37_catalog_inventory(env):
    d, f = env
    got = _rows(d.sql("""
      select i_item_id, i_item_desc, i_current_price
      from item, inventory, date_dim, catalog_sales
      where i_current_price between 20.00 and 50.00
        and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
        and d_date between cast('2000-02-01' as date)
                       and (cast('2000-02-01' as date) + 60 days)
        and i_manufact_id in (5, 20, 40, 80)
        and inv_quantity_on_hand between 100 and 500
        and cs_item_sk = i_item_sk
      group by i_item_id, i_item_desc, i_current_price
      order by i_item_id limit 100"""))
    _check(got, _q37_oracle(f, "catalog_sales", "cs_item_sk",
                            20.0, 50.0, "2000-02-01", [5, 20, 40, 80]))


def test_q82_store_inventory(env):
    d, f = env
    got = _rows(d.sql("""
      select i_item_id, i_item_desc, i_current_price
      from item, inventory, date_dim, store_sales
      where i_current_price between 30.00 and 60.00
        and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
        and d_date between cast('2001-06-01' as date)
                       and (cast('2001-06-01' as date) + 60 days)
        and i_manufact_id in (10, 30, 50, 70)
        and inv_quantity_on_hand between 100 and 500
        and ss_item_sk = i_item_sk
      group by i_item_id, i_item_desc, i_current_price
      order by i_item_id limit 100"""))
    _check(got, _q37_oracle(f, "store_sales", "ss_item_sk",
                            30.0, 60.0, "2001-06-01", [10, 30, 50, 70]))


# ----------------------------------------------------------------------
# correlated / derived-table shapes (Q32 / Q61 / Q65)
# ----------------------------------------------------------------------

def test_q32_excess_discount(env):
    d, f = env
    got = _rows(d.sql("""
      select sum(cs_ext_discount_amt) as excess_discount_amount
      from catalog_sales, item, date_dim
      where i_manufact_id = 29 and i_item_sk = cs_item_sk
        and d_date between cast('1999-01-07' as date)
                       and (cast('1999-01-07' as date) + 90 days)
        and d_date_sk = cs_sold_date_sk
        and cs_ext_discount_amt > (
            select 1.3 * avg(cs_ext_discount_amt)
            from catalog_sales, date_dim
            where cs_item_sk = i_item_sk
              and d_date between cast('1999-01-07' as date)
                             and (cast('1999-01-07' as date) + 90 days)
              and d_date_sk = cs_sold_date_sk)
      limit 100"""))
    lo, hi = _day("1999-01-07"), _day("1999-01-07") + 90
    cs = f["catalog_sales"].merge(f["date_dim"], left_on="cs_sold_date_sk",
                                  right_on="d_date_sk")
    cs = cs[(cs.d_date >= lo) & (cs.d_date <= hi)]
    avg_by_item = cs.groupby("cs_item_sk").cs_ext_discount_amt.mean()
    j = cs.merge(f["item"], left_on="cs_item_sk", right_on="i_item_sk")
    j = j[j.i_manufact_id == 29]
    j = j[j.cs_ext_discount_amt
          > 1.3 * j.cs_item_sk.map(avg_by_item).fillna(np.inf)]
    want = None if len(j) == 0 else j.cs_ext_discount_amt.sum()
    assert len(got) == 1
    if want is None:
        assert got[0][0] is None
    else:
        assert got[0][0] == pytest.approx(want, rel=1e-9)


def test_q61_promotion_ratio(env):
    d, f = env
    got = _rows(d.sql("""
      select promotions, total,
             cast(promotions as decimal(15,4))
               / cast(total as decimal(15,4)) * 100
      from
        (select sum(ss_ext_sales_price) promotions
         from store_sales, store, promotion, date_dim, customer,
              customer_address, item
         where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
           and ss_promo_sk = p_promo_sk and ss_customer_sk = c_customer_sk
           and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
           and ca_gmt_offset = -5 and i_category = 'Jewelry'
           and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
                or p_channel_tv = 'Y')
           and s_gmt_offset = -5 and d_year = 1998 and d_moy = 11)
          promotional_sales,
        (select sum(ss_ext_sales_price) total
         from store_sales, store, date_dim, customer, customer_address, item
         where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
           and ss_customer_sk = c_customer_sk
           and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
           and ca_gmt_offset = -5 and i_category = 'Jewelry'
           and s_gmt_offset = -5 and d_year = 1998 and d_moy = 11) all_sales
      order by promotions, total limit 100"""))
    base = (f["store_sales"]
            .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
            .merge(f["date_dim"], left_on="ss_sold_date_sk",
                   right_on="d_date_sk")
            .merge(f["customer"], left_on="ss_customer_sk",
                   right_on="c_customer_sk")
            .merge(f["customer_address"], left_on="c_current_addr_sk",
                   right_on="ca_address_sk")
            .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    base = base[(base.ca_gmt_offset == -5) & (base.i_category == "Jewelry")
                & (base.s_gmt_offset == -5) & (base.d_year == 1998)
                & (base.d_moy == 11)]
    promo = base.merge(f["promotion"], left_on="ss_promo_sk",
                       right_on="p_promo_sk")
    promo = promo[(promo.p_channel_dmail == "Y")
                  | (promo.p_channel_email == "Y")
                  | (promo.p_channel_tv == "Y")]
    p, t = promo.ss_ext_sales_price.sum(), base.ss_ext_sales_price.sum()
    assert len(got) == 1
    assert got[0][0] == pytest.approx(p, rel=1e-9)
    assert got[0][1] == pytest.approx(t, rel=1e-9)
    assert got[0][2] == pytest.approx(p / t * 100, rel=1e-4)


def test_q65_low_revenue_items(env):
    d, f = env
    got = _rows(d.sql("""
      select s_store_name, i_item_desc, sc.revenue, i_current_price,
             i_wholesale_cost, i_brand
      from store, item,
        (select ss_store_sk, avg(revenue) as ave
         from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
               from store_sales, date_dim
               where ss_sold_date_sk = d_date_sk
                 and d_month_seq between 1176 and 1187
               group by ss_store_sk, ss_item_sk) sa
         group by ss_store_sk) sb,
        (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
         from store_sales, date_dim
         where ss_sold_date_sk = d_date_sk
           and d_month_seq between 1176 and 1187
         group by ss_store_sk, ss_item_sk) sc
      where sb.ss_store_sk = sc.ss_store_sk and sc.revenue <= 0.1 * sb.ave
        and s_store_sk = sc.ss_store_sk and i_item_sk = sc.ss_item_sk
      order by s_store_name, i_item_desc, sc.revenue, i_brand limit 100"""))
    ss = f["store_sales"].merge(f["date_dim"], left_on="ss_sold_date_sk",
                                right_on="d_date_sk")
    ss = ss[(ss.d_month_seq >= 1176) & (ss.d_month_seq <= 1187)]
    rev = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
             .ss_sales_price.sum().rename(columns={"ss_sales_price":
                                                   "revenue"}))
    ave = rev.groupby("ss_store_sk").revenue.mean()
    j = rev[rev.revenue <= 0.1 * rev.ss_store_sk.map(ave)]
    j = (j.merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
          .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    w = _nlast(j, ["s_store_name", "i_item_desc", "revenue",
                   "i_brand"]).head(100)
    w = w[["s_store_name", "i_item_desc", "revenue", "i_current_price",
           "i_wholesale_cost", "i_brand"]]
    _check(got, w, approx_cols=(2, 3, 4))


# ----------------------------------------------------------------------
# per-ticket shapes (Q68 / Q73)
# ----------------------------------------------------------------------

def test_q68_ticket_city_mismatch(env):
    d, f = env
    got = _rows(d.sql("""
      select c_last_name, c_first_name, ca_city, bought_city,
             ss_ticket_number, extended_price, extended_tax, list_price
      from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
                   sum(ss_ext_sales_price) extended_price,
                   sum(ss_ext_list_price) list_price,
                   sum(ss_ext_tax) extended_tax
            from store_sales, date_dim, store, household_demographics,
                 customer_address
            where store_sales.ss_sold_date_sk = date_dim.d_date_sk
              and store_sales.ss_store_sk = store.s_store_sk
              and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
              and store_sales.ss_addr_sk = customer_address.ca_address_sk
              and date_dim.d_dom between 1 and 2
              and (household_demographics.hd_dep_count = 4
                   or household_demographics.hd_vehicle_count = 3)
              and date_dim.d_year in (1999, 2000, 2001)
              and store.s_city in ('Midway', 'Fairview')
            group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
                     ca_city) dn,
           customer, customer_address current_addr
      where ss_customer_sk = c_customer_sk
        and customer.c_current_addr_sk = current_addr.ca_address_sk
        and current_addr.ca_city <> bought_city
      order by c_last_name, ss_ticket_number limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(f["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(f["customer_address"], left_on="ss_addr_sk",
                right_on="ca_address_sk"))
    j = j[j.d_dom.between(1, 2)
          & ((j.hd_dep_count == 4) | (j.hd_vehicle_count == 3))
          & j.d_year.isin([1999, 2000, 2001])
          & j.s_city.isin(["Midway", "Fairview"])]
    dn = (j.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                     "ca_city"], as_index=False)
           .agg(extended_price=("ss_ext_sales_price", "sum"),
                list_price=("ss_ext_list_price", "sum"),
                extended_tax=("ss_ext_tax", "sum"))
           .rename(columns={"ca_city": "bought_city"}))
    w = (dn.merge(f["customer"], left_on="ss_customer_sk",
                  right_on="c_customer_sk")
           .merge(f["customer_address"], left_on="c_current_addr_sk",
                  right_on="ca_address_sk"))
    w = w[w.ca_city != w.bought_city]
    w = _nlast(w, ["c_last_name", "ss_ticket_number"]).head(100)
    w = w[["c_last_name", "c_first_name", "ca_city", "bought_city",
           "ss_ticket_number", "extended_price", "extended_tax",
           "list_price"]]
    _check(got, w, approx_cols=(5, 6, 7))


def test_q73_ticket_line_counts(env):
    d, f = env
    got = _rows(d.sql("""
      select c_last_name, c_first_name, c_salutation,
             c_preferred_cust_flag, ss_ticket_number, cnt
      from (select ss_ticket_number, ss_customer_sk, count(*) cnt
            from store_sales, date_dim, store, household_demographics
            where store_sales.ss_sold_date_sk = date_dim.d_date_sk
              and store_sales.ss_store_sk = store.s_store_sk
              and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
              and date_dim.d_dom between 1 and 2
              and (household_demographics.hd_buy_potential = '>10000'
                   or household_demographics.hd_buy_potential = 'Unknown')
              and household_demographics.hd_vehicle_count > 0
              and household_demographics.hd_dep_count
                  / household_demographics.hd_vehicle_count > 1
              and date_dim.d_year in (1999, 2000, 2001)
              and store.s_county in ('Ziebach County 1', 'Walker County 2',
                                     'Daviess County 1', 'Barrow County 2')
            group by ss_ticket_number, ss_customer_sk) dj, customer
      where ss_customer_sk = c_customer_sk and cnt between 1 and 5
      order by cnt desc, c_last_name, ss_ticket_number limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk")
         .merge(f["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk"))
    # integer division truncating toward zero (the engine's PG semantics)
    ratio = (j.hd_dep_count // np.where(j.hd_vehicle_count != 0,
                                        j.hd_vehicle_count, 1))
    j = j[j.d_dom.between(1, 2)
          & (j.hd_buy_potential.isin([">10000", "Unknown"]))
          & (j.hd_vehicle_count > 0) & (ratio > 1)
          & j.d_year.isin([1999, 2000, 2001])
          & j.s_county.isin(["Ziebach County 1", "Walker County 2",
                             "Daviess County 1", "Barrow County 2"])]
    dj = (j.groupby(["ss_ticket_number", "ss_customer_sk"])
           .size().reset_index(name="cnt"))
    dj = dj[dj.cnt.between(1, 5)]
    w = dj.merge(f["customer"], left_on="ss_customer_sk",
                 right_on="c_customer_sk")
    w = _nlast(w, ["cnt", "c_last_name", "ss_ticket_number"],
               [False, True, True]).head(100)
    w = w[["c_last_name", "c_first_name", "c_salutation",
           "c_preferred_cust_flag", "ss_ticket_number", "cnt"]]
    _check(got, w)


# ----------------------------------------------------------------------
# day-name pivots, store channels (Q43 / Q89 / Q96)
# ----------------------------------------------------------------------

def test_q43_sales_by_day_name(env):
    d, f = env
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    cases = ",\n".join(
        f"sum(case when d_day_name = '{dn}' then ss_sales_price "
        f"else null end) {dn[:3].lower()}_sales" for dn in days)
    got = _rows(d.sql(f"""
      select s_store_name, s_store_id, {cases}
      from date_dim, store_sales, store
      where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
        and s_gmt_offset = -5 and d_year = 2000
      group by s_store_name, s_store_id
      order by s_store_name, s_store_id limit 100"""))
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    j = j[(j.s_gmt_offset == -5) & (j.d_year == 2000)]
    grp = j.groupby(["s_store_name", "s_store_id"])
    rows = []
    for (nm, sid), g in grp:
        row = {"s_store_name": nm, "s_store_id": sid}
        for dn in days:
            sub = g[g.d_day_name == dn]
            row[dn] = sub.ss_sales_price.sum() if len(sub) else None
        rows.append(row)
    w = _nlast(pd.DataFrame(rows), ["s_store_name", "s_store_id"]).head(100)
    _check(got, w, approx_cols=tuple(range(2, 9)))


def test_q89_monthly_vs_average(env):
    d, f = env
    got = _rows(d.sql("""
      select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy, sum_sales, avg_monthly_sales
      from (select i_category, i_class, i_brand, s_store_name,
                   s_company_name, d_moy, sum(ss_sales_price) sum_sales,
                   avg(sum(ss_sales_price)) over
                     (partition by i_category, i_brand, s_store_name,
                      s_company_name) avg_monthly_sales
            from item, store_sales, date_dim, store
            where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
              and ss_store_sk = s_store_sk and d_year = 1999
              and ((i_category in ('Books', 'Electronics', 'Sports')
                    and i_class in ('class 1', 'class 2', 'class 3'))
                or (i_category in ('Men', 'Jewelry', 'Women')
                    and i_class in ('class 4', 'class 5', 'class 6')))
            group by i_category, i_class, i_brand, s_store_name,
                     s_company_name, d_moy) tmp1
      where case when avg_monthly_sales <> 0
                 then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
                 else null end > 0.1
      order by sum_sales - avg_monthly_sales, s_store_name, i_brand,
               i_class, d_moy limit 100"""))
    j = (f["store_sales"]
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk")
         .merge(f["date_dim"], left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    sel = ((j.i_category.isin(["Books", "Electronics", "Sports"])
            & j.i_class.isin(["class 1", "class 2", "class 3"]))
           | (j.i_category.isin(["Men", "Jewelry", "Women"])
              & j.i_class.isin(["class 4", "class 5", "class 6"])))
    j = j[(j.d_year == 1999) & sel]
    g = (j.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy"], as_index=False)
          .ss_sales_price.sum().rename(columns={"ss_sales_price":
                                                "sum_sales"}))
    g["avg_monthly_sales"] = g.groupby(
        ["i_category", "i_brand", "s_store_name",
         "s_company_name"]).sum_sales.transform("mean")
    g = g[np.where(g.avg_monthly_sales != 0,
                   np.abs(g.sum_sales - g.avg_monthly_sales)
                   / np.where(g.avg_monthly_sales != 0,
                              g.avg_monthly_sales, 1), np.nan) > 0.1]
    g["diff"] = g.sum_sales - g.avg_monthly_sales
    w = _nlast(g, ["diff", "s_store_name", "i_brand", "i_class",
                   "d_moy"]).head(100)
    w = w[["i_category", "i_class", "i_brand", "s_store_name",
           "s_company_name", "d_moy", "sum_sales", "avg_monthly_sales"]]
    _check(got, w, approx_cols=(6, 7), rel=1e-6)


def test_q96_evening_store_traffic(env):
    d, f = env
    got = _rows(d.sql("""
      select count(*)
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 20 and time_dim.t_minute >= 30
        and household_demographics.hd_dep_count = 7
        and store.s_store_name = 'ese'
      order by count(*) limit 100"""))
    j = (f["store_sales"]
         .merge(f["time_dim"], left_on="ss_sold_time_sk", right_on="t_time_sk")
         .merge(f["household_demographics"], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
         .merge(f["store"], left_on="ss_store_sk", right_on="s_store_sk"))
    want = len(j[(j.t_hour == 20) & (j.t_minute >= 30)
                 & (j.hd_dep_count == 7) & (j.s_store_name == "ese")])
    assert got == [(want,)]
