"""Resource groups + backoff prioritization (reference parity:
src/backend/utils/resgroup/resgroup.c slots/memory shares and
src/backend/postmaster/backoff.c weighted CPU scheduling). Groups cap
concurrent mesh statements and per-query HBM; when the global cap binds,
the next statement comes from the group with least weighted chip time."""

import threading
import time

import pytest

import greengage_tpu
from greengage_tpu.runtime.resgroup import GroupTimeout
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.sql("insert into t values " + ",".join(f"({i}, {i})" for i in range(50)))
    return d


def test_ddl_and_status(db):
    db.sql("create resource group etl with (concurrency=2, "
           "memory_limit_mb=512, cpu_weight=50)")
    st = {g["name"]: g for g in db.resgroup_status()}
    assert st["etl"]["concurrency"] == 2
    assert st["etl"]["memory_limit_mb"] == 512
    assert st["default_group"]["cpu_weight"] == 100
    db.sql("alter resource group etl set concurrency 3")
    st = {g["name"]: g for g in db.resgroup_status()}
    assert st["etl"]["concurrency"] == 3
    db.sql("drop resource group etl")
    assert "etl" not in {g["name"] for g in db.resgroup_status()}
    with pytest.raises(ValueError, match="built-in"):
        db.sql("drop resource group default_group")
    with pytest.raises(SqlError, match="unknown resource group option"):
        db.sql("create resource group x with (nope=1)")


def test_groups_persist_across_reopen(db, tmp_path):
    db.sql("create resource group rpt with (concurrency=1, cpu_weight=10)")
    d2 = greengage_tpu.connect(str(tmp_path / "c"))
    st = {g["name"]: g for g in d2.resgroup_status()}
    assert st["rpt"]["concurrency"] == 1 and st["rpt"]["cpu_weight"] == 10


def test_set_group_and_chip_accounting(db):
    db.sql("create resource group rpt with (concurrency=2)")
    db.sql("set resource_group = rpt")
    assert db.sql("show resource_group") == "rpt"
    db.sql("select count(*) from t")
    st = {g["name"]: g for g in db.resgroup_status()}
    assert st["rpt"]["admitted"] >= 1
    assert st["rpt"]["chip_seconds"] > 0
    db.sql("set resource_group = default_group")
    with pytest.raises(ValueError, match="does not exist"):
        db.sql("set resource_group = nosuch")


def test_concurrency_slots_queue_and_timeout(db):
    db.sql("create resource group one with (concurrency=1)")
    db.sql("set resource_queue_timeout_s = 1")
    slot = db.resgroups.admit("one")
    slot.__enter__()
    try:
        with pytest.raises(GroupTimeout, match="no slot"):
            with db.resgroups.admit("one"):
                pass
    finally:
        slot.__exit__(None, None, None)
    # slot freed: admission works again
    with db.resgroups.admit("one"):
        pass
    st = {g["name"]: g for g in db.resgroup_status()}
    assert st["one"]["timed_out"] == 1 and st["one"]["active"] == 0


def test_group_memory_cap_triggers_spill_or_error(db):
    """A tiny per-group memory share forces the spill path (or a clean
    rejection) instead of running uncapped — effective_limit_bytes takes
    the thread's group ceiling."""
    from greengage_tpu.exec.executor import effective_limit_bytes

    db.sql("create resource group tiny with (concurrency=1, "
           "memory_limit_mb=1)")
    with db.resgroups.admit("tiny"):
        assert effective_limit_bytes(db.settings) == 1 << 20
    assert effective_limit_bytes(db.settings) in (
        0, db.settings.vmem_protect_limit_mb << 20)


def test_backoff_prefers_higher_weight(db):
    """With the global cap binding, the waiter from the higher-weight
    (less consumed, weighted) group is admitted first."""
    db.sql("create resource group fast with (cpu_weight=1000)")
    db.sql("create resource group slow with (cpu_weight=10)")
    db.sql("set resource_group_global_active = 1")
    db.sql("set resource_queue_timeout_s = 20")
    # charge both groups with identical raw chip time: weighted consumed
    # = t/1000 vs t/10 -> "fast" should win the next free slot
    for g in ("fast", "slow"):
        db.resgroups.groups[g].consumed_s = 5.0
    hold = db.resgroups.admit("default_group")
    hold.__enter__()
    order = []

    def worker(g):
        with db.resgroups.admit(g):
            order.append(g)

    ts = [threading.Thread(target=worker, args=("slow",)),
          threading.Thread(target=worker, args=("fast",))]
    ts[0].start()
    time.sleep(0.2)   # slow is first in line FIFO-wise
    ts[1].start()
    time.sleep(0.2)
    hold.__exit__(None, None, None)   # one slot frees -> scheduler picks
    [t.join(10) for t in ts]
    assert order[0] == "fast", order
    db.sql("set resource_group_global_active = 0")
