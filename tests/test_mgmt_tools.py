"""Management-plane tools: logging + gplogfilter, gpstart/gpstop daemon
lifecycle, analyzedb incremental stats, gpload YAML loads, gppkg
packages, gpcheckperf. Reference: gpMgmt/bin counterparts."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.mgmt import cli
from greengage_tpu.runtime.logger import filter_entries, read_entries


def run_cli(*argv):
    return cli.main(list(argv))


@pytest.fixture()
def clu(tmp_path, devices8):
    d = str(tmp_path / "clu")
    assert run_cli("init", "-d", d, "-n", "4") == 0
    return d


# ---------------------------------------------------------------------------
# gg scrub (storage verify + repair; the full behavior matrix lives in
# test_scrub.py — this keeps the COMMAND itself wired)
# ---------------------------------------------------------------------------

def test_scrub_smoke_clean_cluster(clu, capsys):
    import json

    db = greengage_tpu.connect(path=clu)
    db.sql("create table st (a int, b int) distributed by (a)")
    db.sql("insert into st values " + ",".join(
        f"({i},{i})" for i in range(32)))
    db.close()
    assert run_cli("scrub", "-d", clu, "--json") == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["files_scanned"] > 0
    assert rep["files_verified"] == rep["files_scanned"]
    assert rep["files_repaired"] == rep["files_quarantined"] == 0
    assert rep["bytes_scanned"] > 0
    # human-readable variant + the scrub event lands in the cluster log
    assert run_cli("scrub", "-d", clu) == 0
    assert "verified" in capsys.readouterr().out
    assert any(e["kind"] == "scrub" for e in read_entries(clu))


def test_scrub_smoke_reports_corruption(clu, capsys):
    db = greengage_tpu.connect(path=clu)
    db.sql("create table st (a int) distributed by (a)")
    db.sql("insert into st values " + ",".join(f"({i})" for i in range(32)))
    snap = db.store.manifest.snapshot()
    rel = next(rels[0] for rels in
               snap["tables"]["st"]["segfiles"].values() if rels)
    db.close()
    path = os.path.join(clu, "data", "st", rel)
    with open(path, "r+b") as f:
        f.seek(40)
        b = f.read(1)
        f.seek(40)
        f.write(bytes([b[0] ^ 0xFF]))
    # no mirrors: the bad file quarantines and the command reports failure
    assert run_cli("scrub", "-d", clu) == 1
    out = capsys.readouterr().out
    assert "quarantined 1" in out


# ---------------------------------------------------------------------------
# logging + logfilter
# ---------------------------------------------------------------------------

def test_statement_logging_and_filter(clu):
    db = greengage_tpu.connect(path=clu)
    db.sql("create table t (a int) distributed by (a)")
    db.sql("insert into t values (1), (2)")
    db.sql("select count(*) from t")
    with pytest.raises(Exception):
        db.sql("select nope from t")
    entries = read_entries(clu)
    kinds = {e["kind"] for e in entries}
    assert "lifecycle" in kinds and "statement" in kinds
    errs = filter_entries(entries, trouble=True)
    assert any("nope" in e["message"] for e in errs)
    assert all(e["severity"] == "ERROR" for e in errs)
    sel = filter_entries(entries, match="count")
    assert sel and all("count" in e["message"] for e in sel)
    # duration floor keeps only real statements
    slow = filter_entries(entries, min_duration_ms=0.0)
    assert all(e["kind"] == "statement" for e in slow if e["duration_ms"])


def test_log_statement_off(clu):
    db = greengage_tpu.connect(path=clu)
    db.sql("set log_statement to off")
    before = len(read_entries(clu))
    db.sql("create table q (a int) distributed by (a)")
    assert len(read_entries(clu)) == before
    db.sql("set log_statement to on")


# ---------------------------------------------------------------------------
# analyzedb incremental
# ---------------------------------------------------------------------------

def test_analyzedb_incremental(clu, capsys):
    db = greengage_tpu.connect(path=clu)
    db.sql("create table s1 (a int, b int) distributed by (a)")
    db.sql("insert into s1 values (1, 10), (2, 20)")
    db.sql("create table s2 (a int) distributed by (a)")
    db.sql("insert into s2 values (5)")
    assert run_cli("analyzedb", "-d", clu) == 0
    out = capsys.readouterr().out
    assert "analyzed s1" in out and "analyzed s2" in out
    # second run: nothing changed -> both skipped
    assert run_cli("analyzedb", "-d", clu) == 0
    out = capsys.readouterr().out
    assert "skipped s1" in out and "skipped s2" in out
    # touch one table -> only it re-analyzes
    db2 = greengage_tpu.connect(path=clu)
    db2.sql("insert into s1 values (3, 30)")
    assert run_cli("analyzedb", "-d", clu) == 0
    out = capsys.readouterr().out
    assert "analyzed s1" in out and "skipped s2" in out


# ---------------------------------------------------------------------------
# gpload
# ---------------------------------------------------------------------------

def test_gpload_yaml(clu, tmp_path, capsys):
    db = greengage_tpu.connect(path=clu)
    db.sql("create table sales (id int, region text, amt decimal(8,2)) "
           "distributed by (id)")
    csv = tmp_path / "sales.csv"
    csv.write_text("id,region,amt\n1,east,10.50\n2,west,20.25\nbad,x,y\n")
    cfg = tmp_path / "load.yml"
    cfg.write_text(textwrap.dedent(f"""
        gpload:
          input:
            source:
              file: [{csv}]
            format: csv
            header: true
            error_limit: 5
          output:
            table: sales
            mode: insert
    """))
    assert run_cli("load", "-d", clu, "-f", str(cfg)) == 0
    assert "now 2 rows" in capsys.readouterr().out
    db2 = greengage_tpu.connect(path=clu)
    assert db2.sql("select count(*) from sales").rows() == [(2,)]
    # truncate mode replaces
    assert run_cli("load", "-d", clu, "-f", str(cfg)) == 0  # insert appends
    db3 = greengage_tpu.connect(path=clu)
    assert db3.sql("select count(*) from sales").rows() == [(4,)]
    cfg.write_text(cfg.read_text().replace("mode: insert", "mode: truncate"))
    assert run_cli("load", "-d", clu, "-f", str(cfg)) == 0
    db4 = greengage_tpu.connect(path=clu)
    assert db4.sql("select count(*) from sales").rows() == [(2,)]


# ---------------------------------------------------------------------------
# gppkg
# ---------------------------------------------------------------------------

def test_pkg_install_and_create_extension(clu, tmp_path, capsys):
    pkg = tmp_path / "triple"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "from greengage_tpu.extensions import register_scalar\n"
        "register_scalar('triple_it', lambda a: a * 3, ('numeric',), "
        "'first')\n")
    assert run_cli("pkg", "install", str(pkg), "-d", clu) == 0
    assert run_cli("pkg", "list", "-d", clu) == 0
    assert "triple" in capsys.readouterr().out
    db = greengage_tpu.connect(path=clu)
    db.sql("create extension triple")
    db.sql("create table n (a int) distributed by (a)")
    db.sql("insert into n values (7)")
    assert db.sql("select triple_it(a) from n").rows() == [(21,)]
    # removal is refused while created
    assert run_cli("pkg", "remove", "triple", "-d", clu) == 1


# ---------------------------------------------------------------------------
# gg ps / gg cancel (pg_stat_activity / pg_cancel_backend analogs; the
# full wait-state cancellation matrix lives in test_interrupt.py)
# ---------------------------------------------------------------------------

def test_ps_and_cancel_smoke(clu, tmp_path, capsys):
    import threading
    import time

    from greengage_tpu.runtime.faultinject import faults
    from greengage_tpu.runtime.interrupt import StatementCancelled
    from greengage_tpu.runtime.server import SqlServer

    db = greengage_tpu.connect(path=clu)
    db.sql("create table pt (a int) distributed by (a)")
    db.sql("insert into pt values " + ",".join(f"({i})" for i in range(64)))
    sock = str(tmp_path / "ps.sock")
    srv = SqlServer(db, sock)
    srv.start()
    faults.inject("cancel_before_dispatch", "sleep", sleep_s=3.0,
                  occurrences=1)
    err = {}

    def victim():
        try:
            db.sql("select count(*) from pt -- ps-victim")
            err["e"] = None
        except Exception as e:
            err["e"] = e

    t = threading.Thread(target=victim)
    t.start()
    try:
        # poll gg ps until the in-flight statement shows
        line = None
        end = time.monotonic() + 5
        while line is None and time.monotonic() < end:
            assert run_cli("ps", "-s", sock) == 0
            out = capsys.readouterr().out
            line = next((ln for ln in out.splitlines()
                         if "ps-victim" in ln), None)
            if line is None:
                time.sleep(0.05)
        assert line is not None, "gg ps never showed the statement"
        # topology surfacing (the reform counters' operator window):
        # `gg ps` leads with the cluster state + topology version, and the
        # status frame carries the mh_*/manifest_* counter family
        assert "cluster: local  topology v" in out
        from greengage_tpu.runtime.server import SqlClient

        c = SqlClient(sock)
        try:
            st = c.op({"op": "status"})
        finally:
            c.close()
        assert st["ok"] and st["cluster"]["state"] == "local"
        assert "mh_topology_version" in st["cluster"]["counters"]
        sid = line.split()[0]
        assert run_cli("cancel", sid, "-s", sock) == 0
        assert f"statement {sid} cancelled" in capsys.readouterr().out
        t.join(timeout=15)
        assert not t.is_alive()
        assert isinstance(err["e"], StatementCancelled), err["e"]
        assert err["e"].cause == "user"
        # cancelling a finished id is a clean error, not a crash
        assert run_cli("cancel", sid, "-s", sock) == 1
    finally:
        faults.reset("cancel_before_dispatch")
        srv.stop()
        t.join(timeout=15)


def test_ps_requires_running_server(tmp_path, capsys):
    assert run_cli("ps", "-d", str(tmp_path / "nowhere")) == 1
    assert "running server" in capsys.readouterr().err


def test_gg_mem_smoke(clu, tmp_path, capsys):
    """`gg mem` (the measured-memory surface, docs/OBSERVABILITY.md):
    summary + --json against a live server."""
    import json as _json

    from greengage_tpu.runtime.server import SqlServer

    db = greengage_tpu.connect(path=clu)
    db.sql("create table memt (a int) distributed by (a)")
    db.sql("insert into memt values " + ",".join(f"({i})" for i in range(64)))
    db.sql("select count(*) from memt")
    sock = str(tmp_path / "mem.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        assert run_cli("mem", "-s", sock) == 0
        out = capsys.readouterr().out
        assert "host: rss" in out and "device:" in out
        assert run_cli("mem", "-s", sock, "--json") == 0
        payload = _json.loads(capsys.readouterr().out)
        assert "process" in payload and "executables" in payload
    finally:
        srv.stop()
    assert run_cli("mem", "-d", str(tmp_path / "nowhere")) == 1
    assert "running server" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# daemon lifecycle (subprocess: fork conflicts with pytest/jax state)
# ---------------------------------------------------------------------------

def test_constant_select(clu):
    db = greengage_tpu.connect(path=clu)
    assert db.sql("select 1").rows() == [(1,)]
    assert db.sql("select 1 + 2 as x, 'a' || 'b' as s").rows() == [(3, "ab")]
    assert db.sql("select null as n").rows() == [(None,)]
    assert db.sql("select upper('q'), abs(-4)").rows() == [("Q", 4)]
    assert db.sql("select 1 limit 0").rows() == []
    assert db.sql("select 1 where 1 = 0").rows() == []
    assert db.sql("select 1 where 2 > 1").rows() == [(1,)]


def test_pkg_missing_argument(clu, capsys):
    assert run_cli("pkg", "install", "-d", clu) == 1
    assert "requires a package" in capsys.readouterr().err


def test_start_stop_lifecycle(clu):
    env = dict(os.environ, JAX_PLATFORMS="cpu", GGTPU_PLATFORM="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, "-m", "greengage_tpu.mgmt.cli", "start", "-d", clu],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "server started" in r.stdout
    try:
        sock = os.path.join(clu, ".gg.sock")
        r = subprocess.run(
            [sys.executable, "-m", "greengage_tpu.mgmt.cli", "sql",
             "-s", sock, "select 1 as one"],
            env=env, capture_output=True, text=True, timeout=120)
        assert "1" in r.stdout, r.stdout + r.stderr
    finally:
        r = subprocess.run(
            [sys.executable, "-m", "greengage_tpu.mgmt.cli", "stop",
             "-d", clu], env=env, capture_output=True, text=True, timeout=60)
    assert "server stopped" in r.stdout
    assert not os.path.exists(os.path.join(clu, "server.pid"))


def test_gpconfig_persisted_settings(devices8, tmp_path, capsys):
    """gpconfig analog: persisted cluster GUCs adopted at every connect."""
    import greengage_tpu
    from greengage_tpu.mgmt import cli

    path = str(tmp_path / "c")
    greengage_tpu.connect(path=path, numsegments=2).close()
    rc = cli.main(["config", "-d", path,
                   "-c", "vmem_protect_limit_mb", "-v", "777"])
    assert rc == 0
    rc = cli.main(["config", "-d", path,
                   "-c", "fused_dense_agg", "-v", "off"])
    assert rc == 0
    d = greengage_tpu.connect(path=path, numsegments=2)
    assert d.settings.vmem_protect_limit_mb == 777
    assert d.settings.fused_dense_agg is False
    assert "777" in str(d.sql("show vmem_protect_limit_mb"))
    # listing marks persisted values
    capsys.readouterr()
    cli.main(["config", "-d", path])
    out = capsys.readouterr().out
    assert "vmem_protect_limit_mb            777 (persisted)" in out
    # unknown names are rejected at write time
    import pytest as _pytest
    with _pytest.raises(ValueError):
        cli.main(["config", "-d", path, "-c", "no_such_guc", "-v", "1"])
    d.close()


def test_settings_adoption_failures_surface(devices8, tmp_path, capsys):
    """A persisted GUC this build can't adopt (operator typo, version skew)
    must surface as a warning in `gg state` and the cluster log — never a
    silent divergence (guc.c validation analog)."""
    import json

    import greengage_tpu
    from greengage_tpu.mgmt import cli

    path = str(tmp_path / "c")
    greengage_tpu.connect(path=path, numsegments=2).close()
    with open(os.path.join(path, "settings.json"), "w") as f:
        json.dump({"vmem_protect_limit_mb": 512, "no_such_guc": 1}, f)
    d = greengage_tpu.connect(path=path, numsegments=2)
    assert d.settings.vmem_protect_limit_mb == 512   # good one adopted
    assert any("no_such_guc" in w for w in d.settings_warnings)
    d.close()
    capsys.readouterr()
    cli.main(["state", "-d", path])
    out = capsys.readouterr().out
    assert "WARNING" in out and "no_such_guc" in out
    # and it reached the cluster log for logfilter forensics
    logdir = os.path.join(path, "log")
    blob = "".join(open(os.path.join(logdir, p)).read()
                   for p in os.listdir(logdir))
    assert "no_such_guc" in blob


# ---------------------------------------------------------------------------
# gg check --list (ISSUE 14: the check catalog with per-check counts — the
# tier-1 log's receipt of what ran; the analyzers' behavior matrix lives in
# test_analysis.py, this keeps the COMMAND itself wired)
# ---------------------------------------------------------------------------

def test_check_list_smoke(capsys):
    assert run_cli("check", "--list") == 0
    out = capsys.readouterr().out
    for name in ("locks", "interrupts", "tracer", "registry", "imports",
                 "threads", "races"):
        assert name in out, out
    assert "finding(s)" in out


# ---------------------------------------------------------------------------
# gg checkperf --feedback (the self-tuning loop's operator surface; the
# calibration behavior matrix lives in test_feedback.py — this keeps the
# COMMAND and the server frame wired)
# ---------------------------------------------------------------------------

def test_checkperf_feedback_report_and_reset(clu, tmp_path, capsys):
    db = greengage_tpu.connect(path=clu)
    db.sql("create table cp (a int, b int) distributed by (a)")
    db.sql("insert into cp values " +
           ",".join(f"({i},{i % 7})" for i in range(500)))
    db.sql("select count(*) from cp where b >= 0")   # 3x-wrong estimate
    db.sql("select count(*) from cp where b >= 0")
    db.close()
    assert run_cli("checkperf", "-d", clu, "--feedback") == 0
    out = capsys.readouterr().out
    assert "self-tuning: calibration generation" in out
    assert "applied row scales" in out               # the promotion shows
    assert "rows err%" in out
    # --apply is a no-op when nothing is pending, but must be wired
    assert run_cli("checkperf", "-d", clu, "--feedback", "--apply") == 0
    assert "applied 0 pending correction(s)" in capsys.readouterr().out
    # --reset clears the store
    assert run_cli("checkperf", "-d", clu, "--reset") == 0
    assert "feedback store cleared" in capsys.readouterr().out
    assert run_cli("checkperf", "-d", clu, "--feedback") == 0
    assert "0 digest(s) tracked" in capsys.readouterr().out


def test_checkperf_server_frame(clu, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    db = greengage_tpu.connect(path=clu)
    db.sql("create table cp (a int, b int) distributed by (a)")
    db.sql("insert into cp values " +
           ",".join(f"({i},{i % 7})" for i in range(500)))
    db.sql("select count(*) from cp where b >= 0")
    sock = str(tmp_path / "cp.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        c = SqlClient(sock)
        try:
            st = c.op({"op": "checkperf"})
            assert st["ok"]
            assert st["feedback"]["gen"] >= 1
            assert st["feedback"]["shapes"]
            ap = c.op({"op": "checkperf", "apply": True})
            assert ap["ok"] and ap["applied"] == 0
            rs = c.op({"op": "checkperf", "reset": True})
            assert rs["ok"] and rs["reset"] is True
            assert c.op({"op": "checkperf"})["feedback"]["digests"] == 0
        finally:
            c.close()
    finally:
        srv.stop()
