"""Runtime cardinality feedback — VERDICT r3 weak #3: the exact counts
the executor's overflow machinery already collects (join expansion
totals, agg group counts) persist per statement, so a post-DML replan
compiles right-sized instead of re-discovering the cardinality through
capacity-tier recompiles (each tier is a full XLA recompile)."""

import numpy as np
import pytest

import greengage_tpu


@pytest.fixture()
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table pr (k int, fk int) distributed by (k)")
    d.load_table("pr", {"k": np.arange(2000),
                        "fk": (np.arange(2000) % 100).astype(np.int64)})
    d.sql("create table bl (pk int, m int) distributed by (m)")
    # analyzed with 3000 UNIQUE build keys (high NDV)...
    d.load_table("bl", {"pk": np.arange(3000), "m": np.arange(3000)})
    d.sql("analyze")
    # ...then rewritten as 30x duplicates of 100 keys WITHOUT
    # re-analyzing: |L||R|/max(ndv) underestimates the join fanout 30x,
    # so the CSR expansion capacity is far too small on the first run
    d.sql("delete from bl")
    reps = np.repeat(np.arange(100), 30)
    d.load_table("bl", {"pk": reps, "m": 100 + np.arange(len(reps))})
    return d


Q = "select count(*) from pr, bl where pr.fk = bl.pk"


def test_second_plan_uses_observed_cardinality(db):
    r1 = db.sql(Q)
    assert r1.rows()[0][0] == 2000 * 30
    assert r1.stats["tiers_used"] > 1          # stale stats: paid retries
    # DML bumps the manifest version: the statement replans and recompiles
    db.sql("insert into pr values (999999, 999)")
    r2 = db.sql(Q)
    assert r2.rows()[0][0] == 2000 * 30
    assert r2.stats["compiled"] is True        # fresh compile (new version)
    assert r2.stats["tiers_used"] == 1         # ...sized by the feedback
    # steady state stays cached
    r3 = db.sql(Q)
    assert r3.stats["compiled"] is False
    assert r3.rows()[0][0] == 2000 * 30


def test_hints_self_correct_when_data_grows_again(db):
    db.sql(Q)
    # triple the duplicates: the recorded hint is now too SMALL — the
    # overflow retry self-heals and re-records
    reps = np.repeat(np.arange(100), 60)
    db.load_table("bl", {"pk": reps, "m": 5000 + np.arange(len(reps))})
    r = db.sql(Q)
    assert r.rows()[0][0] == 2000 * 90
    db.sql("insert into pr values (999998, 998)")
    r2 = db.sql(Q)
    assert r2.rows()[0][0] == 2000 * 90
    assert r2.stats["tiers_used"] == 1
