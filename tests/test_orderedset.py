"""Ordered-set aggregates: percentile_cont / percentile_disc / median
WITHIN GROUP (pg_aggregate.h:246 ordered-set family) — rewritten onto
the engine's distributed window sort + grouped order statistics."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(21)
    n = 400
    g = rng.integers(0, 4, n).astype(np.int32)
    x = rng.normal(100, 25, n)
    nulls = rng.random(n) < 0.1
    d.sql("create table ps (g int, x double precision, w int, k int) "
          "distributed by (k)")
    d.load_table("ps", {"g": g, "x": x,
                        "w": rng.integers(0, 9, n).astype(np.int32),
                        "k": np.arange(n, dtype=np.int32)})
    d.sql("update ps set x = null where k in (%s)" %
          ",".join(str(i) for i in np.flatnonzero(nulls)))
    d.df = pd.DataFrame({"g": g, "x": np.where(nulls, np.nan, x)})
    yield d
    d.close()


def test_percentile_cont_grouped(db):
    r = db.sql("select g, percentile_cont(0.5) within group (order by x) m,"
               " percentile_cont(0.9) within group (order by x) p90"
               " from ps group by g order by g")
    for grp, m, p90 in r.rows():
        vals = db.df[db.df.g == grp].x.dropna()
        np.testing.assert_allclose(m, np.percentile(vals, 50), rtol=1e-12)
        np.testing.assert_allclose(p90, np.percentile(vals, 90), rtol=1e-12)


def test_percentile_disc_and_median(db):
    r = db.sql("select g, percentile_disc(0.25) within group (order by x) d,"
               " median(x) med from ps group by g order by g")
    for grp, dv, med in r.rows():
        vals = db.df[db.df.g == grp].x.dropna().sort_values()
        want_d = vals.iloc[max(int(np.ceil(0.25 * len(vals))), 1) - 1]
        np.testing.assert_allclose(dv, want_d, rtol=1e-12)
        np.testing.assert_allclose(med, np.percentile(vals, 50), rtol=1e-12)


def test_scalar_percentile_with_other_aggs(db):
    r = db.sql("select count(*), percentile_cont(0.5) within group "
               "(order by x), sum(w) from ps")
    n, med, sw = r.rows()[0]
    assert n == len(db.df)
    np.testing.assert_allclose(
        med, np.percentile(db.df.x.dropna(), 50), rtol=1e-12)


def test_percentile_edge_fractions(db):
    r = db.sql("select percentile_cont(0) within group (order by x) lo,"
               " percentile_cont(1) within group (order by x) hi,"
               " percentile_disc(0) within group (order by x) dlo"
               " from ps")
    lo, hi, dlo = r.rows()[0]
    vals = db.df.x.dropna()
    np.testing.assert_allclose(lo, vals.min(), rtol=1e-12)
    np.testing.assert_allclose(hi, vals.max(), rtol=1e-12)
    np.testing.assert_allclose(dlo, vals.min(), rtol=1e-12)


def test_percentile_in_expression_and_filter(db):
    r = db.sql("select g from ps group by g "
               "having percentile_cont(0.5) within group (order by x) > 95 "
               "order by g")
    want = [g for g in range(4)
            if np.percentile(db.df[db.df.g == g].x.dropna(), 50) > 95]
    assert [row[0] for row in r.rows()] == want


def test_errors(db):
    with pytest.raises(SqlError, match="WITHIN GROUP"):
        db.sql("select percentile_cont(0.5) from ps")
    with pytest.raises(SqlError, match="fraction"):
        db.sql("select percentile_cont(1.5) within group (order by x) from ps")
    with pytest.raises(SqlError, match="DESC"):
        db.sql("select percentile_cont(0.5) within group (order by x desc) "
               "from ps")


def test_group_by_ordinal_and_qualified_names(db):
    r1 = db.sql("select g, percentile_cont(0.5) within group (order by x) "
                "from ps group by 1 order by 1")
    r2 = db.sql("select ps.g, percentile_cont(0.5) within group "
                "(order by ps.x) from ps group by ps.g order by ps.g")
    assert r1.rows() == r2.rows()
    for grp, m in r1.rows():
        vals = db.df[db.df.g == grp].x.dropna()
        np.testing.assert_allclose(m, np.percentile(vals, 50), rtol=1e-12)


def test_within_group_rejected_for_plain_aggs(db):
    with pytest.raises(SqlError, match="not supported for sum"):
        db.sql("select sum(x) within group (order by x) from ps")


def test_percentile_under_rollup(db):
    """Composition with grouping sets: each ROLLUP branch re-enters the
    ordered-set expansion with its own group keys."""
    r = db.sql("select g, percentile_cont(0.5) within group (order by x) m "
               "from ps group by rollup(g) order by g nulls last")
    rows = r.rows()
    assert len(rows) == db.df.g.nunique() + 1
    for g, m in rows:
        vals = (db.df[db.df.g == g] if g is not None else db.df).x.dropna()
        np.testing.assert_allclose(m, np.percentile(vals, 50), rtol=1e-12)


def test_percentile_of_grouping_key_under_rollup(db):
    """WITHIN GROUP (ORDER BY <grouping key>): the key inside the
    aggregate must see real rows in every branch, not the branch NULL."""
    r = db.sql("select g, percentile_cont(0.5) within group (order by g) m "
               "from ps group by rollup(g) order by g nulls last")
    total = r.rows()[-1]
    assert total[0] is None
    np.testing.assert_allclose(total[1], np.percentile(db.df.g, 50),
                               rtol=1e-12)


def test_order_by_percentile_under_rollup(db):
    r = db.sql("select g, percentile_cont(0.5) within group (order by x) m "
               "from ps group by rollup(g) "
               "order by percentile_cont(0.5) within group (order by x)")
    meds = [m for _, m in r.rows()]
    assert meds == sorted(meds)
