"""FROM-less SELECT as a relation (PG Result node / ConstRel leaf) +
cartesian joins against small relations + UNION in derived tables."""

import numpy as np
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table t (a int, b int) distributed by (a)")
    d.sql("insert into t values (5, 1), (6, 2), (7, 3)")
    yield d
    d.close()


def test_constant_subquery(db):
    assert db.sql("select q.x from (select 1 as x) q").rows() == [(1,)]
    assert db.sql("select x + y from (select 2 as x, 3 as y) q").rows() \
        == [(5,)]


def test_cross_join_constants_onto_table(db):
    r = db.sql("select a, s.x from t, (select 41 as x) s order by a")
    assert r.rows() == [(5, 41), (6, 41), (7, 41)]


def test_plain_cte_constant_body(db):
    r = db.sql("with c as (select 7 as v) select a + c.v from t, c "
               "order by 1")
    assert r.rows() == [(12,), (13,), (14,)]


def test_union_in_derived_table(db):
    r = db.sql("select x from (select 1 as x union all select 2) u "
               "order by x")
    assert r.rows() == [(1,), (2,)]


def test_small_cartesian_product(db):
    r = db.sql("select a, u.y from t, (select 1 as y union all select 2) u "
               "order by a, y")
    assert r.rows() == [(5, 1), (5, 2), (6, 1), (6, 2), (7, 1), (7, 2)]


def test_cartesian_with_aggregate(db):
    r = db.sql("select count(*), sum(a + u.y) from t, "
               "(select 10 as y union all select 20) u")
    assert r.rows() == [(6, (5 + 6 + 7) * 2 + 3 * 30)]


def test_recursive_cte_constant_base(db):
    r = db.sql("with recursive s(n) as (select 1 union all "
               "select n + 1 from s where n < 6) select sum(n) from s")
    assert r.rows() == [(21,)]
