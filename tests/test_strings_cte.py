"""CTEs (WITH), FULL OUTER JOIN, and string scalar functions.

Reference parity targets: WITH binding in parse_analyze / ShareInputScan
(src/backend/executor/nodeShareInputScan.c — here: inline expansion + XLA
CSE), FULL hash join fill (src/backend/executor/nodeHashjoin.c HJ_FILL
logic — here: left-join ∪ anti-join union rewrite), and the varlena
string functions (src/backend/utils/adt/varlena.c, oracle_compat.c).
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table t (a int, b int) distributed by (a)")
    d.sql("insert into t values (1, 10), (2, 20), (3, 30), (4, 40)")
    d.sql("create table s (a int, c int) distributed by (a)")
    d.sql("insert into s values (3, 300), (4, 400), (5, 500), (6, 600)")
    d.sql("create table w (k int, tag text) distributed by (k)")
    d.sql("insert into w values (1, 'alpha'), (2, 'Beta'), (3, 'GAMMA q'), "
          "(4, 'alpha')")
    return d


@pytest.fixture(scope="module")
def rawdb(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table r (a int, c text) distributed by (a)")
    col = d.catalog.get("r").column("c")
    object.__setattr__(col, "encoding", "raw")
    d.load_table("r", {
        "a": np.array([1, 2, 3], np.int32),
        "c": np.array(["Hello World", "bye", "  pad  "], dtype=object),
    })
    return d


# ---------------------------------------------------------------------------
# WITH
# ---------------------------------------------------------------------------

def test_cte_basic(db):
    r = db.sql("with big as (select a, b from t where b > 15) "
               "select a, b from big order by a")
    assert r.rows() == [(2, 20), (3, 30), (4, 40)]


def test_cte_referenced_twice(db):
    r = db.sql("with c as (select a, b from t) "
               "select x.a, y.b from c x join c y on x.a = y.a order by x.a")
    assert r.rows() == [(1, 10), (2, 20), (3, 30), (4, 40)]


def test_cte_column_aliases(db):
    r = db.sql("with c(k, v) as (select a, b from t) "
               "select k, v from c where v >= 30 order by k")
    assert r.rows() == [(3, 30), (4, 40)]


def test_cte_chained(db):
    r = db.sql("with c1 as (select a, b from t), "
               "c2 as (select a from c1 where b > 25) "
               "select a from c2 order by a")
    assert r.rows() == [(3,), (4,)]


def test_cte_with_aggregate_body(db):
    r = db.sql("with totals as (select a, sum(b) as sb from t group by a) "
               "select count(*), sum(sb) from totals")
    assert r.rows() == [(4, 100)]


def test_cte_in_derived_table(db):
    r = db.sql("select q.a from "
               "(with c as (select a from t where a > 2) "
               "select a from c) q order by a")
    assert r.rows() == [(3,), (4,)]


def test_cte_shadows_table(db):
    # CTE name takes precedence over a catalog table of the same name
    r = db.sql("with s as (select a from t where a = 1) select a from s")
    assert r.rows() == [(1,)]


def test_cte_recursive_keyword_non_self_ref(db):
    # RECURSIVE with a non-self-referencing CTE degrades to a plain CTE
    # (PG semantics); actual recursion lives in tests/test_recursive_cte.py
    r = db.sql("with recursive c as (select a from t where a = 1) "
               "select * from c")
    assert r.rows() == [(1,)]


def test_cte_union_body(db):
    r = db.sql("with c as (select a from t where a <= 1 union all "
               "select a from t where a >= 4) select a from c order by a")
    assert r.rows() == [(1,), (4,)]


# ---------------------------------------------------------------------------
# FULL OUTER JOIN
# ---------------------------------------------------------------------------

def test_full_join_rows(db):
    r = db.sql("select t.a, t.b, s.c from t full join s on t.a = s.a "
               "order by t.a nulls last, s.c")
    assert r.rows() == [
        (1, 10, None), (2, 20, None), (3, 30, 300), (4, 40, 400),
        (None, None, 500), (None, None, 600)]


def test_full_join_counts(db):
    r = db.sql("select count(*), count(t.b), count(s.c) "
               "from t full outer join s on t.a = s.a")
    assert r.rows() == [(6, 4, 4)]


def test_full_join_where(db):
    # WHERE after the join filters null-extended rows like PG
    r = db.sql("select t.a, s.c from t full join s on t.a = s.a "
               "where s.c is null order by t.a")
    assert r.rows() == [(1, None), (2, None)]


def test_full_join_aggregate_grouped(db):
    r = db.sql("select s.a, count(t.a) from t full join s on t.a = s.a "
               "group by s.a order by s.a nulls first")
    assert r.rows() == [(None, 2), (3, 1), (4, 1), (5, 0), (6, 0)]


def test_full_join_non_equi_rejected(db):
    with pytest.raises(SqlError, match="equality"):
        db.sql("select * from t full join s on t.a = s.a and t.b > s.c")


# ---------------------------------------------------------------------------
# string functions: dictionary columns
# ---------------------------------------------------------------------------

def test_upper_lower_projection(db):
    r = db.sql("select k, upper(tag), lower(tag) from w order by k")
    assert r.rows() == [
        (1, "ALPHA", "alpha"), (2, "BETA", "beta"),
        (3, "GAMMA Q", "gamma q"), (4, "ALPHA", "alpha")]


def test_length_substring(db):
    r = db.sql("select k, length(tag), substring(tag, 2, 3) from w "
               "order by k")
    assert r.rows() == [(1, 5, "lph"), (2, 4, "eta"), (3, 7, "AMM"),
                        (4, 5, "lph")]


def test_substring_from_for_syntax(db):
    r = db.sql("select k from w where substring(tag from 1 for 1) = 'a' "
               "order by k")
    assert r.rows() == [(1,), (4,)]


def test_concat_operator(db):
    r = db.sql("select k, 'x-' || tag || '!' from w order by k limit 2")
    assert r.rows() == [(1, "x-alpha!"), (2, "x-Beta!")]


def test_group_by_string_function(db):
    r = db.sql("select upper(tag) as u, count(*) from w group by upper(tag) "
               "order by u")
    assert r.rows() == [("ALPHA", 2), ("BETA", 1), ("GAMMA Q", 1)]


def test_where_on_function_result(db):
    assert db.sql("select k from w where upper(tag) = 'ALPHA' "
                  "order by k").rows() == [(1,), (4,)]
    assert db.sql("select k from w where length(tag) > 5").rows() == [(3,)]


def test_function_like(db):
    r = db.sql("select k from w where lower(tag) like '%a%q' order by k")
    assert r.rows() == [(3,)]


def test_nested_functions(db):
    r = db.sql("select k, upper(substring(trim(tag), 1, 2)) from w "
               "order by k limit 2")
    assert r.rows() == [(1, "AL"), (2, "BE")]


def test_replace_trim_pad(db):
    r = db.sql("select replace(tag, 'a', 'o'), lpad(tag, 7, '.') from w "
               "where k = 1")
    assert r.rows() == [("olpho", "..alpha")]


def test_literal_folding(db):
    r = db.sql("select k from w where 'FOO' = upper('foo') and k = 1")
    assert r.rows() == [(1,)]


def test_strpos(db):
    assert db.sql("select k from w where strpos(tag, 'q') > 0").rows() \
        == [(3,)]


def test_order_by_string_function(db):
    r = db.sql("select k from w order by lower(tag) desc, k")
    assert [x[0] for x in r.rows()] == [3, 2, 1, 4]


# ---------------------------------------------------------------------------
# string functions: raw-encoded columns (host chains)
# ---------------------------------------------------------------------------

def test_raw_projection_chain(rawdb):
    r = rawdb.sql("select a, upper(c) from r order by a")
    assert r.rows() == [(1, "HELLO WORLD"), (2, "BYE"), (3, "  PAD  ")]


def test_raw_predicate_chains(rawdb):
    assert rawdb.sql("select a from r where length(c) > 5 "
                     "order by a").rows() == [(1,), (3,)]
    assert rawdb.sql("select a from r where upper(c) like 'HELLO%'").rows() \
        == [(1,)]
    assert rawdb.sql("select a from r where substring(c, 1, 1) in ('H', 'b') "
                     "order by a").rows() == [(1,), (2,)]
    assert rawdb.sql("select a from r where 3 = length(c)").rows() == [(2,)]


def test_raw_concat_projection(rawdb):
    r = rawdb.sql("select a, trim(c) || '.' from r order by a")
    assert r.rows() == [(1, "Hello World."), (2, "bye."), (3, "pad.")]


def test_raw_length_projection_device(rawdb):
    # ISSUE 13: length(raw) is a device byte-window int32 (E.RawStrOp) —
    # projectable anywhere, not just WHERE (the pre-fusion rejection)
    r = rawdb.sql("select a, length(c) from r order by a")
    assert r.rows() == [(1, 11), (2, 3), (3, 7)]


def test_raw_group_by_function(rawdb):
    # round-2: function-of-raw group keys lower through the transient
    # dictionary + derived-dictionary LUT chain
    r = rawdb.sql("select upper(c) as u, count(*) from r group by upper(c) "
                  "order by u")
    assert r.rows() == [("  PAD  ", 1), ("BYE", 1), ("HELLO WORLD", 1)]


def test_left_right_functions(db):
    r = db.sql("select left(tag, 2), right(tag, 2) from w where k = 1")
    assert r.rows() == [("al", "ha")]


def test_raw_length_in_arithmetic_and_aggs(rawdb):
    # ISSUE 13: the device length view is a real int32 — arithmetic and
    # aggregates over it are legal now (the surrogate never leaks: the
    # byte-window op replaces it before any numeric context sees it)
    assert rawdb.sql(
        "select a from r where length(c) + 0 = 11").rows() == [(1,)]
    assert rawdb.sql("select sum(length(c)) from r").rows() == [(21,)]


def test_raw_chain_through_subquery(rawdb):
    r = rawdb.sql("select u from (select a, upper(c) as u from r) q "
                  "order by a")
    assert [x[0] for x in r.rows()] == ["HELLO WORLD", "BYE", "  PAD  "]
    r = rawdb.sql("select * from (select a, trim(c) as v from r) q "
                  "order by a")
    assert [x[1] for x in r.rows()] == ["Hello World", "bye", "pad"]


def test_raw_order_by_chain(rawdb):
    # round-2: raw sort keys ride transient-dictionary codes; chains
    # (length/upper) compose through derived dictionaries
    assert [x[0] for x in rawdb.sql(
        "select a from r order by length(c), a").rows()] == [2, 3, 1]
    assert [x[0] for x in rawdb.sql(
        "select a from r order by upper(c)").rows()] == [3, 2, 1]


def test_raw_chain_case_through_subquery_rejected(rawdb):
    with pytest.raises(SqlError, match="CASE"):
        rawdb.sql("select case when a > 0 then u else u end "
                  "from (select a, upper(c) as u from r) s")


def test_cte_nested_with_outer_reference(db):
    r = db.sql("with a1 as (select a, b from t), "
               "b1 as (with c1 as (select a from a1 where b > 25) "
               "select a from c1) select a from b1 order by a")
    assert r.rows() == [(3,), (4,)]


def test_negative_substring_length_is_sql_error(db, rawdb):
    with pytest.raises(SqlError, match="negative substring length"):
        db.sql("select substring(tag, 2, -1) from w")
    with pytest.raises(SqlError, match="negative substring length"):
        rawdb.sql("select a from r where substring(c, 2, -1) = 'x'")
    with pytest.raises(SqlError, match="negative substring length"):
        db.sql("select k from w where substring('abc', 1, -2) = 'a'")


def test_raw_chain_decimal_compare(rawdb):
    r = rawdb.sql("select a from r where length(c) > 2.5 order by a")
    assert r.rows() == [(1,), (2,), (3,)]
    assert rawdb.sql("select a from r where length(c) < 3.5").rows() == [(2,)]
