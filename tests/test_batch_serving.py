"""Vectorized serving (ISSUE 11, docs/PERF.md "Vectorized serving"):
batch concurrent same-shape statements into ONE XLA dispatch behind the
async executor pipeline (exec/batchserve.py).

The contract under test:
  (a) demux correctness — every member of a batch gets exactly the rows
      a serial execution of its statement returns, across mixed
      literals (ints, floats, ORDER BY/LIMIT shapes);
  (b) width-bucketed compiles — N same-shape members compile once per
      observed pow2 width bucket (jit-count + counter verified), never
      once per width;
  (c) cancellation isolation — a cancelled member raises its typed
      StatementCancelled and its batch-mates' results are untouched;
  (d) window behavior — full windows flush on batch_max_width, partial
      windows flush on the batch_window_ms timer;
  (e) pipelining — stage(k+1) overlaps dispatch(k), asserted from the
      batch traces' span timestamps (a sleep fault pins the overlap
      deterministically);
  (f) the disabled path spawns no pipeline and serves classically.
"""

import threading
import time

import numpy as np
import pytest

import greengage_tpu
import greengage_tpu.exec.compile as C
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.interrupt import REGISTRY, StatementCancelled
from greengage_tpu.runtime.logger import counters
from greengage_tpu.sql.parser import parse
from greengage_tpu.sql.paramize import ParamVector


@pytest.fixture()
def jits(monkeypatch):
    """Counts compiled programs: exec/compile.py wraps every traced
    query program in exactly one jax.jit call."""
    calls = {"n": 0}
    real = C.jax.jit

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(C.jax, "jit", counting)
    return calls


@pytest.fixture()
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table t (k int, a int, v double precision, g int) "
          "distributed by (k)")
    n = 3000
    vals = np.arange(n) * 0.5
    d.load_table("t", {"k": np.arange(n, dtype=np.int32),
                       "a": np.arange(n, dtype=np.int32),
                       "v": vals,
                       "g": np.arange(n, dtype=np.int32) % 7})
    yield d
    faults.reset("batch_dispatch")
    d.close()


def _q(i: int) -> str:
    return f"select count(*), sum(v) from t where a > {i}"


def _rows_match(got, want) -> bool:
    """Row-set equality with FP tolerance: a vmapped program's HLO may
    round differently at the ulp level (e.g. divide vs reciprocal
    multiply) than the classic program — SQL float semantics do not pin
    the associativity, so the oracle compare must not either."""
    if len(got) != len(want):
        return False
    for rg, rw in zip(got, want):
        if len(rg) != len(rw):
            return False
        for a, b in zip(rg, rw):
            if isinstance(a, float) or isinstance(b, float):
                if b != pytest.approx(a, rel=1e-9, abs=1e-12):
                    return False
            elif a != b:
                return False
    return True


def _serve(db, sqls: dict, timeout=60.0):
    """Run each sql on its own thread (the server's one-connection-one-
    thread shape); -> ({key: rows}, {key: exception})."""
    results, errors = {}, {}

    def worker(key, sql):
        try:
            results[key] = db.sql(sql).rows()
        except Exception as e:   # noqa: BLE001 — the assertion surface
            errors[key] = e

    ts = [threading.Thread(target=worker, args=(k, s))
          for k, s in sqls.items()]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in ts), "serving threads hung"
    return results, errors


# ---------------------------------------------------------------------
# (a) demux correctness vs the serial oracle
# ---------------------------------------------------------------------
def test_demux_matches_serial_oracle(db):
    mixed = {
        # int literal spread
        **{f"i{i}": _q(100 + i) for i in range(6)},
        # float literal + projection arithmetic
        "f1": "select k, v * 2.5 from t where v < 10.0 and a >= 3",
        "f2": "select k, v * 7.5 from t where v < 4.0 and a >= 1",
        # ORDER BY + LIMIT exercises per-member merge keys + host trim
        "o1": "select k, v from t where a > 2990 order by v desc",
        "o2": "select k, v from t where a > 2980 order by v desc",
    }
    oracle = {k: db.sql(s).rows() for k, s in mixed.items()}

    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_window_ms = 150")
    for s in mixed.values():
        db.sql(s)   # warm plan cache + width-1 buckets, serially
    # hold the first dispatch so a real multi-member window accumulates
    faults.inject("batch_dispatch", "sleep", sleep_s=0.4, occurrences=1)
    c0 = counters.snapshot()
    results, errors = _serve(db, mixed)
    d = counters.since(c0)
    assert not errors, errors
    for k in mixed:
        assert _rows_match(results[k], oracle[k]), k
    # amortization really happened: fewer dispatches than members
    assert d.get("batch_members_total", 0) > d.get("batch_dispatch_total", 0)
    assert d.get("batch_fallback_total", 0) == 0, d


# ---------------------------------------------------------------------
# (b) one compile per observed pow2 width bucket
# ---------------------------------------------------------------------
def test_compile_once_per_width_bucket(db, jits):
    stmt = parse(_q(100))[0]
    planned, consts, outs, ek = db._cached_plan(stmt)
    pv = consts["@params@"]

    def rows(vals):
        return [ParamVector((v,), pv.types) for v in vals]

    # oracle values FIRST: the first classic execution compiles the
    # classic (width-0) program, which must not count against buckets
    oracle = {v: db.sql(_q(v)).rows()
              for v in (100, 7, 9, 1, 2, 3, 4, 5)}

    n0 = jits["n"]
    res = db.executor.run_batch(planned, consts, outs, ek, rows([100, 7, 9]))
    assert jits["n"] == n0 + 1          # bucket 4 compiles once
    for v, r in zip((100, 7, 9), res):
        assert r.rows() == oracle[v]
    c0 = counters.snapshot()
    res = db.executor.run_batch(planned, consts, outs, ek,
                                rows([1, 2, 3, 4]))
    assert jits["n"] == n0 + 1, "same bucket must not recompile"
    assert counters.since(c0).get("program_cache_hit", 0) == 1
    for v, r in zip((1, 2, 3, 4), res):
        assert r.rows() == oracle[v]
    db.executor.run_batch(planned, consts, outs, ek, rows([5] * 5))
    assert jits["n"] == n0 + 2          # bucket 8 is a new program

    # warm the remaining pow2 buckets (1, 2, 16), then drive 16
    # concurrent same-shape statements through the real pipeline:
    # whatever widths the windows happened to form, every bucket is
    # warm, so the storm must compile NOTHING (counter-verified)
    for w in (1, 2, 16):
        db.executor.run_batch(planned, consts, outs, ek, rows([6] * w))
    n_all = jits["n"]
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_window_ms = 100")
    c0 = counters.snapshot()
    faults.inject("batch_dispatch", "sleep", sleep_s=0.3, occurrences=1)
    results, errors = _serve(db, {i: _q(600 + i) for i in range(16)})
    assert not errors, errors
    d = counters.since(c0)
    assert jits["n"] == n_all, \
        "a warm width bucket must serve every later batch of its width"
    assert d.get("batch_members_total", 0) == 16
    assert d.get("program_cache_miss", 0) == 0, d


# ---------------------------------------------------------------------
# (c) per-member cancellation isolation
# ---------------------------------------------------------------------
def test_member_cancel_leaves_mates_intact(db):
    oracle = {i: db.sql(_q(i)).rows() for i in (300, 301, 302, 303)}
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_window_ms = 200")
    db.sql(_q(300))   # warm
    # plug: one statement rides a dispatch held on-device by the fault,
    # so the three real members accumulate in the next window
    faults.inject("batch_dispatch", "sleep", sleep_s=0.6, occurrences=1)
    results, errors = {}, {}

    def worker(i):
        try:
            results[i] = db.sql(_q(i)).rows()
        except StatementCancelled as e:
            errors[i] = e.cause

    plug = threading.Thread(target=worker, args=(300,))
    plug.start()
    time.sleep(0.1)
    ts = [threading.Thread(target=worker, args=(i,))
          for i in (301, 302, 303)]
    for t in ts:
        t.start()
    time.sleep(0.15)   # members parked in the window / staged batch
    target = [r for r in REGISTRY.snapshot() if "> 302" in r["sql"]]
    assert target, "member 302 should be in flight"
    assert REGISTRY.cancel(target[0]["id"], "user")
    for t in ts:
        t.join(timeout=30)
    plug.join(timeout=30)
    # the cancelled member died with its typed cause; its batch-mates'
    # results match the serial oracle exactly
    assert errors == {302: "user"}
    for i in (300, 301, 303):
        assert results[i] == oracle[i], i


# ---------------------------------------------------------------------
# (d) window flush reasons: full vs timer
# ---------------------------------------------------------------------
def test_window_flush_full_vs_timer(db):
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_max_width = 4")
    # a wide window for the FULL-flush phase: the flush must come from
    # the width cap, and a straggling thread start must not let the
    # timer fire first and split the members across two partial windows
    db.sql("set batch_window_ms = 800")
    db.sql(_q(42))   # warm width-1
    try:
        # hold the pipeline so windows accumulate rather than flush idle
        faults.inject("batch_dispatch", "sleep", sleep_s=1.0, occurrences=1)
        plug = threading.Thread(target=db.sql, args=(_q(42),))
        plug.start()
        time.sleep(0.1)
        c0 = counters.snapshot()
        # exactly max_width members: the window must flush FULL (well
        # before its 800 ms deadline — the sleep holds the device)
        results, errors = _serve(db, {i: _q(700 + i) for i in range(4)})
        assert not errors, errors
        d = counters.since(c0)
        assert d.get("batch_window_flush_full", 0) >= 1, d
        plug.join(timeout=30)

        # a partial window behind a busy pipeline flushes on the TIMER
        db.sql("set batch_window_ms = 120")
        faults.inject("batch_dispatch", "sleep", sleep_s=0.5, occurrences=1)
        plug = threading.Thread(target=db.sql, args=(_q(43),))
        plug.start()
        time.sleep(0.1)
        c0 = counters.snapshot()
        results, errors = _serve(db, {i: _q(800 + i) for i in range(2)})
        assert not errors, errors
        d = counters.since(c0)
        assert d.get("batch_window_flush_timer", 0) >= 1, d
        assert d.get("batch_window_flush_full", 0) == 0, d
        plug.join(timeout=30)
    finally:
        db.sql("set batch_max_width = 16")


# ---------------------------------------------------------------------
# (e) pipelining: stage(k+1) overlaps dispatch(k)
# ---------------------------------------------------------------------
def test_pipeline_stage_overlaps_dispatch(db):
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_max_width = 4")
    db.sql("set batch_window_ms = 60")
    db.sql(_q(0))   # warm
    try:
        # every dispatch sleeps 0.4 s on the "device": while batch k
        # sleeps there, the stager must stage batch k+1
        faults.inject("batch_dispatch", "sleep", sleep_s=0.4,
                      occurrences=-1)
        results, errors = _serve(db, {i: _q(900 + i) for i in range(8)})
        assert not errors, errors
        faults.reset("batch_dispatch")
        batches = [b for b in db._batch_server.recent
                   if b.find_spans("dispatch")]
        assert len(batches) >= 2, "expected at least two flushed batches"

        def absolute(tr, name):
            spans = tr.find_spans(name)
            assert spans, (name, [s["name"] for s in tr.export()])
            s = spans[0]
            start = tr.wall0 + s["ts"] / 1e3
            return start, start + (s["dur"] or 0.0) / 1e3

        # the pipeline property: batch k+1's STAGE begins before batch
        # k's DISPATCH ends (each dispatch holds the device >=0.4 s via
        # the fault, so a serial stage-after-dispatch pipeline could
        # never produce this ordering). Staging that finished even
        # before the next dispatch STARTED is more overlapped, not less
        # — so the assertion is on the stage-start vs dispatch-end edge.
        batches.sort(key=lambda tr: absolute(tr, "dispatch")[0])
        pipelined = False
        for prev, nxt in zip(batches, batches[1:]):
            d0, d1 = absolute(prev, "dispatch")
            s0, _s1 = absolute(nxt, "stage")
            if s0 < d1:
                pipelined = True
        assert pipelined, \
            "every stage serialized behind the previous dispatch"
    finally:
        faults.reset("batch_dispatch")
        db.sql("set batch_max_width = 16")


# ---------------------------------------------------------------------
# (f) the disabled path is untouched
# ---------------------------------------------------------------------
def test_disabled_path_spawns_nothing(db):
    r = db.sql(_q(100))
    assert db._batch_server is None, \
        "batching off must not create the serving pipeline"
    assert "batched" not in (r.stats or {})
    assert r.rows()[0][0] == 2899


def test_fallback_routes_members_to_serial_path(db, monkeypatch):
    """Any overflow flag (value-dependent capacity need, duplicate join
    keys) sends the WHOLE window down the classic serial path: members
    still get correct results, the fallback is counted, and nothing
    surfaces to the client."""
    oracle = {i: db.sql(_q(i)).rows() for i in (400, 401, 402)}
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_window_ms = 150")
    db.sql(_q(400))   # warm
    monkeypatch.setattr(db.executor, "batch_overflowed",
                        lambda comp, flat: ["join_expand_overflow_0"])
    faults.inject("batch_dispatch", "sleep", sleep_s=0.3, occurrences=1)
    c0 = counters.snapshot()
    results, errors = _serve(db, {i: _q(i) for i in (400, 401, 402)})
    d = counters.since(c0)
    assert not errors, errors
    for i in (400, 401, 402):
        assert results[i] == oracle[i], i
    assert d.get("batch_fallback_total", 0) >= 1, d
    # the serial re-runs landed on the classic (bucket-0) warm program
    assert d.get("batch_members_total", 0) == 0, d


def test_stop_releases_waiting_members(db):
    """BatchServer.stop() (Database.close) must release members parked
    in open windows — each degrades to the classic serial path on its
    own thread instead of waiting out the wedge timeout against a dead
    pipeline — and statements issued after stop still serve classically."""
    oracle = {i: db.sql(_q(i)).rows() for i in (500, 501, 502)}
    db.sql("set batch_serving_enabled = on")
    db.sql("set batch_window_ms = 800")
    db.sql(_q(500))   # warm + spawn the pipeline
    faults.inject("batch_dispatch", "sleep", sleep_s=1.0, occurrences=1)
    plug = threading.Thread(target=db.sql, args=(_q(500),))
    plug.start()
    time.sleep(0.1)
    results, errors = {}, {}

    def worker(i):
        try:
            results[i] = db.sql(_q(i)).rows()
        except Exception as e:   # noqa: BLE001
            errors[i] = e

    ts = [threading.Thread(target=worker, args=(i,)) for i in (501, 502)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    time.sleep(0.2)   # members parked in the open window
    db._batch_server.stop()
    for t in ts:
        t.join(timeout=30)
    plug.join(timeout=30)
    assert not any(t.is_alive() for t in ts)
    assert not errors, errors
    # released promptly (classic re-run), nowhere near the wedge timeout
    assert time.monotonic() - t0 < 20
    for i in (501, 502):
        assert results[i] == oracle[i], i
    # post-stop statements still serve (classic path, dead pipeline)
    assert db.sql(_q(502)).rows() == oracle[502]


def test_batched_stats_and_trace_graft(db):
    """A batched member's Result carries the batch stats block and its
    statement trace contains the grafted batch-dispatch subtree."""
    from greengage_tpu.runtime.trace import TRACES

    db.sql("set batch_serving_enabled = on")
    db.sql(_q(55))   # warm; idle pipeline -> immediate width-1 flush
    r = db.sql(_q(56))
    assert r.stats and r.stats.get("batched") is True
    assert r.stats.get("batch_width") == 1
    assert r.stats.get("batch_bucket") == 1
    tr = TRACES.last()
    # the member's own trace shows the whole batch: wait span + grafted
    # batch-dispatch + the member child
    names = {s["name"] for s in tr.export()}
    assert "batch-wait" in names, names
    assert "batch-dispatch" in names, names
    assert "batch-member" in names, names
