"""Concurrent sessions — VERDICT r1 item #7 (the isolation2 / multi-client
analog): thread-safe Database, optimistic writer retry across Database
objects, DML inside transactions, and the line-protocol server."""

import threading
import time

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table acc (id int, bal int) distributed by (id)")
    d.sql("insert into acc values " + ",".join(f"({i},100)" for i in range(40)))
    return d


def test_threaded_writers_same_database(db):
    """Two threads inserting through ONE Database serialize on the write
    lock; all rows land."""
    errs = []

    def w(lo):
        try:
            for i in range(5):
                db.sql(f"insert into acc values ({lo + i}, 1)")
        except Exception as e:   # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=w, args=(1000,)),
          threading.Thread(target=w, args=(2000,))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs
    assert db.sql("select count(*) from acc").rows()[0][0] == 50


def test_cross_database_writers_retry(db):
    """Two Database objects on the same cluster dir: the CAS loser retries
    against the fresh snapshot and both commits land (no dictionary growth
    involved, so retry is safe)."""
    db2 = greengage_tpu.connect(db.path)
    errs = []

    def w(d, lo):
        try:
            for i in range(4):
                d.sql(f"insert into acc values ({lo + i}, 7)")
        except Exception as e:
            errs.append(e)

    ts = [threading.Thread(target=w, args=(db, 3000,)),
          threading.Thread(target=w, args=(db2, 4000,))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    db3 = greengage_tpu.connect(db.path)
    assert db3.sql("select count(*) from acc").rows()[0][0] == 48


def test_reader_sees_consistent_snapshots_during_writes(db):
    """A reader thread polling counts must only ever observe committed
    row-count multiples (snapshot isolation; no torn reads)."""
    stop = threading.Event()
    seen = []
    errs = []

    def reader():
        try:
            while not stop.is_set():
                n = db.sql("select count(*) from acc").rows()[0][0]
                seen.append(int(n))
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=reader)
    t.start()
    for b in range(6):
        db.sql("insert into acc values " + ",".join(
            f"({5000 + b * 10 + i}, 1)" for i in range(10)))
    stop.set()
    t.join()
    assert not errs, errs
    assert all(n % 10 == 0 for n in seen), seen
    assert sorted(set(seen))[-1] <= 100


def test_dml_inside_transaction(db):
    db.sql("begin")
    db.sql("update acc set bal = 0 where id < 10")
    # committed snapshot still visible inside the tx
    assert db.sql("select sum(bal) from acc").rows()[0][0] == 4000
    db.sql("commit")
    assert db.sql("select sum(bal) from acc").rows()[0][0] == 3000


def test_dml_rollback_inside_transaction(db):
    db.sql("begin")
    db.sql("delete from acc where id >= 0")
    db.sql("rollback")
    assert db.sql("select count(*) from acc").rows()[0][0] == 40


def test_dml_after_insert_same_table_rejected(db):
    db.sql("begin")
    db.sql("insert into acc values (999, 5)")
    with pytest.raises(SqlError) as ei:
        db.sql("update acc set bal = 1 where id = 999")
    assert "already modified" in str(ei.value)
    db.sql("rollback")


def test_interleaving_with_fault_point(db):
    """isolation2-style: a writer suspended after prepare must not be
    visible to a concurrent reader; after commit it is."""
    counts = {}
    faults.inject("dtx_after_prepare", "sleep", sleep_s=0.5)

    def writer():
        db.sql("begin")
        db.sql("insert into acc values (7777, 1)")
        db.sql("commit")

    t = threading.Thread(target=writer)
    t.start()
    time.sleep(0.2)   # writer is inside the post-prepare sleep
    counts["during"] = db.sql(
        "select count(*) from acc where id = 7777").rows()[0][0]
    t.join()
    counts["after"] = db.sql(
        "select count(*) from acc where id = 7777").rows()[0][0]
    assert counts == {"during": 0, "after": 1}


def test_server_concurrent_clients(db, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        results = {}
        errs = []

        def client(name, stmts):
            try:
                c = SqlClient(sock)
                out = [c.sql(s) for s in stmts]
                results[name] = out
                c.close()
            except Exception as e:
                errs.append(e)

        ts = [
            threading.Thread(target=client, args=("r1", [
                "select count(*) from acc"] * 5)),
            threading.Thread(target=client, args=("w", [
                "insert into acc values (8000, 1)",
                "update acc set bal = 42 where id = 8000",
                "select bal from acc where id = 8000"])),
            threading.Thread(target=client, args=("r2", [
                "select sum(bal) from acc"] * 5)),
        ]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs, errs
        assert results["w"][2]["rows"] == [[42]]
        # errors are per-statement: the connection stays usable
        c = SqlClient(sock)
        with pytest.raises(RuntimeError):
            c.sql("select * from nosuch")
        assert c.sql("select count(*) from acc")["rows"][0][0] == 41
        c.close()
        assert srv.connections_served >= 4
    finally:
        srv.stop()


def test_eight_appenders_four_tables_zero_cas_retries(devices8, tmp_path):
    """The per-table delta-manifest acceptance matrix: 8 concurrent
    appenders across 4 tables all commit with ZERO manifest CAS retries —
    writers to different tables never contend on the commit path (each
    table's delta sequence is its own CAS, the per-segment-WAL analog),
    and same-table appenders stage write intents (or, for dict-growing
    tables, serialize on the session's per-table lock) rather than spin
    on a global manifest claim."""
    from greengage_tpu.runtime.logger import counters

    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    for t in "abcd":
        d.sql(f"create table {t} (k int, v int) distributed by (k)")
    retry_base = counters.get("manifest_cas_retry_total")
    delta_base = (counters.get("manifest_delta_commits")
                  + counters.get("manifest_intent_commits"))
    errs = []

    def appender(table, lo):
        try:
            for i in range(6):
                d.sql(f"insert into {table} values ({lo + i}, 1)")
        except Exception as e:
            errs.append(e)

    ts = [threading.Thread(target=appender, args=(t, 1000 * j))
          for j, t in enumerate("abcd" * 2)]    # 2 appenders per table
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    assert counters.get("manifest_cas_retry_total") == retry_base
    assert (counters.get("manifest_delta_commits")
            + counters.get("manifest_intent_commits")) >= delta_base + 48
    for t in "abcd":
        assert d.sql(f"select count(*) from {t}").rows()[0][0] == 12


def test_cross_database_cross_table_appenders_zero_retries(devices8,
                                                           tmp_path):
    """Two Database OBJECTS on one cluster dir (the cross-process analog,
    where no in-process lock can help) appending to DIFFERENT tables:
    the per-table sequence CAS means neither writer ever retries."""
    from greengage_tpu.runtime.logger import counters

    d1 = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d1.sql("create table ta (k int, v int) distributed by (k)")
    d1.sql("create table tb (k int, v int) distributed by (k)")
    d2 = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    retry_base = counters.get("manifest_cas_retry_total")
    errs = []

    def w(d, table):
        try:
            for i in range(8):
                d.sql(f"insert into {table} values ({i}, 7)")
        except Exception as e:
            errs.append(e)

    ts = [threading.Thread(target=w, args=(d1, "ta")),
          threading.Thread(target=w, args=(d2, "tb"))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    assert counters.get("manifest_cas_retry_total") == retry_base
    d3 = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    assert d3.sql("select count(*) from ta").rows()[0][0] == 8
    assert d3.sql("select count(*) from tb").rows()[0][0] == 8


def test_commit_during_reform_fault_aborts_cleanly(db):
    """The commit_during_reform fault point sits exactly where a mesh
    re-formation would race a 2PC committer (after the per-table claims,
    before the commit-log line): an error there must abort the tx with
    every claim released, admitting the next writer immediately."""
    faults.inject("commit_during_reform", "error", occurrences=1)
    try:
        db.sql("begin")
        db.sql("insert into acc values (7878, 1)")
        with pytest.raises(Exception, match="commit_during_reform"):
            db.sql("commit")
    finally:
        faults.reset("commit_during_reform")
    assert db.sql("select count(*) from acc where id = 7878").rows()[0][0] == 0
    db.sql("insert into acc values (7879, 1)")   # claims were released
    assert db.sql("select count(*) from acc where id = 7879").rows()[0][0] == 1


@pytest.mark.slow
def test_appender_storm_folds_racing_commits(devices8, tmp_path):
    """Chaos tier (the tier1.yml non-blocking chaos step): 16 appenders
    over 4 tables with the fold threshold at 1 — every commit tries to
    checkpoint, so root folds race delta prepares continuously — and a
    sleep-type delta_fold fault parking early folds mid-window to widen
    the race. Still ZERO cross-table CAS retries, every row lands, and
    the backlog drains to a plain root on recover()."""
    from greengage_tpu.runtime.logger import counters

    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("set manifest_delta_fold_threshold = 1")
    for t in "abcd":
        d.sql(f"create table {t} (k int, v int) distributed by (k)")
    retry_base = counters.get("manifest_cas_retry_total")
    faults.inject("delta_fold", "sleep", sleep_s=0.05, occurrences=8)
    errs = []

    def appender(table, lo):
        try:
            for i in range(10):
                d.sql(f"insert into {table} values ({lo + i}, 1)")
        except Exception as e:
            errs.append(e)

    try:
        ts = [threading.Thread(target=appender, args=(t, 1000 * j))
              for j, t in enumerate("abcd" * 4)]   # 4 appenders per table
        [t.start() for t in ts]
        [t.join() for t in ts]
    finally:
        faults.reset("delta_fold")
    assert not errs, errs
    assert counters.get("manifest_cas_retry_total") == retry_base
    for t in "abcd":
        assert d.sql(f"select count(*) from {t}").rows()[0][0] == 40
    # a fresh open compacts whatever backlog the storm left behind
    d2 = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    assert d2.store.manifest.delta_backlog() == 0
    for t in "abcd":
        assert d2.sql(f"select count(*) from {t}").rows()[0][0] == 40


def test_server_wire_transactions(db, tmp_path):
    """BEGIN/COMMIT are per connection: another client never sees
    uncommitted rows; ROLLBACK discards; a dropped connection aborts."""
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        a, b = SqlClient(sock), SqlClient(sock)
        a.sql("begin")
        a.sql("insert into acc values (9000, 7)")
        # invisible to b until a commits
        assert b.sql("select count(*) from acc where id = 9000")["rows"] == [[0]]
        a.sql("commit")
        deadline = time.time() + 5
        while time.time() < deadline:
            if b.sql("select count(*) from acc where id = 9000")["rows"] == [[1]]:
                break
        assert b.sql("select count(*) from acc where id = 9000")["rows"] == [[1]]
        # rollback discards
        b.sql("begin")
        b.sql("insert into acc values (9001, 7)")
        b.sql("rollback")
        assert a.sql("select count(*) from acc where id = 9001")["rows"] == [[0]]
        # dropping a connection mid-transaction rolls it back
        c = SqlClient(sock)
        c.sql("begin")
        c.sql("insert into acc values (9002, 7)")
        c.close()
        deadline = time.time() + 5
        while time.time() < deadline:
            if db.sql("select count(*) from acc where id = 9002").rows() == [(0,)]:
                break
            time.sleep(0.05)
        assert db.sql("select count(*) from acc where id = 9002").rows()[0][0] == 0
        a.close()
        b.close()
    finally:
        srv.stop()
