"""s3:// external protocol — the gpcontrib/gpcloud analog (VERDICT r3
missing #7). A local mock S3 server (ListObjectsV2 XML + GET/PUT,
pagination, signature checks) stands in for the object store; the SigV4
implementation is pinned by AWS's published test vector."""

import datetime
import http.server
import socketserver
import threading
import urllib.parse

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime import s3


# ---------------------------------------------------------------------------
# SigV4: the published AWS example (GET iam ListUsers, 2015-08-30)
# ---------------------------------------------------------------------------

def test_sigv4_matches_aws_published_vector():
    now = datetime.datetime(2015, 8, 30, 12, 36, 0,
                            tzinfo=datetime.timezone.utc)
    hdrs = s3.sigv4_headers(
        "GET", "iam.amazonaws.com", "/",
        {"Action": "ListUsers", "Version": "2010-05-08"}, b"",
        "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "us-east-1", service="iam", now=now,
        extra_headers={"content-type":
                       "application/x-www-form-urlencoded; charset=utf-8"},
        sign_payload_header=False)   # the iam example has no S3 header
    # the EXACT signature from the AWS SigV4 documentation example
    assert hdrs["authorization"] == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
        "SignedHeaders=content-type;host;x-amz-date, "
        "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
    )
    assert hdrs["x-amz-date"] == "20150830T123600Z"


def test_sigv4_deterministic_and_secret_sensitive():
    now = datetime.datetime(2020, 1, 1, tzinfo=datetime.timezone.utc)
    a = s3.sigv4_headers("GET", "h", "/b/k", {}, b"", "A", "S1", "r", now=now)
    b = s3.sigv4_headers("GET", "h", "/b/k", {}, b"", "A", "S1", "r", now=now)
    c = s3.sigv4_headers("GET", "h", "/b/k", {}, b"", "A", "S2", "r", now=now)
    assert a["authorization"] == b["authorization"]
    assert a["authorization"] != c["authorization"]


def test_url_parsing():
    ep, bucket, prefix, opts = s3.parse_s3_url(
        "s3://127.0.0.1:9000/tb/pre/fix config=/tmp/x.conf region=eu-1")
    assert (ep, bucket, prefix) == ("127.0.0.1:9000", "tb", "pre/fix")
    assert opts == {"config": "/tmp/x.conf", "region": "eu-1"}
    with pytest.raises(s3.S3Error):
        s3.parse_s3_url("s3://hostonly")


# ---------------------------------------------------------------------------
# mock S3 server
# ---------------------------------------------------------------------------

class MockS3:
    """Path-style S3: ListObjectsV2 (with pagination), GET, PUT. Records
    whether requests carried a SigV4 Authorization header."""

    def __init__(self, require_auth=False):
        self.objects: dict = {}       # (bucket, key) -> bytes
        self.require_auth = require_auth
        self.saw_auth: list = []
        mock = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reject(self, code, msg):
                self.send_response(code)
                self.end_headers()
                self.wfile.write(msg.encode())

            def do_GET(self):
                auth = self.headers.get("Authorization", "")
                mock.saw_auth.append(bool(auth))
                if mock.require_auth and "AWS4-HMAC-SHA256" not in auth:
                    return self._reject(403, "AccessDenied")
                parsed = urllib.parse.urlparse(self.path)
                q = dict(urllib.parse.parse_qsl(parsed.query))
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = parts[0]
                if "list-type" in q:           # ListObjectsV2
                    prefix = q.get("prefix", "")
                    keys = sorted(k for (b, k) in mock.objects
                                  if b == bucket and k.startswith(prefix))
                    start = int(q.get("continuation-token", "0"))
                    page = keys[start:start + 2]          # tiny pages
                    more = start + 2 < len(keys)
                    xml = ["<ListBucketResult>"]
                    for k in page:
                        xml.append(f"<Contents><Key>{k}</Key></Contents>")
                    xml.append(f"<IsTruncated>{'true' if more else 'false'}"
                               "</IsTruncated>")
                    if more:
                        xml.append(f"<NextContinuationToken>{start + 2}"
                                   "</NextContinuationToken>")
                    xml.append("</ListBucketResult>")
                    body = "".join(xml).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                blob = mock.objects.get((bucket, key))
                if blob is None:
                    return self._reject(404, "NoSuchKey")
                self.send_response(200)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_PUT(self):
                auth = self.headers.get("Authorization", "")
                mock.saw_auth.append(bool(auth))
                if mock.require_auth and "AWS4-HMAC-SHA256" not in auth:
                    return self._reject(403, "AccessDenied")
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                n = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(n)
                mock.objects[(parts[0],
                              urllib.parse.unquote(parts[1]))] = body
                self.send_response(200)
                self.end_headers()

        class Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True

        self._srv = Srv(("127.0.0.1", 0), H)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    @property
    def endpoint(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self._srv.shutdown()


@pytest.fixture()
def mock_s3():
    m = MockS3()
    yield m
    m.stop()


def test_list_get_put_roundtrip(mock_s3):
    conf = {"https": False}
    for i in range(5):
        s3.put_object(mock_s3.endpoint, "b", f"data/part{i}.csv",
                      f"row{i}\n".encode(), conf)
    keys = s3.list_objects(mock_s3.endpoint, "b", "data/", conf)
    assert keys == [f"data/part{i}.csv" for i in range(5)]   # paginated (2/page)
    assert s3.get_object(mock_s3.endpoint, "b", "data/part3.csv",
                         conf) == b"row3\n"


def test_external_table_scan_from_s3(mock_s3, devices8):
    conf = {"https": False}
    s3.put_object(mock_s3.endpoint, "tpch", "li/a.csv",
                  b"1,alpha,10\n2,beta,20\n", conf)
    s3.put_object(mock_s3.endpoint, "tpch", "li/b.csv",
                  b"3,gamma,30\n", conf)
    s3.put_object(mock_s3.endpoint, "tpch", "other/x.csv",
                  b"9,zzz,99\n", conf)
    d = greengage_tpu.connect(numsegments=4)
    d.sql(f"""create external table ext (k int, name text, v int)
              location ('s3://{mock_s3.endpoint}/tpch/li/')
              format 'csv'""")
    r = d.sql("select k, name, v from ext order by k")
    assert r.rows() == [(1, "alpha", 10), (2, "beta", 20), (3, "gamma", 30)]
    # prefix scoping: other/ was not read
    assert d.sql("select count(*) from ext").rows()[0][0] == 3
    # INSERT SELECT materializes into a real table
    d.sql("create table t (k int, name text, v int) distributed by (k)")
    d.sql("insert into t select * from ext")
    assert d.sql("select sum(v) from t").rows()[0][0] == 60


def test_writable_external_to_s3(mock_s3, devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table src (a int, b int) distributed by (a)")
    d.load_table("src", {"a": np.arange(10), "b": np.arange(10) * 2})
    d.sql(f"""create writable external table wx (a int, b int)
              location ('s3://{mock_s3.endpoint}/out/exports')
              format 'csv'""")
    d.sql("insert into wx select * from src")
    written = [(b, k) for (b, k) in mock_s3.objects if b == "out"]
    assert len(written) == 1
    blob = mock_s3.objects[written[0]]
    rows = sorted(tuple(map(int, ln.split(",")))
                  for ln in blob.decode().strip().splitlines())
    assert rows == [(i, 2 * i) for i in range(10)]


def test_signed_requests_accepted(mock_s3, tmp_path):
    mock_s3.require_auth = True
    conf_file = tmp_path / "s3.conf"
    conf_file.write_text("[default]\naccessid = AKID\nsecret = sk\n"
                         "region = us-east-1\nhttps = false\n")
    url = f"s3://{mock_s3.endpoint}/sb/pre config={conf_file}"
    s3.store(url, "one.csv", b"1,2\n")
    assert s3.fetch(url) == [("pre/one.csv", b"1,2\n")]
    assert all(mock_s3.saw_auth)   # every request carried SigV4 auth


def test_unreachable_endpoint_is_clean_error():
    with pytest.raises(s3.S3Error, match="unreachable|failed"):
        s3.fetch("s3://127.0.0.1:1/none/x")
