"""Storage tier tests: block codec, dictionaries, manifest MVCC, placement.

Mirrors the reference's storage unit coverage (AO/AOCS format tests,
checksum verification, appendonlywriter concurrency via manifests).
"""

import numpy as np
import pytest

from greengage_tpu import types as T
from greengage_tpu.catalog import Catalog, Column, DistPolicy, PolicyKind, TableSchema
from greengage_tpu.storage import native
from greengage_tpu.storage.blockfile import read_column_file, write_column_file
from greengage_tpu.storage.dictionary import Dictionary
from greengage_tpu.storage.manifest import Manifest
from greengage_tpu.storage.table_store import TableStore


# ---------------------------------------------------------------------------
# block codec
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comp", [native.COMP_NONE, native.COMP_ZLIB, native.COMP_ZSTD])
def test_block_roundtrip(comp):
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 50, size=10000, dtype=np.int64).tobytes()
    frame = native.block_encode(raw, 10000, comp)
    out, nrows, consumed = native.block_decode(frame)
    assert out == raw and nrows == 10000 and consumed == len(frame)


def test_block_checksum_detects_corruption():
    frame = bytearray(native.block_encode(b"hello world " * 100, 100, native.COMP_ZLIB))
    frame[native.HDR_LEN + 3] ^= 0xFF
    with pytest.raises(IOError, match="checksum"):
        native.block_decode(bytes(frame))


def test_column_file_roundtrip(tmp_path):
    vals = np.random.default_rng(1).standard_normal(200_000)
    path = str(tmp_path / "c.ggb")
    write_column_file(path, vals, "zstd", block_rows=1 << 14)
    back = read_column_file(path)
    assert back.dtype == vals.dtype and np.array_equal(back, vals)


def test_column_file_block_projection(tmp_path):
    vals = np.arange(100_000, dtype=np.int64)
    path = str(tmp_path / "c.ggb")
    write_column_file(path, vals, "zlib", block_rows=10_000)
    back = read_column_file(path, block_indices=[2, 5])
    assert np.array_equal(back, np.concatenate([vals[20000:30000], vals[50000:60000]]))


# ---------------------------------------------------------------------------
# hashing spec: native vs numpy fallback must agree bit-for-bit
# ---------------------------------------------------------------------------

def test_hash_native_matches_fallback(monkeypatch):
    vals = np.array([0, 1, -1, 2**40, -(2**40), 123456789], dtype=np.int64)
    h_native = native.hash_i64(vals)
    monkeypatch.setattr(native, "_lib", False)
    h_py = native.hash_i64(vals)
    assert np.array_equal(h_native, h_py)
    c_native = native.hash_combine(h_native, h_native[::-1].copy())
    monkeypatch.setattr(native, "_lib", False)
    c_py = native.hash_combine(h_native, h_native[::-1].copy())
    assert np.array_equal(c_native, c_py)


def test_hash_bytes_native_matches_fallback(monkeypatch):
    for s in [b"", b"a", b"hello", b"0123456789abcdef", b"x" * 31]:
        hn = native.hash_bytes(s)
        monkeypatch.setattr(native, "_lib", False)
        hp = native.hash_bytes(s)
        monkeypatch.undo()
        assert hn == hp, s


# ---------------------------------------------------------------------------
# dictionary
# ---------------------------------------------------------------------------

def test_dictionary_stable_codes(tmp_path):
    d = Dictionary()
    c1 = d.encode(["a", "b", "a", "c"])
    assert list(c1) == [0, 1, 0, 2]
    p = str(tmp_path / "d.json")
    d.save(p)
    d2 = Dictionary.load(p)
    c2 = d2.encode(["c", "d"])
    assert list(c2) == [2, 3]
    assert d2.lookup("zzz") == -1


# ---------------------------------------------------------------------------
# manifest MVCC / 2PC-lite
# ---------------------------------------------------------------------------

def test_manifest_two_phase(tmp_path):
    m = Manifest(str(tmp_path))
    tx = m.begin()
    tx["tables"]["t"] = {"segfiles": {"0": ["f1"]}, "nrows": {"0": 10}}
    v = m.prepare(tx)
    # not yet visible
    assert m.snapshot()["version"] == 0
    m.commit(v)
    assert m.snapshot()["version"] == 1
    assert m.snapshot()["tables"]["t"]["nrows"]["0"] == 10


def test_manifest_corruption_is_fatal_and_named(tmp_path):
    """A corrupt manifest.json must surface as a clear fatal error naming
    the path (never a bare JSONDecodeError), from snapshot() AND from
    startup recovery."""
    from greengage_tpu.storage.manifest import ManifestError

    m = Manifest(str(tmp_path))
    tx = m.begin()
    tx["tables"]["t"] = {"segfiles": {}, "nrows": {"0": 1}}
    m.commit(m.prepare(tx))
    with open(m.path, "w") as f:
        f.write('{"version": 1, "tables": {TRUNCATED')
    with pytest.raises(ManifestError, match="manifest.json"):
        m.snapshot()
    with pytest.raises(ManifestError, match="manifest.json"):
        m.recover()


def test_manifest_conflict_and_recover(tmp_path):
    m = Manifest(str(tmp_path))
    tx1, tx2 = m.begin(), m.begin()
    tx1["tables"]["a"] = {"nrows": {"0": 1}, "segfiles": {}}
    m.commit(m.prepare(tx1))
    tx2["tables"]["b"] = {"nrows": {"0": 2}, "segfiles": {}}
    with pytest.raises(RuntimeError, match="conflict"):
        m.prepare(tx2)
    # crash with a prepared-but-uncommitted manifest -> recovery rolls back
    tx3 = m.begin()
    tx3["tables"]["c"] = {"nrows": {}, "segfiles": {}}
    m.prepare(tx3)
    assert m.recover() == [2]
    assert m.snapshot()["version"] == 1


# ---------------------------------------------------------------------------
# table store end-to-end
# ---------------------------------------------------------------------------

def _mk_store(tmp_path, nseg=4):
    cat = Catalog(nseg, path=str(tmp_path))
    return cat, TableStore(str(tmp_path), cat)


def test_insert_read_roundtrip_hash_distributed(tmp_path):
    cat, store = _mk_store(tmp_path)
    cat.create_table(TableSchema(
        "t",
        [Column("k", T.INT64), Column("v", T.decimal(2)), Column("s", T.TEXT),
         Column("d", T.DATE)],
        DistPolicy(PolicyKind.HASH, ("k",)),
    ))
    n = 1000
    rng = np.random.default_rng(2)
    k = rng.integers(0, 10**6, n).astype(np.int64)
    v = ["%d.%02d" % (i, i % 100) for i in range(n)]
    s = [f"str{i % 7}" for i in range(n)]
    d = ["2024-01-0%d" % (1 + i % 9) for i in range(n)]
    store.insert("t", {"k": k, "v": v, "s": s, "d": d})

    # all rows come back, each on the segment its key hashes to
    seen = 0
    for seg in range(4):
        cols, valids, nrows = store.read_segment("t", seg)
        seen += nrows
        if nrows:
            expect = native.hash_i64(cols["k"]) % np.uint32(4)
            assert np.all(expect == seg)
            assert valids["k"] is None
    assert seen == n
    assert sum(store.segment_rowcounts("t")) == n


def test_insert_nulls_and_replicated(tmp_path):
    cat, store = _mk_store(tmp_path, nseg=3)
    cat.create_table(TableSchema(
        "r", [Column("x", T.INT32)], DistPolicy(PolicyKind.REPLICATED)))
    x = np.arange(5, dtype=np.int32)
    valid = np.array([1, 1, 0, 1, 0], dtype=bool)
    store.insert("r", {"x": x}, valids={"x": valid})
    for seg in range(3):
        cols, valids, nrows = store.read_segment("r", seg)
        assert nrows == 5
        assert np.array_equal(cols["x"], x)
        assert np.array_equal(valids["x"], valid)


def test_snapshot_isolation(tmp_path):
    cat, store = _mk_store(tmp_path, nseg=2)
    cat.create_table(TableSchema(
        "t", [Column("k", T.INT64)], DistPolicy(PolicyKind.HASH, ("k",))))
    store.insert("t", {"k": np.arange(100, dtype=np.int64)})
    snap = store.manifest.snapshot()
    store.insert("t", {"k": np.arange(100, 200, dtype=np.int64)})
    # old snapshot still sees 100 rows, new sees 200
    assert sum(store.segment_rowcounts("t", snapshot=snap)) == 100
    assert sum(store.segment_rowcounts("t")) == 200
