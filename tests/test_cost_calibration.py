"""Self-calibrating cost model — VERDICT r3 #10 (gpcheckperf +
libgpdbcost calibration intent: gpMgmt/bin/gpcheckperf:1).

`gg checkperf --device --apply` measures the planner's primitive costs on
the live backend and persists <cluster>/calibration.json; connect() loads
it, so on any TPU generation the constants track the hardware instead of
round-2 folklore."""

import json
import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner import cost as C


@pytest.fixture(autouse=True)
def _reset_calibration():
    yield
    C.set_calibration(None)


def test_set_calibration_roundtrip():
    base = C.current_calibration()
    assert base["ns_sort_row"] == 40.0
    C.set_calibration({"ns_sort_row": 1.5, "ns_ici_byte": 0.5})
    assert C.NS_SORT_ROW == 1.5
    assert C.NS_ICI_BYTE == 0.5
    assert C.NS_GATHER_ROW == 10.7        # unmentioned keys keep defaults
    C.set_calibration({"ns_sort_row": -3, "ns_ici_byte": "junk"})
    assert C.NS_SORT_ROW == 40.0          # invalid values fall back
    C.set_calibration(None)
    assert C.current_calibration() == base


def test_connect_loads_cluster_calibration(devices8, tmp_path):
    path = str(tmp_path / "c")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "calibration.json"), "w") as f:
        json.dump({"ns_gather_row": 99.5}, f)
    greengage_tpu.connect(path=path, numsegments=2)
    assert C.NS_GATHER_ROW == 99.5


def test_calibration_flips_broadcast_choice(devices8):
    """The r2-measured asymmetry (replicated sort build ~250x its ICI
    bytes) is exactly what makes a 4000-row build REDISTRIBUTE
    (test_calibrated_costs golden). On hardware whose measured sort is
    100x cheaper, the same query must flip to BROADCAST — calibration
    changes plans, not just numbers."""
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(3)
    nf = 200_000
    d.sql("create table fact (k int, fk int, v int) distributed by (k)")
    d.load_table("fact", {"k": np.arange(nf),
                          "fk": rng.integers(0, 4000, nf),
                          "v": rng.integers(0, 1000, nf)})
    d.sql("create table dim (pk int, m int, w int) distributed by (m)")
    d.load_table("dim", {"pk": np.arange(4000), "m": np.arange(4000),
                         "w": np.arange(4000)})
    d.sql("analyze")
    q = "select sum(f.v) from fact f, dim d where f.fk = d.pk"

    def motion_above_dim(text):
        lines = text.splitlines()
        for i, ln in enumerate(lines):
            if "Scan dim" in ln:
                for j in range(i - 1, -1, -1):
                    if "Motion" in lines[j] or "Join" in lines[j]:
                        return lines[j]
        return ""

    planned, _, _ = d._plan(parse(q)[0])
    assert "Motion Redistribute" in motion_above_dim(describe(planned))
    C.set_calibration({"ns_sort_row": 0.4, "ns_scatter_row": 0.9})
    d._select_cache.clear()
    planned, _, _ = d._plan(parse(q)[0])
    assert "Motion Broadcast" in motion_above_dim(describe(planned))


def test_checkperf_device_writes_calibration(devices8, tmp_path):
    from greengage_tpu.mgmt import cli

    path = str(tmp_path / "c")
    greengage_tpu.connect(path=path, numsegments=2).close()
    rc = cli.main(["checkperf", "-d", path, "--size-mb", "8",
                   "--device", "--apply"])
    assert rc == 0
    with open(os.path.join(path, "calibration.json")) as f:
        cal = json.load(f)
    for k in ("ns_gather_row", "ns_scatter_row", "ns_sort_row",
              "ns_stream_byte", "ns_host_call", "ns_host_byte"):
        assert cal[k] > 0, (k, cal)
    # a fresh connect adopts the measured values
    greengage_tpu.connect(path=path, numsegments=2)
    assert C.NS_GATHER_ROW == pytest.approx(cal["ns_gather_row"])
