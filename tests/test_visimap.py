"""Deletion-bitmap DML (the appendonly visimap + SplitUpdate analog) —
VERDICT r3 #5.

Reference parity: src/backend/access/appendonly/appendonly_visimap.c (per-
segfile visibility bitmap consulted at scan time), nodeSplitUpdate.c
(UPDATE = delete old version + insert new, re-placed by distribution key),
and lazy VACUUM compaction. DELETE/UPDATE here publish an '@del' bitmap
sidecar per segment and (for UPDATE) append the new row versions — data
segfiles are never rewritten, so a 1-row UPDATE touches O(segfile), not
O(table).
"""

import threading

import numpy as np
import pytest

import greengage_tpu


def _segfiles(db, table):
    """(data rels, bitmap rels) currently referenced by the manifest."""
    snap = db.store.manifest.snapshot()
    tmeta = snap["tables"].get(table, {"segfiles": {}})
    data, masks = set(), set()
    for files in tmeta["segfiles"].values():
        for rel in files:
            (masks if "/@del." in rel or rel.startswith("@del.")
             else data).add(rel)
    return data, masks


@pytest.fixture
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    n = 20_000
    d.sql("create table t (k int, g int, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(n),
                       "g": (np.arange(n) % 97).astype(np.int64),
                       "v": np.arange(n, dtype=np.int64)})
    return d


# ---------------------------------------------------------------------------
# DELETE: bitmap only, no data rewrite
# ---------------------------------------------------------------------------

def test_delete_is_bitmap_only(db):
    before, _ = _segfiles(db, "t")
    out = db.sql("delete from t where k < 100")
    assert out == "DELETE 100"
    after, masks = _segfiles(db, "t")
    assert after == before          # NO data file rewritten
    assert masks                    # bitmap published
    assert db.sql("select count(*) from t").rows()[0][0] == 19_900
    assert db.sql("select count(*) from t where k < 100").rows()[0][0] == 0


def test_truncating_delete_counts_live_rows_only(db):
    db.sql("delete from t where k < 100")
    assert db.sql("delete from t") == "DELETE 19900"
    assert db.sql("select count(*) from t").rows()[0][0] == 0


def test_delete_accumulates_and_null_predicate_keeps_row(db):
    db.sql("insert into t values (100000, null, 5)")
    db.sql("delete from t where g = 0")       # NULL g rows survive
    n0 = 20_000 - int((np.arange(20_000) % 97 == 0).sum()) + 1
    assert db.sql("select count(*) from t").rows()[0][0] == n0
    db.sql("delete from t where v >= 10000")
    want = sum(1 for k in range(20_000)
               if k % 97 != 0 and k < 10000) + 1   # the null-g row (v=5)
    assert db.sql("select count(*) from t").rows()[0][0] == want


def test_aggregates_and_joins_skip_deleted(db):
    total = db.sql("select sum(v) from t").rows()[0][0]
    db.sql("delete from t where k % 2 = 0")
    odd_sum = int(np.arange(20_000, dtype=np.int64)[1::2].sum())
    assert db.sql("select sum(v) from t").rows()[0][0] == odd_sum != total
    db.sql("create table d (pk int, w int) distributed by (pk)")
    db.load_table("d", {"pk": np.arange(200), "w": np.arange(200)})
    got = db.sql("select count(*) from t, d where t.k = d.pk").rows()[0][0]
    assert got == 100   # only odd k < 200 survive


def test_insert_after_delete_rows_are_live(db):
    db.sql("delete from t where k < 19000")
    db.sql("insert into t values (1, 1, 777)")   # k=1 again, NEW row
    r = db.sql("select v from t where k = 1").rows()
    assert [x[0] for x in r] == [777]
    db.sql("delete from t where v = 777")        # bitmap shorter than nrows
    assert db.sql("select count(*) from t where k = 1").rows()[0][0] == 0


# ---------------------------------------------------------------------------
# UPDATE: bitmap + appended new versions
# ---------------------------------------------------------------------------

def test_update_one_row_touches_o_segfile(db):
    before, _ = _segfiles(db, "t")
    out = db.sql("update t set v = -5 where k = 123")
    assert out == "UPDATE 1"
    after, masks = _segfiles(db, "t")
    assert before <= after          # old data files all still referenced
    new = after - before
    assert masks
    # the append touched exactly ONE segment's worth of new files
    # (3 columns), not a table rewrite
    assert 0 < len(new) <= 3, new
    assert db.sql("select v from t where k = 123").rows() == [(-5,)]
    assert db.sql("select count(*) from t").rows()[0][0] == 20_000


def test_update_moves_row_across_segments(db):
    # k is the distribution key: the new version must land on k=777777's
    # owner segment and be found by a direct-dispatch equality probe
    db.sql("update t set k = 777777 where k = 42")
    assert db.sql("select count(*) from t where k = 42").rows()[0][0] == 0
    assert db.sql("select v from t where k = 777777").rows() == [(42,)]
    assert db.sql("select count(*) from t").rows()[0][0] == 20_000


def test_update_expression_and_where_null(db):
    db.sql("update t set v = v * 2 where g < 3")
    m = (np.arange(20_000) % 97) < 3
    v = np.arange(20_000, dtype=np.int64)
    want = int(np.where(m, v * 2, v).sum())
    assert db.sql("select sum(v) from t").rows()[0][0] == want


def test_whole_table_update_still_republishes(db):
    out = db.sql("update t set v = 1")
    assert out == "UPDATE 20000"
    assert db.sql("select sum(v) from t").rows()[0][0] == 20_000
    _, masks = _segfiles(db, "t")
    assert not masks    # republish path: no bitmap


# ---------------------------------------------------------------------------
# transactions
# ---------------------------------------------------------------------------

def test_delete_rollback_restores_rows(db):
    db.sql("begin")
    db.sql("delete from t where k < 500")
    assert db.sql("select count(*) from t where k < 500").rows()[0][0] == 500
    db.sql("rollback")
    assert db.sql("select count(*) from t where k < 500").rows()[0][0] == 500


def test_update_commit_is_atomic(db):
    db.sql("begin")
    db.sql("update t set v = -1 where k < 10")
    db.sql("commit")
    assert db.sql("select sum(v) from t where k < 10").rows()[0][0] == -10
    assert db.sql("select count(*) from t").rows()[0][0] == 20_000


def test_update_rollback_discards_both_halves(db):
    db.sql("begin")
    db.sql("update t set v = -1 where k < 10")
    db.sql("rollback")
    assert db.sql("select sum(v) from t where k < 10").rows()[0][0] == 45
    assert db.sql("select count(*) from t").rows()[0][0] == 20_000


# ---------------------------------------------------------------------------
# interactions: zone maps, raw TEXT, replicated, analyze, expand
# ---------------------------------------------------------------------------

def test_pruned_range_scan_exact_after_delete(db):
    db.sql("analyze t")
    db.sql("delete from t where k >= 100 and k < 200")
    got = db.sql("select count(*) from t where k < 1000").rows()[0][0]
    assert got == 900


def test_replicated_table_delete_update(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table r (a int, b int) distributed replicated")
    d.load_table("r", {"a": np.arange(100), "b": np.arange(100)})
    d.sql("delete from r where a < 10")
    assert d.sql("select count(*) from r").rows()[0][0] == 90
    d.sql("update r set b = -1 where a = 50")
    assert d.sql("select b from r where a = 50").rows() == [(-1,)]
    assert d.sql("select count(*) from r").rows()[0][0] == 90


def test_raw_text_delete_update(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table rt (k int, s text) distributed by (k)")
    strs = np.array([f"payload-{i:06d}-{'x' * (i % 13)}" for i in range(5000)],
                    dtype=object)
    d.load_table("rt", {"k": np.arange(5000), "s": strs})
    assert d.catalog.get("rt").column("s").encoding == "raw"
    d.sql("delete from rt where k % 5 = 0")
    assert d.sql("select count(*) from rt").rows()[0][0] == 4000
    r = d.sql("select s from rt where k = 7").rows()
    assert r == [(strs[7],)]
    d.sql("update rt set k = 999999 where k = 7")
    assert d.sql("select s from rt where k = 999999").rows() == [(strs[7],)]


def test_analyze_sees_live_rows_only(db):
    db.sql("delete from t where k >= 1000")
    db.sql("analyze t")
    assert db.catalog.get("t").stats.rows == 1000


def test_expand_drops_bitmap_and_keeps_live_rows(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(5000), "v": np.arange(5000)})
    d.sql("delete from t where k >= 1000")
    d.expand(8)
    assert d.sql("select count(*) from t").rows()[0][0] == 1000
    _, masks = _segfiles(d, "t")
    assert not masks


# ---------------------------------------------------------------------------
# VACUUM compaction
# ---------------------------------------------------------------------------

def test_vacuum_compacts_bitmap_away(db):
    db.sql("delete from t where k % 3 = 0")
    live = db.sql("select count(*) from t").rows()[0][0]
    before, masks0 = _segfiles(db, "t")
    assert masks0
    got = db.vacuum("t")
    assert got == {"t": live}
    after, masks1 = _segfiles(db, "t")
    assert not masks1               # bitmap gone
    assert after.isdisjoint(before)  # data rewritten live-only
    assert db.sql("select count(*) from t").rows()[0][0] == live
    # counts now exact in the manifest again
    assert sum(db.store.segment_rowcounts("t")) == live


# ---------------------------------------------------------------------------
# concurrency: snapshot readers vs a deleting writer
# ---------------------------------------------------------------------------

def test_concurrent_reads_during_delete(db):
    """Readers racing a DELETE must always see a consistent count: either
    the full table or the post-delete table, never a partial bitmap."""
    errs = []
    seen = set()
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                n = db.sql("select count(*) from t").rows()[0][0]
                seen.add(int(n))
                if n not in (20_000, 10_000):
                    errs.append(n)
                    return
        except Exception as e:   # pragma: no cover
            errs.append(repr(e))

    th = [threading.Thread(target=reader) for _ in range(2)]
    for x in th:
        x.start()
    db.sql("delete from t where k < 10000")
    stop.set()
    for x in th:
        x.join()
    assert not errs, errs
    assert db.sql("select count(*) from t").rows()[0][0] == 10_000
