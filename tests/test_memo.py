"""Cascades-lite memo optimizer (planner/memo.py) — the ORCA analog.

Unit tests drive the search directly with synthetic stats; integration
tests check planner selection (GUC 'optimizer'), plan equivalence of
results, and that the bushy search actually changes plans where the
left-deep fallback cannot express the winner.
"""

import re

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner.logical import describe
from greengage_tpu.planner.memo import EdgeInfo, RelInfo, optimize
from greengage_tpu.sql.parser import parse


def leaves(t):
    if isinstance(t, tuple):
        return leaves(t[0]) | leaves(t[1])
    return {t}


# ---------------------------------------------------------------------------
# unit: the search itself
# ---------------------------------------------------------------------------

def test_bushy_beats_left_deep():
    # A⋈B colocated, C⋈D colocated, one cross edge B-C needing motion:
    # the winner must join the two colocated pairs first — a bushy shape
    # no left-deep enumeration contains.
    rels = [RelInfo(1e6, 16, ("a1",)), RelInfo(1e6, 16, ("b1",)),
            RelInfo(1e6, 16, ("c1",)), RelInfo(1e6, 16, ("d1",))]
    edges = [EdgeInfo(0, 1, [("a1", "b1")], 1e-6),
             EdgeInfo(2, 3, [("c1", "d1")], 1e-6),
             EdgeInfo(1, 2, [("b2", "c2")], 1e-6)]
    t = optimize(rels, edges, 8)
    assert t is not None
    sides = {frozenset(leaves(t[0])), frozenset(leaves(t[1]))}
    assert sides == {frozenset({0, 1}), frozenset({2, 3})}


def test_all_relations_present():
    rels = [RelInfo(10 ** (6 - i), 8, (f"k{i}",)) for i in range(5)]
    edges = [EdgeInfo(i, i + 1, [(f"x{i}", f"k{i+1}")], 1e-3)
             for i in range(4)]
    t = optimize(rels, edges, 8)
    assert leaves(t) == {0, 1, 2, 3, 4}


def test_replicated_dimension_prefers_no_motion():
    # joining against a replicated dim must not force the big side to move:
    # with a replicated B the plan keeps A's distribution (join A first or
    # last, no redistribute of A) — assert the search completes and total
    # leaves survive; the cost ranking is covered by the integration plan
    rels = [RelInfo(1e7, 32, ("a1",)),
            RelInfo(1e3, 8, (), replicated=True),
            RelInfo(1e3, 8, ("c1",))]
    edges = [EdgeInfo(0, 1, [("ax", "b1")], 1e-3),
             EdgeInfo(0, 2, [("a1", "c1")], 1e-3)]
    t = optimize(rels, edges, 8)
    assert leaves(t) == {0, 1, 2}


def test_disconnected_graph_bails():
    rels = [RelInfo(100, 8, ("a",)), RelInfo(100, 8, ("b",)),
            RelInfo(100, 8, ("c",))]
    edges = [EdgeInfo(0, 1, [("a", "b")], 0.01)]   # 2 unreachable
    assert optimize(rels, edges, 8) is None


def test_too_many_relations_bails():
    rels = [RelInfo(100, 8, (f"k{i}",)) for i in range(11)]
    edges = [EdgeInfo(i, i + 1, [(f"k{i}", f"k{i+1}")], 0.1)
             for i in range(10)]
    assert optimize(rels, edges, 8) is None


# ---------------------------------------------------------------------------
# integration: planner selection + plan shape + result equivalence
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(7)
    n = 20000
    d.sql("create table fa (k1 int, x int, v double precision) "
          "distributed by (k1)")
    d.load_table("fa", {"k1": rng.integers(0, 500, n).astype(np.int32),
                        "x": rng.integers(0, 100, n).astype(np.int32),
                        "v": rng.random(n)})
    d.sql("create table da (k1 int, link int) distributed by (k1)")
    d.load_table("da", {"k1": np.arange(500, dtype=np.int32),
                        "link": (np.arange(500) % 40).astype(np.int32)})
    d.sql("create table fb (k2 int, link int) distributed by (k2)")
    d.load_table("fb", {"k2": rng.integers(0, 400, n).astype(np.int32),
                        "link": rng.integers(0, 40, n).astype(np.int32)})
    d.sql("create table dbb (k2 int, w double precision) "
          "distributed by (k2)")
    d.load_table("dbb", {"k2": np.arange(400, dtype=np.int32),
                         "w": rng.random(400)})
    d.sql("analyze")
    return d


BUSHY_Q = ("select count(*), sum(fa.v) from fa, da, fb, dbb "
           "where fa.k1 = da.k1 and fb.k2 = dbb.k2 and da.link = fb.link")


def _plan_text(db, q):
    planned, _, _ = db._plan(parse(q)[0])
    return re.sub(r"#\d+", "", describe(planned))


def test_memo_plan_is_bushy(db):
    txt = _plan_text(db, BUSHY_Q)
    # both colocated pairs join motion-free: the two local joins appear
    # with their scans directly under them (no Motion between)
    assert re.search(r"Join inner.*\n\s+Scan fa.*\n\s+Scan da", txt), txt
    assert re.search(r"Join inner.*\n\s+Scan fb.*\n\s+Scan dbb", txt) \
        or re.search(r"Join inner.*\n\s+Scan dbb.*\n\s+Scan fb", txt), txt


def test_results_match_fallback(db):
    on = db.sql(BUSHY_Q).rows()
    db.sql("set optimizer to off")
    try:
        off = db.sql(BUSHY_Q).rows()
    finally:
        db.sql("set optimizer to on")
    assert on[0][0] == off[0][0]
    # summation order differs between plan shapes
    assert abs(on[0][1] - off[0][1]) <= 1e-9 * abs(off[0][1])


def test_explain_reports_optimizer(db):
    r = db.sql("explain " + BUSHY_Q)
    assert "memo (Cascades-lite)" in r.plan_text
    db.sql("set optimizer to off")
    try:
        r = db.sql("explain " + BUSHY_Q)
        assert "fallback" in r.plan_text
    finally:
        db.sql("set optimizer to on")


def test_three_way_same_results_small(db):
    q = ("select da.link, count(*) from fa, da where fa.k1 = da.k1 "
         "group by da.link order by da.link limit 5")
    on = db.sql(q).rows()
    db.sql("set optimizer to off")
    try:
        off = db.sql(q).rows()
    finally:
        db.sql("set optimizer to on")
    assert on == off


def test_memo_failure_falls_back_to_greedy(db, monkeypatch):
    """ORCA fallback-on-failure semantics: a crashing memo search must
    degrade to the left-deep order, never fail the statement."""
    from greengage_tpu.planner import memo

    def boom(*a, **k):
        raise RuntimeError("injected memo crash")

    monkeypatch.setattr(memo, "optimize", boom)
    r = db.sql("select count(*) from fa join da on fa.k1 = da.k1 "
               "join fb on da.link = fb.link")
    assert len(r.rows()) == 1 and r.rows()[0][0] >= 0
