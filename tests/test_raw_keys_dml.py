"""Raw-encoded TEXT as first-class keys + raw DML.

Transient per-version dictionaries (TableStore.raw_dictionary) let raw
columns serve as GROUP BY / ORDER BY / DISTINCT / join / min-max keys;
DELETE/UPDATE/expand republish decoded strings. Also covers the TEXT
min/max rank fix (first-seen dictionary codes don't order; ranks do).
Reference: varlena grouping/sort paths the reference gets for free from
per-row datums (execGrouping.c, tuplesort), rebuilt here as host-coded
int32 columns."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(path=str(tmp_path / "c"), numsegments=4)
    d.sql("create table r (a int, v int, c text) distributed by (a)")
    object.__setattr__(d.catalog.get("r").column("c"), "encoding", "raw")
    d.load_table("r", {
        "a": np.arange(6, dtype=np.int32),
        "v": (np.arange(6, dtype=np.int32) + 1) * 10,
        "c": np.array(["pear", "apple", "pear", "kiwi", "apple", "plum"],
                      dtype=object)})
    return d


def test_raw_group_by(db):
    r = db.sql("select c, count(*), sum(v) from r group by c order by c")
    assert r.rows() == [("apple", 2, 70), ("kiwi", 1, 40),
                        ("pear", 2, 40), ("plum", 1, 60)]


def test_raw_group_by_function(db):
    r = db.sql("select length(c) as l, count(*) from r group by length(c) "
               "order by l")
    assert r.rows() == [(4, 4), (5, 2)]
    r = db.sql("select upper(c) as u, count(*) from r group by upper(c) "
               "order by u limit 2")
    assert r.rows() == [("APPLE", 2), ("KIWI", 1)]


def test_raw_order_by(db):
    r = db.sql("select a, c from r order by c desc, a limit 3")
    assert r.rows() == [(5, "plum"), (0, "pear"), (2, "pear")]


def test_raw_distinct(db):
    r = db.sql("select distinct c from r order by c")
    assert [x[0] for x in r.rows()] == ["apple", "kiwi", "pear", "plum"]


def test_raw_min_max(db):
    assert db.sql("select min(c), max(c) from r").rows() == \
        [("apple", "plum")]


def test_dict_text_min_max_is_lexicographic(db):
    # regression: first-seen codes used to be compared directly
    db.sql("create table w (k int, tag text) distributed by (k)")
    db.sql("insert into w values (1, 'banana'), (2, 'apple'), (3, 'cherry')")
    assert db.sql("select min(tag), max(tag) from w").rows() == \
        [("apple", "cherry")]
    r = db.sql("select k, min(tag) from w group by k order by k")
    assert [x[1] for x in r.rows()] == ["banana", "apple", "cherry"]


def test_raw_join(db):
    db.sql("create table s (b int, c text) distributed by (b)")
    object.__setattr__(db.catalog.get("s").column("c"), "encoding", "raw")
    db.load_table("s", {"b": np.arange(3, dtype=np.int32),
                        "c": np.array(["apple", "plum", "mango"],
                                      dtype=object)})
    r = db.sql("select r.a, s.b from r join s on r.c = s.c order by r.a")
    assert r.rows() == [(1, 0), (4, 0), (5, 1)]


def test_raw_join_against_dict(db):
    db.sql("create table d (b int, c text) distributed by (b)")
    db.sql("insert into d values (7, 'kiwi'), (8, 'nope')")
    r = db.sql("select r.a, d.b from r join d on r.c = d.c")
    assert r.rows() == [(3, 7)]


def test_raw_delete(db):
    assert db.sql("delete from r where c = 'pear'") == "DELETE 2"
    assert db.sql("select count(*) from r").rows() == [(4,)]
    assert db.sql("select a, c from r order by a").rows() == [
        (1, "apple"), (3, "kiwi"), (4, "apple"), (5, "plum")]


def test_raw_update_passthrough(db):
    assert db.sql("update r set v = v + 1 where length(c) = 4") == "UPDATE 4"
    r = db.sql("select a, v, c from r order by a")
    assert r.rows() == [(0, 11, "pear"), (1, 20, "apple"), (2, 31, "pear"),
                        (3, 41, "kiwi"), (4, 50, "apple"), (5, 61, "plum")]


def test_raw_set_rejected(db):
    with pytest.raises(SqlError, match="raw"):
        db.sql("update r set c = 'zzz'")


def test_raw_delete_all_and_reload(db):
    db.sql("delete from r")
    assert db.sql("select count(*) from r").rows() == [(0,)]
    db.load_table("r", {"a": np.array([9], np.int32),
                        "v": np.array([1], np.int32),
                        "c": np.array(["back"], dtype=object)})
    assert db.sql("select a, c from r").rows() == [(9, "back")]


def test_raw_dml_in_transaction(db):
    db.sql("begin")
    db.sql("delete from r where a < 3")
    db.sql("rollback")
    assert db.sql("select count(*) from r").rows() == [(6,)]
    db.sql("begin")
    db.sql("delete from r where a < 3")
    db.sql("commit")
    assert db.sql("select count(*) from r").rows() == [(3,)]


def test_raw_expand(db, tmp_path):
    db.expand(8)
    r = db.sql("select a, c from r order by a")
    assert [x[1] for x in r.rows()] == ["pear", "apple", "pear", "kiwi",
                                       "apple", "plum"]
    d2 = greengage_tpu.connect(path=str(tmp_path / "c"))
    assert len(d2.sql("select a from r").rows()) == 6


def test_raw_order_by_ordinal_and_alias(db):
    assert [x[0] for x in db.sql(
        "select c from r order by 1 limit 2").rows()] == ["apple", "apple"]
    assert [x[0] for x in db.sql(
        "select c as u from r order by u limit 2").rows()] == \
        ["apple", "apple"]


def test_rawdict_eviction_respects_table(db, tmp_path):
    # 17+ same-named raw columns across tables must not evict each
    # other's code arrays mid-query (cache purge used to ignore table)
    for i in range(18):
        db.sql(f"create table ev{i} (a int, c text) distributed by (a)")
        object.__setattr__(db.catalog.get(f"ev{i}").column("c"),
                           "encoding", "raw")
        db.load_table(f"ev{i}", {
            "a": np.arange(2, dtype=np.int32),
            "c": np.array([f"x{i}", f"y{i}"], dtype=object)})
    for i in range(18):
        r = db.sql(f"select c, count(*) from ev{i} group by c order by c")
        assert r.rows() == [(f"x{i}", 1), (f"y{i}", 1)]


def test_raw_dml_rollback_keeps_cursor(db):
    db.sql("declare keepcur parallel retrieve cursor for select a from r")
    db.sql("begin")
    db.sql("delete from r where a = 0")
    db.sql("rollback")
    # rollback never GC'd the old blobs: the cursor must still serve
    db.sql("retrieve all from endpoint 0 of keepcur")
    # ... but a COMMITTED in-transaction raw DML does tombstone it
    db.sql("begin")
    db.sql("delete from r where a = 0")
    db.sql("commit")
    with pytest.raises(ValueError, match="invalidated"):
        db.sql("retrieve all from endpoint 0 of keepcur")


def test_raw_dml_cursor_survives_bitmap_delete(db):
    # bitmap DELETE (visimap) never GCs the old blobs: an open cursor
    # keeps serving its snapshot — strictly better than the republish
    # behavior it replaced
    db.sql("declare cur parallel retrieve cursor for select a, c from r")
    db.sql("delete from r where c = 'pear'")
    db.sql("retrieve all from endpoint 0 of cur")
    # a truncating DELETE still republishes (and GCs blobs) -> tombstone
    db.sql("declare cur2 parallel retrieve cursor for select a, c from r")
    db.sql("delete from r")
    with pytest.raises(ValueError, match="invalidated"):
        db.sql("retrieve all from endpoint 0 of cur2")
