"""Motion-layer tests on the 8-device virtual mesh (interconnect test analog:
src/test/isolation2 ic schedules, but as collectives)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from greengage_tpu.exec.compile import _shard_map
from greengage_tpu.ops import hashing
from greengage_tpu.parallel import SEG_AXIS, make_mesh
from greengage_tpu.parallel import motion


def _run_sharded(mesh, fn, *arrs):
    f = _shard_map(fn, mesh=mesh, in_specs=P(SEG_AXIS),
                   out_specs=P(SEG_AXIS))
    return f(*arrs)


def test_redistribute_by_hash(devices8):
    nseg, per_seg = 8, 64
    mesh = make_mesh(nseg, devices8)
    keys = np.arange(nseg * per_seg, dtype=np.int64)
    np.random.default_rng(0).shuffle(keys)
    cap = per_seg * 2

    def body(k):
        h = hashing.hash_i64(k)
        dest = hashing.segment_of(h, nseg)
        present = jnp.ones(k.shape, dtype=bool)
        recv, precv, overflow = motion.redistribute({"k": k}, present, dest, nseg, cap)
        return recv["k"], precv, jnp.broadcast_to(overflow, (1,))

    rk, rp, ov = _run_sharded(mesh, body, jnp.asarray(keys))
    rk, rp = np.asarray(rk), np.asarray(rp)
    assert not np.asarray(ov).any()
    # every row arrived exactly once, on the segment its hash names
    got = rk[rp]
    assert len(got) == len(keys)
    assert set(got.tolist()) == set(keys.tolist())
    rk_per_seg = rk.reshape(nseg, nseg * cap)
    rp_per_seg = rp.reshape(nseg, nseg * cap)
    from greengage_tpu.storage import native as host_hash
    for s in range(nseg):
        rows = rk_per_seg[s][rp_per_seg[s]]
        assert np.all(host_hash.hash_i64(rows) % np.uint32(nseg) == s)


def test_redistribute_overflow_flag(devices8):
    nseg, per_seg = 8, 32
    mesh = make_mesh(nseg, devices8)
    # all rows target segment 0 with capacity 8 -> must flag overflow
    keys = np.zeros(nseg * per_seg, dtype=np.int64)

    def body(k):
        dest = jnp.zeros(k.shape, dtype=jnp.int32)
        present = jnp.ones(k.shape, dtype=bool)
        _, _, overflow = motion.redistribute({"k": k}, present, dest, nseg, 8)
        return jnp.broadcast_to(overflow, (1,))

    ov = _run_sharded(mesh, body, jnp.asarray(keys))
    assert np.asarray(ov).all()


def test_broadcast(devices8):
    nseg, per_seg = 8, 16
    mesh = make_mesh(nseg, devices8)
    vals = np.arange(nseg * per_seg, dtype=np.int64)

    def body(v):
        present = v % 2 == 0
        recv, precv = motion.broadcast({"v": v}, present)
        return recv["v"], precv

    rv, rp = _run_sharded(mesh, body, jnp.asarray(vals))
    rv = np.asarray(rv).reshape(nseg, nseg * per_seg)
    rp = np.asarray(rp).reshape(nseg, nseg * per_seg)
    for s in range(nseg):
        assert np.array_equal(rv[s], vals)
        assert np.array_equal(rv[s][rp[s]], vals[vals % 2 == 0])
