"""Scalar-function edge semantics + data-path-fusion contracts (ISSUE 13).

Four contract families over the device scalar library (ops/scalar.py):
NULL propagation (strict functions and the NULL-aware constructs),
DECIMAL-exact round/trunc/mod scale behavior (half-away-from-zero, not
the float path's half-to-even), dictionary-LUT vs raw byte-window vs
host-chain parity on identical strings, and the LUT cache-key contract —
a DML that grows a dictionary recompiles the LUT-bearing executable
(PR-5 dictionary-fingerprint keys) instead of serving stale tables.

The fusion acceptance (ISSUE 13): the corpus's scalar shapes plan with
ZERO host materialization between scan and agg — no @hp host-predicate
columns, no RawChain finalize decodes, scalar_host_fallback_total
untouched — while the scalar work runs inside the compiled program."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner.logical import Scan
from greengage_tpu.runtime.logger import counters
from greengage_tpu.sql.parser import parse

STRS = ["  Hello World  ", "promoXYZ", "abcdef", "MiXeD", "promo", ""]


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table s (k int, d date, v decimal(7,2), a int, "
          "cdict text, craw text) distributed by (k)")
    object.__setattr__(d.catalog.get("s").column("craw"), "encoding", "raw")
    n = len(STRS)
    d.load_table("s", {
        "k": np.arange(n, dtype=np.int32),
        # 2000-01-01, 2000-02-29, 2000-12-31, 2001-03-01, x, x
        "d": np.array([10957, 11016, 11322, 11382, 0, 1], dtype=np.int32),
        "v": np.array([12345, 5, 12500, -12345, 770, 0], dtype=np.int64),
        "a": np.array([1, 2, 3, 4, 5, 6], dtype=np.int32),
        "cdict": np.array(STRS, dtype=object),
        "craw": np.array(STRS, dtype=object),
    }, valids={
        "d": np.array([1, 1, 1, 1, 0, 0], dtype=bool),
        "v": np.array([1, 1, 1, 1, 1, 0], dtype=bool),
        "a": np.array([1, 1, 1, 0, 1, 1], dtype=bool),
        "cdict": None, "craw": None, "k": None,
    })
    return d


def _col(db, q):
    return [r[0] for r in db.sql(q).rows()]


# ----------------------------------------------------------------------
# NULL propagation
# ----------------------------------------------------------------------

def test_strict_null_propagation_dates(db):
    # rows 4 and 5 carry NULL d: every date function must yield NULL there
    for expr in ("extract(year from d)", "extract(quarter from d)",
                 "extract(dow from d)", "extract(doy from d)",
                 "extract(week from d)", "extract(epoch from d)",
                 "date_trunc('month', d)", "date_trunc('year', d)",
                 "d + interval '1' month", "d - interval '2' year",
                 "date_part('decade', d)"):
        vals = _col(db, f"select {expr} from s order by k")
        assert vals[4] is None and vals[5] is None, expr
        assert all(v is not None for v in vals[:4]), expr


def test_strict_null_propagation_numeric(db):
    for expr in ("round(v, 1)", "round(v)", "trunc(v, 1)", "mod(v, 1.5)",
                 "abs(v)"):
        vals = _col(db, f"select {expr} from s order by k")
        assert vals[5] is None, expr
        assert all(v is not None for v in vals[:5]), expr


def test_mod_by_zero_is_null(db):
    assert _col(db, "select mod(v, 0.0) from s where k = 0") == [None]
    assert _col(db, "select mod(a, 0) from s where k = 0") == [None]


def test_coalesce_semantics(db):
    # a is NULL at k=3: coalesce falls through; all-NULL stays NULL
    assert _col(db, "select coalesce(a, 0 - 1) from s order by k") == \
        [1, 2, 3, -1, 5, 6]
    assert _col(db, "select coalesce(a, a, a) from s where k = 3") == [None]
    # first non-null wins even when later args are NULL
    assert _col(db, "select coalesce(a, v) from s where k = 5") == [6.0]


def test_nullif_semantics(db):
    assert _col(db, "select nullif(a, 2) from s order by k") == \
        [1, None, 3, None, 5, 6]
    # NULL argument: comparison unknown -> first argument passes through
    assert _col(db, "select nullif(a, v) from s where k = 5") is not None


def test_greatest_least_ignore_nulls(db):
    # PG semantics: NULLs are ignored; NULL only when ALL arguments are
    assert _col(db, "select greatest(a, 3) from s order by k") == \
        [3, 3, 3, 3, 5, 6]
    assert _col(db, "select least(a, 3) from s order by k") == \
        [1, 2, 3, 3, 3, 3]
    # k=3: a NULL -> greatest(a, 4) = 4, not NULL
    assert _col(db, "select greatest(a, 4) from s where k = 3") == [4]
    assert _col(db, "select greatest(a, a) from s where k = 3") == [None]


# ----------------------------------------------------------------------
# DECIMAL scale semantics (round half AWAY from zero — numeric.c)
# ----------------------------------------------------------------------

def test_round_decimal_half_away(db):
    # 123.45 -> 123.5 / -123.45 -> -123.5; the float64 path's
    # half-to-even would give 123.4 / -123.4
    assert _col(db, "select round(v, 1) from s where k = 0") == [123.5]
    assert _col(db, "select round(v, 1) from s where k = 3") == [-123.5]
    # 0.05 -> 0.1 (float round(0.5) is 0.0)
    assert _col(db, "select round(v, 1) from s where k = 1") == [0.1]


def test_round_decimal_negative_digits(db):
    # 125.00 rounded to tens: half away -> 130 (float half-to-even: 120)
    assert _col(db, "select round(v, -1) from s where k = 2") == [130.0]


def test_trunc_decimal(db):
    assert _col(db, "select trunc(v, 1) from s where k = 0") == [123.4]
    assert _col(db, "select trunc(v, 1) from s where k = 3") == [-123.4]


def test_mod_decimal_exact(db):
    # 7.70 mod 1.5 = 0.2 EXACT (the float path leaves 0.20000000000000018)
    assert _col(db, "select mod(v, 1.5) from s where k = 4") == [0.2]
    # sign follows the dividend (numeric.c truncation semantics)
    assert _col(db, "select mod(v, 2.0) from s where k = 3") == [-1.45]


def test_round_over_aggregate(db):
    # scalar-over-aggregate path (_rewritten_expr): sum(v) is DECIMAL(2)
    got = _col(db, "select round(sum(v), 1) from s where k < 3")
    # 123.45 + 0.05 + 125.00 = 248.50 -> round(., 1) = 248.5 exactly
    assert got == [248.5]


# ----------------------------------------------------------------------
# dict-LUT vs raw byte-window vs host-chain parity
# ----------------------------------------------------------------------

_PARITY = [
    ("upper({c})", None),
    ("lower({c})", None),
    ("length({c})", None),
    ("length(trim({c}))", None),
    (None, "upper({c}) = 'PROMO'"),
    (None, "substr({c}, 1, 5) = 'promo'"),
    (None, "trim({c}) like 'Hello%'"),
    (None, "upper({c}) like '%PROMO%'"),
    (None, "length({c}) > 5"),
]


def test_dict_vs_raw_parity(db):
    for proj, pred in _PARITY:
        if proj is not None:
            qd = f"select {proj.format(c='cdict')} from s order by k"
            qr = f"select {proj.format(c='craw')} from s order by k"
        else:
            qd = f"select k from s where {pred.format(c='cdict')} order by k"
            qr = f"select k from s where {pred.format(c='craw')} order by k"
        assert _col(db, qd) == _col(db, qr), (proj, pred)


def test_device_off_guc_parity(db):
    """scalar_device_enabled=off falls back to the host chains — same
    answers, counted as host fallbacks (the microbench baseline path)."""
    q = "select k from s where upper(craw) = 'PROMO' order by k"
    on = _col(db, q)
    db.sql("set scalar_device_enabled = off")
    try:
        c0 = counters.snapshot()
        off = _col(db, "select k from s where upper(craw) = 'PROMO' "
                       "order by k  -- host")
        assert on == off == [4]
        assert counters.since(c0).get("scalar_host_fallback_total", 0) >= 1
    finally:
        db.sql("set scalar_device_enabled = on")


def test_nonascii_raw_falls_back_correctly(db):
    """Non-ASCII raw data fails the byte-window ascii gate: the chain runs
    on the host (counted) and still answers correctly."""
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table nr (k int, c text) distributed by (k)")
    object.__setattr__(d.catalog.get("nr").column("c"), "encoding", "raw")
    d.load_table("nr", {"k": np.arange(3, dtype=np.int32),
                        "c": np.array(["café", "cafe", "CAFÉ"],
                                      dtype=object)})
    c0 = counters.snapshot()
    got = [r[0] for r in d.sql(
        "select k from nr where upper(c) = 'CAFÉ' order by k").rows()]
    assert got == [0, 2]
    assert counters.since(c0).get("scalar_host_fallback_total", 0) >= 1


def test_coalesce_fallback_absent_from_dictionary(devices8):
    """Review finding: a coalesce fallback literal ABSENT from the
    column's dictionary must come back as the string, not decode to NULL
    through the -1 sentinel (the binder re-codes through a derived
    dictionary that contains it)."""
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table ct (k int, c text) distributed by (k)")
    d.load_table("ct", {"k": np.array([0, 1], np.int32),
                        "c": np.array(["alpha", "beta"], dtype=object)},
                 valids={"k": None,
                         "c": np.array([True, False])})
    got = [r[0] for r in d.sql(
        "select coalesce(c, 'zzz') from ct order by k").rows()]
    assert got == ["alpha", "zzz"]
    # present-in-dictionary fallback still works
    got = [r[0] for r in d.sql(
        "select coalesce(c, 'alpha') from ct order by k").rows()]
    assert got == ["alpha", "alpha"]


def test_nullif_text_literal_first(db):
    """Review finding: nullif('lit', col) must return STRINGS (codes in
    the column's dictionary space decode through it), and an absent
    literal folds to itself — never a bare int or a sentinel NULL."""
    got = [r[0] for r in db.sql(
        "select nullif('promo', cdict) from s order by k").rows()]
    assert got == ["promo", "promo", "promo", "promo", None, "promo"]
    got = [r[0] for r in db.sql(
        "select nullif('zzz', cdict) from s where k = 0").rows()]
    assert got == ["zzz"]
    assert db.sql("select nullif('a', 'a') from s where k = 0").rows() \
        == [(None,)]


def test_empty_like_pattern_on_chain(db):
    """Review finding: chain LIKE '' matches only empty strings (not
    every row); '%' matches everything."""
    assert [r[0] for r in db.sql(
        "select k from s where trim(craw) like '' order by k").rows()] \
        == [5]
    assert len(db.sql(
        "select k from s where trim(craw) like '%'").rows()) == len(STRS)


def test_trim_space_only_parity(devices8):
    """Review finding: trim() strips SPACES only (PG btrim) on every
    path — dict LUT, raw byte window, and the host chain agree on data
    containing tabs."""
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table tt (k int, cd text, cr text) distributed by (k)")
    object.__setattr__(d.catalog.get("tt").column("cr"), "encoding", "raw")
    vals = np.array(["\tx ", " y ", "z\t"], dtype=object)
    d.load_table("tt", {"k": np.arange(3, dtype=np.int32),
                        "cd": vals, "cr": vals.copy()})
    # \t survives trim on every path; the raw chain falls back to the
    # host (non-ascii gate is unrelated — tab IS ascii — but the dict
    # LUT and byte window must agree with it regardless)
    want = [(0, "\tx"), (1, "y"), (2, "z\t")]
    got_d = d.sql("select k, trim(cd) from tt order by k").rows()
    got_r = d.sql("select k, trim(cr) from tt order by k").rows()
    assert [tuple(x) for x in got_d] == want
    assert [tuple(x) for x in got_r] == want
    assert [r[0] for r in d.sql(
        "select k from tt where trim(cr) = 'y' order by k").rows()] == [1]


def test_extract_year_prune_fires_with_param_cache(devices8):
    """Review finding: the extract_year zone-map prune must fire in the
    DEFAULT configuration (plan_cache_params on) — the year literal is
    pinned by paramize, not hoisted into an inert Param."""
    from greengage_tpu.planner.logical import Scan
    from greengage_tpu.sql.paramize import paramize

    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table pt (k int, dt date, v int) distributed by (k)")
    d.load_table("pt", {"k": np.arange(100, dtype=np.int32),
                        "dt": (10000 + np.arange(100) * 40).astype(np.int32),
                        "v": np.arange(100, dtype=np.int32)})
    d.sql("analyze")
    stmt = parse("select sum(v) from pt "
                 "where extract(year from dt) = 2000 and v > 3")[0]
    norm, vec, _sig = paramize(stmt, d.catalog)
    assert vec is not None and 2000 not in vec.values, vec
    planned, _, _ = d._plan(norm)
    preds = []
    stack = [planned]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan):
            preds.extend(p.prune_preds or ())
        stack.extend(p.children)
    assert any(c == "dt" and op == ">=" for c, op, _ in preds), preds
    assert any(c == "dt" and op == "<=" for c, op, _ in preds), preds


# ----------------------------------------------------------------------
# LUT cache keys: DML growing the dictionary recomputes the LUT
# ----------------------------------------------------------------------

def test_lut_recomputed_after_dict_growth(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table lt (k int, c text) distributed by (k)")
    d.load_table("lt", {"k": np.array([0, 1], np.int32),
                        "c": np.array(["alpha", "beta"], dtype=object)})
    q = "select k from lt where upper(c) = 'GAMMA' order by k"
    assert d.sql(q).rows() == []
    c0 = counters.snapshot()
    assert d.sql(q).rows() == []        # warm: cached program serves it
    warm = counters.since(c0)
    assert warm.get("program_cache_miss", 0) == 0, warm
    # DML grows the dictionary: the upper() LUT must be recomputed and
    # the LUT-bearing executable recompiled (dictionary fingerprint +
    # consts digest are in the shape signature) — never a stale miss
    d.sql("insert into lt values (2, 'gamma')")
    c1 = counters.snapshot()
    assert d.sql(q).rows() == [(2,)]
    delta = counters.since(c1)
    assert delta.get("program_cache_miss", 0) >= 1, delta


# ----------------------------------------------------------------------
# fusion acceptance: zero host materialization between scan and agg
# ----------------------------------------------------------------------

def _scan_cols(db, sql):
    planned, _, _ = db._plan(parse(sql)[0])
    out = []
    stack = [planned]
    while stack:
        p = stack.pop()
        if isinstance(p, Scan):
            out.extend(c.name for c in p.cols)
        stack.extend(p.children)
    return out


def test_raw_strop_plan_is_gather_and_host_free(db):
    cols = _scan_cols(db, "select k from s where upper(craw) = 'PROMO'")
    assert any(c.startswith("@rw:") for c in cols), cols
    assert not any(c.startswith("@hp:") for c in cols), cols


def test_corpus_scalar_shapes_fully_fused(devices8):
    """ISSUE 13 acceptance: the plan-corpus scalar shapes (Q42-class date
    math over a dict-encoded dimension included) execute with the scalar
    work INSIDE the fused program — scalar_host_fallback_total untouched,
    no @hp host-predicate columns staged, correct answers."""
    from greengage_tpu.analysis.plancorpus import (TPCDS_QUERIES,
                                                   load_tpcds_mini)

    d = greengage_tpu.connect(numsegments=4)
    load_tpcds_mini(d, n_fact=5_000)
    shapes = {k: q for k, q in TPCDS_QUERIES.items()
              if k.startswith("ds_scalar_")}
    assert len(shapes) >= 3, sorted(shapes)
    c0 = counters.snapshot()
    for name, q in shapes.items():
        cols = _scan_cols(d, q)
        assert not any(c.startswith("@hp:") for c in cols), (name, cols)
        r = d.sql(q)
        assert r.rows() is not None, name
    delta = counters.since(c0)
    assert delta.get("scalar_host_fallback_total", 0) == 0, delta
    assert delta.get("scalar_device_total", 0) >= len(shapes), delta
    # and the Q42 date-math acceptance query itself, vs a direct oracle
    r = d.sql("""select extract(year from d_date) y, sum(ss_ext_sales_price)
                 from store_sales, date_dim
                 where ss_sold_date_sk = d_date_sk
                 group by extract(year from d_date) order by y""")
    rows = r.rows()
    assert len(rows) >= 1 and all(x[0] >= 1998 for x in rows)
