"""Secondary indexes (block-value sidecars) — the btree/bitmap AM analog.

CREATE INDEX registers in the catalog and builds per-segfile sorted
(value, block) sidecars; equality scans stage only the blocks containing
the probe key — block-selective scans on UNCLUSTERED data where zone
maps can't prune. Reference roles: src/backend/access/nbtree (equality/
range probes), src/backend/access/bitmap (low-NDV), the AO block
directory for block addressing."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError

N = 800_000   # several 64k blocks per segment


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(0)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.load_table("t", {"k": rng.permutation(N).astype(np.int32),
                       "v": np.arange(N, dtype=np.int32)})
    return d


def test_index_prunes_unclustered_equality(db):
    before = db.sql("select v from t where k = 12345")
    db.sql("create index t_k on t (k)")
    after = db.sql("select v from t where k = 12345")
    assert after.rows() == before.rows()
    bk, bt = before.stats["zone_prune"]["t"]
    ak, at = after.stats["zone_prune"]["t"]
    assert bk == bt            # zone maps keep everything (unclustered)
    assert ak < at             # the index actually prunes
    db.sql("drop index t_k")


def test_index_correct_across_many_probes(db):
    db.sql("create index t_k2 on t (k)")
    rng = np.random.default_rng(7)
    for k in rng.integers(0, N, 5):
        r = db.sql(f"select v from t where k = {int(k)}")
        assert len(r) == 1
    assert db.sql("select v from t where k = -5").rows() == []
    db.sql("drop index t_k2")


def test_bitmap_low_ndv(db):
    db.sql("create table ev (k int, code int) distributed by (k)")
    code = np.ones(400_000, np.int32)
    code[-1] = 7
    db.load_table("ev", {"k": np.arange(400_000, dtype=np.int32),
                         "code": code})
    db.sql("create index ev_code on ev using bitmap (code)")
    r = db.sql("select k from ev where code = 7")
    assert r.rows() == [(399_999,)]
    kept, total = r.stats["zone_prune"]["ev"]
    assert kept < total


def test_index_survives_reopen_and_new_inserts(db, tmp_path):
    p = str(tmp_path / "idx")
    d = greengage_tpu.connect(path=p, numsegments=4)
    d.sql("create table s (k int, v int) distributed by (k)")
    rng = np.random.default_rng(3)
    d.load_table("s", {"k": rng.permutation(300_000).astype(np.int32),
                       "v": np.zeros(300_000, np.int32)})
    d.sql("create index s_k on s (k)")
    d2 = greengage_tpu.connect(path=p)
    assert "s_k" in d2.catalog.get("s").indexes
    # new segfiles after the index: lazily indexed, still correct
    d2.sql("insert into s values (1000001, 42)")
    r = d2.sql("select v from s where k = 1000001")
    assert r.rows() == [(42,)]


def test_index_ddl_errors(db):
    db.sql("create index dup_i on t (k)")
    with pytest.raises(SqlError, match="already exists"):
        db.sql("create index dup_i on t (v)")
    db.sql("create index if not exists dup_i on t (v)")   # no-op
    with pytest.raises(SqlError, match="access method"):
        db.sql("create index h on t using hash (k)")
    with pytest.raises(SqlError, match="does not exist"):
        db.sql("drop index nope")
    db.sql("drop index if exists nope")
    db.sql("drop index dup_i")


def test_raw_column_not_indexable(db):
    db.sql("create table rr (a int, c text) distributed by (a)")
    object.__setattr__(db.catalog.get("rr").column("c"), "encoding", "raw")
    db.load_table("rr", {"a": np.array([1], np.int32),
                         "c": np.array(["x"], dtype=object)})
    with pytest.raises(SqlError, match="raw-encoded"):
        db.sql("create index rr_c on rr (c)")


def test_text_index_prunes(db):
    db.sql("create table tx (k int, tag text) distributed by (k)")
    tags = np.array(["common"] * 400_000, dtype=object)
    tags[123_456] = "needle"
    db.load_table("tx", {"k": np.arange(400_000, dtype=np.int32),
                         "tag": greengage_tpu.types.Coded(
                             ["common", "needle"],
                             (tags == "needle").astype(np.int32))})
    db.sql("create index tx_tag on tx (tag)")
    r = db.sql("select k from tx where tag = 'needle'")
    assert r.rows() == [(123_456,)]
    kept, total = r.stats["zone_prune"]["tx"]
    assert kept < total
    # absent literal: code -1 prunes everything
    r = db.sql("select k from tx where tag = 'ghost'")
    assert r.rows() == []


def test_index_with_dml(db, tmp_path):
    d = greengage_tpu.connect(path=str(tmp_path / "dml"), numsegments=4)
    d.sql("create table u (k int, v int) distributed by (k)")
    d.load_table("u", {"k": np.arange(200_000, dtype=np.int32),
                       "v": np.arange(200_000, dtype=np.int32)})
    d.sql("create index u_k on u (k)")
    d.sql("update u set v = 0 where k = 77")
    d.sql("delete from u where k = 99")
    assert d.sql("select v from u where k = 77").rows() == [(0,)]
    assert d.sql("select v from u where k = 99").rows() == []
    assert d.sql("select count(*) from u").rows() == [(199_999,)]


def test_index_range_probe_prunes(db):
    """Range ops probe the sorted (value, block) run — the btree range
    scan (_bt_first) analog; VERDICT r3: 'no range probes'."""
    db.sql("create index t_k3 on t (k)")
    try:
        r = db.sql("select count(*) from t where k < 40")
        assert r.rows()[0][0] == 40
        kept, total = r.stats["zone_prune"]["t"]
        assert kept < total, (kept, total)
        r = db.sql(f"select count(*) from t where k >= {N - 40}")
        assert r.rows()[0][0] == 40
        kept, total = r.stats["zone_prune"]["t"]
        assert kept < total, (kept, total)
        # a wide range honestly keeps everything on unclustered data
        r = db.sql("select count(*) from t where k >= 10")
        assert r.rows()[0][0] == N - 10
    finally:
        db.sql("drop index t_k3")


def test_explain_shows_index_access_path(db):
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    db.sql("create index t_k4 on t (k)")
    try:
        planned, _, _ = db._plan(parse("select v from t where k = 5")[0])
        assert "(index: t_k4)" in describe(planned)
        planned, _, _ = db._plan(parse("select v from t where k < 9")[0])
        assert "(index: t_k4)" in describe(planned)
    finally:
        db.sql("drop index t_k4")
    planned, _, _ = db._plan(parse("select v from t where k = 5")[0])
    assert "(index:" not in describe(planned)
