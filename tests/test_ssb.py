"""Star Schema Benchmark (SSB) query flights vs pandas oracles — the
BASELINE.json "SSB wide GROUP BY + ORDER BY" config at test scale:
lineorder fact + date/customer/supplier/part dimensions, one query per
flight (Q1.1 filtered scan-agg, Q2.1 two-dim star join group-by, Q3.1
three-dim group-by, Q4.1 profit roll-up)."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.types import Coded


N_LO = 120_000
N_CUST, N_SUPP, N_PART = 2000, 400, 1500
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
MFGRS = [f"MFGR#{i}" for i in range(1, 6)]


def _gen(rng):
    years = rng.integers(1992, 1999, N_LO)
    d = {
        "lineorder": {
            "lo_orderkey": np.arange(N_LO, dtype=np.int64),
            "lo_custkey": rng.integers(0, N_CUST, N_LO),
            "lo_suppkey": rng.integers(0, N_SUPP, N_LO),
            "lo_partkey": rng.integers(0, N_PART, N_LO),
            "lo_orderyear": years.astype(np.int32),
            "lo_quantity": rng.integers(1, 51, N_LO),
            "lo_extendedprice": rng.integers(100, 10_000, N_LO).astype(np.int64),
            "lo_discount": rng.integers(0, 11, N_LO),
            "lo_revenue": rng.integers(100, 10_000, N_LO).astype(np.int64),
            "lo_supplycost": rng.integers(50, 5000, N_LO).astype(np.int64),
        },
        "customer": {
            "c_custkey": np.arange(N_CUST, dtype=np.int64),
            "c_region": Coded(REGIONS,
                              rng.integers(0, 5, N_CUST).astype(np.int32)),
            "c_nation": Coded([f"NATION{i}" for i in range(25)],
                              rng.integers(0, 25, N_CUST).astype(np.int32)),
        },
        "supplier": {
            "s_suppkey": np.arange(N_SUPP, dtype=np.int64),
            "s_region": Coded(REGIONS,
                              rng.integers(0, 5, N_SUPP).astype(np.int32)),
            "s_nation": Coded([f"NATION{i}" for i in range(25)],
                              rng.integers(0, 25, N_SUPP).astype(np.int32)),
        },
        "part": {
            "p_partkey": np.arange(N_PART, dtype=np.int64),
            "p_mfgr": Coded(MFGRS,
                            rng.integers(0, 5, N_PART).astype(np.int32)),
            "p_category": Coded([f"MFGR#{i}{j}" for i in range(1, 6)
                                 for j in range(1, 6)],
                                rng.integers(0, 25, N_PART).astype(np.int32)),
            "p_brand": Coded([f"MFGR#{i}" for i in range(1000)],
                             rng.integers(0, 1000, N_PART).astype(np.int32)),
        },
    }
    return d


@pytest.fixture(scope="module")
def env(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(41)
    data = _gen(rng)
    d.sql("""create table lineorder (
        lo_orderkey bigint, lo_custkey bigint, lo_suppkey bigint,
        lo_partkey bigint, lo_orderyear int, lo_quantity int,
        lo_extendedprice bigint, lo_discount int, lo_revenue bigint,
        lo_supplycost bigint) distributed by (lo_orderkey)""")
    d.sql("create table customer (c_custkey bigint, c_region text, "
          "c_nation text) distributed by (c_custkey)")
    d.sql("create table supplier (s_suppkey bigint, s_region text, "
          "s_nation text) distributed by (s_suppkey)")
    d.sql("create table part (p_partkey bigint, p_mfgr text, "
          "p_category text, p_brand text) distributed by (p_partkey)")
    for t, cols in data.items():
        d.load_table(t, cols)
    d.sql("analyze")
    dfs = {}
    for t, cols in data.items():
        dfs[t] = pd.DataFrame({n: (v.decode() if isinstance(v, Coded) else v)
                               for n, v in cols.items()})
    return d, dfs


def test_ssb_q1_1(env):
    d, f = env
    r = d.sql("""select sum(lo_extendedprice * lo_discount) as revenue
      from lineorder
      where lo_orderyear = 1993 and lo_discount between 1 and 3
        and lo_quantity < 25""")
    lo = f["lineorder"]
    m = ((lo.lo_orderyear == 1993) & (lo.lo_discount >= 1)
         & (lo.lo_discount <= 3) & (lo.lo_quantity < 25))
    assert r.rows()[0][0] == (lo.lo_extendedprice[m] * lo.lo_discount[m]).sum()


def test_ssb_q2_1(env):
    d, f = env
    r = d.sql("""select sum(lo_revenue), lo_orderyear, p_category
      from lineorder, part, supplier
      where lo_partkey = p_partkey and lo_suppkey = s_suppkey
        and p_mfgr = 'MFGR#1' and s_region = 'AMERICA'
      group by lo_orderyear, p_category
      order by lo_orderyear, p_category""")
    j = (f["lineorder"]
         .merge(f["part"], left_on="lo_partkey", right_on="p_partkey")
         .merge(f["supplier"], left_on="lo_suppkey", right_on="s_suppkey"))
    j = j[(j.p_mfgr == "MFGR#1") & (j.s_region == "AMERICA")]
    want = (j.groupby(["lo_orderyear", "p_category"])["lo_revenue"].sum()
             .reset_index().sort_values(["lo_orderyear", "p_category"]))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[1], row[2], row[0]) == (w.lo_orderyear, w.p_category,
                                            w.lo_revenue)


def test_ssb_q3_1(env):
    d, f = env
    r = d.sql("""select c_nation, s_nation, lo_orderyear,
             sum(lo_revenue) as revenue
      from customer, lineorder, supplier
      where lo_custkey = c_custkey and lo_suppkey = s_suppkey
        and c_region = 'ASIA' and s_region = 'ASIA'
        and lo_orderyear >= 1992 and lo_orderyear <= 1997
      group by c_nation, s_nation, lo_orderyear
      order by lo_orderyear, revenue desc, c_nation, s_nation limit 20""")
    j = (f["lineorder"]
         .merge(f["customer"], left_on="lo_custkey", right_on="c_custkey")
         .merge(f["supplier"], left_on="lo_suppkey", right_on="s_suppkey"))
    j = j[(j.c_region == "ASIA") & (j.s_region == "ASIA")
          & (j.lo_orderyear >= 1992) & (j.lo_orderyear <= 1997)]
    want = (j.groupby(["c_nation", "s_nation", "lo_orderyear"])
             ["lo_revenue"].sum().reset_index(name="revenue")
             .sort_values(["lo_orderyear", "revenue", "c_nation", "s_nation"],
                          ascending=[True, False, True, True]).head(20))
    got = r.rows()
    assert len(got) == min(20, len(want))
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2], row[3]) == \
            (w.c_nation, w.s_nation, w.lo_orderyear, w.revenue)


def test_ssb_q4_1(env):
    d, f = env
    r = d.sql("""select lo_orderyear, c_nation,
             sum(lo_revenue - lo_supplycost) as profit
      from customer, supplier, part, lineorder
      where lo_custkey = c_custkey and lo_suppkey = s_suppkey
        and lo_partkey = p_partkey and c_region = 'AMERICA'
        and s_region = 'AMERICA'
        and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
      group by lo_orderyear, c_nation
      order by lo_orderyear, c_nation""")
    j = (f["lineorder"]
         .merge(f["customer"], left_on="lo_custkey", right_on="c_custkey")
         .merge(f["supplier"], left_on="lo_suppkey", right_on="s_suppkey")
         .merge(f["part"], left_on="lo_partkey", right_on="p_partkey"))
    j = j[(j.c_region == "AMERICA") & (j.s_region == "AMERICA")
          & j.p_mfgr.isin(["MFGR#1", "MFGR#2"])]
    j["profit"] = j.lo_revenue - j.lo_supplycost
    want = (j.groupby(["lo_orderyear", "c_nation"])["profit"].sum()
             .reset_index().sort_values(["lo_orderyear", "c_nation"]))
    got = r.rows()
    assert len(got) == len(want)
    for row, (_, w) in zip(got, want.iterrows()):
        assert (row[0], row[1], row[2]) == (w.lo_orderyear, w.c_nation,
                                            w.profit)
