"""Block zone maps + scan pruning — VERDICT r1 item #9 (the TPU-native
PartitionSelector / block-directory analog): per-block min/max in the .ggb
footer lets staging skip blocks a scan predicate rules out."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.storage.blockfile import read_footer, write_column_file


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=2)
    d.sql("create table events (id bigint, day date, amount int) "
          "distributed by (id)")
    n = 600_000   # ~5 blocks of 65536 rows per segment
    # loaded in day order: consecutive blocks hold tight day ranges (the
    # realistic time-series ingest pattern zone maps exist for)
    days = np.sort(np.random.default_rng(0).integers(8000, 9000, n)).astype(np.int32)
    d.load_table("events", {"id": np.arange(n), "day": days,
                            "amount": np.arange(n) % 1000})
    return d


def test_footer_carries_zone_maps(db, tmp_path):
    p = str(tmp_path / "z.ggb")
    write_column_file(p, np.arange(200_000, dtype=np.int64), "zlib", 1)
    f = read_footer(p)
    assert len(f["blocks"]) == 4
    assert f["blocks"][0]["zmin"] == 0 and f["blocks"][0]["zmax"] == 65535
    assert f["blocks"][3]["zmin"] == 196608


def test_range_scan_prunes_blocks(db):
    total = db.sql("select count(*) from events").rows()[0][0]
    assert total == 600_000
    r = db.sql("select count(*) from events where day >= date '1994-08-15' "
               "and day < date '1994-08-30'")
    # correctness first
    import greengage_tpu.types as T

    lo, hi = T.date_to_days("1994-08-15"), T.date_to_days("1994-08-30")
    # recompute oracle on host
    snap = db.store.manifest.snapshot()
    want = 0
    for seg in range(2):
        cols, _, _ = db.store.read_segment("events", seg, ["day"], snap)
        want += int(((cols["day"] >= lo) & (cols["day"] < hi)).sum())
    assert r.rows()[0][0] == want
    # and the scan staged a strict subset of blocks
    zp = r.stats["zone_prune"]
    assert "events" in zp, r.stats
    kept, tot = zp["events"]
    assert tot >= 8 and kept < tot, zp


def test_equality_prune_and_point_correctness(db):
    r = db.sql("select count(*) from events where amount = 7 and day = date '1994-01-20'")
    rows = r.rows()[0][0]
    zp = r.stats.get("zone_prune", {})
    assert "events" in zp
    # oracle
    import greengage_tpu.types as T

    d0 = T.date_to_days("1994-01-20")
    snap = db.store.manifest.snapshot()
    want = 0
    for seg in range(2):
        cols, _, _ = db.store.read_segment("events", seg, ["day", "amount"], snap)
        want += int(((cols["day"] == d0) & (cols["amount"] == 7)).sum())
    assert rows == want


def test_prune_never_loses_matches_random_data(db):
    """Unsorted column: zones span everything, nothing prunes, results
    stay exact."""
    db.sql("create table rnd (k int, v int) distributed by (k)")
    rng = np.random.default_rng(2)
    db.load_table("rnd", {"k": np.arange(200_000),
                          "v": rng.integers(0, 1_000_000, 200_000)})
    r = db.sql("select count(*) from rnd where v < 500000")
    snap = db.store.manifest.snapshot()
    want = 0
    for seg in range(2):
        cols, _, _ = db.store.read_segment("rnd", seg, ["v"], snap)
        want += int((cols["v"] < 500000).sum())
    assert r.rows()[0][0] == want
