"""Parallel retrieve cursors (reference parity: DECLARE PARALLEL RETRIEVE
CURSOR + endpoints, src/backend/cdb/endpoint/): results stay per-segment
and are drained one endpoint at a time without a cross-segment gather."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table f (k bigint, v bigint) distributed by (k)")
    d.sql("insert into f values " + ",".join(
        f"({i}, {i % 10})" for i in range(2000)))
    return d


def test_endpoints_union_equals_select(db):
    whole = db.sql("select k, v from f where v < 3")
    db.sql("declare c0 parallel retrieve cursor for select k, v from f where v < 3")
    eps = db.endpoints("c0")
    assert len(eps) == 8 and all(e["state"] == "READY" for e in eps)
    got = []
    for e in eps:
        r = db.sql(f"retrieve all from endpoint {e['endpoint']} of c0")
        got.extend(zip(r.to_pandas().k, r.to_pandas().v))
    assert sorted(got) == sorted(zip(whole.to_pandas().k, whole.to_pandas().v))
    db.sql("close c0")
    with pytest.raises(ValueError, match="does not exist"):
        db.sql("retrieve all from endpoint 0 of c0")


def test_endpoint_rows_follow_distribution(db):
    """Each endpoint must hold exactly its segment's hash share — the
    point of the feature is parallel drain without redistribution."""
    db.sql("declare c1 parallel retrieve cursor for select k from f")
    counts = [len(db.sql(f"retrieve all from endpoint {k} of c1").to_pandas())
              for k in range(8)]
    assert sum(counts) == 2000 and max(counts) > 0
    db.sql("close c1")


def test_aggregate_under_cursor(db):
    db.sql("declare c2 parallel retrieve cursor for "
           "select v, count(*) as n from f group by v")
    rows = []
    for k in range(8):
        r = db.sql(f"retrieve all from endpoint {k} of c2").to_pandas()
        rows.extend(zip(r.v, r.n))
    assert sorted(rows) == [(v, 200) for v in range(10)]
    db.sql("close c2")


def test_order_by_rejected(db):
    with pytest.raises(SqlError, match="ORDER BY"):
        db.sql("declare c3 parallel retrieve cursor for "
               "select k from f order by k")


def test_offset_rejected(db):
    with pytest.raises(SqlError, match="OFFSET"):
        db.sql("declare co parallel retrieve cursor for select k from f offset 5")


def test_retrieve_decodes_after_raw_mode_dml(devices8):
    """A DML between DECLARE and RETRIEVE flips the executor into raw mode
    internally; the cursor must keep decoding (decimals scaled, text
    looked up) with the mode captured at DECLARE time."""
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table m (k bigint, amt numeric(10,2), tag text) "
          "distributed by (k)")
    d.sql("insert into m values (1, 12.50, 'aa'), (2, 7.25, 'bb')")
    d.sql("declare cm parallel retrieve cursor for select k, amt, tag from m")
    d.sql("update m set amt = 0.0 where k = 2")   # raw-mode internal run
    rows = []
    for k in range(4):
        r = d.sql(f"retrieve all from endpoint {k} of cm").to_pandas()
        rows.extend(zip(r.k, r.amt, r.tag))
    assert sorted(rows) == [(1, 12.50, "aa"), (2, 7.25, "bb")]
    d.sql("close cm")


def test_declare_duplicate_build_key_replans(db):
    """A join whose build side has duplicate keys must work under DECLARE
    exactly as it does under plain SELECT (multi-match re-plan)."""
    db.sql("create table dim (k bigint, w bigint) distributed by (k)")
    db.sql("insert into dim values (1, 10), (1, 11), (2, 20)")
    whole = db.sql("select f.k, dim.w from f join dim on f.k = dim.k")
    db.sql("declare cj parallel retrieve cursor for "
           "select f.k, dim.w from f join dim on f.k = dim.k")
    got = []
    for e in db.endpoints("cj"):
        r = db.sql(f"retrieve all from endpoint {e['endpoint']} of cj")
        got.extend(map(tuple, r.rows()))
    assert sorted(got) == sorted(map(tuple, whole.rows()))
    db.sql("close cj")


def test_drop_table_invalidates_cursor(db):
    db.sql("declare cd parallel retrieve cursor for select k from f")
    db.sql("drop table f")
    with pytest.raises(ValueError, match="invalidated by DROP TABLE"):
        db.sql("retrieve all from endpoint 0 of cd")
    # the name is reusable (tombstone), and CLOSE clears it
    db.sql("close cd")
    with pytest.raises(ValueError, match="does not exist"):
        db.sql("retrieve all from endpoint 0 of cd")


def test_connection_drop_closes_cursors(db, tmp_path):
    """A server connection's cursors die with it (session-scoped)."""
    import time

    from greengage_tpu.runtime.server import SqlClient, SqlServer

    sock = str(tmp_path / "gg.sock")
    srv = SqlServer(db, sock)
    srv.start()
    try:
        c = SqlClient(sock)
        c.sql("declare conn_c parallel retrieve cursor for select k from f")
        assert c.sql("retrieve all from endpoint 0 of conn_c")["ok"]
        c.close()
        deadline = time.time() + 5
        while time.time() < deadline and "conn_c" in db._cursors:
            time.sleep(0.05)
        assert "conn_c" not in db._cursors   # freed, name reusable
        db.sql("declare conn_c parallel retrieve cursor for select k from f")
        db.sql("close conn_c")
    finally:
        srv.stop()


def test_retrieve_errors(db):
    db.sql("declare c4 parallel retrieve cursor for select k from f")
    with pytest.raises(ValueError, match="out of range"):
        db.sql("retrieve all from endpoint 8 of c4")
    with pytest.raises(ValueError, match="already exists"):
        db.sql("declare c4 parallel retrieve cursor for select k from f")
    db.sql("close c4")
