"""Device-side result compaction before the Gather Motion (VERDICT r2 #9):
a selective SELECT must ship ~actual rows through the device->host relay,
not the scan's padded capacity. Reference: Gather Motion semantics
(src/backend/executor/nodeMotion.c:171) — tuples stream, padding doesn't.
"""

import numpy as np
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=2)
    rng = np.random.default_rng(13)
    n = 100_000
    d.sql("create table big (k int, v int, w int) distributed by (k)")
    d.load_table("big", {
        "k": np.arange(n),
        "v": rng.integers(0, 100_000, n).astype(np.int64),
        "w": rng.integers(0, 50, n).astype(np.int64),
    }, valids={"w": np.arange(n) % 7 != 0})
    d.sql("analyze")
    return d


def test_selective_select_ships_compacted(db):
    # ~0.1% selectivity: the shipped capacity must be a small fraction of
    # the 50k-row per-segment scan capacity
    r = db.sql("select k, v, w from big where v < 100")
    actual = len(r)
    assert 20 <= actual <= 300
    shipped = r.stats["below_gather_capacity"]
    assert shipped < 5000, (shipped, actual)
    # and the rows themselves are right (spot-check against numpy)
    want = int((np.asarray(db.sql("select count(*) from big where v < 100")
                           .rows()[0][0])))
    assert actual == want


def test_compaction_preserves_nulls_and_values(db):
    rows = db.sql("select k, w from big where v < 60").rows()
    for k, w in rows:
        if k % 7 == 0:
            assert w is None
        else:
            assert w is not None


def test_underestimate_retries_to_exact(db):
    # force a bad estimate: a predicate the planner rates ~equality-selective
    # but which actually passes half the table; the compaction must overflow
    # and retry to the exact count, never drop rows
    r = db.sql("select k from big where v % 2 = 0")
    n = len(r)
    want = db.sql("select count(*) from big where v % 2 = 0").rows()[0][0]
    assert n == want
    assert n > 40_000


def test_full_table_select_not_compacted(db):
    r = db.sql("select k from big")
    assert len(r) == 100_000
