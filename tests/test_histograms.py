"""Equi-depth histograms (planner/stats.py) + bucket range selectivity
(planner/cost.py _hist_frac_below) — VERDICT r3 #4.

Reference parity: pg_statistic histogram_bounds consumed by
ineq_histogram_selectivity, and ORCA's bucket calculus
(libnaucrates/src/statistics/CHistogram.cpp). Linear [min, max]
interpolation is wrong on any skewed distribution; the golden here pins a
broadcast-vs-redistribute join flip that interpolation gets wrong and
buckets get right.
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu import types as T
from greengage_tpu.planner import cost as C
from greengage_tpu.planner import stats as S
from greengage_tpu.planner.logical import describe
from greengage_tpu.sql.parser import parse


def _skewed(n, rng):
    """99% of mass packed into [9000, 10000), 1% spread over [0, 9000)."""
    tail = rng.integers(0, 9000, n // 100)
    head = rng.integers(9000, 10000, n - len(tail))
    return rng.permutation(np.concatenate([head, tail])).astype(np.int64)


# ---------------------------------------------------------------------------
# estimator units
# ---------------------------------------------------------------------------

def test_histogram_collected_and_selectivity_tracks_skew():
    rng = np.random.default_rng(11)
    vals = _skewed(100_000, rng)
    cs = S.analyze_column(vals, None, len(vals), T.Kind.INT64, rng)
    assert len(cs.hist) == S.HIST_BUCKETS + 1
    truth = float((vals < 4500).mean())           # ~0.005
    est = C._range_sel(cs, 4500.0, "<")
    assert abs(est - truth) <= 0.02, (est, truth)
    # the interpolation fallback (no histogram) is off by an order of
    # magnitude on this distribution — the failure mode buckets fix
    flat = S.ColumnStats(ndv=cs.ndv, min=cs.min, max=cs.max)
    interp = C._range_sel(flat, 4500.0, "<")
    assert interp > 10 * max(truth, 1e-9), (interp, truth)


def test_histogram_endpoints_and_direction():
    cs = S.ColumnStats(hist=[0.0, 1.0, 2.0, 10.0, 100.0])
    assert C._range_sel(cs, -5.0, "<") == 0.0
    assert C._range_sel(cs, 500.0, "<") == 1.0
    assert C._range_sel(cs, 500.0, ">") == 0.0
    lo = C._range_sel(cs, 1.5, "<")      # 1.5 buckets of 4
    assert abs(lo - 1.5 / 4) < 1e-9
    assert abs(C._range_sel(cs, 1.5, ">") - (1 - 1.5 / 4)) < 1e-9


def test_stats_roundtrip_preserves_histogram():
    cs = S.ColumnStats(ndv=5, hist=[0.0, 1.0, 2.0])
    back = S.ColumnStats.from_dict(cs.to_dict())
    assert back.hist == cs.hist
    # pre-histogram persisted stats (round <=3 clusters) load cleanly
    legacy = S.ColumnStats.from_dict({"ndv": 3.0, "min": 0.0, "max": 9.0})
    assert legacy.hist == []
    assert C._range_sel(legacy, 4.5, "<") == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# the plan golden: skewed range predicate flips broadcast <-> redistribute
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(23)
    nf, nd = 200_000, 4000
    d.sql("create table fact (k int, fk int, v int) distributed by (k)")
    d.load_table("fact", {
        "k": np.arange(nf),
        "fk": rng.integers(0, nd, nf),
        "v": rng.integers(0, 1000, nf),
    })
    # dim distributed by a non-join column: the join always needs motion,
    # so the build side's ESTIMATED size decides broadcast vs redistribute
    d.sql("create table dim (pk int, m int, s int) distributed by (m)")
    d.load_table("dim", {
        "pk": np.arange(nd), "m": np.arange(nd), "s": _skewed(nd, rng)})
    d.sql("analyze")
    return d


def _plan(db, sql: str) -> str:
    planned, _, _ = db._plan(parse(sql)[0])
    return describe(planned)


def _motion_above(plan_text: str, scan_substr: str) -> str:
    lines = plan_text.splitlines()
    for i, ln in enumerate(lines):
        if scan_substr in ln:
            for j in range(i - 1, -1, -1):
                if "Motion" in lines[j] or "Join" in lines[j]:
                    return lines[j]
    return ""


def test_skewed_range_filter_flips_to_broadcast(db):
    # s < 4500 truly passes ~0.5% of dim (~20 rows): the histogram
    # estimates ~60 (half of one 1/32 bucket) -> broadcast the tiny
    # build. Linear interpolation says ~45% (~1800 rows) ->
    # redistribute-both, the wrong plan (test_calibrated_costs.py pins
    # that a 4000-row build at this fact size redistributes). The SAME
    # query with a predicate whose linear and bucket estimates agree
    # (s < 9750 ~ 76%) stays redistributed.
    selective = _plan(db, "select sum(f.v) from fact f, dim d "
                          "where f.fk = d.pk and d.s < 4500")
    wide = _plan(db, "select sum(f.v) from fact f, dim d "
                     "where f.fk = d.pk and d.s < 9750")
    assert "Motion Broadcast" in _motion_above(selective, "Scan dim"), selective
    assert "Motion Redistribute" in _motion_above(wide, "Scan dim"), wide


def test_skewed_filter_execution_exact(db):
    got = db.sql("select count(*) from fact f, dim d "
                 "where f.fk = d.pk and d.s < 4500").rows()[0][0]
    # host truth
    import numpy as np
    d = db.sql("select pk from dim where s < 4500").rows()
    keep = {r[0] for r in d}
    fk = db.sql("select fk from fact").rows()
    want = sum(1 for (x,) in fk if x in keep)
    assert got == want
