"""Equi-depth histograms (planner/stats.py) + bucket range selectivity
(planner/cost.py _hist_frac_below) — VERDICT r3 #4.

Reference parity: pg_statistic histogram_bounds consumed by
ineq_histogram_selectivity, and ORCA's bucket calculus
(libnaucrates/src/statistics/CHistogram.cpp). Linear [min, max]
interpolation is wrong on any skewed distribution; the golden here pins a
broadcast-vs-redistribute join flip that interpolation gets wrong and
buckets get right.
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu import types as T
from greengage_tpu.planner import cost as C
from greengage_tpu.planner import stats as S
from greengage_tpu.planner.logical import describe
from greengage_tpu.sql.parser import parse


def _skewed(n, rng):
    """99% of mass packed into [9000, 10000), 1% spread over [0, 9000)."""
    tail = rng.integers(0, 9000, n // 100)
    head = rng.integers(9000, 10000, n - len(tail))
    return rng.permutation(np.concatenate([head, tail])).astype(np.int64)


# ---------------------------------------------------------------------------
# estimator units
# ---------------------------------------------------------------------------

def test_histogram_collected_and_selectivity_tracks_skew():
    rng = np.random.default_rng(11)
    vals = _skewed(100_000, rng)
    cs = S.analyze_column(vals, None, len(vals), T.Kind.INT64, rng)
    assert len(cs.hist) == S.HIST_BUCKETS + 1
    truth = float((vals < 4500).mean())           # ~0.005
    est = C._range_sel(cs, 4500.0, "<")
    assert abs(est - truth) <= 0.02, (est, truth)
    # the interpolation fallback (no histogram) is off by an order of
    # magnitude on this distribution — the failure mode buckets fix
    flat = S.ColumnStats(ndv=cs.ndv, min=cs.min, max=cs.max)
    interp = C._range_sel(flat, 4500.0, "<")
    assert interp > 10 * max(truth, 1e-9), (interp, truth)


def test_histogram_endpoints_and_direction():
    cs = S.ColumnStats(hist=[0.0, 1.0, 2.0, 10.0, 100.0])
    assert C._range_sel(cs, -5.0, "<") == 0.0
    assert C._range_sel(cs, 500.0, "<") == 1.0
    assert C._range_sel(cs, 500.0, ">") == 0.0
    lo = C._range_sel(cs, 1.5, "<")      # 1.5 buckets of 4
    assert abs(lo - 1.5 / 4) < 1e-9
    assert abs(C._range_sel(cs, 1.5, ">") - (1 - 1.5 / 4)) < 1e-9


def test_stats_roundtrip_preserves_histogram():
    cs = S.ColumnStats(ndv=5, hist=[0.0, 1.0, 2.0])
    back = S.ColumnStats.from_dict(cs.to_dict())
    assert back.hist == cs.hist
    # pre-histogram persisted stats (round <=3 clusters) load cleanly
    legacy = S.ColumnStats.from_dict({"ndv": 3.0, "min": 0.0, "max": 9.0})
    assert legacy.hist == []
    assert C._range_sel(legacy, 4.5, "<") == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# the plan golden: skewed range predicate flips broadcast <-> redistribute
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(23)
    nf, nd = 200_000, 4000
    d.sql("create table fact (k int, fk int, v int) distributed by (k)")
    d.load_table("fact", {
        "k": np.arange(nf),
        "fk": rng.integers(0, nd, nf),
        "v": rng.integers(0, 1000, nf),
    })
    # dim distributed by a non-join column: the join always needs motion,
    # so the build side's ESTIMATED size decides broadcast vs redistribute
    d.sql("create table dim (pk int, m int, s int) distributed by (m)")
    d.load_table("dim", {
        "pk": np.arange(nd), "m": np.arange(nd), "s": _skewed(nd, rng)})
    d.sql("analyze")
    return d


def _plan(db, sql: str) -> str:
    planned, _, _ = db._plan(parse(sql)[0])
    return describe(planned)


def _motion_above(plan_text: str, scan_substr: str) -> str:
    lines = plan_text.splitlines()
    for i, ln in enumerate(lines):
        if scan_substr in ln:
            for j in range(i - 1, -1, -1):
                if "Motion" in lines[j] or "Join" in lines[j]:
                    return lines[j]
    return ""


def test_skewed_range_filter_flips_to_broadcast(db):
    # s < 4500 truly passes ~0.5% of dim (~20 rows): the histogram
    # estimates ~60 (half of one 1/32 bucket) -> broadcast the tiny
    # build. Linear interpolation says ~45% (~1800 rows) ->
    # redistribute-both, the wrong plan (test_calibrated_costs.py pins
    # that a 4000-row build at this fact size redistributes). The SAME
    # query with a predicate whose linear and bucket estimates agree
    # (s < 9750 ~ 76%) stays redistributed.
    selective = _plan(db, "select sum(f.v) from fact f, dim d "
                          "where f.fk = d.pk and d.s < 4500")
    wide = _plan(db, "select sum(f.v) from fact f, dim d "
                     "where f.fk = d.pk and d.s < 9750")
    assert "Motion Broadcast" in _motion_above(selective, "Scan dim"), selective
    assert "Motion Redistribute" in _motion_above(wide, "Scan dim"), wide


def test_skewed_filter_execution_exact(db):
    got = db.sql("select count(*) from fact f, dim d "
                 "where f.fk = d.pk and d.s < 4500").rows()[0][0]
    # host truth
    import numpy as np
    d = db.sql("select pk from dim where s < 4500").rows()
    keep = {r[0] for r in d}
    fk = db.sql("select fk from fact").rows()
    want = sum(1 for (x,) in fk if x in keep)
    assert got == want


# ---------------------------------------------------------------------------
# histogram JOIN calculus (CJoinStatsProcessor.cpp role, VERDICT r4 #6)
# ---------------------------------------------------------------------------

def test_join_selectivity_uniform_matches_ndv_division():
    from greengage_tpu.planner.stats import ColumnStats, join_selectivity

    hist = [float(x) for x in range(0, 1001, 125)]   # uniform 0..1000
    ls = ColumnStats(ndv=1000, hist=list(hist))
    rs = ColumnStats(ndv=500, hist=list(hist))
    sel = join_selectivity(ls, rs)
    assert abs(sel - 1.0 / 1000) / (1.0 / 1000) < 0.2


def test_join_selectivity_disjoint_ranges_near_zero():
    from greengage_tpu.planner.stats import ColumnStats, join_selectivity

    ls = ColumnStats(ndv=1000, hist=[0.0, 250.0, 500.0, 750.0, 1000.0])
    rs = ColumnStats(ndv=1000, hist=[5000.0, 5250.0, 5500.0, 5750.0, 6000.0])
    assert join_selectivity(ls, rs) < 1e-9


def test_join_selectivity_partial_overlap_scales_down():
    from greengage_tpu.planner.stats import ColumnStats, join_selectivity

    full = [float(x) for x in range(0, 1001, 250)]
    shifted = [float(x) for x in range(500, 1501, 250)]   # half overlap
    ls = ColumnStats(ndv=1000, hist=full)
    rs = ColumnStats(ndv=1000, hist=shifted)
    sel = join_selectivity(ls, rs)
    # ~half of each side participates: 500 shared values at 1e-3 each
    assert abs(sel - 0.5 / 1000) / (0.5 / 1000) < 0.2


def test_join_selectivity_point_mass_skew():
    from greengage_tpu.planner.stats import ColumnStats, join_selectivity

    # 70% of mass on value 1 shows as repeated boundaries (zero-width
    # buckets); both sides skewed -> sel ~= 0.49, where NDV division
    # says 1/199
    B = 32
    heavy = int(B * 0.7)
    hist = [1.0] * (heavy + 1) + [
        float(2 + i * (200 - 2) / (B - heavy - 1)) for i in range(B - heavy)]
    ls = ColumnStats(ndv=199, hist=list(hist))
    rs = ColumnStats(ndv=199, hist=list(hist))
    sel = join_selectivity(ls, rs)
    assert 0.3 < sel < 0.7


def test_skewed_fk_join_order_plan_golden(devices8):
    """The VERDICT criterion: a skew-skew join NDV division underestimates
    25x must be ordered LAST — the unique-key join runs first (deepest)."""
    import numpy as np

    import greengage_tpu
    from greengage_tpu.planner.logical import describe
    from greengage_tpu.sql.parser import parse

    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(11)
    nf, ns, nt = 40_000, 3_000, 5_000
    fa = np.where(rng.random(nf) < 0.7, 1,
                  rng.integers(2, 200, nf)).astype(np.int64)
    sa = np.where(rng.random(ns) < 0.7, 1,
                  rng.integers(2, 200, ns)).astype(np.int64)
    d.sql("create table f (a bigint, b bigint, v int) distributed by (b)")
    d.sql("create table s (a bigint, w int) distributed by (a)")
    d.sql("create table t (b bigint, u int) distributed by (b)")
    d.load_table("f", {"a": fa, "b": rng.integers(0, nt, nf),
                       "v": rng.integers(0, 9, nf).astype(np.int32)})
    d.load_table("s", {"a": sa, "w": rng.integers(0, 9, ns).astype(np.int32)})
    d.load_table("t", {"b": np.arange(nt, dtype=np.int64),
                       "u": rng.integers(0, 9, nt).astype(np.int32)})
    d.sql("analyze")
    planned, _, _ = d._plan(parse(
        "select count(*) from f, s, t where f.a = s.a and f.b = t.b")[0])
    txt = describe(planned)
    lines = txt.split("\n")
    depth = {}
    for ln in lines:
        for tbl in ("s", "t"):
            if f"Scan {tbl} " in ln:
                depth[tbl] = len(ln) - len(ln.lstrip())
    # t joins first (deeper in the left-deep tree); s joins last
    assert depth["t"] > depth["s"], txt
    # and the skew join estimate is within 3x of the true ~58.6M rows
    import re
    import collections
    ca = collections.Counter(fa)
    cs = collections.Counter(sa)
    true_fs = sum(ca[k] * cs.get(k, 0) for k in ca)
    ests = [int(m.group(1)) for m in re.finditer(r"Join inner.*rows=(\d+)",
                                                 txt)]
    top_join = max(ests)
    assert true_fs / 3 < top_join < true_fs * 3, (top_join, true_fs)
    d.close()
