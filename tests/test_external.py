"""External tables (reference parity: CREATE [WRITABLE] EXTERNAL TABLE,
src/backend/access/external/fileam.c + exttablecmds.c): catalog-only
relations whose rows come from files/gpfdist/commands at scan time, with
SREH reject limits; WRITABLE external tables receive INSERT ... SELECT."""

import os

import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    return greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)


def _write_csv(path, lines):
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def test_file_location_scan(db, tmp_path):
    _write_csv(tmp_path / "a.csv", [f"{i},n{i % 3},{i}.50" for i in range(30)])
    db.sql(f"""create external table ext (k int, tag text, amt decimal(8,2))
               location ('file://{tmp_path}/a.csv') format 'csv'""")
    r = db.sql("select count(*), sum(amt) from ext")
    assert r.rows() == [(30, sum(i + 0.5 for i in range(30)))]
    r = db.sql("select tag, count(*) from ext group by tag order by tag")
    assert r.rows() == [("n0", 10), ("n1", 10), ("n2", 10)]
    # re-reads the source every scan (fileam semantics)
    _write_csv(tmp_path / "a.csv", ["1,x,2.00"])
    assert db.sql("select count(*) from ext").rows() == [(1,)]


def test_glob_multiple_files_and_join(db, tmp_path):
    _write_csv(tmp_path / "p1.csv", ["1,10", "2,20"])
    _write_csv(tmp_path / "p2.csv", ["3,30"])
    db.sql(f"create external table pe (k int, v int) "
           f"location ('file://{tmp_path}/p*.csv') format 'csv'")
    db.sql("create table dim (k int, name text) distributed by (k)")
    db.sql("insert into dim values (1, 'one'), (3, 'three')")
    r = db.sql("select name, v from pe join dim on pe.k = dim.k "
               "order by v")
    assert r.rows() == [("one", 10), ("three", 30)]


def test_reject_limit_sreh(db, tmp_path):
    _write_csv(tmp_path / "bad.csv", ["1,a", "2", "3,c", "oops,x,y", "4,d"])
    db.sql(f"create external table se (k int, s text) "
           f"location ('file://{tmp_path}/bad.csv') format 'csv' "
           f"segment reject limit 3")
    assert db.sql("select count(*) from se").rows() == [(3,)]
    # rejects logged to the error table file (gp_read_error_log analog)
    err = os.path.join(db.path, "errlog", "se.jsonl")
    assert os.path.exists(err)
    # without a limit: first bad row aborts
    db.sql(f"create external table s2 (k int, s text) "
           f"location ('file://{tmp_path}/bad.csv') format 'csv'")
    with pytest.raises(SqlError, match="line"):
        db.sql("select count(*) from s2")


def test_execute_source(db):
    db.sql("""create external table gen (seg int, x int) execute
              'for i in 1 2 3; do echo "$GP_SEGMENT_ID,$i"; done' on all""")
    r = db.sql("select count(*) from gen")
    assert r.rows() == [(12,)]   # 3 rows x 4 segments
    r = db.sql("select seg, count(*) from gen group by seg order by seg")
    assert r.rows() == [(0, 3), (1, 3), (2, 3), (3, 3)]


def test_gpfdist_location(db, tmp_path):
    from greengage_tpu.runtime.ingest import FileDistServer

    _write_csv(tmp_path / "serve.csv",
               [f"{i},{i * 2}" for i in range(100)])
    srv = FileDistServer(str(tmp_path))
    srv.start()
    try:
        db.sql(f"create external table ge (k int, v int) "
               f"location ('{srv.url('serve.csv')}') format 'csv'")
        assert db.sql("select sum(v) from ge").rows() == [(9900,)]
    finally:
        srv.stop()


def test_writable_external_roundtrip(db, tmp_path):
    db.sql("create table src (k int, s text) distributed by (k)")
    db.sql("insert into src values (1, 'a'), (2, 'b'), (3, 'a')")
    out = tmp_path / "out" / "dump.csv"
    db.sql(f"create writable external table wet (k int, s text) "
           f"location ('file://{out}') format 'csv'")
    assert db.sql("insert into wet select k, s from src").startswith("INSERT 0 3")
    db.sql(f"create external table rd (k int, s text) "
           f"location ('file://{out}') format 'csv'")
    r = db.sql("select k, s from rd order by k")
    assert r.rows() == [(1, "a"), (2, "b"), (3, "a")]
    # writable tables cannot be scanned; readable cannot be written
    with pytest.raises(SqlError, match="WRITABLE"):
        db.sql("select * from wet")
    with pytest.raises(SqlError, match="READABLE"):
        db.sql("insert into rd select k, s from src")


def test_insert_select_regular_table(db):
    db.sql("create table a (k int, amt decimal(8,2), d date, s text) "
           "distributed by (k)")
    db.sql("insert into a values (1, 1.25, date '2024-05-01', 'x'), "
           "(2, 2.50, date '2024-06-01', null)")
    db.sql("create table b (k int, amt decimal(8,2), d date, s text) "
           "distributed by (k)")
    db.sql("insert into b select k, amt, d, s from a")
    assert db.sql("select * from b order by k").rows() == \
        db.sql("select * from a order by k").rows()
    # arity mismatch is a clean error
    with pytest.raises(SqlError, match="arity"):
        db.sql("insert into b select k from a")


def test_header_stripped_per_file(db, tmp_path):
    _write_csv(tmp_path / "h1.csv", ["k,v", "1,10"])
    _write_csv(tmp_path / "h2.csv", ["k,v", "2,20"])
    db.sql(f"create external table he (k int, v int) "
           f"location ('file://{tmp_path}/h*.csv') format 'csv' (header)")
    assert db.sql("select sum(v) from he").rows() == [(30,)]


def test_external_in_cursor_and_subquery(db, tmp_path):
    _write_csv(tmp_path / "c.csv", [f"{i},{i * 2}" for i in range(20)])
    db.sql(f"create external table ce (k int, v int) "
           f"location ('file://{tmp_path}/c.csv') format 'csv'")
    # scalar subquery over an external table
    db.sql("create table h (k int) distributed by (k)")
    db.sql("insert into h values (1), (2)")
    r = db.sql("select k from h where k < (select max(k) from ce) order by k")
    assert r.rows() == [(1,), (2,)]
    # parallel retrieve cursor over an external table
    db.sql("declare ce_cur parallel retrieve cursor for select k, v from ce")
    got = []
    for e in db.endpoints("ce_cur"):
        got += db.sql(
            f"retrieve all from endpoint {e['endpoint']} of ce_cur").rows()
    assert sorted(got) == [(i, i * 2) for i in range(20)]
    db.sql("close ce_cur")


def test_external_guards(db, tmp_path):
    _write_csv(tmp_path / "g.csv", ["1,2"])
    db.sql(f"create external table gt (k int, v int) "
           f"location ('file://{tmp_path}/g.csv') format 'csv'")
    with pytest.raises(SqlError, match="external"):
        db.sql("delete from gt where k = 1")
    with pytest.raises(SqlError, match="external"):
        db.sql("update gt set v = 2")
    with pytest.raises(SqlError, match="external"):
        db.sql("insert into gt values (1, 2)")
    with pytest.raises(SqlError, match="ANALYZE"):
        db.sql("analyze gt")
    db.sql("analyze")   # database-wide skips externals
    db.sql("drop table gt")
    with pytest.raises(ValueError, match="does not exist"):
        db.sql("select * from gt")
