"""Gather-free ordered-global windows (ISSUE 12): ntile/lag/lead/
first_value/last_value via the packed-key all-gather rank machinery,
raw-TEXT order keys via transient-dictionary rank space, sampled-splitter
range repartition for keys that cannot pack, and whole-frame
first_value/last_value without ORDER BY — all oracle-checked vs pandas
and plan-checked gather-free (`gg check` I3/I5)."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.analysis.plancheck import validate_plan
from greengage_tpu.planner.logical import Motion, MotionKind, Window, describe
from greengage_tpu.sql.parser import parse


def _planned(db, q):
    planned, _, _ = db._plan(parse(q)[0])
    return planned


def _assert_gather_free(db, q):
    """The root Gather is the ONLY Gather and nothing funnels to one
    chip; the plan also passes the machine checks (I1-I6)."""
    planned = _planned(db, q)
    txt = describe(planned)
    assert txt.count("Gather") == 1, txt
    assert "SingleQE" not in txt, txt
    validate_plan(planned, db.catalog)
    return planned


def _pg_ntile(pos, n, k):
    q, r = divmod(n, k)
    big = r * (q + 1)
    if q == 0:
        return min(pos, k - 1) + 1
    return (pos // (q + 1) if pos < big else r + (pos - big) // q) + 1


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    rng = np.random.default_rng(21)
    n = 400
    d.sql("create table s (k int, d int, v int, f double precision, "
          "nv int) distributed by (k)")
    nv = rng.integers(0, 90, n).astype(np.float64)
    nv[rng.random(n) < 0.15] = np.nan
    d.df = pd.DataFrame({
        "k": np.arange(n),
        "d": rng.integers(0, 40, n),         # ties
        "v": rng.integers(0, 1000, n),
        "f": np.round(rng.random(n), 6),
        "nv": nv,
    })
    d.load_table("s", {
        "k": d.df.k.values.astype(np.int32),
        "d": d.df.d.values.astype(np.int32),
        "v": d.df.v.values.astype(np.int32),
        "f": d.df.f.values,
        "nv": np.where(np.isnan(nv), 0, nv).astype(np.int32),
    }, valids={"nv": ~np.isnan(nv)})
    d.sql("analyze")
    yield d
    d.close()


# ---------------------------------------------------------------------
# ordered-global ntile / lag / lead (all-gather rank machinery)
# ---------------------------------------------------------------------

def test_ntile_global_unique_key(db):
    q = "select k, ntile(7) over (order by k) nt from s"
    _assert_gather_free(db, q)
    rows = dict(db.sql(q).rows())
    n = len(db.df)
    for k, nt in rows.items():
        assert nt == _pg_ntile(k, n, 7), (k, nt)


def test_ntile_global_desc_and_more_buckets_than_rows(db):
    q = "select k, ntile(1000) over (order by k desc) nt from s"
    _assert_gather_free(db, q)
    rows = dict(db.sql(q).rows())
    n = len(db.df)
    for k, nt in rows.items():
        assert nt == _pg_ntile(n - 1 - k, n, 1000), (k, nt)


def test_ntile_global_ties_bucket_sizes(db):
    """Tied keys may permute within adjacent buckets, but bucket SIZES
    and the key->bucket multiset are fixed by the global order."""
    q = "select d, ntile(6) over (order by d) nt from s"
    _assert_gather_free(db, q)
    rows = db.sql(q).rows()
    n = len(db.df)
    sizes = {}
    for _, nt in rows:
        sizes[nt] = sizes.get(nt, 0) + 1
    assert sizes == {b + 1: (n // 6) + (1 if b < n % 6 else 0)
                     for b in range(6)}
    # per-position key order must agree with a pandas stable sort
    want = sorted(db.df.d.values)
    got = sorted(rows, key=lambda x: (x[1],))
    # within equal nt the d values are a multiset of the oracle's slice
    pos = 0
    for b in range(1, 7):
        cnt = sizes[b]
        assert sorted(x[0] for x in got[pos:pos + cnt]) \
            == sorted(want[pos:pos + cnt])
        pos += cnt


def test_lag_lead_global_unique_key(db):
    q = ("select k, lag(v) over (order by k) lg, "
         "lead(v, 3) over (order by k) ld, "
         "lag(v, 2, -5) over (order by k) lgd from s")
    _assert_gather_free(db, q)
    vs = dict(zip(db.df.k, db.df.v))
    n = len(db.df)
    for k, lg, ld, lgd in db.sql(q).rows():
        assert lg == (vs[k - 1] if k >= 1 else None)
        assert ld == (vs[k + 3] if k + 3 < n else None)
        assert lgd == (vs[k - 2] if k >= 2 else -5)


def test_lag_global_ties_multiset(db):
    """With tied order keys the row->value mapping is tie-break
    dependent; the MULTISET of lag values per key group is not."""
    q = "select d, lag(d) over (order by d) lg from s"
    _assert_gather_free(db, q)
    got = {}
    for d, lg in db.sql(q).rows():
        got.setdefault(d, []).append(lg)
    ds = sorted(db.df.d.values)
    want = {}
    for i, d in enumerate(ds):
        want.setdefault(d, []).append(ds[i - 1] if i else None)
    assert {k: sorted(v, key=lambda x: (x is None, x))
            for k, v in got.items()} \
        == {k: sorted(v, key=lambda x: (x is None, x))
            for k, v in want.items()}


def test_lag_lead_global_nullable_keys(db):
    """NULL order keys form the runtime NULL class (full64): they rank
    after all values (ASC default) and lag/lead walk straight through
    the boundary in global position order."""
    q = ("select k, nv, row_number() over (order by nv) rn, "
         "lead(k) over (order by nv) ld from s")
    _assert_gather_free(db, q)
    rows = sorted(db.sql(q).rows(), key=lambda x: x[2])
    n = len(db.df)
    assert [r[2] for r in rows] == list(range(1, n + 1))
    # nulls last: every non-null nv before every null
    nulls = [r for r in rows if r[1] is None]
    assert nulls and all(r[1] is not None for r in rows[:n - len(nulls)])
    nvs = [r[1] for r in rows[:n - len(nulls)]]
    assert nvs == sorted(nvs)
    # lead(k) at global position i returns position i+1's k
    for i in range(n - 1):
        assert rows[i][3] == rows[i + 1][0]
    assert rows[-1][3] is None


def test_lag_global_nulls_first_desc(db):
    q = ("select k, nv, row_number() over (order by nv desc) rn, "
         "lag(k) over (order by nv desc) lg from s")
    _assert_gather_free(db, q)
    rows = sorted(db.sql(q).rows(), key=lambda x: x[2])
    nn = int(db.df.nv.isna().sum())
    assert all(r[1] is None for r in rows[:nn])       # nulls first (desc)
    vals = [r[1] for r in rows[nn:]]
    assert vals == sorted(vals, reverse=True)
    for i in range(1, len(rows)):
        assert rows[i][3] == rows[i - 1][0]
    assert rows[0][3] is None


def test_first_last_value_ordered_global(db):
    """Default frame: first_value = global partition start, last_value =
    the row's last PEER."""
    q = ("select k, first_value(v) over (order by k) f, "
         "last_value(v) over (order by k) l from s")
    _assert_gather_free(db, q)
    vs = dict(zip(db.df.k, db.df.v))
    for k, f, l in db.sql(q).rows():
        assert f == vs[0]
        assert l == vs[k]     # unique keys: each row is its own peer


def test_last_value_ordered_global_peers(db):
    q = ("select d, last_value(d) over (order by d) l, "
         "first_value(d) over (order by d) f from s")
    _assert_gather_free(db, q)
    dmin = int(db.df.d.min())
    for d, l, f in db.sql(q).rows():
        assert l == d and f == dmin


def test_multikey_packed_ntile_lag(db):
    q = ("select k, ntile(5) over (order by d, k) nt, "
         "lag(v) over (order by d, k) lg from s")
    _assert_gather_free(db, q)
    order = db.df.sort_values(["d", "k"]).reset_index(drop=True)
    pos_of = {int(k): i for i, k in enumerate(order.k)}
    vs = dict(zip(db.df.k, db.df.v))
    n = len(db.df)
    for k, nt, lg in db.sql(q).rows():
        pos = pos_of[k]
        assert nt == _pg_ntile(pos, n, 5)
        want = vs[int(order.k[pos - 1])] if pos else None
        assert lg == want


def test_decimal_order_key_gather_free(db):
    db.sql("create table dec (k int, p decimal(12,2)) distributed by (k)")
    db.sql("insert into dec values (0, 10.25), (1, 3.50), (2, 99.99), "
           "(3, 3.49), (4, 50.00)")
    db.sql("analyze")
    q = "select k, rank() over (order by p desc) rk, " \
        "ntile(2) over (order by p desc) nt from dec"
    _assert_gather_free(db, q)
    rows = dict((k, (rk, nt)) for k, rk, nt in db.sql(q).rows())
    assert rows[2][0] == 1 and rows[4][0] == 2 and rows[0][0] == 3
    assert rows[1][0] == 4 and rows[3][0] == 5
    assert rows[2][1] == 1 and rows[3][1] == 2


def test_float_order_key_full64(db):
    q = ("select k, row_number() over (order by f) rn, "
         "lag(k) over (order by f) lg from s")
    _assert_gather_free(db, q)
    order = db.df.sort_values("f").reset_index(drop=True)
    rows = sorted(db.sql(q).rows(), key=lambda x: x[1])
    assert [r[0] for r in rows] == [int(x) for x in order.k]
    for i in range(1, len(rows)):
        assert rows[i][2] == rows[i - 1][0]


# ---------------------------------------------------------------------
# raw-TEXT order keys (acceptance: zero Gather + oracle)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def rawdb(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table rt (k int, c text) distributed by (k)")
    col = d.catalog.get("rt").column("c")
    object.__setattr__(col, "encoding", "raw")
    rng = np.random.default_rng(7)
    strs = [f"w{i:04d}-{j}" for j, i in
            enumerate(rng.permutation(120))]
    d.load_table("rt", {"k": np.arange(len(strs), dtype=np.int32),
                        "c": np.array(strs, dtype=object)})
    d.sql("analyze")
    d.strs = strs
    yield d
    d.close()


def test_ntile_raw_text_plan_golden(rawdb):
    """THE acceptance shape: `ntile(4) over (order by raw_text_col)`
    plans with no Gather node but the root — pinned as a golden."""
    import re

    q = "select c, ntile(4) over (order by c) nt from rt"
    planned = _assert_gather_free(rawdb, q)
    txt = re.sub(r" rows=\d+", "", describe(planned))
    txt = re.sub(r"#\d+", "#N", txt)
    assert txt == """\
Motion Gather  [Entry]
  Project [c=c#N, nt=ntile#N]  [Strewn x4]
    Window global=ordered  [Strewn x4]
      Scan rt  [Strewn x4]"""
    w = planned
    while not isinstance(w, Window):
        w = w.children[0]
    assert w.global_mode == "ordered"
    assert w.gkey_spec["mode"] == "packed"


def test_ntile_lag_raw_text_oracle(rawdb):
    q = ("select k, ntile(4) over (order by c) nt, "
         "lag(c) over (order by c) lg from rt")
    _assert_gather_free(rawdb, q)
    strs = rawdb.strs
    order = sorted(range(len(strs)), key=lambda i: strs[i])
    pos_of = {i: p for p, i in enumerate(order)}
    n = len(strs)
    for k, nt, lg in rawdb.sql(q).rows():
        pos = pos_of[k]
        assert nt == _pg_ntile(pos, n, 4)
        assert lg == (strs[order[pos - 1]] if pos else None)


def test_raw_text_partition_key(rawdb):
    rawdb.sql("create table rp (k int, c text, v int) distributed by (k)")
    col = rawdb.catalog.get("rp").column("c")
    object.__setattr__(col, "encoding", "raw")
    strs = ["alpha", "beta", "alpha", "gamma", "beta", "alpha"]
    rawdb.load_table("rp", {
        "k": np.arange(6, dtype=np.int32),
        "c": np.array(strs, dtype=object),
        "v": np.array([1, 2, 4, 8, 16, 32], dtype=np.int32)})
    r = rawdb.sql("select c, sum(v) over (partition by c) s from rp")
    want = {"alpha": 37, "beta": 18, "gamma": 8}
    for c, s in r.rows():
        assert s == want[c], (c, s)


# ---------------------------------------------------------------------
# range repartition (keys that cannot pack)
# ---------------------------------------------------------------------

def _assert_range_mode(db, q):
    planned = _assert_gather_free(db, q)
    w = planned
    while not isinstance(w, Window):
        w = w.children[0]
    assert w.global_mode == "range", describe(planned)
    assert isinstance(w.child, Motion) \
        and w.child.kind is MotionKind.REDISTRIBUTE \
        and w.child.range_spec is not None
    return planned


def test_range_mode_running_sum_oracle(db):
    # (int, float) multi-key cannot pack -> range repartition
    q = ("select k, sum(v) over (order by d, f, k) rs, "
         "row_number() over (order by d, f, k) rn, "
         "rank() over (order by d, f, k) rk, "
         "dense_rank() over (order by d, f, k) dr from s")
    _assert_range_mode(db, q)
    order = db.df.sort_values(["d", "f", "k"]).reset_index(drop=True)
    want_rs = order.v.cumsum()
    pos_of = {int(k): i for i, k in enumerate(order.k)}
    for k, rs, rn, rk, dr in db.sql(q).rows():
        pos = pos_of[k]
        assert rn == pos + 1
        assert rk == pos + 1       # (d, f, k) unique
        assert dr == pos + 1
        assert rs == want_rs[pos]


def test_range_mode_ntile_lag_minmax(db):
    q = ("select k, ntile(9) over (order by d, f) nt, "
         "lag(v, 2) over (order by d, f) lg, "
         "min(v) over (order by d, f) mn, "
         "max(v) over (order by d, f) mx, "
         "count(*) over (order by d, f) c, "
         "avg(v) over (order by d, f) av from s")
    _assert_range_mode(db, q)
    order = db.df.sort_values(["d", "f"], kind="stable") \
        .reset_index(drop=True)
    pos_of = {int(k): i for i, k in enumerate(order.k)}
    n = len(order)
    vs = list(order.v)
    run_min = np.minimum.accumulate(vs)
    run_max = np.maximum.accumulate(vs)
    run_sum = np.cumsum(vs)
    for k, nt, lg, mn, mx, c, av in db.sql(q).rows():
        pos = pos_of[k]      # (d, f) unique with f ~ U(0,1)
        assert nt == _pg_ntile(pos, n, 9)
        assert lg == (vs[pos - 2] if pos >= 2 else None)
        assert mn == run_min[pos] and mx == run_max[pos]
        assert c == pos + 1
        assert av == pytest.approx(run_sum[pos] / (pos + 1))


def test_range_mode_first_last_value(db):
    q = ("select k, first_value(v) over (order by f, k) fv, "
         "last_value(v) over (order by f, k) lv from s")
    _assert_range_mode(db, q)
    order = db.df.sort_values(["f", "k"]).reset_index(drop=True)
    first = int(order.v[0])
    vs = dict(zip(db.df.k, db.df.v))
    for k, fv, lv in db.sql(q).rows():
        assert fv == first
        assert lv == vs[k]    # unique keys: own peer


def test_range_mode_desc_and_nulls(db):
    q = ("select k, nv, row_number() over (order by nv desc, f, k) rn "
         "from s")
    _assert_range_mode(db, q)
    rows = sorted(db.sql(q).rows(), key=lambda x: x[2])
    nn = int(db.df.nv.isna().sum())
    # nulls first under DESC (PG default)
    assert all(r[1] is None for r in rows[:nn])
    vals = [r[1] for r in rows[nn:]]
    assert vals == sorted(vals, reverse=True)


def test_range_vs_funnel_equivalence(db):
    """The range-mode result must equal the funnel path's. A constant
    BOOL leading key forces the funnel (unencodable for range routing)
    without changing the effective (d, f) order."""
    q1 = "select k, sum(v) over (order by d, f) rs from s"
    q2 = "select k, sum(v) over (order by (d < 10000), d, f) rs from s"
    _assert_range_mode(db, q1)
    txt2 = describe(_planned(db, q2))
    assert "SingleQE" in txt2    # still the funnel: control group
    assert sorted(db.sql(q1).rows()) == sorted(db.sql(q2).rows())


# ---------------------------------------------------------------------
# first_value / last_value without ORDER BY (binder satellite)
# ---------------------------------------------------------------------

def _storage_order(db, table, cols, nseg=4):
    snap = db.store.manifest.snapshot()
    out = []
    for seg in range(nseg):
        c, _, n = db.store.read_segment(table, seg, None, snap)
        for i in range(n):
            out.append(tuple(int(c[x][i]) for x in cols))
    return out


def test_first_last_value_no_order_global(db):
    """Legal without ORDER BY (whole-frame semantics, PG): pinned to the
    deterministic storage (segment, row) order, gather-free."""
    q = "select k, first_value(v) over () f, last_value(v) over () l from s"
    planned = _assert_gather_free(db, q)
    w = planned
    while not isinstance(w, Window):
        w = w.children[0]
    assert w.global_mode is True
    rows_st = _storage_order(db, "s", ("k", "v"))
    fv, lv = rows_st[0][1], rows_st[-1][1]
    for _, f, l in db.sql(q).rows():
        assert f == fv and l == lv


def test_first_last_value_no_order_partitioned(db):
    q = ("select d, first_value(v) over (partition by d) f, "
         "last_value(v) over (partition by d) l from s")
    rows_st = _storage_order(db, "s", ("d", "v"))
    first, last = {}, {}
    for d, v in rows_st:
        first.setdefault(d, v)
        last[d] = v
    for d, f, l in db.sql(q).rows():
        assert f == first[d] and l == last[d]


def test_first_value_still_needs_args(db):
    from greengage_tpu.sql.parser import SqlError

    with pytest.raises(SqlError, match="requires an argument"):
        db.sql("select first_value() over () from s")
    with pytest.raises(SqlError, match="ORDER BY"):
        db.sql("select ntile(4) over () from s")


# ---------------------------------------------------------------------
# EXPLAIN ANALYZE / instrument still works on the new shapes
# ---------------------------------------------------------------------

def test_explain_analyze_ordered_global(db):
    r = db.sql("explain analyze select k, ntile(4) over (order by k) "
               "from s")
    assert "Window global=ordered" in r.plan_text
    assert "actual rows=" in r.plan_text


def test_explain_analyze_range_mode(db):
    r = db.sql("explain analyze select k, sum(v) over (order by d, f) "
               "from s")
    assert "Window global=range" in r.plan_text
    assert "Redistribute range" in r.plan_text
