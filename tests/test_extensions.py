"""Scalar UDFs + CREATE EXTENSION (extensions.py; reference parity:
pg_proc lookup in parse_func.c and commands/extension.c)."""

import math

import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=2)
    d.sql("create table t (a bigint, b double precision, d numeric(10,2)) "
          "distributed by (a)")
    d.sql("insert into t values (4, 2.25, 12.50), (9, -3.0, 0.25), "
          "(null, null, null)")
    return d


def test_builtin_math(db):
    r = db.sql("select sqrt(a) as s, abs(b) as ab, mod(a, 5) as m from t "
               "where a is not null").to_pandas().sort_values("s")
    assert list(r["s"]) == [2.0, 3.0]
    assert list(r["ab"]) == [2.25, 3.0]
    assert list(r["m"]) == [4, 4]


def test_round_two_arg_and_power(db):
    r = db.sql("select round(b, 1) as r, power(abs(b), 2.0) as p from t "
               "where a = 4").to_pandas()
    assert list(r["r"]) == [2.2] or list(r["r"]) == [2.3]  # banker's vs half-up
    assert list(r["p"]) == [pytest.approx(5.0625)]


def test_decimal_coerced_to_float(db):
    r = db.sql("select sqrt(d) as s from t where a = 9").to_pandas()
    assert list(r["s"]) == [0.5]


def test_null_propagates(db):
    r = db.sql("select count(sqrt(b)) as c, count(*) as n from t").to_pandas()
    assert list(r["c"]) == [2]   # sqrt(-3.0) is NaN but not NULL; NULL row drops
    assert list(r["n"]) == [3]


def test_arity_and_unknown_errors(db):
    with pytest.raises(SqlError, match="argument"):
        db.sql("select sqrt(a, b) from t")
    with pytest.raises(SqlError, match="unknown function"):
        db.sql("select frobnicate(a) from t")


def test_udf_in_predicate_and_groupby(db):
    r = db.sql("select sign(b) as s, count(*) as c from t "
               "where b is not null group by sign(b)").to_pandas()
    assert sorted(zip(r["s"], r["c"])) == [(-1, 1), (1, 1)]


def test_create_extension_geo(db):
    db.sql("create extension geo")
    r = db.sql(
        "select round(haversine_km(48.8566, 2.3522, 51.5074, -0.1278), 0) "
        "as km from t where a = 4").to_pandas()
    assert abs(r["km"][0] - 343.5) < 2


def test_extension_persists_across_reopen(tmp_path):
    path = str(tmp_path / "c")
    d = greengage_tpu.connect(path, numsegments=2)
    d.sql("create table p (x double precision) distributed randomly")
    d.sql("insert into p values (1.0)")
    d.sql("create extension geo")
    d2 = greengage_tpu.connect(path)
    r = d2.sql("select haversine_km(x, x, x, x) as k from p").to_pandas()
    assert list(r["k"]) == [0.0]
    d2.sql("create extension if not exists geo")   # idempotent


def test_unknown_extension(db):
    with pytest.raises(Exception, match="not available"):
        db.sql("create extension no_such_ext")


def test_stdlib_module_is_not_an_extension(db):
    with pytest.raises(Exception, match="registered no functions"):
        db.sql("create extension json")


def test_duplicate_create_errors(db):
    db.sql("create extension geo")
    with pytest.raises(Exception, match="already exists"):
        db.sql("create extension geo")


def test_extension_visibility_is_per_database(db, tmp_path):
    db.sql("create extension geo")   # registers globally, records in catalog
    other = greengage_tpu.connect(str(tmp_path / "other"), numsegments=2)
    other.sql("create table o (x double precision) distributed randomly")
    other.sql("insert into o values (1.0)")
    with pytest.raises(SqlError, match="unknown function"):
        other.sql("select haversine_km(x, x, x, x) from o")


def test_mod_truncation_and_zero(db):
    r = db.sql("select mod(-7 + a - a, 5) as m, mod(a, a - a) as z from t "
               "where a = 4").to_pandas()
    assert list(r["m"]) == [-2]          # PG sign-of-dividend semantics
    assert r["z"].isna().all()           # mod(x, 0) -> NULL (PG raises)


def test_date_rejected_by_math_funcs(tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "dd"), numsegments=2)
    d.sql("create table ev (dt date) distributed randomly")
    d.sql("insert into ev values (date '2024-01-01')")
    with pytest.raises(SqlError, match="expects"):
        d.sql("select sqrt(dt) from ev")
