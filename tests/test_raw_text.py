"""Raw-encoded TEXT columns (byte blob + offsets, the varlena/datum-stream
analog — VERDICT r1 item #5): high-NDV strings without dictionaries.

The device carries row surrogates; string predicates evaluate on host into
staged boolean columns (version-cached), and projections decode at result
finalize."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table msgs (id int, body text, tag text) distributed by (id)")
    n = 10_000
    rng = np.random.default_rng(5)
    # high-NDV body -> auto-resolves to raw; low-NDV tag -> dict
    bodies = np.array([f"message body {i} with payload {rng.integers(1e9)}"
                       for i in range(n)], dtype=object)
    bodies[42] = "special requests go here"
    bodies[7777] = "nothing special requests"
    tags = greengage_tpu.types.Coded(
        ["news", "spam", "work"], rng.integers(0, 3, n).astype(np.int32))
    d.load_table("msgs", {"id": np.arange(n), "body": bodies, "tag": tags})
    return d


def test_encoding_auto_resolution(db):
    schema = db.catalog.get("msgs")
    assert schema.column("body").encoding == "raw"
    assert schema.column("tag").encoding == "dict"
    # and the dictionary did NOT absorb 10k distinct bodies
    assert len(db.store.dictionary("msgs", "body")) == 0


def test_projection_roundtrip(db):
    r = db.sql("select id, body from msgs where id = 42")
    assert r.rows() == [(42, "special requests go here")]
    r = db.sql("select count(*) from msgs")
    assert r.rows()[0][0] == 10_000


def test_like_on_raw(db):
    r = db.sql("select id from msgs where body like '%special requests%' "
               "order by id")
    assert [x[0] for x in r.rows()] == [42, 7777]
    r = db.sql("select count(*) from msgs where body not like '%special requests%'")
    assert r.rows()[0][0] == 9998


def test_eq_and_in_on_raw(db):
    r = db.sql("select id from msgs where body = 'special requests go here'")
    assert [x[0] for x in r.rows()] == [42]
    r = db.sql("select count(*) from msgs where body <> 'special requests go here'")
    assert r.rows()[0][0] == 9999
    r = db.sql("select id from msgs where body in "
               "('special requests go here', 'nothing special requests') order by id")
    assert [x[0] for x in r.rows()] == [42, 7777]


def test_raw_pred_combines_with_device_preds(db):
    r = db.sql("select count(*) from msgs "
               "where body like 'message body 1%' and id < 200 and tag = 'news'")
    # oracle: host-side count
    strs = db.store.fetch_raw("msgs", "body", np.array([], np.int64))
    # cross-check via two independent queries
    a = db.sql("select id from msgs where body like 'message body 1%' and id < 200").rows()
    want = 0
    for (i,) in a:
        t = db.sql(f"select tag from msgs where id = {i}").rows()[0][0]
        want += t == "news"
    assert r.rows()[0][0] == want


def test_raw_keys_now_supported(db):
    # round-2: these lower onto transient per-version dictionaries
    # (tests/test_raw_keys_dml.py covers semantics; here: they run at
    # 10k-row scale on the high-NDV column without error)
    r = db.sql("select body, count(*) from msgs group by body "
               "order by body limit 2")
    assert len(r) == 2 and r.rows()[0][1] == 1
    r = db.sql("select id from msgs order by body limit 1")
    assert len(r) == 1
    r = db.sql("select count(*) from msgs a join msgs b on a.body = b.body")
    assert r.rows() == [(10_000,)]
    r = db.sql("select count(*) from (select distinct body from msgs) q")
    assert r.rows() == [(10_000,)]


def test_raw_nullable(db):
    db.sql("create table rnul (id int, body text) distributed by (id)")
    n = 5000
    bodies = np.array([f"unique body {i} {i*i}" for i in range(n)], dtype=object)
    valid = np.ones(n, bool)
    valid[::7] = False
    db.load_table("rnul", {"id": np.arange(n), "body": bodies},
                  valids={"body": valid})
    assert db.catalog.get("rnul").column("body").encoding == "raw"
    r = db.sql("select count(*) from rnul where body is null")
    assert r.rows()[0][0] == int((~valid).sum())
    # NOT LIKE must not count NULL bodies (3VL)
    r = db.sql("select count(*) from rnul where body not like '%unique%'")
    assert r.rows()[0][0] == 0
    r = db.sql("select body from rnul where id = 7")
    assert r.rows()[0][0] is None


def test_raw_survives_restart(db):
    db.catalog._save()
    db2 = greengage_tpu.connect(db.path)
    r = db2.sql("select body from msgs where id = 42")
    assert r.rows()[0][0] == "special requests go here"
    assert db2.catalog.get("msgs").column("body").encoding == "raw"


def test_left_join_null_extended_raw_projection(db):
    """Unmatched probe rows project a raw column as NULL — their pad
    surrogates must never be dereferenced (r2 review finding)."""
    db.sql("create table probe9 (k int, tag int) distributed by (k)")
    db.sql("insert into probe9 values (42, 1), (999999, 2)")
    r = db.sql("select probe9.k, body from probe9 left join msgs "
               "on probe9.k = msgs.id order by probe9.k")
    rows = r.rows()
    assert rows[0][0] == 42 and rows[0][1] == "special requests go here"
    assert rows[1][0] == 999999 and rows[1][1] is None


def test_minmax_on_raw(db):
    r = db.sql("select min(body), max(body) from msgs")
    # lexicographic extremes of the generated corpus
    lo, hi = r.rows()[0]
    assert lo.startswith("message body 0 ")
    assert hi == "special requests go here"
    # count over raw is fine (counts validity, not values)
    r = db.sql("select count(body) from msgs")
    assert r.rows()[0][0] == 10_000
