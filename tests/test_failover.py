"""Automatic coordinator failover (docs/ROBUSTNESS.md "Coordinator
failover"): hot-standby raw-tail shipping, the promotion fence,
watcher auto-promotion, worker re-homing, and the kill -9 promotion
correctness matrix (mid-2PC, mid-intent-resolve, mid-stream) — the
promoted standby must show every committed row exactly once, roll
in-doubt work back, and resume ingest streams with zero loss and zero
duplicates."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import greengage_tpu
import test_crash_recovery as _tcr
from greengage_tpu.runtime import standby
from greengage_tpu.runtime.logger import counters
from greengage_tpu.storage.manifest import CoordinatorFenced, Manifest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def cluster(devices8, tmp_path):
    path = str(tmp_path / "primary")
    d = greengage_tpu.connect(path=path, numsegments=4)
    d.sql("create table t (k int, name text, v int) distributed by (k)")
    d.load_table("t", {"k": np.arange(100),
                       "name": greengage_tpu.types.Coded(
                           ["a", "b"], (np.arange(100) % 2).astype(np.int32)),
                       "v": np.arange(100)})
    return d, path, str(tmp_path / "standby")


# ---------------------------------------------------------------------------
# raw-tail shipping: the standby holds root + log + deltas that compose
# to exactly the primary's committed state (no composed-root shortcuts)
# ---------------------------------------------------------------------------

def test_raw_tail_ships_unfolded_commits(cluster):
    d, path, sb = cluster
    standby.init_standby(path, sb)
    d.sql("insert into t values (1000, 'a', 1)")
    d.sql("delete from t where k < 5")
    # composed standby state == composed primary state, commit for commit
    assert Manifest(sb).snapshot()["version"] == \
        d.store.manifest.snapshot()["version"]
    # byte-identical commit log: the tail shipped incrementally, and the
    # root went across RAW (its version is the fold watermark, BEHIND the
    # composed head while unfolded log lines exist — a composed root next
    # to this log would double-apply them)
    with open(os.path.join(path, "commits.log"), "rb") as f:
        plog = f.read()
    with open(os.path.join(sb, "commits.log"), "rb") as f:
        assert f.read() == plog
    with open(os.path.join(sb, "manifest.json")) as f:
        root = json.load(f)
    assert root.get("version", 0) <= Manifest(sb).snapshot()["version"]
    assert standby.lag(path) == 0


def test_failed_sync_counts_and_widens_lag(cluster):
    import shutil

    d, path, sb = cluster
    standby.init_standby(path, sb)
    shutil.rmtree(sb)                            # standby host dies
    base = counters.snapshot()
    d.sql("insert into t values (2000, 'b', 2)")   # write still succeeds
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    # the formerly-silent swallow is a first-class signal now
    assert counters.since(base).get("standby_sync_fail_total", 0) >= 1
    assert counters.get("standby_lag_commits") >= 1
    st = d.mh_state()
    assert st["standby"]["lag_commits"] >= 1
    assert st["standby"]["sync_fail_total"] >= 1


# ---------------------------------------------------------------------------
# the promotion fence: exclusive hard-link claim, re-verified inside
# every manifest commit point
# ---------------------------------------------------------------------------

def test_fence_blocks_live_primary_commits(cluster):
    d, path, sb = cluster
    standby.init_standby(path, sb)
    standby.write_fence(path, sb)
    with pytest.raises(RuntimeError, match="fenced"):
        d.sql("insert into t values (3000, 'a', 3)")
    # the hard-link CAS: a second standby cannot steal the claim...
    with pytest.raises(RuntimeError, match="raced"):
        standby.write_fence(path, sb + "_other")
    # ... while re-fencing by the owner is idempotent
    assert standby.write_fence(path, sb)["standby"] == os.path.abspath(sb)
    standby.clear_fence(path)
    d.sql("insert into t values (3000, 'a', 3)")
    assert d.sql("select count(*) from t where k = 3000"
                 ).rows()[0][0] == 1


def test_promote_fences_old_primary_and_serves(cluster):
    d, path, sb = cluster
    standby.init_standby(path, sb)
    d.sql("insert into t values (4000, 'b', 4)")
    base = counters.snapshot()
    st = standby.promote(sb, reason="operator")
    assert st["role"] == "activated"
    assert st["promoted"]["reason"] == "operator"
    assert counters.since(base).get("standby_promote_total", 0) == 1
    assert standby.fenced(path)["standby"] == os.path.abspath(sb)
    # a paused-not-dead primary wakes into the fence, not split-brain
    with pytest.raises(RuntimeError, match="fenced"):
        d.sql("insert into t values (4001, 'a', 5)")
    assert standby.promote(sb)["role"] == "activated"   # idempotent
    try:
        d.close()
    except RuntimeError:
        pass                                   # fenced close-time flush
    d2 = greengage_tpu.connect(path=sb, numsegments=4)
    assert d2.sql("select count(*) from t").rows()[0][0] == 101
    assert d2.sql("select v from t where k = 4000").rows() == [(4,)]
    d2.sql("insert into t values (4002, 'a', 6)")
    assert d2.sql("select count(*) from t").rows()[0][0] == 102


def test_watcher_auto_promotes_on_primary_silence(cluster):
    d, path, sb = cluster
    standby.init_standby(path, sb)
    d.sql("insert into t values (5000, 'a', 7)")
    d.close()                    # coordinator gone; the beat goes stale
    base = counters.snapshot()
    fired = []
    w = standby.StandbyWatcher(sb, interval_s=0.05, deadline_s=0.4,
                               on_promote=fired.append)
    end = time.monotonic() + 15.0
    promoted = False
    while not promoted and time.monotonic() < end:
        promoted = w.poll_once()
        time.sleep(0.02)
    assert promoted, "watcher never promoted a silent primary"
    assert fired and fired[0]["role"] == "activated"
    assert "silent" in fired[0]["promoted"]["reason"]
    assert counters.since(base).get("standby_promote_total", 0) == 1
    # the split-brain invariant: the old primary's dir is fenced, so its
    # next locked commit point refuses
    assert standby.fenced(path) is not None
    with pytest.raises(CoordinatorFenced):
        Manifest(path)._check_fence()
    d2 = greengage_tpu.connect(path=sb, numsegments=4)
    assert d2.sql("select count(*) from t").rows()[0][0] == 101


def test_cli_standby_status_and_unfence(cluster, capsys):
    from greengage_tpu.mgmt import cli

    d, path, sb = cluster
    assert cli.main(["initstandby", "-d", path, "-s", sb]) == 0
    assert cli.main(["standby", "-s", sb]) == 0
    out = capsys.readouterr().out
    assert "role: standby" in out and "lag" in out
    standby.write_fence(path, sb)
    assert cli.main(["standby", "--unfence", path]) == 0
    assert standby.fenced(path) is None
    d.sql("insert into t values (42, 'a', 42)")   # unfenced primary serves
    assert d.sql("select count(*) from t").rows()[0][0] == 101


# ---------------------------------------------------------------------------
# client/worker contract: typed-retryable failures and the redial walk
# ---------------------------------------------------------------------------

def test_failover_errors_classify_as_57p01():
    from greengage_tpu.parallel.multihost import CoordinatorLost
    from greengage_tpu.runtime.server import _is_failover_error

    assert _is_failover_error(CoordinatorFenced("fenced"))
    assert _is_failover_error(CoordinatorLost("gone"))
    wrapped = RuntimeError("statement failed")
    wrapped.__cause__ = CoordinatorFenced("fenced")
    assert _is_failover_error(wrapped)          # one causal hop
    assert not _is_failover_error(RuntimeError("boom"))
    assert not _is_failover_error(ValueError("nope"))


def test_parse_addrs_order_dedupe_malformed():
    from greengage_tpu.parallel.multihost import WorkerChannel

    assert WorkerChannel.parse_addrs(
        "127.0.0.1:7001, 127.0.0.1:7002,127.0.0.1:7001") == \
        [("127.0.0.1", 7001), ("127.0.0.1", 7002)]
    # empty host defaults to loopback; malformed entries are dropped,
    # never crash a worker on a broadcast GUC value
    assert WorkerChannel.parse_addrs(":7003,oops,host:bad,") == \
        [("127.0.0.1", 7003)]
    assert WorkerChannel.parse_addrs("") == []
    assert WorkerChannel.parse_addrs(None) == []


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _kill_coordinator(ch):
    """Abrupt coordinator death: tear the connections and listener down
    with NO stop frame (close() sends a clean stop)."""
    for p in ch._workers:
        p.close()
    ch._srv.close()


def test_worker_redial_rehomes_to_standby_address():
    from greengage_tpu.config import Settings
    from greengage_tpu.parallel.multihost import (CoordinatorChannel,
                                                  CoordinatorLost,
                                                  WorkerChannel)

    port_a, port_b = _free_port(), _free_port()
    s = Settings()
    s.mh_coordinator_addrs = f"127.0.0.1:{port_a},127.0.0.1:{port_b}"
    box = {}

    def serve_a():
        box["a"] = CoordinatorChannel(port_a, 1, connect_deadline=10.0)

    t = threading.Thread(target=serve_a, daemon=True)
    t.start()
    w = WorkerChannel("127.0.0.1", port_a, process_id=1, settings=s,
                      connect_deadline=6.0)
    t.join(10)
    assert "a" in box, "coordinator accept never completed"
    _kill_coordinator(box["a"])           # dies without a stop frame
    with pytest.raises(CoordinatorLost):
        w.recv()

    def serve_b():
        box["b"] = CoordinatorChannel(port_b, 1, connect_deadline=15.0)

    t2 = threading.Thread(target=serve_b, daemon=True)
    t2.start()
    base = counters.snapshot()
    # the walk visits the dead current address (refused-at-rejoin fails
    # fast), then lands on the promoted standby's listener; retried until
    # the listener thread has bound
    end = time.monotonic() + 10.0
    ok = False
    while not ok and time.monotonic() < end:
        ok = w.reconnect()
        if not ok:
            time.sleep(0.05)
    assert ok, "candidate walk never reached the standby address"
    t2.join(10)
    assert "b" in box, "promoted listener never adopted the worker"
    assert (w.host, w.port) == ("127.0.0.1", port_b)
    assert counters.since(base).get("mh_rehome_total", 0) == 1
    box["b"].close()
    w.close()


def test_worker_redial_all_addresses_dead_is_bounded():
    from greengage_tpu.config import Settings
    from greengage_tpu.parallel.multihost import (CoordinatorChannel,
                                                  WorkerChannel)

    port_a, port_b = _free_port(), _free_port()
    box = {}

    def serve_a():
        box["a"] = CoordinatorChannel(port_a, 1, connect_deadline=10.0)

    t = threading.Thread(target=serve_a, daemon=True)
    t.start()
    s = Settings()
    s.mh_coordinator_addrs = f"127.0.0.1:{port_a},127.0.0.1:{port_b}"
    w = WorkerChannel("127.0.0.1", port_a, process_id=1, settings=s,
                      connect_deadline=4.0)
    t.join(10)
    _kill_coordinator(box["a"])
    t0 = time.monotonic()
    assert w.reconnect() is False        # every candidate is dead
    assert time.monotonic() - t0 < 10.0  # bounded: no deadline burn-out
    w.close()


# ---------------------------------------------------------------------------
# kill -9 promotion correctness: the crash matrix from
# test_crash_recovery, re-run with a registered standby and the promoted
# standby (not a restarted primary) doing the recovery
# ---------------------------------------------------------------------------

def test_kill9_mid_2pc_promoted_standby_rolls_back(tmp_path):
    path = str(tmp_path / "c")
    _tcr._setup(path)
    sb = str(tmp_path / "sb")
    standby.init_standby(path, sb)
    _tcr._run_child_until(
        path, "dtx_after_prepare",
        lambda: {fn.split(".")[0]
                 for fn in _tcr._staged_uncommitted_deltas(path)}
        >= {"t", "u"})
    # the promotion's final tail pull ships the in-doubt claims; the
    # promoted standby's recover() resolves them exactly as a restarted
    # primary would: ABORT, neither half applied
    st = standby.promote(sb)
    assert st["role"] == "activated"
    d = greengage_tpu.connect(path=sb, numsegments=4)
    assert not _tcr._staged_uncommitted_deltas(sb)
    assert d.sql("select count(*) from t").rows()[0][0] == 100
    assert d.sql("select count(*) from u").rows()[0][0] == 50
    d.sql("insert into t values (555, 555)")     # released claims admit
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert standby.fenced(path) is not None      # zombie revival fenced


@pytest.mark.parametrize("window", [0, 1])
def test_kill9_mid_intent_promoted_standby_exactly_once(tmp_path, window):
    path = str(tmp_path / f"c{window}")
    _tcr._setup(path)
    sb = str(tmp_path / "sb")
    standby.init_standby(path, sb)

    if window == 0:
        def parked():
            return bool(_tcr._intent_files(path))
    else:
        def parked():
            return _tcr._merged_rows_for(path, "t") >= 1

    _tcr._run_child_until(path, "intent_resolve", parked,
                          child=_tcr.INTENT_CHILD,
                          extra_env={"GGTPU_INTENT_WINDOW": str(window)})
    standby.promote(sb)
    d = greengage_tpu.connect(path=sb, numsegments=4)
    # window 0: in-doubt intent rolled back; window 1: the durable merge
    # line survived promotion — either way EXACTLY one outcome
    assert not _tcr._intent_files(sb)
    expect = 100 if window == 0 else 101
    assert d.sql("select count(*) from t").rows()[0][0] == expect
    if window == 1:
        assert d.sql("select v from t where k = 100000").rows() == [(7,)]
    d.sql("insert into t values (100001, 8)")
    assert d.sql("select count(*) from t").rows()[0][0] == expect + 1
    assert d.store.manifest.recover() == []


def test_kill9_mid_stream_promoted_standby_resumes_exactly(tmp_path):
    path = str(tmp_path / "c")
    _tcr._setup(path)
    sb = str(tmp_path / "sb")
    standby.init_standby(path, sb)
    _tcr._run_child_until(
        path, "ingest_flush",
        lambda: os.path.exists(path + ".batch2")
        and _tcr._stream_mark(path, "t", "s1") >= 1,
        child=_tcr.STREAM_CHILD)
    standby.promote(sb)
    d = greengage_tpu.connect(path=sb, numsegments=4)
    # batch 1 (committed) crossed the failover; batch 2 (buffered) died
    assert d.sql("select count(*) from t").rows()[0][0] == 101
    assert d.sql("select v from t where k = 200000").rows() == [(1,)]
    assert d.sql("select count(*) from t where k = 200001").rows() \
        == [(0,)]
    # the durable resume watermark survived promotion intact: re-begin
    # names exactly what to re-send, replays dedup — zero loss, zero dup
    out = d.ingest.stream_begin("t", "s1")
    assert out["resume_seq"] == 1
    dup = d.ingest.stream_rows("s1", {"k": [200000], "v": [1]}, 1)
    assert dup["duplicate"] is True
    d.ingest.stream_rows("s1", {"k": [200001], "v": [2]}, 2)
    d.ingest.stream_end("s1")
    assert d.sql("select count(*) from t").rows()[0][0] == 102
    assert d.sql("select count(*) from t where k = 200001").rows() \
        == [(1,)]
    assert d.store.manifest.recover() == []


# ---------------------------------------------------------------------------
# the failover storm canary (slow, CI chaos tier): kill -9 a live
# coordinator mid mixed read/write storm with the watcher running
# concurrently; auto-promotion must land every acked commit exactly once
# ---------------------------------------------------------------------------

STORM_CHILD = r"""
import os, sys
os.environ["GGTPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[2])
import greengage_tpu
db = greengage_tpu.connect(sys.argv[1], numsegments=4)
open(sys.argv[1] + ".ready", "w").close()
i = 300000
while True:
    db.sql(f"insert into t values ({i}, {i % 7})")
    if i % 3 == 0:
        db.sql("select count(*) from t")        # mixed storm
    print(f"ACK {i}", flush=True)
    i += 1
"""


@pytest.mark.slow
def test_storm_kill9_auto_promotion_exactly_once(tmp_path):
    path = str(tmp_path / "c")
    _tcr._setup(path)
    sb = str(tmp_path / "sb")
    standby.init_standby(path, sb)
    env = dict(os.environ)
    env["GGTPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    proc = subprocess.Popen(
        [sys.executable, "-c", STORM_CHILD, path, REPO],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    # the watcher runs CONCURRENTLY with the storm (deployment shape):
    # the live beat (post-commit + FTS cadence, <= ~5s stale) holds the
    # 10s deadline back until the kill actually lands
    base = counters.snapshot()
    fired = threading.Event()
    w = standby.StandbyWatcher(sb, interval_s=0.25, deadline_s=10.0,
                               on_promote=lambda st: fired.set())
    w.start()
    acked = []
    deadline = time.monotonic() + 240
    try:
        while len(acked) < 25 and time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise AssertionError("storm child died early")
            if line.startswith("ACK "):
                acked.append(int(line.split()[1]))
        assert len(acked) >= 25, "storm never ramped up"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        # acks already committed-and-printed but still in the pipe
        for line in (proc.stdout.read() or "").splitlines():
            if line.startswith("ACK "):
                acked.append(int(line.split()[1]))
        assert fired.wait(90), "watcher never promoted after the kill"
    finally:
        w.stop()
        if proc.poll() is None:
            proc.kill()
    assert counters.since(base).get("standby_promote_total", 0) == 1
    assert standby.fenced(path) is not None
    d = greengage_tpu.connect(path=sb, numsegments=4)
    ks = sorted(int(r[0]) for r in
                d.sql("select k from t where k >= 300000").rows())
    assert len(ks) == len(set(ks)), "duplicate rows after failover"
    missing = set(acked) - set(ks)
    assert not missing, f"acked commits lost in failover: {sorted(missing)}"
    # at most the ONE in-flight statement (committed, kill before print)
    extra = set(ks) - set(acked)
    assert len(extra) <= 1, f"phantom rows after failover: {sorted(extra)}"
    # the promoted coordinator keeps serving the storm's table
    d.sql("insert into t values (400000, 1)")
    assert d.sql("select count(*) from t where k = 400000").rows() \
        == [(1,)]
