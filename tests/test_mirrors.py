"""Mirrors that hold data: replication, failover serving real rows, and
rebuild — VERDICT r1 item #3 (gp_replication.c / buildMirrorSegments.py
analog). The r1 gap: promotion was bookkeeping over an empty mirror; these
tests kill a segment's storage and require the SAME rows back."""

import glob
import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.catalog.segments import SegmentRole, SegmentStatus
from greengage_tpu.runtime.replication import replicated_version
from greengage_tpu.storage.table_store import mirror_root


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "cluster"), numsegments=8, mirrors=True)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.sql("insert into t values " + ",".join(f"({i},{i*10})" for i in range(64)))
    return d


def _kill_content_storage(db, content: int):
    """Simulate losing the primary's disk for one content."""
    for f in glob.glob(os.path.join(db.path, "data", "*", f"seg{content}", "*")):
        os.remove(f)


def test_mirrors_replicate_on_commit(db):
    # synchronous replication: every commit leaves mirrors at head version
    v = db.store.manifest.snapshot()["version"]
    for content in range(8):
        assert replicated_version(db.path, content) == v
        mdir = mirror_root(db.path, content)
        files = glob.glob(os.path.join(mdir, "t", f"seg{content}", "*.ggb"))
        # every manifest-referenced file for this content is mirrored
        snap = db.store.manifest.snapshot()
        want = snap["tables"]["t"]["segfiles"].get(str(content), [])
        assert len(files) >= len(want)
    assert all(e.mode_synced for e in db.catalog.segments.entries
               if e.role is SegmentRole.MIRROR)


def test_failover_serves_identical_rows(db):
    before = sorted(db.sql("select k, v from t").rows())
    assert len(before) == 64
    victim = 3
    _kill_content_storage(db, victim)
    res = db.fts.probe_once()
    assert res[victim] is False
    acting = db.catalog.segments.acting_primary(victim)
    assert acting is not None and acting.preferred_role is SegmentRole.MIRROR
    # reads now come from the mirror tree: same rows
    after = sorted(db.sql("select k, v from t").rows())
    assert after == before


def test_degraded_writes_land_on_mirror_and_survive(db):
    victim = 5
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    db.sql("insert into t values " + ",".join(f"({i},{i})" for i in range(64, 96)))
    got = sorted(db.sql("select k from t").rows())
    assert len(got) == 96
    # new files for the victim content were written into the mirror tree
    assert db.store.data_root(victim) == mirror_root(db.path, victim)


def test_recover_rebuilds_and_rebalances(db, tmp_path):
    from greengage_tpu.mgmt import cli

    before = sorted(db.sql("select k, v from t").rows())
    victim = 2
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    db.sql("insert into t values (1000, 1)")
    db.close()
    rc = cli.main(["recover", "-d", db.path])
    assert rc == 0
    db2 = greengage_tpu.connect(db.path)
    cfg = db2.catalog.segments
    assert all(e.role is e.preferred_role for e in cfg.entries)
    assert all(e.status is SegmentStatus.UP for e in cfg.entries)
    rows = sorted(db2.sql("select k, v from t").rows())
    assert (1000, 1) in rows
    assert [r for r in rows if r[0] < 64] == before
    # primary tree is whole again
    assert db2.store.storage_ok(victim)
    assert cli.main(["checkcat", "-d", db.path]) == 0


def test_stale_mirror_never_promoted(db):
    db.sql("set mirror_sync = off")
    db.sql("insert into t values (500, 5)")   # mirrors now behind
    victim = 1
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    acting = db.catalog.segments.acting_primary(victim)
    # no promotion: the stale mirror keeps its role; the primary is down
    assert acting is not None and acting.preferred_role is SegmentRole.PRIMARY
    assert acting.status is SegmentStatus.DOWN


def test_double_failover_round_trip(db):
    """Writes committed AFTER a failover must replicate back to the demoted
    primary's tree, so a second failover (mirror tree dies) can promote the
    original primary WITHOUT losing them — r2 code-review finding: sync()
    used to copy acting->acting and stamp the marker anyway."""
    victim = 6
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    # committed write while the mirror is acting primary
    db.sql("insert into t values (2000, 2), (2001, 3)")
    want = sorted(db.sql("select k, v from t").rows())
    # now the MIRROR tree dies; the original primary must be in sync again
    for f in glob.glob(os.path.join(db.path, "mirror", f"content{victim}",
                                    "*", f"seg{victim}", "*")):
        os.remove(f)
    res = db.fts.probe_once()
    assert res[victim] is False
    acting = db.catalog.segments.acting_primary(victim)
    assert acting is not None and acting.preferred_role is SegmentRole.PRIMARY
    got = sorted(db.sql("select k, v from t").rows())
    assert got == want
    assert any(r[0] == 2000 for r in got)


def test_promotion_survives_restart(db):
    victim = 4
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    before = sorted(db.sql("select k, v from t").rows())
    db.close()
    db2 = greengage_tpu.connect(db.path)
    acting = db2.catalog.segments.acting_primary(victim)
    assert acting is not None and acting.preferred_role is SegmentRole.MIRROR
    assert sorted(db2.sql("select k, v from t").rows()) == before


def test_text_dictionary_survives_failover_writes(db):
    """Dictionaries are authoritative in the data tree; a post-failover
    INSERT with new TEXT values must not be clobbered by replication
    copying a stale mirror dictionary back (r2 review finding)."""
    db.sql("create table mtx (k int, name text) distributed by (k)")
    db.sql("insert into mtx values (1, 'alpha'), (2, 'beta')")
    victim = 0
    _kill_content_storage(db, victim)
    db.fts.probe_once()
    db.sql("insert into mtx values (3, 'gamma'), (4, 'delta')")
    got = sorted(r[1] for r in db.sql("select k, name from mtx").rows())
    assert got == ["alpha", "beta", "delta", "gamma"]
    # reopen: dictionary on disk must decode every committed code
    db.catalog._save()
    import greengage_tpu

    db2 = greengage_tpu.connect(db.path)
    got2 = sorted(r[1] for r in db2.sql("select k, name from mtx").rows())
    assert got2 == got


def test_expand_new_mirrors_start_unsynced(db):
    cfg = db.catalog.segments
    # direct topology expansion (the session-level expand is exercised in
    # test_runtime): new mirrors must not be promotable before replication
    cfg.expand(10)
    from greengage_tpu.catalog.segments import SegmentRole

    for c in (8, 9):
        assert cfg.entry(c, SegmentRole.MIRROR).mode_synced is False


# ---------------------------------------------------------------------------
# cross-host mirror placement (gpaddmirrors spread / VERDICT r4 #8)
# ---------------------------------------------------------------------------

def test_mirror_roots_spread_and_promote(devices8, tmp_path):
    """Mirror trees on per-host roots: `gg mirrorroots --roots a,b` places
    content k's mirror on root (k+1) % n, moves existing trees, keeps
    replication flowing there — and a lost primary disk promotes the
    mirror at its EXTERNAL root, which then serves the same rows."""
    from greengage_tpu.mgmt import cli

    path = str(tmp_path / "cluster")
    hostA = str(tmp_path / "hostA")
    hostB = str(tmp_path / "hostB")
    d = greengage_tpu.connect(path, numsegments=4, mirrors=True)
    d.sql("create table t (k int, v int) distributed by (k)")
    d.sql("insert into t values " + ",".join(
        f"({i},{i * 10})" for i in range(64)))
    want = d.sql("select count(*), sum(v) from t").rows()
    d.close()
    rc = cli.main(["mirrorroots", "-d", path, "--roots",
                   f"{hostA},{hostB}"])
    assert rc == 0
    d = greengage_tpu.connect(path, numsegments=4)
    # placement: content k under roots[(k+1) % 2]
    for k in range(4):
        host = hostB if (k + 1) % 2 else hostA
        assert mirror_root(path, k).startswith(host)
        assert os.path.isdir(mirror_root(path, k)), k
    # replication continues to the external roots
    d.sql("insert into t values (1000, 1)")
    v = d.store.manifest.snapshot()["version"]
    for k in range(4):
        assert replicated_version(path, k) == v, k
    # disk loss on content 2's primary -> promotion serves from hostA
    _kill_content_storage(d, 2)
    d.fts.probe_once()
    seg = d.catalog.segments.acting_primary(2)
    assert seg.preferred_role is SegmentRole.MIRROR
    r = d.sql("select count(*), sum(v) from t").rows()
    assert r == [(want[0][0] + 1, want[0][1] + 1)]
    d.close()
