"""Data-movement-optimal execution (ISSUE 18, docs/PERF.md "Data
movement"): pipelined bucket schedules (exec/motionpipe.py), the tiered
host-RAM -> disk spill workfile (exec/workfile.py), and the bucketed
redistribute split (parallel/motion.py).

The contract under test:
  (a) bucketed redistribute — motion_pipeline_buckets splits the
      compiled exchange into sub-exchanges with row-order-identical
      results (the serial baseline and the cost-model-only
      motion_pipeline=off path agree too);
  (b) pipelining — bucket k+1's STAGE span overlaps bucket k's COMPUTE
      span, asserted from trace timestamps (a sleep fault on the
      motion_bucket point widens staging so the overlap is
      deterministic, not wall-clock luck), and the realized overlap
      lands in the motion_overlap_ms counter;
  (c) disk tier — a spill whose captured passes exceed spill_host_limit_mb
      by >4x completes oracle-equal via compressed segment files
      (demote + promote counters move, nothing is left on disk);
  (d) cleanup — an error mid-capture leaks no segment files (the spill
      paths' finally closes the workfile), and Database init sweeps
      segments orphaned by a killed process.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.exec import workfile as workfile_mod
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters
from greengage_tpu.runtime.trace import TRACES


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table dim (pk int, grp int) distributed by (pk)")
    d.sql("insert into dim values " + ",".join(
        f"({i},{i % 11})" for i in range(1, 501)))
    d.sql("create table big (k int, fk int, v int) distributed by (k)")
    n = 400_000
    rng = np.random.default_rng(18)
    d.load_table("big", {"k": np.arange(n),
                         "fk": rng.integers(1, 501, n),
                         "v": rng.integers(0, 100, n)})
    d.sql("analyze")
    yield d
    faults.reset("motion_bucket")
    faults.reset("spill_capture")


Q = ("select grp, count(*), sum(v) from big join dim on big.fk = dim.pk "
     "group by grp order by grp")
# full-width sort: the captured runs are raw rows (~9 MB of int64
# columns), so a 1 MB host tier must overflow to disk many times over
QS = "select k, fk, v from big order by v, k limit 5"


def _spill_files(directory):
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return [n for n in names if workfile_mod._FILE_RE.match(n)]


# ---------------------------------------------------------------------
# (a) bucketed redistribute oracle equality
# ---------------------------------------------------------------------
def test_bucketed_redistribute_matches_serial(db):
    """Splitting the compiled redistribute into 4 sub-exchanges (a new
    codegen signature -> a recompile) must be row-order identical, and
    motion_pipeline=off (cost model only, same program) must agree."""
    want = db.sql(Q).rows()
    db.sql("set motion_pipeline_buckets = 4")
    try:
        assert db.sql(Q).rows() == want
    finally:
        db.sql("set motion_pipeline_buckets = 1")
    db.sql("set motion_pipeline = off")
    try:
        assert db.sql(Q).rows() == want
    finally:
        db.sql("set motion_pipeline = on")


# ---------------------------------------------------------------------
# (b) stage(k+1) overlaps compute(k), from span timestamps
# ---------------------------------------------------------------------
def test_stage_overlaps_compute_trace_asserted(db):
    """The bucketed dedupe merge runs on the bucket pipeline: while the
    statement thread runs bucket k's DEVICE program, the stager builds
    bucket k+1's host subset. The sleep fault inside every motion-stage
    span holds each stage open 50 ms, so compute(k) — a multi-ms XLA
    dispatch — must land INSIDE stage(k+1)'s window; asserted on
    [ts, ts+dur] intersection in the statement trace, which shares one
    clock across both threads."""
    q = "select count(distinct k) from big"
    db.sql(q)   # warm the spill-free program
    db.sql("set vmem_protect_limit_mb = 1")
    faults.inject("motion_bucket", "sleep", sleep_s=0.05, occurrences=-1)
    c0 = counters.snapshot()
    tr = None
    try:
        r = db.sql(q)
        tr = TRACES.last()   # before the finally's SET becomes "last"
        assert r.rows() == [(400_000,)]
        assert r.stats.get("spill_merge_buckets", 0) >= 2, r.stats
    finally:
        faults.reset("motion_bucket")
        db.sql("set vmem_protect_limit_mb = 12288")
    d = counters.since(c0)
    assert d.get("motion_overlap_ms", 0) >= 1, d

    stages = tr.find_spans("motion-stage")
    computes = tr.find_spans("motion-compute")
    assert stages and computes, [s["name"] for s in tr.export()]
    overlapped = False
    for c in computes:
        for s in stages:
            if s["args"].get("label") != c["args"].get("label"):
                continue
            if s["args"].get("index") != c["args"].get("index") + 1:
                continue
            c_end = c["ts"] + (c["dur"] or 0.0)
            s_end = s["ts"] + (s["dur"] or 0.0)
            if s["ts"] < c_end and s_end > c["ts"]:
                overlapped = True
    assert overlapped, \
        "no stage(k+1) span overlapped its compute(k) span"


# ---------------------------------------------------------------------
# (c) disk tier: >4x the host budget, oracle-equal, nothing left behind
# ---------------------------------------------------------------------
def test_disk_tier_spill_oracle_equal(db, tmp_path):
    """spill_host_limit_mb=1 puts every multi-MB captured pass (the
    workfile here is well over 4x the budget) through demote -> segment
    file -> promote-on-merge; the rows must match the in-memory run
    exactly and the statement must delete every segment it wrote."""
    sdir = str(tmp_path / "spill")
    want = db.sql(QS).rows()
    db.sql(f"set spill_dir to '{sdir}'")
    db.sql("set spill_host_limit_mb = 1")
    db.sql("set vmem_protect_limit_mb = 1")
    c0 = counters.snapshot()
    try:
        r = db.sql(QS)
        assert r.stats.get("spill_kind") == "sort", r.stats
        assert r.stats.get("spill_passes", 0) >= 2, r.stats
        assert r.rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
        db.sql("set spill_host_limit_mb = 512")
        db.sql("set spill_dir to ''")
    d = counters.since(c0)
    assert d.get("spill_demote_total", 0) >= 1, d
    assert d.get("spill_promote_total", 0) >= 1, d
    assert _spill_files(sdir) == [], "statement leaked spill segments"
    assert counters.get("spill_tier_disk_bytes") == 0


def test_ram_only_mode_never_touches_disk(db, tmp_path):
    """spill_host_limit_mb=0 is the pre-tiered behavior: the RAM tier
    has no budget to overflow, so no segment file is ever written."""
    sdir = str(tmp_path / "spill0")
    want = db.sql(QS).rows()
    db.sql(f"set spill_dir to '{sdir}'")
    db.sql("set spill_host_limit_mb = 0")
    db.sql("set vmem_protect_limit_mb = 1")
    c0 = counters.snapshot()
    try:
        assert db.sql(QS).rows() == want
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")
        db.sql("set spill_host_limit_mb = 512")
        db.sql("set spill_dir to ''")
    assert counters.since(c0).get("spill_demote_total", 0) == 0
    assert not os.path.isdir(sdir) or _spill_files(sdir) == []


# ---------------------------------------------------------------------
# (d) cleanup: error mid-capture + orphan sweep
# ---------------------------------------------------------------------
def test_error_mid_capture_leaks_no_segments(db, tmp_path):
    """Early passes demote to disk (1 MB budget), then the spill_capture
    fault kills pass 4's capture: the statement fails with segments on
    disk, but the spill path's finally closes the workfile and unlinks
    every one of them."""
    sdir = str(tmp_path / "spillerr")
    db.sql(f"set spill_dir to '{sdir}'")
    db.sql("set spill_host_limit_mb = 1")
    db.sql("set vmem_protect_limit_mb = 1")
    faults.inject("spill_capture", "error", start_after=3, occurrences=1)
    c0 = counters.snapshot()
    try:
        with pytest.raises(Exception, match="fault injected"):
            db.sql(QS)
    finally:
        faults.reset("spill_capture")
        db.sql("set vmem_protect_limit_mb = 12288")
        db.sql("set spill_host_limit_mb = 512")
        db.sql("set spill_dir to ''")
    # the premise held: segment files existed when the capture died
    assert counters.since(c0).get("spill_demote_total", 0) >= 1
    assert _spill_files(sdir) == [], "failed statement leaked segments"
    assert counters.get("spill_tier_disk_bytes") == 0
    # the engine still serves (and still spills) after the failure
    db.sql("set vmem_protect_limit_mb = 1")
    try:
        assert db.sql(QS).stats.get("spill_passes", 0) >= 2
    finally:
        db.sql("set vmem_protect_limit_mb = 12288")


def test_sweep_orphans_removes_only_dead_owners(tmp_path):
    d = str(tmp_path)
    # a genuinely dead pid: a subprocess that has already exited
    dead = int(subprocess.run(
        [sys.executable, "-c", "import os; print(os.getpid())"],
        capture_output=True, text=True, check=True).stdout)
    orphan = os.path.join(d, f"gg-spill-{dead}-1-deadbeef.wf")
    live = os.path.join(d, f"gg-spill-{os.getpid()}-2-deadbeef.wf")
    other = os.path.join(d, "not-a-spill-file.wf")
    for p in (orphan, live, other):
        with open(p, "wb") as f:
            f.write(b"x")
    c0 = counters.snapshot()
    assert workfile_mod.sweep_orphans(d) == 1
    assert not os.path.exists(orphan)
    assert os.path.exists(live) and os.path.exists(other)
    assert counters.since(c0).get("spill_orphan_sweep_total", 0) == 1


def test_connect_sweeps_orphans_at_init(tmp_path, devices8):
    """A kill mid-pass leaves segments behind; the next coordinator
    Database over the same cluster removes them at init."""
    path = str(tmp_path / "cluster")
    d1 = greengage_tpu.connect(path, numsegments=4)
    sdir = workfile_mod.spill_dir_of(d1.settings, d1.store)
    d1.close()
    dead = int(subprocess.run(
        [sys.executable, "-c", "import os; print(os.getpid())"],
        capture_output=True, text=True, check=True).stdout)
    os.makedirs(sdir, exist_ok=True)
    orphan = os.path.join(sdir, f"gg-spill-{dead}-7-cafef00d.wf")
    with open(orphan, "wb") as f:
        f.write(b"orphaned segment")
    d2 = greengage_tpu.connect(path, numsegments=4)
    try:
        assert not os.path.exists(orphan), \
            "Database init did not sweep the orphaned segment"
    finally:
        d2.close()
