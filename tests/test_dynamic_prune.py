"""Join-driven runtime partition elimination — VERDICT r3 missing #8,
the nodePartitionSelector.c execution-time role: a partitioned probe
joined to a filtered small build ON THE PARTITION KEY stages only the
child partitions a surviving build key can land in. Static pruning can
never do this (the selecting predicate lives on the other table)."""

import numpy as np
import pytest

import greengage_tpu


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("""create table fact (k int, pd int, v int) distributed by (k)
             partition by range (pd)
             (partition p0 start (0) end (100),
              partition p1 start (100) end (200),
              partition p2 start (200) end (300),
              partition p3 start (300) end (400))""")
    n = 80_000
    rng = np.random.default_rng(5)
    d.load_table("fact", {"k": np.arange(n),
                          "pd": rng.integers(0, 400, n),
                          "v": rng.integers(0, 100, n)})
    # dim: 400 keys, category selects a narrow pd band
    d.sql("create table dim (pk int, cat int) distributed by (pk)")
    d.load_table("dim", {"pk": np.arange(400),
                         "cat": np.arange(400) // 100})
    d.sql("analyze")
    return d


def test_build_filter_prunes_probe_partitions(db):
    # cat = 2 selects pk in [200, 300): only partition p2 can match
    r = db.sql("select count(*), sum(f.v) from fact f, dim d "
               "where f.pd = d.pk and d.cat = 2")
    dyn = r.stats.get("dynamic_prune", {})
    assert dyn.get("fact") == (1, 4), r.stats
    # oracle
    want = db.sql("select count(*), sum(v) from fact "
                  "where pd >= 200 and pd < 300").rows()
    assert r.rows() == want


def test_no_build_filter_still_prunes_by_existing_keys(db):
    d2 = greengage_tpu.connect(numsegments=4)
    d2.sql("""create table f2 (k int, pd int) distributed by (k)
              partition by range (pd)
              (partition a start (0) end (50),
               partition b start (50) end (100))""")
    d2.load_table("f2", {"k": np.arange(1000),
                         "pd": np.arange(1000) % 100})
    d2.sql("create table d2 (pk int) distributed by (pk)")
    d2.load_table("d2", {"pk": np.arange(10)})   # keys 0..9: partition a only
    d2.sql("analyze")
    r = d2.sql("select count(*) from f2, d2 where f2.pd = d2.pk")
    assert r.stats.get("dynamic_prune", {}).get("f2") == (1, 2), r.stats
    assert r.rows()[0][0] == 10 * 10


def test_left_join_never_prunes_probe(db):
    r = db.sql("select count(*) from fact f left join dim d "
               "on f.pd = d.pk and d.cat = 2")
    assert "fact" not in r.stats.get("dynamic_prune", {}), r.stats
    assert r.rows()[0][0] == 80_000   # every probe row survives


def test_semi_join_prunes(db):
    r = db.sql("select count(*) from fact where pd in "
               "(select pk from dim where cat = 0)")
    dyn = r.stats.get("dynamic_prune", {})
    want = db.sql("select count(*) from fact where pd < 100").rows()
    assert r.rows() == want
    if "fact" in dyn:          # semi-join shape reached the annotation
        assert dyn["fact"] == (1, 4)


def test_empty_build_filter_keeps_nothing_but_defaults(db):
    r = db.sql("select count(*) from fact f, dim d "
               "where f.pd = d.pk and d.cat = 99")
    assert r.rows()[0][0] == 0
    dyn = r.stats.get("dynamic_prune", {})
    assert dyn.get("fact") == (0, 4), r.stats


def test_static_and_dynamic_compose(db):
    # static prune (pd < 200 keeps p0,p1) AND the build filter (cat=0
    # keeps p0): the intersection stages one child
    r = db.sql("select count(*) from fact f, dim d "
               "where f.pd = d.pk and d.cat = 0 and f.pd < 200")
    want = db.sql("select count(*) from fact where pd < 100").rows()
    assert r.rows() == want
    dyn = r.stats.get("dynamic_prune", {})
    assert dyn.get("fact", (99, 99))[0] <= 1, r.stats


def test_explicit_join_syntax_also_prunes(db):
    """WHERE conjuncts sink below explicit JOIN ... ON sides (qual
    pushdown), so the build filter reaches the dim scan and the runtime
    partition selector fires for this syntax too."""
    r = db.sql("select count(*), sum(f.v) from fact f join dim d "
               "on f.pd = d.pk where d.cat = 2")
    assert r.stats.get("dynamic_prune", {}).get("fact") == (1, 4), r.stats
    want = db.sql("select count(*), sum(v) from fact "
                  "where pd >= 200 and pd < 300").rows()
    assert r.rows() == want


def test_left_join_where_on_nullable_side_not_sunk(db):
    # WHERE d.cat = 2 on the NULLABLE side of a left join rejects
    # null-extended rows — it must stay ABOVE the join (inner-join
    # equivalence is a rewrite we deliberately do not apply)
    r = db.sql("select count(*) from fact f left join dim d "
               "on f.pd = d.pk where d.cat = 2")
    want = db.sql("select count(*) from fact where pd >= 200 and pd < 300"
                  ).rows()
    assert r.rows() == want


def test_explain_analyze_surfaces_runtime_pruning(db):
    r = db.sql("explain analyze select count(*) from fact f, dim d "
               "where f.pd = d.pk and d.cat = 2")
    txt = r.plan_text
    assert "Dynamic partition selector fact: 1/4 children staged" in txt, txt
