"""End-to-end SQL regression tests on the 8-segment virtual cluster —
the pg_regress greengage_schedule analog, with pandas as oracle."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import QueryError
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.002)
    return d


@pytest.fixture(scope="module")
def oracle():
    return tpch.to_pandas(tpch.generate(0.002))


# ---------------------------------------------------------------------------
# basics
# ---------------------------------------------------------------------------

def test_basic_select_where(db, oracle):
    r = db.sql("select l_orderkey, l_quantity from lineitem "
               "where l_quantity > 45 order by l_orderkey, l_quantity")
    li = oracle["lineitem"]
    want = li[li.l_quantity > 45].sort_values(["l_orderkey", "l_quantity"])
    assert len(r) == len(want)
    got = r.to_pandas()
    assert np.array_equal(got["l_orderkey"], want["l_orderkey"])
    assert np.allclose(got["l_quantity"], want["l_quantity"])


def test_projection_arithmetic(db, oracle):
    r = db.sql("select l_orderkey, l_extendedprice * (1 - l_discount) as rev "
               "from lineitem where l_orderkey <= 20 order by 1, 2")
    li = oracle["lineitem"]
    want = li[li.l_orderkey <= 20].copy()
    want["rev"] = want.l_extendedprice * (1 - want.l_discount)
    want = want.sort_values(["l_orderkey", "rev"])
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.allclose(got["rev"], want["rev"], atol=1e-6)


def test_limit_offset(db, oracle):
    r = db.sql("select o_orderkey from orders order by o_orderkey limit 5 offset 3")
    assert [row[0] for row in r.rows()] == [4, 5, 6, 7, 8]


def test_distinct(db, oracle):
    r = db.sql("select distinct l_returnflag from lineitem order by l_returnflag")
    assert [row[0] for row in r.rows()] == ["A", "N", "R"]


def test_in_between_like(db, oracle):
    r = db.sql("select count(*) from lineitem where l_shipmode in ('AIR', 'RAIL')")
    li = oracle["lineitem"]
    assert r.rows()[0][0] == int(li.l_shipmode.isin(["AIR", "RAIL"]).sum())
    r = db.sql("select count(*) from orders where o_orderpriority like '1%'")
    o = oracle["orders"]
    assert r.rows()[0][0] == int(o.o_orderpriority.str.startswith("1").sum())
    r = db.sql("select count(*) from lineitem where l_quantity between 10 and 20")
    assert r.rows()[0][0] == int(li.l_quantity.between(10, 20).sum())


def test_case_expr(db, oracle):
    r = db.sql(
        "select sum(case when l_returnflag = 'A' then 1 else 0 end) from lineitem")
    li = oracle["lineitem"]
    assert r.rows()[0][0] == int((li.l_returnflag == "A").sum())


def test_extract_year(db, oracle):
    r = db.sql("select extract(year from o_orderdate) y, count(*) c "
               "from orders group by 1 order by 1")
    o = oracle["orders"]
    want = o.groupby(pd.to_datetime(o.o_orderdate, unit="D").dt.year).size()
    got = r.to_pandas()
    assert list(got["y"]) == list(want.index)
    assert list(got["c"]) == list(want.values)


# ---------------------------------------------------------------------------
# TPC-H queries
# ---------------------------------------------------------------------------

def test_q1_pricing_summary(db, oracle):
    r = db.sql("""
      select l_returnflag, l_linestatus,
             sum(l_quantity) as sum_qty,
             sum(l_extendedprice) as sum_base_price,
             sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
             sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
             avg(l_quantity) as avg_qty,
             avg(l_extendedprice) as avg_price,
             avg(l_discount) as avg_disc,
             count(*) as count_order
      from lineitem
      where l_shipdate <= date '1998-12-01' - interval '90' day
      group by l_returnflag, l_linestatus
      order by l_returnflag, l_linestatus
    """)
    li = oracle["lineitem"]
    cutoff = (np.datetime64("1998-12-01") - np.timedelta64(90, "D")
              - np.datetime64("1970-01-01")).astype(int)
    f = li[li.l_shipdate <= cutoff]
    want = f.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"])
    got = r.to_pandas()
    assert len(got) == len(want)
    assert list(got.l_returnflag) == list(want.l_returnflag)
    assert np.allclose(got.sum_qty, want.sum_qty)
    assert np.allclose(got.sum_base_price, want.sum_base_price)
    assert np.allclose(got.avg_qty, want.avg_qty, atol=1e-9)
    assert np.allclose(got.avg_disc, want.avg_disc, atol=1e-9)
    assert np.array_equal(got.count_order, want.count_order)
    disc = f.l_extendedprice * (1 - f.l_discount)
    want_disc = disc.groupby([f.l_returnflag, f.l_linestatus]).sum().reset_index(drop=True)
    assert np.allclose(np.sort(got.sum_disc_price), np.sort(want_disc), rtol=1e-12)


def test_q6_forecast_revenue(db, oracle):
    r = db.sql("""
      select sum(l_extendedprice * l_discount) as revenue
      from lineitem
      where l_shipdate >= date '1994-01-01'
        and l_shipdate < date '1994-01-01' + interval '1' year
        and l_discount between 0.05 and 0.07
        and l_quantity < 24
    """)
    li = oracle["lineitem"]
    lo = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)
    f = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)
           & (li.l_discount >= 0.05) & (li.l_discount <= 0.07) & (li.l_quantity < 24)]
    want = (f.l_extendedprice * f.l_discount).sum()
    got = r.rows()[0][0]
    assert got == pytest.approx(want, rel=1e-12)


def test_q3_shipping_priority(db, oracle):
    r = db.sql("""
      select l_orderkey,
             sum(l_extendedprice * (1 - l_discount)) as revenue,
             o_orderdate, o_shippriority
      from customer, orders, lineitem
      where c_mktsegment = 'BUILDING'
        and c_custkey = o_custkey and l_orderkey = o_orderkey
        and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
      group by l_orderkey, o_orderdate, o_shippriority
      order by revenue desc, o_orderdate limit 10
    """)
    c, o, li = oracle["customer"], oracle["orders"], oracle["lineitem"]
    cut = (np.datetime64("1995-03-15") - np.datetime64("1970-01-01")).astype(int)
    j = li[li.l_shipdate > cut].merge(
        o[(o.o_orderdate < cut)], left_on="l_orderkey", right_on="o_orderkey"
    ).merge(c[c.c_mktsegment == "BUILDING"], left_on="o_custkey", right_on="c_custkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False) \
        .agg(revenue=("revenue", "sum")) \
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True]).head(10)
    got = r.to_pandas()
    assert len(got) == len(want)
    assert np.allclose(got.revenue, want.revenue, rtol=1e-12)
    assert list(got.l_orderkey) == list(want.l_orderkey)


def test_q5_local_supplier_volume(db, oracle):
    r = db.sql("""
      select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
      from customer, orders, lineitem, supplier, nation, region
      where c_custkey = o_custkey and l_orderkey = o_orderkey
        and l_suppkey = s_suppkey and c_nationkey = s_nationkey
        and s_nationkey = n_nationkey and n_regionkey = r_regionkey
        and r_name = 'ASIA'
        and o_orderdate >= date '1994-01-01'
        and o_orderdate < date '1994-01-01' + interval '1' year
      group by n_name
      order by revenue desc
    """)
    c, o, li = oracle["customer"], oracle["orders"], oracle["lineitem"]
    s, n, reg = oracle["supplier"], oracle["nation"], oracle["region"]
    lo = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)
    j = (o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)]
         .merge(c, left_on="o_custkey", right_on="c_custkey")
         .merge(li, left_on="o_orderkey", right_on="l_orderkey")
         .merge(s, left_on=["l_suppkey", "c_nationkey"],
                right_on=["s_suppkey", "s_nationkey"])
         .merge(n, left_on="s_nationkey", right_on="n_nationkey")
         .merge(reg[reg.r_name == "ASIA"], left_on="n_regionkey",
                right_on="r_regionkey"))
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    want = j.groupby("n_name", as_index=False).agg(revenue=("revenue", "sum")) \
        .sort_values("revenue", ascending=False)
    got = r.to_pandas()
    assert len(got) == len(want)
    assert list(got.n_name) == list(want.n_name)
    assert np.allclose(got.revenue, want.revenue, rtol=1e-12)


# ---------------------------------------------------------------------------
# joins + NULL semantics + errors
# ---------------------------------------------------------------------------

def test_explicit_join_syntax(db, oracle):
    r = db.sql("""
      select o_orderkey, c_name from orders
      join customer on c_custkey = o_custkey
      where o_orderkey <= 5 order by o_orderkey
    """)
    o, c = oracle["orders"], oracle["customer"]
    want = o[o.o_orderkey <= 5].merge(c, left_on="o_custkey", right_on="c_custkey") \
        .sort_values("o_orderkey")
    got = r.to_pandas()
    assert list(got.c_name) == list(want.c_name)


def test_left_join_nulls(db):
    db.sql("create table lj_a (k int, v int) distributed by (k);"
           "create table lj_b (k int, w int) distributed by (k);"
           "insert into lj_a values (1, 10), (2, 20), (3, 30);"
           "insert into lj_b values (1, 100), (3, 300)")
    r = db.sql("select a.k, w from lj_a a left join lj_b b on a.k = b.k order by a.k")
    assert r.rows() == [(1, 100), (2, None), (3, 300)]


def test_duplicate_build_keys_multi_match(db):
    db.sql("create table dup_b (k int, v int) distributed by (k);"
           "insert into dup_b values (1, 1), (1, 2), (2, 3), (3, 4), (4, 5), "
           "(5, 6), (6, 7), (7, 8)")
    # self-join on a duplicated key: k=1 appears twice on the build side
    r = db.sql("select a.v av, b.v bv from dup_b a join dup_b b on a.k = b.k "
               "order by av, bv")
    df = pd.DataFrame({"k": [1, 1, 2, 3, 4, 5, 6, 7],
                       "v": [1, 2, 3, 4, 5, 6, 7, 8]})
    want = df.merge(df, on="k").sort_values(["v_x", "v_y"])
    got = r.to_pandas()
    assert len(got) == len(want) == 10  # k=1 expands 2x2, six other keys 1x1
    assert list(got.av) == list(want.v_x)
    assert list(got.bv) == list(want.v_y)
    # dist key == join key, so the planner chose the unique path first; the
    # runtime dup flag must have forced the multi re-plan (retry pinned)
    assert any(k[0].endswith("#multi") for k in db.executor._plan_cache)
    # repeat must hit the cached multi plan, not re-fail on the stale program
    r2 = db.sql("select a.v av, b.v bv from dup_b a join dup_b b on a.k = b.k "
                "order by av, bv")
    assert len(r2) == 10


def test_fk_fk_join_planned_multi_directly(db, oracle):
    # join on a non-key column both sides (c_nationkey = s_nationkey):
    # neither side looks unique at plan time -> multi-match CSR join chosen
    # directly (no runtime retry involved)
    r = db.sql("select count(*) from customer, supplier "
               "where c_nationkey = s_nationkey")
    c, s = oracle["customer"], oracle["supplier"]
    want = len(c.merge(s, left_on="c_nationkey", right_on="s_nationkey"))
    assert r.rows()[0][0] == want


def test_left_join_duplicate_build(db):
    db.sql("create table ml_a (k int, v int) distributed by (k);"
           "create table ml_b (k int, w int) distributed by (k);"
           "insert into ml_a values (1, 10), (2, 20), (3, 30);"
           "insert into ml_b values (1, 100), (1, 101), (3, 300)")
    r = db.sql("select a.k, w from ml_a a left join ml_b b on a.k = b.k "
               "order by a.k, w nulls last")
    assert r.rows() == [(1, 100), (1, 101), (2, None), (3, 300)]


def test_having(db, oracle):
    r = db.sql("select l_returnflag, count(*) c from lineitem "
               "group by l_returnflag having count(*) > 100 order by 1")
    li = oracle["lineitem"]
    want = li.groupby("l_returnflag").size()
    want = want[want > 100]
    got = r.to_pandas()
    assert list(got.l_returnflag) == list(want.index)
    assert list(got.c) == list(want.values)


def test_scalar_agg_empty_result(db):
    r = db.sql("select count(*), sum(l_quantity) from lineitem where l_quantity < 0")
    assert r.rows() == [(0, None)]


def test_distinct_aggregates(db, oracle):
    li = oracle["lineitem"]
    r = db.sql("select count(distinct l_suppkey) from lineitem")
    assert r.rows()[0][0] == li.l_suppkey.nunique()
    r = db.sql("select l_returnflag, count(distinct l_shipmode) c from lineitem "
               "group by l_returnflag order by l_returnflag")
    want = li.groupby("l_returnflag").l_shipmode.nunique()
    got = r.to_pandas()
    assert list(got.c) == list(want.values)
