"""Subquery + set-operation regression tests (pg_regress subselect analog)."""

import numpy as np
import pandas as pd
import pytest

import greengage_tpu
from greengage_tpu.sql.parser import SqlError
from greengage_tpu.utils import tpch


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    tpch.load(d, sf=0.002)
    d.sql("create table sq_a (k int, v int) distributed by (k);"
          "create table sq_b (k int, w int) distributed by (k);"
          "insert into sq_a values (1, 10), (2, 20), (3, 30), (4, null);"
          "insert into sq_b values (1, 100), (3, 300), (5, 500)")
    return d


@pytest.fixture(scope="module")
def oracle():
    return tpch.to_pandas(tpch.generate(0.002))


def test_in_subquery_semi_join(db):
    r = db.sql("select k from sq_a where k in (select k from sq_b) order by k")
    assert [x[0] for x in r.rows()] == [1, 3]


def test_not_in_subquery(db):
    r = db.sql("select k from sq_a where k not in (select k from sq_b) order by k")
    assert [x[0] for x in r.rows()] == [2, 4]


def test_not_in_with_null_in_subquery(db):
    # v contains NULL -> NOT IN yields no rows (PG three-valued semantics)
    db.sql("create table sq_n (x int) distributed by (x);"
           "insert into sq_n values (10), (999)")
    r = db.sql("select k from sq_a where k not in (select v from sq_a)")
    assert len(r) == 0
    # without nulls it behaves normally
    r = db.sql("select x from sq_n where x not in (select w from sq_b) order by x")
    assert [x[0] for x in r.rows()] == [10, 999]


def test_not_in_empty_subquery(db):
    r = db.sql("select count(*) from sq_a where k not in (select k from sq_b where k > 1000)")
    assert r.rows()[0][0] == 4   # empty subquery: everything qualifies


def test_exists_correlated(db):
    r = db.sql("select k from sq_a a where exists "
               "(select 1 from sq_b b where b.k = a.k) order by k")
    assert [x[0] for x in r.rows()] == [1, 3]
    r = db.sql("select k from sq_a a where not exists "
               "(select 1 from sq_b b where b.k = a.k) order by k")
    assert [x[0] for x in r.rows()] == [2, 4]


def test_exists_uncorrelated(db):
    assert db.sql("select count(*) from sq_a where exists (select 1 from sq_b)"
                  ).rows()[0][0] == 4
    assert db.sql("select count(*) from sq_a where exists "
                  "(select 1 from sq_b where k > 1000)").rows()[0][0] == 0


def test_scalar_subquery(db, oracle):
    li = oracle["lineitem"]
    want = int((li.l_quantity > li.l_quantity.mean()).sum())
    r = db.sql("select count(*) from lineitem "
               "where l_quantity > (select avg(l_quantity) from lineitem)")
    assert r.rows()[0][0] == want


def test_tpch_q4_order_priority(db, oracle):
    r = db.sql("""
      select o_orderpriority, count(*) as order_count
      from orders
      where o_orderdate >= date '1993-07-01'
        and o_orderdate < date '1993-07-01' + interval '3' month
        and exists (
          select 1 from lineitem
          where l_orderkey = o_orderkey and l_commitdate < l_receiptdate)
      group by o_orderpriority
      order by o_orderpriority
    """)
    o, li = oracle["orders"], oracle["lineitem"]
    lo = (np.datetime64("1993-07-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1993-10-01") - np.datetime64("1970-01-01")).astype(int)
    ok_orders = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    f = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)
          & o.o_orderkey.isin(ok_orders)]
    want = f.groupby("o_orderpriority").size().sort_index()
    got = r.to_pandas()
    assert list(got.o_orderpriority) == list(want.index)
    assert list(got.order_count) == list(want.values)


def test_union_all_and_distinct(db):
    r = db.sql("select k from sq_a union all select k from sq_b order by k")
    assert [x[0] for x in r.rows()] == [1, 1, 2, 3, 3, 4, 5]
    r = db.sql("select k from sq_a union select k from sq_b order by k")
    assert [x[0] for x in r.rows()] == [1, 2, 3, 4, 5]


def test_union_type_promotion(db):
    r = db.sql("select v from sq_a union all select cast(w as bigint) from sq_b "
               "order by v nulls last")
    vals = [x[0] for x in r.rows()]
    assert vals[:6] == [10, 20, 30, 100, 300, 500] and vals[6] is None


def test_union_replicated_branch_no_duplication(db):
    db.sql("create table sq_r (x int) distributed replicated;"
           "insert into sq_r values (7), (8)")
    r = db.sql("select x from sq_r union all select k from sq_b order by x")
    assert [x[0] for x in r.rows()] == [1, 3, 5, 7, 8]


def test_subquery_error_paths(db):
    with pytest.raises(SqlError, match="one column"):
        db.sql("select k from sq_a where k in (select k, w from sq_b)")
    with pytest.raises(SqlError, match="more than one row"):
        db.sql("select k from sq_a where k > (select k from sq_b)")


def test_tpch_q17_correlated_scalar(db, oracle):
    r = db.sql("""
      select sum(l_extendedprice) / 7.0 as avg_yearly
      from lineitem, part
      where p_partkey = l_partkey and p_brand = 'Brand#23'
        and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                          where l_partkey = p_partkey)
    """)
    li, p = oracle["lineitem"], oracle["part"]
    avg02 = li.groupby("l_partkey").l_quantity.mean() * 0.2
    j = li.merge(p[p.p_brand == "Brand#23"], left_on="l_partkey",
                 right_on="p_partkey")
    j = j[j.l_quantity < j.l_partkey.map(avg02)]
    want = j.l_extendedprice.sum() / 7.0
    got = r.rows()[0][0]
    if want == 0:
        assert got is None or got == 0
    else:
        assert got == pytest.approx(want, abs=5e-6)


def test_correlated_scalar_missing_group_drops_row(db):
    db.sql("create table cs_a (k int, v int) distributed by (k);"
           "create table cs_b (k int, w int) distributed by (k);"
           "insert into cs_a values (1, 10), (2, 20), (3, 30);"
           "insert into cs_b values (1, 5), (1, 7)")
    # k=2,3 have no group in cs_b: scalar is NULL, comparison NULL -> dropped
    r = db.sql("select k from cs_a a where v > (select avg(w) from cs_b b "
               "where b.k = a.k) order by k")
    assert [x[0] for x in r.rows()] == [1]
