"""Joint join-order + aggregation-placement optimization (planner/memo.py
AggInfo) — VERDICT r3 #3, the CXformSplitGbAgg role
(/root/reference/src/backend/gporca/libgpopt/src/xforms/CXformSplitGbAgg.cpp).

The sequential pipeline (pick join order on join cost alone, then place
the agg) can strand a high-NDV GROUP BY on the wrong distribution: the
join-only winner saves a few bytes on an intermediate motion, then pays a
full-width redistribute of the entire join output to group. Folding the
agg completion cost into the memo's final selection picks the order whose
result is already hashed on the group key.
"""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner import memo as M
from greengage_tpu.planner.logical import describe
from greengage_tpu.sql.parser import parse


# ---------------------------------------------------------------------------
# memo-level golden: the joint choice beats both sequential choices
# ---------------------------------------------------------------------------

def _scenario():
    """f1 (wide-ish fact) joins f2 (wide, hashed on its join key) and g
    (narrow, hashed on its join key). GROUP BY g's key. Join-only search
    prefers joining g last (the narrower intermediate), which ends
    distributed on f2's key; joint search joins f2 last, ending on g's
    key where the agg is motion-free."""
    rels = [
        M.RelInfo(400_000, 32.0, dist_cols=("f1.k",)),            # 0: f1
        M.RelInfo(400_000, 48.0, dist_cols=("f2.j",)),            # 1: f2
        M.RelInfo(400_000, 16.0, dist_cols=("g.pk",)),            # 2: g
    ]
    edges = [
        M.EdgeInfo(0, 1, pairs=[("f1.j", "f2.j")], sel=1 / 400_000),
        M.EdgeInfo(0, 2, pairs=[("f1.g", "g.pk")], sel=1 / 400_000),
    ]
    agg = M.AggInfo(group_cols=("g.pk",), groups=400_000.0, naggs=1)
    return rels, edges, agg


def test_joint_choice_beats_sequential():
    rels, edges, agg = _scenario()
    plain = M.optimize(rels, edges, 8)
    joint = M.optimize(rels, edges, 8, agg)
    # join-only: g joins FIRST (the f1xg intermediate is narrower than
    # f1xf2, so the second redistribute moves fewer bytes) and the result
    # ends hashed on f2's key; joint: g joins LAST so the result lands
    # hashed on g.pk and the high-NDV agg needs no motion at all
    assert plain == ((0, 2), 1), plain
    assert joint == ((0, 1), 2), joint


def test_agg_completion_cost_prefers_matching_distribution():
    _, _, agg = _scenario()
    on_key = M.agg_completion_cost(("g.pk",), 400_000, 96.0, agg, 8)
    off_key = M.agg_completion_cost(("f2.j",), 400_000, 96.0, agg, 8)
    assert on_key < off_key
    # low-NDV groups make the placement nearly free either way (partial
    # states collapse): completion must NOT dominate then
    small = M.AggInfo(("g.pk",), 40.0, 1)
    delta = (M.agg_completion_cost(("f2.j",), 400_000, 96.0, small, 8)
             - M.agg_completion_cost(("g.pk",), 400_000, 96.0, small, 8))
    big_delta = off_key - on_key
    assert delta < big_delta


# ---------------------------------------------------------------------------
# end-to-end golden through SQL: the plan shape flips on the GROUP BY
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    rng = np.random.default_rng(31)
    n = 50_000
    d.sql("create table f1 (k1 int, j int, g int, v int) distributed by (k1)")
    d.load_table("f1", {
        "k1": np.arange(n), "j": rng.permutation(n).astype(np.int64),
        "g": rng.permutation(n).astype(np.int64),
        "v": rng.integers(0, 100, n)})
    d.sql("create table f2 (j2 int, w1 int, w2 int, w3 int, w4 int, w5 int) "
          "distributed by (j2)")
    d.load_table("f2", {"j2": np.arange(n), "w1": np.arange(n),
                        "w2": np.arange(n), "w3": np.arange(n),
                        "w4": np.arange(n), "w5": np.arange(n)})
    d.sql("create table gt (pk int, z int) distributed by (pk)")
    d.load_table("gt", {"pk": np.arange(n), "z": np.arange(n)})
    d.sql("analyze")
    return d


def _plan(db, sql: str) -> str:
    planned, _, _ = db._plan(parse(sql)[0])
    return describe(planned)


SQL_GROUPED = ("select gt.pk, sum(f1.v) from f1, f2, gt "
               "where f1.j = f2.j2 and f1.g = gt.pk group by gt.pk")


def test_grouped_plan_lands_on_group_key_distribution(db):
    got = _plan(db, SQL_GROUPED)
    # the aggregate runs single-phase with NO motion of its own: the last
    # join already redistributed onto gt.pk
    assert "Aggregate single" in got, got
    assert "Aggregate partial" not in got, got
    agg_i = got.index("Aggregate single")
    below = got[agg_i:].splitlines()
    # no Motion between the Aggregate and the top Join: the aggregate
    # rides the distribution the (joint-chosen) last join produced
    for ln in below[1:]:
        if ln.strip().startswith("Join"):
            break
        assert "Motion" not in ln, got
    # and the top join's build side is gt (joined LAST): the f1xf2 join
    # sits beneath it behind the redistribute by f1.g
    assert got.index("Scan gt") > got.index("Scan f2"), got


def test_grouped_results_exact(db):
    r = db.sql(SQL_GROUPED).rows()
    assert len(r) == 50_000
    want = db.sql("select sum(v) from f1").rows()[0][0]
    assert sum(s for _, s in r) == want
