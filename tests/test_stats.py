"""ANALYZE statistics + stats-driven planning — VERDICT r1 item #4
(pg_statistic / analyze.c sampling / ORCA statistics calculus analog)."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.planner.stats import _haas_stokes


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=8)
    d.sql("create table s (k int, grp int, val float, lab text, n int) "
          "distributed by (k)")
    rng = np.random.default_rng(11)
    n = 20_000
    d.load_table("s", {
        "k": np.arange(n),
        "grp": rng.integers(0, 500, n),
        "val": rng.uniform(-50.0, 150.0, n),
        "lab": greengage_tpu.types.Coded(
            ["x", "y", "z"], rng.integers(0, 3, n).astype(np.int32)),
        "n": np.arange(n) % 100,
    }, valids={"n": np.arange(n) % 10 != 0})
    d.sql("analyze s")
    return d


def test_stats_collected(db):
    ts = db.catalog.get("s").stats
    assert ts is not None and ts.rows == 20_000
    g = ts.columns["grp"]
    assert 400 <= g.ndv <= 600
    assert g.min == 0 and g.max == 499
    v = ts.columns["val"]
    assert -50.5 < v.min < -49 and 149 < v.max < 150.5
    nn = ts.columns["n"]
    assert abs(nn.null_frac - 0.1) < 0.01
    lab = ts.columns["lab"]
    assert 2.5 <= lab.ndv <= 3.5
    assert len(lab.mcv) == 3   # low-NDV column keeps MCVs


def test_stats_persist_across_restart(db):
    db.catalog._save()
    db2 = greengage_tpu.connect(db.path)
    ts = db2.catalog.get("s").stats
    assert ts is not None and ts.rows == 20_000
    assert 400 <= ts.columns["grp"].ndv <= 600


def test_estimates_follow_stats(db):
    """Planned row estimates must track stats: eq ~ rows/ndv, range via
    min/max interpolation, group count via NDV."""
    from greengage_tpu.planner.logical import Aggregate, Filter

    planned, _, _ = db._plan(_parse_one(db, "select count(*) from s where grp = 7"))
    f = _find(planned, Filter)
    assert 20 <= f.est_rows <= 60          # 20000/500 = 40
    planned, _, _ = db._plan(_parse_one(db, "select count(*) from s where val < 0.0"))
    f = _find(planned, Filter)
    assert 3000 <= f.est_rows <= 6000      # 25% of uniform [-50, 150]
    planned, _, _ = db._plan(
        _parse_one(db, "select grp, count(*) from s group by grp"))
    a = _find(planned, Aggregate)
    assert 300 <= a.est_rows <= 800        # ~500 groups, not sqrt(20000)*4=565...
    # tighter: the FINAL agg est must be ndv-derived, not the row count
    assert a.est_rows < 2000


def test_join_estimate_uses_ndv(db):
    from greengage_tpu.planner.logical import Join

    db.sql("create table dim (grp int, name text) distributed by (grp)")
    db.sql("insert into dim values " +
           ",".join(f"({i},'g{i}')" for i in range(0, 500, 5)))
    db.sql("analyze dim")
    planned, _, _ = db._plan(_parse_one(
        db, "select s.k from s join dim on s.grp = dim.grp"))
    j = _find(planned, Join)
    # |s|*|dim| / max(ndv) = 20000*100/500 = 4000
    assert 2000 <= j.est_rows <= 8000


def test_haas_stokes_bounds():
    # all-distinct sample extrapolates to the table
    assert _haas_stokes(1000, 1000, 1000, 1_000_000) == 1_000_000
    # no singletons: domain essentially covered
    assert _haas_stokes(1000, 10, 0, 1_000_000) == 10
    # estimator stays within [d, N]
    e = _haas_stokes(1000, 500, 250, 1_000_000)
    assert 500 <= e <= 1_000_000


def _parse_one(db, sql):
    from greengage_tpu.sql.parser import parse

    return parse(sql)[0]


def _find(plan, klass):
    if isinstance(plan, klass):
        return plan
    for c in plan.children:
        got = _find(c, klass)
        if got is not None:
            return got
    return None


def test_direct_addressed_join_plan_and_results(db):
    """Dense integer PK (stats min/max ~ rowcount) -> direct-addressed
    join: one scatter build, one gather probe."""
    from greengage_tpu.planner.logical import Join

    db.sql("create table djd (pk int, label int) distributed by (pk)")
    db.sql("insert into djd values " + ",".join(f"({i},{i*7})" for i in range(1, 401)))
    db.sql("create table djf (k int, fk int) distributed by (k)")
    db.sql("insert into djf values " + ",".join(
        f"({i},{(i % 400) + 1})" for i in range(1200)))
    db.sql("analyze djd"); db.sql("analyze djf")
    planned, _, _ = db._plan(_parse_one(
        db, "select djf.k, djd.label from djf join djd on djf.fk = djd.pk"))
    j = _find(planned, Join)
    assert j.direct_domain is not None and j.direct_lo == 1
    assert 380 <= j.direct_domain <= 420
    r = db.sql("select sum(label) from djf join djd on djf.fk = djd.pk")
    want = sum(((i % 400) + 1) * 7 for i in range(1200))
    assert r.rows()[0][0] == want
    # unmatched probes drop out
    db.sql("insert into djf values (9999, 4000)")
    r = db.sql("select count(*) from djf join djd on djf.fk = djd.pk")
    assert r.rows()[0][0] == 1200


def test_direct_join_stale_stats_fallback(db):
    """The direct path's safety net: live build keys beyond the analyzed
    max raise the build overflow flag, and the tier-1 retry falls back to
    the general hash join — no silently dropped matches."""
    db.sql("create table sdd (pk bigint, v int) distributed by (pk)")
    db.sql("insert into sdd values (1,1),(2,2),(3,3)")
    db.sql("analyze sdd")
    db.sql("create table sdf (k int, fk bigint) distributed by (k)")
    db.sql("insert into sdf values (1,1),(2,9000)")
    # NOT re-analyzed: 9000 is outside sdd's recorded [1,3] domain
    db.sql("insert into sdd values (9000, 90)")
    r = db.sql("select v from sdf join sdd on sdf.fk = sdd.pk order by v")
    assert [x[0] for x in r.rows()] == [1, 90], r.rows()
    assert r.stats["tiers_used"] == 2
