"""Measured memory accounting (docs/OBSERVABILITY.md "Memory
accounting"): XLA memory_analysis attached to cached executables (zero
re-analysis on warm hits), the per-statement owner tree, OOM
classification + one-shot spill demotion + the mem-<id>.json forensics
dump, graceful CPU fallback for device watermarks, and the metrics /
server surfaces — the memaccounting.c-analog PR's acceptance tests."""

import glob
import json
import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.exec.executor import OutOfDeviceMemory
from greengage_tpu.runtime import memaccount
from greengage_tpu.runtime.faultinject import faults
from greengage_tpu.runtime.logger import counters, prometheus_text
from greengage_tpu.runtime.runaway import TRACKER
from greengage_tpu.runtime.trace import TRACES

N = 20_000
Q = "select g, count(*), sum(v) from mt group by g order by g"


@pytest.fixture(scope="module")
def db(devices8):
    d = greengage_tpu.connect(numsegments=4)
    d.sql("create table mt (k int, g int, v int) distributed by (k)")
    d.load_table("mt", {"k": np.arange(N), "g": np.arange(N) % 7,
                        "v": np.arange(N) % 11})
    d.sql("analyze")
    return d


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# executable measurement: memory_analysis attached once, reused warm
# ---------------------------------------------------------------------------

def test_measured_bytes_attached_and_zero_reanalysis_on_warm_hit(db):
    db.sql(Q)   # compile + first dispatch: analysis attaches here
    r = db.sql(Q)
    mem = (r.stats or {}).get("mem")
    assert mem, r.stats
    meas = mem["measured"]
    assert meas is not None, mem
    # argument/output bytes are real allocations of the all-segment
    # program — never zero for a staged scan
    assert meas["argument_bytes"] > 0 and meas["output_bytes"] > 0, meas
    assert mem["est_bytes"] > 0
    # a warm program-cache hit performs ZERO re-analysis (and zero
    # re-compilation): the analysis rides the cached CompileResult
    c0 = counters.get("mem_analysis_runs")
    j0 = counters.get("program_cache_hit")
    r2 = db.sql(Q)
    assert counters.get("mem_analysis_runs") - c0 == 0
    assert counters.get("program_cache_hit") > j0
    assert (r2.stats["mem"]["measured"] or {}) == (meas or {})


def test_owner_tree_charges_staging_blockcache_device(db):
    # force a cold stage (fresh reads + fresh cache inserts)
    db.executor._stage_cache.clear()
    db.store.blockcache.clear()
    r = db.sql(Q)
    owners = r.stats["mem"]["owners"]
    assert owners.get("staging", 0) > 0, owners
    assert owners.get("blockcache", 0) > 0, owners
    assert owners.get("device", 0) > 0, owners
    # accounts retire into the ring with the full tree
    ring = memaccount.ACCOUNTS.ring()
    assert ring, "completed account did not land in the ring"
    snap = ring[-1]
    assert snap["owners"]["staging"]["items"], snap
    assert snap["total_bytes"] > 0


def test_estimate_error_gauge_and_mem_histogram(db):
    db.sql(Q)
    assert counters.kind("mem_est_error_pct") == "gauge"
    text = prometheus_text()
    assert "# TYPE ggtpu_executable_mem_mb histogram" in text
    assert 'ggtpu_executable_mem_mb_bucket{le="1"}' in text


# ---------------------------------------------------------------------------
# OOM forensics: classification, spill demotion, typed error + dump
# ---------------------------------------------------------------------------

def test_oom_demotes_to_spill_once(db):
    e0 = counters.get("oom_events")
    s0 = counters.get("oom_spill_retries")
    faults.inject("device_oom", "skip", occurrences=1)
    r = db.sql(Q)   # first dispatch fakes RESOURCE_EXHAUSTED
    # ... and the statement completes on the spill path anyway
    assert r.stats.get("oom_demoted") is True, r.stats
    assert r.stats.get("spill_passes", 0) >= 1
    assert counters.get("oom_events") == e0 + 1
    assert counters.get("oom_spill_retries") == s0 + 1
    # correct answer survives the demotion
    rows = {int(g): (int(c), int(s)) for g, c, s in r.rows()}
    g = np.arange(N) % 7
    v = np.arange(N) % 11
    for k in range(7):
        m = g == k
        assert rows[k] == (int(m.sum()), int(v[m].sum()))


def test_oom_typed_error_carries_accounting_and_dumps_json(db):
    db.sql("set oom_spill_retry = off")
    db.executor._stage_cache.clear()   # guarantee a staging owner charge
    faults.inject("device_oom", "skip", occurrences=1)
    try:
        with pytest.raises(OutOfDeviceMemory) as ei:
            db.sql(Q)
    finally:
        db.sql("set oom_spill_retry = on")
    e = ei.value
    assert "out of device memory" in str(e).lower()
    owners = e.snapshot.get("owners") or {}
    assert "device" in owners and "staging" in owners, e.snapshot
    # the dump lands beside the slow-log traces with the full tree
    dumps = sorted(glob.glob(os.path.join(db.path, "log", "mem-*.json")),
                   key=os.path.getmtime)
    assert dumps, "mem-<id>.json forensics dump missing"
    with open(dumps[-1]) as f:
        payload = json.load(f)
    assert payload["error"]
    assert payload["accounting"]["owners"]["device"]["bytes"] > 0
    assert payload["accounting"]["owners"]["staging"]["bytes"] > 0
    assert payload["statement_id"] == e.snapshot.get("statement_id")


def test_oom_classifier_shapes():
    assert memaccount.is_oom_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 123 bytes"))
    assert memaccount.is_oom_error(RuntimeError("Out of memory"))
    assert not memaccount.is_oom_error(RuntimeError("bloom filter failed"))
    assert not memaccount.is_oom_error(ValueError("shape mismatch"))


# ---------------------------------------------------------------------------
# CPU fallback: memory_stats() is None, everything stays graceful
# ---------------------------------------------------------------------------

def test_cpu_memory_stats_none_is_graceful(db):
    # tier-1 runs JAX_PLATFORMS=cpu: the CPU backend has no allocator
    # stats; the sampler must return None (and self-disable), statements
    # must run untouched, and spans must stay free of hbm args
    stats = memaccount.device_memory_stats()
    if stats is not None:
        pytest.skip("backend reports allocator stats (not the CPU path)")
    assert memaccount.sample_watermark() is None
    assert memaccount.sample_watermark() is None   # repeat: stays None
    db.sql(Q)
    tr = TRACES.last()
    assert all("hbm_bytes" not in s["args"] for s in tr.export())


# ---------------------------------------------------------------------------
# process gauges, runaway ledger, report + server surfaces
# ---------------------------------------------------------------------------

def test_process_gauges_rss_fds_pool_depth(db):
    out = memaccount.update_process_gauges()
    assert out.get("host_rss_bytes", 0) > 0
    assert out.get("host_open_fds", 0) > 0
    assert out.get("staging_pool_queue_depth", -1) >= 0
    text = prometheus_text()
    assert "# TYPE ggtpu_host_rss_bytes gauge" in text
    assert "# TYPE ggtpu_staging_pool_queue_depth gauge" in text


def test_owner_gauges_exported_during_statement(db):
    db.executor._stage_cache.clear()
    db.sql(Q)
    # live totals drain when statements retire; the gauge names must
    # still be present (written at least once during the run above via
    # update_process_gauges) and non-negative
    memaccount.update_process_gauges()
    snap = counters.snapshot()
    for name in ("mem_owner_bytes_staging", "mem_owner_bytes_device"):
        assert snap.get(name, 0) >= 0


def test_runaway_ledger_measured_flag():
    TRACKER.enter()
    try:
        TRACKER.reprice(1 << 20, 0, 0.9, measured=True)
        snap = [e for e in TRACKER.snapshot() if e["bytes"] == 1 << 20]
        assert snap and snap[0]["measured"] is True
        assert "statement_id" in snap[0]
    finally:
        TRACKER.release()


def test_mem_report_and_server_op(db, tmp_path):
    from greengage_tpu.runtime.server import SqlClient, SqlServer

    rep = memaccount.report(db)
    assert "process" in rep and "vmem_tracker" in rep
    assert any(x["measured"] for x in rep["executables"]), \
        rep["executables"]
    srv = SqlServer(db, str(tmp_path / "mem.sock"))
    srv.start()
    try:
        c = SqlClient(str(tmp_path / "mem.sock"))
        c.sql("select count(*) from mt")
        m = c.op({"op": "mem"})
        assert m["ok"], m
        assert "block_cache" in m["mem"]
        assert m["mem"]["device"] is None or "bytes_in_use" in m["mem"]["device"]
        # the metrics op refreshes host gauges at scrape time
        t = c.op({"op": "metrics"})
        assert "ggtpu_host_rss_bytes" in t["text"]
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE surfaces (acceptance criterion)
# ---------------------------------------------------------------------------

def test_explain_analyze_prints_measured_memory_on_warm_statement(db):
    db.sql(Q)   # warm the statement's plan
    txt = db.sql("explain analyze " + Q).plan_text
    assert "Memory: vmem estimate" in txt, txt
    assert "executable measured: args" in txt, txt
    assert "+ temps" in txt and "+ out" in txt, txt
    # per-node Memory annotation rides the instrumented tree
    assert "memory ~" in txt, txt
