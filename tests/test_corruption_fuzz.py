"""Corruption fuzz over the block codec — both paths (native .so and the
numpy fallback): bit flips and truncation anywhere in a frame or a column
file must either return the EXACT original bytes or raise the typed
CorruptionError. Never silently wrong data.

The frame CRC covers the header fields as well as the payload, so this
holds for every byte of the frame (a flipped nrows/raw_len/codec byte is a
checksum mismatch, not a misread). Footer damage is covered by the footer
CRC in the file tail.

Tier-1 runs small deterministic variants; the exhaustive every-bit loops
are marked slow."""

import os
import zlib

import numpy as np
import pytest

from greengage_tpu.storage import native
from greengage_tpu.storage.blockfile import (FOOTER_TAIL, read_column_file,
                                             write_column_file)
from greengage_tpu.storage.corruption import CorruptionError


@pytest.fixture(params=["native", "numpy"])
def codec(request, monkeypatch):
    """Run the SAME fuzz under the .so and the numpy fallback."""
    if request.param == "numpy":
        monkeypatch.setattr(native, "_lib", False)
    elif not native.have_native():
        pytest.skip("native codec unavailable")
    return request.param


def _frame(comp, n=2048, seed=3):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 7, n, dtype=np.int64).tobytes()   # compressible
    return raw, native.block_encode(raw, n, comp), n


def _assert_exact_or_typed(frame, raw, nrows):
    try:
        out, rows, _ = native.block_decode(bytes(frame))
    except CorruptionError:
        return False
    assert out == raw and rows == nrows, "silently wrong data"
    return True


# ---------------------------------------------------------------------------
# frame level
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comp", [native.COMP_NONE, native.COMP_ZLIB])
def test_frame_byte_flips_detected(codec, comp):
    """Deterministic tier-1 variant: every header byte at every bit, plus
    every payload byte at one bit — all must raise (CRC covers both)."""
    raw, frame, n = _frame(comp)
    for pos in range(native.HDR_LEN):
        for bit in range(8):
            bad = bytearray(frame)
            bad[pos] ^= 1 << bit
            assert not _assert_exact_or_typed(bad, raw, n), \
                f"header flip undetected at {pos}.{bit}"
    for pos in range(native.HDR_LEN, len(frame)):
        bad = bytearray(frame)
        bad[pos] ^= 0xFF
        assert not _assert_exact_or_typed(bad, raw, n), \
            f"payload flip undetected at {pos}"


@pytest.mark.parametrize("comp", [native.COMP_NONE, native.COMP_ZLIB])
def test_frame_truncation_detected(codec, comp):
    raw, frame, n = _frame(comp)
    for k in sorted({0, 1, 4, 31, 32, 33, len(frame) // 2, len(frame) - 1}):
        with pytest.raises(CorruptionError):
            native.block_decode(frame[:k])


def test_frame_roundtrip_unmodified(codec):
    for comp in (native.COMP_NONE, native.COMP_ZLIB, native.COMP_ZSTD):
        raw, frame, n = _frame(comp)
        out, rows, consumed = native.block_decode(frame)
        assert out == raw and rows == n and consumed == len(frame)


@pytest.mark.slow
@pytest.mark.parametrize("comp", [native.COMP_NONE, native.COMP_ZLIB])
def test_frame_every_bit_flip_slow(codec, comp):
    """Exhaustive: EVERY bit of the frame, multiple seeds/sizes."""
    for seed, n in [(0, 512), (1, 4096), (2, 16384)]:
        raw, frame, nrows = _frame(comp, n=n, seed=seed)
        for pos in range(len(frame)):
            for bit in range(8):
                bad = bytearray(frame)
                bad[pos] ^= 1 << bit
                assert not _assert_exact_or_typed(bad, raw, nrows), \
                    f"flip undetected at seed={seed} {pos}.{bit}"


@pytest.mark.slow
def test_frame_every_truncation_slow(codec):
    raw, frame, n = _frame(native.COMP_ZLIB)
    for k in range(len(frame)):
        with pytest.raises(CorruptionError):
            native.block_decode(frame[:k])


# ---------------------------------------------------------------------------
# file level (footer + frames; the shape reads actually take)
# ---------------------------------------------------------------------------

def _file(tmp_path, comp="zlib", n=6000, seed=9):
    vals = np.random.default_rng(seed).integers(0, 100, n).astype(np.int64)
    path = str(tmp_path / "fuzz.ggb")
    write_column_file(path, vals, comp, block_rows=2048)
    return path, vals


def _assert_file_exact_or_typed(path, vals):
    try:
        back = read_column_file(path)
    except CorruptionError:
        return False
    assert np.array_equal(back, vals), "silently wrong data"
    return True


def test_file_flip_fuzz_deterministic(tmp_path, codec):
    """200 deterministic positions across the file + the whole footer
    tail region: exact data or typed error, never garbage."""
    path, vals = _file(tmp_path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pristine = f.read()
    rng = np.random.default_rng(7)
    positions = sorted(set(rng.integers(0, size, 200).tolist())
                       | set(range(size - FOOTER_TAIL - 64, size)))
    for pos in positions:
        bad = bytearray(pristine)
        bad[pos] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bad)
        _assert_file_exact_or_typed(path, vals)
    with open(path, "wb") as f:
        f.write(pristine)
    assert np.array_equal(read_column_file(path), vals)


def test_file_truncations_classified(tmp_path, codec):
    path, vals = _file(tmp_path)
    with open(path, "rb") as f:
        pristine = f.read()
    for k in [0, 5, FOOTER_TAIL - 1, len(pristine) // 2, len(pristine) - 1]:
        with open(path, "wb") as f:
            f.write(pristine[:k])
        with pytest.raises(CorruptionError) as ei:
            read_column_file(path)
        assert ei.value.cause in ("truncated", "bad_footer", "crc_mismatch")
        assert path in str(ei.value)


@pytest.mark.slow
def test_file_flip_every_byte_slow(tmp_path, codec):
    path, vals = _file(tmp_path, n=2000)
    with open(path, "rb") as f:
        pristine = f.read()
    for pos in range(len(pristine)):
        bad = bytearray(pristine)
        bad[pos] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bad)
        _assert_file_exact_or_typed(path, vals)


# ---------------------------------------------------------------------------
# footer classification (satellite: short/truncated/garbage-tail files)
# ---------------------------------------------------------------------------

def test_footer_short_file_classified(tmp_path):
    from greengage_tpu.storage.blockfile import read_footer

    p = str(tmp_path / "short.ggb")
    with open(p, "wb") as f:
        f.write(b"tiny")
    with pytest.raises(CorruptionError) as ei:
        read_footer(p)
    assert ei.value.cause == "truncated" and p in str(ei.value)


def test_footer_garbage_tail_classified(tmp_path):
    from greengage_tpu.storage.blockfile import read_footer

    path, _vals = _file(tmp_path)
    with open(path, "ab") as f:
        f.write(b"\x00" * 64)   # garbage appended past the footer
    with pytest.raises(CorruptionError) as ei:
        read_footer(path)
    assert ei.value.cause == "bad_footer"


def test_footer_json_damage_classified(tmp_path):
    """A flip INSIDE the footer json (still valid length/magic) must trip
    the footer CRC, not silently change dtype/offsets."""
    path, _vals = _file(tmp_path)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - FOOTER_TAIL - 10)
        b = f.read(1)
        f.seek(size - FOOTER_TAIL - 10)
        f.write(bytes([b[0] ^ 0x01]))
    with pytest.raises(CorruptionError) as ei:
        read_column_file(path)
    assert ei.value.cause == "bad_footer"
    assert "checksum" in str(ei.value)


def test_footer_crc_matches_spec(tmp_path):
    """The tail layout is [json][crc32(json) u32][len u64][magic u32]."""
    path, _vals = _file(tmp_path)
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        f.seek(size - FOOTER_TAIL)
        tail = f.read(FOOTER_TAIL)
        flen = int.from_bytes(tail[4:12], "little")
        f.seek(size - FOOTER_TAIL - flen)
        fj = f.read(flen)
    assert int.from_bytes(tail[:4], "little") == (zlib.crc32(fj) & 0xFFFFFFFF)
