"""bench.py harness smoke: the time-to-first-number engineering is itself
under test. Round 2-4 lost their TPU number to setup cost + a wedged
backend; the fix is a warm path — dataset pickle cache, row-exact bench-dir
reuse, baseline sidecar — so a single probe window suffices. These tests
pin that the warm path actually skips generation and still lands the same
headline (reference analog: the perf harness reuses loaded clusters,
src/test/performance)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(tmp, tag):
    headline = os.path.join(tmp, f"headline_{tag}.json")
    env = dict(os.environ)
    env.update({
        "GGTPU_BENCH_PLATFORM": "cpu",
        "GGTPU_BENCH_SF": "0.01",
        "GGTPU_BENCH_RUNS": "1",
        "GGTPU_BENCH_QUERIES": "q1",
        "GGTPU_BENCH_DIR": os.path.join(tmp, "cluster"),
        "GGTPU_HEADLINE_FILE": headline,
        "GGTPU_BENCH_CHILD": "1",
        # dataset pickle cache scoped to the test tmpdir, not /tmp
        "GGTPU_TPCH_CACHE_DIR": tmp,
    })
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--run"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, p.stderr[-3000:]
    with open(headline) as f:
        line = json.load(f)
    return line, p.stderr


def test_bench_cold_then_warm(tmp_path):
    tmp = str(tmp_path)
    line1, err1 = _run_bench(tmp, "cold")
    assert line1["metric"] == "tpch_q1_rows_per_sec_per_chip"
    assert line1["value"] > 0
    assert "generating" in err1

    # warm run: same dir — generation must be skipped entirely and the
    # baseline must come from the sidecar (no second baseline computation)
    line2, err2 = _run_bench(tmp, "warm")
    assert line2["value"] > 0
    assert "skipping generation" in err2
    assert "generating" not in err2
    meta_file = os.path.join(tmp, "cluster.meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    assert meta["baselines"]["q1"] > 0
    # SF0.01: 15k orders x 1-7 lines (avg 4) — seed-dependent but bounded
    assert 45_000 < meta["counts"]["lineitem"] < 75_000
