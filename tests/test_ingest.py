"""gpfdist-lite parallel ingest + SREH — VERDICT r1 item #8
(gpfdist.c chunk serving; cdbsreh.c SEGMENT REJECT LIMIT)."""

import os

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.ingest import (FileDistServer, _read_chunk,
                                          fetch_chunks)
from greengage_tpu.sql.parser import SqlError


@pytest.fixture()
def db(devices8, tmp_path):
    d = greengage_tpu.connect(str(tmp_path / "c"), numsegments=4)
    d.sql("create table ld (id int, qty decimal(10,2), tag text) "
          "distributed by (id)")
    return d


def _write_csv(path, nrows=5000, bad_lines=()):
    with open(path, "w") as f:
        f.write("id,qty,tag\n")
        for i in range(nrows):
            if i in bad_lines:
                f.write(f"{i},not-a-number,t{i % 7}\n")
            else:
                f.write(f"{i},{i}.25,t{i % 7}\n")


def test_chunk_alignment_covers_every_row(tmp_path):
    p = str(tmp_path / "f.csv")
    _write_csv(p, nrows=997)
    whole = open(p, "rb").read()
    for n in (1, 3, 8):
        parts = [_read_chunk(p, i, n) for i in range(n)]
        assert b"".join(parts) == whole
        # every chunk is newline-terminated (no split rows)
        for part in parts:
            assert part == b"" or part.endswith(b"\n")


def test_parallel_gpfdist_load(db, tmp_path):
    _write_csv(str(tmp_path / "ld.csv"), nrows=4000)
    srv = FileDistServer(str(tmp_path))
    srv.start()
    try:
        tag = db.sql(f"copy ld from '{srv.url('ld.csv')}' "
                     "with (header true, chunks 6)")
        assert tag == "COPY 4000"
        assert srv.requests_served >= 6
        r = db.sql("select count(*), min(id), max(id) from ld")
        assert r.rows() == [(4000, 0, 3999)]
        r = db.sql("select qty from ld where id = 7")
        assert abs(r.rows()[0][0] - 7.25) < 1e-9
    finally:
        srv.stop()


def test_sreh_reject_limit_holds(db, tmp_path):
    p = str(tmp_path / "bad.csv")
    _write_csv(p, nrows=1000, bad_lines=(10, 500, 900))
    tag = db.sql(f"copy ld from '{p}' with (header true, "
                 "segment_reject_limit 5)")
    assert tag.startswith("COPY 997")
    assert "rejected 3" in tag
    log = db.error_log("ld")
    assert len(log) == 3
    assert all("not-a-number" in e["row"] for e in log)
    assert any(e["line"] == 12 for e in log)   # 1-based incl. header


def test_sreh_reject_limit_exceeded_aborts(db, tmp_path):
    p = str(tmp_path / "vbad.csv")
    _write_csv(p, nrows=100, bad_lines=tuple(range(0, 60)))
    before = db.sql("select count(*) from ld").rows()[0][0]
    with pytest.raises(SqlError, match="REJECT LIMIT"):
        db.sql(f"copy ld from '{p}' with (header true, "
               "segment_reject_limit 10)")
    assert db.sql("select count(*) from ld").rows()[0][0] == before


def test_no_reject_limit_aborts_on_first_bad_row(db, tmp_path):
    p = str(tmp_path / "one.csv")
    _write_csv(p, nrows=50, bad_lines=(25,))
    with pytest.raises(SqlError, match="COPY line"):
        db.sql(f"copy ld from '{p}' with (header true)")


def test_sreh_over_gpfdist(db, tmp_path):
    _write_csv(str(tmp_path / "g.csv"), nrows=2000, bad_lines=(100, 1500))
    srv = FileDistServer(str(tmp_path))
    srv.start()
    try:
        tag = db.sql(f"copy ld from '{srv.url('g.csv')}' "
                     "with (header true, chunks 4, segment_reject_limit 10)")
        assert tag.startswith("COPY 1998")
        assert len(db.error_log("ld")) == 2
    finally:
        srv.stop()
