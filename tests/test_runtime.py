"""Runtime-services tests: fault injection, FTS failover, DTM transactions,
expansion — the isolation2 / fts_errors / crash_recovery_dtm analog tier."""

import numpy as np
import pytest

import greengage_tpu
from greengage_tpu.runtime.dtm import TransactionError
from greengage_tpu.runtime.faultinject import FaultError, faults
from greengage_tpu.runtime.fts import cluster_state


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def db(tmp_path, devices8):
    d = greengage_tpu.connect(path=str(tmp_path / "cl"), numsegments=4)
    d.sql("create table t (k bigint, v int) distributed by (k)")
    d.sql("insert into t values (1, 10), (2, 20), (3, 30), (4, 40)")
    return d


# ---------------------------------------------------------------------------
# fault injection registry
# ---------------------------------------------------------------------------

def test_fault_types():
    # throwaway names: this IS the injector unit test
    faults.inject("p1", "error", occurrences=1)   # gg:ok(registry)
    with pytest.raises(FaultError):
        faults.check("p1")
    assert not faults.check("p1")  # occurrence consumed
    faults.inject("p2", "skip", occurrences=2)   # gg:ok(registry)
    assert faults.check("p2") and faults.check("p2") and not faults.check("p2")
    faults.inject("p3", "error", segment=1)   # gg:ok(registry)
    assert not faults.check("p3", segment=0)
    with pytest.raises(FaultError):
        faults.check("p3", segment=1)


# ---------------------------------------------------------------------------
# FTS: probe, failure, promotion
# ---------------------------------------------------------------------------

def test_fts_probe_all_up(db):
    assert db.fts.probe_once() == {0: True, 1: True, 2: True, 3: True}
    assert db.catalog.segments.all_up()


def test_fts_failover_promotes_mirror(tmp_path, devices8):
    """Promotion requires an in-sync mirror. A freshly created mirror holds
    no data (mode_synced=False) and must NOT be promoted; after a sync it
    is. Full end-to-end failover over real replicated files is in
    tests/test_mirrors.py."""
    from greengage_tpu.catalog.segments import (
        SegmentConfig, SegmentRole, SegmentStatus)
    from greengage_tpu.runtime.fts import FtsProber

    cfg = SegmentConfig.create(4, with_mirrors=True)
    prober = FtsProber(cfg)
    faults.inject("fts_probe", "error", segment=1, occurrences=1)
    res = prober.probe_once()
    assert res[1] is False
    # unsynced mirror: primary down, NO promotion (would lose data)
    down = cfg.entry(1, SegmentRole.PRIMARY)
    assert down.preferred_role is SegmentRole.PRIMARY
    assert down.status is SegmentStatus.DOWN

    # content 2's mirror is in sync (replication ran): promotion proceeds
    cfg.entry(2, SegmentRole.MIRROR).mode_synced = True
    faults.inject("fts_probe", "error", segment=2, occurrences=1)
    v0 = cfg.version
    res = prober.probe_once()
    assert res[2] is False
    promoted = cfg.entry(2, SegmentRole.PRIMARY)
    assert promoted.preferred_role is SegmentRole.MIRROR
    assert cfg.version == v0 + 1
    # dispatcher topology invalidation hook: version moved
    rows = cluster_state(cfg)
    assert any(r["content"] == 2 and r["role"] == "p" for r in rows)


# ---------------------------------------------------------------------------
# DTM transactions
# ---------------------------------------------------------------------------

def test_tx_commit_and_visibility(db):
    db.sql("begin")
    db.sql("insert into t values (5, 50)")
    # uncommitted writes invisible to reads (snapshot isolation)
    assert db.sql("select count(*) from t").rows()[0][0] == 4
    db.sql("commit")
    assert db.sql("select count(*) from t").rows()[0][0] == 5


def test_tx_abort_discards(db):
    db.sql("begin")
    db.sql("insert into t values (6, 60)")
    db.sql("rollback")
    assert db.sql("select count(*) from t").rows()[0][0] == 4


def test_tx_crash_between_prepare_and_commit(db):
    faults.inject("dtx_before_commit", "error", occurrences=1)
    db.sql("begin")
    db.sql("insert into t values (7, 70)")
    with pytest.raises(FaultError):
        db.sql("commit")
    # in-process failure SELF-HEALS (r2): the version claim is released in
    # the error path, nothing stays in doubt, and new writes proceed
    assert db.sql("select count(*) from t").rows()[0][0] == 4
    assert db.store.manifest.recover() == []
    db.sql("insert into t values (70, 700)")
    assert db.sql("select count(*) from t").rows()[0][0] == 5
    db.sql("delete from t where k = 70")

    # a REAL crash leaves the prepared-but-uncommitted manifest behind (no
    # cleanup code ran): recover() must roll it back, unblocking writers
    tx = db.store.manifest.begin()
    v = db.store.manifest.prepare(tx)
    rolled = db.store.manifest.recover()
    assert rolled == [v]
    assert db.sql("select count(*) from t").rows()[0][0] == 4
    db.sql("insert into t values (71, 710)")
    db.sql("delete from t where k = 71")


def test_tx_nesting_rejected(db):
    db.sql("begin")
    with pytest.raises(TransactionError):
        db.sql("begin")
    db.sql("rollback")


# ---------------------------------------------------------------------------
# expansion (gpexpand analog)
# ---------------------------------------------------------------------------

def test_expand_redistributes(tmp_path, devices8):
    db = greengage_tpu.connect(path=str(tmp_path / "ex"), numsegments=2)
    db.sql("create table e (k bigint, s text) distributed by (k)")
    ks = np.arange(1000, dtype=np.int64)
    db.load_table("e", {"k": ks, "s": [f"s{i%5}" for i in range(1000)]})
    before = db.sql("select s, count(*) c from e group by s order by s").rows()

    moved = db.expand(6)
    assert moved["e"] == 1000
    # every segment now holds its hash share, placement invariant preserved
    from greengage_tpu.storage import native
    seen = 0
    for seg in range(6):
        cols, _, n = db.store.read_segment("e", seg)
        seen += n
        if n:
            assert np.all(native.hash_i64(cols["k"]) % np.uint32(6) == seg)
    assert seen == 1000
    after = db.sql("select s, count(*) c from e group by s order by s").rows()
    assert after == before


def test_expand_replicated_table(tmp_path, devices8):
    db = greengage_tpu.connect(path=str(tmp_path / "ex2"), numsegments=2)
    db.sql("create table r (x int) distributed replicated")
    db.sql("insert into r values (1), (2), (3)")
    db.expand(4)
    for seg in range(4):
        _, _, n = db.store.read_segment("r", seg)
        assert n == 3
    assert db.sql("select count(*) from r").rows()[0][0] == 3


# ---------------------------------------------------------------------------
# CLI (behave/mgmt_utils analog, in-process)
# ---------------------------------------------------------------------------

def test_cli_roundtrip(tmp_path, capsys, devices8):
    from greengage_tpu.mgmt import cli

    d = str(tmp_path / "cli")
    assert cli.main(["init", "-d", d, "-n", "4"]) == 0
    assert cli.main(["sql", "-d", d,
                     "create table c (k int, v int) distributed by (k)"]) == 0
    assert cli.main(["sql", "-d", d, "insert into c values (1, 2), (3, 4)"]) == 0
    assert cli.main(["sql", "-d", d, "select sum(v) from c"]) == 0
    out = capsys.readouterr().out
    assert "6" in out
    assert cli.main(["state", "-d", d]) == 0
    out = capsys.readouterr().out
    assert "c: 2 rows" in out
    assert cli.main(["checkcat", "-d", d]) == 0
    assert "consistent" in capsys.readouterr().out
