// ggcodec — native host-side codec for greengage_tpu.
//
// Role parity with the reference's native storage/hash path:
//   - distribution hashing          ≙ src/backend/cdb/cdbhash.c
//   - block checksum + frame codec  ≙ src/backend/cdb/cdbappendonlystorageformat.c
//
// The hash spec here MUST stay bit-identical to greengage_tpu/ops/hashing.py
// (the JAX device implementation): murmur3 fmix32 finalizer over the 32-bit
// halves of each 64-bit value, FNV-style combine across columns, placement =
// hash % numsegments. All arithmetic is wrapping uint32.
//
// Build: make -C native  (produces libggcodec.so, loaded via ctypes)

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// Hashing (cdbhash.c analog)
// ---------------------------------------------------------------------------

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

static const uint32_t GG_HASH_INIT = 0x9e3779b9u;
static const uint32_t GG_COMBINE_MUL = 0x01000193u;  // FNV prime

uint32_t gg_hash_i64(int64_t v, uint32_t seed) {
  uint32_t lo = (uint32_t)((uint64_t)v & 0xffffffffu);
  uint32_t hi = (uint32_t)(((uint64_t)v >> 32) & 0xffffffffu);
  uint32_t h = seed ^ GG_HASH_INIT;
  h = fmix32(h ^ lo);
  h = fmix32(h ^ hi);
  return h;
}

uint32_t gg_hash_combine(uint32_t acc, uint32_t h) {
  return fmix32(acc * GG_COMBINE_MUL ^ h);
}

// Batch: hash one int64 column into out (uint32), with seed.
void gg_hash_i64_batch(const int64_t* vals, int64_t n, uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = gg_hash_i64(vals[i], seed);
}

// Batch combine: acc[i] = combine(acc[i], h[i])
void gg_hash_combine_batch(uint32_t* acc, const uint32_t* h, int64_t n) {
  for (int64_t i = 0; i < n; i++) acc[i] = gg_hash_combine(acc[i], h[i]);
}

// Hash a byte string by folding 8-byte little-endian chunks (zero padded)
// through hash_i64 + combine. Used for TEXT placement hashes.
uint32_t gg_hash_bytes(const uint8_t* data, int64_t len, uint32_t seed) {
  uint32_t acc = seed ^ GG_HASH_INIT;
  int64_t i = 0;
  while (i < len) {
    uint64_t chunk = 0;
    int64_t take = len - i < 8 ? len - i : 8;
    memcpy(&chunk, data + i, (size_t)take);
    acc = gg_hash_combine(acc, gg_hash_i64((int64_t)chunk, 0));
    i += 8;
  }
  acc = gg_hash_combine(acc, gg_hash_i64(len, 0));
  return acc;
}

// ---------------------------------------------------------------------------
// Block frame codec (cdbappendonlystorageformat.c analog)
//
// Frame layout (little endian):
//   u32 magic 0x47474231 ("GGB1")  u32 nrows  u8 compression  u8 encoding
//   u16 reserved  u64 raw_len  u64 comp_len  u32 crc32(payload)
// followed by comp_len payload bytes. compression: 0=none 1=zlib. encoding:
// 0=plain. (zstd frames are produced on the Python side; the native path
// covers the zlib fast path for bulk ingest.)
// ---------------------------------------------------------------------------

static const uint32_t GG_BLOCK_MAGIC = 0x47474231u;
static const int64_t GG_HDR_LEN = 4 + 4 + 1 + 1 + 2 + 8 + 8 + 4;

int64_t gg_block_header_len(void) { return GG_HDR_LEN; }

// Encode src[0..raw_len) into dst (capacity dstcap). Returns total frame
// bytes written, or -1 on error / insufficient capacity.
int64_t gg_block_encode(const uint8_t* src, int64_t raw_len, uint32_t nrows,
                        int32_t compression, int32_t level,
                        uint8_t* dst, int64_t dstcap) {
  uint8_t* payload = dst + GG_HDR_LEN;
  int64_t comp_len;
  if (compression == 1) {
    uLongf out_len = (uLongf)(dstcap - GG_HDR_LEN);
    int zrc = compress2(payload, &out_len, src, (uLong)raw_len, level);
    comp_len = (zrc == Z_OK) ? (int64_t)out_len : raw_len;
    if (zrc != Z_OK || comp_len >= raw_len) {  // incompressible or no room: store raw
      compression = 0;
      if (dstcap - GG_HDR_LEN < raw_len) return -1;
      memcpy(payload, src, (size_t)raw_len);
      comp_len = raw_len;
    }
  } else {
    if (dstcap - GG_HDR_LEN < raw_len) return -1;
    memcpy(payload, src, (size_t)raw_len);
    comp_len = raw_len;
  }
  uint32_t crc = (uint32_t)crc32(0L, payload, (uInt)comp_len);
  uint8_t* p = dst;
  memcpy(p, &GG_BLOCK_MAGIC, 4); p += 4;
  memcpy(p, &nrows, 4); p += 4;
  *p++ = (uint8_t)compression;
  *p++ = 0;  // encoding = plain
  uint16_t rsv = 0; memcpy(p, &rsv, 2); p += 2;
  memcpy(p, &raw_len, 8); p += 8;
  memcpy(p, &comp_len, 8); p += 8;
  memcpy(p, &crc, 4);
  return GG_HDR_LEN + comp_len;
}

// Decode one frame at src into dst (capacity dstcap, must be >= raw_len).
// Returns raw_len, or -1 bad magic, -2 checksum mismatch, -3 error.
int64_t gg_block_decode(const uint8_t* src, int64_t srclen, uint8_t* dst,
                        int64_t dstcap, uint32_t* nrows_out) {
  if (srclen < GG_HDR_LEN) return -1;
  uint32_t magic; memcpy(&magic, src, 4);
  if (magic != GG_BLOCK_MAGIC) return -1;
  uint32_t nrows; memcpy(&nrows, src + 4, 4);
  uint8_t compression = src[8];
  int64_t raw_len, comp_len;
  memcpy(&raw_len, src + 12, 8);
  memcpy(&comp_len, src + 20, 8);
  if (srclen < GG_HDR_LEN + comp_len || dstcap < raw_len) return -3;
  const uint8_t* payload = src + GG_HDR_LEN;
  uint32_t crc = (uint32_t)crc32(0L, payload, (uInt)comp_len);
  uint32_t want; memcpy(&want, src + 28, 4);
  if (crc != want) return -2;
  if (compression == 1) {
    uLongf out_len = (uLongf)dstcap;
    if (uncompress(dst, &out_len, payload, (uLong)comp_len) != Z_OK) return -3;
    if ((int64_t)out_len != raw_len) return -3;
  } else {
    memcpy(dst, payload, (size_t)raw_len);
  }
  if (nrows_out) *nrows_out = nrows;
  return raw_len;
}

uint32_t gg_crc32(const uint8_t* data, int64_t len) {
  return (uint32_t)crc32(0L, data, (uInt)len);
}

}  // extern "C"
