// ggcodec — native host-side codec for greengage_tpu.
//
// Role parity with the reference's native storage/hash path:
//   - distribution hashing          ≙ src/backend/cdb/cdbhash.c
//   - block checksum + frame codec  ≙ src/backend/cdb/cdbappendonlystorageformat.c
//
// The hash spec here MUST stay bit-identical to greengage_tpu/ops/hashing.py
// (the JAX device implementation): murmur3 fmix32 finalizer over the 32-bit
// halves of each 64-bit value, FNV-style combine across columns, placement =
// hash % numsegments. All arithmetic is wrapping uint32.
//
// Build: make -C native  (produces libggcodec.so, loaded via ctypes)

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// Hashing (cdbhash.c analog)
// ---------------------------------------------------------------------------

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

static const uint32_t GG_HASH_INIT = 0x9e3779b9u;
static const uint32_t GG_COMBINE_MUL = 0x01000193u;  // FNV prime

uint32_t gg_hash_i64(int64_t v, uint32_t seed) {
  uint32_t lo = (uint32_t)((uint64_t)v & 0xffffffffu);
  uint32_t hi = (uint32_t)(((uint64_t)v >> 32) & 0xffffffffu);
  uint32_t h = seed ^ GG_HASH_INIT;
  h = fmix32(h ^ lo);
  h = fmix32(h ^ hi);
  return h;
}

uint32_t gg_hash_combine(uint32_t acc, uint32_t h) {
  return fmix32(acc * GG_COMBINE_MUL ^ h);
}

// Batch: hash one int64 column into out (uint32), with seed.
void gg_hash_i64_batch(const int64_t* vals, int64_t n, uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = gg_hash_i64(vals[i], seed);
}

// Batch combine: acc[i] = combine(acc[i], h[i])
void gg_hash_combine_batch(uint32_t* acc, const uint32_t* h, int64_t n) {
  for (int64_t i = 0; i < n; i++) acc[i] = gg_hash_combine(acc[i], h[i]);
}

// Hash a byte string by folding 8-byte little-endian chunks (zero padded)
// through hash_i64 + combine. Used for TEXT placement hashes.
uint32_t gg_hash_bytes(const uint8_t* data, int64_t len, uint32_t seed) {
  uint32_t acc = seed ^ GG_HASH_INIT;
  int64_t i = 0;
  while (i < len) {
    uint64_t chunk = 0;
    int64_t take = len - i < 8 ? len - i : 8;
    memcpy(&chunk, data + i, (size_t)take);
    acc = gg_hash_combine(acc, gg_hash_i64((int64_t)chunk, 0));
    i += 8;
  }
  acc = gg_hash_combine(acc, gg_hash_i64(len, 0));
  return acc;
}

// ---------------------------------------------------------------------------
// Block frame codec (cdbappendonlystorageformat.c analog)
//
// Frame layout (little endian):
//   u32 magic 0x47474232 ("GGB2")  u32 nrows  u8 compression  u8 encoding
//   u16 reserved  u64 raw_len  u64 comp_len  u32 crc32(header[0:28] || payload)
// followed by comp_len payload bytes. compression: 0=none 1=zlib. encoding:
// 0=plain. (zstd frames are produced on the Python side; the native path
// covers the zlib fast path for bulk ingest.)
//
// The CRC covers the 28 header bytes BEFORE the crc field as well as the
// payload, so a flipped nrows/raw_len/comp_len/compression byte is caught
// at decode like payload damage (the reference checksums its AO block
// headers separately for the same reason). Must stay bit-identical to the
// numpy fallback in greengage_tpu/storage/native.py.
// ---------------------------------------------------------------------------

static const uint32_t GG_BLOCK_MAGIC = 0x47474232u;
static const int64_t GG_HDR_LEN = 4 + 4 + 1 + 1 + 2 + 8 + 8 + 4;

int64_t gg_block_header_len(void) { return GG_HDR_LEN; }

// Encode src[0..raw_len) into dst (capacity dstcap). Returns total frame
// bytes written, or -1 on error / insufficient capacity.
int64_t gg_block_encode(const uint8_t* src, int64_t raw_len, uint32_t nrows,
                        int32_t compression, int32_t level,
                        uint8_t* dst, int64_t dstcap) {
  uint8_t* payload = dst + GG_HDR_LEN;
  int64_t comp_len;
  if (compression == 1) {
    uLongf out_len = (uLongf)(dstcap - GG_HDR_LEN);
    int zrc = compress2(payload, &out_len, src, (uLong)raw_len, level);
    comp_len = (zrc == Z_OK) ? (int64_t)out_len : raw_len;
    if (zrc != Z_OK || comp_len >= raw_len) {  // incompressible or no room: store raw
      compression = 0;
      if (dstcap - GG_HDR_LEN < raw_len) return -1;
      memcpy(payload, src, (size_t)raw_len);
      comp_len = raw_len;
    }
  } else {
    if (dstcap - GG_HDR_LEN < raw_len) return -1;
    memcpy(payload, src, (size_t)raw_len);
    comp_len = raw_len;
  }
  uint8_t* p = dst;
  memcpy(p, &GG_BLOCK_MAGIC, 4); p += 4;
  memcpy(p, &nrows, 4); p += 4;
  *p++ = (uint8_t)compression;
  *p++ = 0;  // encoding = plain
  uint16_t rsv = 0; memcpy(p, &rsv, 2); p += 2;
  memcpy(p, &raw_len, 8); p += 8;
  memcpy(p, &comp_len, 8); p += 8;
  uint32_t crc = (uint32_t)crc32(0L, dst, (uInt)(GG_HDR_LEN - 4));
  crc = (uint32_t)crc32(crc, payload, (uInt)comp_len);
  memcpy(p, &crc, 4);
  return GG_HDR_LEN + comp_len;
}

// Decode one frame at src into dst (capacity dstcap, must be >= raw_len).
// Returns raw_len, or -1 bad magic, -2 checksum mismatch, -3 error.
int64_t gg_block_decode(const uint8_t* src, int64_t srclen, uint8_t* dst,
                        int64_t dstcap, uint32_t* nrows_out) {
  if (srclen < GG_HDR_LEN) return -1;
  uint32_t magic; memcpy(&magic, src, 4);
  if (magic != GG_BLOCK_MAGIC) return -1;
  uint32_t nrows; memcpy(&nrows, src + 4, 4);
  uint8_t compression = src[8];
  int64_t raw_len, comp_len;
  memcpy(&raw_len, src + 12, 8);
  memcpy(&comp_len, src + 20, 8);
  if (raw_len < 0 || comp_len < 0) return -3;
  if (srclen < GG_HDR_LEN + comp_len || dstcap < raw_len) return -3;
  const uint8_t* payload = src + GG_HDR_LEN;
  uint32_t crc = (uint32_t)crc32(0L, src, (uInt)(GG_HDR_LEN - 4));
  crc = (uint32_t)crc32(crc, payload, (uInt)comp_len);
  uint32_t want; memcpy(&want, src + 28, 4);
  if (crc != want) return -2;
  if (compression == 1) {
    uLongf out_len = (uLongf)dstcap;
    if (uncompress(dst, &out_len, payload, (uLong)comp_len) != Z_OK) return -3;
    if ((int64_t)out_len != raw_len) return -3;
  } else {
    if (raw_len != comp_len) return -3;  // stored-raw frames are 1:1
    memcpy(dst, payload, (size_t)raw_len);
  }
  if (nrows_out) *nrows_out = nrows;
  return raw_len;
}

uint32_t gg_crc32(const uint8_t* data, int64_t len) {
  return (uint32_t)crc32(0L, data, (uInt)len);
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// CSV ingest fast path (the reference's fstream/gpfdist parsing role,
// src/backend/utils/misc/fstream, src/bin/gpfdist). Two-phase interface:
// index fields once, then parse columns natively by type. Quoted fields are
// detected and reported so the caller can fall back to a full CSV reader.
// ---------------------------------------------------------------------------

// Index delimiter-separated fields. Returns number of fields written, or
// -1 if capacity exhausted, -2 if a double-quote was seen (caller falls
// back to the quoting-aware reader). Rows are separated by '\n' (a
// trailing '\r' is stripped); field k of row r is entry r*ncols+k.
int64_t gg_csv_index(const uint8_t* buf, int64_t len, uint8_t delim,
                     int64_t cap, int64_t* starts, int32_t* lens) {
  int64_t nf = 0;
  int64_t field_start = 0;
  for (int64_t i = 0; i <= len; i++) {
    uint8_t c = (i == len) ? '\n' : buf[i];
    if (c == '"') return -2;
    if (c == delim || c == '\n') {
      if (i == len && field_start == i &&
          (len == 0 || buf[len - 1] == '\n')) break;  // file ended with newline
      if (nf >= cap) return -1;
      int64_t flen = i - field_start;
      if (c == '\n' && flen > 0 && buf[i - 1] == '\r') flen--;
      starts[nf] = field_start;
      lens[nf] = (int32_t)flen;
      nf++;
      field_start = i + 1;
    }
  }
  return nf;
}

// Parse int64 fields (optionally scaled decimals: scale=2 turns "12.3" into
// 1230). Writes valid=0 for empty fields. Returns -(row+1) on a bad field.
int64_t gg_parse_i64(const uint8_t* buf, const int64_t* starts,
                     const int32_t* lens, int64_t n, int64_t stride,
                     int64_t offset, int32_t scale, int64_t* out,
                     uint8_t* valid) {
  for (int64_t r = 0; r < n; r++) {
    int64_t idx = r * stride + offset;
    const uint8_t* p = buf + starts[idx];
    int32_t l = lens[idx];
    if (l == 0) { out[r] = 0; valid[r] = 0; continue; }
    valid[r] = 1;
    int64_t i = 0, sign = 1, v = 0;
    while (i < l && p[i] == ' ') i++;                  // leading spaces
    while (l > i && p[l - 1] == ' ') l--;              // trailing spaces
    if (i >= l) { out[r] = 0; valid[r] = 0; continue; } // all-space = NULL
    if (p[i] == '-') { sign = -1; i++; }
    else if (p[i] == '+') i++;
    int32_t frac_seen = -1;
    int32_t frac_digits = 0;
    int32_t ndigits = 0;
    for (; i < l; i++) {
      uint8_t c = p[i];
      if (c == '.') {
        if (frac_seen >= 0) return -(r + 1);
        frac_seen = 0;
        continue;
      }
      if (c < '0' || c > '9') return -(r + 1);
      ndigits++;
      if (frac_seen >= 0) {
        if (frac_digits < scale) { v = v * 10 + (c - '0'); frac_digits++; }
        else if (frac_digits == scale) {
          // round half away from zero on the first extra digit
          if (c >= '5') v += 1;
          frac_digits++;
        }
      } else {
        v = v * 10 + (c - '0');
      }
    }
    if (ndigits == 0) return -(r + 1);
    while (frac_digits < scale) { v *= 10; frac_digits++; }
    out[r] = sign * v;
  }
  return 0;
}

// Parse float64 fields. Empty -> NULL.
int64_t gg_parse_f64(const uint8_t* buf, const int64_t* starts,
                     const int32_t* lens, int64_t n, int64_t stride,
                     int64_t offset, double* out, uint8_t* valid) {
  char tmp[64];
  for (int64_t r = 0; r < n; r++) {
    int64_t idx = r * stride + offset;
    int32_t l = lens[idx];
    if (l == 0) { out[r] = 0; valid[r] = 0; continue; }
    if (l >= (int32_t)sizeof(tmp)) return -(r + 1);
    memcpy(tmp, buf + starts[idx], l);
    tmp[l] = 0;
    char* end = nullptr;
    out[r] = strtod(tmp, &end);
    if (end != tmp + l) return -(r + 1);
    valid[r] = 1;
  }
  return 0;
}

// Parse ISO dates (YYYY-MM-DD) into days since 1970-01-01. Empty -> NULL.
static int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
  y -= m <= 2;
  int64_t era = (y >= 0 ? y : y - 399) / 400;
  int64_t yoe = y - era * 400;
  int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

int64_t gg_parse_date(const uint8_t* buf, const int64_t* starts,
                      const int32_t* lens, int64_t n, int64_t stride,
                      int64_t offset, int32_t* out, uint8_t* valid) {
  for (int64_t r = 0; r < n; r++) {
    int64_t idx = r * stride + offset;
    const uint8_t* p = buf + starts[idx];
    int32_t l = lens[idx];
    if (l == 0) { out[r] = 0; valid[r] = 0; continue; }
    if (l != 10 || p[4] != '-' || p[7] != '-') return -(r + 1);
    int64_t y = 0, m = 0, d = 0;
    for (int i = 0; i < 4; i++) { if (p[i] < '0' || p[i] > '9') return -(r+1); y = y*10 + (p[i]-'0'); }
    for (int i = 5; i < 7; i++) { if (p[i] < '0' || p[i] > '9') return -(r+1); m = m*10 + (p[i]-'0'); }
    for (int i = 8; i < 10; i++) { if (p[i] < '0' || p[i] > '9') return -(r+1); d = d*10 + (p[i]-'0'); }
    if (m < 1 || m > 12 || d < 1) return -(r + 1);
    static const int dim[12] = {31,28,31,30,31,30,31,31,30,31,30,31};
    int64_t maxd = dim[m - 1];
    if (m == 2 && (y % 4 == 0 && (y % 100 != 0 || y % 400 == 0))) maxd = 29;
    if (d > maxd) return -(r + 1);
    out[r] = (int32_t)days_from_civil(y, m, d);
    valid[r] = 1;
  }
  return 0;
}

}  // extern "C"
