"""Benchmark: TPC-H Q1 pricing summary on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value       = rows/sec/chip through the full engine (SQL -> plan -> jitted
              SPMD program -> gather), steady state (plan + staging cached),
              best of N runs.
vs_baseline = speedup over a CPU columnar baseline executing the same Q1
              aggregation with numpy/pandas on this host (the reference
              publishes no absolute numbers — BASELINE.md — so the recorded
              baseline is the measured CPU path, standing in for a
              CPU-segment executor on identical data).

Env: GGTPU_BENCH_SF (default 0.5), GGTPU_BENCH_RUNS (default 5).
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SF = float(os.environ.get("GGTPU_BENCH_SF", "1"))
RUNS = int(os.environ.get("GGTPU_BENCH_RUNS", "11"))  # best-of; per-call
# latency through tunneled device transports jitters, so take more samples

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""


def cpu_baseline(data: dict) -> tuple[float, list]:
    """Columnar numpy execution of Q1 (vectorized CPU segment stand-in)."""
    li = data["lineitem"]
    cutoff = (np.datetime64("1998-12-01") - np.timedelta64(90, "D")
              - np.datetime64("1970-01-01")).astype(np.int32)
    qty = li["l_quantity"]
    price = li["l_extendedprice"]
    disc = li["l_discount"]
    tax = li["l_tax"]
    ship = li["l_shipdate"]
    rf = np.asarray(li["l_returnflag"])
    ls = np.asarray(li["l_linestatus"])

    def run():
        m = ship <= cutoff
        # group id over the 3x2 flag/status domain
        rf_c = np.searchsorted(np.array(["A", "N", "R"]), rf)
        ls_c = np.searchsorted(np.array(["F", "O"]), ls)
        gid = np.where(m, rf_c * 2 + ls_c, 6)
        disc_price = price * (100 - disc)            # scaled 1e4
        charge = disc_price * (100 + tax)            # scaled 1e6
        out = []
        for g in range(6):
            mask = gid == g
            cnt = int(mask.sum())
            out.append((
                np.sum(qty, where=mask), np.sum(price, where=mask),
                np.sum(disc_price, where=mask), np.sum(charge, where=mask),
                np.sum(qty, where=mask) / max(cnt, 1),
                np.sum(price, where=mask) / max(cnt, 1),
                np.sum(disc, where=mask) / max(cnt, 1), cnt,
            ))
        return out

    run()  # warm cache
    best = float("inf")
    rows = None
    for _ in range(3):
        t0 = time.monotonic()
        rows = run()
        best = min(best, time.monotonic() - t0)
    return best, rows


def main():
    import jax

    import greengage_tpu
    from greengage_tpu.utils import tpch

    t_setup = time.monotonic()
    data = tpch.generate(SF)
    n_rows = len(data["lineitem"]["l_orderkey"])

    dev = jax.devices()[0]
    db = greengage_tpu.connect(
        path=tempfile.mkdtemp(prefix="ggtpu_bench_"), numsegments=1)
    db.sql(tpch.DDL)
    db.load_table("lineitem", data["lineitem"])
    setup_s = time.monotonic() - t_setup

    # device path: first run compiles + stages, then steady state
    t0 = time.monotonic()
    db.sql(Q1)
    compile_s = time.monotonic() - t0
    best = float("inf")
    for _ in range(RUNS):
        t0 = time.monotonic()
        r = db.sql(Q1)
        best = min(best, time.monotonic() - t0)
    assert len(r) == 6, f"Q1 expected 6 groups, got {len(r)}"

    cpu_s, _ = cpu_baseline(data)

    value = n_rows / best
    baseline = n_rows / cpu_s
    result = {
        "metric": "tpch_q1_rows_per_sec_per_chip",
        "value": round(value),
        "unit": "rows/s",
        "vs_baseline": round(value / baseline, 3),
    }
    print(json.dumps(result))
    print(f"# sf={SF} rows={n_rows} device={dev.device_kind} "
          f"best={best*1e3:.1f}ms cpu_numpy={cpu_s*1e3:.1f}ms "
          f"compile={compile_s:.1f}s setup={setup_s:.1f}s", file=sys.stderr)


if __name__ == "__main__":
    main()
