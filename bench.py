"""Benchmark: TPC-H Q1 / Q3 / Q5 through the full engine on the real chip.

Prints ONE JSON line (the Q1 headline, comparable across rounds):
    {"metric": "tpch_q1_rows_per_sec_per_chip", "value": N, "unit": "rows/s",
     "vs_baseline": N}
and a per-query detail block on stderr (Q3/Q5 rows/s/chip + their CPU
baselines), since the driver records exactly one line.

value       = lineitem rows/sec/chip through SQL -> plan -> jitted SPMD
              program -> gather, steady state (plan + staging cached),
              best of N runs.
vs_baseline = speedup over a CPU columnar baseline executing the same query
              with numpy/pandas on this host (the reference publishes no
              absolute numbers — BASELINE.md — so the measured CPU path
              stands in for a CPU-segment executor on identical data).

The Q1 headline line is printed (and flushed) IMMEDIATELY after Q1
completes, before any other query runs — a later query blowing the driver's
time budget must never discard a finished Q1 measurement. Q3/Q5 are
budget-gated: each starts only while elapsed wall time is under
GGTPU_BENCH_BUDGET_S (they compile for minutes on a cold XLA cache).

Env: GGTPU_BENCH_SF (default 10), GGTPU_BENCH_RUNS (default 3),
     GGTPU_BENCH_DIR (default /tmp/ggtpu_bench_sf<SF>; reused when already
     loaded at the right scale), GGTPU_BENCH_QUERIES (default q1,q3,q5),
     GGTPU_BENCH_BUDGET_S (default 1200; start no new query past this).
"""

import json
import os
import sys
import time

import numpy as np

T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SF = float(os.environ.get("GGTPU_BENCH_SF", "10"))
RUNS = int(os.environ.get("GGTPU_BENCH_RUNS", "3"))  # best-of; per-call
QUERIES = os.environ.get("GGTPU_BENCH_QUERIES", "q1,q3,q5").split(",")
BUDGET_S = float(os.environ.get("GGTPU_BENCH_BUDGET_S", "1200"))

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q3 = """
select l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

Q5 = """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1994-01-01' + interval '1' year
group by n_name
order by revenue desc
"""


def _cut(day: str) -> int:
    return (np.datetime64(day) - np.datetime64("1970-01-01")).astype(np.int64)


def baseline_q1(data) -> float:
    li = data["lineitem"]
    cutoff = _cut("1998-12-01") - 90
    qty, price = li["l_quantity"], li["l_extendedprice"]
    disc, tax, ship = li["l_discount"], li["l_tax"], li["l_shipdate"]
    rf, ls = li["l_returnflag"].codes, li["l_linestatus"].codes

    def run():
        m = ship <= cutoff
        gid = np.where(m, rf * 2 + ls, 6)
        disc_price = price * (100 - disc)
        charge = disc_price * (100 + tax)
        out = []
        for g in range(6):
            mask = gid == g
            cnt = int(mask.sum())
            # all 8 Q1 aggregates, matching what the engine computes
            out.append((np.sum(qty, where=mask), np.sum(price, where=mask),
                        np.sum(disc_price, where=mask), np.sum(charge, where=mask),
                        np.sum(qty, where=mask) / max(cnt, 1),
                        np.sum(price, where=mask) / max(cnt, 1),
                        np.sum(disc, where=mask) / max(cnt, 1), cnt))
        return out

    run()
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def baseline_q3(data) -> float:
    import pandas as pd

    li, o, c = data["lineitem"], data["orders"], data["customer"]
    cut = _cut("1995-03-15")

    def run():
        lf = pd.DataFrame({
            "l_orderkey": li["l_orderkey"], "rev": li["l_extendedprice"] * (100 - li["l_discount"]),
        })[li["l_shipdate"] > cut]
        of = pd.DataFrame({
            "o_orderkey": o["o_orderkey"], "o_custkey": o["o_custkey"],
            "o_orderdate": o["o_orderdate"],
        })[o["o_orderdate"] < cut]
        cf = pd.DataFrame({"c_custkey": c["c_custkey"]})[c["c_mktsegment"].codes ==
                                                         c["c_mktsegment"].vocab.index("BUILDING")]
        j = lf.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        j = j.merge(cf, left_on="o_custkey", right_on="c_custkey")
        g = j.groupby(["l_orderkey", "o_orderdate"], as_index=False)["rev"].sum()
        return g.nlargest(10, "rev")

    run()   # warm caches: compare steady CPU vs steady device
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def baseline_q5(data) -> float:
    import pandas as pd

    li, o, c = data["lineitem"], data["orders"], data["customer"]
    s, n, r = data["supplier"], data["nation"], data["region"]
    lo, hi = _cut("1994-01-01"), _cut("1995-01-01")

    def run():
        asia = [i for i, (nm, rk) in enumerate(
            zip(n["n_name"], n["n_regionkey"]))
            if r["r_name"][rk] == "ASIA"]
        sf = pd.DataFrame({"s_suppkey": s["s_suppkey"], "s_nationkey": s["s_nationkey"]})
        sf = sf[sf.s_nationkey.isin(asia)]
        cf = pd.DataFrame({"c_custkey": c["c_custkey"], "c_nationkey": c["c_nationkey"]})
        of = pd.DataFrame({
            "o_orderkey": o["o_orderkey"], "o_custkey": o["o_custkey"],
        })[(o["o_orderdate"] >= lo) & (o["o_orderdate"] < hi)]
        lf = pd.DataFrame({
            "l_orderkey": li["l_orderkey"], "l_suppkey": li["l_suppkey"],
            "rev": li["l_extendedprice"] * (100 - li["l_discount"]),
        })
        j = lf.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        j = j.merge(sf, left_on="l_suppkey", right_on="s_suppkey")
        j = j.merge(cf, left_on="o_custkey", right_on="c_custkey")
        j = j[j.c_nationkey == j.s_nationkey]
        return j.groupby("s_nationkey")["rev"].sum()

    run()   # warm caches: compare steady CPU vs steady device
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def ensure_loaded(db, data, counts_want):
    """Reuse the bench dir only when it holds EXACTLY the expected rows; a
    partial/mismatched dir (killed prior run, different SF) is wiped and
    reloaded — load_table is append-only, so loading on top would silently
    inflate every number."""
    have = {}
    for t in counts_want:
        try:
            have[t] = sum(db.store.segment_rowcounts(t))
        except Exception:
            have[t] = -1
    if have == counts_want:
        return db
    from greengage_tpu.utils import tpch

    if any(v > 0 for v in have.values()):
        import shutil

        import greengage_tpu

        path = db.path
        log(f"bench dir rowcounts mismatch {have} — wiping and reloading")
        db.close()
        shutil.rmtree(path, ignore_errors=True)
        db = greengage_tpu.connect(path=path, numsegments=1)
    db.sql(tpch.DDL)
    for name, cols in data.items():
        db.load_table(name, cols)
    db._loaded_now = True
    return db


def timed(db, sql, runs):
    t0 = time.monotonic()
    r = db.sql(sql)
    first = time.monotonic() - t0
    log(f"first run {first:.1f}s (tiers={r.stats['tiers_used']})")
    best = float("inf")
    for i in range(runs):
        t0 = time.monotonic()
        r = db.sql(sql)
        best = min(best, time.monotonic() - t0)
    log(f"steady best {best * 1e3:.1f}ms over {runs} runs")
    return best, first, r


def main():
    import jax

    import greengage_tpu
    from greengage_tpu.utils import tpch

    t_setup = time.monotonic()
    log(f"generating SF{SF:g}")
    data = tpch.generate(SF)
    n_rows = len(data["lineitem"]["l_orderkey"])
    counts = {t: len(next(iter(v.values()))) for t, v in data.items()}

    dev = jax.devices()[0]
    bench_dir = os.environ.get(
        "GGTPU_BENCH_DIR", f"/tmp/ggtpu_bench_sf{SF:g}_{len(jax.devices())}d")
    db = greengage_tpu.connect(path=bench_dir, numsegments=1)
    log("loading")
    db = ensure_loaded(db, data, counts)
    loaded = getattr(db, "_loaded_now", False)
    if loaded or db.catalog.get("lineitem").stats is None:
        log("analyzing")
        db.sql("analyze")   # NDV-accurate capacities avoid recompile tiers
    setup_s = time.monotonic() - t_setup
    log(f"setup done ({setup_s:.0f}s, loaded_now={loaded})")

    detail = {"sf": SF, "rows": n_rows, "device": str(dev.device_kind),
              "loaded_now": loaded, "setup_s": round(setup_s, 1)}
    # the chip's real HBM is the limit for this known workload (the default
    # admission guard is conservative for ad-hoc queries)
    db.sql("set vmem_protect_limit_mb = 15000")
    # Q1 streams 7 lineitem columns: 4×int64 + 3×int32 codes/dates = 44 B/row
    q1_bytes_per_row = 44
    headline_emitted = False

    def emit_headline(line):
        nonlocal headline_emitted
        if headline_emitted:
            return
        print(json.dumps(line), flush=True)
        headline_emitted = True

    for qname, sql, nbase in (("q1", Q1, "baseline_q1"),
                              ("q3", Q3, "baseline_q3"),
                              ("q5", Q5, "baseline_q5")):
        if qname not in QUERIES:
            continue
        elapsed = time.monotonic() - T0
        if qname != "q1" and elapsed > BUDGET_S:
            detail[qname] = {"skipped": f"budget: elapsed {elapsed:.0f}s > {BUDGET_S:.0f}s"}
            log(f"=== {qname} skipped (budget) ===")
            continue
        try:
            log(f"=== {qname} ===")
            # release the previous query's staged device arrays: at SF10
            # the three queries' column sets together exceed HBM
            db.executor._stage_cache.clear()
            best, first, r = timed(db, sql, RUNS)
            cpu_s = globals()[nbase](data)
            value = n_rows / best
            base = n_rows / cpu_s
            detail[qname] = {
                "rows_per_sec_per_chip": round(value),
                "best_ms": round(best * 1e3, 1),
                "first_run_s": round(first, 1),
                "cpu_baseline_ms": round(cpu_s * 1e3, 1),
                "vs_baseline": round(value / base, 3),
                "rows_out": len(r),
            }
            if qname == "q1":
                assert len(r) == 6, f"Q1 expected 6 groups, got {len(r)}"
                detail[qname]["gb_per_sec"] = round(
                    n_rows * q1_bytes_per_row / best / 1e9, 1)
                # emit the headline NOW: a later query timing out or dying
                # must not cost the round its one recorded number
                emit_headline({
                    "metric": "tpch_q1_rows_per_sec_per_chip",
                    "value": round(value),
                    "unit": "rows/s",
                    "vs_baseline": round(value / base, 3),
                })
        except Exception as e:  # one failing query must not kill the line
            detail[qname] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps({qname: detail.get(qname)}), file=sys.stderr, flush=True)

    print(json.dumps(detail, indent=None), file=sys.stderr, flush=True)
    if not headline_emitted:
        emit_headline({
            "metric": "tpch_q1_rows_per_sec_per_chip", "value": 0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "error": detail.get("q1", {}).get("error", "q1 not run")})


if __name__ == "__main__":
    main()
