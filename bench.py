"""Benchmark: TPC-H Q1 / Q3 / Q5 through the full engine on the real chip.

Prints ONE JSON line (the Q1 headline, comparable across rounds):
    {"metric": "tpch_q1_rows_per_sec_per_chip", "value": N, "unit": "rows/s",
     "vs_baseline": N}
and a per-query detail block on stderr (Q3/Q5 rows/s/chip + their CPU
baselines), since the driver records exactly one line.

Structure (the round-3 lesson: two consecutive rounds lost their number to
a wedged TPU backend and a driver timeout):

  parent  -- this process; NEVER imports jax (a wedged axon backend hangs
             jax.devices() indefinitely inside plugin bootstrap). It
             remediates stale chip-holding processes, probes the backend in
             a deadlined subprocess with retry/backoff (the wedge clears
             when stale clients die), runs the measurement child under a
             deadline, and prints the headline the MOMENT the child records
             it. If everything fails it still prints a parseable headline
             with value 0 and the error.
  --probe -- child: import jax, list devices, print the device kind.
  --run   -- child: generate/load/measure; writes the headline atomically
             to GGTPU_HEADLINE_FILE as soon as Q1 completes, then keeps
             going with Q3/Q5 detail (stderr).

Attempt order: SF10 first (the round target); if its child dies or the
deadline nears with no headline, a short SF1 attempt still lands a real
measured number (r1 proved SF1 end-to-end in ~40s).

Env: GGTPU_BENCH_SF (default 10), GGTPU_BENCH_RUNS (default 3),
     GGTPU_BENCH_DIR (default /tmp/ggtpu_bench_sf<SF>; reused when already
     loaded at the right scale), GGTPU_BENCH_QUERIES (default q1,q3,q5),
     GGTPU_BENCH_DEADLINE_S (default 1650: the driver's observed budget is
     ~1800s and rc=124 discards nothing only because the parent prints the
     headline incrementally), GGTPU_BENCH_PROBE_S (probe window, 480),
     GGTPU_BENCH_FALLBACK_SF (default 1; 0 disables the fallback attempt).
"""

import json
import os
import signal
import subprocess
import sys
import time

T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _retry_mod():
    """runtime/retry.py loaded BY FILE PATH: the shared Deadline/backoff
    policy without importing the greengage_tpu package (its __init__
    imports jax, and the parent must never touch the chips the children
    need). The module is stdlib-only by contract."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "greengage_tpu", "runtime", "retry.py")
    spec = importlib.util.spec_from_file_location("_ggtpu_retry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

SF = float(os.environ.get("GGTPU_BENCH_SF", "10"))
RUNS = int(os.environ.get("GGTPU_BENCH_RUNS", "3"))  # best-of; per-call
QUERIES = os.environ.get("GGTPU_BENCH_QUERIES", "q1,q3,q5").split(",")
DEADLINE_S = float(os.environ.get("GGTPU_BENCH_DEADLINE_S", "1650"))
PROBE_S = float(os.environ.get("GGTPU_BENCH_PROBE_S", "480"))
FALLBACK_SF = float(os.environ.get("GGTPU_BENCH_FALLBACK_SF", "1"))
HBM_PEAK_GBS = 819.0   # v5e HBM bandwidth roofline
BASELINE_V = 1         # bump when any baseline_qN implementation changes

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q3 = """
select l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

Q5 = """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1994-01-01' + interval '1' year
group by n_name
order by revenue desc
"""


# ======================================================================
# parent: orchestration without ever touching a jax backend
# ======================================================================

def _kill_stale_clients() -> int:
    """Kill leftover bench children from a previous (timed-out) round: the
    driver's `timeout` kills only the parent, orphaning children that still
    hold the chip client — exactly the state that wedges the next backend
    init. Identified by the GGTPU_BENCH_CHILD env marker or a bench.py
    cmdline; never this process or its ancestors. Returns the kill count
    (recorded in the preflight's wedge report)."""
    me = os.getpid()
    killed = 0
    ancestors = set()
    pid = me
    for _ in range(16):
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().split(") ")[-1].split()[1])   # ppid
        except Exception:
            break
        if pid <= 1:
            break
        ancestors.add(pid)
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        pid = int(d)
        if pid == me or pid in ancestors:
            continue
        try:
            with open(f"/proc/{d}/stat") as f:
                ppid = int(f.read().split(") ")[-1].split()[1])
            if ppid == me:
                continue   # a live child of THIS parent is never stale
            with open(f"/proc/{d}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
            with open(f"/proc/{d}/environ", "rb") as f:
                env = f.read()
        except Exception:
            continue
        stale = b"GGTPU_BENCH_CHILD=1" in env or (
            "bench.py" in cmd and "python" in cmd)
        if stale:
            log(f"remediation: killing stale bench process {pid}: {cmd[:120]}")
            try:
                os.kill(pid, signal.SIGKILL)
                killed += 1
            except Exception:
                pass
    return killed


def _spawn_child(args, timeout_s, headline_file=None, tag="child",
                 capture=None):
    """Run a child with its own process group and a hard deadline; stdout
    is redirected to stderr (the parent owns the real stdout), or to
    ``capture`` so the preflight can classify a wedge from the output.
    Polls the headline file while waiting, caching the LATEST headline
    (the child enriches it with Q3/Q5 once they complete), and prints it
    when the child finishes — the parent's SIGTERM handler flushes the
    cached line, so a driver kill still never discards it.
    -> (rc | None on timeout, headline_printed)."""
    env = dict(os.environ)
    env["GGTPU_BENCH_CHILD"] = "1"
    if headline_file:
        env["GGTPU_HEADLINE_FILE"] = headline_file
    out = open(capture, "wb") if capture else sys.stderr
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + args,
            env=env, stdout=out, stderr=out,
            start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    finally:
        if capture:
            out.close()
    end = time.monotonic() + timeout_s
    rc = None
    while time.monotonic() < end:
        rc = proc.poll()
        if headline_file:
            _note_headline(headline_file)
        if rc is not None:
            break
        time.sleep(2)
    if rc is None:
        log(f"{tag}: deadline ({timeout_s:.0f}s) — killing process group")
        for sig in (signal.SIGTERM, signal.SIGKILL):
            try:
                os.killpg(proc.pid, sig)
            except Exception:
                pass
            try:
                proc.wait(timeout=10)
                break
            except Exception:
                continue
    printed = False
    if headline_file:
        _note_headline(headline_file)
        printed = _flush_headline()
    return rc, printed


_HEADLINE_DONE = False
_PENDING_HEADLINE = None


def _note_headline(path) -> None:
    """Cache the latest recorded headline (the child atomically replaces
    the file as later queries complete)."""
    global _PENDING_HEADLINE
    try:
        with open(path) as f:
            _PENDING_HEADLINE = json.loads(f.read())
    except Exception:
        pass


def _flush_headline() -> bool:
    """Print the cached headline exactly once."""
    global _HEADLINE_DONE
    if _HEADLINE_DONE:
        return True
    if _PENDING_HEADLINE is None:
        return False
    print(json.dumps(_PENDING_HEADLINE), flush=True)
    _HEADLINE_DONE = True
    return True


def _tail_file(path, n=4000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def _classify_wedge(rc, tail: str) -> str:
    """Name the wedge mode from the probe child's output, so BENCH_*.json
    records WHY there is no number instead of a bare 0 (VERDICT r5
    standing order). The three observed modes: init hang (backend plugin
    bootstrap never returns — the r2-r5 state), compile hang (devices
    list but the tiny jit never completes — the r3 state), and a typed
    probe error."""
    if rc is None:
        if "probe:" in tail:
            return ("backend_compile_hang: devices listed but the probe "
                    "computation never completed inside the window")
        return ("backend_init_hang: jax backend init produced no devices "
                "inside the window")
    for line in reversed([ln for ln in tail.splitlines() if ln.strip()]):
        if any(k in line for k in ("Error", "error", "FAILED", "Traceback",
                                   "assert")):
            return f"probe_error rc={rc}: {line.strip()[:200]}"
    return f"probe_exit rc={rc}"


def parent() -> None:
    errors = []
    wedges = []
    stale_killed = _kill_stale_clients()
    # a driver kill (SIGTERM from `timeout`) must still emit whatever
    # headline the child has recorded so far — Q1-only beats nothing
    signal.signal(signal.SIGTERM,
                  lambda *a: (_flush_headline(), os._exit(124)))

    # ---- preflight: deadlined + retried backend init, with the wedge
    # mode CLASSIFIED from captured probe output (VERDICT r5 standing
    # order: record WHY there is no number, never a bare 0). The shared
    # retry policy (runtime/retry.py): a Deadline bounds the whole
    # window, jittered exponential backoff paces the re-probes.
    retry = _retry_mod()
    probe_dl = retry.Deadline(min(PROBE_S, DEADLINE_S * 0.4))
    delays = retry.backoff_delays(base=20.0, cap=60.0, jitter=0.25,
                                  deadline=probe_dl)
    probe_cap = f"/tmp/ggtpu_bench_probe_{os.getpid()}.log"
    probe_ok = False
    attempt = 0
    while not probe_dl.expired:
        attempt += 1
        budget = min(150.0, probe_dl.remaining() + 30)
        log(f"probe attempt {attempt} (timeout {budget:.0f}s)")
        rc, _ = _spawn_child(["--probe"], budget, tag="probe",
                             capture=probe_cap)
        tail = _tail_file(probe_cap)
        if tail.strip():
            log("probe output tail:\n" + tail[-800:])
        if rc == 0:
            probe_ok = True
            break
        errors.append(f"probe#{attempt} rc={rc if rc is not None else 'timeout'}")
        wedges.append(_classify_wedge(rc, tail))
        log(f"wedge classified: {wedges[-1]}")
        stale_killed += _kill_stale_clients()   # a hung probe child is
        sleep = next(delays, None)              # itself a stale client
        if sleep is None or (probe_dl.remaining() or 0) <= sleep:
            break
        log(f"probe failed ({errors[-1]}); backoff {sleep:.0f}s")
        time.sleep(sleep)
    if not probe_ok:
        log("backend never initialized inside the probe window")
        print(json.dumps({
            "metric": "tpch_q1_rows_per_sec_per_chip", "value": 0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "error": "TPU backend unavailable: " + "; ".join(errors[-4:]),
            "wedge": {"reason": wedges[-1] if wedges else "unknown",
                      "probe_attempts": attempt,
                      "stale_clients_killed": stale_killed,
                      "history": wedges[-4:]}}),
            flush=True)
        return

    # ---- measurement: SF target first, small-SF fallback --------------
    headline_file = f"/tmp/ggtpu_bench_headline_{os.getpid()}.json"
    try:   # a recycled PID must never replay a previous round's number
        os.unlink(headline_file)
    except OSError:
        pass
    attempts = [SF] + ([FALLBACK_SF] if FALLBACK_SF and FALLBACK_SF != SF
                       else [])
    # reserve time for the fallback attempt (r1 measured SF1 end-to-end,
    # cold, in ~40s; 240s is compile-cache-cold slack)
    reserve = 240.0 if len(attempts) > 1 else 0.0
    for i, sf in enumerate(attempts):
        remaining = DEADLINE_S - (time.monotonic() - T0)
        budget = remaining - (reserve if i == 0 else 0.0)
        if budget < 60:
            errors.append(f"sf{sf:g}: no time left ({remaining:.0f}s)")
            break
        log(f"run attempt at SF{sf:g} (budget {budget:.0f}s)")
        env_sf = os.environ.get("GGTPU_BENCH_SF")
        os.environ["GGTPU_BENCH_SF"] = str(sf)
        rc, printed = _spawn_child(["--run"], budget,
                                   headline_file=headline_file,
                                   tag=f"run sf{sf:g}")
        if env_sf is None:
            os.environ.pop("GGTPU_BENCH_SF", None)
        else:
            os.environ["GGTPU_BENCH_SF"] = env_sf
        if printed:
            return
        errors.append(f"sf{sf:g} rc={rc if rc is not None else 'timeout'}")
        log(f"run attempt at SF{sf:g} produced no headline ({errors[-1]})")
        _kill_stale_clients()
    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec_per_chip", "value": 0,
        "unit": "rows/s", "vs_baseline": 0.0,
        "error": "; ".join(errors[-6:])}), flush=True)


# ======================================================================
# microbench: CPU-runnable host-data-path metrics (no TPU probe needed)
# ======================================================================

def microbench_staging() -> None:
    """Cold/warm staging-throughput microbench (docs/PERF.md): stages a
    multi-segment, multi-column table through the real executor path and
    reports decoded bytes / staging wall seconds. CPU-only by design — it
    measures the HOST data path (read + CRC/zlib decode + buffer fill +
    transfer), so the bench trajectory records host-path numbers even when
    the TPU probe times out. Prints the standard one-line JSON:

        {"metric": "staging_cold_mb_per_sec", "value": N, "unit": "MB/s",
         "vs_baseline": <vs single-threaded staging>, ...}

    Env: GGTPU_MB_ROWS (default 1000000), GGTPU_MB_COLS (6),
         GGTPU_MB_SEGS (4), GGTPU_MB_RUNS (3)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu
    from greengage_tpu.runtime.logger import counters

    rows = int(os.environ.get("GGTPU_MB_ROWS", "1000000"))
    ncols = int(os.environ.get("GGTPU_MB_COLS", "6"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    runs = int(os.environ.get("GGTPU_MB_RUNS", "3"))
    path = tempfile.mkdtemp(prefix="ggtpu_staging_mb_")
    try:
        db = greengage_tpu.connect(path, numsegments=nseg)
        cols_ddl = ", ".join(f"c{i} bigint" for i in range(ncols))
        db.sql(f"create table t (k int, {cols_ddl}) distributed by (k)")
        rng = np.random.default_rng(7)
        data = {"k": np.arange(rows, dtype=np.int32)}
        for i in range(ncols):
            data[f"c{i}"] = rng.integers(0, 1 << 40, rows, dtype=np.int64)
        t0 = time.monotonic()
        db.load_table("t", data)
        log(f"microbench: loaded {rows} rows x {ncols + 1} cols across "
            f"{nseg} segments in {time.monotonic() - t0:.1f}s")
        q = ("select " + ", ".join(f"sum(c{i})" for i in range(ncols))
             + ", sum(k) from t")
        db.sql(q)   # compile once; measurement runs reuse the program

        def staged_run(clear_blocks: bool) -> tuple[float, dict]:
            db.executor._stage_cache.clear()
            if clear_blocks:
                db.store.blockcache.clear()
            c0 = counters.snapshot()
            r = db.sql(q)
            return r.stats["stage_ms"] / 1e3, counters.since(c0, "scan_")

        # cold: every block read + decoded from disk
        cold_s, cold_io = 1e9, {}
        for _ in range(runs):
            s, io = staged_run(clear_blocks=True)
            if s < cold_s:
                cold_s, cold_io = s, io
        cold_bytes = cold_io.get("scan_bytes_decoded", 0)
        cold_mbs = cold_bytes / max(cold_s, 1e-9) / 1e6
        # warm: stage cache cleared but blocks resident — the block-cache
        # service rate (buffer fill + device put, no disk/decode)
        warm_s, warm_io = 1e9, {}
        for _ in range(runs):
            s, io = staged_run(clear_blocks=False)
            if s < warm_s:
                warm_s, warm_io = s, io
        warm_mbs = cold_bytes / max(warm_s, 1e-9) / 1e6
        # baseline: the same cold staging forced single-threaded — the
        # pre-pipeline serial loop shape
        db.sql("set scan_threads = 1")
        serial_s = 1e9
        for _ in range(runs):
            s, _io = staged_run(clear_blocks=True)
            serial_s = min(serial_s, s)
        db.sql("set scan_threads = 0")
        line = {
            "metric": "staging_cold_mb_per_sec",
            "value": round(cold_mbs, 1),
            "unit": "MB/s",
            "vs_baseline": round(max(serial_s, 1e-9) / max(cold_s, 1e-9), 3),
            "warm_mb_per_sec": round(warm_mbs, 1),
            "cold_stage_ms": round(cold_s * 1e3, 1),
            "warm_stage_ms": round(warm_s * 1e3, 1),
            "serial_stage_ms": round(serial_s * 1e3, 1),
            "bytes_decoded": int(cold_bytes),
            "files_read": cold_io.get("scan_files_read", 0),
            "warm_files_read": warm_io.get("scan_files_read", 0),
            "rows": rows, "segments": nseg,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def microbench_plan_cache() -> None:
    """Repeated-shape statement throughput (ISSUE 5, docs/PERF.md "Plan
    cache"): dashboard-style SELECTs that differ only in literal values.
    Cold = every statement re-plans and recompiles (plan_cache_params off,
    caches cleared per statement — the seed behavior); warm = the
    parameterized plan + executable cache serves every value from ONE
    compiled program. CPU-only by design (XLA compile cost dominates on
    every backend). Prints the standard one-line JSON:

        {"metric": "plan_cache_stmts_per_sec", "value": N, "unit":
         "stmts/s", "vs_baseline": <speedup vs cold-compile-every-time>,
         "recompiles_avoided": ..., ...}

    Env: GGTPU_MB_ROWS (default 200000), GGTPU_MB_SEGS (4),
         GGTPU_MB_WARM (30 statements), GGTPU_MB_COLD (3 statements)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax  # noqa: F401  (platform pinning below)

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu
    from greengage_tpu.runtime.logger import counters

    rows = int(os.environ.get("GGTPU_MB_ROWS", "200000"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    nwarm = int(os.environ.get("GGTPU_MB_WARM", "30"))
    ncold = int(os.environ.get("GGTPU_MB_COLD", "3"))
    path = tempfile.mkdtemp(prefix="ggtpu_plancache_mb_")
    try:
        # the persistent XLA disk cache would hide recompile cost: point it
        # at a throwaway dir so cold statements pay the real compile
        os.environ["GGTPU_XLA_CACHE"] = os.path.join(path, "xla")
        import jax as _j

        _j.config.update("jax_compilation_cache_dir",
                         os.path.join(path, "xla"))
        db = greengage_tpu.connect(path, numsegments=nseg)
        db.sql("create table d (k int, grp int, v double precision) "
               "distributed by (k)")
        rng = np.random.default_rng(11)
        db.load_table("d", {
            "k": np.arange(rows, dtype=np.int32),
            "grp": rng.integers(0, 50, rows, dtype=np.int32),
            "v": rng.random(rows)})

        def q(i: int) -> str:
            return (f"select count(*), sum(v), min(grp) from d "
                    f"where grp >= {i % 40} and v < 0.{51 + i % 37}")

        def clear_all() -> None:
            db._select_cache.clear()
            db.executor._plan_cache.clear()
            _j.clear_caches()   # in-memory jit cache, not just ours

        # cold: the seed behavior — every literal change replans+recompiles
        db.sql("set plan_cache_params = off")
        cold_s = 0.0
        for i in range(ncold):
            clear_all()
            t0 = time.monotonic()
            db.sql(q(i))
            cold_s += time.monotonic() - t0
        cold_per = cold_s / max(ncold, 1)

        # warm: parameterized cache — one compile serves every value
        db.sql("set plan_cache_params = on")
        clear_all()
        db.sql(q(0))   # populate
        c0 = counters.snapshot()
        t0 = time.monotonic()
        for i in range(1, nwarm + 1):
            db.sql(q(i))
        warm_s = time.monotonic() - t0
        delta = counters.since(c0)
        warm_per = warm_s / max(nwarm, 1)
        line = {
            "metric": "plan_cache_stmts_per_sec",
            "value": round(1.0 / max(warm_per, 1e-9), 1),
            "unit": "stmts/s",
            "vs_baseline": round(cold_per / max(warm_per, 1e-9), 2),
            "cold_stmt_ms": round(cold_per * 1e3, 1),
            "warm_stmt_ms": round(warm_per * 1e3, 1),
            "recompiles_avoided": nwarm - delta.get("program_cache_miss", 0),
            "plan_cache_hits": delta.get("plan_cache_hit", 0),
            "program_cache_hits": delta.get("program_cache_hit", 0),
            "params_hoisted": delta.get("params_hoisted", 0),
            "rows": rows, "segments": nseg,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def _batch_serving_measure(db, make_q, concs=(1, 4, 16),
                           per_thread=16) -> dict:
    """Statements/sec with batched serving on vs off at each concurrency
    (shared by the microbench and the TPU bench's detail rider). Warms
    every pow2 width bucket first so the measurement is steady-state
    serving, not bucket compiles."""
    import threading

    from greengage_tpu.runtime.logger import counters
    from greengage_tpu.sql.parser import parse

    maxw = int(db.settings.batch_max_width)
    db.sql("set batch_serving_enabled = off")
    db.sql(make_q(0))   # warm plan cache + width-0 classic program
    stmt = parse(make_q(0))[0]
    planned, consts, outs, ek = db._cached_plan(stmt)
    pv = consts["@params@"]
    w = 1
    while w <= maxw:
        # the member values are irrelevant for warming — the bucket's
        # program is value-generic; repeating one vector is type-exact
        db.executor.run_batch(planned, consts, outs, ek, [pv] * w)
        w *= 2

    def run_conc(conc: int) -> float:
        def worker(tid):
            for j in range(per_thread):
                db.sql(make_q(tid * per_thread + j))
        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(conc)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return conc * per_thread / (time.monotonic() - t0)

    out = {}
    for conc in concs:
        db.sql("set batch_serving_enabled = off")
        off = run_conc(conc)
        db.sql("set batch_serving_enabled = on")
        c0 = counters.snapshot()
        on = run_conc(conc)
        d = counters.since(c0)
        ndisp = max(d.get("batch_dispatch_total", 0), 1)
        out[f"conc{conc}"] = {
            "off_stmts_per_sec": round(off, 1),
            "on_stmts_per_sec": round(on, 1),
            "speedup": round(on / max(off, 1e-9), 2),
            "avg_width": round(d.get("batch_members_total", 0) / ndisp, 1),
            "dispatches": d.get("batch_dispatch_total", 0),
            "fallbacks": d.get("batch_fallback_total", 0),
        }
    db.sql("set batch_serving_enabled = off")
    return out


def microbench_batch_serving() -> None:
    """Vectorized-serving throughput (ISSUE 11, docs/PERF.md "Vectorized
    serving"): point-query statements/sec at concurrency {1, 4, 16} with
    batched serving on vs off. CPU-runnable by design — the win there is
    amortized per-statement host overhead (the CPU backend executes vmap
    members serially); on TPU the stacked members additionally share the
    device. Prints the standard one-line JSON:

        {"metric": "batch_serving_stmts_per_sec", "value": <conc-16 on>,
         "unit": "stmts/s", "vs_baseline": <on/off at conc 16>, ...}

    Env: GGTPU_MB_ROWS (default 8000), GGTPU_MB_SEGS (4),
         GGTPU_MB_PER_THREAD (16 statements per thread)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax  # noqa: F401  (platform pinning below)

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu

    rows = int(os.environ.get("GGTPU_MB_ROWS", "8000"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    per_thread = int(os.environ.get("GGTPU_MB_PER_THREAD", "16"))
    path = tempfile.mkdtemp(prefix="ggtpu_batchserve_mb_")
    try:
        db = greengage_tpu.connect(path, numsegments=nseg)
        db.sql("create table d (k int, a int, v double precision) "
               "distributed by (k)")
        rng = np.random.default_rng(7)
        db.load_table("d", {
            "k": np.arange(rows, dtype=np.int32),
            "a": np.arange(rows, dtype=np.int32),
            "v": rng.random(rows)})

        def q(i: int) -> str:
            return (f"select count(*), sum(v) from d "
                    f"where a > {100 + i % 400}")

        res = _batch_serving_measure(db, q, per_thread=per_thread)
        c16 = res.get("conc16", {})
        line = {
            "metric": "batch_serving_stmts_per_sec",
            "value": c16.get("on_stmts_per_sec", 0),
            "unit": "stmts/s",
            "vs_baseline": c16.get("speedup", 0),
            "rows": rows, "segments": nseg,
            **res,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def microbench_scalar_fusion() -> None:
    """Fused device scalar path vs the host-chain fallback (ISSUE 13,
    docs/PERF.md "Scalar data-path fusion") on a dict-encoded AND a raw
    TEXT column: `upper(col) = literal` counted over the table. The raw
    column compares three ways — device byte-window ops
    (scalar_device_enabled=on), the legacy per-row host chain (off), and
    the dictionary column's LUT path. Each measurement clears the staging
    + host-predicate + raw-window caches first, so both paths pay their
    honest per-manifest-version cost (the cost a fresh DML version
    re-incurs — cached repeats are ~free on both paths and measure
    nothing). Prints the standard one-line JSON:

        {"metric": "scalar_fusion_speedup", "value": <host/device on raw>,
         "unit": "x", "vs_baseline": <same>, ...}

    Env: GGTPU_MB_ROWS (default 300000), GGTPU_MB_SEGS (4),
         GGTPU_MB_RUNS (3)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax  # noqa: F401  (platform pinning below)

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu
    from greengage_tpu.runtime.logger import counters

    rows = int(os.environ.get("GGTPU_MB_ROWS", "300000"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    runs = int(os.environ.get("GGTPU_MB_RUNS", "3"))
    path = tempfile.mkdtemp(prefix="ggtpu_scalar_mb_")
    try:
        db = greengage_tpu.connect(path, numsegments=nseg)
        db.sql("create table t (k int, cdict text, craw text) "
               "distributed by (k)")
        object.__setattr__(db.catalog.get("t").column("craw"),
                           "encoding", "raw")
        rng = np.random.default_rng(13)
        vocab = [f"  val{i:05d}  " for i in range(2000)]
        codes = rng.integers(0, len(vocab), rows)
        strs = np.array(vocab, dtype=object)[codes]
        db.load_table("t", {"k": np.arange(rows, dtype=np.int32),
                            "cdict": strs, "craw": strs.copy()})

        def timed_stmt(q: str) -> float:
            db.sql(q)   # compile/LUT warm; measurement pays the data path
            best = 1e9
            for _ in range(runs):
                db.executor._stage_cache.clear()
                db.store._hp_cache.clear()
                db.store._rawprefix_cache.clear()
                db.store._raw_cache.clear()
                t0 = time.monotonic()
                db.sql(q)
                best = min(best, time.monotonic() - t0)
            return best

        q_chain = ("select count(*) from t "
                   "where length(trim({c})) > 8 and upper(trim({c})) "
                   "like 'VAL0004%'")
        q_eq = "select count(*) from t where upper(trim({c})) = 'VAL00042'"
        c0 = counters.snapshot()
        dict_s = timed_stmt(q_chain.format(c="cdict"))
        raw_dev_chain = timed_stmt(q_chain.format(c="craw"))
        raw_dev_eq = timed_stmt(q_eq.format(c="craw"))
        db.sql("set scalar_device_enabled = off")
        raw_host_chain = timed_stmt(q_chain.format(c="craw") + " -- host")
        raw_host_eq = timed_stmt(q_eq.format(c="craw") + " -- host")
        db.sql("set scalar_device_enabled = on")
        d = counters.since(c0)
        speedup = raw_host_chain / max(raw_dev_chain, 1e-9)
        line = {
            "metric": "scalar_fusion_speedup",
            "value": round(speedup, 2),
            "unit": "x",
            "vs_baseline": round(speedup, 2),
            "raw_device_ms": round(raw_dev_chain * 1e3, 1),
            "raw_host_ms": round(raw_host_chain * 1e3, 1),
            "raw_eq_device_ms": round(raw_dev_eq * 1e3, 1),
            "raw_eq_host_ms": round(raw_host_eq * 1e3, 1),
            "dict_lut_ms": round(dict_s * 1e3, 1),
            "scalar_device_total": d.get("scalar_device_total", 0),
            "scalar_host_fallback_total":
                d.get("scalar_host_fallback_total", 0),
            "rows": rows, "segments": nseg,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def _motion_pipeline_measure(db, q, runs=3) -> dict:
    """Wall time of a bucketed spill merge with the bucket pipeline on
    vs off (identical programs — motion_pipeline only changes whether
    stage k+1 overlaps compute k), plus the realized overlap counter
    (shared by the microbench and the TPU bench's detail rider). The
    caller has already set the vmem budget that forces the spill."""
    from greengage_tpu.runtime.logger import counters

    def best_of(n):
        best, r = 1e9, None
        for _ in range(n):
            t0 = time.monotonic()
            r = db.sql(q)
            best = min(best, time.monotonic() - t0)
        return best, r

    db.sql("set motion_pipeline = on")
    db.sql(q)   # warm: the pass/merge programs compile once
    c0 = counters.snapshot()
    on_s, r = best_of(runs)
    overlap = counters.since(c0).get("motion_overlap_ms", 0)
    db.sql("set motion_pipeline = off")
    off_s, _ = best_of(runs)
    db.sql("set motion_pipeline = on")
    return {
        "on_ms": round(on_s * 1e3, 1),
        "off_ms": round(off_s * 1e3, 1),
        "speedup": round(off_s / max(on_s, 1e-9), 2),
        "overlap_ms_per_run": round(overlap / max(runs, 1), 1),
        "merge_buckets": (r.stats or {}).get("spill_merge_buckets"),
    }


def microbench_motion_pipeline() -> None:
    """Pipelined bucket schedules + the tiered workfile (ISSUE 18,
    docs/PERF.md "Data movement"): a bucketed DISTINCT spill merge with
    the bucket pipeline on vs off — the off path is the strict
    stage/compute alternation, so the headline is the overlap win
    (bounded by min(stage, compute) per bucket pair; >=1.3x once the
    buckets are multi-ms) — plus the disk tier's round-trip cost on a
    full-width sort whose captured passes exceed a 1 MB host tier.
    Prints the standard one-line JSON:

        {"metric": "motion_pipeline_speedup", "value": <off/on>,
         "unit": "x", "vs_baseline": <same>, ...}

    The overlap needs the two legs on DISTINCT execution resources —
    device compute vs host staging on TPU, or >=2 cores on CPU, where
    the XLA dispatch releases the GIL while the stager subsets the next
    bucket. On a single-vCPU container both legs serialize on the same
    core and the ratio honestly reads ~1.0x (the banked
    motion_overlap_ms still proves the schedule overlapped); host_cpus
    rides the JSON so the reader can tell which case they measured.
    Env: GGTPU_MB_ROWS (default 400000), GGTPU_MB_SEGS (4),
         GGTPU_MB_RUNS (3)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax  # noqa: F401  (platform pinning below)

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu
    from greengage_tpu.runtime.logger import counters

    rows = int(os.environ.get("GGTPU_MB_ROWS", "400000"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    runs = int(os.environ.get("GGTPU_MB_RUNS", "3"))
    path = tempfile.mkdtemp(prefix="ggtpu_motion_mb_")
    try:
        db = greengage_tpu.connect(path, numsegments=nseg)
        db.sql("create table mp (k int, v int) distributed by (k)")
        rng = np.random.default_rng(18)
        db.load_table("mp", {"k": np.arange(rows, dtype=np.int64),
                             "v": rng.integers(0, 100, rows)})
        db.sql("analyze")
        q = "select count(distinct k) from mp"
        qs = "select k, v from mp order by v, k limit 5"
        db.sql("set vmem_protect_limit_mb = 1")
        mp = _motion_pipeline_measure(db, q, runs=runs)
        # disk tier: the same sort with the host tier at 1 MB vs
        # unbounded — what demote -> segment file -> promote costs when
        # the workfile cannot stay resident
        db.sql(qs)   # warm
        t0 = time.monotonic()
        db.sql(qs)
        ram_s = time.monotonic() - t0
        db.sql(f"set spill_dir to '{os.path.join(path, 'spill-mb')}'")
        db.sql("set spill_host_limit_mb = 1")
        c0 = counters.snapshot()
        t0 = time.monotonic()
        db.sql(qs)
        disk_s = time.monotonic() - t0
        d = counters.since(c0)
        line = {
            "metric": "motion_pipeline_speedup",
            "value": mp["speedup"],
            "unit": "x",
            "vs_baseline": mp["speedup"],
            **mp,
            "spill_ram_ms": round(ram_s * 1e3, 1),
            "spill_disk_tier_ms": round(disk_s * 1e3, 1),
            "disk_tier_overhead": round(disk_s / max(ram_s, 1e-9), 2),
            "demotes": d.get("spill_demote_total", 0),
            "promotes": d.get("spill_promote_total", 0),
            "host_cpus": os.cpu_count(),
            "rows": rows, "segments": nseg,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def microbench_feedback() -> None:
    """Closed measurement loop (docs/PERF.md "Self-tuning"): a statement
    whose row estimate is ~3x wrong runs cold (priced off the bad
    estimate), the reconcile pass promotes a calibration, and the SECOND
    execution plans and admits against ground truth. Prints the standard
    one-line JSON:

        {"metric": "feedback_mem_err_pct_warm", "value": N, "unit":
         "pct", "vs_baseline": <cold err / warm err>, ...receipts...}

    Env: GGTPU_MB_ROWS (default 100000), GGTPU_MB_SEGS (4)."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax  # noqa: F401  (platform pinning below)

    _apply_platform_override()
    import shutil
    import tempfile

    import numpy as np

    import greengage_tpu
    from greengage_tpu.runtime.logger import counters

    rows = int(os.environ.get("GGTPU_MB_ROWS", "100000"))
    nseg = int(os.environ.get("GGTPU_MB_SEGS", "4"))
    path = tempfile.mkdtemp(prefix="ggtpu_feedback_mb_")
    try:
        db = greengage_tpu.connect(path, numsegments=nseg)
        db.sql("create table t (k int, b int, v double precision) "
               "distributed by (k)")
        rng = np.random.default_rng(7)
        # b in [0, 7): `where b >= 0` passes EVERYTHING but the default
        # selectivity prices it at ~1/3 — the canonical 3x underestimate
        db.load_table("t", {
            "k": np.arange(rows, dtype=np.int32),
            "b": (np.arange(rows) % 7).astype(np.int32),
            "v": rng.random(rows)})
        q = "select count(*), sum(v) from t where b >= 0"
        c0 = counters.snapshot()
        t0 = time.monotonic()
        db.sql(q)
        cold_ms = (time.monotonic() - t0) * 1e3
        cold_err = abs(int(counters.get("mem_est_error_pct")))
        t0 = time.monotonic()
        db.sql(q)
        warm_ms = (time.monotonic() - t0) * 1e3
        warm_err = abs(int(counters.get("mem_est_error_pct")))
        d = counters.since(c0)
        rep = db.feedback.report()
        line = {
            "metric": "feedback_mem_err_pct_warm",
            "value": warm_err,
            "unit": "pct",
            "vs_baseline": round(cold_err / max(warm_err, 1), 2),
            "cold_mem_err_pct": cold_err,
            "warm_mem_err_pct": warm_err,
            "corrections_applied": d.get("feedback_applied_total", 0),
            "calibration_gen": rep["gen"],
            "pending": rep["pending"],
            "admission_measured": d.get("admission_measured_total", 0),
            "admission_estimated": d.get("admission_estimated_total", 0),
            "cold_stmt_ms": round(cold_ms, 1),
            "warm_stmt_ms": round(warm_ms, 1),
            "rows": rows, "segments": nseg,
        }
        print(json.dumps(line), flush=True)
    finally:
        shutil.rmtree(path, ignore_errors=True)


def microbench(name: str) -> None:
    fn = globals().get("microbench_" + name)
    if fn is None:
        print(json.dumps({"metric": f"microbench_{name}", "value": 0,
                          "error": f"unknown microbench {name!r}"}),
              flush=True)
        raise SystemExit(2)
    fn()


# ======================================================================
# probe child
# ======================================================================

def _apply_platform_override() -> None:
    """GGTPU_BENCH_PLATFORM=cpu pins the children to the CPU backend for
    harness smoke tests. Env vars (JAX_PLATFORMS) are NOT enough: the
    environment's site hook re-registers the TPU plugin regardless — only
    jax.config wins."""
    plat = os.environ.get("GGTPU_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def probe_child() -> None:
    import jax

    _apply_platform_override()
    devs = jax.devices()
    log(f"probe: {len(devs)} device(s), kind={devs[0].device_kind}, "
        f"platform={devs[0].platform}")
    # one tiny real computation: a backend that lists devices but cannot
    # compile (the r3 'setup/compile error' state) must fail the probe
    import jax.numpy as jnp

    assert int(jax.jit(lambda x: (x * 2).sum())(jnp.arange(8))) == 56
    print("probe ok", file=sys.stderr, flush=True)


# ======================================================================
# measurement child (the original bench body)
# ======================================================================

def _cut(day: str) -> int:
    import numpy as np

    return (np.datetime64(day) - np.datetime64("1970-01-01")).astype(np.int64)


def baseline_q1(data) -> float:
    import numpy as np

    li = data["lineitem"]
    cutoff = _cut("1998-12-01") - 90
    qty, price = li["l_quantity"], li["l_extendedprice"]
    disc, tax, ship = li["l_discount"], li["l_tax"], li["l_shipdate"]
    rf, ls = li["l_returnflag"].codes, li["l_linestatus"].codes

    def run():
        m = ship <= cutoff
        gid = np.where(m, rf * 2 + ls, 6)
        disc_price = price * (100 - disc)
        charge = disc_price * (100 + tax)
        out = []
        for g in range(6):
            mask = gid == g
            cnt = int(mask.sum())
            # all 8 Q1 aggregates, matching what the engine computes
            out.append((np.sum(qty, where=mask), np.sum(price, where=mask),
                        np.sum(disc_price, where=mask), np.sum(charge, where=mask),
                        np.sum(qty, where=mask) / max(cnt, 1),
                        np.sum(price, where=mask) / max(cnt, 1),
                        np.sum(disc, where=mask) / max(cnt, 1), cnt))
        return out

    run()
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def baseline_q3(data) -> float:
    import pandas as pd

    li, o, c = data["lineitem"], data["orders"], data["customer"]
    cut = _cut("1995-03-15")

    def run():
        lf = pd.DataFrame({
            "l_orderkey": li["l_orderkey"], "rev": li["l_extendedprice"] * (100 - li["l_discount"]),
        })[li["l_shipdate"] > cut]
        of = pd.DataFrame({
            "o_orderkey": o["o_orderkey"], "o_custkey": o["o_custkey"],
            "o_orderdate": o["o_orderdate"],
        })[o["o_orderdate"] < cut]
        cf = pd.DataFrame({"c_custkey": c["c_custkey"]})[c["c_mktsegment"].codes ==
                                                         c["c_mktsegment"].vocab.index("BUILDING")]
        j = lf.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        j = j.merge(cf, left_on="o_custkey", right_on="c_custkey")
        g = j.groupby(["l_orderkey", "o_orderdate"], as_index=False)["rev"].sum()
        return g.nlargest(10, "rev")

    run()   # warm caches: compare steady CPU vs steady device
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def baseline_q5(data) -> float:
    import pandas as pd

    li, o, c = data["lineitem"], data["orders"], data["customer"]
    s, n, r = data["supplier"], data["nation"], data["region"]
    lo, hi = _cut("1994-01-01"), _cut("1995-01-01")

    def run():
        asia = [i for i, (nm, rk) in enumerate(
            zip(n["n_name"], n["n_regionkey"]))
            if r["r_name"][rk] == "ASIA"]
        sf = pd.DataFrame({"s_suppkey": s["s_suppkey"], "s_nationkey": s["s_nationkey"]})
        sf = sf[sf.s_nationkey.isin(asia)]
        cf = pd.DataFrame({"c_custkey": c["c_custkey"], "c_nationkey": c["c_nationkey"]})
        of = pd.DataFrame({
            "o_orderkey": o["o_orderkey"], "o_custkey": o["o_custkey"],
        })[(o["o_orderdate"] >= lo) & (o["o_orderdate"] < hi)]
        lf = pd.DataFrame({
            "l_orderkey": li["l_orderkey"], "l_suppkey": li["l_suppkey"],
            "rev": li["l_extendedprice"] * (100 - li["l_discount"]),
        })
        j = lf.merge(of, left_on="l_orderkey", right_on="o_orderkey")
        j = j.merge(sf, left_on="l_suppkey", right_on="s_suppkey")
        j = j.merge(cf, left_on="o_custkey", right_on="c_custkey")
        j = j[j.c_nationkey == j.s_nationkey]
        return j.groupby("s_nationkey")["rev"].sum()

    run()   # warm caches: compare steady CPU vs steady device
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        run()
        best = min(best, time.monotonic() - t0)
    return best


def _meta_path(bench_dir):
    # sidecar NEXT TO the cluster dir, not inside it: the store owns its
    # tree (gpcheckcat walks it) and ensure_loaded may wipe it wholesale
    return bench_dir.rstrip("/") + ".meta.json"


def _load_meta(bench_dir):
    try:
        with open(_meta_path(bench_dir)) as f:
            return json.load(f)
    except Exception:
        return None


def _save_meta(bench_dir, meta):
    tmp = _meta_path(bench_dir) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, _meta_path(bench_dir))


def _counts_match(db, counts) -> bool:
    for t, want in counts.items():
        try:
            if sum(db.store.segment_rowcounts(t)) != want:
                return False
        except Exception:
            return False
    return True


def ensure_loaded(db, data, counts_want):
    """Reuse the bench dir only when it holds EXACTLY the expected rows; a
    partial/mismatched dir (killed prior run, different SF) is wiped and
    reloaded — load_table is append-only, so loading on top would silently
    inflate every number."""
    import numpy as np  # noqa: F401  (tpch data arrays)

    have = {}
    for t in counts_want:
        try:
            have[t] = sum(db.store.segment_rowcounts(t))
        except Exception:
            have[t] = -1
    if have == counts_want:
        return db
    from greengage_tpu.utils import tpch

    if any(v > 0 for v in have.values()):
        import shutil

        import greengage_tpu

        path = db.path
        log(f"bench dir rowcounts mismatch {have} — wiping and reloading")
        db.close()
        shutil.rmtree(path, ignore_errors=True)
        db = greengage_tpu.connect(path=path, numsegments=1)
    db.sql(tpch.DDL)
    for name, cols in data.items():
        db.load_table(name, cols)
    db._loaded_now = True
    return db


def timed(db, sql, runs):
    t0 = time.monotonic()
    r = db.sql(sql)
    first = time.monotonic() - t0
    log(f"first run {first:.1f}s (tiers={r.stats['tiers_used']})")
    best = float("inf")
    for i in range(runs):
        t0 = time.monotonic()
        r = db.sql(sql)
        best = min(best, time.monotonic() - t0)
    log(f"steady best {best * 1e3:.1f}ms over {runs} runs")
    return best, first, r


def record_trace(db, qname: str) -> str | None:
    """Export the newest statement trace (the last timed run) as Chrome
    trace_event JSON next to the bench cluster, so an unwedged TPU run
    yields a per-phase PROFILE (stage vs dispatch vs fetch spans), not
    just a headline number. Best-effort — profiling must never fail the
    measurement."""
    try:
        from greengage_tpu.runtime.trace import TRACES, to_chrome

        tr = TRACES.last()
        if tr is None:
            return None
        path = os.path.join(db.path, f"trace_{qname}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(to_chrome(tr), f)
        os.replace(tmp, path)
        log(f"trace recorded: {path}")
        return path
    except Exception as e:
        log(f"trace recording failed (non-fatal): {e}")
        return None


class _Setup:
    """Shared by --run (measurement) and --prewarm (cache population):
    connect / validate-or-load the bench cluster, expose sidecar-cached
    CPU baselines."""

    def __init__(self, sf: float):
        from greengage_tpu.utils import tpch

        import greengage_tpu

        self.sf = sf
        self.tpch = tpch
        t_setup = time.monotonic()
        # dir name keyed by segment count (always 1 here), NOT device
        # count: the stored cluster is identical regardless of platform,
        # which is what lets a CPU --prewarm warm the dir a TPU --run reads
        self.bench_dir = os.environ.get(
            "GGTPU_BENCH_DIR", f"/tmp/ggtpu_bench_sf{sf:g}_1seg")
        db = greengage_tpu.connect(path=self.bench_dir, numsegments=1)
        # warm path (the time-to-first-number fix): a bench dir already
        # loaded at this SF — validated row-exact against the sidecar —
        # goes straight to measurement; generation (minutes at SF10) and
        # the CPU baselines are skipped or served from the sidecar cache
        meta = _load_meta(self.bench_dir)
        self.data = None
        # baseline_v invalidates sidecar-cached baselines whenever a
        # baseline_qN implementation changes — bump on edit, or stale
        # numbers silently skew vs_baseline across rounds
        if meta and meta.get("baseline_v") != BASELINE_V:
            meta["baselines"] = {}
            meta["baseline_v"] = BASELINE_V
        if meta and meta.get("sf") == sf and _counts_match(db, meta["counts"]):
            counts = meta["counts"]
            loaded = False
            log(f"bench dir warm at SF{sf:g} — skipping generation")
        else:
            log(f"generating SF{sf:g}")
            self.data = tpch.generate_cached(sf)
            counts = {t: len(next(iter(v.values())))
                      for t, v in self.data.items()}
            log("loading")
            db = ensure_loaded(db, self.data, counts)
            loaded = getattr(db, "_loaded_now", False)
            meta = {"sf": sf, "counts": counts, "baselines": {},
                    "baseline_v": BASELINE_V}
            _save_meta(self.bench_dir, meta)
        self.db, self.meta, self.counts, self.loaded = db, meta, counts, loaded
        if loaded or db.catalog.get("lineitem").stats is None:
            log("analyzing")
            db.sql("analyze")   # NDV-accurate capacities avoid recompiles
        self.setup_s = time.monotonic() - t_setup
        log(f"setup done ({self.setup_s:.0f}s, loaded_now={loaded})")

    def get_baseline(self, qname: str) -> float:
        """CPU baseline seconds, from the sidecar when already measured —
        the generated arrays are only materialized if a baseline is
        actually missing."""
        if qname in self.meta.get("baselines", {}):
            return self.meta["baselines"][qname]
        if self.data is None:
            self.data = self.tpch.generate_cached(self.sf)
        s = globals()["baseline_" + qname](self.data)
        self.meta.setdefault("baselines", {})[qname] = s
        _save_meta(self.bench_dir, self.meta)
        return s


def prewarm_child():
    """Populate every cache the measurement path reads — dataset pickle,
    loaded cluster, stats, baseline sidecar — WITHOUT touching a TPU
    backend (forced CPU platform, 1 device, same dir name the real run
    computes). Run during the build round so the end-of-round bench's
    first probe window goes straight to Q1."""
    os.environ.setdefault("GGTPU_BENCH_PLATFORM", "cpu")
    import jax

    _apply_platform_override()
    assert jax.devices()[0].platform == "cpu"
    sf = float(os.environ.get("GGTPU_BENCH_SF", "10"))
    s = _Setup(sf)
    for q in QUERIES:
        q = q.strip()
        if "baseline_" + q not in globals():
            log(f"prewarm: no baseline for {q!r} — skipped")
            continue
        log(f"prewarm baseline {q}")
        s.get_baseline(q)
    log(f"prewarm complete: {s.bench_dir}")


def run_child():
    import numpy as np  # noqa: F401

    import jax

    _apply_platform_override()

    sf = float(os.environ.get("GGTPU_BENCH_SF", "10"))
    headline_file = os.environ.get("GGTPU_HEADLINE_FILE", "")

    dev = jax.devices()[0]
    s = _Setup(sf)
    db, get_baseline = s.db, s.get_baseline
    n_rows = s.counts["lineitem"]
    loaded, setup_s = s.loaded, s.setup_s

    detail = {"sf": sf, "rows": n_rows, "device": str(dev.device_kind),
              "loaded_now": loaded, "setup_s": round(setup_s, 1)}
    # the chip's real HBM is the limit for this known workload (the default
    # admission guard is conservative for ad-hoc queries)
    db.sql("set vmem_protect_limit_mb = 15000")
    # Q1 streams 7 lineitem columns: 4×int64 + 3×int32 codes/dates = 44 B/row
    q1_bytes_per_row = 44

    def record_headline(line):
        """Atomic write; the parent polls this file and prints the line the
        moment it appears — a later kill can never discard it."""
        if not headline_file:
            print(json.dumps(line), flush=True)
            return
        tmp = headline_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(line))
        os.replace(tmp, headline_file)
        log(f"headline recorded: {line}")

    # ONE headline object, re-recorded (atomic replace) as each query
    # lands: the Q1 number is the cross-round metric, and Q3/Q5 ride the
    # same line so a single unwedged run captures all three (VERDICT r5
    # standing order) — the parent prints whatever the latest recording
    # holds, even if the driver kills it between queries
    headline = None
    for qname, sql in (("q1", Q1), ("q3", Q3), ("q5", Q5)):
        if qname not in QUERIES:
            continue
        try:
            log(f"=== {qname} ===")
            # release the previous query's staged device arrays: at SF10
            # the three queries' column sets together exceed HBM
            db.executor._stage_cache.clear()
            best, first, r = timed(db, sql, RUNS)
            trace_path = record_trace(db, qname)
            cpu_s = get_baseline(qname)
            value = n_rows / best
            base = n_rows / cpu_s
            detail[qname] = {
                "rows_per_sec_per_chip": round(value),
                "best_ms": round(best * 1e3, 1),
                "first_run_s": round(first, 1),
                "cpu_baseline_ms": round(cpu_s * 1e3, 1),
                "vs_baseline": round(value / base, 3),
                "rows_out": len(r),
                "trace": trace_path,
            }
            # memory profile (VERDICT r5 standing order rider): the
            # device allocator's live/peak bytes after this query plus
            # the executable's measured memory_analysis, so the first
            # unwedged TPU run also yields a memory profile. memory_stats
            # is None on CPU — recorded as null, never a crash.
            try:
                dstats = dev.memory_stats() or {}
            except Exception:
                dstats = {}
            detail[qname]["peak_bytes_in_use"] = dstats.get(
                "peak_bytes_in_use")
            detail[qname]["bytes_in_use"] = dstats.get("bytes_in_use")
            mem = (r.stats or {}).get("mem") or {}
            if mem.get("measured"):
                detail[qname]["executable_mem"] = mem["measured"]
            if qname == "q1":
                assert len(r) == 6, f"Q1 expected 6 groups, got {len(r)}"
                gbs = n_rows * q1_bytes_per_row / best / 1e9
                detail[qname]["gb_per_sec"] = round(gbs, 1)
                # roofline: fraction of v5e HBM peak the scan achieved, and
                # whether the fused pallas kernel actually ran (a silent
                # XLA fallback must not pose as a pallas measurement)
                detail[qname]["hbm_peak_frac"] = round(gbs / HBM_PEAK_GBS, 3)
                detail[qname]["fused_kernel"] = bool(
                    r.stats.get("fused_kernel"))
                if db.executor.last_fused_error:
                    detail[qname]["fused_error"] = db.executor.last_fused_error
                headline = {
                    "metric": "tpch_q1_rows_per_sec_per_chip",
                    "value": round(value),
                    "unit": "rows/s",
                    "vs_baseline": round(value / base, 3),
                }
                record_headline(headline)
            elif headline is not None:
                headline[qname] = {
                    "rows_per_sec_per_chip": round(value),
                    "vs_baseline": round(value / base, 3),
                }
                record_headline(headline)
        except Exception as e:  # one failing query must not kill the rest
            detail[qname] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps({qname: detail.get(qname)}), file=sys.stderr,
              flush=True)

    # vectorized-serving rider (ISSUE 11): a small point-query table in
    # the same cluster, measured at concurrency 4 with batching on vs
    # off — so the first unwedged TPU run also captures the serving
    # amortization on silicon, not just the CPU microbench number
    try:
        log("=== batch_serving rider ===")
        db.executor._stage_cache.clear()
        import numpy as _np
        db.sql("create table bserve (k int, a int, v double precision) "
               "distributed by (k)")
        db.load_table("bserve", {
            "k": _np.arange(50_000, dtype=_np.int32),
            "a": _np.arange(50_000, dtype=_np.int32),
            "v": _np.arange(50_000) * 0.5})
        detail["batch_serving"] = _batch_serving_measure(
            db, lambda i: ("select count(*), sum(v) from bserve "
                           f"where a > {100 + i % 400}"),
            concs=(4,), per_thread=8)
    except Exception as e:
        detail["batch_serving"] = {"error": f"{type(e).__name__}: {e}"}

    # window-engine rider (ISSUE 12): an ordered-global ntile (the
    # gather-free all-gather rank machinery) and a partitioned running
    # sum over lineitem, warm-timed — so the first unwedged TPU run
    # captures window kernel timings alongside Q1/Q3/Q5
    try:
        log("=== window rider ===")
        from greengage_tpu.runtime.logger import counters as _wc

        wq = {
            "ntile_global": ("select max(nt) from (select ntile(8) over "
                             "(order by o_orderkey) nt from orders) t"),
            "partitioned_running_sum": (
                "select max(rs) from (select sum(l_quantity) over "
                "(partition by l_suppkey order by l_extendedprice, "
                "l_orderkey) rs from lineitem) t"),
        }
        wd = {}
        for name, q in wq.items():
            db.sql(q)   # warm: compile once, then measure dispatch
            t0 = time.monotonic()
            r = db.sql(q)
            wd[name] = {"ms": round((time.monotonic() - t0) * 1e3, 1),
                        "compute_ms": r.stats.get("compute_ms"),
                        "fused": r.stats.get("fused_kernel")}
        wd["gather_free_total"] = _wc.get("window_gather_free_total")
        wd["funnel_total"] = _wc.get("window_funnel_total")
        detail["window"] = wd
    except Exception as e:
        detail["window"] = {"error": f"{type(e).__name__}: {e}"}

    # TPC-DS / scalar-fusion rider (ISSUE 13): Q42's date-math star join
    # over the dict-encoded dimension, warm-timed with the scalar fusion
    # counters — so the first unwedged TPU run (BENCH_r02..r05 standing
    # order) also captures TPC-DS-class scalar work on silicon
    try:
        log("=== tpcds scalar rider ===")
        from greengage_tpu.runtime.logger import counters as _sc
        from greengage_tpu.utils import tpcds as _tpcds

        db.executor._stage_cache.clear()
        _tpcds.load(db, 1.0)
        db.sql("analyze")
        q42 = """select dt.d_year, item.i_category_id, item.i_category,
                        sum(ss_ext_sales_price) rev
                 from date_dim dt, store_sales, item
                 where dt.d_date_sk = store_sales.ss_sold_date_sk
                   and store_sales.ss_item_sk = item.i_item_sk
                   and item.i_manager_id = 1 and dt.d_moy = 11
                   and dt.d_year = 2000
                 group by dt.d_year, item.i_category_id, item.i_category
                 order by rev desc, d_year, i_category_id limit 100"""
        qext = """select extract(year from d_date) y, date_trunc('quarter',
                         d_date) q, sum(ss_ext_sales_price) rev
                  from store_sales, date_dim
                  where ss_sold_date_sk = d_date_sk
                  group by extract(year from d_date),
                           date_trunc('quarter', d_date)
                  order by y, q"""
        ds = {}
        for name, q in (("q42", q42), ("extract_rollup", qext)):
            db.sql(q)   # warm: compile once, then measure dispatch
            t0 = time.monotonic()
            r = db.sql(q)
            ds[name] = {"ms": round((time.monotonic() - t0) * 1e3, 1),
                        "rows": len(r)}
        ds["scalar_device_total"] = _sc.get("scalar_device_total")
        ds["scalar_host_fallback_total"] = \
            _sc.get("scalar_host_fallback_total")
        detail["tpcds"] = ds
    except Exception as e:
        detail["tpcds"] = {"error": f"{type(e).__name__}: {e}"}

    # data-movement rider (ISSUE 18): the bucketed DISTINCT spill merge
    # with the bucket pipeline on vs off, then the same statement through
    # the disk tier — so the first unwedged TPU run also captures the
    # stage/compute overlap win and the tiered workfile's round-trip
    # cost on silicon, next to the CPU microbench numbers
    try:
        log("=== motion pipeline rider ===")
        from greengage_tpu.runtime.logger import counters as _mc

        db.executor._stage_cache.clear()
        qmd = "select count(distinct l_orderkey) from lineitem"
        saved_vmem = int(db.settings.vmem_protect_limit_mb)
        db.sql("set vmem_protect_limit_mb = 64")
        try:
            md = _motion_pipeline_measure(db, qmd, runs=2)
            db.sql("set spill_host_limit_mb = 64")
            c0 = _mc.snapshot()
            t0 = time.monotonic()
            db.sql(qmd)
            md["disk_tier_ms"] = round((time.monotonic() - t0) * 1e3, 1)
            dd = _mc.since(c0)
            md["demotes"] = dd.get("spill_demote_total", 0)
            md["promotes"] = dd.get("spill_promote_total", 0)
        finally:
            db.sql("set spill_host_limit_mb = 512")
            db.sql(f"set vmem_protect_limit_mb = {saved_vmem}")
        detail["motion_pipeline"] = md
    except Exception as e:
        detail["motion_pipeline"] = {"error": f"{type(e).__name__}: {e}"}

    # self-tuning rider (ISSUE 20): the same Q1 shape run twice through
    # the closed loop — on silicon the second execution should admit by
    # MEASURED footprint (live HBM allocator stats), and the est-vs-actual
    # admission error gauge should collapse; receipts land next to the
    # CPU microbench numbers
    try:
        log("=== feedback rider ===")
        from greengage_tpu.runtime.logger import counters as _fc

        qf = ("select l_returnflag, count(*), sum(l_quantity) "
              "from lineitem where l_quantity >= 0 group by l_returnflag")
        c0 = _fc.snapshot()
        db.sql(qf)
        cold_err = abs(int(_fc.get("mem_est_error_pct")))
        t0 = time.monotonic()
        r2 = db.sql(qf)
        fd = _fc.since(c0)
        detail["feedback"] = {
            "warm_stmt_ms": round((time.monotonic() - t0) * 1e3, 1),
            "cold_mem_err_pct": cold_err,
            "warm_mem_err_pct": abs(int(_fc.get("mem_est_error_pct"))),
            "admitted_by": r2.stats.get("mem", {}).get("admitted_by"),
            "corrections_applied": fd.get("feedback_applied_total", 0),
            "admission_measured": fd.get("admission_measured_total", 0),
            "calibration_gen": db.feedback.report()["gen"],
        }
    except Exception as e:
        detail["feedback"] = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps(detail, indent=None), file=sys.stderr, flush=True)
    if "q1" not in QUERIES:
        # the headline is defined as the Q1 number; record an explicit
        # not-run line so the parent doesn't burn a fallback attempt
        record_headline({
            "metric": "tpch_q1_rows_per_sec_per_chip", "value": 0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "error": "q1 not in GGTPU_BENCH_QUERIES"})
    elif "error" in detail.get("q1", {}):
        raise SystemExit(f"q1 failed: {detail['q1']['error']}")


if __name__ == "__main__":
    if "--microbench" in sys.argv:
        i = sys.argv.index("--microbench")
        microbench(sys.argv[i + 1] if i + 1 < len(sys.argv) else "staging")
    elif "--probe" in sys.argv:
        probe_child()
    elif "--prewarm" in sys.argv:
        prewarm_child()
    elif "--run" in sys.argv:
        run_child()
    else:
        parent()
