"""Import-hygiene lint: no function-local imports of cheap stdlib modules.

Function-local imports are legitimate exactly twice in this codebase:
breaking package-internal import cycles, and deferring genuinely heavy
or optional dependencies (jax and friends take ~seconds and initialize
backends; pandas/yaml/zstandard are optional). Everything else — a
``import json`` inside a hot helper — re-pays a dict lookup per call,
hides the module's real dependency surface, and (as PR 4 found with a
function-local ``import time`` inside the resource-queue admit path)
lands in exactly the code least prepared for extra latency. PR 4 and
PR 7 each hoisted stragglers by hand; this lint keeps them hoisted.

Scope: imports of CHEAP_STDLIB modules inside any function/method.
Package-internal (``greengage_tpu.*``) and heavy/optional imports are
out of scope by design, not by baseline.
"""

from __future__ import annotations

import ast

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report

# stdlib modules cheap enough that deferring them buys nothing
CHEAP_STDLIB = frozenset({
    "bisect", "collections", "configparser", "contextlib", "copy", "csv",
    "dataclasses", "datetime", "decimal", "functools", "glob", "hashlib",
    "io", "itertools", "json", "math", "operator", "os", "pickle", "re",
    "select", "shutil", "signal", "socket", "string", "struct",
    "subprocess", "sys", "tarfile", "tempfile", "threading", "time",
    "types", "uuid", "warnings",
})


def run(sources=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet(
        exclude=("greengage_tpu/analysis/",))
    for src in sources:
        for fn in astutil.functions(src.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Import):
                    mods = [a.name for a in node.names]
                elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                        and node.module:
                    mods = [node.module]
                else:
                    continue
                for mod in mods:
                    top = mod.split(".", 1)[0]
                    if top not in CHEAP_STDLIB:
                        continue
                    if src.pragma_ok(node.lineno, "imports"):
                        continue
                    report.add(
                        "imports", src.rel, node.lineno,
                        f"{fn.name}:{mod}",
                        f"function-local `import {mod}` in {fn.name}() — "
                        "cheap stdlib imports belong at module top "
                        "(docs/ANALYSIS.md import hygiene)")
    return report
