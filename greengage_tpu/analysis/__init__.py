"""Static analysis suite — the assert-rich-planner discipline as a tool.

Reference parity: the reference walks every sliced plan before dispatch
(cdbmutate.c checkPlan machinery) and ships an assertion-heavy build for
development; this package is that discipline turned outward, in two
halves surfaced as ``gg check``:

* ``plancheck`` — plan-tree invariant validation run on every planned
  statement under the ``plan_validate`` GUC and over the TPC-H/TPC-DS
  plan corpus in tests: Motion placement, join/agg distribution-key
  locality, pow2 capacity bucketing, prune-predicate well-formedness,
  no interior Gather funnels.
* ``lint_*`` — stdlib-``ast`` lints over the package source for this
  codebase's recurring bug classes: lock-order cycles, blocking waits
  that skip the interrupt registry, host sync inside jit-traced code,
  executable-cache keys digesting estimates, metric/GUC/fault-point
  registry drift, and function-local stdlib imports.

All findings flow through one reporter (``report.Report``) with a
checked-in baseline (``analysis/baseline.txt``) for the rare deliberate
suppression, so ``gg check`` is zero-findings-clean at merge and gates
CI thereafter (docs/ANALYSIS.md).
"""

from greengage_tpu.analysis.plancheck import (PlanInvariantError,
                                              validate_capacities,
                                              validate_plan)
from greengage_tpu.analysis.report import Finding, Report
from greengage_tpu.analysis.runner import CHECKS, run_checks

__all__ = ["PlanInvariantError", "validate_plan", "validate_capacities",
           "Finding", "Report", "CHECKS", "run_checks"]
