"""The declared thread topology of the engine — who runs on what thread.

The host side of this engine is genuinely multi-threaded: statement /
connection threads, the PR-3 staging pool, the PR-11 batch-serving
pipeline pair, the FTS prober, the multihost heartbeat and rejoin
acceptors, the spill prefetcher, the gpfdist loader. Every one of them
mutates shared structures (program/plan LRUs, the BlockCache registry,
counters, manifest state). The reference relies on decades of
battle-testing for this class of bug; we substitute a *declared model*
that two analyzers cross-check against the code:

* ``THREAD_ROLES`` names every thread role, the package call sites that
  spawn it, and the functions that are its entry points. The
  registry-hygiene check (``run`` below, check id ``threads``) walks the
  package for ``threading.Thread(target=...)`` / ``ThreadPoolExecutor``
  / ``ThreadingMixIn`` spawn sites and fails in BOTH directions: an
  unregistered spawn site (a new thread nobody modelled) and a declared
  spawn with no site (a stale model).
* ``lint_races.py`` (check id ``races``) walks interprocedurally from
  each role's entries and reports shared attributes written by one role
  and touched by another with no common lock.
* ``runtime/lockdebug.py``'s access witness maps live threads back to
  roles through ``ROLE_NAME_PREFIXES`` (every spawn site names its
  thread, so the name prefix IS the role tag at runtime).

The model is deliberately explicit rather than inferred: adding a
thread means adding a row here, which is exactly the moment to decide
what state it may touch and under which lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report


@dataclass(frozen=True)
class Role:
    name: str
    description: str
    # ((path suffix, marker), ...): marker is the Thread target's trailing
    # name, "ThreadPoolExecutor" for pool construction, or "class:<Name>"
    # for a ThreadingMixIn-derived server class (its handler threads)
    spawns: tuple
    # ((path suffix, owning class or "", function name), ...) — the role's
    # entry points, where the race walk starts
    entries: tuple


THREAD_ROLES: dict[str, Role] = {
    "statement": Role(
        "statement",
        "statement/connection threads: Database.sql on the caller's "
        "thread, including every server handler thread executing it "
        "(and the inline staging pool at scan_threads=1). Handler.handle "
        "is an entry so the serving plane's shared state (SqlServer "
        "admission/drain bookkeeping, the per-connection watcher "
        "arm/disarm surface) is race-analyzed from the threads that "
        "actually touch it",
        spawns=(),          # spawned by callers/socketserver, not by us
        entries=(("exec/session.py", "Database", "sql"),
                 # server handler threads: admission, serve loop, drain
                 ("runtime/server.py", "Handler", "handle"),
                 # scan_threads=1 runs read units on the calling thread
                 ("exec/executor.py", "Executor", "_read_unit")),
    ),
    "server": Role(
        "server",
        "socket accept loops plus the per-CONNECTION client-disconnect "
        "watcher (_ConnWatcher: armed per statement, parked between "
        "statements; the handler threads themselves run statements and "
        "are modelled as the statement role)",
        spawns=(("runtime/server.py", "serve_forever"),
                ("runtime/server.py", "_loop"),
                ("runtime/server.py", "class:Server"),
                ("runtime/server.py", "class:TcpServer")),
        entries=(("runtime/server.py", "_ConnWatcher", "_loop"),),
    ),
    "staging": Role(
        "staging",
        "PR-3 staging pool workers: concurrent (table, segment) "
        "read+decode units through the store's caches",
        spawns=(("exec/staging.py", "ThreadPoolExecutor"),),
        entries=(("exec/executor.py", "Executor", "_read_unit"),),
    ),
    "spill-prefetch": Role(
        "spill-prefetch",
        "spill-pass read-ahead: warms pass k+1's block reads while pass "
        "k runs on device",
        spawns=(("exec/staging.py", "_warm"),),
        entries=(("exec/staging.py", "PassPrefetcher", "_warm"),),
    ),
    "motion-stage": Role(
        "motion-stage",
        "bucket-pipeline stager (exec/motionpipe.py): runs bucket k+1's "
        "side-effect-free stage callable (subset builds, workfile "
        "promotion reads) while the statement thread computes bucket k; "
        "slot handoff under the pipeline's own condition lock",
        spawns=(("exec/motionpipe.py", "_stage_loop"),),
        entries=(("exec/motionpipe.py", "BucketPipeline", "_stage_loop"),),
    ),
    "batch-stage": Role(
        "batch-stage",
        "vectorized-serving stager: pops admission windows and runs "
        "compile-or-reuse + admission + host staging",
        spawns=(("exec/batchserve.py", "_stage_loop"),),
        entries=(("exec/batchserve.py", "BatchServer", "_stage_loop"),),
    ),
    "batch-dispatch": Role(
        "batch-dispatch",
        "vectorized-serving dispatcher: device dispatch + per-member "
        "demux of staged batches",
        spawns=(("exec/batchserve.py", "_dispatch_loop"),),
        entries=(("exec/batchserve.py", "BatchServer", "_dispatch_loop"),),
    ),
    "fts": Role(
        "fts",
        "fault-tolerance prober daemon: segment health probes, mirror "
        "promotion, topology-version bumps",
        spawns=(("runtime/fts.py", "loop"),),
        entries=(("runtime/fts.py", "", "loop"),),
    ),
    "standby-watch": Role(
        "standby-watch",
        "coordinator-failover watcher daemon (runtime/standby.py "
        "StandbyWatcher): pulls the primary's commit tail into the "
        "standby, tracks the liveness beat, and fences + promotes when "
        "the primary is silent past standby_promote_deadline_s",
        spawns=(("runtime/standby.py", "loop"),),
        entries=(("runtime/standby.py", "StandbyWatcher", "loop"),),
    ),
    "heartbeat": Role(
        "heartbeat",
        "multihost idle ping/pong heartbeat over the coordinator "
        "channel",
        spawns=(("parallel/multihost.py", "loop"),),
        entries=(("parallel/multihost.py", "", "loop"),),
    ),
    "rejoin": Role(
        "rejoin",
        "multihost rejoin acceptor: collects re-dialing workers while a "
        "degraded gang serves",
        spawns=(("parallel/multihost.py", "accept_loop"),),
        entries=(("parallel/multihost.py", "", "accept_loop"),),
    ),
    "ingest": Role(
        "ingest",
        "gpfdist loader: HTTP chunk server handler threads plus the "
        "parallel chunk fetchers, and the streaming-plane deadline "
        "flusher (time-watermark micro-batch commits, idle reaping)",
        spawns=(("runtime/ingest.py", "serve_forever"),
                ("runtime/ingest.py", "one"),
                ("runtime/ingest.py", "class:Server"),
                ("runtime/ingest.py", "_flush_loop")),
        entries=(("runtime/ingest.py", "", "one"),
                 ("runtime/ingest.py", "", "do_GET"),
                 ("runtime/ingest.py", "StreamIngestor", "_flush_loop")),
    ),
}


# thread-name prefix -> role, first match wins; every spawn site above
# names its thread, so the runtime witness can tag accesses by role.
# Unmatched threads (MainThread, socketserver "Thread-N" handlers, test
# threads) default to "statement" — they run statements or behave as
# callers.
ROLE_NAME_PREFIXES: tuple = (
    ("gg-stage", "staging"),              # ThreadPoolExecutor prefix
    ("gg-spill-prefetch", "spill-prefetch"),
    ("gg-motion-stage", "motion-stage"),
    ("gg-batch-stage", "batch-stage"),
    ("gg-batch-dispatch", "batch-dispatch"),
    ("gg-client-watch", "server"),
    ("gg-server", "server"),
    ("gg-gpfdist", "ingest"),
    ("gg-ingest-flush", "ingest"),
    ("fts-prober", "fts"),
    ("gg-standby-watch", "standby-watch"),
    ("mh-heartbeat", "heartbeat"),
    ("mh-rejoin-accept", "rejoin"),
)

DEFAULT_ROLE = "statement"


def role_of_thread_name(name: str) -> str:
    for prefix, role in ROLE_NAME_PREFIXES:
        if name.startswith(prefix):
            return role
    return DEFAULT_ROLE


# Classes whose instances are genuinely SHARED across threads — the race
# analyzer only pairs accesses on these (and on module globals): a
# per-statement object (Compiler, Binder, Batch, Result, ...) has one
# static identity but a fresh instance per call, so pairing its
# attributes across roles would fabricate races. Adding a class here
# puts its whole attribute surface under cross-role analysis.
SHARED_CLASSES: dict[str, str] = {
    "Executor":          "one per Database; statement + serving pipeline",
    "BatchServer":       "admission windows + pipeline queue",
    "CacheRegistry":     "global block-cache byte budget",
    "BlockCache":        "named member caches of the registry",
    "TableStore":        "storage read paths + self-heal state",
    "Manifest":          "compose memo + delta cache + commit log",
    "Counters":          "process-wide metric registry",
    "Histograms":        "process-wide metric registry",
    "ClusterLog":        "shared CSV appender",
    "Database":          "session state reached from handler threads",
    "StatementRegistry": "interrupt contexts, cancelled cross-thread",
    "StatementContext":  "flag set by watcher/FTS/runaway threads",
    "SqlServer":         "connection admission/drain state, mutated by "
                         "every handler thread and stop()",
    "_ConnWatcher":      "armed/epoch state shared between the handler "
                         "thread and its watcher",
    "OverloadController": "process-wide brownout state machine, "
                          "evaluated from any statement thread",
    "FTSProber":         "probe bookkeeping",
    "StreamIngestor":    "stream registry shared by server handler "
                         "threads and the deadline flusher",
    "StreamSession":     "per-stream buffer/watermarks, fed by handlers "
                         "and flushed by the deadline thread",
    "SegmentConfig":     "topology mutated by FTS, read at dispatch",
    "PassPrefetcher":    "kicked by the spill loop, joined at close",
    "BucketPipeline":    "slot exchange between the statement thread and "
                         "its motion stager, under the pipeline's "
                         "condition lock",
    "_OrderTable":       "lockdebug's own global table",
    "FeedbackStore":     "calibration scales read at plan time by every "
                         "statement thread, written by reconcile after "
                         "execution and by the serve loop's adopt()",
}

# Attribute name -> class name: receiver typing the race walk cannot
# infer from constructor assignments (factory returns). Lets generic
# method calls (`self._stage_cache.get(...)`) resolve into the shared
# class's methods instead of going dark.
RECEIVER_TYPES: dict[str, str] = {
    "_stage_cache": "BlockCache",
    "blockcache": "CacheRegistry",
    # TableStore's named member caches (storage/table_store.py __init__,
    # all created by CacheRegistry.cache())
    "_block_cache": "BlockCache",
    "_footer_cache": "BlockCache",
    "_raw_cache": "BlockCache",
    "_hp_cache": "BlockCache",
    "_rawcode_cache": "BlockCache",
    "_rawprefix_cache": "BlockCache",
    # Database.feedback / Executor.feedback (planner/feedback.py store)
    "feedback": "FeedbackStore",
}


# ---------------------------------------------------------------------
# registry hygiene: every spawn site modelled, every model row live
# ---------------------------------------------------------------------

def _spawn_sites(src):
    """Yield (marker, lineno) for every thread-creating site in a module:
    Thread targets (trailing name), pool construction, ThreadingMixIn
    server classes."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name == "Thread":
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and node.args:
                    target = node.args[0]
                if target is None:
                    yield "Thread-without-target", node.lineno
                    continue
                if isinstance(target, ast.Attribute):
                    yield target.attr, node.lineno
                elif isinstance(target, ast.Name):
                    yield target.id, node.lineno
                else:
                    yield "Thread-computed-target", node.lineno
            elif name == "ThreadPoolExecutor":
                yield "ThreadPoolExecutor", node.lineno
        elif isinstance(node, ast.ClassDef):
            for base in node.bases:
                dn = astutil.dotted(base) or ""
                if "Threading" in dn:
                    yield f"class:{node.name}", node.lineno
                    break


def _declared() -> dict[tuple, list[str]]:
    """(path suffix, marker) -> [role names declaring it]."""
    out: dict[tuple, list[str]] = {}
    for role in THREAD_ROLES.values():
        for suffix, marker in role.spawns:
            out.setdefault((suffix, marker), []).append(role.name)
    return out


def run(sources=None) -> Report:
    """Check id ``threads``: cross-check spawn sites against THREAD_ROLES
    both ways, and that every declared entry point resolves to a real
    function."""
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet(
        exclude=("greengage_tpu/analysis/",))
    declared = _declared()
    found: set[tuple] = set()
    nsites = 0
    for src in sources:
        for marker, lineno in _spawn_sites(src):
            nsites += 1
            hits = [(suffix, m) for (suffix, m) in declared
                    if m == marker and src.rel.endswith(suffix)]
            if not hits:
                if src.pragma_ok(lineno, "threads"):
                    continue
                report.add(
                    "threads", src.rel, lineno,
                    f"unregistered-spawn:{marker}",
                    f"thread spawn site (target {marker!r}) is not "
                    "declared in analysis/threadmodel.py THREAD_ROLES — "
                    "model the new thread role (and what state it may "
                    "touch) before shipping it")
            else:
                found.update(hits)
    for (suffix, marker), roles in sorted(declared.items()):
        if (suffix, marker) not in found:
            report.add(
                "threads", "analysis/threadmodel.py", 1,
                f"stale-spawn:{marker}",
                f"THREAD_ROLES role(s) {', '.join(roles)} declare spawn "
                f"({suffix!r}, {marker!r}) but no such site exists — "
                "stale model row")
    # entry points must resolve to real functions
    index: set[tuple] = set()
    for src in sources:
        for cls, fn in _function_index(src.tree):
            index.add((src.rel, cls, fn))
            index.add((src.rel, "", fn))
    for role in THREAD_ROLES.values():
        for suffix, cls, fn in role.entries:
            if not any(rel.endswith(suffix) and c == cls and f == fn
                       for rel, c, f in index):
                report.add(
                    "threads", "analysis/threadmodel.py", 1,
                    f"dead-entry:{role.name}:{fn}",
                    f"role {role.name!r} entry point ({suffix}, "
                    f"{cls or '<module>'}, {fn}) resolves to no function "
                    "in the package")
    report.notes["thread_spawn_sites"] = nsites
    report.notes["thread_roles"] = len(THREAD_ROLES)
    return report


def _function_index(tree: ast.Module):
    """Yield (owning class or '', function name) for every function,
    attributing nested defs to their nearest enclosing class (a thread
    body defined inside a method still runs with that class's self)."""
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child.name
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)
    yield from walk(tree, "")
