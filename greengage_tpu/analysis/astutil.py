"""Shared ``ast`` plumbing for the ``gg check`` lints.

Every lint walks the same parsed package, so sources are read and parsed
once per run (``SourceSet``). Helpers keep the lints about their
invariants, not about AST shapes.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from greengage_tpu.analysis.report import line_pragmas


def package_root() -> str:
    """Directory of the ``greengage_tpu`` package itself."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


@dataclass
class Source:
    path: str            # absolute
    rel: str             # repo-relative (the path findings report)
    text: str
    tree: ast.Module
    lines: list[str]

    def pragma_ok(self, lineno: int, check: str) -> bool:
        """True when the 1-based line (or its statement's first line)
        carries ``# gg:ok(<check>)``."""
        if 1 <= lineno <= len(self.lines):
            if check in line_pragmas(self.lines[lineno - 1]):
                return True
        return False


class SourceSet:
    """Parsed sources of the package (and optionally the test tree)."""

    def __init__(self, roots: list[str] | None = None,
                 exclude: tuple[str, ...] = ()):
        self.sources: list[Source] = []
        base = repo_root()
        for root in roots or [package_root()]:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, base)
                    if any(rel.startswith(e) for e in exclude):
                        continue
                    with open(path, encoding="utf-8") as f:
                        text = f.read()
                    try:
                        tree = ast.parse(text, filename=rel)
                    except SyntaxError:
                        continue   # not this analyzer's finding to make
                    self.sources.append(Source(path, rel, text, tree,
                                               text.splitlines()))

    def __iter__(self):
        return iter(self.sources)

    def get(self, rel_suffix: str) -> Source | None:
        for s in self.sources:
            if s.rel.endswith(rel_suffix):
                return s
        return None


def call_name(node: ast.Call) -> str | None:
    """Trailing name of the called expression: ``a.b.c(...)`` -> ``c``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def dotted(node: ast.expr) -> str | None:
    """``a.b.c`` -> "a.b.c" for pure Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_prefix(node: ast.expr) -> str | None:
    """For ``f"name_{x}"`` -> "name_" (the literal head of a JoinedStr);
    None for non-f-strings or ones not starting with a literal."""
    if not isinstance(node, ast.JoinedStr) or not node.values:
        return None
    head = node.values[0]
    if isinstance(head, ast.Constant) and isinstance(head.value, str):
        return head.value
    return None


def functions(tree: ast.Module):
    """Yield every (possibly nested) function/method definition."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def enclosing_class_map(tree: ast.Module) -> dict[int, str]:
    """id(function node) -> name of the class that directly owns it."""
    out: dict[int, str] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[id(item)] = cls.name
    return out
