"""Interrupt-coverage lint: every blocking host wait on a statement path
must poll the PR-4 interrupt registry.

The cancellation design (runtime/interrupt.py) is boundary-granular: a
statement dies only where the host polls. Each new wait site added
without a poll silently re-opens the "cancel does nothing" bug class
PR 4 closed, so this lint finds blocking wait shapes statically:

* ``time.sleep`` inside a loop (retry/backoff/poll loops),
* ``.wait(...)`` on Condition/Event receivers,
* ``.result(...)`` on futures,
* ``.recv(...)`` / zero-arg ``.accept()`` socket reads,
* ``.get(...)`` on queue-named receivers, and ANY ``.get(timeout=...)``
  (the PR-11 serving pipeline's ready-queue wait shape — a timeout
  keyword is a blocking wait whatever the receiver is called),
* ``.join(timeout=...)`` on thread-named receivers (the PR-12
  prefetcher-drain shape: a statement thread waiting out a worker),

and requires an interrupt poll — ``check_interrupts()``, a ``ctx.check()``
/ ``.check()`` on a statement context, or a ``.cancelled`` test — in the
same function (helpers may poll beside the wait rather than inside it).

Modules whose waits can NEVER run on a statement thread are exempt here
with their reason; anything subtler carries an inline ``# gg:ok(interrupts)``
pragma next to its justification in the source.
"""

from __future__ import annotations

import ast

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report

# module path prefix (repo-relative) -> why its waits are exempt
EXEMPT = {
    "greengage_tpu/mgmt/": "operator CLI process; no statement registry",
    "greengage_tpu/runtime/server.py":
        "listener/watcher threads; statement threads poll in the session",
    "greengage_tpu/runtime/fts.py": "prober daemon thread",
    "greengage_tpu/runtime/standby.py": "standby sync runs off-statement",
    "greengage_tpu/runtime/runaway.py":
        "cleaner thread; victims die at their own cancellation points",
    "greengage_tpu/runtime/faultinject.py":
        "test machinery; suspend loops end by fault reset",
    "greengage_tpu/runtime/replication.py":
        "mirror copy pool joins are commit-side, bounded by file count",
    "greengage_tpu/storage/": "storage write/GC paths; statement-side "
                              "reads poll in exec/staging and exec/executor",
    "greengage_tpu/runtime/ingest.py": "host CSV parse helpers",
    "greengage_tpu/analysis/": "the analyzers themselves",
}

_POLL_ATTRS = {"check", "check_interrupts"}


def _is_exempt(rel: str) -> str | None:
    for prefix, why in EXEMPT.items():
        if rel.startswith(prefix):
            return why
    return None


def _has_poll(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name is not None and name.endswith("check_interrupts"):
                return True
            if name == "check" and isinstance(node.func, ast.Attribute):
                recv = astutil.dotted(node.func.value) or ""
                if "ctx" in recv or "interrupt" in recv.lower() \
                        or recv.endswith("TRACKER"):
                    return True
        elif isinstance(node, ast.Attribute) and node.attr == "cancelled":
            return True
    return False


def _wait_kind(node: ast.Call, in_loop: bool) -> str | None:
    name = astutil.call_name(node)
    if name is None or not isinstance(node.func, ast.Attribute):
        if name == "sleep":   # bare `sleep(...)` from `from time import`
            return "sleep-loop" if in_loop else None
        return None
    recv = astutil.dotted(node.func.value) or ""
    if name == "sleep" and recv.endswith("time"):
        return "sleep-loop" if in_loop else None
    if name == "wait":
        return "condition-wait"
    if name == "result":
        return "future-result"
    if name in ("recv", "recv_into"):
        return "socket-recv"
    if name == "accept" and not node.args and not node.keywords:
        return "socket-accept"
    if name == "get":
        if "queue" in recv.lower() or recv in ("q", "jobs"):
            return "queue-get"
        if any(kw.arg == "timeout" for kw in node.keywords):
            # whatever the receiver's name, get(timeout=...) is a
            # blocking dequeue (the serving pipeline's `_dq.get`)
            return "queue-get"
    if name == "join" and ("thread" in recv.lower() or recv == "t"):
        return "thread-join"
    return None


def run(sources=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet()
    exempt_count = 0
    for src in sources:
        why = _is_exempt(src.rel)
        for fn in astutil.functions(src.tree):
            # loops owned by THIS function (not nested defs)
            loop_lines: set[int] = set()
            own_nodes: list[ast.AST] = []
            stack: list[ast.AST] = list(fn.body)
            while stack:
                n = stack.pop()
                own_nodes.append(n)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(n, (ast.While, ast.For)):
                    for sub in ast.walk(n):
                        loop_lines.add(getattr(sub, "lineno", -1))
                stack.extend(ast.iter_child_nodes(n))
            polled = _has_poll(fn)
            for n in own_nodes:
                if not isinstance(n, ast.Call):
                    continue
                kind = _wait_kind(n, n.lineno in loop_lines)
                if kind is None:
                    continue
                if why is not None:
                    exempt_count += 1
                    continue
                if polled:
                    continue
                if src.pragma_ok(n.lineno, "interrupts"):
                    continue
                report.add(
                    "interrupts", src.rel, n.lineno,
                    f"{fn.name}:{kind}",
                    f"blocking wait ({kind}) in {fn.name}() without an "
                    "interrupt poll — a cancelled statement blocks here "
                    "forever (runtime/interrupt.py discipline)")
    report.notes["interrupt_exempt_waits"] = exempt_count
    return report
