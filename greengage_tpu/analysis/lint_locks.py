"""Static lock-order analysis over the package's ~35 lock sites.

Deadlock class this targets: thread 1 takes A then B while thread 2
takes B then A. The chaos storm in PR 6 caught two manifest-lock races
only at runtime; this extracts the ACQUISITION GRAPH statically and
fails on cycles, so an inconsistent order is a merge-time finding.

Model (heuristic by design — suppressions go through the baseline):

* A lock identity is the attribute (or module global) a ``threading``
  Lock/RLock/Condition (or the session ``_RWLock``) is assigned to,
  named ``module.Class.attr``. Dict-stored per-key lock families
  (``self._repair_locks[...]``, ``self._table_locks[...]``) collapse to
  one identity each — ordering *within* such a family is the runtime
  hook's job (``runtime/lockdebug.py``), not static analysis.
* An acquisition is ``with <lock>:``, ``<lock>.acquire()``, or — for
  Condition-backed classes — ``with self._cond`` / ``wait()`` blocks.
* Held-across edges: inside a ``with A`` body, every direct acquisition
  of B adds A -> B, and every CALL to a package function/method known
  to directly acquire B adds A -> B (one interprocedural hop, resolved
  by method name across the package — deliberately conservative).

A cycle in that graph is a finding naming the participating locks.
"""

from __future__ import annotations

import ast
from collections import defaultdict

from greengage_tpu.analysis import astutil
from greengage_tpu.analysis.report import Report

_LOCK_CTORS = {"Lock", "RLock", "Condition", "_RWLock"}

# Method names too generic to resolve by name across the package: a call
# ``x.get(...)`` under a held lock is almost always dict/queue access, not
# ``StatementRegistry.get`` — resolving it to every lock-acquiring class
# with a ``get`` method fabricates cycles. Calls to these names create
# interprocedural edges only for ``self.<name>()`` (resolved to the same
# class, which IS reliable).
_GENERIC_METHODS = frozenset({
    "get", "set", "add", "pop", "popitem", "update", "clear", "append",
    "remove", "discard", "keys", "values", "items", "copy", "close",
    "put", "join", "start", "run", "send", "write", "read", "next",
    "check", "reset", "wait", "notify", "notify_all", "info", "error",
    "log", "snapshot", "describe", "observe", "inc",
})

# attribute names that ARE locks but are assigned indirectly (aliases the
# constructor scan below can't see): Condition(self._lock) keeps the
# underlying lock identity, so alias both names to one node
_KNOWN_ALIASES = {
    # resqueue: self._slots = threading.Condition(self._lock)
    ("runtime.resqueue", "_slots"): ("runtime.resqueue", "_lock"),
}


def _lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = astutil.call_name(node)
    if name == "named" and node.args:
        # lockdebug.named(threading.Lock(), "...") keeps lock identity
        return _lock_ctor(node.args[0])
    return name in _LOCK_CTORS


def _module_key(rel: str) -> str:
    # greengage_tpu/runtime/resqueue.py -> runtime.resqueue
    parts = rel.replace("\\", "/").split("/")
    if parts and parts[0] == "greengage_tpu":
        parts = parts[1:]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _SiteCollector(ast.NodeVisitor):
    """Pass 1: find every lock identity in a module."""

    def __init__(self, mod: str):
        self.mod = mod
        self._class: list[str] = []
        # (scope, attr) -> lineno; scope = class name or "" for globals
        self.sites: dict[tuple[str, str], int] = {}

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def visit_Assign(self, node: ast.Assign):
        if _lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    self.sites[(self._class[-1] if self._class else "",
                                t.attr)] = node.lineno
                elif isinstance(t, ast.Name):
                    self.sites[("", t.id)] = node.lineno
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Attribute):
                    # per-key lock family: self._table_locks[k] = Lock()
                    self.sites[(self._class[-1] if self._class else "",
                                t.value.attr)] = node.lineno
        self.generic_visit(node)


def _collect_sites(sources) -> dict[str, tuple[str, int]]:
    """-> lock id "mod.Class.attr" -> (rel path, line)."""
    out: dict[str, tuple[str, int]] = {}
    for src in sources:
        mod = _module_key(src.rel)
        c = _SiteCollector(mod)
        c.visit(src.tree)
        for (scope, attr), line in c.sites.items():
            key = _KNOWN_ALIASES.get((mod, attr), None)
            if key is not None:
                ident = f"{key[0]}.{key[1]}"
            else:
                ident = f"{mod}.{scope}.{attr}" if scope else f"{mod}.{attr}"
            out[ident] = (src.rel, line)
    return out


def _attr_names_to_ids(sites: dict) -> dict[str, list[str]]:
    """attr name (last path component) -> every lock id carrying it."""
    out: dict[str, list[str]] = defaultdict(list)
    for ident in sites:
        out[ident.rsplit(".", 1)[-1]].append(ident)
    return out


def _acquired_lock(node: ast.expr, mod: str, cls: str,
                   by_attr: dict[str, list[str]]) -> str | None:
    """Resolve a with/acquire target expression to a lock identity.
    ``self._x`` prefers this module+class's site; a foreign attribute
    matches only when exactly ONE class in the package owns that attr
    (ambiguous names are skipped rather than guessed)."""
    expr = node
    if isinstance(expr, ast.Call):
        name = astutil.call_name(expr)
        if name in ("acquire", "shared"):
            expr = expr.func.value if isinstance(expr.func, ast.Attribute) \
                else expr
        else:
            return None
    if isinstance(expr, ast.Subscript):
        expr = expr.value           # lock family: self._locks[key]
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        cands = by_attr.get(attr, [])
        if not cands:
            return None
        mine = [c for c in cands if c == f"{mod}.{cls}.{attr}"]
        if mine:
            return mine[0]
        alias = _KNOWN_ALIASES.get((mod, attr))
        if alias:
            return f"{alias[0]}.{alias[1]}"
        if len(cands) == 1 and isinstance(expr.value, ast.Attribute | ast.Name):
            return cands[0]
        return None
    if isinstance(expr, ast.Name):
        cands = [c for c in by_attr.get(expr.id, [])
                 if c == f"{mod}.{expr.id}"]
        return cands[0] if cands else None
    return None


class _FnScanner(ast.NodeVisitor):
    """Pass 2 per function: direct acquisitions + calls made while held."""

    def __init__(self, mod: str, cls: str, by_attr: dict):
        self.mod, self.cls, self.by_attr = mod, cls, by_attr
        self.held: list[str] = []
        # lock -> [(callee name, lineno)] calls made while held
        self.calls_under: dict[str, list[tuple[str, int]]] = defaultdict(list)
        # direct nesting edges: (outer, inner, lineno)
        self.edges: list[tuple[str, str, int]] = []
        self.direct: set[str] = set()       # locks this fn acquires

    def visit_With(self, node: ast.With):
        got: list[str] = []
        for item in node.items:
            lk = _acquired_lock(item.context_expr, self.mod, self.cls,
                                self.by_attr)
            if lk is not None:
                self.direct.add(lk)
                for outer in self.held:
                    if outer != lk:
                        self.edges.append((outer, lk, node.lineno))
                got.append(lk)
        self.held.extend(got)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self.held.pop()

    def visit_Call(self, node: ast.Call):
        lk = _acquired_lock(node, self.mod, self.cls, self.by_attr)
        if lk is not None and astutil.call_name(node) == "acquire":
            self.direct.add(lk)
            for outer in self.held:
                if outer != lk:
                    self.edges.append((outer, lk, node.lineno))
        elif self.held:
            name = astutil.call_name(node)
            if name is not None:
                is_self = (isinstance(node.func, ast.Attribute)
                           and isinstance(node.func.value, ast.Name)
                           and node.func.value.id == "self")
                for outer in self.held:
                    self.calls_under[outer].append(
                        (name, node.lineno, is_self))
        self.generic_visit(node)

    # nested defs scan separately (their bodies run later, not under the
    # with); visiting them here would fabricate held-across edges
    def visit_FunctionDef(self, node):   # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def run(sources=None) -> Report:
    report = Report()
    sources = sources if sources is not None else astutil.SourceSet(
        exclude=("greengage_tpu/analysis/",))
    srcs = list(sources)
    sites = _collect_sites(srcs)
    by_attr = _attr_names_to_ids(sites)
    report.notes["lock_sites"] = len(sites)

    # lock sets keyed two ways: (class, fn) for `self.m()` calls (reliable
    # resolution) and bare fn name for distinctive cross-object calls —
    # generic names (get/put/check/...) resolve via self ONLY, because
    # name-matching them across the package fabricates edges from plain
    # dict/queue access (see _GENERIC_METHODS)
    fn_locks_self: dict[tuple[str, str], set[str]] = defaultdict(set)
    fn_locks_any: dict[str, set[str]] = defaultdict(set)
    scanned = []   # (src, class name, fn node, scanner)
    for src in srcs:
        mod = _module_key(src.rel)
        cls_of = astutil.enclosing_class_map(src.tree)
        for fn in astutil.functions(src.tree):
            cls = cls_of.get(id(fn), "")
            sc = _FnScanner(mod, cls, by_attr)
            for stmt in fn.body:
                sc.visit(stmt)
            scanned.append((src, cls, fn, sc))
            if sc.direct:
                fn_locks_self[(cls, fn.name)] |= sc.direct
                if fn.name not in _GENERIC_METHODS:
                    fn_locks_any[fn.name] |= sc.direct

    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    for src, cls, fn, sc in scanned:
        for a, b, line in sc.edges:
            edges.setdefault((a, b), (src.rel, line, fn.name))
        for outer, calls in sc.calls_under.items():
            for callee, line, is_self in calls:
                inners = (fn_locks_self.get((cls, callee), set())
                          if is_self else fn_locks_any.get(callee, set()))
                for inner in inners:
                    if inner != outer:
                        edges.setdefault(
                            (outer, inner),
                            (src.rel, line, f"{fn.name} -> {callee}()"))
    report.notes["lock_edges"] = len(edges)

    # cycle detection over the acquisition graph
    graph: dict[str, set[str]] = defaultdict(set)
    for a, b in edges:
        graph[a].add(b)
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = tuple(sorted(path))
                if cyc not in seen_cycles:
                    seen_cycles.add(cyc)
                    rel, line, via = edges[(path[-1], start)]
                    src = next((s for s in srcs if s.rel == rel), None)
                    if src is not None and src.pragma_ok(line, "locks"):
                        continue
                    report.add(
                        "locks", rel, line,
                        "cycle:" + ">".join(cyc),
                        "lock-order cycle: " + " -> ".join(path + [start])
                        + f" (closing edge via {via}); threads taking "
                        "these in different orders can deadlock")
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return report
