"""The plan corpus: TPC-H + TPC-DS statement shapes for whole-corpus
plan validation (``gg check --plans`` and ``tests/test_analysis.py``).

The per-statement ``plan_validate`` GUC catches violations as they
happen; this corpus makes the sweep REPEATABLE and CI-gated — every
planner change re-proves the full query-shape spectrum (joins of every
motion flavor, one/two/three-phase aggregates, windows global and
partitioned, funneled LIMITs, semi/anti subqueries, unions) against the
invariants in ``analysis/plancheck.py``.

Queries are the shapes the oracle tests already execute (tests/
test_tpch_*.py, test_tpcds_subset.py) so the corpus can never drift
ahead of what the engine actually supports.
"""

from __future__ import annotations

import numpy as np

TPCH_QUERIES: dict[str, str] = {
    "q1_pricing_summary": """
      select l_returnflag, l_linestatus, sum(l_quantity), count(*)
      from lineitem where l_shipdate <= date '1998-09-02'
      group by l_returnflag, l_linestatus
      order by l_returnflag, l_linestatus""",
    "q3_shipping_priority": """
      select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
             o_orderdate, o_shippriority
      from customer, orders, lineitem
      where c_mktsegment = 'BUILDING'
        and c_custkey = o_custkey and l_orderkey = o_orderkey
        and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
      group by l_orderkey, o_orderdate, o_shippriority
      order by revenue desc, o_orderdate limit 10""",
    "q6_forecast_revenue": """
      select sum(l_extendedprice * l_discount) as revenue
      from lineitem
      where l_shipdate >= date '1994-01-01'
        and l_shipdate < date '1995-01-01'
        and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    "q10_returned_items": """
      select c_custkey, c_name,
             sum(l_extendedprice * (1 - l_discount)) as revenue,
             c_acctbal, n_name
      from customer, orders, lineitem, nation
      where c_custkey = o_custkey and l_orderkey = o_orderkey
        and o_orderdate >= date '1993-10-01'
        and o_orderdate < date '1994-01-01'
        and l_returnflag = 'R' and c_nationkey = n_nationkey
      group by c_custkey, c_name, c_acctbal, n_name
      order by revenue desc limit 20""",
    "q12_shipmode": """
      select l_shipmode,
             sum(case when o_orderpriority = '1-URGENT'
                       or o_orderpriority = '2-HIGH' then 1 else 0 end)
               as high_line_count,
             sum(case when o_orderpriority <> '1-URGENT'
                       and o_orderpriority <> '2-HIGH' then 1 else 0 end)
               as low_line_count
      from orders, lineitem
      where o_orderkey = l_orderkey
        and l_shipmode in ('MAIL', 'SHIP')
        and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
        and l_receiptdate >= date '1994-01-01'
        and l_receiptdate < date '1995-01-01'
      group by l_shipmode order by l_shipmode""",
    "q14_promo_effect": """
      select 100.00 * sum(case when p_type like 'type 1%'
                               then l_extendedprice * (1 - l_discount)
                               else 0 end)
             / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
      from lineitem, part
      where l_partkey = p_partkey
        and l_shipdate >= date '1995-09-01'
        and l_shipdate < date '1995-10-01'""",
    "q18_large_volume": """
      select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
             sum(l_quantity)
      from customer, orders, lineitem
      where o_orderkey in (
              select l_orderkey from lineitem
              group by l_orderkey having sum(l_quantity) > 250)
        and c_custkey = o_custkey and o_orderkey = l_orderkey
      group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
      order by o_totalprice desc, o_orderdate limit 100""",
    "point_direct_dispatch":
        "select o_totalprice from orders where o_orderkey = 100",
    "semi_exists": """
      select c_custkey, c_name from customer
      where exists (select 1 from orders where o_custkey = c_custkey
                    and o_totalprice > 100000)
      order by c_custkey limit 50""",
    "anti_not_in": """
      select c_custkey from customer
      where c_custkey not in (select o_custkey from orders)
      order by c_custkey limit 50""",
    "scalar_subquery": """
      select l_orderkey, l_extendedprice from lineitem
      where l_extendedprice > (select avg(l_extendedprice) from lineitem)
      order by l_extendedprice desc limit 25""",
    "global_window_rank": """
      select o_orderkey, o_totalprice,
             row_number() over (order by o_orderkey) rn
      from orders order by rn limit 20""",
    "partitioned_window": """
      select o_custkey, o_orderkey, o_totalprice,
             sum(o_totalprice) over (partition by o_custkey) cust_total
      from orders order by o_custkey, o_orderkey limit 30""",
    "union_all_branches": """
      select o_orderkey as k, o_totalprice as v from orders
        where o_totalprice > 150000
      union all
      select l_orderkey as k, l_extendedprice as v from lineitem
        where l_quantity > 45
      order by k, v limit 40""",
    "distinct_group": """
      select distinct l_shipmode from lineitem order by l_shipmode""",
    "cross_join_scalar": """
      select n_name, r_name from nation, region
      where n_regionkey = r_regionkey order by n_name limit 10""",
    "buried_limit_subquery": """
      select k from (select o_orderkey as k from orders
                     order by o_totalprice desc limit 5) t
      order by k""",
    "two_phase_strewn_group": """
      select l_suppkey, count(*) c, sum(l_quantity) q
      from lineitem group by l_suppkey order by c desc, l_suppkey limit 15""",
    # ---- window engine (ISSUE 12): every shape below must plan with the
    # ---- root Gather as its ONLY Gather and no SingleQE funnel --------
    "ordered_global_ntile": """
      select o_orderkey, ntile(4) over (order by o_orderkey) nt
      from orders order by o_orderkey limit 20""",
    "ordered_global_lag_lead": """
      select o_orderkey, lag(o_totalprice) over (order by o_orderdate,
                                                 o_orderkey) lp,
             lead(o_custkey, 2) over (order by o_orderdate, o_orderkey) lc
      from orders order by o_orderkey limit 20""",
    "ordered_global_text_rank": """
      select o_clerk, ntile(3) over (order by o_clerk) nt,
             dense_rank() over (order by o_clerk) dr
      from orders order by o_clerk limit 20""",
    "range_window_running_sum": """
      select o_orderkey, sum(o_totalprice) over (order by o_totalprice,
                                                 o_orderkey) rs
      from orders order by o_orderkey limit 20""",
    "ordered_global_decimal_rank": """
      select o_orderkey, rank() over (order by o_totalprice desc) rk
      from orders order by o_orderkey limit 20""",
    "whole_frame_first_value": """
      select o_custkey, first_value(o_totalprice) over
               (partition by o_custkey) f
      from orders order by o_orderkey limit 20""",
}

# the test-scale star schema of tests/test_tpcds_subset.py
TPCDS_QUERIES: dict[str, str] = {
    "ds_q3_brand_revenue": """
      select d_year, i_brand_id, sum(ss_ext_sales_price) as rev
      from store_sales, date_dim, item
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and i_manufact_id = 28 and d_moy = 11
      group by d_year, i_brand_id
      order by d_year, rev desc, i_brand_id limit 25""",
    "ds_q42_category_rollup": """
      select d_year, i_category, sum(ss_ext_sales_price) as rev
      from store_sales, date_dim, item
      where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 1 and d_moy = 11 and d_year = 1999
      group by d_year, i_category order by rev desc, i_category""",
    "ds_semi_bitmap": """
      select s_state, count(*) as cnt, sum(ss_quantity) as q
      from store_sales, store
      where ss_store_sk = s_store_sk
        and ss_item_sk in (select i_item_sk from item where i_brand_id < 5)
        and ss_sold_date_sk in (select d_date_sk from date_dim
                                where d_year = 2000)
      group by s_state order by s_state""",
    "ds_q52_brand_by_month": """
      select d_year, i_brand_id, sum(ss_ext_sales_price) as p
      from date_dim, store_sales, item
      where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
        and i_manager_id = 1 and d_moy = 12 and d_year = 1998
      group by d_year, i_brand_id order by d_year, p desc, i_brand_id
      limit 10""",
    "ds_q27_rollup_grouping": """
      select i_category, s_state, grouping(i_category, s_state) g,
             avg(ss_quantity) aq, count(*) c
      from store_sales, item, store
      where ss_item_sk = i_item_sk and ss_store_sk = s_store_sk
        and i_manager_id < 10
      group by rollup(i_category, s_state)
      order by g, i_category, s_state""",
    "ds_q70_grouped_rank": """
      select s_state, sum(ss_ext_sales_price) rev,
             rank() over (order by sum(ss_ext_sales_price) desc) rnk
      from store_sales, store
      where ss_store_sk = s_store_sk
      group by s_state order by rnk""",
    "ds_q86_share_of_total": """
      select i_category, sum(ss_ext_sales_price) rev,
             sum(ss_ext_sales_price) * 100.0
               / sum(sum(ss_ext_sales_price)) over () share
      from store_sales, item
      where ss_item_sk = i_item_sk
      group by i_category order by i_category""",
    # ---- scalar data-path fusion (ISSUE 13): every shape below must
    # ---- lower its scalar work INTO the fused device program — no host
    # ---- chains, no materialization between scan and agg ---------------
    "ds_scalar_extract_group": """
      select extract(year from d_date) y, extract(quarter from d_date) q,
             count(*) c
      from date_dim
      where extract(year from d_date) >= 1999
      group by extract(year from d_date), extract(quarter from d_date)
      order by y, q""",
    "ds_scalar_date_trunc_agg": """
      select date_trunc('month', d_date) m, sum(ss_quantity) tq
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_date < date '2000-01-01' + interval '6' month
      group by date_trunc('month', d_date) order by m limit 12""",
    "ds_scalar_substr_case_agg": """
      select substr(i_category, 1, 3) pfx,
             sum(case when ss_ext_sales_price > 500
                      then ss_ext_sales_price else 0 end) big_rev,
             sum(coalesce(ss_ext_sales_price, 0)) rev
      from store_sales, item
      where ss_item_sk = i_item_sk
      group by substr(i_category, 1, 3) order by pfx""",
    "ds_scalar_nullif_greatest": """
      select i_manager_id, greatest(i_brand_id, i_manufact_id) g,
             count(nullif(i_manager_id, 1)) c
      from item
      group by i_manager_id, greatest(i_brand_id, i_manufact_id)
      order by i_manager_id, g limit 20""",
}


def load_tpcds_mini(db, n_fact: int = 20_000, seed: int = 77) -> None:
    """Create the TPC-DS star subset (store_sales + 3 dims) at validation
    scale — same schema as tests/test_tpcds_subset.py."""
    from greengage_tpu.types import Coded

    rng = np.random.default_rng(seed)
    n_date, n_item, n_store = 400, 300, 12
    db.sql("create table date_dim (d_date_sk bigint, d_date date, "
           "d_year int, d_moy int) distributed replicated")
    db.sql("create table item (i_item_sk bigint, i_brand_id int, "
           "i_category text, i_manufact_id int, i_manager_id int) "
           "distributed by (i_item_sk)")
    db.sql("create table store (s_store_sk bigint, s_state text) "
           "distributed replicated")
    db.sql("create table store_sales (ss_sold_date_sk bigint, "
           "ss_item_sk bigint, ss_store_sk bigint, ss_quantity int, "
           "ss_ext_sales_price bigint) distributed by (ss_item_sk)")
    db.load_table("date_dim", {
        "d_date_sk": np.arange(n_date, dtype=np.int64),
        # days since epoch starting 1998-01-01 (10227), one per sk
        "d_date": (10227 + np.arange(n_date)).astype(np.int32),
        "d_year": (1998 + np.arange(n_date) // 180).astype(np.int32),
        "d_moy": (1 + (np.arange(n_date) // 15) % 12).astype(np.int32)})
    db.load_table("item", {
        "i_item_sk": np.arange(n_item, dtype=np.int64),
        "i_brand_id": rng.integers(1, 60, n_item).astype(np.int32),
        "i_category": Coded([f"Cat{i}" for i in range(10)],
                            rng.integers(0, 10, n_item).astype(np.int32)),
        "i_manufact_id": rng.integers(1, 100, n_item).astype(np.int32),
        "i_manager_id": rng.integers(1, 40, n_item).astype(np.int32)})
    db.load_table("store", {
        "s_store_sk": np.arange(n_store, dtype=np.int64),
        "s_state": Coded(["CA", "NY", "TX", "WA"],
                         rng.integers(0, 4, n_store).astype(np.int32))})
    db.load_table("store_sales", {
        "ss_sold_date_sk": rng.integers(0, n_date, n_fact),
        "ss_item_sk": rng.integers(0, n_item, n_fact),
        "ss_store_sk": rng.integers(0, n_store, n_fact),
        "ss_quantity": rng.integers(1, 100, n_fact).astype(np.int32),
        "ss_ext_sales_price":
            rng.integers(100, 100_000, n_fact).astype(np.int64)})
    db.sql("analyze")


def validate_corpus(db, queries: dict[str, str]) -> list[tuple[str, str]]:
    """Plan + validate every corpus statement against ``db``; also prove
    the I7 capacity contract through a real Compiler. -> [(name, error)]
    for statements that failed (empty = clean)."""
    from greengage_tpu.analysis.plancheck import (validate_capacities,
                                                  validate_plan)
    from greengage_tpu.exec.compile import Compiler
    from greengage_tpu.sql.parser import parse

    failures: list[tuple[str, str]] = []
    for name, sql in queries.items():
        try:
            stmt = parse(sql)[0]
            planned, consts, _outs = db._plan(stmt)
            validate_plan(planned, db.catalog)   # explicit even if GUC off
            comp = Compiler(db.catalog, db.store, db.mesh, db.numsegments,
                            consts, db.settings,
                            multihost=db.multihost is not None)
            validate_capacities(comp, planned)
        except Exception as e:   # noqa: BLE001 — report, don't abort sweep
            failures.append((name, f"{type(e).__name__}: {e}"))
    return failures
