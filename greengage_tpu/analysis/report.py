"""One reporter for every ``gg check`` finding.

Findings carry a stable suppression *key* (path + symbol + detail, no
line numbers) so the checked-in baseline survives unrelated edits. Two
suppression channels:

* ``analysis/baseline.txt`` — one ``check<TAB>key`` per line, checked in
  beside this module. The file starts near-empty by policy: a finding
  lands here only when it is a verified false positive of the analyzer,
  never to dodge a real fix (docs/ANALYSIS.md).
* an inline ``# gg:ok(<check>)`` pragma on the flagged line, for
  deliberate exceptions whose justification belongs next to the code
  (e.g. a wait loop that provably never runs on a statement thread).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

_PRAGMA_RE = re.compile(r"#\s*gg:ok\(([a-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    check: str          # analyzer id: locks | interrupts | tracer | ...
    path: str           # repo-relative source path
    line: int           # 1-based; informational only (keys are line-free)
    key: str            # stable suppression key within (check, path)
    message: str

    @property
    def full_key(self) -> str:
        return f"{self.path}::{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    # analyzer-level notes (counts, skipped modules) for --json consumers
    notes: dict = field(default_factory=dict)

    def add(self, check: str, path: str, line: int, key: str,
            message: str) -> None:
        self.findings.append(Finding(check, path, line, key, message))

    def extend(self, other: "Report") -> None:
        self.findings.extend(other.findings)
        self.notes.update(other.notes)

    def suppressed(self, baseline: set[tuple[str, str]]) -> "Report":
        """-> a Report holding only findings NOT covered by the baseline
        (pragma suppression happens in the analyzers, which see source)."""
        out = Report(notes=dict(self.notes))
        out.findings = [f for f in self.findings
                        if (f.check, f.full_key) not in baseline]
        return out

    def to_json(self) -> str:
        return json.dumps({
            "findings": [{"check": f.check, "path": f.path, "line": f.line,
                          "key": f.full_key, "message": f.message}
                         for f in self.findings],
            "notes": self.notes,
            "clean": not self.findings,
        }, indent=1, sort_keys=True)

    def to_text(self) -> str:
        if not self.findings:
            return "gg check: clean (0 findings)"
        lines = [f.render() for f in sorted(
            self.findings, key=lambda f: (f.check, f.path, f.line))]
        lines.append(f"gg check: {len(self.findings)} finding(s)")
        return "\n".join(lines)


def baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: str | None = None) -> set[tuple[str, str]]:
    """-> {(check, full_key)} from the checked-in baseline file."""
    path = path or baseline_path()
    out: set[tuple[str, str]] = set()
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 1)
            if len(parts) == 2:
                out.add((parts[0], parts[1]))
    return out


def line_pragmas(source_line: str) -> set[str]:
    """Checks suppressed by an inline ``# gg:ok(a, b)`` pragma."""
    m = _PRAGMA_RE.search(source_line)
    if not m:
        return set()
    return {p.strip() for p in m.group(1).split(",") if p.strip()}
