"""``gg check`` driver: run analyzers, apply the baseline, report.

Static checks are pure stdlib-``ast`` over the package tree and run in
well under a second; the plan-corpus sweep (``run_plan_corpus``) builds
throwaway TPC-H/TPC-DS clusters and needs a jax backend, so it hides
behind ``gg check --plans`` (CI runs both).
"""

from __future__ import annotations

from greengage_tpu.analysis import (astutil, lint_imports, lint_interrupts,
                                    lint_locks, lint_races, lint_registry,
                                    lint_tracer, threadmodel)
from greengage_tpu.analysis.report import Report, load_baseline

CHECKS = {
    "locks": lint_locks.run,
    "interrupts": lint_interrupts.run,
    "tracer": lint_tracer.run,
    "registry": lint_registry.run,
    "imports": lint_imports.run,
    "threads": threadmodel.run,
    "races": lint_races.run,
}

# one-line catalog (gg check --list); keep in step with docs/ANALYSIS.md
DESCRIPTIONS = {
    "locks": "lock-order cycles over the package acquisition graph",
    "interrupts": "blocking waits on statement paths poll the "
                  "interrupt registry",
    "tracer": "no host-forcing of tracers under jit; cache-key purity",
    "registry": "metric/GUC/fault-point/plan-cache-GUC catalogs match "
                "the code both ways",
    "imports": "no function-local imports of cheap stdlib modules",
    "threads": "every thread spawn site is declared in THREAD_ROLES "
               "(and every declared role is live)",
    "races": "no shared attribute written by one thread role and "
             "touched by another without a common lock",
}


def run_checks(names: list[str] | None = None,
               baseline_file: str | None = None,
               use_baseline: bool = True) -> Report:
    """Run the named static analyzers (all by default) over one shared
    parsed view of the package; findings surviving the baseline remain."""
    sources = astutil.SourceSet(exclude=("greengage_tpu/analysis/",))
    report = Report()
    for name in names or sorted(CHECKS):
        if name not in CHECKS:
            raise ValueError(f"unknown check {name!r} "
                             f"(have: {', '.join(sorted(CHECKS))})")
        report.extend(CHECKS[name](sources))
    if use_baseline:
        baseline = load_baseline(baseline_file)
        before = len(report.findings)
        report = report.suppressed(baseline)
        report.notes["baseline_suppressed"] = before - len(report.findings)
    return report


def run_plan_corpus(numsegments: int = 4) -> Report:
    """Validate every TPC-H/TPC-DS corpus plan (I1-I7) on throwaway
    in-memory clusters — the ``gg check --plans`` / CI half."""
    import greengage_tpu
    from greengage_tpu.analysis import plancorpus
    from greengage_tpu.utils import tpch

    report = Report()
    db = greengage_tpu.connect(numsegments=numsegments)
    try:
        tpch.load(db, sf=0.005)
        db.sql("analyze")
        for name, err in plancorpus.validate_corpus(
                db, plancorpus.TPCH_QUERIES):
            report.add("plans", "analysis/plancorpus.py", 1,
                       f"tpch:{name}", f"{name}: {err}")
        report.notes["tpch_validated"] = len(plancorpus.TPCH_QUERIES)
    finally:
        db.close()
    db = greengage_tpu.connect(numsegments=numsegments)
    try:
        plancorpus.load_tpcds_mini(db)
        for name, err in plancorpus.validate_corpus(
                db, plancorpus.TPCDS_QUERIES):
            report.add("plans", "analysis/plancorpus.py", 1,
                       f"tpcds:{name}", f"{name}: {err}")
        report.notes["tpcds_validated"] = len(plancorpus.TPCDS_QUERIES)
    finally:
        db.close()
    return report
